"""Generic prime fields, polynomial extension fields, and short-Weierstrass curves.

Pure-Python, arbitrary-precision. This is the host oracle layer the device
kernels (spectre_tpu.ops) and the native C++ library are tested against, and the
math the proof *verifier* runs on (pairings are verifier-side and cold).

Reference parity: plays the role of `halo2curves-axiom` (host-side BN254 +
BLS12-381 arithmetic; SURVEY.md §2b N1/N5) — re-designed as a generic tower
rather than a port.
"""

from __future__ import annotations

import secrets


# ---------------------------------------------------------------------------
# modular helpers
# ---------------------------------------------------------------------------

def modinv(a: int, p: int) -> int:
    """Modular inverse via Fermat (p prime)."""
    a %= p
    if a == 0:
        raise ZeroDivisionError("inverse of 0")
    return pow(a, p - 2, p)


def legendre(a: int, p: int) -> int:
    """Legendre symbol: 1 if QR, -1 if non-residue, 0 if 0."""
    a %= p
    if a == 0:
        return 0
    ls = pow(a, (p - 1) // 2, p)
    return -1 if ls == p - 1 else 1


def tonelli_shanks(a: int, p: int) -> int | None:
    """Square root mod odd prime p, or None if a is a non-residue."""
    a %= p
    if a == 0:
        return 0
    if legendre(a, p) != 1:
        return None
    if p % 4 == 3:
        return pow(a, (p + 1) // 4, p)
    # factor p-1 = q * 2^s
    q, s = p - 1, 0
    while q % 2 == 0:
        q //= 2
        s += 1
    # find a non-residue z
    z = 2
    while legendre(z, p) != -1:
        z += 1
    m, c, t, r = s, pow(z, q, p), pow(a, q, p), pow(a, (q + 1) // 2, p)
    while t != 1:
        # find least i with t^(2^i) == 1
        i, t2i = 0, t
        while t2i != 1:
            t2i = t2i * t2i % p
            i += 1
        b = pow(c, 1 << (m - i - 1), p)
        m, c = i, b * b % p
        t, r = t * c % p, r * b % p
    return r


# ---------------------------------------------------------------------------
# prime field (int-backed, class-per-modulus via factory)
# ---------------------------------------------------------------------------

class PrimeField:
    """Base prime field element. Subclasses set `p` (via make_prime_field).

    Elements of *different* prime fields never mix silently: any binary op with
    an element of another field class raises TypeError (this codebase juggles
    four prime fields — BN254 Fq/Fr and BLS12-381 Fq/Fr — and a silent
    cross-field coercion produces wrong values, not errors).
    """

    __slots__ = ("n",)
    p: int = 0
    degree = 1  # tower degree over the base prime field

    def __init__(self, n):
        if isinstance(n, PrimeField):
            if type(n) is not type(self):
                raise TypeError(f"cannot build {type(self).__name__} from {type(n).__name__}")
            self.n = n.n
        else:
            self.n = int(n) % self.p

    def _val(self, o) -> int:
        if isinstance(o, PrimeField):
            if type(o) is not type(self):
                raise TypeError(f"field mismatch: {type(self).__name__} vs {type(o).__name__}")
            return o.n
        if isinstance(o, int):
            return o
        raise TypeError(f"cannot operate on {type(self).__name__} and {type(o).__name__}")

    # -- arithmetic --
    def __add__(self, o):
        return type(self)(self.n + self._val(o))

    __radd__ = __add__

    def __sub__(self, o):
        return type(self)(self.n - self._val(o))

    def __rsub__(self, o):
        return type(self)(self._val(o) - self.n)

    def __mul__(self, o):
        return type(self)(self.n * self._val(o))

    __rmul__ = __mul__

    def __neg__(self):
        return type(self)(-self.n)

    def __truediv__(self, o):
        return type(self)(self.n * modinv(self._val(o), self.p))

    def __rtruediv__(self, o):
        return type(self)(self._val(o) * modinv(self.n, self.p))

    def __pow__(self, e: int):
        if e < 0:
            return type(self)(pow(modinv(self.n, self.p), -e, self.p))
        return type(self)(pow(self.n, e, self.p))

    def inv(self):
        return type(self)(modinv(self.n, self.p))

    def sqrt(self):
        r = tonelli_shanks(self.n, self.p)
        return None if r is None else type(self)(r)

    def is_square(self) -> bool:
        return legendre(self.n, self.p) >= 0

    def sgn0(self) -> int:
        """RFC 9380 sgn0 for m=1: parity of the integer representative."""
        return self.n & 1

    # -- comparisons / misc --
    def __eq__(self, o):
        if isinstance(o, PrimeField):
            return type(o) is type(self) and self.n == o.n
        if isinstance(o, int):
            return self.n == o % self.p
        return NotImplemented

    def __hash__(self):
        return hash((self.p, self.n))

    def __int__(self):
        return self.n

    def __repr__(self):
        return f"{type(self).__name__}(0x{self.n:x})"

    @classmethod
    def zero(cls):
        return cls(0)

    @classmethod
    def one(cls):
        return cls(1)

    @classmethod
    def random(cls):
        return cls(secrets.randbelow(cls.p))


_field_cache: dict[tuple, type] = {}


def make_prime_field(p: int, name: str) -> type[PrimeField]:
    key = (p, name)
    if key not in _field_cache:
        _field_cache[key] = type(name, (PrimeField,), {"p": p})
    return _field_cache[key]


# ---------------------------------------------------------------------------
# polynomial extension fields  F_p[x] / (modulus)
# ---------------------------------------------------------------------------

class ExtField:
    """Element of F_p[x]/(f(x)), coeffs little-endian ints mod p.

    Subclasses (via make_ext_field) set: p, modulus_coeffs (list of ints c_i such
    that x^deg = -(c_0 + c_1 x + ... + c_{deg-1} x^{deg-1})), deg.
    """

    __slots__ = ("c",)
    p: int = 0
    deg: int = 0
    modulus_coeffs: tuple = ()

    def __init__(self, coeffs):
        p = self.p
        if isinstance(coeffs, ExtField):
            self.c = coeffs.c
            return
        c = [int(x) % p for x in coeffs]
        assert len(c) == self.deg, (len(c), self.deg)
        self.c = c

    # -- helpers --
    @classmethod
    def zero(cls):
        return cls([0] * cls.deg)

    @classmethod
    def one(cls):
        return cls([1] + [0] * (cls.deg - 1))

    @classmethod
    def from_base(cls, n: int):
        return cls([int(n)] + [0] * (cls.deg - 1))

    @classmethod
    def random(cls):
        return cls([secrets.randbelow(cls.p) for _ in range(cls.deg)])

    def _coerce(self, o):
        if isinstance(o, type(self)):
            return o
        if isinstance(o, int):
            return type(self).from_base(o)
        if isinstance(o, PrimeField):
            if o.p != self.p:
                raise TypeError(f"field mismatch: {type(self).__name__} vs {type(o).__name__}")
            return type(self).from_base(o.n)
        return NotImplemented

    # -- arithmetic --
    def __add__(self, o):
        o = self._coerce(o)
        if o is NotImplemented:
            return o
        p = self.p
        return type(self)([(a + b) % p for a, b in zip(self.c, o.c)])

    __radd__ = __add__

    def __sub__(self, o):
        o = self._coerce(o)
        if o is NotImplemented:
            return o
        p = self.p
        return type(self)([(a - b) % p for a, b in zip(self.c, o.c)])

    def __rsub__(self, o):
        return self._coerce(o) - self

    def __neg__(self):
        p = self.p
        return type(self)([(-a) % p for a in self.c])

    def __mul__(self, o):
        if isinstance(o, int):
            p = self.p
            return type(self)([a * o % p for a in self.c])
        if isinstance(o, PrimeField):
            p = self.p
            return type(self)([a * o.n % p for a in self.c])
        if not isinstance(o, type(self)):
            return NotImplemented
        p, deg = self.p, self.deg
        a, b = self.c, o.c
        # schoolbook product
        prod = [0] * (2 * deg - 1)
        for i, ai in enumerate(a):
            if ai:
                for j, bj in enumerate(b):
                    prod[i + j] += ai * bj
        # reduce by modulus: x^deg = -modulus_coeffs
        mc = self.modulus_coeffs
        for k in range(2 * deg - 2, deg - 1, -1):
            top = prod[k]
            if top:
                prod[k] = 0
                for i, m in enumerate(mc):
                    if m:
                        prod[k - deg + i] -= top * m
        return type(self)([x % p for x in prod[:deg]])

    __rmul__ = __mul__

    def __pow__(self, e: int):
        if e < 0:
            return self.inv() ** (-e)
        result = type(self).one()
        base = self
        while e:
            if e & 1:
                result = result * base
            base = base * base
            e >>= 1
        return result

    def inv(self):
        """Extended Euclid on polynomials over F_p."""
        p, deg = self.p, self.deg
        lm, hm = [1] + [0] * deg, [0] * (deg + 1)
        low = list(self.c) + [0]
        high = list(self.modulus_coeffs) + [1]
        while _poly_deg(low):
            r = _poly_divmod(high, low, p)
            nm = [(hm[i] - sum(r[j] * lm[i - j] for j in range(len(r)) if 0 <= i - j < len(lm))) % p
                  for i in range(deg + 1)]
            lm, low, hm, high = nm, _poly_sub_mul(high, low, r, p), lm, low
        linv = modinv(low[0], p)
        return type(self)([x * linv % p for x in lm[:deg]])

    def __truediv__(self, o):
        o = self._coerce(o)
        if o is NotImplemented:
            return o
        return self * o.inv()

    def __rtruediv__(self, o):
        return self._coerce(o) * self.inv()

    # -- comparisons / misc --
    def __eq__(self, o):
        o = self._coerce(o)
        if o is NotImplemented:
            return False
        return self.c == o.c

    def __hash__(self):
        return hash((self.p, tuple(self.c)))

    def is_zero(self):
        return all(x == 0 for x in self.c)

    def sgn0(self) -> int:
        """RFC 9380 sgn0 for extension fields (little-endian coefficient order)."""
        sign, zero = 0, 1
        for a in self.c:
            sign_i = a & 1
            zero_i = 1 if a == 0 else 0
            sign = sign | (zero & sign_i)
            zero = zero & zero_i
        return sign

    @classmethod
    def _nonresidue_candidates(cls):
        """Deterministic stream of candidate non-residues for sqrt."""
        for k in range(1, 64):
            coeffs = [0] * cls.deg
            coeffs[0] = k
            if cls.deg > 1:
                coeffs[1] = 1
            yield cls(coeffs)

    def frobenius(self):
        """x -> x^p (generic, via pow; subclasses may override with coeff tables)."""
        return self ** self.p

    def __repr__(self):
        return f"{type(self).__name__}({self.c})"

    def sqrt(self):
        """Square root via generic Tonelli–Shanks over the extension field."""
        q = self.p ** self.deg
        if self.is_zero():
            return self
        # Euler criterion
        if self ** ((q - 1) // 2) != type(self).one():
            return None
        if q % 4 == 3:
            return self ** ((q + 1) // 4)
        # Tonelli-Shanks in the extension group
        s, t = 0, q - 1
        while t % 2 == 0:
            s, t = s + 1, t // 2
        # find a non-residue, deterministically (reproducible across processes)
        z = None
        for cand in self._nonresidue_candidates():
            if not cand.is_zero() and cand ** ((q - 1) // 2) != type(self).one():
                z = cand
                break
        assert z is not None, "no quadratic non-residue found"
        m, c = s, z ** t
        u, r = self ** t, self ** ((t + 1) // 2)
        one = type(self).one()
        while u != one:
            i, u2i = 0, u
            while u2i != one:
                u2i = u2i * u2i
                i += 1
            b = c ** (1 << (m - i - 1))
            m, c = i, b * b
            u, r = u * c, r * b
        return r


def _poly_deg(c):
    for i in range(len(c) - 1, -1, -1):
        if c[i]:
            return i
    return 0


def _poly_divmod(a, b, p):
    """Quotient of polynomial a by b over F_p (coeff lists, little-endian)."""
    da, db = _poly_deg(a), _poly_deg(b)
    if da < db:
        return [0]
    a = list(a)
    q = [0] * (da - db + 1)
    binv = modinv(b[db], p)
    for i in range(da - db, -1, -1):
        coef = a[i + db] * binv % p
        q[i] = coef
        if coef:
            for j in range(db + 1):
                a[i + j] = (a[i + j] - coef * b[j]) % p
    return q


def _poly_sub_mul(a, b, q, p):
    """a - b*q over F_p, truncated to len(a)."""
    res = list(a)
    for i, qi in enumerate(q):
        if qi:
            for j, bj in enumerate(b):
                if bj and i + j < len(res):
                    res[i + j] = (res[i + j] - qi * bj) % p
    return res


def make_ext_field(p: int, modulus_coeffs, name: str, base_degree: int = 1) -> type[ExtField]:
    key = (p, tuple(int(c) for c in modulus_coeffs), name)
    if key not in _field_cache:
        _field_cache[key] = type(
            name,
            (ExtField,),
            {
                "p": p,
                "deg": len(modulus_coeffs),
                "modulus_coeffs": tuple(int(c) % p for c in modulus_coeffs),
            },
        )
    return _field_cache[key]


# ---------------------------------------------------------------------------
# short-Weierstrass curve group, generic over the coordinate field
# ---------------------------------------------------------------------------

class CurveGroup:
    """y^2 = x^3 + a*x + b over a field class F. Points are (x, y) or None (inf).

    Affine representation with exact arithmetic — this is the oracle/verifier
    path; the throughput path is jacobian limb arithmetic on device (ops.ec).
    """

    def __init__(self, F, a, b, order: int | None = None, cofactor: int | None = None):
        self.F = F
        self.a = a if not isinstance(a, int) else self._embed(F, a)
        self.b = b if not isinstance(b, int) else self._embed(F, b)
        self.order = order
        self.cofactor = cofactor

    @staticmethod
    def _embed(F, n):
        return F.from_base(n) if hasattr(F, "from_base") else F(n)

    def is_on_curve(self, pt) -> bool:
        if pt is None:
            return True
        x, y = pt
        return y * y == x * x * x + self.a * x + self.b

    def add(self, p1, p2):
        if p1 is None:
            return p2
        if p2 is None:
            return p1
        x1, y1 = p1
        x2, y2 = p2
        if x1 == x2:
            if y1 == y2:
                if y1 == y1 - y1:  # y == 0
                    return None
                lam = (x1 * x1 * 3 + self.a) / (y1 * 2)
            else:
                return None
        else:
            lam = (y2 - y1) / (x2 - x1)
        x3 = lam * lam - x1 - x2
        y3 = lam * (x1 - x3) - y1
        return (x3, y3)

    def double(self, p):
        return self.add(p, p)

    def neg(self, p):
        if p is None:
            return None
        return (p[0], -p[1])

    def mul(self, p, k: int):
        """Scalar mul for points in the prime-order subgroup (k reduced mod order)."""
        if self.order is not None:
            k %= self.order
        return self.mul_unsafe(p, k)

    def mul_unsafe(self, p, k: int):
        """Scalar mul WITHOUT reducing k — required for subgroup/cofactor ops."""
        if k < 0:
            return self.neg(self.mul_unsafe(p, -k))
        if k == 0 or p is None:
            return None
        result = None
        addend = p
        while k:
            if k & 1:
                result = self.add(result, addend)
            addend = self.double(addend)
            k >>= 1
        return result

    def in_subgroup(self, p) -> bool:
        """Prime-order subgroup membership: order * p == O (unreduced mul)."""
        assert self.order is not None
        return self.is_on_curve(p) and self.mul_unsafe(p, self.order) is None

    def msm(self, points, scalars):
        """Naive host MSM (oracle only — real MSM is ops.msm / native)."""
        acc = None
        for p, s in zip(points, scalars):
            acc = self.add(acc, self.mul(p, int(s)))
        return acc

    def random_point(self, generator):
        k = secrets.randbelow(self.order or (1 << 128))
        return self.mul(generator, k)

"""Host-side exact arithmetic: prime fields, extension towers, curves, pairings.

These are the correctness oracles and the verifier-side math. Hot bulk math runs
on device (spectre_tpu.ops) or in C++ (spectre_tpu.native); this package is pure
Python working over arbitrary-precision ints.
"""

from .common import PrimeField, make_prime_field, CurveGroup  # noqa: F401

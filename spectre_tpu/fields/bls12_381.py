"""BLS12-381: fields, groups, pairing, hash-to-curve, BLS signatures, serialization.

Witness-side curve: sync-committee pubkeys are G1 (48B compressed), aggregate
signatures are G2 (96B compressed). The preprocessor decompresses/aggregates
natively here (reference parity: `preprocessor/src/step.rs:62-158` +
`halo2curves` host ops, SURVEY.md §2b N5); the in-circuit constraint generation
happens over BN254 Fr via builder.fp_chip.

Tower: Fq2 = Fq[u]/(u^2+1), Fq12 = Fq[w]/(w^12 - 2 w^6 + 2) (so u = w^6 - 1);
G2 embeds into E(Fq12) via the M-twist x -> x/w^2, y -> y/w^3.

Hash-to-curve: BLS12381G2_XMD:SHA-256_SSWU_RO — expand_message_xmd(SHA-256) +
hash_to_field + simplified-SWU on the 3-isogenous curve + a Vélu-DERIVED
3-isogeny (kernel pinned by the j=0 codomain condition; isomorphism
normalization pinned by value, validated against blst-signed fixtures).
Interoperable with real eth2 validators (reference suite: the halo2-lib
`feat/bls12-381-hash2curve` fork, SURVEY.md L0). The round-1 SvdW variant
remains as `hash_to_g2_svdw` (uniform, spec-derivable, non-interoperable).
"""

from __future__ import annotations

import functools
import hashlib
import math

from ..spec import DST
from .common import CurveGroup, make_ext_field, make_prime_field
from .pairing import PairingEngine

P = 0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFAAAB
R = 0x73EDA753299D7D483339D80809A1D80553BDA402FFFE5BFEFFFFFFFF00000001
BLS_X = -0xD201000000010000  # BLS parameter (negative)

Fq = make_prime_field(P, "FqBLS")
Fr = make_prime_field(R, "FrBLS")
Fq2 = make_ext_field(P, [1, 0], "Fq2BLS")
Fq12 = make_ext_field(P, [2, 0, 0, 0, 0, 0, -2 % P, 0, 0, 0, 0, 0], "Fq12BLS")

B1 = Fq(4)
B2 = Fq2([4, 4])

g1_curve = CurveGroup(Fq, Fq(0), B1, order=R)
g2_curve = CurveGroup(Fq2, Fq2.zero(), B2, order=R)
g12_curve = CurveGroup(Fq12, Fq12.zero(), Fq12.from_base(4), order=R)

G1_GEN = (
    Fq(0x17F1D3A73197D7942695638C4FA9AC0FC3688C4F9774B905A14E3A3F171BAC586C55E83FF97A1AEFFB3AF00ADB22C6BB),
    Fq(0x08B3F481E3AAA0F1A09E30ED741D8AE4FCF5E095D5D00AF600DB18CB2C04B3EDD03CC744A2888AE40CAA232946C5E7E1),
)
G2_GEN = (
    Fq2([
        0x024AA2B2F08F0A91260805272DC51051C6E47AD4FA403B02B4510B647AE3D1770BAC0326A805BBEFD48056C8C121BDB8,
        0x13E02B6052719F607DACD3A088274F65596BD0D09920B61AB5DA61BBDC7F5049334CF11213945D57E5AC7D055D042B7E,
    ]),
    Fq2([
        0x0CE5D527727D6E118CC9CDC6DA2E351AADFD9BAA8CBDD3A76D429A695160D12C923AC9CC3BACA289E193548608B82801,
        0x0606C4A02EA734CC32ACD2B02BC28B99CB3E287E85A763AF267492AB572E99AB3F370D275CEC1DA1AAA9075FF05F79BE,
    ]),
)

assert g1_curve.is_on_curve(G1_GEN)
assert g2_curve.is_on_curve(G2_GEN)

# ---------------------------------------------------------------------------
# group orders & cofactors (lazily derived, deterministic, then sanity-checked)
# ---------------------------------------------------------------------------

_t1 = BLS_X + 1                    # trace of Frobenius over Fq
N1 = P + 1 - _t1                   # |E(Fq)|
H1 = N1 // R                       # G1 cofactor
assert N1 % R == 0


def _deterministic_twist_points(count: int):
    """First `count` points on E'(Fq2) with x = k + u, k = 0,1,2,..."""
    pts = []
    k = 0
    while len(pts) < count:
        x = Fq2([k, 1])
        y = (x * x * x + B2).sqrt()
        if y is not None:
            pts.append((x, y))
        k += 1
    return pts


@functools.cache
def twist_order() -> int:
    """|E'(Fq2)| for the M-twist, found among the six sextic-twist candidate
    orders p^2 + 1 - t' (checked against on-curve points). Avoids hardcoding."""
    t2 = _t1 * _t1 - 2 * P         # trace over Fq2
    # 4p^2 = t2^2 + 3 f2^2
    f2_sq, rem = divmod(4 * P * P - t2 * t2, 3)
    assert rem == 0
    f2 = math.isqrt(f2_sq)
    assert f2 * f2 == f2_sq
    candidates = [
        P * P + 1 - t2, P * P + 1 + t2,
        P * P + 1 - (t2 + 3 * f2) // 2, P * P + 1 + (t2 + 3 * f2) // 2,
        P * P + 1 - (t2 - 3 * f2) // 2, P * P + 1 + (t2 - 3 * f2) // 2,
    ]
    pts = _deterministic_twist_points(2)
    for n in candidates:
        if n % R == 0 and all(g2_curve.mul_unsafe(pt, n) is None for pt in pts):
            return n
    raise AssertionError("no twist order candidate matched")


@functools.cache
def g2_cofactor() -> int:
    return twist_order() // R


def clear_cofactor_g2(pt):
    return g2_curve.mul_unsafe(pt, g2_cofactor())


def clear_cofactor_g1(pt):
    return g1_curve.mul_unsafe(pt, H1)


# ---------------------------------------------------------------------------
# pairing (shared engine; BLS has no post-loop corrections)
# ---------------------------------------------------------------------------

ATE_LOOP_COUNT = -BLS_X  # 15132376222941642752

_W2_INV = Fq12([0, 0, 1] + [0] * 9).inv()
_W3_INV = Fq12([0, 0, 0, 1] + [0] * 8).inv()


def _fq2_to_fq12(x):
    """a0 + a1*u -> (a0 - a1) + a1 w^6   (u = w^6 - 1)."""
    a0, a1 = x.c
    return Fq12([(a0 - a1) % P, 0, 0, 0, 0, 0, a1, 0, 0, 0, 0, 0])


def twist(pt):
    if pt is None:
        return None
    x, y = pt
    return (_fq2_to_fq12(x) * _W2_INV, _fq2_to_fq12(y) * _W3_INV)


def cast_g1(pt):
    if pt is None:
        return None
    return (Fq12.from_base(pt[0].n), Fq12.from_base(pt[1].n))


ENGINE = PairingEngine(
    p=P, r=R, fq12=Fq12, g12_curve=g12_curve, twist=twist, cast_g1=cast_g1,
    loop_count=ATE_LOOP_COUNT, corrections=None,
)


def miller_loop(q, p, final_exp: bool = True):
    return ENGINE.miller_loop(q, p, final_exp)


def final_exponentiation(f):
    return ENGINE.final_exponentiation(f)


def pairing(q, p):
    """e(p, q): p in G1, q in G2 (twist coords)."""
    assert g2_curve.is_on_curve(q) and g1_curve.is_on_curve(p)
    return ENGINE.pairing(q, p)


def pairing_check(pairs) -> bool:
    return ENGINE.pairing_check(pairs)


# ---------------------------------------------------------------------------
# RFC 9380 hashing: expand_message_xmd + hash_to_field
# ---------------------------------------------------------------------------

def expand_message_xmd(msg: bytes, dst: bytes, len_in_bytes: int) -> bytes:
    """expand_message_xmd with SHA-256 (RFC 9380 §5.3.1)."""
    assert len(dst) <= 255
    b_in_bytes, r_in_bytes = 32, 64
    ell = (len_in_bytes + b_in_bytes - 1) // b_in_bytes
    assert ell <= 255
    dst_prime = dst + bytes([len(dst)])
    z_pad = b"\x00" * r_in_bytes
    l_i_b_str = len_in_bytes.to_bytes(2, "big")
    b0 = hashlib.sha256(z_pad + msg + l_i_b_str + b"\x00" + dst_prime).digest()
    b1 = hashlib.sha256(b0 + b"\x01" + dst_prime).digest()
    out = [b1]
    for i in range(2, ell + 1):
        prev = out[-1]
        tmp = bytes(a ^ c for a, c in zip(b0, prev))
        out.append(hashlib.sha256(tmp + bytes([i]) + dst_prime).digest())
    return b"".join(out)[:len_in_bytes]


L_FIELD = 64  # ceil((ceil(log2(p)) + k) / 8) with k=128 for BLS12-381


def hash_to_field_fq2(msg: bytes, dst: bytes, count: int = 2):
    """hash_to_field into Fq2 (m=2, L=64)."""
    len_in_bytes = count * 2 * L_FIELD
    pseudo = expand_message_xmd(msg, dst, len_in_bytes)
    out = []
    for i in range(count):
        coeffs = []
        for j in range(2):
            off = L_FIELD * (j + i * 2)
            coeffs.append(int.from_bytes(pseudo[off:off + L_FIELD], "big") % P)
        out.append(Fq2(coeffs))
    return out


# ---------------------------------------------------------------------------
# Shallue–van de Woestijne map to G2 (constants derived per RFC 9380 §H.1)
# ---------------------------------------------------------------------------

def _g2_rhs(x):
    return x * x * x + B2


@functools.cache
def _svdw_constants():
    """(Z, c1, c2, c3, c4) for the SvdW map on E': y^2 = x^3 + 4(1+u), derived
    from the RFC 9380 H.1 criteria over a fixed deterministic candidate order."""
    def candidates():
        for k in range(1, 20):
            yield Fq2([k, 0]); yield Fq2([-k % P, 0])
            yield Fq2([0, k]); yield Fq2([0, -k % P])
            yield Fq2([k, k]); yield Fq2([-k % P, -k % P])
    z = None
    for cand in candidates():
        gz = _g2_rhs(cand)
        if gz.is_zero():
            continue
        h = -(cand * cand * 3) / (gz * 4)      # A = 0
        if h.is_zero() or h.sqrt() is None:
            continue
        g_half = _g2_rhs(-cand / Fq2([2, 0]))
        if gz.sqrt() is not None or g_half.sqrt() is not None:
            z = cand
            break
    assert z is not None, "no SvdW Z found"
    c1 = _g2_rhs(z)
    c2 = -z / Fq2([2, 0])
    c3 = (-c1 * (z * z * 3)).sqrt()
    assert c3 is not None
    if c3.sgn0() != 0:
        c3 = -c3
    c4 = (-c1 * 4) / (z * z * 3)
    return z, c1, c2, c3, c4


def map_to_curve_svdw_g2(u: "Fq2"):
    """RFC 9380 §6.6.1 straight-line SvdW (constant set derived above)."""
    z, c1, c2, c3, c4 = _svdw_constants()
    one = Fq2.one()
    tv1 = u * u * c1
    tv2 = one + tv1
    tv1 = one - tv1
    tv3 = tv1 * tv2
    tv3 = tv3.inv() if not tv3.is_zero() else Fq2.zero()
    tv4 = u * tv1 * tv3 * c3
    x1 = c2 - tv4
    gx1 = _g2_rhs(x1)
    e1 = gx1.sqrt() is not None
    x2 = c2 + tv4
    gx2 = _g2_rhs(x2)
    e2 = (gx2.sqrt() is not None) and not e1
    x3 = (tv2 * tv2 * tv3) ** 2 * c4 + z
    x = x1 if e1 else (x2 if e2 else x3)
    gx = _g2_rhs(x)
    y = gx.sqrt()
    assert y is not None
    if u.sgn0() != y.sgn0():
        y = -y
    return (x, y)


def hash_to_g2_svdw(msg: bytes, dst: bytes = DST):
    """Round-1 SvdW variant (kept for reference/tests; NOT eth2-interoperable)."""
    u0, u1 = hash_to_field_fq2(msg, dst)
    q0 = map_to_curve_svdw_g2(u0)
    q1 = map_to_curve_svdw_g2(u1)
    return clear_cofactor_g2(g2_curve.add(q0, q1))


# ---------------------------------------------------------------------------
# RFC 9380 BLS12381G2_XMD:SHA-256_SSWU_RO (the eth2 ciphersuite)
#
# Simplified SWU on the 3-isogenous curve E2': y^2 = x^3 + A'x + B', followed
# by the 3-isogeny to E2. The isogeny is DERIVED here via Velu's formulas
# (the kernel x-coordinate is rationally determined by the j=0 codomain
# condition), then the one isomorphism normalization matching the standard
# suite is pinned as a constant validated against blst-signed fixtures
# (tests/test_fields.py) — no opaque hardcoded coefficient tables.
# Reference parity: the halo2-lib fork's `HashToCurveChip` (SURVEY.md L0,
# `Cargo.toml:77-86`) implements exactly this suite.
# ---------------------------------------------------------------------------

SSWU_A = Fq2([0, 240])            # A' = 240 u       (RFC 9380 §8.8.2)
SSWU_B = Fq2([1012, 1012])        # B' = 1012 (1+u)
SSWU_Z = Fq2([-2 % P, -1 % P])    # Z  = -(2+u)


def map_to_curve_sswu_g2prime(u: "Fq2"):
    """Simplified SWU (RFC 9380 §6.6.2) onto E2'."""
    A, B, Z = SSWU_A, SSWU_B, SSWU_Z
    one = Fq2.one()
    zu2 = Z * u * u
    tv1 = zu2 * zu2 + zu2            # Z^2 u^4 + Z u^2
    if tv1.is_zero():
        x1 = B / (Z * A)
    else:
        x1 = (-B / A) * (one + tv1.inv())
    gx1 = x1 * x1 * x1 + A * x1 + B
    y1 = gx1.sqrt()
    if y1 is not None:
        x, y = x1, y1
    else:
        x2 = zu2 * x1
        gx2 = x2 * x2 * x2 + A * x2 + B
        y2 = gx2.sqrt()
        assert y2 is not None, "SSWU: neither gx1 nor gx2 square"
        x, y = x2, y2
    if u.sgn0() != y.sgn0():
        y = -y
    return (x, y)


def _fq2_cbrt(a: "Fq2"):
    """Cube root in Fq2 (Adleman–Manders–Miller for r=3); None if non-residue."""
    q = P * P
    one = Fq2.one()
    if a.is_zero():
        return a
    if a ** ((q - 1) // 3) != one:
        return None
    s, t = 0, q - 1
    while t % 3 == 0:
        s, t = s + 1, t // 3
    alpha = pow(3, -1, t)
    x = a ** alpha                    # x^3 = a * b,  b in the 3-Sylow subgroup
    b = a ** (3 * alpha - 1)
    g = None
    for cand in Fq2._nonresidue_candidates():
        if not cand.is_zero() and cand ** ((q - 1) // 3) != one:
            g = cand ** t             # generator of the 3-Sylow (order 3^s)
            break
    assert g is not None
    order = 3 ** s
    # brute-force dlog of b^-1 in <g> (3-Sylow is tiny for BLS12-381)
    binv = b.inv()
    acc, j = one, None
    for i in range(order):
        if acc == binv:
            j = i
            break
        acc = acc * g
    assert j is not None and j % 3 == 0, "cbrt: dlog failed"
    return x * g ** (j // 3)


@functools.cache
def _iso3_constants():
    """Velu 3-isogeny E2' -> E2: kernel x, map coefficients, isomorphism
    scalings. The kernel is the unique order-3 subgroup whose quotient has
    j = 0; (c2, c3) = (c^2, c^3) for the c with c^6 = B2/b'' matching the
    standard suite (pinned by _ISO3_C_INDEX, fixture-validated)."""
    A, B = SSWU_A, SSWU_B
    # j(E2'/K) = 0  <=>  A - 5t = 0, t = 6 xQ^2 + 2A  =>  xQ^2 = -3A/10
    s_val = -A * Fq2([3, 0]) / Fq2([10, 0])
    # psi3(xQ) = 3 xQ^4 + 6 A xQ^2 + 12 B xQ - A^2 = 0 pins xQ rationally
    xq = (A * A - Fq2([3, 0]) * s_val * s_val - Fq2([6, 0]) * A * s_val) \
        / (Fq2([12, 0]) * B)
    assert xq * xq == s_val, "Velu: kernel x inconsistent"
    gq = xq * xq * xq + A * xq + B
    t = Fq2([6, 0]) * s_val + Fq2([2, 0]) * A
    uq = Fq2([4, 0]) * gq
    w = uq + xq * t
    assert (A - Fq2([5, 0]) * t).is_zero(), "Velu: codomain j != 0"
    b2 = B - Fq2([7, 0]) * w          # codomain: y^2 = x^3 + b2
    v = B2 / b2
    # the 6 isomorphism scalings c with c^6 = v
    d0 = _fq2_cbrt(v)
    assert d0 is not None, "B2/b'' not a cube — isogeny derivation wrong"
    omega = None
    for cand in Fq2._nonresidue_candidates():
        h = cand ** ((P * P - 1) // 3)
        if h != Fq2.one():
            omega = h
            break
    cs = []
    for i in range(3):
        d = d0 * omega ** i
        c = d.sqrt()
        if c is not None:
            cs.append(c)
            cs.append(-c)
    assert cs, "no isomorphism E2'/K -> E2 over Fq2"
    assert _ISO3_C in cs, "pinned isomorphism constant not among derived roots"
    return xq, t, uq, cs


# Which of the 6 isomorphism normalizations equals the standard ciphersuite
# map: selected once against the blst-signed 512-validator fixture (see
# tests/test_fields.py) and pinned BY VALUE; _iso3_constants asserts it is
# one of the derived c^6 = B2/b'' roots, so a derivation drift is caught.
_ISO3_C = None  # set below (needs Fq2 defined)


def iso3_map(pt):
    """The derived 3-isogeny E2' -> E2 (Velu rational map + isomorphism)."""
    xq, t, uq, _cs = _iso3_constants()
    c = _ISO3_C
    c2, c3 = c * c, c * c * c
    x, y = pt
    dx = x - xq
    if dx.is_zero():
        return None  # kernel point: iso_map sends it to the identity (RFC 9380)
    dxi = dx.inv()
    dxi2 = dxi * dxi
    xx = x + t * dxi + uq * dxi2
    yy = y * (Fq2.one() - t * dxi2 - Fq2([2, 0]) * uq * dxi2 * dxi)
    return (c2 * xx, c3 * yy)


_ISO3_C = Fq2([0x8AB05F8BDD54CDE190937E76BC3E447CC27C3D6FBD7063FCD104635A790520C0A395554E5C6AAAA9354FFFFFFFFE38E, 0])


# ---------------------------------------------------------------------------
# psi endomorphism on E'(Fq2): untwist -> p-Frobenius -> twist, in constant
# form psi(x, y) = (cx * conj(x), cy * conj(y)). Used for fast cofactor
# clearing (Budroni–Pintore) and G2 subgroup checks (psi(Q) == [x]Q), both
# host-side and as the oracle for the in-circuit pairing chips.
# ---------------------------------------------------------------------------

def _fq2_conj(a: "Fq2") -> "Fq2":
    return Fq2([a.c[0], (-a.c[1]) % P])


@functools.cache
def psi_constants():
    """(cx, cy) with psi(x,y) = (cx*conj(x), cy*conj(y)); derived by pushing
    a sample point through twist -> Frobenius -> untwist and verified on an
    independent point."""
    W2 = Fq12([0, 0, 1] + [0] * 9)
    W3 = Fq12([0, 0, 0, 1] + [0] * 8)

    def raw_psi(pt):
        x, y = twist(pt)
        fx, fy = x ** P, y ** P

        def to_fq2(v):
            c = v.c
            assert all(ci == 0 for i, ci in enumerate(c) if i not in (0, 6))
            return Fq2([(c[0] + c[6]) % P, c[6]])

        return (to_fq2(fx * W2), to_fq2(fy * W3))

    q1 = g2_curve.mul(G2_GEN, 123)
    px, py = raw_psi(q1)
    cx = px / _fq2_conj(q1[0])
    cy = py / _fq2_conj(q1[1])
    q2 = g2_curve.mul(G2_GEN, 987654321987654321)
    assert raw_psi(q2) == (cx * _fq2_conj(q2[0]), cy * _fq2_conj(q2[1]))
    return cx, cy


def g2_psi(pt):
    if pt is None:
        return None
    cx, cy = psi_constants()
    return (cx * _fq2_conj(pt[0]), cy * _fq2_conj(pt[1]))


def g2_smul(pt, k: int):
    """Scalar mul with signed k (no subgroup assumption)."""
    if k < 0:
        r = g2_curve.mul_unsafe(pt, -k)
        return None if r is None else g2_curve.neg(r)
    return g2_curve.mul_unsafe(pt, k)


def g2_in_subgroup_psi(pt) -> bool:
    """Q in G2 iff psi(Q) == [x]Q (endomorphism eigenvalue check)."""
    if pt is None:
        return True
    return g2_psi(pt) == g2_smul(pt, BLS_X)


def clear_cofactor_g2_bp(pt):
    """Budroni–Pintore: [x^2-x-1]Q + [x-1]psi(Q) + psi^2(2Q). Equal to
    H_EFF_G2 * Q for every curve point (asserted in tests)."""
    a = g2_smul(pt, BLS_X * BLS_X - BLS_X - 1)
    b = g2_smul(g2_psi(pt), BLS_X - 1)
    c = g2_psi(g2_psi(g2_smul(pt, 2)))
    return g2_curve.add(g2_curve.add(a, b), c)


# h_eff for the G2 suite (RFC 9380 §8.8.2): the scalar equivalent of the
# Budroni–Pintore endomorphism-accelerated clearing. NOT equal to the plain
# cofactor H2 — outputs differ by a unit mod r, so interop REQUIRES h_eff.
# Structural check (h_eff kills the cofactor part: h_eff = m*H2 mod N2 with
# m a unit mod r) + blst-fixture validation live in tests/test_fields.py.
H_EFF_G2 = 0xBC69F08F2EE75B3584C6A0EA91B352888E2A8E9145AD7689986FF031508FFE1329C2F178731DB956D82BF015D1212B02EC0EC69D7477C1AE954CBC06689F6A359894C0ADEBBF6B4E8020005AAA95551


def hash_to_g2(msg: bytes, dst: bytes = DST):
    """hash_to_curve per BLS12381G2_XMD:SHA-256_SSWU_RO (eth2 interop).

    Reference parity: `HashToCurveChip` (SSWU + ExpandMsgXmd) in the
    halo2-lib fork (`sync_step_circuit.rs:165-169` uses it in-circuit)."""
    u0, u1 = hash_to_field_fq2(msg, dst)
    q0 = iso3_map(map_to_curve_sswu_g2prime(u0))
    q1 = iso3_map(map_to_curve_sswu_g2prime(u1))
    return g2_curve.mul_unsafe(g2_curve.add(q0, q1), H_EFF_G2)


# ---------------------------------------------------------------------------
# BLS signatures (eth2 flavor: pubkeys in G1, signatures in G2)
# ---------------------------------------------------------------------------

def sk_to_pk(sk: int):
    return g1_curve.mul(G1_GEN, sk % R)


def sign(sk: int, msg: bytes, dst: bytes = DST):
    return g2_curve.mul(hash_to_g2(msg, dst), sk % R)


def aggregate_signatures(sigs):
    acc = None
    for s in sigs:
        acc = g2_curve.add(acc, s)
    return acc


def aggregate_pubkeys(pks):
    acc = None
    for pk in pks:
        acc = g1_curve.add(acc, pk)
    return acc


def verify(pk, msg: bytes, sig, dst: bytes = DST) -> bool:
    """e(pk, H(m)) == e(g1, sig)  <=>  e(pk, H(m)) * e(-g1, sig) == 1.

    Rejects identity pubkey/signature up front (eth2 KeyValidate: accepting the
    point at infinity enables the classic zero-key forgery)."""
    if pk is None or sig is None:
        return False
    h = hash_to_g2(msg, dst)
    return pairing_check([(pk, h), (g1_curve.neg(G1_GEN), sig)])


def fast_aggregate_verify(pks, msg: bytes, sig, dst: bytes = DST) -> bool:
    if not pks or any(pk is None for pk in pks):
        return False
    return verify(aggregate_pubkeys(pks), msg, sig, dst)


# ---------------------------------------------------------------------------
# ZCash/eth2 point serialization (compressed, with flag bits)
# ---------------------------------------------------------------------------

_COMP_FLAG = 1 << 7
_INF_FLAG = 1 << 6
_SIGN_FLAG = 1 << 5


def _fq_sign(y: "Fq") -> bool:
    return y.n > (P - 1) // 2


def _fq2_sign(y: "Fq2") -> bool:
    """Lexicographic: c1 dominates; tie-break on c0."""
    if y.c[1] != 0:
        return y.c[1] > (P - 1) // 2
    return y.c[0] > (P - 1) // 2


def g1_compress(pt) -> bytes:
    """48-byte compressed G1 (reference handles these in
    `committee_update_circuit.rs:129` / preprocessor pubkey decompress)."""
    if pt is None:
        return bytes([_COMP_FLAG | _INF_FLAG]) + b"\x00" * 47
    x, y = pt
    b = bytearray(int(x).to_bytes(48, "big"))
    b[0] |= _COMP_FLAG
    if _fq_sign(y):
        b[0] |= _SIGN_FLAG
    return bytes(b)


def g1_decompress(b: bytes, subgroup_check: bool = False):
    assert len(b) == 48
    flags = b[0]
    assert flags & _COMP_FLAG, "uncompressed flag"
    if flags & _INF_FLAG:
        assert flags == (_COMP_FLAG | _INF_FLAG) and b[1:] == b"\x00" * 47, \
            "non-canonical infinity encoding"
        return None
    xi = int.from_bytes(bytes([flags & 0x1F]) + b[1:], "big")
    assert xi < P, "x not canonical"
    x = Fq(xi)
    y = (x * x * x + B1).sqrt()
    assert y is not None, "x not on curve"
    if _fq_sign(y) != bool(flags & _SIGN_FLAG):
        y = -y
    pt = (x, y)
    if subgroup_check:
        assert g1_curve.in_subgroup(pt), "point not in G1 subgroup"
    return pt


def g2_compress(pt) -> bytes:
    if pt is None:
        return bytes([_COMP_FLAG | _INF_FLAG]) + b"\x00" * 95
    x, y = pt
    b = bytearray(x.c[1].to_bytes(48, "big") + x.c[0].to_bytes(48, "big"))
    b[0] |= _COMP_FLAG
    if _fq2_sign(y):
        b[0] |= _SIGN_FLAG
    return bytes(b)


def g2_decompress(b: bytes, subgroup_check: bool = False):
    assert len(b) == 96
    flags = b[0]
    assert flags & _COMP_FLAG, "uncompressed flag"
    if flags & _INF_FLAG:
        assert flags == (_COMP_FLAG | _INF_FLAG) and b[1:] == b"\x00" * 95, \
            "non-canonical infinity encoding"
        return None
    c1 = int.from_bytes(bytes([flags & 0x1F]) + b[1:48], "big")
    c0 = int.from_bytes(b[48:], "big")
    assert c0 < P and c1 < P, "x not canonical"
    x = Fq2([c0, c1])
    y = (x * x * x + B2).sqrt()
    assert y is not None, "x not on curve"
    if _fq2_sign(y) != bool(flags & _SIGN_FLAG):
        y = -y
    pt = (x, y)
    if subgroup_check:
        assert g2_curve.in_subgroup(pt), "point not in G2 subgroup"
    return pt


def __getattr__(name):
    # lazily-derived constants kept available under their public names
    if name == "N2":
        return twist_order()
    if name == "H2":
        return g2_cofactor()
    if name == "Z_SVDW":
        return _svdw_constants()[0]
    if name == "DST_G2":  # legacy alias
        return DST
    raise AttributeError(name)

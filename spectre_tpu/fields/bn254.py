"""BN254 (alt_bn128): fields, groups, optimal-ate pairing, Fr FFT constants.

This curve hosts the proving system (KZG commitments live in G1, the verifier
pairs against G2). Plays the role of the reference's `halo2curves-axiom` BN254
host arithmetic (SURVEY.md §2b N1); the throughput path is ops.field_ops /
ops.msm on TPU and native/ in C++ — this module is the exact oracle and the
verifier math.

Pairing construction follows the standard optimal-ate recipe over the tower
Fq12 = Fq[w]/(w^12 - 18 w^6 + 82)  (so u = w^6 - 9 with Fq2 = Fq[u]/(u^2+1)),
with G2 points embedded via the sextic twist x -> x*w^2, y -> y*w^3.
"""

from __future__ import annotations

from .common import CurveGroup, make_ext_field, make_prime_field

# field moduli
P = 21888242871839275222246405745257275088696311157297823662689037894645226208583
R = 21888242871839275222246405745257275088548364400416034343698204186575808495617

Fq = make_prime_field(P, "FqBN254")
Fr = make_prime_field(R, "FrBN254")

Fq2 = make_ext_field(P, [1, 0], "Fq2BN254")           # u^2 = -1
Fq12 = make_ext_field(P, [82, 0, 0, 0, 0, 0, -18 % P, 0, 0, 0, 0, 0], "Fq12BN254")

# curves
g1_curve = CurveGroup(Fq, Fq(0), Fq(3), order=R, cofactor=1)
g2_curve = CurveGroup(Fq2, Fq2.zero(), Fq2([3, 0]) / Fq2([9, 1]), order=R)
g12_curve = CurveGroup(Fq12, Fq12.zero(), Fq12.from_base(3), order=R)

G1_GEN = (Fq(1), Fq(2))
G2_GEN = (
    Fq2([
        10857046999023057135944570762232829481370756359578518086990519993285655852781,
        11559732032986387107991004021392285783925812861821192530917403151452391805634,
    ]),
    Fq2([
        8495653923123431417604973247489272438418190587263600148770280649306958101930,
        4082367875863433681332203403145435568316851327593401208105741076214120093531,
    ]),
)

# BN parameter t: p(t), r(t) are the standard BN polynomials; ate loop is 6t+2.
BN_T = 4965661367192848881
ATE_LOOP_COUNT = 6 * BN_T + 2  # 29793968203157093288


# ---------------------------------------------------------------------------
# twist embedding  E'(Fq2) -> E(Fq12)
# ---------------------------------------------------------------------------

_W2 = Fq12([0, 0, 1] + [0] * 9)   # w^2
_W3 = Fq12([0, 0, 0, 1] + [0] * 8)  # w^3


def _fq2_to_fq12(x: "Fq2") -> "Fq12":
    """a0 + a1*u  ->  (a0 - 9 a1) + a1 w^6   (since u = w^6 - 9)."""
    a0, a1 = x.c
    return Fq12([(a0 - 9 * a1) % P, 0, 0, 0, 0, 0, a1, 0, 0, 0, 0, 0])


def twist(pt):
    """Embed a G2 (twist-curve) point into E(Fq12)."""
    if pt is None:
        return None
    x, y = pt
    return (_fq2_to_fq12(x) * _W2, _fq2_to_fq12(y) * _W3)


def cast_g1(pt):
    if pt is None:
        return None
    x, y = pt
    return (Fq12.from_base(x.n), Fq12.from_base(y.n))


# ---------------------------------------------------------------------------
# optimal ate pairing (shared engine + BN frobenius corrections)
# ---------------------------------------------------------------------------

from .pairing import PairingEngine, linefunc  # noqa: E402


def _bn_corrections(f, r_pt, q, pt):
    """The two extra frobenius-twisted line evaluations BN curves require."""
    q1 = (q[0] ** P, q[1] ** P)
    nq2 = (q1[0] ** P, -(q1[1] ** P))
    f = f * linefunc(r_pt, q1, pt)
    r_pt = g12_curve.add(r_pt, q1)
    return f * linefunc(r_pt, nq2, pt)


ENGINE = PairingEngine(
    p=P, r=R, fq12=Fq12, g12_curve=g12_curve, twist=twist, cast_g1=cast_g1,
    loop_count=ATE_LOOP_COUNT, corrections=_bn_corrections,
)


def miller_loop(q, p, final_exp: bool = True):
    return ENGINE.miller_loop(q, p, final_exp)


def final_exponentiation(f: "Fq12") -> "Fq12":
    return ENGINE.final_exponentiation(f)


def pairing(q, p):
    """e(p, q): p in G1 (Fq coords), q in G2 (Fq2 coords)."""
    assert g2_curve.is_on_curve(q), "q not on twist curve"
    assert g1_curve.is_on_curve(p), "p not on curve"
    return ENGINE.pairing(q, p)


def pairing_check(pairs) -> bool:
    """prod e(p_i, q_i) == 1, with a single shared final exponentiation.

    This is the verifier's KZG check  e(W, [tau]_2) * e(Z, -[1]_2) * ... == 1.
    (A None entry is the zero commitment: e(O, Q) = 1, legitimately skipped.)
    """
    return ENGINE.pairing_check(pairs)


# ---------------------------------------------------------------------------
# Fr FFT/NTT constants (used by plonk.domain and ops.ntt)
# ---------------------------------------------------------------------------

# 2-adicity of r-1 and a multiplicative generator of Fr^*.
FR_S = 28
FR_GENERATOR = 7
_t = (R - 1) >> FR_S
FR_ROOT_OF_UNITY = pow(FR_GENERATOR, _t, R)  # order 2^28
assert pow(FR_ROOT_OF_UNITY, 1 << 27, R) == R - 1, "root of unity sanity"


def fr_root_of_unity(k: int) -> int:
    """Primitive 2^k-th root of unity in Fr."""
    assert 0 <= k <= FR_S
    return pow(FR_ROOT_OF_UNITY, 1 << (FR_S - k), R)


# ---------------------------------------------------------------------------
# serialization (uncompressed + compressed, for transcripts/SRS files)
# ---------------------------------------------------------------------------

def g1_to_bytes(pt) -> bytes:
    """64-byte uncompressed BE (x||y); all-zero for infinity."""
    if pt is None:
        return b"\x00" * 64
    return int(pt[0]).to_bytes(32, "big") + int(pt[1]).to_bytes(32, "big")


def g1_from_bytes(b: bytes):
    # explicit raises: deserializes untrusted proof/SRS bytes and must
    # reject under `python -O` (asserts stripped) as well
    if len(b) != 64:
        raise ValueError("g1 point must be 64 bytes")
    if b == b"\x00" * 64:
        return None
    x, y = int.from_bytes(b[:32], "big"), int.from_bytes(b[32:], "big")
    if x >= P or y >= P:
        raise ValueError("non-canonical g1 coordinate")
    pt = (Fq(x), Fq(y))
    if not g1_curve.is_on_curve(pt):
        raise ValueError("g1 point not on curve")
    return pt


def g2_to_bytes(pt) -> bytes:
    """128-byte uncompressed BE (x.c1||x.c0||y.c1||y.c0); zeros for infinity."""
    if pt is None:
        return b"\x00" * 128
    x, y = pt
    return (x.c[1].to_bytes(32, "big") + x.c[0].to_bytes(32, "big")
            + y.c[1].to_bytes(32, "big") + y.c[0].to_bytes(32, "big"))


def g2_from_bytes(b: bytes):
    if len(b) != 128:
        raise ValueError("g2 point must be 128 bytes")
    if b == b"\x00" * 128:
        return None
    ws = [int.from_bytes(b[i:i + 32], "big") for i in range(0, 128, 32)]
    if any(w >= P for w in ws):
        raise ValueError("non-canonical g2 coordinate")
    x = Fq2([ws[1], ws[0]])
    y = Fq2([ws[3], ws[2]])
    pt = (x, y)
    if not g2_curve.is_on_curve(pt):
        raise ValueError("g2 point not on curve")
    return pt

"""Shared Miller-loop / final-exponentiation machinery for BN and BLS pairings.

One parameterized engine instead of two near-identical copies: a curve module
supplies its Fq12, the E(Fq12) group, the twist embedding, the ate loop count,
and an optional post-loop correction hook (BN curves add two frobenius lines;
BLS curves add nothing).
"""

from __future__ import annotations


def linefunc(p1, p2, t):
    """Evaluate the line through p1,p2 (tangent if equal) at t; affine Fq12 coords."""
    x1, y1 = p1
    x2, y2 = p2
    xt, yt = t
    if x1 != x2:
        m = (y2 - y1) / (x2 - x1)
        return m * (xt - x1) - (yt - y1)
    elif y1 == y2:
        m = (x1 * x1 * 3) / (y1 * 2)
        return m * (xt - x1) - (yt - y1)
    else:
        return xt - x1


class PairingEngine:
    """Optimal-ate pairing over a sextic-twist embedding into Fq12."""

    def __init__(self, *, p, r, fq12, g12_curve, twist, cast_g1, loop_count,
                 corrections=None):
        self.p = p
        self.r = r
        self.fq12 = fq12
        self.g12 = g12_curve
        self.twist = twist
        self.cast_g1 = cast_g1
        self.loop_count = loop_count
        self.corrections = corrections  # fn(f, r_pt, q, p_cast) -> f

    def miller_loop(self, q, pt, final_exp: bool = True):
        """q: twisted G2 point in E(Fq12); pt: G1 point cast into E(Fq12)."""
        if q is None or pt is None:
            return self.fq12.one()
        r_pt, f = q, self.fq12.one()
        for i in range(self.loop_count.bit_length() - 2, -1, -1):
            f = f * f * linefunc(r_pt, r_pt, pt)
            r_pt = self.g12.double(r_pt)
            if self.loop_count & (1 << i):
                f = f * linefunc(r_pt, q, pt)
                r_pt = self.g12.add(r_pt, q)
        if self.corrections is not None:
            f = self.corrections(f, r_pt, q, pt)
        if final_exp:
            return self.final_exponentiation(f)
        return f

    def final_exponentiation(self, f):
        return f ** ((self.p ** 12 - 1) // self.r)

    def pairing(self, q, pt, final_exp: bool = True):
        """e(pt, q) with q in G2 (twist coords), pt in G1 (base-field coords)."""
        return self.miller_loop(self.twist(q), self.cast_g1(pt), final_exp)

    def pairing_check(self, pairs) -> bool:
        """prod e(p_i, q_i) == 1 with one shared final exponentiation.

        NOTE: a None (infinity) entry contributes the trivial factor 1 — that is
        the correct group-theoretic behavior for e(O, Q). Protocol-level rules
        (e.g. BLS KeyValidate rejecting identity pubkeys) belong to the caller.
        """
        f = self.fq12.one()
        for pt, q in pairs:
            if pt is None or q is None:
                continue
            f = f * self.miller_loop(self.twist(q), self.cast_g1(pt), final_exp=False)
        return self.final_exponentiation(f) == self.fq12.one()

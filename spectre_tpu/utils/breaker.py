"""Generic circuit breaker (ISSUE 11: extracted from the beacon client).

The beacon client grew the reference breaker in PR 3: N consecutive
failures trip it OPEN (calls fail fast for a cooldown), then HALF-OPEN
admits exactly one trial request — success closes it, failure re-opens
it for another cooldown. The proof-farm dispatcher needs the identical
machinery per prover replica, so the state machine lives here once and
both layers parameterize it with their own counter prefix:

* ``beacon_breaker_trips`` / ``beacon_breaker_half_open`` (BeaconClient)
* ``dispatcher_breaker_trips`` / ``dispatcher_breaker_half_open``
  (prover_service/dispatcher.py, one breaker per replica)

Counters ride :data:`~spectre_tpu.utils.health.HEALTH`, so they surface
in ``/healthz`` and as ``spectre_*_total`` in ``/metrics`` with zero
exporter changes. ``clock`` is injectable for deterministic tests.
"""

from __future__ import annotations

import time

from .health import HEALTH

# numeric codes for the Prometheus exporter (a gauge can't carry a
# string; alerting rules compare against these)
STATE_CODES = {"closed": 0, "half-open": 1, "open": 2}


class BreakerOpen(RuntimeError):
    """Failing fast: the breaker is open (downstream considered down)."""


class CircuitBreaker:
    """Consecutive-failure breaker with a half-open trial admission.

    State is derived, never stored: ``opened_at is None`` means closed;
    an ``opened_at`` older than ``cooldown`` means half-open (one trial
    admitted); anything younger means open. ``record(ok)`` feeds it.
    """

    def __init__(self, threshold: int = 5, cooldown: float = 30.0,
                 health=HEALTH, counter_prefix: str = "breaker",
                 clock=time.time):
        self.threshold = int(threshold)
        self.cooldown = float(cooldown)
        self.health = health
        self.counter_prefix = counter_prefix
        self._clock = clock
        self.consecutive_failures = 0
        self.opened_at: float | None = None
        self._half_open = False

    @property
    def state(self) -> str:
        if self.opened_at is None:
            return "closed"
        if self._clock() - self.opened_at >= self.cooldown:
            return "half-open"
        return "open"

    @property
    def state_code(self) -> int:
        return STATE_CODES.get(self.state, -1)

    def remaining(self) -> float:
        """Seconds of cooldown left (0 when not open)."""
        if self.opened_at is None:
            return 0.0
        return max(0.0, self.cooldown - (self._clock() - self.opened_at))

    def admit(self):
        """Gate one call: raises :class:`BreakerOpen` while open; the
        first admission after the cooldown marks the half-open trial
        (counted on ``<prefix>_half_open``)."""
        state = self.state
        if state == "open":
            raise BreakerOpen(
                f"circuit breaker open for another {self.remaining():.1f}s "
                f"after {self.consecutive_failures} consecutive failures")
        if state == "half-open" and not self._half_open:
            self._half_open = True
            self.health.incr(f"{self.counter_prefix}_half_open")

    def record(self, ok: bool):
        """Feed one call outcome. A success closes the breaker; a failed
        half-open trial (or hitting the threshold) re-opens it for a full
        cooldown and counts a trip on ``<prefix>_trips``."""
        if ok:
            self.consecutive_failures = 0
            self.opened_at = None
            self._half_open = False
            return
        self.consecutive_failures += 1
        half_open_failed = self._half_open
        self._half_open = False
        if (half_open_failed
                or self.consecutive_failures >= self.threshold):
            if self.opened_at is None or half_open_failed:
                self.health.incr(f"{self.counter_prefix}_trips")
            self.opened_at = self._clock()

    def snapshot(self) -> dict:
        return {"state": self.state, "state_code": self.state_code,
                "consecutive_failures": self.consecutive_failures}

"""Circuit-shape pinning: freeze (k, columns, tables, break points) to JSON.

Reference parity: `Halo2ConfigPinning` / `Eth2ConfigPinning`
(`util/circuit.rs:26-78`) + the JSON files under `lightclient-circuits/
config/` — the reproducible-prover-setup system: the prover re-creates the
circuit from pinning per request, never re-deriving the layout.
"""

from __future__ import annotations

import json
import os
from dataclasses import asdict, dataclass

from ..plonk.constraint_system import CircuitConfig


@dataclass
class Pinning:
    config: CircuitConfig
    break_points: list

    def write(self, path: str):
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        with open(path, "w") as f:
            json.dump({
                "config": {**asdict(self.config),
                           "lookup_tables": list(self.config.lookup_tables)},
                "break_points": self.break_points,
            }, f, indent=1)

    @classmethod
    def read(cls, path: str) -> "Pinning":
        with open(path) as f:
            data = json.load(f)
        c = data["config"]
        c["lookup_tables"] = tuple(c.get("lookup_tables") or ())
        return cls(CircuitConfig(**c), data["break_points"])

    @classmethod
    def load_or_create(cls, path: str, ctx, k: int, lookup_bits: int) -> "Pinning":
        """Use the pinned shape if present; otherwise auto-size from the
        context and persist (reference: written on first keygen,
        `util/circuit.rs:132-135`)."""
        if path and os.path.exists(path):
            pin = cls.read(path)
            # a pinning written for a different circuit shape must not be
            # silently reused: the layout would place the new witness into
            # the old column plan and fail (at best) after a full prove
            assert pin.config.lookup_bits == lookup_bits, \
                f"pinned lookup_bits {pin.config.lookup_bits} != requested " \
                f"{lookup_bits}: circuit shape changed — delete {path} (and " \
                f"the matching .pk) to re-pin"
            assert pin.config.num_sha_slots >= len(ctx.sha_slots), \
                f"pinning has {pin.config.num_sha_slots} sha slots, circuit " \
                f"uses {len(ctx.sha_slots)}: shape changed — delete {path}"
            return pin
        cfg = ctx.auto_config(k=k, lookup_bits=lookup_bits)
        _, _, _, _, _, _, bp = ctx.layout(cfg)
        pin = cls(cfg, bp)
        if path:
            pin.write(path)
        return pin

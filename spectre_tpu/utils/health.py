"""ServiceHealth: thread-safe degradation/retry counters.

Every graceful-degradation path in the service (beacon retry/backoff,
circuit-breaker transitions, device-prove CPU fallback, fixed-base MSM
table-budget degrade, job-queue dedup/requeue, proof-farm dispatch:
`dispatcher_*` lease takeovers/breaker skips/SDC reroutes and
`beacon_quorum_*` dissent counting) increments a named counter here
instead of logging and forgetting. The prover service surfaces the
snapshot via the `health` RPC method and GET /healthz, and every counter
exports as `spectre_<name>_total` in /metrics — new counters need zero
exporter changes.

Dependency-free on purpose: ops/ kernels and the preprocessor increment
counters without pulling in the service layer.
"""

from __future__ import annotations

import threading
import time


class ServiceHealth:
    def __init__(self):
        self._lock = threading.Lock()
        self._counters: dict[str, int] = {}
        self._gauges: dict[str, tuple[int, float]] = {}  # name -> (n, sum)
        self._started = time.time()

    def incr(self, name: str, n: int = 1) -> int:
        with self._lock:
            v = self._counters.get(name, 0) + n
            self._counters[name] = v
            return v

    def get(self, name: str) -> int:
        with self._lock:
            return self._counters.get(name, 0)

    def observe(self, name: str, value: float):
        """Record a sample for a running-mean gauge (e.g. prove latency —
        the admission controller derives retry_after_s from its mean)."""
        with self._lock:
            n, total = self._gauges.get(name, (0, 0.0))
            self._gauges[name] = (n + 1, total + float(value))

    def mean(self, name: str, default: float = 0.0) -> float:
        with self._lock:
            n, total = self._gauges.get(name, (0, 0.0))
            return total / n if n else default

    def snapshot(self) -> dict:
        with self._lock:
            snap = {"uptime_s": round(time.time() - self._started, 3),
                    "counters": dict(sorted(self._counters.items()))}
            if self._gauges:
                snap["means"] = {k: round(total / n, 6)
                                 for k, (n, total)
                                 in sorted(self._gauges.items()) if n}
            return snap

    def reset(self):
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._started = time.time()


# process-global default: the service, the beacon client and the MSM
# degrade path all meet on this instance unless a caller injects its own
HEALTH = ServiceHealth()

"""Deterministic fault injection (SPECTRE_FAULT_PLAN) for resilience tests.

Grammar::

    SPECTRE_FAULT_PLAN = entry[,entry...]
    entry              = site ":" kind [":" count]      (count defaults to 1)

e.g. ``SPECTRE_FAULT_PLAN=beacon.fetch:http503:3,backend.prove:oom`` arms
three injected HTTP 503s at the beacon-fetch boundary and one simulated
device OOM at the backend-prove boundary. Each armed entry fires ``count``
times (in plan order per site) and then disarms; un-named sites are
zero-cost no-ops.

Injection sites are registered in :data:`SITES` (site -> (module,
description)); render the table with ``render_site_table()`` or
``python -m spectre_tpu.prover_service faults --list``. The README's
fault-site table is generated from that registry and pinned by a parity
test — extend SITES when threading a new ``faults.check(...)`` call.

Kinds and the exception they raise:

    raise       InjectedFault                (generic transient error)
    oom         InjectedFault, oom-classified by backend.is_device_oom
    compile     InjectedFault, classified by backend.is_compile_failure
    http503     urllib HTTPError 503 (Retry-After: 0)
    http429     urllib HTTPError 429 (Retry-After: 0.01)
    timeout     TimeoutError
    connreset   ConnectionResetError
    ioerror     OSError
    diskfull    OSError(errno.ENOSPC) — a full disk at a write site; the
                job must fail with a typed error (or degrade best-effort
                where the write is optional, e.g. manifests), never crash
                the worker or wedge the queue
    crash       InjectedCrash (BaseException: simulates a hard worker kill —
                deliberately NOT caught by ``except Exception`` recovery
                paths, so journal-replay tests exercise a real mid-prove
                death)
    corrupt     no exception — DATA corruption: ``mangle(site, data)``
                bit-flips one byte of the payload passing through the
                site (silent disk rot / a torn DMA, the failure mode
                end-to-end checksums exist for). ``check()`` ignores
                ``corrupt`` entries; only ``mangle()`` consumes them.

The registry is thread-safe and records every firing in ``fired`` so tests
assert exact retry counts. Tests arm plans programmatically via ``arm()``/
``install_plan()``; CI can arm whole scenarios through the environment.
"""

from __future__ import annotations

import io
import os
import threading

ENV_VAR = "SPECTRE_FAULT_PLAN"

KINDS = ("raise", "oom", "compile", "http503", "http429", "timeout",
         "connreset", "ioerror", "diskfull", "crash", "corrupt")

# Canonical site registry: site -> (module that calls check()/mangle(),
# what the fault injects into). The README table and the
# `prover_service faults --list` CLI are both generated from this dict,
# so a new site added here shows up everywhere at once.
SITES = {
    "beacon.fetch": ("preprocessor/beacon.py",
                     "every beacon REST GET attempt"),
    "srs.load": ("plonk/srs.py", "SRS file read / setup"),
    "backend.prove": ("plonk/backend.py", "prove_with_fallback entry"),
    "journal.write": ("prover_service/jobs.py",
                      "each fsync'd job-journal append"),
    "journal.compact": ("prover_service/jobs.py",
                        "staged-sidecar swap window"),
    "artifact.write": ("utils/artifacts.py", "result-file atomic write"),
    "artifact.read": ("utils/artifacts.py", "result-file read + verify"),
    "metrics.write": ("utils/profiling.py",
                      "SPECTRE_METRICS JSONL append (a broken metrics "
                      "sink must never fail a prove)"),
    "manifest.write": ("prover_service/jobs.py",
                       "provenance-manifest artifact write (tolerated: "
                       "the job still finishes, the manifest degrades "
                       "to absent)"),
    "proof.bytes": ("prover_service/selfverify.py",
                    "fresh proof bytes between prove and "
                    "verify-before-serve (kind `corrupt` is the silent "
                    "data corruption the self-verify layer catches)"),
    "follower.journal": ("follower/updates.py",
                         "verified-update-store journal append (the "
                         "follower chain record behind each stored "
                         "light-client update)"),
    "replica.dispatch": ("prover_service/dispatcher.py",
                         "replica-side prove entry under a dispatcher "
                         "lease (kind `crash` kills the replica "
                         "mid-prove: the lease dies unrenewed and the "
                         "job moves to a surviving replica)"),
    "replica.health": ("prover_service/dispatcher.py",
                       "replica health probe during dispatch routing "
                       "(a failing probe marks the replica unhealthy; "
                       "it is skipped, not crashed)"),
    "replica.lease": ("prover_service/dispatcher.py",
                      "lease-journal append, AFTER the record lands "
                      "(the post-append crash window restart replay "
                      "must cover; `ioerror` is tolerated — counted on "
                      "dispatcher_lease_journal_failures)"),
    "replica.lease_compact": ("prover_service/dispatcher.py",
                              "lease-journal compaction, staged-sidecar "
                              "swap window (kind `crash` leaves the "
                              "original journal intact; replay must "
                              "still see every open lease)"),
    "gateway.pack_write": ("gateway/packs.py",
                           "update-range pack artifact write (tolerated: "
                           "serving falls back to the update store, "
                           "counted on gateway_pack_build_failures, "
                           "rebuilt on the next seal event)"),
    "replica.register": ("prover_service/dispatcher.py",
                         "dispatcher-side registerReplica admission "
                         "(`raise`/`timeout`/`connreset` surface to the "
                         "announcing replica as an RPC error; the fleet "
                         "is unchanged and the replica re-announces next "
                         "interval)"),
    "replica.announce": ("prover_service/rpc.py",
                         "replica-side announce-loop POST to the "
                         "dispatcher head (tolerated: counted on "
                         "replica_announce_failures, the replica keeps "
                         "serving and retries next interval — only a "
                         "TTL of silence deregisters it)"),
}


def render_site_table() -> str:
    """Markdown table of every registered injection site (the single
    source the README section and the CLI listing are generated from)."""
    lines = ["| site | where | injects into |",
             "|------|-------|--------------|"]
    for site, (module, desc) in SITES.items():
        lines.append(f"| `{site}` | `{module}` | {desc} |")
    return "\n".join(lines)


class InjectedFault(Exception):
    """A deliberately injected transient failure."""

    def __init__(self, site: str, kind: str):
        super().__init__(f"injected fault at {site} ({kind})")
        self.site = site
        self.kind = kind


class InjectedCrash(BaseException):
    """Simulated hard kill (power loss / SIGKILL mid-prove).

    BaseException on purpose: the worker's ``except Exception`` failure
    handling must NOT see it — a crashed worker writes nothing, which is
    exactly the state journal replay has to recover from."""

    def __init__(self, site: str):
        super().__init__(f"injected crash at {site}")
        self.site = site


def _make_exc(site: str, kind: str) -> BaseException:
    if kind == "crash":
        return InjectedCrash(site)
    if kind in ("raise", "oom", "compile"):
        return InjectedFault(site, kind)
    if kind in ("http503", "http429"):
        import email.message
        import urllib.error
        hdrs = email.message.Message()
        hdrs["Retry-After"] = "0" if kind == "http503" else "0.01"
        code = 503 if kind == "http503" else 429
        return urllib.error.HTTPError(f"fault://{site}", code,
                                      f"injected {kind}", hdrs,
                                      io.BytesIO(b""))
    if kind == "timeout":
        return TimeoutError(f"injected timeout at {site}")
    if kind == "connreset":
        return ConnectionResetError(f"injected connection reset at {site}")
    if kind == "ioerror":
        return OSError(f"injected I/O error at {site}")
    if kind == "diskfull":
        import errno
        return OSError(errno.ENOSPC, f"injected ENOSPC (disk full) at {site}")
    raise ValueError(f"unknown fault kind {kind!r} (one of {KINDS})")


def parse_plan(text: str) -> list[list]:
    """Parse the SPECTRE_FAULT_PLAN grammar into [site, kind, remaining]
    entries (order-preserving; multiple entries per site fire in order)."""
    plan = []
    for raw in (text or "").split(","):
        raw = raw.strip()
        if not raw:
            continue
        parts = raw.split(":")
        if len(parts) == 2:
            site, kind, count = parts[0], parts[1], 1
        elif len(parts) == 3:
            site, kind, count = parts[0], parts[1], int(parts[2])
        else:
            raise ValueError(f"bad fault-plan entry {raw!r} "
                             f"(want site:kind[:count])")
        if kind not in KINDS:
            raise ValueError(f"unknown fault kind {kind!r} in {raw!r} "
                             f"(one of {KINDS})")
        if count < 1:
            raise ValueError(f"bad fault count in {raw!r}")
        plan.append([site, kind, count])
    return plan


class FaultRegistry:
    def __init__(self):
        self._lock = threading.Lock()
        self._plan: list[list] = []
        self._env_seen: str | None = None
        self.fired: list[tuple[str, str]] = []
        self._observers: list = []

    def add_observer(self, fn):
        """Register `fn(site, kind)` to be called (outside the registry
        lock) every time a fault actually fires. Idempotent per callable;
        observers must never raise — the provenance-manifest event
        recorder uses this to stamp injected faults into the job record."""
        with self._lock:
            if fn not in self._observers:
                self._observers.append(fn)

    def _notify(self, site: str, kind: str):
        with self._lock:
            observers = list(self._observers)
        for fn in observers:
            try:
                fn(site, kind)
            except Exception:
                pass               # observers are best-effort by contract

    def install_plan(self, text: str):
        """Replace the active plan (also resets the fired log)."""
        plan = parse_plan(text)
        with self._lock:
            self._plan = plan
            self._env_seen = None          # explicit plan wins over env
            self.fired = []

    def arm(self, site: str, kind: str, count: int = 1):
        if kind not in KINDS:
            raise ValueError(f"unknown fault kind {kind!r}")
        with self._lock:
            self._plan.append([site, kind, count])

    def clear(self):
        with self._lock:
            self._plan = []
            self._env_seen = ""            # suppress env re-reads until changed
            self.fired = []

    def _sync_env_locked(self):
        env = os.environ.get(ENV_VAR, "")
        if env != (self._env_seen or ""):
            self._env_seen = env
            self._plan = parse_plan(env)
            self.fired = []

    def check(self, site: str):
        """Fire (raise) the next armed fault for `site`, if any.

        Zero-cost for unarmed sites beyond one dict-free list scan; the env
        plan is re-parsed only when SPECTRE_FAULT_PLAN changes."""
        with self._lock:
            if self._env_seen is not None or not self._plan:
                self._sync_env_locked()
            for entry in self._plan:
                if entry[0] == site and entry[2] > 0 \
                        and entry[1] != "corrupt":
                    entry[2] -= 1
                    self.fired.append((site, entry[1]))
                    exc = _make_exc(site, entry[1])
                    break
            else:
                return
        self._notify(site, entry[1])
        raise exc

    def mangle(self, site: str, data: bytes) -> bytes:
        """Consume an armed ``corrupt`` entry for `site` by bit-flipping
        one byte of `data` (silent corruption — no exception). Unarmed
        sites return the payload untouched."""
        with self._lock:
            if self._env_seen is not None or not self._plan:
                self._sync_env_locked()
            for entry in self._plan:
                if entry[0] == site and entry[2] > 0 \
                        and entry[1] == "corrupt":
                    entry[2] -= 1
                    self.fired.append((site, "corrupt"))
                    break
            else:
                return data
        self._notify(site, "corrupt")
        if not data:
            return data
        buf = bytearray(data)
        buf[len(buf) // 2] ^= 0x01
        return bytes(buf)

    def fired_count(self, site: str | None = None) -> int:
        with self._lock:
            if site is None:
                return len(self.fired)
            return sum(1 for s, _ in self.fired if s == site)

    def armed(self, site: str | None = None) -> int:
        """Remaining armed firings (for tests asserting exhaustion)."""
        with self._lock:
            return sum(e[2] for e in self._plan
                       if site is None or e[0] == site)


# process-global registry: injection sites call faults.check("<site>")
REGISTRY = FaultRegistry()
check = REGISTRY.check
mangle = REGISTRY.mangle
arm = REGISTRY.arm
clear = REGISTRY.clear
install_plan = REGISTRY.install_plan
fired_count = REGISTRY.fired_count
armed = REGISTRY.armed
add_observer = REGISTRY.add_observer

"""Utilities: circuit pinning, artifact caching."""

from .pinning import Pinning  # noqa: F401

"""Phase timers + structured logging for the prover pipeline.

Reference parity (SURVEY.md §5): ark-std `start_timer!/end_timer!` under the
`print-trace` feature + `RUST_LOG` env filtering. Here: `phase(...)` context
managers emit wall-clock per prover phase when SPECTRE_TRACE=1 (or via
logging at DEBUG), and a process-wide registry accumulates totals so services
can expose them (the JSON-RPC server reports them under `ping`-style
diagnostics).
"""

from __future__ import annotations

import contextlib
import logging
import os
import time
from collections import defaultdict

log = logging.getLogger("spectre_tpu")

_TOTALS: dict[str, float] = defaultdict(float)
_COUNTS: dict[str, int] = defaultdict(int)


def trace_enabled() -> bool:
    return os.environ.get("SPECTRE_TRACE", "") not in ("", "0")


def _metrics_path() -> str | None:
    return os.environ.get("SPECTRE_METRICS") or None


@contextlib.contextmanager
def phase(name: str):
    """Time a prover phase; nestable. SPECTRE_METRICS=<path> additionally
    appends one JSON line per phase ({"phase", "seconds", "ts"}) — the
    structured-metrics sink services/CI can scrape."""
    t0 = time.perf_counter()
    try:
        yield
    finally:
        dt = time.perf_counter() - t0
        _TOTALS[name] += dt
        _COUNTS[name] += 1
        if trace_enabled():
            print(f"[trace] {name}: {dt * 1000:.1f} ms", flush=True)
        mp = _metrics_path()
        if mp:
            import json
            try:
                with open(mp, "a") as f:
                    f.write(json.dumps({"phase": name,
                                        "seconds": round(dt, 6),
                                        "ts": round(time.time(), 3)}) + "\n")
            except OSError:   # metrics must never break proving
                pass
        log.debug("phase %s: %.1f ms", name, dt * 1000)


def totals() -> dict:
    return {k: {"seconds": round(v, 4), "count": _COUNTS[k]}
            for k, v in sorted(_TOTALS.items())}


def reset():
    _TOTALS.clear()
    _COUNTS.clear()

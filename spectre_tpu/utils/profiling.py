"""Phase timers + structured logging for the prover pipeline.

Reference parity (SURVEY.md §5): ark-std `start_timer!/end_timer!` under the
`print-trace` feature + `RUST_LOG` env filtering. Here: `phase(...)` context
managers emit wall-clock per prover phase when SPECTRE_TRACE=1 (or via
logging at DEBUG), and a process-wide registry accumulates totals so services
can expose them (the JSON-RPC server reports them under `ping`-style
diagnostics).

Observability integration (ISSUE 7): every `phase` additionally

* becomes a child span of the active per-job trace
  (observability/tracing — no trace active => a no-op), so the existing
  call sites in plonk/prover.py yield full span trees for `getTrace`;
* feeds the `spectre_phase_seconds{phase=...}` histogram
  (observability/metrics) rendered by GET /metrics.

The SPECTRE_METRICS JSONL sink is IO-error tolerant (a full disk or
revoked fd must never fail a prove — pinned via fault site
`metrics.write` in `make test-faults`); failures count on
ServiceHealth as `metrics_write_failures`.
"""

from __future__ import annotations

import contextlib
import json
import logging
import os
import time
from collections import defaultdict

from ..observability import metrics as _obs_metrics
from ..observability import tracing as _obs_tracing
from . import faults

log = logging.getLogger("spectre_tpu")

_TOTALS: dict[str, float] = defaultdict(float)
_COUNTS: dict[str, int] = defaultdict(int)


def trace_enabled() -> bool:
    return os.environ.get("SPECTRE_TRACE", "") not in ("", "0")


def _metrics_path() -> str | None:
    return os.environ.get("SPECTRE_METRICS") or None


@contextlib.contextmanager
def phase(name: str):
    """Time a prover phase; nestable. SPECTRE_METRICS=<path> additionally
    appends one JSON line per phase ({"phase", "seconds", "ts"}) — the
    structured-metrics sink services/CI can scrape."""
    t0 = time.perf_counter()
    try:
        with _obs_tracing.span(name):
            yield
    finally:
        dt = time.perf_counter() - t0
        _TOTALS[name] += dt
        _COUNTS[name] += 1
        _obs_metrics.PHASE_SECONDS.labels(phase=name).observe(dt)
        if trace_enabled():
            print(f"[trace] {name}: {dt * 1000:.1f} ms", flush=True)
        mp = _metrics_path()
        if mp:
            try:
                faults.check("metrics.write")
                with open(mp, "a") as f:
                    f.write(json.dumps({"phase": name,
                                        "seconds": round(dt, 6),
                                        "ts": round(time.time(), 3)}) + "\n")
            except OSError:   # metrics must never break proving
                from .health import HEALTH
                HEALTH.incr("metrics_write_failures")
        log.debug("phase %s: %.1f ms", name, dt * 1000)


def totals() -> dict:
    return {k: {"seconds": round(v, 4), "count": _COUNTS[k]}
            for k, v in sorted(_TOTALS.items())}


def reset():
    _TOTALS.clear()
    _COUNTS.clear()

"""Integrity-checked durable artifact store (ISSUE 6, tentpole part 3).

Large proof payloads used to live INSIDE the fsync'd job journal — every
multi-hundred-KB proof re-written on each compaction, re-parsed on every
replay, and served back with zero end-to-end verification. This module
moves them to content-addressed files with the sha256 as the name, so:

* the journal stays O(#jobs) — a terminal record carries a 64-char digest,
  not the proof bytes;
* every read re-hashes and compares: silent disk rot (bit flips, torn
  writes that survived fsync lies) is DETECTED, the poisoned file is moved
  to ``quarantine/`` (never served, never silently deleted — operators can
  forensic it), and the caller gets a typed :class:`ArtifactCorrupt`;
* writes are crash-atomic: tmp file + flush + fsync + ``os.replace`` +
  directory fsync, the same discipline as the journal compaction sidecar.

The store is also the home of the sidecar-checksum helpers the SRS loader
uses (``<path>.sha256``): params files are multi-GB at production degrees
and a corrupt SRS must be a clear typed startup failure, not a deep
assertion blow-up three layers into keygen.

Fault-injection sites: ``artifact.write`` / ``artifact.read`` (kinds
``ioerror`` and the bytes-mangling ``corrupt``) — see utils/faults.
"""

from __future__ import annotations

import hashlib
import os
import threading

from . import faults
from .health import HEALTH

RESULTS_DIR = "results"
QUARANTINE_DIR = "quarantine"
SIDECAR_SUFFIX = ".sha256"


class ArtifactCorrupt(RuntimeError):
    """An artifact's bytes do not match its recorded digest.

    Raised instead of serving poisoned data; the service layer reports it
    as a clear integrity failure (the result file was quarantined / the
    SRS refused to load), never as a generic internal error."""

    def __init__(self, path: str, expected: str, actual: str):
        super().__init__(
            f"artifact integrity failure: {path} hashes to "
            f"{actual[:16]}…, journal/sidecar says {expected[:16]}…")
        self.path = path
        self.expected = expected
        self.actual = actual


def sha256_hex(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()


def _fsync_dir(path: str):
    try:
        dfd = os.open(path or ".", os.O_RDONLY)
        try:
            os.fsync(dfd)
        finally:
            os.close(dfd)
    except OSError:
        pass                       # not all filesystems allow dir fsync


def _atomic_write(path: str, data: bytes):
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    _fsync_dir(os.path.dirname(path))


class ArtifactStore:
    """Content-addressed blob store under ``<base_dir>/results/``.

    ``write`` returns the sha256 hex digest (the journal records it);
    ``read(digest)`` re-verifies and quarantines on mismatch. Thread-safe:
    concurrent writers of the same content converge on the same file."""

    def __init__(self, base_dir: str, health=HEALTH):
        self.dir = os.path.join(base_dir, RESULTS_DIR)
        self.quarantine_dir = os.path.join(self.dir, QUARANTINE_DIR)
        os.makedirs(self.dir, exist_ok=True)
        self.health = health
        self._lock = threading.Lock()

    def path_for(self, digest: str, suffix: str = ".bin") -> str:
        # `suffix` namespaces artifact kinds sharing the store: proof
        # results are `<sha256>.bin`, provenance manifests
        # `<sha256>.manifest.json` — same digest addressing, same
        # verification and quarantine rules
        return os.path.join(self.dir, f"{digest}{suffix}")

    def exists(self, digest: str, suffix: str = ".bin") -> bool:
        return os.path.exists(self.path_for(digest, suffix))

    def size(self, digest: str, suffix: str = ".bin") -> int | None:
        """On-disk byte size of an artifact, or None when absent —
        metadata-only (no read, no verification); cache-budget
        accounting for the gateway's pack hot set."""
        try:
            return os.stat(self.path_for(digest, suffix)).st_size
        except OSError:
            return None

    def write(self, data: bytes, suffix: str = ".bin",
              fault_site: str = "artifact.write") -> str:
        """Atomically persist `data`; returns its sha256 hex digest."""
        faults.check(fault_site)
        digest = sha256_hex(data)
        # corrupt-at-write: digest records the INTENDED bytes, the disk
        # gets flipped ones — exactly the rot the read-side check catches
        data = faults.mangle(fault_site, data)
        path = self.path_for(digest, suffix)
        with self._lock:
            if not os.path.exists(path):
                _atomic_write(path, data)
        return digest

    def read(self, digest: str, suffix: str = ".bin") -> bytes:
        """Load + verify; a digest mismatch quarantines the file and
        raises :class:`ArtifactCorrupt` instead of serving it."""
        faults.check("artifact.read")
        path = self.path_for(digest, suffix)
        with open(path, "rb") as f:
            data = f.read()
        data = faults.mangle("artifact.read", data)
        actual = sha256_hex(data)
        if actual != digest:
            self._quarantine(path)
            raise ArtifactCorrupt(path, digest, actual)
        return data

    def quarantine_bytes(self, data: bytes, suffix: str = ".bin") -> str:
        """Persist suspect bytes straight into ``quarantine/`` (named by
        their own sha256) for forensics — never into the served results
        namespace. Used by verify-before-serve when a fresh proof fails
        its host-side check; returns the quarantine digest."""
        digest = sha256_hex(data)
        path = os.path.join(self.quarantine_dir, f"{digest}{suffix}")
        with self._lock:
            os.makedirs(self.quarantine_dir, exist_ok=True)
            if not os.path.exists(path):
                _atomic_write(path, data)
        self.health.incr("artifacts_quarantined")
        return digest

    def _quarantine(self, path: str):
        """Move a poisoned file aside (never served again, never silently
        destroyed) and count it."""
        with self._lock:
            os.makedirs(self.quarantine_dir, exist_ok=True)
            try:
                os.replace(path, os.path.join(self.quarantine_dir,
                                              os.path.basename(path)))
            except OSError:
                pass               # already moved by a racing reader
        self.health.incr("artifacts_quarantined")


# -- sidecar checksums (SRS / params files) --------------------------------

def write_sidecar(path: str) -> str:
    """Write ``<path>.sha256`` next to an existing file; returns the hex
    digest. The sidecar itself is written atomically."""
    with open(path, "rb") as f:
        digest = sha256_hex(f.read())
    _atomic_write(path + SIDECAR_SUFFIX, (digest + "\n").encode())
    return digest


def verify_sidecar(path: str, data: bytes | None = None):
    """Verify `path` (or pre-read `data`) against ``<path>.sha256``.

    A MISSING sidecar is not an error (pre-checksum params dirs stay
    loadable); a mismatching one raises :class:`ArtifactCorrupt`."""
    sidecar = path + SIDECAR_SUFFIX
    if not os.path.exists(sidecar):
        return
    with open(sidecar) as f:
        expected = f.read().strip()
    if data is None:
        with open(path, "rb") as f:
            data = f.read()
    actual = sha256_hex(data)
    if actual != expected:
        raise ArtifactCorrupt(path, expected, actual)

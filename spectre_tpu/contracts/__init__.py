"""On-chain layer: the Spectre light-client state machine + verifier interface.

Reference parity (SURVEY.md L6): the `Spectre.sol` contract (head tracking,
per-period committee poseidons, block/execution root maps) and
`contract-tests/` (protocol tests against MockVerifiers). The EVM toolchain
(solc/anvil) is not available in this environment, so the contract logic is
maintained as an executable Python reference model with the same storage
layout and entry points; Solidity emission tracks it in round 2+.
"""

from .spectre import MockVerifier, NativeVerifier, SpectreContract  # noqa: F401

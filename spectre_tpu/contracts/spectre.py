"""Executable reference model of the Spectre light-client contract.

Reference parity: the `Spectre` contract consumed by
`contract-tests/tests/spectre.rs:56-79` — storage: `head`,
`block_header_roots[slot]`, `execution_payload_roots[slot]`,
`sync_committee_poseidons[period]`; entry points `step(...)` and
`rotate(...)`, each gated by a pluggable verifier (MockVerifier in protocol
tests, the real SNARK verifier in production).
"""

from __future__ import annotations

from dataclasses import dataclass, field


class MockVerifier:
    """Accepts everything (reference `MockVerifier.sol` — protocol tests
    without proving)."""

    def verify(self, instances, proof) -> bool:
        return True


class NativeVerifier:
    """Wraps the real plonk verifier (stands in for the generated SNARK
    verifier contract until Solidity emission lands)."""

    def __init__(self, vk, srs):
        self.vk, self.srs = vk, srs

    def verify(self, instances, proof) -> bool:
        from ..plonk.verifier import verify
        return verify(self.vk, self.srs, [list(instances)], proof)


class EvmProofVerifier:
    """Runs proofs through the GENERATED Solidity verifier in the EVM
    simulator — the closest thing to on-chain verification the repo can
    do (ISSUE 18 aggregation cadence publishes through this). Construct
    with the output of ``evm.gen_evm_verifier``; each ``verify`` call
    deploys + calls the contract in ``evm.simulator``."""

    def __init__(self, sol_src: str):
        self.sol_src = sol_src

    def verify(self, instances, proof) -> bool:
        from ..evm.simulator import run_verifier
        return run_verifier(self.sol_src, list(instances), proof)


@dataclass
class StepInput:
    """Mirror of the Solidity step input struct
    (`contract-tests/tests/step_input_encoding.rs`)."""

    attested_slot: int
    finalized_slot: int
    participation: int
    finalized_header_root: bytes
    execution_payload_root: bytes

    def to_public_inputs_commitment(self) -> int:
        """Solidity `toPublicInputsCommitment` equivalence
        (`step_input_encoding.rs:109-116`): must equal the circuit's
        instance[0]."""
        import hashlib
        data = (self.attested_slot.to_bytes(8, "little")
                + self.finalized_slot.to_bytes(8, "little")
                + self.participation.to_bytes(8, "little")
                + self.finalized_header_root
                + self.execution_payload_root)
        digest = bytearray(hashlib.sha256(data).digest())
        digest[31] &= 0x1F
        return int.from_bytes(bytes(digest), "little")


@dataclass
class SpectreContract:
    spec: object
    initial_sync_period: int
    initial_committee_poseidon: int
    step_verifier: object = field(default_factory=MockVerifier)
    rotate_verifier: object = field(default_factory=MockVerifier)
    head: int = 0
    block_header_roots: dict = field(default_factory=dict)
    execution_payload_roots: dict = field(default_factory=dict)
    sync_committee_poseidons: dict = field(default_factory=dict)
    # ISSUE 18 aggregation cadence: end-period -> published window
    # record; `agg_verifier` gates publishes (falls back to the rotate
    # verifier — the window tip IS a committee-class proof)
    aggregated_ranges: dict = field(default_factory=dict)
    agg_verifier: object = None

    def __post_init__(self):
        self.sync_committee_poseidons[self.initial_sync_period] = \
            self.initial_committee_poseidon

    # -- entry points ---------------------------------------------------
    def step(self, inp: StepInput, proof: bytes):
        period = self.spec.sync_period(inp.attested_slot)
        poseidon = self.sync_committee_poseidons.get(period)
        assert poseidon is not None, f"no committee for period {period}"
        commitment = inp.to_public_inputs_commitment()
        assert self.step_verifier.verify([commitment, poseidon], proof), \
            "step proof invalid"
        min_participation = 2 * self.spec.sync_committee_size // 3
        assert inp.participation > min_participation, "insufficient participation"
        if inp.finalized_slot > self.head:
            self.head = inp.finalized_slot
        self.block_header_roots[inp.finalized_slot] = inp.finalized_header_root
        self.execution_payload_roots[inp.finalized_slot] = inp.execution_payload_root

    def rotate(self, finalized_slot: int, next_committee_poseidon: int,
               header_root_lo: int, header_root_hi: int, proof: bytes):
        assert self.rotate_verifier.verify(
            [next_committee_poseidon, header_root_lo, header_root_hi], proof), \
            "rotate proof invalid"
        # the finalized header must already be known to the light client
        root = self.block_header_roots.get(finalized_slot)
        assert root is not None, "unknown finalized header"
        lo = int.from_bytes(root[16:], "big")
        hi = int.from_bytes(root[:16], "big")
        assert (lo, hi) == (header_root_lo, header_root_hi), \
            "header root mismatch"
        next_period = self.spec.sync_period(finalized_slot) + 1
        assert next_period not in self.sync_committee_poseidons, \
            "period already rotated"
        self.sync_committee_poseidons[next_period] = next_committee_poseidon

    def publish_aggregate(self, start_period: int, period: int,
                          committee_poseidon, instances, proof: bytes,
                          calldata=None) -> dict:
        """Publish an aggregation-cadence proof covering committee
        periods ``[start_period, period]`` (ISSUE 18). The proof is
        verified by ``agg_verifier`` (the generated EVM verifier via
        :class:`EvmProofVerifier` in drills; ``rotate_verifier``
        otherwise). Replay-safe: re-publishing the IDENTICAL window is
        an idempotent no-op (crash between publish and journal append),
        but a conflicting proof for an already-published end period is
        refused."""
        period, start_period = int(period), int(start_period)
        assert start_period <= period, "empty aggregation window"
        prior = self.aggregated_ranges.get(period)
        if prior is not None:
            assert prior["committee_poseidon"] == committee_poseidon \
                and prior["start_period"] == start_period, \
                f"period {period} already aggregated with different content"
            return prior
        verifier = self.agg_verifier or self.rotate_verifier
        assert verifier.verify(list(instances), proof), \
            "aggregation proof invalid"
        rec = {"start_period": start_period, "period": period,
               "committee_poseidon": committee_poseidon,
               "calldata": calldata}
        self.aggregated_ranges[period] = rec
        return rec

"""Default (mock-rooted) CommitteeUpdateArgs builder.

Reference parity: `witness/rotation.rs:28-94` — deterministic pubkeys and a
fabricated merkle branch (`mock_root`): the state root is COMPUTED from the
committee leaf and an arbitrary branch, so the witness is self-consistent
without any real chain data.
"""

from __future__ import annotations

from ..fields import bls12_381 as bls
from ..gadgets.ssz_merkle import sha256_pair_native
from .types import BeaconBlockHeader, CommitteeUpdateArgs


def mock_root(leaf: bytes, branch: list[bytes], gindex: int) -> bytes:
    """Fold leaf up the branch to produce a consistent root (reference
    `witness/rotation.rs:77-94`)."""
    node = leaf
    g = gindex
    for sib in branch:
        node = sha256_pair_native(node, sib) if g % 2 == 0 \
            else sha256_pair_native(sib, node)
        g //= 2
    return node


def default_committee_update_args(spec, seed: int = 42) -> CommitteeUpdateArgs:
    n = spec.sync_committee_size
    pubkeys = [bls.g1_compress(bls.sk_to_pk(seed + i + 1)) for i in range(n)]
    args = CommitteeUpdateArgs(pubkeys_compressed=pubkeys)

    depth = spec.sync_committee_pubkeys_depth
    gindex = spec.sync_committee_pubkeys_root_index
    branch = [bytes([d]) * 32 for d in range(depth)]
    state_root = mock_root(args.committee_pubkeys_root(), branch, gindex)
    args.sync_committee_branch = branch
    args.finalized_header = BeaconBlockHeader(
        slot=spec.slots_per_period * 2 + 1,
        proposer_index=7,
        parent_root=b"\x11" * 32,
        state_root=state_root,
        body_root=b"\x22" * 32,
    )
    return args

"""Default (self-signed) SyncStepArgs builder.

Reference parity: `witness/step.rs:52-148` — a deterministic committee signs
the signing root of a fabricated attested header; finality and execution
branches are mock-rooted. Produces a witness that satisfies StepCircuit
without any chain data (used for keygen and tests).
"""

from __future__ import annotations

from ..fields import bls12_381 as bls
from .rotation import mock_root
from .types import BeaconBlockHeader, SyncStepArgs


def default_sync_step_args(spec, seed: int = 1234,
                           participation: float = 1.0) -> SyncStepArgs:
    n = spec.sync_committee_size
    sks = [seed * 7919 + i + 1 for i in range(n)]
    pks = [bls.sk_to_pk(sk) for sk in sks]
    bits = [1 if i < int(n * participation) else 0 for i in range(n)]

    finalized = BeaconBlockHeader(
        slot=spec.slots_per_period + 32,
        proposer_index=3,
        parent_root=b"\x33" * 32,
        state_root=b"\x44" * 32,
        body_root=b"\x00" * 32,  # filled below from the execution branch
    )
    # execution payload root proven into the finalized BODY root
    exec_root = b"\x55" * 32
    exec_branch = [bytes([0xA0 + d]) * 32 for d in range(spec.execution_state_root_depth)]
    body_root = mock_root(exec_root, exec_branch, spec.execution_state_root_index)
    finalized.body_root = body_root

    # finalized header proven into the attested STATE root
    fin_root = finalized.hash_tree_root()
    fin_branch = [bytes([0xB0 + d]) * 32 for d in range(spec.finalized_header_depth)]
    attested_state_root = mock_root(fin_root, fin_branch, spec.finalized_header_index)
    attested = BeaconBlockHeader(
        slot=finalized.slot + 64,
        proposer_index=11,
        parent_root=b"\x66" * 32,
        state_root=attested_state_root,
        body_root=b"\x77" * 32,
    )

    args = SyncStepArgs(
        pubkeys_uncompressed=[(int(p[0]), int(p[1])) for p in pks],
        participation_bits=bits,
        attested_header=attested,
        finalized_header=finalized,
        finality_branch=fin_branch,
        execution_payload_root=exec_root,
        execution_payload_branch=exec_branch,
        domain=b"\x07" * 32,
    )
    signing_root = args.signing_root()
    msg_point = bls.hash_to_g2(signing_root, spec.dst)
    sigs = [bls.g2_curve.mul(msg_point, sk) for sk, b in zip(sks, bits) if b]
    args.signature_compressed = bls.g2_compress(bls.aggregate_signatures(sigs))
    return args

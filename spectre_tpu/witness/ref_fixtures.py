"""Loaders for the reference's checked-in witness fixtures (DATA files).

Reference parity: the reference's unit tests run against
`test_data/sync_step_512.json` / `rotation_512.json` (serde of
`witness/step.rs:28-49` / `witness/rotation.rs:16-25`, loaded at
`sync_step_circuit.rs:455-457`). Loading the same JSON into this
framework's witness types gives cross-implementation conformance: the
fixtures were produced by the reference's Rust+blst generator, so a
signature/branch/instance that validates here proves interop of the whole
host stack (SSWU hash-to-curve, pairing, SSZ, gindex constants)."""

from __future__ import annotations

import json

from .types import BeaconBlockHeader, CommitteeUpdateArgs, SyncStepArgs


def _header(h: dict) -> BeaconBlockHeader:
    return BeaconBlockHeader(
        slot=int(h["slot"]),
        proposer_index=int(h["proposer_index"]),
        parent_root=bytes.fromhex(h["parent_root"][2:]),
        state_root=bytes.fromhex(h["state_root"][2:]),
        body_root=bytes.fromhex(h["body_root"][2:]),
    )


def load_sync_step(path: str) -> SyncStepArgs:
    with open(path) as f:
        d = json.load(f)
    return SyncStepArgs(
        signature_compressed=bytes(d["signature_compressed"]),
        pubkeys_uncompressed=[
            (int.from_bytes(bytes(pk[:48]), "big"),
             int.from_bytes(bytes(pk[48:]), "big"))
            for pk in d["pubkeys_uncompressed"]],
        # (sic) the reference serializes the field misspelled
        participation_bits=[1 if b else 0 for b in d["pariticipation_bits"]],
        attested_header=_header(d["attested_header"]),
        finalized_header=_header(d["finalized_header"]),
        finality_branch=[bytes(b) for b in d["finality_branch"]],
        execution_payload_root=bytes(d["execution_payload_root"]),
        execution_payload_branch=[bytes(b) for b in
                                  d["execution_payload_branch"]],
        domain=bytes(d["domain"]),
    )


def load_rotation(path: str) -> CommitteeUpdateArgs:
    with open(path) as f:
        d = json.load(f)
    return CommitteeUpdateArgs(
        pubkeys_compressed=[bytes(pk) for pk in d["pubkeys_compressed"]],
        finalized_header=_header(d["finalized_header"]),
        sync_committee_branch=[bytes(b) for b in d["sync_committee_branch"]],
    )

"""Witness types and builders for the application circuits.

Reference parity: `lightclient-circuits/src/witness/` — `SyncStepArgs`
(`witness/step.rs:28-49`), `CommitteeUpdateArgs` (`witness/rotation.rs:16-25`)
and their Default (self-signed / mock-rooted) constructions used by tests.
"""

from .types import BeaconBlockHeader, CommitteeUpdateArgs, SyncStepArgs  # noqa: F401
from .rotation import default_committee_update_args  # noqa: F401
from .step import default_sync_step_args  # noqa: F401

"""Witness data structures + native SSZ helpers.

Reference parity: `witness/step.rs:28-49` (SyncStepArgs), `witness/
rotation.rs:16-25` (CommitteeUpdateArgs), plus the SSZ hash_tree_root rules
these circuits re-compute (uint64 -> LE chunk, Bytes48 -> 2-chunk root,
containers -> merkleized field roots).
"""

from __future__ import annotations

from dataclasses import dataclass, field

from ..gadgets.ssz_merkle import merkleize_chunks_native, sha256_pair_native


def uint64_chunk(v: int) -> bytes:
    return int(v).to_bytes(8, "little") + b"\x00" * 24


def bytes48_root(b: bytes) -> bytes:
    assert len(b) == 48
    padded = b + b"\x00" * 16
    return sha256_pair_native(padded[:32], padded[32:])


@dataclass
class BeaconBlockHeader:
    slot: int = 0
    proposer_index: int = 0
    parent_root: bytes = b"\x00" * 32
    state_root: bytes = b"\x00" * 32
    body_root: bytes = b"\x00" * 32

    def hash_tree_root(self) -> bytes:
        return merkleize_chunks_native([
            uint64_chunk(self.slot),
            uint64_chunk(self.proposer_index),
            self.parent_root,
            self.state_root,
            self.body_root,
        ], limit=8)


@dataclass
class SyncStepArgs:
    """Inputs of StepCircuit (reference `witness/step.rs:28-49`)."""

    signature_compressed: bytes = b""          # 96B G2 signature
    pubkeys_uncompressed: list = field(default_factory=list)  # [(x, y) ints]
    participation_bits: list = field(default_factory=list)    # [0/1]
    attested_header: BeaconBlockHeader = field(default_factory=BeaconBlockHeader)
    finalized_header: BeaconBlockHeader = field(default_factory=BeaconBlockHeader)
    finality_branch: list = field(default_factory=list)       # [bytes32]
    execution_payload_root: bytes = b"\x00" * 32
    execution_payload_branch: list = field(default_factory=list)
    domain: bytes = b"\x00" * 32

    def signing_root(self) -> bytes:
        return sha256_pair_native(self.attested_header.hash_tree_root(), self.domain)


@dataclass
class CommitteeUpdateArgs:
    """Inputs of CommitteeUpdateCircuit (reference `witness/rotation.rs:16-25`)."""

    pubkeys_compressed: list = field(default_factory=list)    # [bytes48]
    finalized_header: BeaconBlockHeader = field(default_factory=BeaconBlockHeader)
    sync_committee_branch: list = field(default_factory=list)  # [bytes32]

    def committee_pubkeys_root(self) -> bytes:
        """Root of the pubkeys LIST (not the SyncCommittee container) —
        matches the in-circuit `sync_committee_root_ssz`."""
        import hashlib
        leaves = [hashlib.sha256(pk + b"\x00" * 16).digest()
                  for pk in self.pubkeys_compressed]
        return merkleize_chunks_native(leaves)

"""Static analysis for the prover: circuit audit + kernel lint + trace lint.

Three engines, one finding stream (motivation: ISSUE 1 — every MXU/limb
rewrite of the prover's hot path is a chance to drop a constraint or
overflow a limb with no test that notices; zkSpeed and SZKP both flag this
as the cost of porting provers to wide SIMD/systolic datapaths):

- `circuit_audit` walks a builder `Context` + synthesized `CircuitConfig`
  and reports under-constrained advice cells, degree-budget violations,
  unbound lookup tables, copy-constraint orphans, dead (all-zero)
  fixed/selector columns, and row-level coverage holes over the physical
  assignment grid (CA-ROW-UNBOUND / CA-ROW-DEAD-SELECTOR).
- `kernel_lint` traces the hot device ops to jaxprs and flags integer
  multiplies/adds whose worst-case value exceeds the lane dtype, float
  dtypes leaking into field arithmetic, and host callbacks inside kernels.
- `trace_lint` guards the trace-cache discipline (the rc=124 retrace bug
  class): an AST scan of jit/shard_map/pallas_call construction sites in
  ops/, parallel/, plonk/ cross-checked against the declared runner
  registry (TC-FRESH-JIT, TC-CONST-CAPTURE, TC-UNSTABLE-STATIC,
  TC-UNCACHED-RUNNER), plus dynamic double-call probes asserting zero
  recompiles on the second call of every runner family (TC-RETRACE-DYN).

CLI: `python -m spectre_tpu.analysis --fail-on error` (`--engine trace` is
the deep tier behind `make lint-deep`). Accepted findings live in the
checked-in `baseline.json` next to this file (see README "Static analysis"
for the suppression workflow).
"""

from .findings import (Finding, Severity, load_baseline, write_baseline,
                       partition_findings, format_finding)
from .circuit_audit import audit_context, audit_rows, DegreeCtx
from .kernel_lint import lint_kernel, lint_all_kernels, KERNELS
from .trace_lint import lint_trace, scan_files, run_probes, PROBES

__all__ = [
    "Finding", "Severity", "load_baseline", "write_baseline",
    "partition_findings", "format_finding", "audit_context", "audit_rows",
    "DegreeCtx", "lint_kernel", "lint_all_kernels", "KERNELS",
    "lint_trace", "scan_files", "run_probes", "PROBES",
]

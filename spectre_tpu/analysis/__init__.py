"""Static analysis for the prover: circuit soundness audit + JAX kernel lint.

Two engines, one finding stream (motivation: ISSUE 1 — every MXU/limb
rewrite of the prover's hot path is a chance to drop a constraint or
overflow a limb with no test that notices; zkSpeed and SZKP both flag this
as the cost of porting provers to wide SIMD/systolic datapaths):

- `circuit_audit` walks a builder `Context` + synthesized `CircuitConfig`
  and reports under-constrained advice cells, degree-budget violations,
  unbound lookup tables, copy-constraint orphans, and dead (all-zero)
  fixed/selector columns.
- `kernel_lint` traces the hot device ops to jaxprs and flags integer
  multiplies/adds whose worst-case value exceeds the lane dtype, float
  dtypes leaking into field arithmetic, and host callbacks inside kernels.

CLI: `python -m spectre_tpu.analysis --fail-on error`. Accepted findings
live in the checked-in `baseline.json` next to this file (see README
"Static analysis" for the suppression workflow).
"""

from .findings import (Finding, Severity, load_baseline, write_baseline,
                       partition_findings, format_finding)
from .circuit_audit import audit_context, DegreeCtx
from .kernel_lint import lint_kernel, lint_all_kernels, KERNELS

__all__ = [
    "Finding", "Severity", "load_baseline", "write_baseline",
    "partition_findings", "format_finding", "audit_context", "DegreeCtx",
    "lint_kernel", "lint_all_kernels", "KERNELS",
]

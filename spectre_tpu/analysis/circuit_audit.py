"""Engine 1: circuit soundness audit over a builder Context + CircuitConfig.

Rules (all keyed for baseline suppression, see findings.py):

  CA-UNDERCONSTRAINED  error    ungated advice cell referenced by no gate,
                                copy, constant pin, lookup push, or instance
                                exposure — a free witness the proof never
                                binds (the classic dropped-constraint bug).
  CA-DEGREE            error    constraint expression whose column-degree
                                exceeds cfg.max_expr_degree (the quotient
                                would not fit NUM_H_CHUNKS committed chunks;
                                the prover only discovers this at prove time
                                as an inexact division).
  CA-TABLE-UNBOUND     error    lookup stream bound to a table id with no
                                configured lookup-advice column (layout
                                would assert), or a configured table id the
                                constraint system cannot synthesize.
  CA-TABLE-OVERFLOW    error    lookup stream longer than its configured
                                columns can hold.
  CA-COPY-ORPHAN       error    copy constraint / constant pin / instance
                                exposure referencing a cell that was never
                                assigned (out-of-range stream index, missing
                                lookup stream, unallocated SHA slot row).
  CA-DEAD-SELECTOR     warning  all-zero selector column: the gate in that
                                advice column is never active.
  CA-DEAD-FIXED        warning  all-zero fixed column (dead constants).
  CA-ROW-UNBOUND       error    PLACED advice cell whose physical row no
                                gate window (selector rotations 0..+3)
                                covers and no copy/constant/instance
                                endpoint binds. The row-wise sharpening of
                                CA-UNDERCONSTRAINED: that rule reasons over
                                builder streams, this one over the actual
                                assignment grid after layout — it catches
                                placement bugs the stream view cannot see
                                (a selector landing on the wrong row, a
                                copy translated to the wrong coordinate).
  CA-ROW-DEAD-SELECTOR warning  selector fires on a row whose gate window
                                reads no placed cell — a vacuous gate
                                activation (satisfied by the zero padding
                                today, a trap for the next layout change).
                                With SHA slots configured, also flags
                                structural SHA selectors armed over slots
                                the circuit never filled.

The walk is pure host Python over builder streams — no SRS, no keygen, no
proving; tiny-spec circuits audit in seconds.
"""

from __future__ import annotations

import itertools

import numpy as np

from ..plonk.constraint_system import (GATE_ROWS, SHA_SLOT_ROWS,
                                       SHA_WORD_COLS, CircuitConfig,
                                       gate_coverage, sha_selector_columns,
                                       table_column)
from ..plonk.expressions import all_expressions
from .findings import Finding, Severity

_CS_FILE = "spectre_tpu/plonk/constraint_system.py"
_CTX_FILE = "spectre_tpu/builder/context.py"


class DegreeCtx:
    """all_expressions context computing each expression's column-degree:
    every column polynomial (advice, fixed, selector, sigma, grand product,
    l0/llast/lblind, the identity X) counts as degree 1; mul adds degrees,
    add/sub take the max, scalar ops preserve them. The same protocol the
    prover/verifier/mock contexts implement, so the audited degrees are the
    degrees of exactly the expressions that get proven."""

    l0 = 1
    llast = 1
    lblind = 1
    x_col = 1

    def var(self, key, rot):
        return 1

    def mul(self, a, b):
        return a + b

    def add(self, a, b):
        return max(a, b)

    def sub(self, a, b):
        return max(a, b)

    def scale(self, a, s):
        return a

    def add_const(self, a, s):
        return a

    def const(self, s):
        return 0


def expression_degrees(cfg: CircuitConfig, expressions_fn=all_expressions):
    """Column-degree of every constraint expression, in yield order."""
    # beta/gamma enter as scalars (degree 0); any nonzero values work
    return list(expressions_fn(cfg, DegreeCtx(), 0xBEEF, 0xCAFE))


def _audit_degrees(cfg, name, expressions_fn) -> list:
    out = []
    budget = cfg.max_expr_degree
    for i, deg in enumerate(expression_degrees(cfg, expressions_fn)):
        if deg > budget:
            out.append(Finding(
                "circuit", "CA-DEGREE", Severity.ERROR, _CS_FILE, name,
                f"expression #{i} has column-degree {deg} > budget {budget} "
                f"(quotient would overflow the committed h chunks)",
                key=f"CA-DEGREE:{name}:expr{i}"))
    return out


def _audit_cell_references(ctx, name) -> list:
    refs = ctx.cell_references()
    n, gated, referenced = refs["n_cells"], refs["gated"], refs["referenced"]
    loose = [i for i in range(n) if not gated[i] and not referenced[i]]
    if not loose:
        return []
    preview = ", ".join(str(i) for i in loose[:8])
    more = f", ... ({len(loose)} total)" if len(loose) > 8 else ""
    return [Finding(
        "circuit", "CA-UNDERCONSTRAINED", Severity.ERROR, _CTX_FILE, name,
        f"{len(loose)} ungated advice cell(s) with no gate/copy/lookup/"
        f"instance reference: stream indices [{preview}{more}] — free "
        f"witnesses the proof never binds",
        # count in the key: the baseline entry resurfaces if the number of
        # accepted loose cells ever drifts
        key=f"CA-UNDERCONSTRAINED:{name}:{len(loose)}")]


def _audit_tables(ctx, cfg, name) -> list:
    out = []
    configured: dict = {}
    for j in range(cfg.num_lookup_advice):
        configured[cfg.table_id(j)] = configured.get(cfg.table_id(j), 0) + 1
    for tid in configured:
        try:
            table_column(cfg, tid)
        except KeyError:
            out.append(Finding(
                "circuit", "CA-TABLE-UNBOUND", Severity.ERROR, _CS_FILE, name,
                f"configured lookup table id {tid!r} is unknown to "
                f"table_column() — keygen would fail",
                key=f"CA-TABLE-UNBOUND:{name}:cfg:{tid}"))
    u = cfg.usable_rows
    for tid, stream in ctx.lkp_streams.items():
        ncols = configured.get(tid, 0)
        if ncols == 0:
            out.append(Finding(
                "circuit", "CA-TABLE-UNBOUND", Severity.ERROR, _CS_FILE, name,
                f"lookup stream {tid!r} ({len(stream)} cells) has no "
                f"lookup-advice column bound in cfg.lookup_tables "
                f"{cfg.lookup_tables!r} — layout would fail and the lookups "
                f"would never be enforced",
                key=f"CA-TABLE-UNBOUND:{name}:{tid}"))
        elif len(stream) > ncols * u:
            out.append(Finding(
                "circuit", "CA-TABLE-OVERFLOW", Severity.ERROR, _CS_FILE, name,
                f"lookup stream {tid!r} has {len(stream)} cells but the "
                f"{ncols} configured column(s) hold only {ncols * u}",
                key=f"CA-TABLE-OVERFLOW:{name}:{tid}"))
    return out


def _audit_copy_orphans(ctx, cfg, name) -> list:
    n_adv = len(ctx.adv_values)
    n_sha_rows = len(ctx.sha_slots) * SHA_SLOT_ROWS

    def endpoint_error(stream, idx):
        if stream == "adv":
            if not (isinstance(idx, int) and 0 <= idx < n_adv):
                return f"advice index {idx} outside stream of {n_adv}"
            return None
        if stream == "shwc":
            j, row = idx
            if not 0 <= j < SHA_WORD_COLS:
                return f"sha word column {j} out of range"
            if not 0 <= row < n_sha_rows:
                return (f"sha word row {row} outside the "
                        f"{len(ctx.sha_slots)} allocated slot(s)")
            return None
        if isinstance(stream, tuple) and stream and stream[0] == "lkp":
            tid = stream[1]
            st = ctx.lkp_streams.get(tid)
            if st is None:
                return f"lookup stream {tid!r} does not exist"
            if not 0 <= idx < len(st):
                return f"lookup index {idx} outside {tid!r} stream of {len(st)}"
            return None
        return f"unknown stream kind {stream!r}"

    out = []
    seen = set()

    def report(detail, where):
        if detail in seen:
            return
        seen.add(detail)
        out.append(Finding(
            "circuit", "CA-COPY-ORPHAN", Severity.ERROR, _CTX_FILE, name,
            f"{where} references an unassigned cell: {detail} — the "
            f"permutation cycle would touch a cell no column carries",
            key=f"CA-COPY-ORPHAN:{name}:{detail}"))

    for (sa, ia), (sb, ib) in ctx.copies:
        for s, i in ((sa, ia), (sb, ib)):
            err = endpoint_error(s, i)
            if err:
                report(err, "copy constraint")
    n_const_rows = len(ctx.constants)
    for adv_idx, fix_row in ctx.const_uses:
        if not 0 <= adv_idx < n_adv:
            report(f"advice index {adv_idx} outside stream of {n_adv}",
                   "constant pin")
        if not 0 <= fix_row < n_const_rows:
            report(f"fixed row {fix_row} outside the {n_const_rows} "
                   f"interned constants", "constant pin")
    for av in ctx.instance_cells:
        err = endpoint_error(av.stream, av.index)
        if err:
            report(err, "instance exposure")
    if len(ctx.instance_cells) > cfg.usable_rows:
        report(f"{len(ctx.instance_cells)} instance cells exceed "
               f"usable rows {cfg.usable_rows}", "instance column")
    return out


def _audit_dead_columns(ctx, cfg, name) -> list:
    out = []
    try:
        _adv, _lkp, fixed, selectors, _cp, _inst, _bp = ctx.layout(cfg)
    except (AssertionError, KeyError) as e:
        # a broken layout is already reported by the orphan/table rules;
        # surface the failure rather than crash the audit
        return [Finding(
            "circuit", "CA-DEAD-FIXED", Severity.WARNING, _CTX_FILE, name,
            f"layout failed ({e}) — dead-column audit skipped",
            key=f"CA-LAYOUT-FAILED:{name}")]
    for j, col in enumerate(selectors):
        if not any(col):
            out.append(Finding(
                "circuit", "CA-DEAD-SELECTOR", Severity.WARNING, _CS_FILE,
                name,
                f"selector column {j} is all-zero: the vertical gate in "
                f"advice column {j} is never active (dead gate)",
                key=f"CA-DEAD-SELECTOR:{name}:{j}"))
    for j, col in enumerate(fixed):
        if not any(col):
            out.append(Finding(
                "circuit", "CA-DEAD-FIXED", Severity.WARNING, _CS_FILE, name,
                f"fixed column {j} is all-zero (dead constants column)",
                key=f"CA-DEAD-FIXED:{name}:{j}"))
    return out


def audit_rows(ctx, cfg, name, mutate=None) -> list:
    """Row-wise gate-coverage audit over the PHYSICAL assignment grid.

    Joins `ctx.cell_placement(cfg)` (stream index -> (column, row)) against
    the layout's selector grid and the global-coordinate copy endpoints:

      * a placed cell is ROW-COVERED when some selector window (rotations
        0..+GATE_ROWS-1, `gate_coverage`) reads its row, and COPY-BOUND
        when some copy/constant-pin/instance endpoint lands on its exact
        (column, row). Neither -> CA-ROW-UNBOUND (error).
      * a selector firing on a row whose whole window holds no placed cell
        is a vacuous activation -> CA-ROW-DEAD-SELECTOR (warning); SHA
        structural selectors armed over unfilled slots are the same class.

    `mutate` exists for the mutation tests: it receives copies of
    (placement, selectors, copies) after layout and may return a modified
    triple — seeded row-level bugs must surface as CA-ROW-* findings."""
    try:
        _adv, _lkp, _fx, selectors, copies, _inst, _bp = ctx.layout(cfg)
        placement = ctx.cell_placement(cfg)
    except (AssertionError, KeyError) as e:
        return [Finding(
            "circuit", "CA-ROW-UNBOUND", Severity.WARNING, _CTX_FILE, name,
            f"layout failed ({e}) — row-coverage audit skipped",
            key=f"CA-ROW-LAYOUT-FAILED:{name}")]
    if mutate is not None:
        # copies, so seeded bugs never poison the Context's layout caches
        res = mutate(dict(placement), [list(c) for c in selectors],
                     list(copies))
        if res is not None:
            placement, selectors, copies = res

    n, ncols = cfg.n, cfg.num_advice
    cov = gate_coverage(selectors)                      # [ncols, n]
    bound = np.zeros((ncols, n), np.uint8)
    if copies:
        # flat int32 fromiter: sync_step:tiny carries ~14M copies — a
        # per-endpoint Python loop is minutes, this is seconds
        ends = np.fromiter(
            itertools.chain.from_iterable(
                itertools.chain.from_iterable(copies)),
            dtype=np.int32, count=4 * len(copies)).reshape(-1, 2)
        cc, rr = ends[:, 0], ends[:, 1]
        ok = (cc >= 0) & (cc < ncols) & (rr >= 0) & (rr < n)
        bound[cc[ok], rr[ok]] = 1
        del ends, cc, rr, ok

    out = []
    if placement:
        cr = np.fromiter(
            itertools.chain.from_iterable(placement.values()),
            dtype=np.int32, count=2 * len(placement)).reshape(-1, 2)
        cols, rows = cr[:, 0], cr[:, 1]
        free = (cov[cols, rows] == 0) & (bound[cols, rows] == 0)
        if free.any():
            idxs = np.fromiter(placement.keys(), dtype=np.int64,
                               count=len(placement))
            per_col = np.bincount(cols[free], minlength=ncols)
            for c in np.nonzero(per_col)[0]:
                sel = free & (cols == c)
                where = sorted(zip(rows[sel].tolist(),
                                   idxs[sel].tolist()))[:6]
                preview = ", ".join(f"r{r}(cell {i})" for r, i in where)
                more = (f", ... ({int(per_col[c])} total)"
                        if per_col[c] > 6 else "")
                out.append(Finding(
                    "circuit", "CA-ROW-UNBOUND", Severity.ERROR, _CTX_FILE,
                    name,
                    f"advice column {int(c)}: {int(per_col[c])} placed "
                    f"cell(s) on rows no gate window covers and no copy "
                    f"binds [{preview}{more}] — free witness rows",
                    key=f"CA-ROW-UNBOUND:{name}:col{int(c)}:"
                        f"{int(per_col[c])}"))

        # occupancy -> window-occupancy: sel row r is live iff ANY of rows
        # r..r+GATE_ROWS-1 holds a placed cell
        occ = np.zeros((ncols, n), np.uint8)
        occ[cols, rows] = 1
    else:
        occ = np.zeros((ncols, n), np.uint8)
    wocc = occ.copy()
    for off in range(1, GATE_ROWS):
        wocc[:, :n - off] |= occ[:, off:]
    sel_grid = np.asarray(selectors, np.uint8)
    dead = (sel_grid == 1) & (wocc == 0)
    for c in np.nonzero(dead.any(axis=1))[0]:
        drows = np.nonzero(dead[c])[0]
        preview = ", ".join(str(int(r)) for r in drows[:6])
        more = f", ... ({len(drows)} total)" if len(drows) > 6 else ""
        out.append(Finding(
            "circuit", "CA-ROW-DEAD-SELECTOR", Severity.WARNING, _CTX_FILE,
            name,
            f"selector column {int(c)} fires on {len(drows)} row(s) whose "
            f"gate window reads no placed cell [rows {preview}{more}] — "
            f"vacuous gate activation",
            key=f"CA-ROW-DEAD-SELECTOR:{name}:col{int(c)}:{len(drows)}"))

    if cfg.num_sha_slots:
        # structural SHA selectors patterned for cfg.num_sha_slots slots;
        # rows of slots the circuit never filled are vacuously gated
        sha_sel, _k = sha_selector_columns(cfg)
        used_rows = len(ctx.sha_slots) * SHA_SLOT_ROWS
        sha = np.asarray(sha_sel, np.uint8)
        stale = sha[:, used_rows:]
        for j in np.nonzero(stale.any(axis=1))[0]:
            cnt = int(stale[j].sum())
            out.append(Finding(
                "circuit", "CA-ROW-DEAD-SELECTOR", Severity.WARNING,
                _CS_FILE, name,
                f"sha selector {int(j)} armed on {cnt} row(s) beyond the "
                f"{len(ctx.sha_slots)} filled slot(s) (cfg allocates "
                f"{cfg.num_sha_slots}) — vacuous structural gating",
                key=f"CA-ROW-DEAD-SELECTOR:{name}:sha{int(j)}:{cnt}"))
    return out


def audit_context(ctx, cfg: CircuitConfig, name: str,
                  expressions_fn=all_expressions, row_mutate=None) -> list:
    """Run every circuit-audit rule; returns findings in severity order.

    `expressions_fn` exists for the mutation tests: injecting a constraint
    generator with a seeded over-degree expression must produce CA-DEGREE.
    `row_mutate` is the row-audit equivalent (see `audit_rows`)."""
    findings = []
    findings += _audit_cell_references(ctx, name)
    findings += _audit_degrees(cfg, name, expressions_fn)
    findings += _audit_tables(ctx, cfg, name)
    findings += _audit_copy_orphans(ctx, cfg, name)
    findings += _audit_dead_columns(ctx, cfg, name)
    findings += audit_rows(ctx, cfg, name, mutate=row_mutate)
    findings.sort(key=lambda f: -Severity.ORDER[f.severity])
    return findings

"""CLI: `python -m spectre_tpu.analysis [--fail-on error]`.

Runs the analysis engines (circuit soundness audit over the tiny-spec app
circuits, kernel lint over the hot device ops, trace-cache hygiene lint over
the jit/shard_map call sites + retrace probes), subtracts the checked-in
`baseline.json` suppressions, prints the rest, and exits nonzero when any
unsuppressed finding reaches the --fail-on severity. `--write-baseline`
accepts the current active findings into the suppression file (review the
diff — every entry is a consciously accepted soundness exception).

`--engine trace` is the deep tier (`make lint-deep`): the static AST scan is
sub-second, the dynamic double-call probes compile every registered runner
family once (~90s on a 1-core CPU host, budgeted under 120s by
tests/test_analysis.py). `--json PATH` writes a machine-readable report:
active/suppressed findings, per-pass wall time, and per-engine root counts.
"""

from __future__ import annotations

import argparse
import json
import sys
import time


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m spectre_tpu.analysis",
        description="circuit soundness auditor + JAX kernel lint "
                    "+ trace-cache hygiene lint")
    ap.add_argument("--engine", choices=("all", "circuit", "kernel", "trace"),
                    default="all")
    ap.add_argument("--circuits", default="committee_update,sync_step,"
                    "aggregation",
                    help="comma list of audit circuits, or 'none'")
    ap.add_argument("--kernels", default="",
                    help="comma list of kernel names (default: all)")
    ap.add_argument("--no-probes", action="store_true",
                    help="trace engine: static AST scan only, skip the "
                         "dynamic retrace probes")
    ap.add_argument("--fail-on", choices=("error", "warning", "never"),
                    default="error", dest="fail_on")
    ap.add_argument("--baseline", default=None,
                    help="suppression file (default: packaged baseline.json)")
    ap.add_argument("--write-baseline", action="store_true",
                    help="accept current active findings into the baseline")
    ap.add_argument("--json", default=None, help="write findings JSON here")
    ap.add_argument("-q", "--quiet", action="store_true")
    opts = ap.parse_args(argv)

    from .findings import (Severity, format_finding, load_baseline,
                           partition_findings, write_baseline)

    findings = []
    passes = []   # [{name, engine, seconds, findings}] for --json
    roots = {}    # per-engine root counts for --json
    t0 = time.time()

    def record(name, engine, t, fs):
        passes.append({"name": name, "engine": engine,
                       "seconds": round(time.time() - t, 3),
                       "findings": len(fs)})
        if not opts.quiet:
            print(f"[analysis] {name}: {len(fs)} finding(s) "
                  f"({time.time() - t:.1f}s)", flush=True)

    if opts.engine in ("all", "circuit") and opts.circuits != "none":
        from .circuit_audit import audit_context
        from .circuits import AUDIT_CIRCUITS
        wanted = [c for c in opts.circuits.split(",") if c]
        roots["circuits"] = len(wanted)
        for cname in wanted:
            build = AUDIT_CIRCUITS.get(cname)
            if build is None:
                ap.error(f"unknown circuit {cname!r} "
                         f"(have: {', '.join(AUDIT_CIRCUITS)})")
            t = time.time()
            ctx, cfg, name = build()
            fs = audit_context(ctx, cfg, name)
            findings += fs
            record(f"circuit {name}", "circuit", t, fs)

    if opts.engine in ("all", "kernel"):
        from .kernel_lint import KERNELS, lint_all_kernels
        t = time.time()
        names = set(k for k in opts.kernels.split(",") if k) or None
        roots["kernels"] = len(names) if names else len(KERNELS)
        fs = lint_all_kernels(names)
        findings += fs
        record("kernel lint", "kernel", t, fs)

    if opts.engine in ("all", "trace"):
        from . import trace_lint
        roots.update(trace_lint.root_counts())
        t = time.time()
        fs = trace_lint.scan_files()
        findings += fs
        record("trace static scan", "trace", t, fs)
        if not opts.no_probes:
            for spec in trace_lint.PROBES:
                t = time.time()
                fs = trace_lint.run_probe(spec)
                findings += fs
                record(f"trace probe {spec.name}", "trace", t, fs)
        else:
            roots["trace_probes"] = 0

    baseline = load_baseline(opts.baseline)
    active, suppressed = partition_findings(findings, baseline)

    if opts.write_baseline and active:
        path = write_baseline(active, opts.baseline)
        print(f"[analysis] accepted {len(active)} finding(s) into {path}")
        suppressed += active
        active = []

    for f in active:
        print(format_finding(f))
    if not opts.quiet:
        for f in suppressed:
            print(format_finding(f, suppressed=True))

    if opts.json:
        with open(opts.json, "w") as fh:
            json.dump({"active": [f.to_dict() for f in active],
                       "suppressed": [f.to_dict() for f in suppressed],
                       "passes": passes,
                       "roots": roots,
                       "seconds": round(time.time() - t0, 3)},
                      fh, indent=1)

    counts = {}
    for f in active:
        counts[f.severity] = counts.get(f.severity, 0) + 1
    print(f"[analysis] {len(active)} active finding(s) "
          f"({', '.join(f'{v} {k}' for k, v in counts.items()) or 'clean'}), "
          f"{len(suppressed)} baselined, {time.time() - t0:.1f}s total")

    if opts.fail_on == "never":
        return 0
    bad = [f for f in active if Severity.at_least(f.severity, opts.fail_on)]
    return 1 if bad else 0


if __name__ == "__main__":
    sys.exit(main())

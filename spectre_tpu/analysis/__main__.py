"""CLI: `python -m spectre_tpu.analysis [--fail-on error]`.

Runs both engines (circuit soundness audit over the tiny-spec app circuits,
kernel lint over the hot device ops), subtracts the checked-in
`baseline.json` suppressions, prints the rest, and exits nonzero when any
unsuppressed finding reaches the --fail-on severity. `--write-baseline`
accepts the current active findings into the suppression file (review the
diff — every entry is a consciously accepted soundness exception).
"""

from __future__ import annotations

import argparse
import json
import sys
import time


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m spectre_tpu.analysis",
        description="circuit soundness auditor + JAX kernel lint")
    ap.add_argument("--engine", choices=("all", "circuit", "kernel"),
                    default="all")
    ap.add_argument("--circuits", default="committee_update,sync_step,"
                    "aggregation",
                    help="comma list of audit circuits, or 'none'")
    ap.add_argument("--kernels", default="",
                    help="comma list of kernel names (default: all)")
    ap.add_argument("--fail-on", choices=("error", "warning", "never"),
                    default="error", dest="fail_on")
    ap.add_argument("--baseline", default=None,
                    help="suppression file (default: packaged baseline.json)")
    ap.add_argument("--write-baseline", action="store_true",
                    help="accept current active findings into the baseline")
    ap.add_argument("--json", default=None, help="write findings JSON here")
    ap.add_argument("-q", "--quiet", action="store_true")
    opts = ap.parse_args(argv)

    from .findings import (Severity, format_finding, load_baseline,
                           partition_findings, write_baseline)

    findings = []
    t0 = time.time()

    if opts.engine in ("all", "circuit") and opts.circuits != "none":
        from .circuit_audit import audit_context
        from .circuits import AUDIT_CIRCUITS
        for cname in [c for c in opts.circuits.split(",") if c]:
            build = AUDIT_CIRCUITS.get(cname)
            if build is None:
                ap.error(f"unknown circuit {cname!r} "
                         f"(have: {', '.join(AUDIT_CIRCUITS)})")
            t = time.time()
            ctx, cfg, name = build()
            fs = audit_context(ctx, cfg, name)
            findings += fs
            if not opts.quiet:
                print(f"[analysis] circuit {name}: {len(fs)} finding(s) "
                      f"({time.time() - t:.1f}s)", flush=True)

    if opts.engine in ("all", "kernel"):
        from .kernel_lint import lint_all_kernels
        t = time.time()
        names = set(k for k in opts.kernels.split(",") if k) or None
        fs = lint_all_kernels(names)
        findings += fs
        if not opts.quiet:
            print(f"[analysis] kernel lint: {len(fs)} finding(s) "
                  f"({time.time() - t:.1f}s)", flush=True)

    baseline = load_baseline(opts.baseline)
    active, suppressed = partition_findings(findings, baseline)

    if opts.write_baseline and active:
        path = write_baseline(active, opts.baseline)
        print(f"[analysis] accepted {len(active)} finding(s) into {path}")
        suppressed += active
        active = []

    for f in active:
        print(format_finding(f))
    if not opts.quiet:
        for f in suppressed:
            print(format_finding(f, suppressed=True))

    if opts.json:
        with open(opts.json, "w") as fh:
            json.dump({"active": [f.to_dict() for f in active],
                       "suppressed": [f.to_dict() for f in suppressed]},
                      fh, indent=1)

    counts = {}
    for f in active:
        counts[f.severity] = counts.get(f.severity, 0) + 1
    print(f"[analysis] {len(active)} active finding(s) "
          f"({', '.join(f'{v} {k}' for k, v in counts.items()) or 'clean'}), "
          f"{len(suppressed)} baselined, {time.time() - t0:.1f}s total")

    if opts.fail_on == "never":
        return 0
    bad = [f for f in active if Severity.at_least(f.severity, opts.fail_on)]
    return 1 if bad else 0


if __name__ == "__main__":
    sys.exit(main())

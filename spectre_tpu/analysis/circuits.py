"""Audit targets: the three app circuits at their smallest real shapes.

Each builder returns (ctx, cfg, name) — a fully witness-generated builder
Context plus the auto-sized CircuitConfig the prover would use. The tiny
spec (2 validators) keeps witness generation to seconds for the
committee-update circuit and tens of seconds for the step circuit's BLS
block; the aggregation target verifies a small k=10 inner snark in-circuit
(the same shape tests/test_aggregation.py exercises).
"""

from __future__ import annotations


def _tiny():
    from .. import spec as S
    return S.TINY


def build_committee_update():
    from ..models import CommitteeUpdateCircuit
    from ..witness import default_committee_update_args
    spec = _tiny()
    args = default_committee_update_args(spec)
    ctx = CommitteeUpdateCircuit.build_context(args, spec)
    cfg = ctx.auto_config(k=17,
                          lookup_bits=CommitteeUpdateCircuit.default_lookup_bits)
    return ctx, cfg, "committee_update:tiny"


def build_sync_step():
    from ..models import StepCircuit
    from ..witness import default_sync_step_args
    spec = _tiny()
    args = default_sync_step_args(spec)
    ctx = StepCircuit.build_context(args, spec)
    # lookup_bits=18 needs k >= 19 for the range table to fit usable rows
    cfg = ctx.auto_config(k=19, lookup_bits=StepCircuit.default_lookup_bits)
    return ctx, cfg, "sync_step:tiny"


def build_aggregation():
    import random

    from ..builder.context import Context
    from ..builder.range_chip import RangeChip
    from ..models.aggregation import AggregationArgs, AggregationCircuit
    from ..plonk.keygen import keygen
    from ..plonk.prover import prove
    from ..plonk.srs import SRS
    from ..plonk.transcript import PoseidonTranscript

    # small inner app snark (mirrors tests/test_aggregation.py::inner)
    random.seed(3)
    ictx = Context()
    rng = RangeChip(lookup_bits=8)
    g = rng.gate
    a = ictx.load_witness(1234)
    b = ictx.load_witness(5678)
    c = g.mul(ictx, a, b)
    rng.range_check(ictx, a, 16)
    ictx.expose_public(c)
    icfg = ictx.auto_config(k=10, lookup_bits=8)
    iasg = ictx.assignment(icfg)
    srs = SRS.unsafe_setup(10)
    pk = keygen(srs, icfg, iasg.fixed, iasg.selectors, iasg.copies)
    proof = prove(pk, srs, iasg, transcript=PoseidonTranscript())

    args = AggregationArgs(inner_vk=pk.vk, srs=srs,
                           inner_instances=iasg.instances, proof=proof)
    spec = _tiny()
    ctx = AggregationCircuit.build_context(args, spec)
    cfg = ctx.auto_config(k=15,
                          lookup_bits=AggregationCircuit.default_lookup_bits)
    return ctx, cfg, "aggregation:tiny"


AUDIT_CIRCUITS = {
    "committee_update": build_committee_update,
    "sync_step": build_sync_step,
    "aggregation": build_aggregation,
}

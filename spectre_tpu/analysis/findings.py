"""Finding records + the checked-in suppression baseline.

A finding is (engine, rule, severity, file, obj, message, key). The `key`
is the STABLE identity used for suppression — it names the rule, the
audited object, and a content detail (a count, an expression index, a
primitive name), so a baseline entry keeps matching across unrelated edits
but resurfaces the moment the underlying fact changes (e.g. the count of
unreferenced cells drifts). Severity gates the CLI exit code via
`--fail-on`.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass


class Severity:
    ERROR = "error"
    WARNING = "warning"
    INFO = "info"
    ORDER = {ERROR: 2, WARNING: 1, INFO: 0}

    @classmethod
    def at_least(cls, sev: str, threshold: str) -> bool:
        return cls.ORDER[sev] >= cls.ORDER[threshold]


@dataclass(frozen=True)
class Finding:
    engine: str      # "circuit" | "kernel" | "trace"
    rule: str        # e.g. "CA-UNDERCONSTRAINED", "KL-OVERFLOW"
    severity: str    # Severity.*
    file: str        # repo-relative path of the audited source
    obj: str         # circuit/kernel/probe name (e.g. "committee_update:tiny")
    message: str
    key: str = ""    # stable suppression key; default derived from the rest

    def __post_init__(self):
        if not self.key:
            object.__setattr__(self, "key", f"{self.rule}:{self.obj}")

    def to_dict(self) -> dict:
        return {"engine": self.engine, "rule": self.rule,
                "severity": self.severity, "file": self.file,
                "obj": self.obj, "message": self.message, "key": self.key}


def format_finding(f: Finding, suppressed: bool = False) -> str:
    tag = " [baseline]" if suppressed else ""
    return f"{f.severity:7s} {f.rule:20s} {f.file} ({f.obj}): {f.message}{tag}"


BASELINE_PATH = os.path.join(os.path.dirname(__file__), "baseline.json")


def load_baseline(path: str | None = None) -> dict:
    """Suppression file: {"suppressions": [{"key": ..., "reason": ...}]}.
    Returns {key -> reason}; missing file = empty baseline."""
    path = path or BASELINE_PATH
    if not os.path.exists(path):
        return {}
    with open(path) as f:
        data = json.load(f)
    return {e["key"]: e.get("reason", "") for e in data.get("suppressions", [])}


def write_baseline(findings: list, path: str | None = None,
                   reason: str = "accepted at baseline creation") -> str:
    """Accept the given findings: write (merge into) the suppression file."""
    path = path or BASELINE_PATH
    existing = load_baseline(path)
    for f in findings:
        existing.setdefault(f.key, f"{reason}: {f.message}")
    with open(path, "w") as fh:
        json.dump({"suppressions": [
            {"key": k, "reason": r} for k, r in sorted(existing.items())
        ]}, fh, indent=1)
        fh.write("\n")
    return path


def partition_findings(findings: list, baseline: dict):
    """-> (active, suppressed) preserving order."""
    active, suppressed = [], []
    for f in findings:
        (suppressed if f.key in baseline else active).append(f)
    return active, suppressed

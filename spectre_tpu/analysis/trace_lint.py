"""Engine 3: trace-cache hygiene lint (TC-*) over ops/, parallel/, plonk/.

The prover only hits hardware speed when every hot MSM/NTT/quotient call
reuses a compiled program. Both trace-cache bug classes this repo has
already paid for were found by hand:

  * ISSUE 13 (MULTICHIP rc=124): a fresh `shard_map` closure wrapped in a
    fresh `jax.jit` per call re-traced and re-lowered the full 8-way SPMD
    program for every MSM/NTT of a prove — ~60 multi-minute retraces on a
    1-core host, so the mesh prove never finished.
  * ISSUE 15 (Pallas MSM): a kernel body capturing a concrete traced array
    constant, which the Pallas lowering rejects (and which would otherwise
    bake a fresh constant into every trace).

This engine catches both classes mechanically, plus the registry drift
that would let them creep back:

  TC-FRESH-JIT       error  `jax.jit` / `shard_map` / `pallas_call`
                            constructed inside a function body with no
                            caching discipline: the enclosing function is
                            not `functools.cache`-decorated, is not itself
                            jit-decorated (an outer jit caches the trace),
                            and never stores into a module-level cache
                            dict. Every call mints a fresh traced program.
  TC-CONST-CAPTURE   error  a Pallas kernel body reads a module/closure
                            binding whose value is a concrete array
                            constructor (`jnp.asarray(...)`, ...) — the
                            PR 15 class; build the constant in-trace from
                            scalar literals instead.
  TC-UNSTABLE-STATIC error  a call site passes a list/dict/set/lambda/
                            comprehension at a `static_argnums` /
                            `static_argnames` position of a jitted entry
                            point: unhashable statics raise, and unstable
                            ones defeat the trace cache.
  TC-UNCACHED-RUNNER error  runner-registry drift: a function that builds
                            a jitted program and stores it in a module
                            cache dict is missing from that module's
                            `TRACE_RUNNER_CACHES` declaration — or a
                            declared entry went stale (builder or cache
                            renamed/removed). Same for `TRACE_JIT_ROOTS`
                            (module-level jitted entry points).
  TC-RETRACE-DYN     error  dynamic cross-check against
                            observability/compilelog: each registered
                            runner is called twice at a tiny shape and the
                            second call must trigger ZERO
                            `backend_compile` events (a warm trace cache).

The static rules are pure-AST (no imports of the scanned modules — ops/
modules cannot import parallel/ at import time, and the lint must not
care). The registry contract is declarative for the same reason: modules
that cache jitted runners declare `TRACE_RUNNER_CACHES = ((builder,
cache_dict), ...)` and modules with module-level jitted entry points
declare `TRACE_JIT_ROOTS = (name, ...)`; this engine cross-checks the
declarations against what the AST actually contains, and the dynamic
probe table below exercises the declared runners.

CLI: `python -m spectre_tpu.analysis --engine trace` (= `make lint-deep`).
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass

from .findings import Finding, Severity

_PKG = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_REPO = os.path.dirname(_PKG)

# directories under spectre_tpu/ the static rules scan
SCAN_DIRS = ("ops", "parallel", "plonk")

# last dotted component of a call that mints a traced program
_JIT_NAMES = {"jit", "shard_map", "pallas_call"}
# decorators that make a per-call jit construction safe (memoized builder)
_CACHE_DECOS = {"cache", "lru_cache", "cached_property"}
# concrete-array constructors whose module/closure bindings a Pallas
# kernel body must not capture
_ARRAY_FNS = {"asarray", "array", "zeros", "ones", "full", "arange",
              "empty", "eye", "linspace"}
_ARRAY_MODULES = {"jnp", "np", "numpy", "jax"}
# calls that build unhashable values (flagged at static positions)
_UNHASHABLE_CTORS = {"list", "dict", "set", "bytearray"}
_UNHASHABLE_NODES = (ast.List, ast.Dict, ast.Set, ast.ListComp, ast.SetComp,
                     ast.DictComp, ast.GeneratorExp, ast.Lambda)


# ---------------------------------------------------------------------------
# AST helpers
# ---------------------------------------------------------------------------

def _dotted(node) -> str | None:
    """`jax.jit` -> "jax.jit", `pl.pallas_call` -> "pl.pallas_call"."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _last(name: str) -> str:
    return name.rsplit(".", 1)[-1]


def _jit_kind(call: ast.Call) -> str | None:
    """The traced-program constructor a Call mints, or None.

    Matches direct calls (`jax.jit(f)`, `shard_map(...)`) and the partial
    idiom (`functools.partial(jax.jit, ...)`, used as decorator factory)."""
    name = _dotted(call.func)
    if name is None:
        return None
    if _last(name) in _JIT_NAMES:
        return _last(name)
    if _last(name) == "partial" and call.args:
        inner = _dotted(call.args[0])
        if inner and _last(inner) in _JIT_NAMES:
            return _last(inner)
    return None


def _pallas_kernel_arg(call: ast.Call):
    """The kernel-body argument of a pallas_call (direct or partial form)."""
    name = _dotted(call.func) or ""
    if _last(name) == "partial":
        return call.args[1] if len(call.args) > 1 else None
    return call.args[0] if call.args else None


def _is_cache_decorated(fn) -> bool:
    for dec in fn.decorator_list:
        name = _dotted(dec if not isinstance(dec, ast.Call) else dec.func)
        if name and _last(name) in _CACHE_DECOS:
            return True
    return False


def _is_jit_decorated(fn) -> bool:
    """@jax.jit / @functools.partial(jax.jit, ...): the OUTER jit caches
    the trace, so constructions inside the body are per-trace, not
    per-call."""
    for dec in fn.decorator_list:
        if isinstance(dec, ast.Call):
            if _jit_kind(dec) is not None:
                return True
        else:
            name = _dotted(dec)
            if name and _last(name) in _JIT_NAMES:
                return True
    return False


def _is_array_constant(value) -> bool:
    if not isinstance(value, ast.Call):
        return False
    name = _dotted(value.func)
    if not name:
        return False
    parts = name.split(".")
    return parts[-1] in _ARRAY_FNS and parts[0] in _ARRAY_MODULES


def _int_tuple(node) -> tuple:
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return (node.value,)
    if isinstance(node, ast.Tuple):
        return tuple(e.value for e in node.elts
                     if isinstance(e, ast.Constant)
                     and isinstance(e.value, int))
    return ()


def _str_tuple(node) -> tuple:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return (node.value,)
    if isinstance(node, ast.Tuple):
        return tuple(e.value for e in node.elts
                     if isinstance(e, ast.Constant)
                     and isinstance(e.value, str))
    return ()


def _static_spec(call: ast.Call):
    """(static positions, static names) of a jit construction, or None."""
    if _jit_kind(call) != "jit":
        return None
    pos, names = (), ()
    for kw in call.keywords:
        if kw.arg == "static_argnums":
            pos = _int_tuple(kw.value)
        elif kw.arg == "static_argnames":
            names = _str_tuple(kw.value)
    if pos or names:
        return (frozenset(pos), frozenset(names))
    return None


def _pairs_literal(node) -> set:
    """TRACE_RUNNER_CACHES literal -> {(builder, cache), ...}."""
    out = set()
    if isinstance(node, (ast.Tuple, ast.List)):
        for e in node.elts:
            if isinstance(e, (ast.Tuple, ast.List)) and len(e.elts) == 2:
                a, b = e.elts
                if (isinstance(a, ast.Constant) and isinstance(a.value, str)
                        and isinstance(b, ast.Constant)
                        and isinstance(b.value, str)):
                    out.add((a.value, b.value))
    return out


# ---------------------------------------------------------------------------
# per-module walk
# ---------------------------------------------------------------------------

class _Walker:
    """Collects function defs, jit-construction sites and pallas sites,
    each with its stack of enclosing FunctionDefs. Decorators are walked
    with the ENCLOSING stack (they evaluate in the outer scope)."""

    def __init__(self):
        self.defs: list = []          # (node, stack tuple)
        self.jit_sites: list = []     # (call, kind, stack tuple)
        self.pallas_sites: list = []  # (call, stack tuple)

    def walk(self, node, stack=()):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                self.walk(dec, stack)
            for default in list(node.args.defaults) + [
                    d for d in node.args.kw_defaults if d is not None]:
                self.walk(default, stack)
            self.defs.append((node, stack))
            inner = stack + (node,)
            for child in node.body:
                self.walk(child, inner)
            return
        if isinstance(node, ast.Call):
            kind = _jit_kind(node)
            if kind is not None:
                self.jit_sites.append((node, kind, stack))
                if kind == "pallas_call":
                    self.pallas_sites.append((node, stack))
        for child in ast.iter_child_nodes(node):
            self.walk(child, stack)


def _module_toplevel(tree):
    """(module names, array-const names, declared cache pairs, declared
    jit roots) from the module's top-level statements."""
    names, array_consts = set(), set()
    declared_caches, declared_roots = set(), ()
    for node in tree.body:
        targets, value = [], None
        if isinstance(node, ast.Assign):
            targets = [t for t in node.targets if isinstance(t, ast.Name)]
            value = node.value
        elif isinstance(node, ast.AnnAssign) and isinstance(node.target,
                                                            ast.Name):
            targets, value = [node.target], node.value
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef)):
            names.add(node.name)
        for t in targets:
            names.add(t.id)
            if value is not None and _is_array_constant(value):
                array_consts.add(t.id)
            if t.id == "TRACE_RUNNER_CACHES" and value is not None:
                declared_caches = _pairs_literal(value)
            if t.id == "TRACE_JIT_ROOTS" and value is not None:
                declared_roots = _str_tuple(value)
    return names, array_consts, declared_caches, declared_roots


def _store_names(fn, mod_names, _cache={}) -> frozenset:
    """Module-level dict names this function's subtree subscript-stores
    into (`_RUNNERS[key] = fn` — the runner-cache discipline)."""
    hit = _cache.get(id(fn))
    if hit is not None:
        return hit
    out = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if (isinstance(t, ast.Subscript)
                        and isinstance(t.value, ast.Name)
                        and t.value.id in mod_names):
                    out.add(t.value.id)
    out = frozenset(out)
    _cache[id(fn)] = out
    return out


def _rel(path: str) -> str:
    ap = os.path.abspath(path)
    if ap.startswith(_REPO + os.sep):
        return os.path.relpath(ap, _REPO)
    return os.path.basename(ap)


def default_files() -> list:
    out = []
    for d in SCAN_DIRS:
        base = os.path.join(_PKG, d)
        for fn in sorted(os.listdir(base)):
            if fn.endswith(".py"):
                out.append(os.path.join(base, fn))
    return out


def _collect_statics(tree, registry: dict):
    """Phase A: {entry-point name -> (static positions, static names)} from
    jit-with-statics decorators and `name = jax.jit(f, static_...)`."""
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                if isinstance(dec, ast.Call):
                    spec = _static_spec(dec)
                    if spec is not None:
                        registry[node.name] = spec
        elif isinstance(node, ast.Assign) and isinstance(node.value,
                                                         ast.Call):
            spec = _static_spec(node.value)
            if spec is not None:
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        registry[t.id] = spec


def _unhashable_desc(node) -> str | None:
    if isinstance(node, _UNHASHABLE_NODES):
        return type(node).__name__.lower()
    if isinstance(node, ast.Call):
        name = _dotted(node.func)
        if name and _last(name) in _UNHASHABLE_CTORS:
            return f"{_last(name)}(...)"
    return None


def _scan_file(path: str, tree, statics: dict) -> list:
    rel = _rel(path)
    mod = os.path.basename(path)[:-3]
    names, array_consts, declared_caches, declared_roots = \
        _module_toplevel(tree)
    w = _Walker()
    w.walk(tree)
    findings = []

    # ---- TC-FRESH-JIT -----------------------------------------------------
    def exempt(stack) -> bool:
        return any(_is_cache_decorated(f) or _is_jit_decorated(f)
                   or _store_names(f, names) for f in stack)

    seen = set()
    for call, kind, stack in w.jit_sites:
        if not stack or exempt(stack):
            continue
        qual = ".".join(f.name for f in stack)
        if (qual, kind) in seen:
            continue
        seen.add((qual, kind))
        findings.append(Finding(
            "trace", "TC-FRESH-JIT", Severity.ERROR, rel, f"{mod}:{qual}",
            f"{kind} constructed inside `{qual}` (line {call.lineno}) with "
            f"no caching discipline: every call re-traces and re-lowers the "
            f"program (the multichip rc=124 class). Hoist to module level, "
            f"memoize the builder, or store the jitted object in a "
            f"module-level runner cache keyed on the static params.",
            key=f"TC-FRESH-JIT:{rel}:{qual}:{kind}"))

    # ---- TC-CONST-CAPTURE -------------------------------------------------
    for call, stack in w.pallas_sites:
        karg = _pallas_kernel_arg(call)
        if not isinstance(karg, ast.Name):
            continue
        # resolve the kernel def: deepest def on the call's scope chain,
        # else module level
        kdef, kstack = None, ()
        for node, dstack in w.defs:
            if node.name != karg.id:
                continue
            if dstack == stack[:len(dstack)] and (
                    kdef is None or len(dstack) > len(kstack)):
                kdef, kstack = node, dstack
        if kdef is None:
            continue
        visible = set(array_consts)
        for f in kstack:  # closure bindings on the defining chain
            for node in ast.walk(f):
                if isinstance(node, ast.Assign) and _is_array_constant(
                        node.value):
                    visible.update(t.id for t in node.targets
                                   if isinstance(t, ast.Name))
        local = {a.arg for a in (kdef.args.args + kdef.args.posonlyargs
                                 + kdef.args.kwonlyargs)}
        local.update(n.id for n in ast.walk(kdef)
                     if isinstance(n, ast.Name)
                     and isinstance(n.ctx, (ast.Store, ast.Del)))
        for n in ast.walk(kdef):
            if (isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load)
                    and n.id in visible and n.id not in local):
                findings.append(Finding(
                    "trace", "TC-CONST-CAPTURE", Severity.ERROR, rel,
                    f"{mod}:{kdef.name}",
                    f"pallas kernel `{kdef.name}` captures the concrete "
                    f"array binding `{n.id}` from an outer scope — Pallas "
                    f"kernel bodies may not capture traced array constants "
                    f"(the PR 15 bug class); build it in-trace from scalar "
                    f"literals instead.",
                    key=f"TC-CONST-CAPTURE:{rel}:{kdef.name}:{n.id}"))
                break

    # ---- TC-UNSTABLE-STATIC -----------------------------------------------
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        callee = _dotted(node.func)
        if callee is None:
            continue
        spec = statics.get(_last(callee))
        if spec is None:
            continue
        pos, kwnames = spec
        for i, arg in enumerate(node.args):
            if i in pos:
                desc = _unhashable_desc(arg)
                if desc:
                    findings.append(Finding(
                        "trace", "TC-UNSTABLE-STATIC", Severity.ERROR, rel,
                        f"{mod}:{_last(callee)}",
                        f"call to `{callee}` (line {node.lineno}) passes "
                        f"{desc} at static position {i}: unhashable "
                        f"statics raise, unstable ones defeat the trace "
                        f"cache — pass a tuple / int / str.",
                        key=f"TC-UNSTABLE-STATIC:{rel}:{_last(callee)}:{i}"))
        for kw in node.keywords:
            if kw.arg in kwnames:
                desc = _unhashable_desc(kw.value)
                if desc:
                    findings.append(Finding(
                        "trace", "TC-UNSTABLE-STATIC", Severity.ERROR, rel,
                        f"{mod}:{_last(callee)}",
                        f"call to `{callee}` (line {node.lineno}) passes "
                        f"{desc} for static arg {kw.arg!r} — pass a "
                        f"hashable value.",
                        key=f"TC-UNSTABLE-STATIC:{rel}:{_last(callee)}"
                            f":{kw.arg}"))

    # ---- TC-UNCACHED-RUNNER (registry drift) ------------------------------
    def_names = {node.name for node, _ in w.defs}
    jit_fns = set()  # functions whose subtree constructs a jit
    for _call, _kind, stack in w.jit_sites:
        jit_fns.update(f.name for f in stack)
    detected = set()
    for node, _stack in w.defs:
        if node.name in jit_fns:
            for cache in _store_names(node, names):
                detected.add((node.name, cache))
    for builder, cache in sorted(detected - declared_caches):
        findings.append(Finding(
            "trace", "TC-UNCACHED-RUNNER", Severity.ERROR, rel,
            f"{mod}:{builder}",
            f"`{builder}` builds a jitted runner and caches it in "
            f"`{cache}` but is missing from this module's "
            f"TRACE_RUNNER_CACHES declaration — register it so the "
            f"retrace probes and the runner registry stay in sync.",
            key=f"TC-UNCACHED-RUNNER:{rel}:{builder}:{cache}"))
    for builder, cache in sorted(declared_caches):
        if builder not in def_names or cache not in names:
            findings.append(Finding(
                "trace", "TC-UNCACHED-RUNNER", Severity.ERROR, rel,
                f"{mod}:{builder}",
                f"TRACE_RUNNER_CACHES declares ({builder!r}, {cache!r}) "
                f"but the module no longer defines "
                f"{'that builder' if builder not in def_names else 'that cache dict'}"
                f" — stale registry entry.",
                key=f"TC-UNCACHED-RUNNER:{rel}:{builder}:{cache}:stale"))
    # module-level jitted entry points declared as lint roots
    jit_decorated = {node.name for node, stack in w.defs
                     if not stack and _is_jit_decorated(node)}
    jit_assigned = set()
    for node in tree.body:
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call) \
                and _jit_kind(node.value) is not None:
            jit_assigned.update(t.id for t in node.targets
                                if isinstance(t, ast.Name))
    for root in declared_roots:
        if root not in jit_decorated and root not in jit_assigned:
            findings.append(Finding(
                "trace", "TC-UNCACHED-RUNNER", Severity.ERROR, rel,
                f"{mod}:{root}",
                f"TRACE_JIT_ROOTS declares {root!r} but no module-level "
                f"jitted def/assignment of that name exists — stale root.",
                key=f"TC-UNCACHED-RUNNER:{rel}:{root}:root"))
    return findings


def scan_files(paths=None) -> list:
    """Static TC-* rules over the given files (default: the ops/,
    parallel/, plonk/ scan roots)."""
    paths = list(paths) if paths is not None else default_files()
    parsed = []
    for p in paths:
        with open(p) as fh:
            parsed.append((p, ast.parse(fh.read(), filename=p)))
    statics: dict = {}
    for _p, tree in parsed:
        _collect_statics(tree, statics)
    findings = []
    for p, tree in parsed:
        findings += _scan_file(p, tree, statics)
    return findings


# ---------------------------------------------------------------------------
# TC-RETRACE-DYN: dynamic double-call probes
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class ProbeSpec:
    """One registered runner exercised at a tiny shape: `build()` returns
    (fn, args); fn(*args) is called twice and the SECOND call must record
    zero `backend_compile` events (compilelog capture)."""
    name: str
    file: str
    build: object


def _probe_msm():
    import jax.numpy as jnp

    from ..ops import msm as MSM
    pts = jnp.zeros((8, 3, 16), jnp.uint32)
    sc = jnp.zeros((8, 16), jnp.uint32)

    # c=2 / nbits=4: the smallest statics that still exercise the full
    # windows->combine pipeline (compile cost scales with bucket count)
    def run(p, s):
        return MSM.combine_windows(MSM.msm_windows_bits(p, s, 2, 4), 2)

    return run, (pts, sc)


def _probe_ntt():
    import jax.numpy as jnp

    from ..fields import bn254
    from ..ops import ntt as NTT
    om = bn254.fr_root_of_unity(4)
    a = jnp.zeros((16, 16), jnp.uint32)

    def run(x):
        return NTT.ntt(x, om)

    return run, (a,)


def _probe_sharded_msm():
    import importlib

    import jax.numpy as jnp

    from ..parallel.plan import current_plan
    # the package re-exports the sharded_msm FUNCTION under the module's
    # name; import the module explicitly (same idiom as plonk/backend)
    SM = importlib.import_module("spectre_tpu.parallel.sharded_msm")
    plan = current_plan()
    n = plan.pad_rows(8)
    pts = plan.place(jnp.zeros((n, 3, 16), jnp.uint32), plan.point_spec)
    sc = plan.place(jnp.zeros((n, 16), jnp.uint32), plan.scalar_spec)

    def run(p, s):
        return SM.sharded_msm(p, s, 2, plan.mesh, nbits=4, plan=plan)

    return run, (pts, sc)


def _probe_sharded_fixed():
    import importlib

    import jax.numpy as jnp

    from ..parallel.plan import current_plan
    SM = importlib.import_module("spectre_tpu.parallel.sharded_msm")
    plan = current_plan()
    n = plan.pad_rows(8)
    nwin = (4 + 2) // 2  # signed windows at c=2 / nbits=4
    pts = plan.place(jnp.zeros((n, 3, 16), jnp.uint32), plan.point_spec)
    sc = plan.place(jnp.zeros((n, 16), jnp.uint32), plan.scalar_spec)
    ng = plan.place(jnp.zeros((n,), bool), plan.sign_spec)

    def run(p, s, g):
        tab = SM.sharded_fixed_table(p, 2, nwin, plan,
                                     base_key=("trace-probe", n))
        return SM.sharded_msm_fixed(tab, s, g, 2, plan, 4)

    return run, (pts, sc, ng)


def _probe_sharded_ntt():
    import importlib

    import jax.numpy as jnp

    from ..fields import bn254
    from ..parallel.plan import current_plan
    SN = importlib.import_module("spectre_tpu.parallel.sharded_ntt")
    plan = current_plan()
    om = bn254.fr_root_of_unity(4)
    a = jnp.zeros((16, 16), jnp.uint32)

    def run(x):
        return SN.sharded_ntt(x, om, plan.mesh, plan=plan)

    return run, (a,)


def _probe_sharded_quotient():
    import importlib

    import jax.numpy as jnp
    import numpy as np

    from ..fields import bn254
    from ..parallel.plan import current_plan
    SQ = importlib.import_module("spectre_tpu.parallel.sharded_quotient")
    plan = current_plan()
    d = plan.n_devices
    # 2^6 extended domain: Bailey 8x8, divisible by any pow2 mesh <= 8
    m, logm = 64, 6
    om = bn254.fr_root_of_unity(logm)
    g = 7  # COSET_GEN
    a = jnp.zeros((m, 16), jnp.uint32)
    stack = jnp.zeros((max(d, 2), m, 16), jnp.uint32)
    s = jnp.zeros((16,), jnp.uint32)

    def run(x, st, sc):
        # one pass through all four runner caches: eval (mul + fold),
        # roll, batch-sharded LDE, fused inverse (tables resident)
        ev = SQ._eval_runner(plan, "mul", m)(x, x)
        ev = SQ._eval_runner(plan, "fold", m)(ev, sc, x)
        r = SQ._roll_runner(plan, m, 4)(ev)
        lde = SQ._lde_runner(plan, st.shape[0], logm, om, g)(st)
        inv = SQ._inv_apply(plan, np.asarray(r), logm, om, g, (1,))
        return lde, inv

    return run, (a, stack, s)


def _probe_batch_msm():
    import jax.numpy as jnp

    from ..parallel.batch_msm import batch_msm_dp
    pts = jnp.zeros((8, 3, 16), jnp.uint32)
    sb = jnp.zeros((2, 8, 16), jnp.uint32)
    ng = jnp.zeros((2, 8), bool)

    # signed/GLV runner: the only batch path that honors a tiny nbits
    # (the unsigned runner hardwires 254-bit windows — far too slow to
    # compile inside the lint-deep budget)
    def run(p, s, g):
        return batch_msm_dp(p, s, c=2, neg_batch=g, nbits=4, signed=True)

    return run, (pts, sb, ng)


# K=6 tiny double-call contexts (the lint-deep runtime budget assumes
# exactly this scale — keep additions tiny-shape and seconds-cheap)
PROBES = [
    ProbeSpec("msm.windows+combine", "spectre_tpu/ops/msm.py", _probe_msm),
    ProbeSpec("ntt.ntt", "spectre_tpu/ops/ntt.py", _probe_ntt),
    ProbeSpec("sharded_msm.windows", "spectre_tpu/parallel/sharded_msm.py",
              _probe_sharded_msm),
    ProbeSpec("sharded_msm.fixed", "spectre_tpu/parallel/sharded_msm.py",
              _probe_sharded_fixed),
    ProbeSpec("sharded_ntt", "spectre_tpu/parallel/sharded_ntt.py",
              _probe_sharded_ntt),
    ProbeSpec("sharded_quotient", "spectre_tpu/parallel/sharded_quotient.py",
              _probe_sharded_quotient),
    ProbeSpec("batch_msm.dp", "spectre_tpu/parallel/batch_msm.py",
              _probe_batch_msm),
]


def run_probe(spec: ProbeSpec) -> list:
    """Warm call, then capture compile events around an identical second
    call: any backend_compile on call #2 means the runner re-traced."""
    from ..observability import compilelog
    compilelog.install()
    fn, args = spec.build()
    with compilelog.entry_point(f"trace_lint/{spec.name}"):
        fn(*args)  # warm the trace cache
        with compilelog.capture() as events:
            fn(*args)
    n = compilelog.summarize(events)["count"]
    if n == 0:
        return []
    return [Finding(
        "trace", "TC-RETRACE-DYN", Severity.ERROR, spec.file, spec.name,
        f"second identical call of `{spec.name}` compiled {n} new XLA "
        f"program(s): the runner is not hitting its trace cache (fresh "
        f"jit/shard_map per call, or an unstable cache key).",
        key=f"TC-RETRACE-DYN:{spec.name}")]


def run_probes(specs=None) -> list:
    findings = []
    for spec in (PROBES if specs is None else specs):
        findings += run_probe(spec)
    return findings


def lint_trace(files=None, probes=None, dynamic=True) -> list:
    """The full trace engine: static AST rules + dynamic retrace probes."""
    findings = scan_files(files)
    if dynamic:
        findings += run_probes(probes)
    findings.sort(key=lambda f: -Severity.ORDER[f.severity])
    return findings


def root_counts() -> dict:
    return {"trace_files": len(default_files()),
            "trace_probes": len(PROBES)}

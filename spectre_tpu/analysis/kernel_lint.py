"""Engine 2: JAX kernel lint — worst-case value-bound analysis over jaxprs.

The hot device ops keep 256-bit field elements as 16-bit limbs in uint32
lanes; every multiply-accumulate is budgeted by hand ("accumulators stay
< 2^24", field_ops.py header). This engine re-derives those budgets
mechanically: each kernel is traced to a jaxpr (`jax.make_jaxpr`, no
execution), input tensors get their DECLARED limb width (16 bits for limb
tensors, not the 32 the dtype would suggest), and an abstract interpreter
propagates worst-case integer value bounds through every primitive —
including scan/while/cond bodies, iterated to their trip count or to a
fixpoint.

Rules:

  KL-OVERFLOW   error   an integer multiply/add/shift/dot whose worst-case
                        TRUE value exceeds the lane dtype's max — the limb
                        headroom bug class (wrap silently corrupts high
                        bits). A product consumed ONLY by `and` masks is
                        exempt: x*y mod 2^32 has exact low bits, so masking
                        idioms like `(t0 * n0inv) & 0xFFFF` are sound.
  KL-FLOAT      error   any floating dtype inside a field-arithmetic jaxpr
                        (field elements through float units lose limbs).
  KL-CALLBACK   error   host callback primitives inside a jitted kernel
                        (pure_callback/io_callback/debug_callback/...): a
                        device round-trip per call, and a determinism leak.
  KL-WIDTH      error   host-side limb conversion (ops/limbs.py) violating
                        its declared 16-bit limb invariant on extreme
                        inputs (numpy probe, not a trace).

Kernels that are SPECIFIED over modular lanes (sha256: u32 addition is
mod-2^32 by FIPS 180-4) register with wrap_ok=True and skip KL-OVERFLOW.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from .findings import Finding, Severity

_CAP = 1 << 192          # bound ceiling: far above any flag threshold
_LOOP_ITER_CAP = 64      # max abstract iterations of a loop body

_CALLBACK_PRIMS = ("callback", "outside_call", "infeed", "outfeed")


def _is_float(dtype) -> bool:
    dt = np.dtype(dtype)
    return (dt.kind == "f" or np.issubdtype(dt, np.floating)
            or "float" in dt.name)  # ml_dtypes (bfloat16, fp8) included


def _dtype_max(dtype) -> int:
    dt = np.dtype(dtype)
    if dt == np.bool_:
        return 1
    if np.issubdtype(dt, np.integer):
        return int(np.iinfo(dt).max)
    return _CAP  # float handled by the KL-FLOAT walk


def _cap(v: int) -> int:
    return v if v < _CAP else _CAP


class _Lint:
    """Shared state across one kernel's interpretation."""

    def __init__(self, name: str, file: str, wrap_ok: bool):
        self.name = name
        self.file = file
        self.wrap_ok = wrap_ok
        self.findings: list = []
        self._keys: set = set()

    def report(self, rule: str, detail_key: str, message: str):
        key = f"{rule}:{self.name}:{detail_key}"
        if key in self._keys:
            return
        self._keys.add(key)
        self.findings.append(Finding(
            "kernel", rule, Severity.ERROR, self.file, self.name, message,
            key=key))


def _atom_bound(atom, env):
    import jax.core as jcore
    if isinstance(atom, jcore.Literal):
        v = atom.val
        arr = np.asarray(v)
        if arr.dtype == np.bool_:
            return 1
        if np.issubdtype(arr.dtype, np.integer):
            return int(arr.max()) if arr.size else 0
        return 0
    return env[atom]


def _masked_only(var, eqns):
    """True when every in-body consumer of var is a bitwise-and (the exact-
    low-bits masking idiom)."""
    used = False
    for eqn in eqns:
        if any(iv is var for iv in eqn.invars
               if not hasattr(iv, "val")):
            used = True
            if eqn.primitive.name != "and":
                return False
    return used  # an unconsumed overflow (escaping output) is not exempt


def _subjaxpr(params, *keys):
    for k in keys:
        if k in params:
            return params[k]
    return None


def _interp_jaxpr(jaxpr, consts, in_bounds, lint: _Lint, check: bool,
                  path: str = ""):
    """Abstract interpretation of one (open) jaxpr. Returns out bounds."""
    env: dict = {}
    cvals: dict = {}   # id(constvar) -> numpy value, for const-aware rules
    for v, c in zip(jaxpr.constvars, consts):
        arr = np.asarray(c)
        if np.issubdtype(arr.dtype, np.integer) or arr.dtype == np.bool_:
            env[v] = int(arr.max()) if arr.size else 0
            cvals[id(v)] = arr
        else:
            env[v] = 0  # float consts caught by the KL-FLOAT walk
    for v, b in zip(jaxpr.invars, in_bounds):
        env[v] = b

    outvar_set = {id(v) for v in jaxpr.outvars if not hasattr(v, "val")}

    for ei, eqn in enumerate(jaxpr.eqns):
        outs = _eval_eqn(eqn, ei, env, jaxpr.eqns, outvar_set, lint, check,
                         path, cvals)
        for ov, ob in zip(eqn.outvars, outs):
            env[ov] = ob
    return [_atom_bound(v, env) for v in jaxpr.outvars]


def _const_value(atom, cvals):
    """Integer numpy value of an atom when statically known, else None."""
    import jax.core as jcore
    if isinstance(atom, jcore.Literal):
        arr = np.asarray(atom.val)
        return arr if np.issubdtype(arr.dtype, np.integer) else None
    return cvals.get(id(atom)) if cvals else None


def _flag(lint, check, eqn, ei, path, env, eqns, outvar_set, true_val,
          dmax, what):
    """Common KL-OVERFLOW gate: wrap-ok kernels and masked-only consumers
    are exempt."""
    if not check or lint.wrap_ok or true_val <= dmax:
        return
    ov = eqn.outvars[0]
    if _masked_only(ov, eqns) and id(ov) not in outvar_set:
        return
    lint.report(
        "KL-OVERFLOW", f"{path}{eqn.primitive.name}{ei}",
        f"{what}: worst-case value 2^{true_val.bit_length()} exceeds "
        f"{np.dtype(ov.aval.dtype).name} max (2^{dmax.bit_length()}-1) and "
        f"the result is not mask-consumed — high bits silently wrap")


def _eval_eqn(eqn, ei, env, eqns, outvar_set, lint: _Lint, check: bool,
              path: str, cvals: dict | None = None):
    prim = eqn.primitive.name
    params = eqn.params
    ins = [_atom_bound(a, env) for a in eqn.invars]
    try:
        dmax = _dtype_max(eqn.outvars[0].aval.dtype)
    except (AttributeError, TypeError):
        dmax = _CAP

    if check and any(p in prim for p in _CALLBACK_PRIMS):
        lint.report("KL-CALLBACK", f"{path}{prim}{ei}",
                    f"host callback primitive '{prim}' inside the kernel "
                    f"jaxpr: device round-trip per call")

    # --- control flow: recurse -----------------------------------------
    if prim == "scan":
        closed = params["jaxpr"]
        ncons, ncarry = params["num_consts"], params["num_carry"]
        length = int(params.get("length", 1) or 1)
        consts_b = ins[:ncons]
        carry_b = list(ins[ncons:ncons + ncarry])
        xs_b = ins[ncons + ncarry:]  # per-step slices share the array bound
        iters = min(length, _LOOP_ITER_CAP)
        converged = False
        for _ in range(iters):
            outs = _interp_jaxpr(closed.jaxpr, closed.consts,
                                 consts_b + carry_b + xs_b, lint,
                                 check=False, path=path)
            new_carry = [max(a, b) for a, b in zip(carry_b, outs[:ncarry])]
            if new_carry == carry_b:
                converged = True
                break
            carry_b = new_carry
        if not converged and length > iters:
            # trip count exceeds the abstract budget and bounds still grow:
            # widen to dtype max and skip checks inside (no false accusals)
            carry_b = [_CAP for _ in carry_b]
            outs = _interp_jaxpr(closed.jaxpr, closed.consts,
                                 consts_b + carry_b + xs_b, lint,
                                 check=False, path=path)
        else:
            outs = _interp_jaxpr(closed.jaxpr, closed.consts,
                                 consts_b + carry_b + xs_b, lint,
                                 check=check, path=path + f"scan{ei}/")
        return outs[:ncarry] + outs[ncarry:]

    if prim == "while":
        cond_j, body_j = params["cond_jaxpr"], params["body_jaxpr"]
        cn, bn = params["cond_nconsts"], params["body_nconsts"]
        cond_consts = ins[:cn]
        body_consts = ins[cn:cn + bn]
        carry_b = list(ins[cn + bn:])
        converged = False
        for _ in range(16):
            _interp_jaxpr(cond_j.jaxpr, cond_j.consts,
                          cond_consts + carry_b, lint, check=False, path=path)
            outs = _interp_jaxpr(body_j.jaxpr, body_j.consts,
                                 body_consts + carry_b, lint, check=False,
                                 path=path)
            new_carry = [max(a, b) for a, b in zip(carry_b, outs)]
            if new_carry == carry_b:
                converged = True
                break
            carry_b = new_carry
        if converged:
            _interp_jaxpr(body_j.jaxpr, body_j.consts, body_consts + carry_b,
                          lint, check=check, path=path + f"while{ei}/")
        else:
            carry_b = [_CAP for _ in carry_b]
        return carry_b

    if prim == "cond":
        branches = params["branches"]
        op_ins = ins[1:]
        outs = None
        for bi, br in enumerate(branches):
            bouts = _interp_jaxpr(br.jaxpr, br.consts, op_ins, lint,
                                  check=check, path=path + f"cond{ei}.{bi}/")
            outs = bouts if outs is None else \
                [max(a, b) for a, b in zip(outs, bouts)]
        return outs

    closed = _subjaxpr(params, "jaxpr", "call_jaxpr", "fun_jaxpr")
    if closed is not None and hasattr(closed, "jaxpr"):
        return _interp_jaxpr(closed.jaxpr, closed.consts, ins, lint,
                             check=check, path=path + f"{prim}{ei}/")

    # --- arithmetic ----------------------------------------------------
    if prim == "mul":
        true = ins[0] * ins[1]
        _flag(lint, check, eqn, ei, path, env, eqns, outvar_set, true, dmax,
              f"integer multiply of bounds 2^{ins[0].bit_length()} x "
              f"2^{ins[1].bit_length()}")
        return [_cap(min(true, dmax))]
    if prim == "add":
        true = ins[0] + ins[1]
        _flag(lint, check, eqn, ei, path, env, eqns, outvar_set, true, dmax,
              "integer add-chain")
        return [_cap(min(true, dmax))]
    if prim == "sub":
        # signed a-b stays within max(|a|,|b|) magnitude (negative results
        # are representable, no wrap); unsigned wrap-to-borrow is a
        # deliberate idiom (_sub_limbs) — conservatively full-width there,
        # recovered by downstream masks
        try:
            if np.issubdtype(np.dtype(eqn.outvars[0].aval.dtype),
                             np.signedinteger):
                return [max(ins)]
        except (AttributeError, TypeError):
            pass
        return [dmax]
    if prim == "dot_general":
        dims = params.get("dimension_numbers")
        k = 1
        try:
            (lc, _rc), _ = dims
            for d in lc:
                k *= eqn.invars[0].aval.shape[d]
        except Exception:
            k = max(eqn.invars[0].aval.size, 1)
        # MXU accumulation dtype: `preferred_element_type` names the
        # systolic-array accumulator (int8 x int8 -> int32 on TPU); the
        # overflow budget is the ACCUMULATOR's, not the operand lanes'.
        # Absent the param, the output dtype is the accumulator (XLA
        # accumulates wider internally but wraps on store — which is
        # exactly the silent-wrap this rule exists to catch).
        acc_dt = params.get("preferred_element_type")
        acc_max = _dtype_max(acc_dt) if acc_dt is not None else dmax
        acc_name = np.dtype(acc_dt).name if acc_dt is not None \
            else np.dtype(eqn.outvars[0].aval.dtype).name
        true = ins[0] * ins[1] * k
        # const-operand refinement: when one side is a statically known
        # integer matrix (one-hot conv reductions, DFT twiddle tables), the
        # true per-output-entry bound is other_bound * max column |sum| of
        # the const over ITS contraction dims — for the one-hot [1024, 63]
        # convolution matrix that is other_bound * L8 (32), not
        # other_bound * 1024, which is what PROVES the C*L*255^2 int32
        # column bound of the matmul-NTT short transform
        try:
            (lc, rc), _ = dims
            for idx, cdims in ((0, tuple(lc)), (1, tuple(rc))):
                arr = _const_value(eqn.invars[idx], cvals)
                if arr is None or not cdims:
                    continue
                colsum = int(np.abs(arr.astype(np.int64)).sum(
                    axis=cdims).max()) if arr.size else 0
                true = min(true, ins[1 - idx] * colsum)
        except Exception:
            pass
        _flag(lint, check, eqn, ei, path, env, eqns, outvar_set, true,
              acc_max,
              f"dot_general accumulating {k} products in {acc_name} "
              f"(MXU accumulator)")
        return [_cap(min(true, acc_max, dmax))]
    if prim == "reduce_sum":
        try:
            k = max(eqn.invars[0].aval.size
                    // max(eqn.outvars[0].aval.size, 1), 1)
        except Exception:
            k = 1
        true = ins[0] * k
        _flag(lint, check, eqn, ei, path, env, eqns, outvar_set, true, dmax,
              f"reduce_sum over {k} lanes")
        return [_cap(min(true, dmax))]
    if prim == "integer_pow":
        y = params.get("y", 2)
        true = _cap(max(ins[0], 1) ** abs(y)) if y >= 0 else dmax
        _flag(lint, check, eqn, ei, path, env, eqns, outvar_set, true, dmax,
              f"integer_pow^{y}")
        return [_cap(min(true, dmax))]
    if prim == "shift_left":
        import jax.core as jcore
        s_atom = eqn.invars[1]
        if isinstance(s_atom, jcore.Literal):
            s = int(np.asarray(s_atom.val).max())
            true = ins[0] << s
            _flag(lint, check, eqn, ei, path, env, eqns, outvar_set, true,
                  dmax, f"shift_left by {s}")
            return [_cap(min(true, dmax))]
        return [dmax]  # data-dependent shift: cannot prove overflow
    if prim in ("shift_right_logical", "shift_right_arithmetic"):
        import jax.core as jcore
        s_atom = eqn.invars[1]
        if isinstance(s_atom, jcore.Literal):
            return [ins[0] >> int(np.asarray(s_atom.val).min())]
        return [ins[0]]
    if prim == "and":
        return [min(ins)]
    if prim in ("or", "xor"):
        bits = max(b.bit_length() for b in ins)
        return [min((1 << bits) - 1, dmax)]
    if prim == "not":
        return [dmax]
    if prim == "rem":
        import jax.core as jcore
        if isinstance(eqn.invars[1], jcore.Literal):
            return [min(ins[0], max(ins[1] - 1, 0))]
        return [ins[0]]
    if prim == "div":
        return [ins[0]]
    if prim in ("max", "min"):
        return [max(ins)] if prim == "max" else [min(ins)]
    if prim == "abs":
        # the bound tracks worst-case magnitude, and sub on signed lanes
        # already returns max(|a|,|b|) — abs preserves that magnitude
        # (signed-digit MSM: |digit| <= 2^(c-1), not int32 max)
        return [max(ins)]
    if prim == "neg":
        try:
            if np.issubdtype(np.dtype(eqn.outvars[0].aval.dtype),
                             np.signedinteger):
                return [max(ins)]
        except (AttributeError, TypeError):
            pass
        return [dmax]     # unsigned negation wraps
    if prim == "clamp":
        return [min(ins[1], ins[2])]
    if prim in ("eq", "ne", "lt", "le", "gt", "ge", "reduce_and",
                "reduce_or"):
        return [1 for _ in eqn.outvars]
    if prim == "iota":
        try:
            d = params.get("dimension", 0)
            return [max(eqn.outvars[0].aval.shape[d] - 1, 0)]
        except Exception:
            return [dmax]
    if prim in ("argmax", "argmin"):
        return [max(eqn.invars[0].aval.size - 1, 0)]
    if prim in ("reduce_max", "reduce_min"):
        return [ins[0]]
    if prim == "select_n":
        return [max(ins[1:]) if len(ins) > 1 else ins[0]]
    if prim == "concatenate":
        return [max(ins)]
    if prim == "pad":
        return [max(ins)]
    if prim == "sort":
        nout = len(eqn.outvars)
        return ins[:nout] if len(ins) >= nout else [max(ins)] * nout
    if prim in ("scatter", "scatter_max", "scatter-max"):
        return [max(ins[0], ins[-1])]
    if prim in ("scatter_add", "scatter-add"):
        upd = eqn.invars[-1].aval.size if hasattr(eqn.invars[-1], "aval") \
            else 1
        return [_cap(min(ins[0] + ins[-1] * max(upd, 1), dmax))]
    if prim == "convert_element_type":
        return [min(ins[0], dmax)]
    if prim in ("broadcast_in_dim", "reshape", "squeeze", "expand_dims",
                "transpose", "slice", "rev", "copy", "stop_gradient",
                "gather", "dynamic_slice", "device_put", "real", "imag",
                "reduce_precision"):
        return [ins[0]] + [dmax] * (len(eqn.outvars) - 1)
    if prim == "dynamic_update_slice":
        return [max(ins[0], ins[1])]

    # unknown primitive: conservative full-width outputs, never a finding
    return [dmax for _ in eqn.outvars]


def _walk_float_and_callbacks(jaxpr, lint: _Lint, path: str = ""):
    """KL-FLOAT: any floating dtype among eqn inputs/outputs/consts."""
    for ei, eqn in enumerate(jaxpr.eqns):
        for atom in list(eqn.invars) + list(eqn.outvars):
            aval = getattr(atom, "aval", None)
            dt = getattr(aval, "dtype", None)
            if dt is not None and _is_float(dt):
                lint.report(
                    "KL-FLOAT", f"{path}{eqn.primitive.name}{ei}",
                    f"float dtype {np.dtype(dt).name} flows through "
                    f"'{eqn.primitive.name}' — field arithmetic must stay "
                    f"integral (rounding destroys limbs)")
                break
        for p in eqn.params.values():
            sub = p if hasattr(p, "jaxpr") else None
            if sub is not None:
                _walk_float_and_callbacks(sub.jaxpr, lint,
                                          path + f"{eqn.primitive.name}{ei}/")
            elif isinstance(p, (tuple, list)):
                for q in p:
                    if hasattr(q, "jaxpr"):
                        _walk_float_and_callbacks(
                            q.jaxpr, lint,
                            path + f"{eqn.primitive.name}{ei}/")


def lint_fn(fn, args, *, name: str, file: str, in_bits=16,
            wrap_ok: bool = False) -> list:
    """Trace fn(*args) to a jaxpr and lint it. in_bits: declared input
    value width — an int for all array inputs, or a list per flattened
    input. The declared width is the analysis ROOT: 16-bit limb tensors in
    uint32 lanes start at 2^16-1, not the dtype's 2^32-1."""
    import jax

    closed = jax.make_jaxpr(fn)(*args)
    lint = _Lint(name, file, wrap_ok)
    invars = closed.jaxpr.invars
    if isinstance(in_bits, int):
        bits_list = [in_bits] * len(invars)
    else:
        bits_list = list(in_bits)
        assert len(bits_list) == len(invars), \
            f"{name}: {len(bits_list)} declared widths for {len(invars)} inputs"
    in_bounds = []
    for v, bits in zip(invars, bits_list):
        dm = _dtype_max(v.aval.dtype)
        in_bounds.append(min((1 << bits) - 1, dm))
    for c in closed.consts:
        arr = np.asarray(c)
        if _is_float(arr.dtype):
            lint.report("KL-FLOAT", "const",
                        f"float constant of dtype {arr.dtype} captured by "
                        f"the kernel trace")
    _interp_jaxpr(closed.jaxpr, closed.consts, in_bounds, lint, check=True)
    _walk_float_and_callbacks(closed.jaxpr, lint)
    return lint.findings


# ---------------------------------------------------------------------------
# kernel registry: the hot ops, traced at small shapes
# ---------------------------------------------------------------------------

@dataclass
class KernelSpec:
    name: str
    file: str
    build: object            # () -> (fn, args)
    in_bits: object = 16     # declared width(s) of the flattened inputs
    wrap_ok: bool = False    # mod-2^width lanes are the SPEC (sha256)


def _u32(shape, fill=0):
    return np.zeros(shape, dtype=np.uint32) + np.uint32(fill)


def _field_pair():
    import jax.numpy as jnp
    a = jnp.asarray(_u32((4, 16)))
    b = jnp.asarray(_u32((4, 16)))
    return a, b


def _build_field(op):
    def build():
        from ..ops import field_ops as F
        ctx = F.fr_ctx()
        a, b = _field_pair()
        if op in ("add", "sub", "mont_mul"):
            return (lambda x, y: getattr(F, op)(ctx, x, y)), (a, b)
        return (lambda x: getattr(F, op)(ctx, x)), (a,)
    return build


def _build_ntt(inverse=False):
    def build():
        import jax.numpy as jnp
        from ..ops import ntt as NTT
        from ..plonk.domain import Domain
        omega = Domain(3).omega
        a = jnp.asarray(_u32((8, 16)))
        # trace the unjitted kernel core (a jitted wrapper would lint as an
        # opaque pjit call) at the radix2 default
        if inverse:
            return (lambda x: NTT._inv_kernel.__wrapped__(
                x, omega, None, False, "radix2")), (a,)
        return (lambda x: NTT._fwd_kernel.__wrapped__(
            x, omega, None, "radix2")), (a,)
    return build


def _build_ntt_many(inverse=False):
    def build():
        import jax.numpy as jnp
        from ..ops import ntt as NTT
        from ..plonk.domain import Domain
        omega = Domain(3).omega
        a = jnp.asarray(_u32((2, 8, 16)))       # [B, n, 16] column stack
        if inverse:
            return (lambda x: NTT._inv_kernel.__wrapped__(
                x, omega, None, False, "radix2")), (a,)
        return (lambda x: NTT._fwd_kernel.__wrapped__(
            x, omega, None, "radix2")), (a,)
    return build


def _build_ntt_fourstep():
    def build():
        import jax.numpy as jnp
        from ..ops import ntt as NTT
        from ..plonk.domain import Domain
        omega = Domain(4).omega                 # n=16 -> 4x4 Bailey split
        a = jnp.asarray(_u32((2, 16, 16)))
        return (lambda x: NTT._fwd_kernel.__wrapped__(
            x, omega, None, "fourstep")), (a,)
    return build


def _build_coset_lde(mode):
    def build():
        import jax.numpy as jnp
        from ..ops import ntt as NTT
        from ..plonk.domain import Domain
        omega = Domain(4).omega
        a = jnp.asarray(_u32((2, 16, 16)))
        # the fused coset-LDE entry: std->mont + g^i scale in stage 0
        return (lambda x: NTT._fwd_kernel.__wrapped__(
            x, omega, ("std", 7), mode)), (a,)
    return build


def _build_coset_intt_std():
    def build():
        import jax.numpy as jnp
        from ..ops import ntt as NTT
        from ..plonk.domain import Domain
        omega = Domain(4).omega
        a = jnp.asarray(_u32((2, 16, 16)))
        # fused inverse: iNTT + combined g^{-i}·n^{-1} + mont->std table
        return (lambda x: NTT._inv_kernel.__wrapped__(
            x, omega, 7, True, "radix2")), (a,)
    return build


def _build_ntt_fourstep_matmul():
    def build():
        import jax.numpy as jnp
        from ..ops import ntt as NTT
        from ..plonk.domain import Domain
        omega = Domain(4).omega                 # n=16 -> 4x4 matmul legs
        a = jnp.asarray(_u32((2, 16, 16)))
        return (lambda x: NTT._fwd_kernel.__wrapped__(
            x, omega, None, "fourstep", "matmul")), (a,)
    return build


def _build_dft_matmul():
    def build():
        import jax.numpy as jnp
        from ..ops import ntt as NTT
        from ..plonk.domain import Domain
        # n=64 is the smallest length where the naive dot_general estimate
        # (n·255² · 1024 one-hot products) exceeds int32 — the const-colsum
        # refinement must PROVE the true C·L·255² column bound here
        omega = Domain(6).omega
        a = jnp.asarray(_u32((64, 16)))
        return (lambda x: NTT._ntt_dft_matmul(x, 6, omega)), (a,)
    return build


def _build_dft_matmul_split():
    def build():
        import jax.numpy as jnp
        from ..ops import ntt as NTT
        from ..plonk.domain import Domain
        # the two-level carry split (n > 1024 production path) traced at a
        # lintable length by forcing the group width: each group's one-hot
        # slice has column |sum| ≤ 4, so the refinement proves the grouped
        # bound n·255²·W — the same structure `lint_matmul_cap` scales to
        # the shipped _MATMUL_MAX_LOGN analytically
        omega = Domain(6).omega
        a = jnp.asarray(_u32((64, 16)))
        return (lambda x: NTT._ntt_dft_matmul(x, 6, omega,
                                              group_width=4)), (a,)
    return build


def _build_coset_intt_std_vinv():
    def build():
        import jax.numpy as jnp
        from ..ops import ntt as NTT
        from ..plonk.domain import Domain
        dom = Domain(2)                         # n_ext = 16
        a = jnp.asarray(_u32((2, 16, 16)))
        # the folded quotient inverse: vanishing-inverse period tuple as
        # the stage-0 pre-scale (real Domain values, as the prover passes)
        return (lambda x: NTT._inv_kernel.__wrapped__(
            x, dom.omega_ext, 7, True, "radix2", "stages",
            dom.vanishing_inv_period_vals())), (a,)
    return build


def _build_pallas_padd():
    def build():
        import jax.numpy as jnp
        from ..ops import msm_pallas as MP
        p = jnp.asarray(_u32((48, 4)))
        q = jnp.asarray(_u32((48, 4)))
        return (lambda a, b: MP._k_padd(a, b)), (p, q)
    return build


def _build_pallas_bucket():
    def build():
        import jax.numpy as jnp
        from ..ops import msm_pallas as MP
        # c=4: nb=2^(c-1)=8 signed-digit buckets, 2 windows, 4 points —
        # the exact body _bucket_kernel runs per block (pts/digs/negs/
        # buckets ref loads), traced here so KL rules walk the nested
        # fori_loops and prove the uint32 accumulators hold
        pts = jnp.asarray(_u32((1, 48, 4)))
        digs = jnp.zeros((2, 4), jnp.int32)
        negs = jnp.asarray(_u32((1, 4)))
        buckets = jnp.asarray(_u32((2, 48, 8)))
        return (lambda p, d, g, b:
                MP._k_bucket_accumulate(p, d, g, b)), (pts, digs, negs,
                                                       buckets)
    return build


def _build_glv_device():
    def build():
        import jax.numpy as jnp
        from ..ops import glv
        # [n, 16] full-scalar 16-bit limbs; the Barrett floor-division and
        # two's-complement residual scans must stay inside uint32 (the
        # CIOS-shaped _mul_const accumulator bound is < 2^22)
        sc = jnp.asarray(_u32((4, 16)))
        return (lambda s: glv.decompose_device.__wrapped__(s)), (sc,)
    return build


def _build_msm():
    import jax.numpy as jnp
    from ..ops import msm as M
    pts = jnp.asarray(_u32((8, 3, 16)))
    sc = jnp.asarray(_u32((8, 16)))
    return (lambda p, s: M.msm_windows.__wrapped__(p, s, 4)), (pts, sc)


def _build_msm_combine():
    import jax.numpy as jnp
    from ..ops import msm as M
    wins = jnp.asarray(_u32((64, 3, 16)))
    return (lambda w: M.combine_windows.__wrapped__(w, 4)), (wins,)


def _build_signed_digits():
    import jax.numpy as jnp
    from ..ops import msm as M
    sc = jnp.asarray(_u32((8, 8)))      # GLV half-scalar magnitudes
    return (lambda s: M.signed_digit_stream(s, 4, 32)), (sc,)


def _build_msm_signed():
    import jax.numpy as jnp
    from ..ops import msm as M
    pts = jnp.asarray(_u32((8, 3, 16)))
    sc = jnp.asarray(_u32((8, 8)))
    neg = jnp.zeros(8, dtype=bool)
    return (lambda p, s, g:
            M.msm_windows_signed.__wrapped__(p, s, g, 4, 126)), (pts, sc, neg)


def _build_msm_fixed():
    import jax.numpy as jnp
    from ..ops import msm as M
    c, nbits, n2 = 8, 126, 4
    nwin = (nbits + c) // c
    table = jnp.asarray(_u32((nwin, n2, 3, 16)))
    sc = jnp.asarray(_u32((n2, 8)))
    neg = jnp.zeros(n2, dtype=bool)
    return (lambda t, s, g:
            M.msm_fixed_run.__wrapped__(t, s, g, c, nbits)), (table, sc, neg)


def _build_msm_bits():
    import jax.numpy as jnp
    from ..ops import msm as M
    pts = jnp.asarray(_u32((8, 3, 16)))
    sc = jnp.asarray(_u32((8, 8)))      # GLV half-scalar width
    return (lambda p, s:
            M.msm_windows_bits.__wrapped__(p, s, 4, 126)), (pts, sc)


def _build_endo():
    import jax.numpy as jnp
    from ..ops import ec as E
    pts = jnp.asarray(_u32((8, 3, 16)))
    return (lambda p: E.endo(p)), (pts,)


# --- mesh-sharded kernels (ISSUE 13) ---------------------------------------
# The SPMD programs are shard_map closures (opaque to this tracer), so the
# per-shard LOCAL bodies are extracted as module functions in
# parallel/sharded_msm.py / sharded_ntt.py and traced here exactly as a
# single shard sees them: widx stands in for lax.axis_index, collectives
# (all_gather/all_to_all) happen outside these roots and move data only.

def _build_sharded_fold():
    import jax.numpy as jnp
    from ..parallel.sharded_msm import _fold_points
    stacked = jnp.asarray(_u32((4, 2, 3, 16)))
    return (lambda s: _fold_points(s)), (stacked,)


def _build_sharded_windows_signed():
    import jax.numpy as jnp
    from ..parallel.sharded_msm import _shard_windows_signed
    pts = jnp.asarray(_u32((8, 3, 16)))
    sc = jnp.asarray(_u32((8, 8)))      # GLV half-scalar magnitudes
    neg = jnp.zeros(8, dtype=bool)
    widx = jnp.uint32(0)
    # c=4 / 32 windows, one window shard (nloc == nwin_padded == nwin)
    return (lambda p, s, g, w: _shard_windows_signed(
        p, s, g, w, 4, 32, 32, 32, (1 << 3) + 1)), (pts, sc, neg, widx)


def _build_sharded_windows_unsigned():
    import jax.numpy as jnp
    from ..parallel.sharded_msm import _shard_windows_unsigned
    pts = jnp.asarray(_u32((8, 3, 16)))
    sc = jnp.asarray(_u32((8, 16)))     # full 254-bit scalars
    widx = jnp.uint32(0)
    return (lambda p, s, w: _shard_windows_unsigned(
        p, s, w, 4, 8, 8, 8, 1 << 4)), (pts, sc, widx)


def _build_sharded_fixed():
    import jax.numpy as jnp
    from ..parallel.sharded_msm import _shard_fixed_local
    c, nwin, n2 = 8, 16, 4
    table = jnp.asarray(_u32((nwin, n2, 3, 16)))
    sc = jnp.asarray(_u32((n2, 8)))
    neg = jnp.zeros(n2, dtype=bool)
    widx = jnp.uint32(0)
    return (lambda t, s, g, w: _shard_fixed_local(
        t, s, g, w, c, nwin, nwin, nwin, (1 << (c - 1)) + 1)), \
        (table, sc, neg, widx)


def _build_sharded_table():
    import jax.numpy as jnp
    from ..parallel.sharded_msm import _build_table_local
    pts = jnp.asarray(_u32((4, 3, 16)))
    # tiny chains (c=2, 4 windows, padded to 8) — bounds don't depend on
    # the doubling-chain length
    return (lambda p: _build_table_local(p, 2, 4, 8)), (pts,)


def _build_sharded_ntt_rows():
    def build():
        import jax.numpy as jnp
        from ..parallel.sharded_ntt import _rows_local
        from ..plonk.domain import Domain
        omega_row = Domain(3).omega
        block = jnp.asarray(_u32((4, 8, 16)))
        twb = jnp.asarray(_u32((4, 8, 16)))
        return (lambda b, t: _rows_local(b, t, omega_row, "radix2")), \
            (block, twb)
    return build


def _build_sharded_ntt_cols():
    def build():
        import jax.numpy as jnp
        from ..parallel.sharded_ntt import _cols_local
        from ..plonk.domain import Domain
        omega_col = Domain(3).omega
        y = jnp.asarray(_u32((4, 8, 16)))
        return (lambda b: _cols_local(b, omega_col, "radix2")), (y,)
    return build


# --- mesh-sharded quotient (ISSUE 19): per-shard bodies of the sharded
# LDE prefetch and the fused inverse boundary, traced single-shard like the
# sharded NTT above (the pointwise eval/roll runners contain only field_ops
# primitives and ppermute/concat — nothing beyond roots already covered).

def _build_sharded_quotient_lde():
    def build():
        import jax.numpy as jnp
        from ..parallel.sharded_quotient import _lde_local
        from ..plonk.domain import COSET_GEN, Domain
        omega = Domain(3).omega
        stack = jnp.asarray(_u32((2, 8, 16)))   # std-form columns
        return (lambda s: _lde_local(s, omega, COSET_GEN, "radix2",
                                     "stages")), (stack,)
    return build


def _build_sharded_quotient_inv_rows():
    def build():
        import jax.numpy as jnp
        from ..fields import bn254
        from ..parallel.sharded_quotient import _inv_rows_local
        from ..plonk.domain import Domain
        omega_row = pow(Domain(3).omega, -1, bn254.R)
        block = jnp.asarray(_u32((4, 8, 16)))
        scb = jnp.asarray(_u32((4, 8, 16)))     # vinv stage-0 pre-scale
        twb = jnp.asarray(_u32((4, 8, 16)))
        return (lambda b, s, t: _inv_rows_local(
            b, s, t, omega_row, "radix2", "stages")), (block, scb, twb)
    return build


def _build_sharded_quotient_inv_cols():
    def build():
        import jax.numpy as jnp
        from ..fields import bn254
        from ..parallel.sharded_quotient import _inv_cols_local
        from ..plonk.domain import Domain
        omega_col = pow(Domain(3).omega, -1, bn254.R)
        y = jnp.asarray(_u32((4, 8, 16)))
        outb = jnp.asarray(_u32((4, 8, 16)))    # raw combined out table
        return (lambda b, o: _inv_cols_local(
            b, o, omega_col, "radix2", "stages")), (y, outb)
    return build


def _build_field_mxu():
    def build():
        from ..ops import field_mxu as M
        from ..ops import field_ops as F
        ctx = F.fr_ctx()
        a, b = _field_pair()
        return (lambda x, y: M.mont_mul(ctx, x, y)), (a, b)
    return build


def _build_poseidon():
    import jax.numpy as jnp
    from ..ops import poseidon as P
    state = jnp.asarray(_u32((2, P.T, 16)))
    return (lambda s: P.permute(s)), (state,)


def _build_sha_compress():
    import jax.numpy as jnp
    from ..ops import sha256 as S
    state = jnp.asarray(_u32((2, 8)))
    blocks = jnp.asarray(_u32((2, 16)))
    return (lambda st, bl: S.compress(st, bl)), (state, blocks)


def _build_sha_pairs():
    import jax.numpy as jnp
    from ..ops import sha256 as S
    left = jnp.asarray(_u32((2, 8)))
    right = jnp.asarray(_u32((2, 8)))
    return (lambda l_, r_: S.hash_pairs(l_, r_)), (left, right)


KERNELS = [
    KernelSpec("field_ops.add", "spectre_tpu/ops/field_ops.py",
               _build_field("add")),
    KernelSpec("field_ops.sub", "spectre_tpu/ops/field_ops.py",
               _build_field("sub")),
    KernelSpec("field_ops.mont_mul", "spectre_tpu/ops/field_ops.py",
               _build_field("mont_mul")),
    KernelSpec("field_ops.neg", "spectre_tpu/ops/field_ops.py",
               _build_field("neg")),
    KernelSpec("field_ops.to_mont", "spectre_tpu/ops/field_ops.py",
               _build_field("to_mont")),
    KernelSpec("field_ops.inv", "spectre_tpu/ops/field_ops.py",
               _build_field("inv")),
    KernelSpec("ntt.ntt", "spectre_tpu/ops/ntt.py", _build_ntt(False)),
    KernelSpec("ntt.intt", "spectre_tpu/ops/ntt.py", _build_ntt(True)),
    # batched / moded NTT pipeline entry points (ISSUE 4): the [B, n, 16]
    # many-polynomial kernels, the four-step (Bailey) mode, and the fused
    # coset-LDE boundaries must stay inside the same value budgets as the
    # per-column radix-2 path they replace
    KernelSpec("ntt.ntt_many", "spectre_tpu/ops/ntt.py",
               _build_ntt_many(False)),
    KernelSpec("ntt.intt_many", "spectre_tpu/ops/ntt.py",
               _build_ntt_many(True)),
    KernelSpec("ntt.fourstep", "spectre_tpu/ops/ntt.py",
               _build_ntt_fourstep()),
    KernelSpec("ntt.coset_lde_std", "spectre_tpu/ops/ntt.py",
               _build_coset_lde("radix2")),
    KernelSpec("ntt.coset_lde_fourstep", "spectre_tpu/ops/ntt.py",
               _build_coset_lde("fourstep")),
    KernelSpec("ntt.coset_intt_std", "spectre_tpu/ops/ntt.py",
               _build_coset_intt_std()),
    # MXU-native matmul NTT (this PR): the DFT-matmul short-transform body
    # both inside the fourstep pipeline and standalone at the length where
    # the int32 column bound needs the const-colsum dot_general refinement,
    # plus the folded quotient vanishing-inverse variant of the fused iNTT
    KernelSpec("ntt.fourstep_matmul", "spectre_tpu/ops/ntt.py",
               _build_ntt_fourstep_matmul()),
    KernelSpec("ntt.dft_matmul", "spectre_tpu/ops/ntt.py",
               _build_dft_matmul()),
    KernelSpec("ntt.dft_matmul_split", "spectre_tpu/ops/ntt.py",
               _build_dft_matmul_split()),
    KernelSpec("ntt.coset_intt_std_vinv", "spectre_tpu/ops/ntt.py",
               _build_coset_intt_std_vinv()),
    # Pallas MSM complete-add body: the exact jaxpr pallas_call runs per
    # block, traced directly so KL rules see the CIOS scans
    KernelSpec("msm_pallas.padd_body", "spectre_tpu/ops/msm_pallas.py",
               _build_pallas_padd()),
    # VMEM-resident bucket accumulation body (this PR): signed digits are
    # int32 lanes (|d| <= 2^(c-1), declared 4 bits at the c=4 probe shape),
    # the GLV sign mask is 1 bit, and the resident bucket tensor must stay
    # a sound 16-bit-limb uint32 accumulator through the cneg+padd chain
    KernelSpec("msm_pallas.bucket_body", "spectre_tpu/ops/msm_pallas.py",
               _build_pallas_bucket(), in_bits=[16, 4, 1, 16]),
    # on-device GLV Babai rounding (this PR): exact Barrett floor division
    # + mod-2^144 two's-complement residuals, all in uint32 limb lanes
    KernelSpec("glv.decompose_device", "spectre_tpu/ops/glv.py",
               _build_glv_device()),
    KernelSpec("msm.msm_windows", "spectre_tpu/ops/msm.py", _build_msm),
    KernelSpec("msm.combine_windows", "spectre_tpu/ops/msm.py",
               _build_msm_combine),
    # GLV / signed-digit / fixed-base MSM entry points (PR 2): the digit
    # recode carries signed int32 lanes and the window kernels fold sign
    # masks into point negations — all must stay inside the same value
    # budgets as the vanilla path
    KernelSpec("msm.signed_digit_stream", "spectre_tpu/ops/msm.py",
               _build_signed_digits),
    KernelSpec("msm.msm_windows_signed", "spectre_tpu/ops/msm.py",
               _build_msm_signed, in_bits=[16, 16, 1]),
    KernelSpec("msm.msm_fixed_run", "spectre_tpu/ops/msm.py",
               _build_msm_fixed, in_bits=[16, 16, 1]),
    # PR 3 (fallback coverage): plain-glv mode enters via msm_windows_bits
    # at GLV half-scalar width — the one MSM entry point not yet traced
    # (the fixed->glv+signed table-budget degrade rides the already-
    # registered msm_windows_signed); register it so every mode a degraded
    # service can select stays under lint
    KernelSpec("msm.msm_windows_bits", "spectre_tpu/ops/msm.py",
               _build_msm_bits),
    KernelSpec("ec.endo", "spectre_tpu/ops/ec.py", _build_endo),
    # mesh-sharded MSM/NTT per-shard bodies (ISSUE 13): the shard_map
    # programs route ALL local math through these extracted roots, so a
    # width/float regression in the distributed path shows up here without
    # needing a device mesh in the linter
    KernelSpec("sharded_msm.fold_points",
               "spectre_tpu/parallel/sharded_msm.py", _build_sharded_fold),
    KernelSpec("sharded_msm.windows_shard_signed",
               "spectre_tpu/parallel/sharded_msm.py",
               _build_sharded_windows_signed, in_bits=[16, 16, 1, 1]),
    KernelSpec("sharded_msm.windows_shard",
               "spectre_tpu/parallel/sharded_msm.py",
               _build_sharded_windows_unsigned, in_bits=[16, 16, 1]),
    KernelSpec("sharded_msm.fixed_shard",
               "spectre_tpu/parallel/sharded_msm.py",
               _build_sharded_fixed, in_bits=[16, 16, 1, 1]),
    KernelSpec("sharded_msm.table_build_shard",
               "spectre_tpu/parallel/sharded_msm.py", _build_sharded_table),
    KernelSpec("sharded_ntt.rows_shard",
               "spectre_tpu/parallel/sharded_ntt.py",
               _build_sharded_ntt_rows()),
    KernelSpec("sharded_ntt.cols_shard",
               "spectre_tpu/parallel/sharded_ntt.py",
               _build_sharded_ntt_cols()),
    KernelSpec("sharded_quotient.lde_shard",
               "spectre_tpu/parallel/sharded_quotient.py",
               _build_sharded_quotient_lde()),
    KernelSpec("sharded_quotient.inv_rows_shard",
               "spectre_tpu/parallel/sharded_quotient.py",
               _build_sharded_quotient_inv_rows()),
    KernelSpec("sharded_quotient.inv_cols_shard",
               "spectre_tpu/parallel/sharded_quotient.py",
               _build_sharded_quotient_inv_cols()),
    # MXU int8-limb matmul field multiply (shapes stabilized; the
    # dot_general rule reads its preferred_element_type accumulator)
    KernelSpec("field_mxu.mont_mul", "spectre_tpu/ops/field_mxu.py",
               _build_field_mxu()),
    KernelSpec("poseidon.permute", "spectre_tpu/ops/poseidon.py",
               _build_poseidon),
    # SHA-256 u32 lanes are modular BY SPEC (FIPS 180-4): wrap is the
    # semantics, so only float/callback rules apply
    KernelSpec("sha256.compress", "spectre_tpu/ops/sha256.py",
               _build_sha_compress, in_bits=32, wrap_ok=True),
    KernelSpec("sha256.hash_pairs", "spectre_tpu/ops/sha256.py",
               _build_sha_pairs, in_bits=32, wrap_ok=True),
]


def lint_limbs_host() -> list:
    """KL-WIDTH probe for the host-side limb converters (numpy, untraceable):
    drive them with extreme inputs and check the declared 16-bit invariant
    plus exact round-trips. A widened limb or dropped mask shows up here."""
    from ..fields import bn254
    from ..ops import limbs as L

    out = []
    file = "spectre_tpu/ops/limbs.py"

    def bad(detail, msg):
        out.append(Finding("kernel", "KL-WIDTH", Severity.ERROR, file,
                           "limbs.host", msg, key=f"KL-WIDTH:limbs:{detail}"))

    ones64 = np.full((3, 4), np.uint64(2**64 - 1), dtype=np.uint64)
    u16 = L.u64limbs_to_u16limbs(ones64)
    if int(u16.max()) > L.LIMB_MASK:
        bad("u64to16-mask", f"u64limbs_to_u16limbs emits limb "
            f"{int(u16.max()):#x} > declared {L.LIMB_BITS}-bit mask")
    if not np.array_equal(L.u16limbs_to_u64limbs(u16), ones64):
        bad("u64-roundtrip", "u64<->u16 limb round-trip loses bits at the "
            "all-ones extreme")
    vals = [0, 1, bn254.R - 1, 2**256 - 1]
    limbs = L.ints_to_limbs16(vals)
    if int(limbs.max()) > L.LIMB_MASK:
        bad("ints-mask", f"ints_to_limbs16 emits limb {int(limbs.max()):#x} "
            f"> declared {L.LIMB_BITS}-bit mask")
    if L.limbs16_to_ints(limbs) != [v % (2**256) for v in vals]:
        bad("ints-roundtrip", "ints<->limbs16 round-trip diverges on "
            "extreme values")
    return out


def lint_matmul_cap() -> list:
    """PROVE the DFT-matmul exactness budget at the shipped
    `ntt._MATMUL_MAX_LOGN` — closed-form over exact host integers, so the cap
    is a theorem, not an assertion. The traced `ntt.dft_matmul*` specs walk
    the real jaxpr structure at a lintable length; this check scales the same
    bounds to the cap, where materializing the [n, n·32] table (512 MB at
    n=4096) is not lintable. Any cap bump without re-widening the group
    split / REDC radix lands here as a KL-OVERFLOW error."""
    from ..ops import field_mxu as MX
    from ..ops import field_ops as F
    from ..ops import ntt as NTT

    out = []
    file = "spectre_tpu/ops/ntt.py"
    int32 = (1 << 31) - 1

    def bad(detail, msg):
        out.append(Finding("kernel", "KL-OVERFLOW", Severity.ERROR, file,
                           "ntt.matmul_cap", msg,
                           key=f"KL-OVERFLOW:ntt.matmul_cap:{detail}"))

    logn = NTT._MATMUL_MAX_LOGN
    n = 1 << logn
    p = F.fr_ctx().p
    width = NTT._conv_group_width(logn)

    # (1) first dot_general column: x8 lanes ≤ 255 times the twiddle-limb
    # matrix's worst contraction column |sum| ≤ 255·n (entries are 8-bit)
    if 255 * 255 * n > int32:
        bad("dot-g", f"point-axis dot_general column 255²·n = {255*255*n} "
            f"exceeds int32 at n={n}")
    # (2) grouped one-hot collapse + carry scan: the REAL conv matrix's
    # per-group column count times the per-product bound, plus the running
    # carry (≤ peak/255) — peak W·n·255·256
    s = MX.conv_matrix(MX.L8, MX.L8, 63)
    for lo in range(0, MX.L8, width):
        colsum = int(np.abs(s[lo * MX.L8:(lo + width) * MX.L8]
                            .astype(np.int64)).sum(axis=0).max())
        peak = colsum * n * 255 * 256       # column sum + carry-scan remainder
        if peak > int32:
            bad("conv-col", f"grouped collapse column at i1∈[{lo},{lo+width})"
                f": colsum {colsum} · n·255·256 = {peak} exceeds int32 at "
                f"the shipped cap n={n} (widen the split: _conv_group_width)")
    # (3) group-sum renormalization: ≤ ceil(32/W) exact 8-bit lanes per limb
    groups = (MX.L8 + width - 1) // width
    if groups * 255 + groups > int32:       # trivially true; kept explicit
        bad("group-sum", "group-sum lanes exceed int32")
    # (4) t and m·p fit the declared limb count
    if n * p * p >= 1 << (8 * NTT._T_LIMBS):
        bad("t-limbs", f"t < n·p² needs more than _T_LIMBS={NTT._T_LIMBS} "
            f"8-bit limbs at n={n}")
    if (1 << NTT._REDC_SHIFT) * p >= 1 << (8 * NTT._T_LIMBS):
        bad("mp-limbs", f"m·p < 2^{NTT._REDC_SHIFT}·p overflows "
            f"_T_LIMBS={NTT._T_LIMBS} limbs")
    # (5) single-REDC full reduction: u < n·p²/2^shift + p < 2p needs
    # n·p < 2^shift — the one conditional subtract is only sound under it
    if n * p >= 1 << NTT._REDC_SHIFT:
        bad("redc", f"single-REDC bound n·p < 2^{NTT._REDC_SHIFT} fails at "
            f"n={n}: u < 2p no longer holds (raise _REDC_SHIFT)")
    # (6) REDC limb products: mul_columns columns ≤ limbs·255²
    if NTT._REDC_LIMBS * 255 * 255 > int32:
        bad("mul-cols", "REDC mul_columns column exceeds int32")
    return out


def lint_kernel(spec: KernelSpec) -> list:
    fn, args = spec.build()
    return lint_fn(fn, args, name=spec.name, file=spec.file,
                   in_bits=spec.in_bits, wrap_ok=spec.wrap_ok)


def lint_all_kernels(names=None) -> list:
    findings = []
    for spec in KERNELS:
        if names and spec.name not in names:
            continue
        findings += lint_kernel(spec)
    if not names or "limbs.host" in names:
        findings += lint_limbs_host()
    if not names or "ntt.matmul_cap" in names:
        findings += lint_matmul_cap()
    return findings

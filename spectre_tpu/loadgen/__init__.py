"""Load generator for the serving gateway: Zipf-distributed simulated
light-client populations with client-side ETag caches. See drill.py."""

from .drill import (DEFAULT_MIX, DEFAULT_ZIPF_S, HttpTarget,
                    InProcessTarget, ZipfSampler, run_drill)

__all__ = ["DEFAULT_MIX", "DEFAULT_ZIPF_S", "HttpTarget",
           "InProcessTarget", "ZipfSampler", "run_drill"]

"""Light-client load drill (ISSUE 14 tentpole, part 2).

Replays a configurable simulated client population against a serving
gateway and reports what a CDN operator would ask: latency percentiles,
requests/s, the 304 ratio, and the gateway's own counters
(pack hits / cache evictions / store fallbacks). The traffic model is
the paper's serving story in miniature:

* **population** — ``clients`` simulated light clients (default 10^6
  from the CLI, 10^4 in the bench tier). Each client keeps a small
  client-side digest cache (the ETag of every response it has seen) and
  sends ``If-None-Match`` on revisits — exactly what
  ``rpc_client.ProverClient.get_update_cached`` does for real clients.
* **periods** — Zipf-distributed over the stored chain (rank 1 = the
  newest period): real light clients overwhelmingly pull the recent
  tail, with a long tail of cold bootstrappers walking history.
* **mix** — bootstrap / range / single-update traffic in configurable
  proportions (defaults: 5% bootstrap, 25% range, 70% single).
* **faults** — arm ``SPECTRE_FAULT_PLAN`` before the run and the drill
  doubles as a chaos exercise; the acceptance drill runs with
  ``gateway.pack_write:ioerror`` + a torn journal tail active.

Targets are duck-typed: :class:`InProcessTarget` drives a
:class:`~spectre_tpu.gateway.Gateway` directly (zero HTTP overhead —
what the bench tier measures), :class:`HttpTarget` drives a live
server's ``/v1/*`` routes over urllib. Everything is stdlib; no numpy
on the request path.
"""

from __future__ import annotations

import bisect
import random
import threading
import time
import urllib.error
import urllib.request

DEFAULT_MIX = {"bootstrap": 0.05, "range": 0.25, "single": 0.70}
DEFAULT_ZIPF_S = 1.1


class ZipfSampler:
    """Zipf over ranks 1..n via inverse-CDF + bisect (stdlib only)."""

    def __init__(self, n: int, s: float = DEFAULT_ZIPF_S):
        self.n = max(1, int(n))
        weights, total = [], 0.0
        for rank in range(1, self.n + 1):
            total += 1.0 / (rank ** s)
            weights.append(total)
        self._cdf = [w / total for w in weights]

    def sample(self, rng: random.Random) -> int:
        """0-based rank: 0 is the hottest."""
        return bisect.bisect_left(self._cdf, rng.random())


class InProcessTarget:
    """Drives a Gateway object directly — the bench tier's target."""

    def __init__(self, gateway):
        self.gateway = gateway

    def get(self, path: str, if_none_match: str | None = None):
        """(status, etag) — the drill only needs cache-validation data."""
        status, headers, _body = self.gateway.handle_http(
            path, {"If-None-Match": if_none_match} if if_none_match
            else None)
        return status, headers.get("ETag")


class HttpTarget:
    """Drives a live server's /v1/* routes (the CLI's default)."""

    def __init__(self, base_url: str, timeout: float = 10.0):
        self.base_url = base_url.rstrip("/")
        self.timeout = timeout

    def get(self, path: str, if_none_match: str | None = None):
        req = urllib.request.Request(self.base_url + path)
        if if_none_match:
            req.add_header("If-None-Match", if_none_match)
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                resp.read()
                return resp.status, resp.headers.get("ETag")
        except urllib.error.HTTPError as exc:
            exc.read()
            return exc.code, exc.headers.get("ETag")


def _percentile(sorted_vals: list[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1, int(q * len(sorted_vals)))
    return sorted_vals[idx]


class _Worker:
    """One drill shard: its own RNG stream + per-client etag caches
    (lazily created — only clients that actually fire allocate one)."""

    def __init__(self, target, periods: list[int], tip: int,
                 zipf: ZipfSampler, mix: dict, clients: int,
                 requests: int, range_count: int, seed: int):
        self.target = target
        self.periods = periods       # newest first (Zipf rank order)
        self.tip = tip
        self.zipf = zipf
        self.mix = mix
        self.clients = clients
        self.requests = requests
        self.range_count = range_count
        self.rng = random.Random(seed)
        self.etags: dict[int, dict] = {}    # client -> {path: etag}
        self.latencies: list[float] = []
        self.statuses: dict[int, int] = {}
        self.sealed_requests = 0
        self.sealed_304s = 0
        self.sent_inm = 0

    def _pick_path(self) -> tuple[str, bool]:
        """(request path, whole request is sealed-period traffic)."""
        r = self.rng.random()
        period = self.periods[self.zipf.sample(self.rng)]
        if r < self.mix["bootstrap"]:
            return "/v1/bootstrap", False
        if r < self.mix["bootstrap"] + self.mix["range"]:
            count = self.rng.randint(1, self.range_count)
            start = max(self.periods[-1], period - count + 1)
            count = min(count, self.tip - start + 1)
            sealed = start + count - 1 < self.tip
            return f"/v1/updates?start={start}&count={count}", sealed
        return f"/v1/update/{period}", period < self.tip

    def run(self):
        for _ in range(self.requests):
            client = self.rng.randrange(self.clients)
            path, sealed = self._pick_path()
            cache = self.etags.get(client)
            inm = cache.get(path) if cache else None
            if inm:
                self.sent_inm += 1
            t0 = time.perf_counter()
            status, etag = self.target.get(path, if_none_match=inm)
            self.latencies.append(time.perf_counter() - t0)
            self.statuses[status] = self.statuses.get(status, 0) + 1
            if sealed:
                self.sealed_requests += 1
                if status == 304:
                    self.sealed_304s += 1
            if etag and status in (200, 304):
                if cache is None:
                    cache = self.etags.setdefault(client, {})
                cache[path] = etag
        return self


def run_drill(target, periods: list[int], tip: int,
              clients: int = 10_000, requests: int | None = None,
              zipf_s: float = DEFAULT_ZIPF_S, mix: dict | None = None,
              range_count: int = 8, threads: int = 1,
              seed: int = 0, health=None) -> dict:
    """Run the drill; returns the report dict (latency percentiles in
    ms, rps, status mix, sealed-traffic accounting, and — when `health`
    is passed — the gateway counter deltas over the run).

    `periods` must be newest-first (Zipf rank 0 = hottest = newest);
    `requests` defaults to 2 per client so revisits exercise the
    If-None-Match -> 304 path.
    """
    if not periods:
        raise ValueError("run_drill needs a non-empty period list")
    mix = dict(DEFAULT_MIX if mix is None else mix)
    total = sum(mix.values())
    mix = {k: v / total for k, v in mix.items()}
    if requests is None:
        requests = 2 * clients
    zipf = ZipfSampler(len(periods), zipf_s)
    before = dict(health.snapshot()["counters"]) if health else {}
    threads = max(1, int(threads))
    share, rem = divmod(requests, threads)
    workers = [_Worker(target, periods, tip, zipf, mix, clients,
                       share + (1 if i < rem else 0), range_count,
                       seed + i) for i in range(threads)]
    t0 = time.perf_counter()
    if threads == 1:
        workers[0].run()
    else:
        ts = [threading.Thread(target=w.run) for w in workers]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
    elapsed = time.perf_counter() - t0

    lat = sorted(x for w in workers for x in w.latencies)
    statuses: dict[int, int] = {}
    for w in workers:
        for s, c in w.statuses.items():
            statuses[s] = statuses.get(s, 0) + c
    n304 = statuses.get(304, 0)
    report = {
        "clients": clients,
        "requests": requests,
        "threads": threads,
        "elapsed_s": round(elapsed, 4),
        "rps": round(requests / elapsed, 1) if elapsed > 0 else 0.0,
        "latency_ms": {
            "p50": round(_percentile(lat, 0.50) * 1e3, 4),
            "p90": round(_percentile(lat, 0.90) * 1e3, 4),
            "p99": round(_percentile(lat, 0.99) * 1e3, 4),
            "max": round((lat[-1] if lat else 0.0) * 1e3, 4),
        },
        "statuses": {str(k): v for k, v in sorted(statuses.items())},
        "ratio_304": round(n304 / requests, 4) if requests else 0.0,
        "if_none_match_sent": sum(w.sent_inm for w in workers),
        "sealed_requests": sum(w.sealed_requests for w in workers),
        "sealed_304s": sum(w.sealed_304s for w in workers),
    }
    if health is not None:
        after = health.snapshot()["counters"]
        report["gateway_counters"] = {
            k: after.get(k, 0) - before.get(k, 0)
            for k in sorted(set(after) | set(before))
            if k.startswith("gateway_")}
    return report

"""CLI entry: drive a live gateway (or a local update store, in
process) with a simulated light-client population.

    # a million clients against a running `follow --gateway` server
    python -m spectre_tpu.loadgen --url http://127.0.0.1:3000 \
        --clients 1000000

    # in-process against a follower's params dir (no server needed)
    python -m spectre_tpu.loadgen --store-dir /path/to/params

Arm SPECTRE_FAULT_PLAN before the run to make it a chaos drill.
"""

from __future__ import annotations

import argparse
import json
import sys


def main(argv=None):
    p = argparse.ArgumentParser(prog="spectre-tpu-loadgen")
    tgt = p.add_mutually_exclusive_group(required=True)
    tgt.add_argument("--url", help="base URL of a server with the "
                     "gateway mounted (follow --gateway)")
    tgt.add_argument("--store-dir", help="params dir holding a "
                     "follower update store: build a Gateway in-process "
                     "and drill it directly (no HTTP)")
    p.add_argument("--clients", type=int, default=1_000_000,
                   help="simulated client population (default 10^6)")
    p.add_argument("--requests", type=int, default=None,
                   help="total requests (default: 2 per client)")
    p.add_argument("--zipf-s", type=float, default=None,
                   help="Zipf exponent over periods, newest=hottest "
                   "(default 1.1)")
    p.add_argument("--range-count", type=int, default=8,
                   help="max periods per range request")
    p.add_argument("--threads", type=int, default=1)
    p.add_argument("--seed", type=int, default=0)
    args = p.parse_args(argv)

    from .drill import DEFAULT_ZIPF_S, HttpTarget, InProcessTarget, run_drill

    health = None
    if args.url:
        target = HttpTarget(args.url)
        # discover the period span from the bootstrap route
        import urllib.request
        with urllib.request.urlopen(args.url.rstrip("/")
                                    + "/v1/bootstrap") as resp:
            boot = json.loads(resp.read())
        anchor, tip = boot["anchor_period"], boot["tip_period"]
    else:
        from ..follower.updates import UpdateStore
        from ..gateway import Gateway
        from ..utils.health import HEALTH
        store = UpdateStore(args.store_dir)
        anchor, tip = store.anchor_period(), store.tip_period()
        if anchor is None:
            sys.exit("store is empty: nothing to serve")
        target = InProcessTarget(Gateway(store))
        health = HEALTH
    periods = list(range(tip, anchor - 1, -1))   # newest first
    report = run_drill(
        target, periods, tip, clients=args.clients,
        requests=args.requests,
        zipf_s=DEFAULT_ZIPF_S if args.zipf_s is None else args.zipf_s,
        range_count=args.range_count, threads=args.threads,
        seed=args.seed, health=health)
    print(json.dumps(report, indent=2))


if __name__ == "__main__":
    main()

"""Proof scheduler: work items -> JobQueue submissions (ISSUE 10).

Every work item flows through the EXISTING admission-control path
(:meth:`JobQueue.submit`) — so proactive follower proving gets the crash
journal, witness-digest dedup, load shedding, worker supervision and the
verify-before-serve gate for free, and shares one concurrency governor
with request-driven proving.

Scheduling policy:

* committee-update items always submit before step items (a missed
  rotation strands the verified update chain; a missed step only delays
  head freshness — steps backfill);
* a ``ServiceOverloaded`` shed backs the item off by the server's own
  ``retry_after_s`` hint (the -32001 contract) instead of hammering;
* a failed job retries with capped exponential backoff
  (``follower_jobs_failed`` counts);
* committee results land in the store in PERIOD ORDER: a completion
  whose earlier committee periods are still pending holds its finished
  job (``follower_chain_waits``) instead of journaling a record with a
  dangling ``prev_poseidon`` — out-of-order completion can never break
  the verified chain;
* double submission is impossible by construction — an item already
  proved is filtered against the update store, an item already in
  flight keeps its job id, and a resubmission after restart hits the
  queue's witness-digest dedup.

Completion side: a ``done`` job's result is appended to the
:class:`~spectre_tpu.follower.updates.UpdateStore` together with its
job id and provenance-manifest digest (the flight-recorder linkage). A
store write failure (e.g. injected ENOSPC) counts on
``follower_store_write_failures`` and retries next cycle — the job
result is still journaled, nothing is lost.

Aggregation cadence (ISSUE 18): with ``SPECTRE_AGG_CADENCE_PERIODS=N``
(or ``cadence_periods=N``), every N sealed committee periods the
scheduler derives an :class:`~spectre_tpu.follower.tracker.AggregationDue`
window purely from the update store — no beacon involved — and submits
the ``genEvmProof_AggregationCadence`` circuit over the stored chain.
The done proof is published through the configured
:class:`AggregationPublisher` (the EVM-verifiable Spectre contract
surface) BEFORE being journaled as an ``aggregate`` record, so a
publish failure (``follower_publish_failures``) retries next cycle with
the finished job kept, and a restart re-derives exactly the unpublished
windows (``store.has_aggregate`` is the dedup key). Aggregation items
sort after committees and steps: compressing history must never starve
the live chain.
"""

from __future__ import annotations

import os
import time

from ..prover_service.jobs import ServiceOverloaded
from ..utils.health import HEALTH
from ..utils.profiling import phase
from .tracker import AggregationDue, CommitteeUpdateDue
from .updates import ChainOrderError

RETRY_BASE_S = 1.0
RETRY_CAP_S = 60.0

CADENCE_ENV = "SPECTRE_AGG_CADENCE_PERIODS"
CADENCE_DEFAULT = 0                      # 0 = cadence disabled


class PublicationError(RuntimeError):
    """Publishing an aggregation proof to the contract surface failed
    (simulator rejected the calldata, replay refused, transport broke).
    The scheduler keeps the finished job and retries next cycle."""


class AggregationPublisher:
    """Publishes a completed aggregation window through the Spectre
    contract surface (``contracts/spectre.py``) — in tests and drills
    the contract's verifier runs the generated Solidity through
    ``evm.simulator``, so a publish IS an EVM verification."""

    def __init__(self, contract, health=HEALTH):
        self.contract = contract
        self.health = health

    def publish(self, item, result: dict) -> None:
        from ..prover_service.selfverify import decode_result
        try:
            proof, instances = decode_result(result)
            self.contract.publish_aggregate(
                start_period=item.start_period,
                period=item.period,
                committee_poseidon=result.get("committee_poseidon"),
                instances=instances,
                proof=proof,
                calldata=result.get("calldata"),
            )
        except Exception as exc:
            raise PublicationError(
                f"aggregation window [{item.start_period}, {item.period}] "
                f"rejected: {exc}") from exc
        self.health.incr("follower_aggregations_published")


class ProofScheduler:
    def __init__(self, jobs, store, health=HEALTH, clock=time.monotonic,
                 retry_base_s: float = RETRY_BASE_S,
                 retry_cap_s: float = RETRY_CAP_S,
                 cadence_periods: int | None = None,
                 publisher: AggregationPublisher | None = None):
        self.jobs = jobs
        self.store = store
        self.health = health
        self._clock = clock
        self.retry_base_s = retry_base_s
        self.retry_cap_s = retry_cap_s
        if cadence_periods is None:
            try:
                cadence_periods = int(os.environ.get(CADENCE_ENV)
                                      or CADENCE_DEFAULT)
            except ValueError:
                cadence_periods = CADENCE_DEFAULT
        self.cadence_periods = max(0, int(cadence_periods))
        self.publisher = publisher
        # key -> {"item", "jid", "attempts", "not_before"}
        self._pending: dict[tuple, dict] = {}

    @property
    def backlog(self) -> int:
        return len(self._pending)

    def _satisfied(self, item) -> bool:
        if isinstance(item, CommitteeUpdateDue):
            return self.store.has_committee(item.period)
        if isinstance(item, AggregationDue):
            return self.store.has_aggregate(item.period)
        return self.store.has_step(item.slot)

    def offer(self, items) -> int:
        """Adopt new work items (idempotent per key). Returns how many
        were actually new."""
        fresh = 0
        for item in items:
            key = item.key()
            if key in self._pending or self._satisfied(item):
                continue
            self._pending[key] = {"item": item, "jid": None,
                                  "attempts": 0, "not_before": 0.0}
            fresh += 1
        return fresh

    def pump(self) -> dict:
        """One scheduling cycle: submit every eligible item (committee
        items first), then collect finished jobs into the store."""
        summary = {"submitted": 0, "stored": 0, "failed": 0, "shed": 0}
        self._offer_cadence()
        now = self._clock()
        entries = sorted(
            self._pending.items(),
            key=lambda kv: (0 if isinstance(kv[1]["item"],
                                            CommitteeUpdateDue)
                            else 2 if isinstance(kv[1]["item"],
                                                 AggregationDue) else 1,
                            kv[0][1]))
        for key, ent in entries:
            if self._pending.get(key) is not ent:
                continue
            if now < ent["not_before"]:
                continue      # backing off (shed, failure OR store retry)
            if ent["jid"] is None:
                self._submit(ent, summary)
            if ent["jid"] is not None:
                self._collect(key, ent, summary, now)
        return summary

    def _offer_cadence(self):
        """Derive due aggregation windows from the update store: one
        per ``cadence_periods`` sealed committee periods, anchored at
        the chain anchor. A window is due once its end period is sealed
        (strictly below the tip — its successor pins it, so the window
        contents can never change) and no ``aggregate`` record exists
        for it yet; a window with a mid-chain hole (quarantined record)
        is skipped this cycle (``follower_cadence_holes``) and
        re-derived once the chain heals."""
        n = self.cadence_periods
        if n <= 0:
            return
        anchor = self.store.anchor_period()
        tip = self.store.tip_period()
        if anchor is None or tip is None:
            return
        for p in range(anchor + n - 1, tip, n):
            key = ("aggregation", p)
            if key in self._pending or self.store.has_aggregate(p):
                continue
            start = p - n + 1
            chain = []
            for q in range(start, p + 1):
                rec = self.store.get_committee(q)
                if rec is None:
                    break
                res = rec.get("result") or {}
                chain.append({
                    "period": rec["period"],
                    "prev_poseidon": rec.get("prev_poseidon"),
                    "committee_poseidon": res.get("committee_poseidon"),
                    "proof": res.get("proof"),
                    "instances": res.get("instances"),
                    "calldata": res.get("calldata"),
                })
            if len(chain) != n:
                self.health.incr("follower_cadence_holes")
                continue
            item = AggregationDue(p, start, {
                "start_period": start, "period": p, "chain": chain})
            self._pending[key] = {"item": item, "jid": None,
                                  "attempts": 0, "not_before": 0.0}
            self.health.incr("follower_cadence_windows")

    def _submit(self, ent: dict, summary: dict):
        item = ent["item"]
        try:
            with phase("follower/submit"):
                ent["jid"] = self.jobs.submit(item.method,
                                              dict(item.params))
            self.health.incr("follower_jobs_submitted")
            summary["submitted"] += 1
        except ServiceOverloaded as exc:
            # honor the server's own backoff pricing (-32001 contract)
            ent["not_before"] = self._clock() + exc.retry_after_s
            self.health.incr("follower_submits_shed")
            summary["shed"] += 1

    def _chain_blocked(self, item) -> bool:
        """Committee results must land in the store in period order —
        a record links to its predecessor's poseidon commitment, so
        storing period p while an earlier period is still pending would
        journal a dangling ``prev_poseidon=None`` that nothing heals.
        Out-of-order completions (a transient failure on p-1, a
        concurrency>1 queue finishing p first) hold their finished job
        until every earlier committee period has been stored; within
        one pump cycle entries are processed in period order, so the
        successor lands in the same cycle its predecessor does."""
        if not isinstance(item, CommitteeUpdateDue):
            return False
        return any(isinstance(e["item"], CommitteeUpdateDue)
                   and e["item"].period < item.period
                   for e in self._pending.values())

    def _collect(self, key: tuple, ent: dict, summary: dict, now: float):
        st = self.jobs.status(ent["jid"])
        if st is None:
            # queue restarted without this job: resubmit next cycle
            ent["jid"] = None
            return
        if st["status"] in ("queued", "running"):
            return
        if st["status"] == "done":
            if self._chain_blocked(ent["item"]):
                # keep the finished job; re-checked every cycle
                self.health.incr("follower_chain_waits")
                return
            job = self.jobs.result(ent["jid"])
            if job is None or job.result is None:
                self._backoff(ent, now)
                self.health.incr("follower_results_unavailable")
                return
            try:
                with phase("follower/store_update"):
                    self._store(ent["item"], job)
            except ChainOrderError:
                # defense in depth: the predecessor is missing from the
                # store and not pending (e.g. backfill hasn't emitted it
                # yet) — keep the finished job until it lands
                self.health.incr("follower_chain_order_rejected")
                return
            except PublicationError:
                # the contract surface refused or broke: the proof is
                # done and journaled — keep the finished job and retry
                # the publish next cycle
                self.health.incr("follower_publish_failures")
                self._backoff(ent, now, keep_job=True)
                return
            except OSError:
                # diskfull & friends: the job result is still journaled;
                # retry the append next cycle
                self.health.incr("follower_store_write_failures")
                self._backoff(ent, now, keep_job=True)
                return
            del self._pending[key]
            summary["stored"] += 1
            return
        # failed / cancelled: capped exponential backoff, then re-prove
        self._backoff(ent, now)
        self.health.incr("follower_jobs_failed")
        summary["failed"] += 1

    def _backoff(self, ent: dict, now: float, keep_job: bool = False):
        ent["attempts"] += 1
        if not keep_job:
            ent["jid"] = None
        ent["not_before"] = now + min(
            self.retry_cap_s, self.retry_base_s * 2 ** (ent["attempts"] - 1))

    def _store(self, item, job):
        manifest_digest = getattr(job, "manifest_digest", None)
        if isinstance(item, CommitteeUpdateDue):
            self.store.append_committee(item.period, job.result,
                                        job_id=job.id,
                                        manifest_digest=manifest_digest)
        elif isinstance(item, AggregationDue):
            # publish BEFORE journaling: has_aggregate() is the dedup
            # key, so a window must never be marked done while its
            # proof is unpublished — a crash between publish and append
            # merely re-publishes (the contract's replay guard absorbs)
            if self.publisher is not None:
                self.publisher.publish(item, job.result)
            self.store.append_aggregate(item.period, job.result,
                                        start_period=item.start_period,
                                        job_id=job.id,
                                        manifest_digest=manifest_digest)
        else:
            self.store.append_step(item.slot, job.result, job_id=job.id,
                                   manifest_digest=manifest_digest)

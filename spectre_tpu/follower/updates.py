"""Verified update store: the follower's durable output (ISSUE 10).

A content-addressed, journal-backed chain of light-client updates:
``{period -> committee-update proof, slot -> step proof}``. Records ride
the existing :class:`~spectre_tpu.utils.artifacts.ArtifactStore`
(``results/<sha256>.update.json``, atomic tmp+fsync+rename, read-side
re-verification + quarantine) plus an append-only fsync'd JSONL journal
(``follower.updates.jsonl``, the JobJournal idiom) holding one metadata
record per stored update.

Integrity contract:

* a record is appended only AFTER the job queue marked the proof
  ``done`` — and every done proof already passed the verify-before-serve
  gate (prover_service/selfverify.py), so nothing unverified can enter
  the chain;
* each committee record carries its own ``committee_poseidon`` (the
  chain-linking commitment the compressed circuit exposes at
  ``instances[12]``) and ``prev_poseidon`` — the predecessor period's
  commitment — so the stored chain is checkable without re-reading any
  proof bytes (:meth:`verify_chain`);
* crash replay re-verifies the chain TIP: the tip artifact is re-read
  (content-hash checked by the store) and its poseidon cross-checked
  against the journal record; a corrupt tip is quarantined and dropped
  so the follower re-proves it instead of serving rot;
* a record whose artifact fails verification at READ time
  (:meth:`get_committee` / :meth:`get_step`) is dropped the same way —
  the tracker sees the period as missing again and the scheduler
  re-proves it (witness-digest dedup makes that a cheap cache hit when
  the original job is still journaled).

Fault sites: artifact bytes go through ``artifact.write`` /
``artifact.read`` (diskfull, corrupt, ...); the journal append is its
own site ``follower.journal`` so the drills can fill the disk under the
chain record specifically.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import OrderedDict

from ..utils import faults
from ..utils.artifacts import ArtifactCorrupt, ArtifactStore
from ..utils.health import HEALTH

JOURNAL_NAME = "follower.updates.jsonl"
UPDATE_SUFFIX = ".update.json"
JOURNAL_FAULT_SITE = "follower.journal"

# in-RAM record-cache bound (ISSUE 11 satellite): a years-long follower
# accumulates tens of thousands of periods; the full journal records
# stay on disk and only this many stay hot in RAM per map
CACHE_PERIODS_ENV = "SPECTRE_UPDATE_CACHE_PERIODS"
DEFAULT_CACHE_PERIODS = 1024


class _JournalMap:
    """Bounded dict façade over journal-backed records (ISSUE 11).

    The full index (key -> (journal byte offset, artifact digest)) is
    tiny and stays resident — membership, iteration, len, max/min and
    the scrubber keep-set never load a record. Full records live in an
    LRU capped at `cache` entries; a miss seeks the journal to the
    record's offset and re-parses that one line
    (``follower_update_cache_evictions`` / reload failures are counted,
    a reloaded line that no longer parses or no longer matches its key
    is bit rot: the index entry is dropped so the follower re-proves).

    NOT thread-safe on its own — every access happens under the owning
    UpdateStore's lock, exactly like the plain dicts it replaces."""

    def __init__(self, path: str, kind: str, key_field: str,
                 cache: int, health=HEALTH):
        self._path = path
        self._kind = kind
        self._key_field = key_field
        self._cache = max(1, int(cache))
        self._health = health
        self._index: dict[int, tuple] = {}      # key -> (offset, digest)
        self._lru: "OrderedDict[int, dict]" = OrderedDict()

    # -- dict façade (what UpdateStore + tests use) ------------------------

    def __contains__(self, key) -> bool:
        return key in self._index

    def __iter__(self):
        return iter(self._index)

    def __len__(self) -> int:
        return len(self._index)

    def __getitem__(self, key) -> dict:
        rec = self._lru.get(key)
        if rec is not None:
            self._lru.move_to_end(key)
            return rec
        if key not in self._index:
            raise KeyError(key)
        rec = self._reload(key)
        if rec is None:
            # the journal line rotted underneath the index: drop the
            # entry (the tracker re-emits the period, the scheduler
            # re-proves it — same contract as read-time invalidation)
            del self._index[key]
            self._health.incr("follower_journal_reload_failures")
            raise KeyError(key)
        self._insert(key, rec)
        return rec

    def get(self, key, default=None):
        try:
            return self[key]
        except KeyError:
            return default

    def __delitem__(self, key):
        del self._index[key]
        self._lru.pop(key, None)

    def keys(self):
        return self._index.keys()

    # -- journal-backed side ----------------------------------------------

    def put(self, key, rec: dict, offset: int):
        self._index[key] = (offset, rec.get("digest"))
        self._insert(key, rec)

    def digests(self) -> set:
        """Artifact digests of every indexed record — no record loads."""
        return {d for _, d in self._index.values() if d}

    def _insert(self, key, rec: dict):
        self._lru[key] = rec
        self._lru.move_to_end(key)
        while len(self._lru) > self._cache:
            self._lru.popitem(last=False)
            self._health.incr("follower_update_cache_evictions")

    def _reload(self, key) -> dict | None:
        offset, _digest = self._index[key]
        try:
            with open(self._path, "rb") as f:
                f.seek(offset)
                rec = json.loads(f.readline())
        except (OSError, ValueError):
            return None
        try:
            if rec.get("kind") != self._kind \
                    or int(rec[self._key_field]) != key:
                return None
        except (KeyError, TypeError, ValueError):
            return None
        return rec


class ChainOrderError(RuntimeError):
    """Appending this committee record would break the chain: its
    predecessor period is not stored (and it is not the trust anchor),
    so the prev_poseidon link cannot be recorded. The caller must store
    the predecessor first (the scheduler gates collection on this)."""


def _canonical(result: dict) -> bytes:
    return json.dumps(result, sort_keys=True,
                      separators=(",", ":")).encode()


class UpdateStore:
    """Thread-safe; one instance per follower, sharing the params dir
    (and therefore the ``results/`` artifact namespace) with the job
    queue — register :meth:`live_artifacts` with the queue's scrubber
    keep-set so stored updates are never expired as orphans."""

    def __init__(self, directory: str, health=HEALTH,
                 cache_periods: int | None = None):
        os.makedirs(directory, exist_ok=True)
        self.dir = directory
        self.health = health
        self.store = ArtifactStore(directory, health=health)
        self.path = os.path.join(directory, JOURNAL_NAME)
        self._lock = threading.RLock()
        if cache_periods is None:
            cache_periods = int(os.environ.get(CACHE_PERIODS_ENV)
                                or DEFAULT_CACHE_PERIODS)
        # period -> record / slot -> record, bounded (ISSUE 11): the
        # resident index is offsets+digests only, full records LRU-cache
        self._committee = _JournalMap(self.path, "committee", "period",
                                      cache_periods, health=health)
        self._steps = _JournalMap(self.path, "step", "slot",
                                  cache_periods, health=health)
        # period -> aggregation record (ISSUE 18 cadence): keyed by the
        # window's END period, so has_aggregate(boundary) is the
        # scheduler's restart-safe "already published" dedup check
        self._aggregates = _JournalMap(self.path, "aggregate", "period",
                                       cache_periods, health=health)
        # lowest committee period ever journaled — the chain's trust
        # anchor. Survives in-memory invalidations (a dropped record is
        # re-proved, not forgotten) so the tracker can re-derive holes
        # anywhere in [anchor, head], not just above the tip.
        self._anchor: int | None = None
        # append observers (ISSUE 14: the gateway's pack-seal hook);
        # called OUTSIDE the lock after each successful append
        self._observers: list = []
        self._replay()

    # -- journal -----------------------------------------------------------

    def _append(self, record: dict) -> int:
        """Append one record; returns its byte offset in the journal
        (the _JournalMap index key for cache-miss reloads)."""
        faults.check(JOURNAL_FAULT_SITE)
        line = json.dumps(record, sort_keys=True,
                          separators=(",", ":")) + "\n"
        with open(self.path, "a") as f:
            f.seek(0, os.SEEK_END)
            offset = f.tell()
            f.write(line)
            f.flush()
            os.fsync(f.fileno())
        return offset

    def _replay(self):
        """Rebuild the maps from the journal (last record per key wins;
        a torn tail from a crash mid-append is tolerated), then
        re-verify the chain tip before trusting it. Only the LAST line
        may be torn — an unparseable line mid-file is bit rot, not a
        crash footprint, so it is skipped and counted
        (``follower_journal_corrupt_lines``) instead of silently
        discarding every valid record after it."""
        if not os.path.exists(self.path):
            return
        with open(self.path, "rb") as f:
            raw = f.read()
        entries, pos = [], 0
        for chunk in raw.split(b"\n"):
            entries.append((pos, chunk))
            pos += len(chunk) + 1
        if entries and not entries[-1][1].strip():
            entries.pop()       # trailing empty chunk: file ends with \n
        for i, (offset, line) in enumerate(entries):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                if i == len(entries) - 1:
                    break          # torn tail: everything before is good
                self.health.incr("follower_journal_corrupt_lines")
                continue
            if rec.get("kind") == "committee":
                period = int(rec["period"])
                self._committee.put(period, rec, offset)
                if self._anchor is None or period < self._anchor:
                    self._anchor = period
            elif rec.get("kind") == "step":
                self._steps.put(int(rec["slot"]), rec, offset)
            elif rec.get("kind") == "aggregate":
                self._aggregates.put(int(rec["period"]), rec, offset)
        if self._committee or self._steps or self._aggregates:
            self.health.incr("follower_journal_replays")
        self._verify_tip()

    def _verify_tip(self):
        """Crash-replay integrity: re-read the committee chain tip's
        artifact and cross-check its poseidon against the journal
        record; drop (the artifact is already quarantined by the store)
        anything that fails so the follower re-proves it."""
        tip = self.tip_period()
        if tip is None:
            return
        try:
            rec = self._committee[tip]
        except KeyError:        # reload failed: already dropped + counted
            self.health.incr("follower_chain_tip_invalid")
            return
        try:
            result = json.loads(self.store.read(rec["digest"],
                                                UPDATE_SUFFIX))
            ok = result.get("committee_poseidon") == \
                rec.get("committee_poseidon")
        except (ArtifactCorrupt, OSError, ValueError):
            ok = False
        prev = self._committee.get(tip - 1)
        if ok and prev is not None:
            ok = rec.get("prev_poseidon") == prev.get("committee_poseidon")
        if not ok:
            del self._committee[tip]
            self.health.incr("follower_chain_tip_invalid")

    # -- append ------------------------------------------------------------

    def append_committee(self, period: int, result: dict,
                         job_id: str | None = None,
                         manifest_digest: str | None = None) -> dict:
        """Store a done committee-update proof for `period`. The journal
        record links to the predecessor period's poseidon commitment
        (None for the trust anchor — the first record of the chain).
        Raises OSError (e.g. ENOSPC) when the store or journal cannot
        persist it (the caller retries on the next cycle) and
        :class:`ChainOrderError` when the append would record a broken
        link: appends must land in period order, so a record whose
        predecessor is neither stored nor the trust anchor is refused
        instead of being written with ``prev_poseidon=None`` — an
        out-of-order completion must wait for its predecessor."""
        period = int(period)
        with self._lock:
            prev = self._committee.get(period - 1)
            if prev is None and self._committee and period != self._anchor:
                # no predecessor and not the trust anchor being
                # re-proved after invalidation: recording this now would
                # journal a dangling prev_poseidon=None link that a
                # later predecessor append could never heal — the
                # out-of-order completion must wait (the scheduler
                # gates collection on this)
                raise ChainOrderError(
                    f"committee period {period} out of order: period "
                    f"{period - 1} is not stored and {period} is not the "
                    f"chain anchor ({self._anchor})")
            digest = self.store.write(_canonical(result),
                                      suffix=UPDATE_SUFFIX)
            rec = {
                "kind": "committee",
                "period": period,
                "digest": digest,
                "committee_poseidon": result.get("committee_poseidon"),
                "prev_poseidon": (prev or {}).get("committee_poseidon"),
                "job_id": job_id,
                "manifest_digest": manifest_digest,
                "ts": time.time(),
            }
            offset = self._append(rec)
            self._committee.put(period, rec, offset)
            if self._anchor is None or period < self._anchor:
                self._anchor = period
        self.health.incr("follower_updates_stored")
        self._notify("committee", period)
        return rec

    def append_step(self, slot: int, result: dict,
                    job_id: str | None = None,
                    manifest_digest: str | None = None) -> dict:
        slot = int(slot)
        with self._lock:
            digest = self.store.write(_canonical(result),
                                      suffix=UPDATE_SUFFIX)
            rec = {"kind": "step", "slot": slot, "digest": digest,
                   "job_id": job_id, "manifest_digest": manifest_digest,
                   "ts": time.time()}
            offset = self._append(rec)
            self._steps.put(slot, rec, offset)
        self.health.incr("follower_steps_stored")
        self._notify("step", slot)
        return rec

    def append_aggregate(self, period: int, result: dict,
                         start_period: int | None = None,
                         job_id: str | None = None,
                         manifest_digest: str | None = None) -> dict:
        """Store a published aggregation proof for the cadence window
        ending at `period` (ISSUE 18). No chain-order gate: each window
        stands alone (the underlying committee chain already links it),
        so the only invariant is one record per boundary period — the
        scheduler's restart-safe dedup key."""
        period = int(period)
        with self._lock:
            digest = self.store.write(_canonical(result),
                                      suffix=UPDATE_SUFFIX)
            rec = {"kind": "aggregate", "period": period,
                   "start_period": (None if start_period is None
                                    else int(start_period)),
                   "digest": digest,
                   "committee_poseidon": result.get("committee_poseidon"),
                   "job_id": job_id, "manifest_digest": manifest_digest,
                   "ts": time.time()}
            offset = self._append(rec)
            self._aggregates.put(period, rec, offset)
        self.health.incr("follower_aggregates_stored")
        self._notify("aggregate", period)
        return rec

    # -- read (serving path: O(artifact read), no prover involved) ---------

    def _load(self, rec: dict) -> dict | None:
        try:
            result = json.loads(self.store.read(rec["digest"],
                                                UPDATE_SUFFIX))
        except (ArtifactCorrupt, OSError, ValueError):
            return None
        out = {k: rec[k] for k in ("kind", "digest", "job_id",
                                   "manifest_digest") if k in rec}
        if rec["kind"] == "committee":
            out["period"] = rec["period"]
            out["prev_poseidon"] = rec.get("prev_poseidon")
        elif rec["kind"] == "aggregate":
            out["period"] = rec["period"]
            out["start_period"] = rec.get("start_period")
        else:
            out["slot"] = rec["slot"]
        out["result"] = result
        return out

    def get_committee(self, period: int) -> dict | None:
        with self._lock:
            rec = self._committee.get(int(period))
            if rec is None:
                return None
            out = self._load(rec)
            if out is None:
                # quarantined by the store's read-side check: drop the
                # record so the tracker re-emits the period and the
                # scheduler re-proves it
                del self._committee[int(period)]
                self.health.incr("follower_updates_invalidated")
            return out

    def get_step(self, slot: int) -> dict | None:
        with self._lock:
            rec = self._steps.get(int(slot))
            if rec is None:
                return None
            out = self._load(rec)
            if out is None:
                del self._steps[int(slot)]
                self.health.incr("follower_updates_invalidated")
            return out

    def get_aggregate(self, period: int) -> dict | None:
        with self._lock:
            rec = self._aggregates.get(int(period))
            if rec is None:
                return None
            out = self._load(rec)
            if out is None:
                del self._aggregates[int(period)]
                self.health.incr("follower_updates_invalidated")
            return out

    def range_committee(self, start_period: int, count: int):
        """(found records, missing periods) over [start, start+count)."""
        updates, missing = [], []
        for p in range(int(start_period), int(start_period) + int(count)):
            rec = self.get_committee(p)
            if rec is None:
                missing.append(p)
            else:
                updates.append(rec)
        return updates, missing

    # -- observers (ISSUE 14: gateway pack-seal hook) ----------------------

    def add_append_observer(self, fn) -> None:
        """Register ``fn(kind, key)`` to run after every successful
        append (outside the store lock). Idempotent per callable."""
        with self._lock:
            if fn not in self._observers:
                self._observers.append(fn)

    def _notify(self, kind: str, key: int) -> None:
        with self._lock:
            observers = list(self._observers)
        for fn in observers:
            try:
                fn(kind, key)
            except Exception:
                # an observer (pack build, metrics) must never break
                # the proving append path
                self.health.incr("follower_observer_failures")

    # -- chain queries -----------------------------------------------------

    def has_committee(self, period: int) -> bool:
        with self._lock:
            return int(period) in self._committee

    def has_step(self, slot: int) -> bool:
        with self._lock:
            return int(slot) in self._steps

    def has_aggregate(self, period: int) -> bool:
        with self._lock:
            return int(period) in self._aggregates

    def latest_aggregate_period(self) -> int | None:
        with self._lock:
            return max(self._aggregates) if self._aggregates else None

    def tip_period(self) -> int | None:
        with self._lock:
            return max(self._committee) if self._committee else None

    def committee_digest(self, period: int) -> str | None:
        """Metadata-only content digest for a stored committee period —
        the gateway's ETag source. Never touches the artifact, so a
        conditional-request (304) path costs one dict lookup."""
        with self._lock:
            rec = self._committee.get(int(period))
            return None if rec is None else rec.get("digest")

    def is_sealed(self, period: int) -> bool:
        """A period is *sealed* once it is stored AND strictly below the
        chain tip: its successor's prev_poseidon pins it, so the record
        can never change — the gateway serves it as immutable."""
        with self._lock:
            period = int(period)
            if period not in self._committee or not self._committee:
                return False
            return period < max(self._committee)

    def anchor_period(self) -> int | None:
        """The chain's trust anchor: the lowest committee period ever
        journaled. Unlike :meth:`tip_period` this does NOT move when a
        record is invalidated at read time, so the tracker can derive
        missing work over the whole [anchor, head] span — a hole below
        the tip (a quarantined mid-chain record, a crash between
        out-of-order completions) is re-emitted instead of being
        shadowed by the tip."""
        with self._lock:
            if self._anchor is not None:
                return self._anchor
            return min(self._committee) if self._committee else None

    def latest_step_slot(self) -> int | None:
        with self._lock:
            return max(self._steps) if self._steps else None

    def verify_chain(self) -> bool:
        """The stored committee chain is unbroken: contiguous periods,
        each record's prev_poseidon matching its predecessor's
        commitment (metadata-only — artifact bytes are verified by the
        content-addressed store at read time)."""
        with self._lock:
            if not self._committee:
                return True
            periods = sorted(self._committee)
            if periods != list(range(periods[0], periods[-1] + 1)):
                return False
            for p in periods[1:]:
                cur = self._committee.get(p)
                prev = self._committee.get(p - 1)
                if cur is None or prev is None:     # rotted under the index
                    return False
                if cur.get("prev_poseidon") != prev.get("committee_poseidon"):
                    return False
            return True

    def live_artifacts(self) -> set:
        """(digest, suffix) keep-set for the artifact scrubber: stored
        updates must never be expired as journal orphans. Reads the
        resident index only — no record loads, regardless of chain
        length."""
        with self._lock:
            digs = self._committee.digests() | self._steps.digests() \
                | self._aggregates.digests()
        return {(d, UPDATE_SUFFIX) for d in digs}

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "committees": len(self._committee),
                "steps": len(self._steps),
                "aggregates": len(self._aggregates),
                "tip_period": max(self._committee) if self._committee
                else None,
                "latest_step_slot": max(self._steps) if self._steps
                else None,
                "latest_aggregate_period": max(self._aggregates)
                if self._aggregates else None,
            }

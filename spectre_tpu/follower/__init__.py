"""Light-client follower subsystem (ISSUE 10).

The reference Spectre is a coprocessor that *continuously follows* the
Altair light-client protocol rather than proving on request: track the
beacon head, prove a step per attested header, prove a committee update
at every sync-period boundary, keep an unbroken chain of verified
updates ready to serve. This package closes that loop over the existing
service layers:

    tracker.py    beacon polling -> typed StepDue/CommitteeUpdateDue items
    scheduler.py  work items -> JobQueue submissions (admission control,
                  witness-digest dedup, retry/backoff per -32001 hints)
    updates.py    verified update store: content-addressed, journal-backed
                  chain linked by committee poseidon commitments
    daemon.py     the supervised loop + /metrics snapshot registry

Serving rides the prover RPC server (`getLightClientUpdate`,
`getUpdateRange`, `followerStatus`) and a cache hit is one artifact
read — it never touches the device.
"""

from .daemon import Follower, follower_snapshot
from .scheduler import ProofScheduler
from .tracker import CommitteeUpdateDue, HeadTracker, StepDue
from .updates import ChainOrderError, UpdateStore

__all__ = ["Follower", "follower_snapshot", "ProofScheduler",
           "HeadTracker", "StepDue", "CommitteeUpdateDue", "UpdateStore",
           "ChainOrderError"]

"""Head tracker: beacon polling -> typed work items (ISSUE 10).

Polls the retrying/breaker-aware BeaconClient (or any object with the
same ``finality_update()`` / ``committee_updates(period)`` surface — the
tests use a fixture-backed fake) for the latest finality update, detects
sync-committee period boundaries from the spec's epoch math
(``spec.sync_period``), and emits typed work items:

* :class:`CommitteeUpdateDue` — one per period missing from the
  verified update store anywhere between the chain anchor and the
  current period (bounded per poll by ``SPECTRE_FOLLOW_BACKFILL``) —
  holes below the chain tip (e.g. a quarantined mid-chain record) are
  re-emitted, not just the gap above the tip. A missed rotation strands
  the update chain, so these always sort ahead of steps.
* :class:`StepDue` — the newest finalized header not yet covered by a
  stored step proof.

Dedup across restarts is structural: the UpdateStore is the persistent
record of what is already proved, so a restarted tracker re-derives
exactly the missing work; in-flight duplicates are absorbed by the job
queue's witness-digest dedup.
"""

from __future__ import annotations

import dataclasses
import os

from ..prover_service.rpc import (RPC_METHOD_AGG, RPC_METHOD_COMMITTEE,
                                  RPC_METHOD_STEP)
from ..utils.health import HEALTH
from ..utils.profiling import phase

BACKFILL_ENV = "SPECTRE_FOLLOW_BACKFILL"
BACKFILL_DEFAULT = 8


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default


@dataclasses.dataclass(frozen=True)
class StepDue:
    """A finalized header awaiting a step proof."""
    slot: int
    params: dict            # genEvmProof_SyncStepCompressed RPC params

    @property
    def method(self) -> str:
        return RPC_METHOD_STEP

    def key(self):
        return ("step", self.slot)


@dataclasses.dataclass(frozen=True)
class CommitteeUpdateDue:
    """A sync-committee period boundary awaiting a rotation proof."""
    period: int
    params: dict            # genEvmProof_CommitteeUpdateCompressed params

    @property
    def method(self) -> str:
        return RPC_METHOD_COMMITTEE

    def key(self):
        return ("committee", self.period)


@dataclasses.dataclass(frozen=True)
class AggregationDue:
    """A cadence window of sealed committee periods awaiting the
    aggregation/compression proof (ISSUE 18). Emitted by the scheduler
    (not the tracker): the window is derived purely from the update
    store, so no beacon access is involved. `period` is the window END
    (the dedup key via ``store.has_aggregate``); `start_period` opens
    the window; `params` carries the stored chain records the replica
    re-links and re-verifies host-side."""
    period: int
    start_period: int
    params: dict            # genEvmProof_AggregationCadence RPC params

    @property
    def method(self) -> str:
        return RPC_METHOD_AGG

    def key(self):
        return ("aggregation", self.period)


def _unwrap(payload):
    """Beacon REST responses wrap the update in {"data": ...}; fixtures
    may hand the update dict directly."""
    if isinstance(payload, dict) and "data" in payload:
        return payload["data"]
    return payload


class HeadTracker:
    """`pubkeys` supplies the compressed committee pubkeys the step
    witness needs (a static list, or a callable ``period -> list``);
    `domain` is the sync-committee signing domain (0x-hex or bytes).
    Without both, step proving is disabled and the tracker follows the
    committee chain only."""

    def __init__(self, beacon, spec, store, pubkeys=None, domain=None,
                 backfill: int | None = None, health=HEALTH):
        self.beacon = beacon
        self.spec = spec
        self.store = store
        self._pubkeys = pubkeys
        if isinstance(domain, bytes):
            domain = "0x" + domain.hex()
        self._domain = domain
        self.backfill = (backfill if backfill is not None
                         else _env_int(BACKFILL_ENV, BACKFILL_DEFAULT))
        self.health = health
        self.last_finalized_slot: int | None = None
        self._first_seen_period: int | None = None
        self._first_seen_slot: int | None = None

    @property
    def steps_enabled(self) -> bool:
        return self._pubkeys is not None and self._domain is not None

    def _pubkeys_for(self, period: int):
        return self._pubkeys(period) if callable(self._pubkeys) \
            else self._pubkeys

    # -- lag gauges --------------------------------------------------------

    @property
    def head_lag_slots(self) -> int:
        """Slots between the newest finalized header seen and the newest
        step proof stored (the empty store counts from the first slot
        this tracker ever observed — it is not behind on history that
        predates its trust anchor)."""
        if self.last_finalized_slot is None:
            return 0
        latest = self.store.latest_step_slot()
        if latest is None:
            latest = self._first_seen_slot or self.last_finalized_slot
        return max(0, self.last_finalized_slot - latest)

    @property
    def periods_behind(self) -> int:
        """Periods between the current period and the verified chain
        tip (an empty store anchors at the first period observed)."""
        if self.last_finalized_slot is None:
            return 0
        current = self.spec.sync_period(self.last_finalized_slot)
        tip = self.store.tip_period()
        if tip is None:
            tip = (self._first_seen_period or current) - 1
        return max(0, current - tip)

    # -- polling -----------------------------------------------------------

    def poll(self) -> list:
        """One beacon poll -> the currently-missing work items
        (committee updates first). Beacon errors propagate — the daemon
        counts them and degrades to draining in-flight work."""
        with phase("follower/poll"):
            update = _unwrap(self.beacon.finality_update())
            fin_slot = int(update["finalized_header"]["slot"])
            self.last_finalized_slot = fin_slot
            period = self.spec.sync_period(fin_slot)
            if self._first_seen_period is None:
                self._first_seen_period = period
                self._first_seen_slot = fin_slot
            self.health.incr("follower_polls")

            items: list = []
            # scan from the chain ANCHOR, not the tip: a hole below the
            # tip (a quarantined mid-chain record, a crash that left
            # later periods stored) must be re-emitted — starting at
            # tip+1 would shadow it forever while verify_chain() stays
            # false with nothing re-proving the gap
            anchor = self.store.anchor_period()
            start = (self._first_seen_period if anchor is None
                     else min(anchor, self._first_seen_period))
            missing = [p for p in range(start, period + 1)
                       if not self.store.has_committee(p)]
            for p in missing[:self.backfill]:
                committee_update = self._fetch_committee_update(p)
                if committee_update is not None:
                    items.append(CommitteeUpdateDue(
                        p, {"light_client_update": committee_update}))
            if len(missing) > self.backfill:
                self.health.incr("follower_backfill_deferred")

            if self.steps_enabled and not self.store.has_step(fin_slot):
                items.append(StepDue(fin_slot, {
                    "light_client_finality_update": update,
                    "pubkeys": self._pubkeys_for(period),
                    "domain": self._domain,
                }))
            return items

    def _fetch_committee_update(self, period: int):
        updates = self.beacon.committee_updates(period)
        if not updates:
            return None
        return _unwrap(updates[0])

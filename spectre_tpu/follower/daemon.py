"""Supervised follower daemon: tracker + scheduler + store (ISSUE 10).

One :class:`Follower` closes the loop from beacon RPC to served
light-client updates:

    beacon poll -> work items -> JobQueue -> verified proofs -> UpdateStore

``run_once()`` is one cycle; ``run(stop_event)`` is the supervised loop
(``SPECTRE_FOLLOW_POLL_S``, exceptions counted, never fatal — the
scrubber/worker-supervisor discipline). A beacon outage degrades the
follower to BACKFILL mode: polls fail (``follower_beacon_errors``
counts, ``degraded`` flips), but the scheduler keeps pumping —
in-flight proofs finish and land in the store, and the backlog drains.
When the beacon recovers, fresh polls re-derive the missed work and
``spectre_follower_head_lag_slots`` returns to 0.

Followers register in a process-level weak registry so the Prometheus
exporter can pull the lag gauges (`spectre_follower_head_lag_slots`,
`spectre_follower_periods_behind`, `spectre_follower_scheduler_backlog`)
without holding them alive — the beacon-client breaker-snapshot pattern.
"""

from __future__ import annotations

import os
import threading
import time
import weakref

from ..utils.health import HEALTH
from .scheduler import ProofScheduler
from .tracker import HeadTracker
from .updates import UpdateStore

POLL_ENV = "SPECTRE_FOLLOW_POLL_S"
POLL_DEFAULT_S = 12.0

_FOLLOWERS: "weakref.WeakSet[Follower]" = weakref.WeakSet()


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


def follower_snapshot() -> list[dict]:
    """Snapshots of every live follower (the /metrics pull source)."""
    return [f.snapshot() for f in list(_FOLLOWERS)]


class Follower:
    """`jobs` is the (already constructed) JobQueue the proofs flow
    through; `store` the UpdateStore (built here from `directory` when
    not passed). The store's live-artifact set is registered with the
    queue so the scrubber never expires a stored update as an orphan."""

    def __init__(self, spec, beacon, jobs, store: UpdateStore | None = None,
                 directory: str | None = None, pubkeys=None, domain=None,
                 backfill: int | None = None, health=HEALTH,
                 clock=time.monotonic, cadence_periods: int | None = None,
                 publisher=None):
        if store is None:
            if directory is None:
                raise ValueError("Follower needs a store or a directory")
            store = UpdateStore(directory, health=health)
        self.spec = spec
        self.jobs = jobs
        self.store = store
        self.health = health
        self.tracker = HeadTracker(beacon, spec, store, pubkeys=pubkeys,
                                   domain=domain, backfill=backfill,
                                   health=health)
        self.scheduler = ProofScheduler(jobs, store, health=health,
                                        clock=clock,
                                        cadence_periods=cadence_periods,
                                        publisher=publisher)
        self.degraded = False
        self.cycles = 0
        add = getattr(jobs, "add_live_provider", None)
        if add is not None:
            add(store.live_artifacts)
        _FOLLOWERS.add(self)

    # -- one cycle ---------------------------------------------------------

    def run_once(self) -> dict:
        """Poll -> offer -> pump. Beacon failures (outage, open breaker)
        degrade to backfill: the pump still runs so in-flight proofs
        land and retries/backoffs advance."""
        items = []
        try:
            items = self.tracker.poll()
            self.degraded = False
        except Exception:
            self.health.incr("follower_beacon_errors")
            self.degraded = True
        self.scheduler.offer(items)
        summary = self.scheduler.pump()
        self.cycles += 1
        return summary

    # -- supervised loop ---------------------------------------------------

    def run(self, stop_event: threading.Event,
            poll_s: float | None = None):
        """Blocking follower loop; a cycle that blows up is counted
        (``follower_cycle_errors``) and never fatal."""
        if poll_s is None:
            poll_s = _env_float(POLL_ENV, POLL_DEFAULT_S)
        while True:
            try:
                self.run_once()
            except Exception:
                self.health.incr("follower_cycle_errors")
            if stop_event.wait(poll_s):
                return

    def start(self, stop_event: threading.Event,
              poll_s: float | None = None) -> threading.Thread:
        t = threading.Thread(target=self.run, args=(stop_event, poll_s),
                             daemon=True, name="spectre-follower")
        t.start()
        return t

    # -- introspection -----------------------------------------------------

    def snapshot(self) -> dict:
        snap = self.store.snapshot()
        snap.update({
            "store": os.path.basename(os.path.abspath(self.store.dir)),
            "head_lag_slots": self.tracker.head_lag_slots,
            "periods_behind": self.tracker.periods_behind,
            "scheduler_backlog": self.scheduler.backlog,
            "last_finalized_slot": self.tracker.last_finalized_slot,
            "chain_ok": self.store.verify_chain(),
            "degraded": self.degraded,
            "cycles": self.cycles,
            "agg_cadence_periods": self.scheduler.cadence_periods,
        })
        return snap

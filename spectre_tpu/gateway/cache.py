"""Byte-budgeted in-process hot cache for the serving gateway (ISSUE 14).

The gateway's working set is small and immutable — sealed update packs
and pre-encoded sealed responses are content-addressed, so a cached
entry can never go stale; the only cache policy needed is a byte budget
(``SPECTRE_GATEWAY_CACHE_MB``) with LRU eviction. Evictions are counted
(``gateway_cache_evictions``) because every eviction of a sealed entry
is a future ``gateway_store_fallbacks`` — the two counters together
tell the operator whether the budget fits the hot set.

Same discipline as the MSM/NTT ``_TableLRU`` caches: explicit sizes
(the caller states the entry's byte cost — values may be tuples holding
parsed indexes whose ``sys.getsizeof`` would lie), thread-safe,
oversize entries pass through uncached instead of thrashing the budget.
"""

from __future__ import annotations

import os
import threading
from collections import OrderedDict

from ..utils.health import HEALTH

CACHE_MB_ENV = "SPECTRE_GATEWAY_CACHE_MB"
DEFAULT_CACHE_MB = 64.0


def _budget_bytes(cache_mb: float | None) -> int:
    if cache_mb is None:
        cache_mb = float(os.environ.get(CACHE_MB_ENV) or DEFAULT_CACHE_MB)
    return max(0, int(cache_mb * (1 << 20)))


class GatewayCache:
    """LRU keyed by arbitrary hashable keys, bounded by a byte budget.

    ``put`` takes the entry's byte cost explicitly; an entry larger than
    the whole budget is refused (the caller serves it uncached) rather
    than evicting the entire hot set for one oversized pack."""

    def __init__(self, cache_mb: float | None = None, health=HEALTH):
        self.budget = _budget_bytes(cache_mb)
        self.health = health
        self._lock = threading.Lock()
        self._entries: "OrderedDict[object, tuple]" = OrderedDict()
        self._bytes = 0
        self._hits = 0
        self._misses = 0

    def get(self, key):
        with self._lock:
            ent = self._entries.get(key)
            if ent is None:
                self._misses += 1
                return None
            self._entries.move_to_end(key)
            self._hits += 1
            return ent[0]

    def put(self, key, value, nbytes: int) -> bool:
        """Insert (or refresh) `key`; returns False when the entry is
        larger than the whole budget and was not cached."""
        nbytes = int(nbytes)
        if nbytes > self.budget:
            return False
        with self._lock:
            old = self._entries.pop(key, None)
            if old is not None:
                self._bytes -= old[1]
            self._entries[key] = (value, nbytes)
            self._bytes += nbytes
            while self._bytes > self.budget and self._entries:
                _, (_, evicted) = self._entries.popitem(last=False)
                self._bytes -= evicted
                self.health.incr("gateway_cache_evictions")
        return True

    def invalidate(self, key) -> None:
        with self._lock:
            ent = self._entries.pop(key, None)
            if ent is not None:
                self._bytes -= ent[1]

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self._bytes = 0

    def stats(self) -> dict:
        with self._lock:
            return {"entries": len(self._entries), "bytes": self._bytes,
                    "budget_bytes": self.budget, "hits": self._hits,
                    "misses": self._misses}

"""Cacheable HTTP read plane for light-client updates (ISSUE 14 tentpole).

The paper's production story is one aggregated proof amortized over
millions of light clients; the scarce resource is the *prove* path, so
the *read* path must be engineered to never touch it. Stored updates
are content-addressed and immutable once their period is sealed —
exactly the workload HTTP caching was built for. This module serves

* ``GET /v1/update/<period>``  — one committee update,
* ``GET /v1/updates?start=..&count=..`` — a contiguous range,
* ``GET /v1/bootstrap`` — trust anchor + tip for a cold client,

with real HTTP cache semantics so ANY stock CDN, reverse proxy or
browser cache can absorb the fan-out:

* ``ETag`` = the update's content digest (the artifact sha256 the
  journal already records) — stable across restarts by construction;
* ``If-None-Match`` -> ``304 Not Modified`` with no body assembly
  beyond a metadata lookup (no artifact read, no pack slice);
* ``Cache-Control: public, immutable, max-age=31536000`` for *sealed*
  periods (finalized, strictly below the chain tip — they can never
  change) vs ``public, max-age=<SPECTRE_GATEWAY_HEAD_TTL_S>`` for the
  head period and anything derived from the tip.

Behind the headers, sealed bodies come from pre-built update-range
packs (gateway/packs.py) held in a byte-budgeted hot cache
(gateway/cache.py, ``SPECTRE_GATEWAY_CACHE_MB``): a range response is a
pack-slice concatenation, not K ``UpdateStore`` reads + K JSON encodes.
A sealed request that has to fall back to the update store (pack build
failed, hole being re-proved) is counted on
``gateway_store_fallbacks`` — the acceptance drill pins that counter to
ZERO for sealed traffic. All ``gateway_*`` counters ride
``HEALTH.snapshot()`` into ``/healthz`` and ``/metrics`` with zero
exporter changes.

Framework-free on purpose: :meth:`Gateway.handle` returns ``(status,
headers, body)`` tuples, so ``prover_service/rpc.py`` mounts it on the
existing ``ThreadingHTTPServer``, the load generator drives it
in-process with zero HTTP overhead, and tests assert on exact bytes.
"""

from __future__ import annotations

import hashlib
import json
import os
import time
import weakref
from urllib.parse import parse_qs, urlsplit

from ..observability.metrics import REGISTRY
from ..utils.health import HEALTH
from .cache import GatewayCache
from .packs import PackBuilder, canonical_update_body

HEAD_TTL_ENV = "SPECTRE_GATEWAY_HEAD_TTL_S"
DEFAULT_HEAD_TTL_S = 12
SEALED_MAX_AGE = 31536000          # one year: "immutable" has no expiry
RANGE_COUNT_CAP = 128              # parity with getUpdateRange

# read-plane latency: sub-millisecond cache/pack hits up through the
# store-fallback and cold-pack-load tail (grafana: "Gateway" row p99)
REQUEST_LATENCY = REGISTRY.histogram(
    "spectre_gateway_request_seconds",
    "Gateway read-plane latency per handled /v1 request (seconds)",
    (0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025,
     0.05, 0.1, 0.25, 1.0))

CONTENT_TYPE = "application/json"

# live gateways for prom gauges (follower_snapshot pattern)
_GATEWAYS: "weakref.WeakSet" = weakref.WeakSet()


def gateway_snapshot() -> list[dict]:
    return [g.snapshot() for g in list(_GATEWAYS)]


def _quote(etag: str) -> str:
    return f'"{etag}"'


def _etag_matches(if_none_match: str | None, etag: str) -> bool:
    if not if_none_match:
        return False
    if if_none_match.strip() == "*":
        return True
    quoted = _quote(etag)
    for candidate in if_none_match.split(","):
        candidate = candidate.strip()
        if candidate.startswith("W/"):
            candidate = candidate[2:]
        if candidate == quoted or candidate == etag:
            return True
    return False


class Gateway:
    """One gateway per served :class:`UpdateStore`."""

    def __init__(self, store, pack_periods: int | None = None,
                 cache_mb: float | None = None,
                 head_ttl_s: float | None = None, health=HEALTH):
        self.store = store
        self.health = health
        if head_ttl_s is None:
            head_ttl_s = float(os.environ.get(HEAD_TTL_ENV)
                               or DEFAULT_HEAD_TTL_S)
        self.head_ttl_s = max(0, int(head_ttl_s))
        self.cache = GatewayCache(cache_mb, health=health)
        self.packs = PackBuilder(store, pack_periods, health=health)
        # pack-seal hook: every committee append re-checks sealing, so
        # packs exist BEFORE the first client asks for the range
        store.add_append_observer(self._on_append)
        self.packs.ensure_packs()      # journal-replay recovery build
        _GATEWAYS.add(self)

    def _on_append(self, kind: str, key: int) -> None:
        if kind == "committee":
            self.packs.ensure_packs()

    def live_artifacts(self) -> set:
        """Forward the pack keep-set (register with the job queue's
        scrubber alongside the store's own provider)."""
        return self.packs.live_artifacts()

    # -- body assembly -----------------------------------------------------

    def _pack_loaded(self, meta: dict):
        key = ("pack", meta["digest"])
        loaded = self.cache.get(key)
        if loaded is not None:
            return loaded
        loaded = self.packs.read_pack(meta)
        if loaded is not None:
            self.cache.put(key, loaded, len(loaded[1]))
        return loaded

    def _sealed_body(self, period: int):
        """(etag, bytes) for a sealed period — pack slice (hot path) or
        counted store fallback. None when the period is missing."""
        meta = self.packs.pack_for(period)
        if meta is None:
            # maybe the pack was never built (write fault): retry now
            self.packs.ensure_packs()
            meta = self.packs.pack_for(period)
        loaded = self._pack_loaded(meta) if meta is not None else None
        if loaded is None and meta is not None:
            # read_pack dropped + rebuilt a corrupt pack: one more try
            meta = self.packs.pack_for(period)
            loaded = self._pack_loaded(meta) if meta is not None else None
        if loaded is not None:
            slices, raw = loaded
            ent = slices.get(period)
            if ent is not None:
                etag, off, length = ent
                self.health.incr("gateway_pack_hits")
                return etag, raw[off:off + length]
        rec = self.store.get_committee(period)
        if rec is None:
            return None
        self.health.incr("gateway_store_fallbacks")
        return rec["digest"], canonical_update_body(rec)

    def _head_body(self, period: int):
        """The head (tip) period: a plain store read — it is the one
        period that may still change, so it is never packed and never a
        'fallback'."""
        rec = self.store.get_committee(period)
        if rec is None:
            return None
        return rec["digest"], canonical_update_body(rec)

    def _body_for(self, period: int, tip: int):
        if period < tip:
            return self._sealed_body(period), True
        return self._head_body(period), False

    # -- responses ---------------------------------------------------------

    def _cache_control(self, sealed: bool) -> str:
        if sealed:
            return f"public, immutable, max-age={SEALED_MAX_AGE}"
        return f"public, max-age={self.head_ttl_s}"

    def _not_found(self, message: str):
        body = json.dumps({"error": message}, sort_keys=True,
                          separators=(",", ":")).encode()
        return 404, {"Cache-Control": "no-store",
                     "Content-Type": CONTENT_TYPE}, body

    def _reply(self, etag: str, sealed: bool, if_none_match: str | None,
               body_fn):
        headers = {"ETag": _quote(etag),
                   "Cache-Control": self._cache_control(sealed),
                   "Content-Type": CONTENT_TYPE}
        if _etag_matches(if_none_match, etag):
            self.health.incr("gateway_304s")
            return 304, headers, b""
        body = body_fn()
        if body is None:
            return self._not_found("update invalidated; re-proving")
        return 200, headers, body

    def update(self, period: int, if_none_match: str | None = None):
        """GET /v1/update/<period>"""
        self.health.incr("gateway_requests")
        period = int(period)
        tip = self.store.tip_period()
        if tip is None or not self.store.has_committee(period):
            return self._not_found(
                f"no verified update for period {period} (not yet "
                f"proved, or invalidated and re-proving)")
        # metadata-only ETag: a 304 never reads an artifact or a pack
        etag = self.store.committee_digest(period)
        if etag is None:
            return self._not_found(
                f"no verified update for period {period}")
        sealed = period < tip

        def body():
            got, _ = self._body_for(period, tip)
            return None if got is None else got[1]

        return self._reply(etag, sealed, if_none_match, body)

    def updates(self, start: int, count: int = 1,
                if_none_match: str | None = None):
        """GET /v1/updates?start=..&count=.. — canonical JSON
        ``{"missing": [...], "updates": [...]}`` assembled from pack
        slices (byte-identical to encoding direct store reads)."""
        self.health.incr("gateway_requests")
        start, count = int(start), min(int(count), RANGE_COUNT_CAP)
        if count < 1:
            return self._not_found("count must be >= 1")
        tip = self.store.tip_period()
        if tip is None:
            return self._not_found("no verified updates stored yet")
        found, missing = [], []
        for p in range(start, start + count):
            digest = self.store.committee_digest(p)
            if digest is None:
                missing.append(p)
            else:
                found.append((p, digest))
        # range ETag: derived from member content digests + the missing
        # set — stable across restarts, changes exactly when content does
        etag = hashlib.sha256(
            ("|".join(f"{p}:{d}" for p, d in found)
             + "//" + ",".join(map(str, missing))).encode()).hexdigest()
        sealed = not missing and bool(found) \
            and max(p for p, _ in found) < tip

        def body():
            parts = []
            for p, _ in found:
                got, _sealed = self._body_for(p, tip)
                if got is None:
                    return None      # invalidated mid-assembly: rare race
                parts.append(got[1])
            return (b'{"missing":' + json.dumps(missing).encode()
                    + b',"updates":[' + b",".join(parts) + b"]}")

        return self._reply(etag, sealed, if_none_match, body)

    def bootstrap(self, if_none_match: str | None = None):
        """GET /v1/bootstrap — the trust anchor update + tip pointer a
        cold client needs before walking ranges. Tip-derived, so head
        (short-TTL) cache semantics even though the anchor is sealed."""
        self.health.incr("gateway_requests")
        anchor = self.store.anchor_period()
        tip = self.store.tip_period()
        if anchor is None or tip is None \
                or not self.store.has_committee(anchor):
            return self._not_found("no verified chain anchor stored yet")
        anchor_digest = self.store.committee_digest(anchor)
        if anchor_digest is None:
            return self._not_found("no verified chain anchor stored yet")
        etag = hashlib.sha256(
            f"{anchor}|{tip}|{anchor_digest}".encode()).hexdigest()

        def body():
            got, _sealed = self._body_for(anchor, tip)
            if got is None:
                return None
            return (b'{"anchor_period":' + str(anchor).encode()
                    + b',"tip_period":' + str(tip).encode()
                    + b',"update":' + got[1] + b"}")

        return self._reply(etag, False, if_none_match, body)

    # -- HTTP plumbing -----------------------------------------------------

    def handle_http(self, raw_path: str, headers=None):
        """Route one GET. `headers` is any mapping with .get (the
        BaseHTTPRequestHandler headers object qualifies). Returns
        (status, headers dict, body bytes); unknown /v1 paths are 404."""
        t0 = time.perf_counter()
        try:
            return self._route(raw_path, headers)
        finally:
            REQUEST_LATENCY.observe(time.perf_counter() - t0)

    def _route(self, raw_path: str, headers=None):
        parts = urlsplit(raw_path)
        inm = headers.get("If-None-Match") if headers is not None else None
        path = parts.path.rstrip("/")
        try:
            if path.startswith("/v1/update/"):
                return self.update(int(path.rsplit("/", 1)[1]),
                                   if_none_match=inm)
            if path == "/v1/updates":
                q = parse_qs(parts.query)
                return self.updates(int(q["start"][0]),
                                    int(q.get("count", ["1"])[0]),
                                    if_none_match=inm)
            if path == "/v1/bootstrap":
                return self.bootstrap(if_none_match=inm)
        except (KeyError, ValueError, IndexError):
            body = json.dumps({"error": "bad request"}).encode()
            return 400, {"Cache-Control": "no-store",
                         "Content-Type": CONTENT_TYPE}, body
        return self._not_found(f"unknown path {path}")

    def snapshot(self) -> dict:
        snap = {"store": getattr(self.store, "dir", ""),
                "head_ttl_s": self.head_ttl_s,
                "cache": self.cache.stats()}
        snap.update(self.packs.snapshot())
        return snap

"""Light-client serving gateway: cacheable HTTP read plane in front of
the follower's UpdateStore (content-addressed edge cache + update-range
packs). See serving.py for the route/semantics contract."""

from .cache import CACHE_MB_ENV, DEFAULT_CACHE_MB, GatewayCache
from .packs import (DEFAULT_PACK_PERIODS, PACK_FAULT_SITE, PACK_MAGIC,
                    PACK_PERIODS_ENV, PACK_SUFFIX, PACKS_JOURNAL_NAME,
                    PackBuilder, canonical_update_body, decode_pack,
                    encode_pack)
from .serving import (DEFAULT_HEAD_TTL_S, HEAD_TTL_ENV, SEALED_MAX_AGE,
                      Gateway, gateway_snapshot)

__all__ = [
    "CACHE_MB_ENV", "DEFAULT_CACHE_MB", "GatewayCache",
    "DEFAULT_PACK_PERIODS", "PACK_FAULT_SITE", "PACK_MAGIC",
    "PACK_PERIODS_ENV", "PACK_SUFFIX", "PACKS_JOURNAL_NAME",
    "PackBuilder", "canonical_update_body", "decode_pack", "encode_pack",
    "DEFAULT_HEAD_TTL_S", "HEAD_TTL_ENV", "SEALED_MAX_AGE",
    "Gateway", "gateway_snapshot",
]

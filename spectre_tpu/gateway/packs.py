"""Content-addressed update-range packs (ISSUE 14, tentpole part 1).

Once a sync-committee period is *sealed* — finalized and strictly below
the chain tip — its light-client update is immutable: the proof bytes
are content-addressed and the chain link to its predecessor can never
change. The pack builder exploits that by pre-encoding every sealed
period's wire response ONCE into a pack artifact, so serving a billion
``getUpdateRange``-shaped reads is a pack-slice copy instead of K
journal-backed ``UpdateStore`` reads + K JSON encodes per request.

Pack layout (length-prefixed canonical encoding + digest index)::

    MAGIC "SPKPACK1" | u32 index_len | index JSON | body

    index = {"start": s, "count": n, "tail": bool,
             "entries": [{"period": p, "etag": <artifact sha256>,
                          "offset": o, "length": l}, ...]}

``offset`` is relative to the body; each body slice is the *exact*
canonical response body the gateway serves for ``/v1/update/<period>``
(pinned byte-identical to a direct ``UpdateStore`` read in tests), so a
range response is assembled by slice concatenation.

Durability: packs ride :class:`~spectre_tpu.utils.artifacts.ArtifactStore`
(atomic write, read-side re-hash + quarantine) under the shared
``results/`` namespace with suffix ``.pack.bin``; the ``start ->
digest`` mapping is an append-only fsync'd JSONL
(``gateway.packs.jsonl``, last record per start wins) and is REBUILT
from the update store on journal replay — a lost or corrupt pack is a
rebuild, never data loss, because the updates themselves remain in the
verified chain. :meth:`live_artifacts` feeds the job-queue scrubber's
keep-set so compaction/orphan-expiry never reap a referenced pack.

Two pack classes:

* **full packs** — every ``SPECTRE_PACK_PERIODS`` consecutive periods
  from the chain anchor, built once when the whole range seals, then
  immutable forever;
* **one tail pack** — the sealed remainder between the last full range
  and the tip, rebuilt as the tip advances so EVERY sealed period is
  always pack-covered (the acceptance drill's "zero store fallbacks for
  sealed traffic" depends on this). A superseded tail pack drops out of
  the live set and is expired by the scrubber like any orphan.

Fault site ``gateway.pack_write`` covers the pack artifact write; a
failed build is counted (``gateway_pack_build_failures``) and retried
on the next seal event — serving degrades to the update store, it never
breaks.
"""

from __future__ import annotations

import json
import os
import struct
import threading

from ..utils import faults
from ..utils.artifacts import ArtifactCorrupt
from ..utils.health import HEALTH

PACK_MAGIC = b"SPKPACK1"
PACK_SUFFIX = ".pack.bin"
PACKS_JOURNAL_NAME = "gateway.packs.jsonl"
PACK_FAULT_SITE = "gateway.pack_write"

PACK_PERIODS_ENV = "SPECTRE_PACK_PERIODS"
DEFAULT_PACK_PERIODS = 8


def canonical_update_body(rec: dict) -> bytes:
    """THE wire encoding of one stored update record: canonical JSON
    (sorted keys, no whitespace). Pack slices and direct store reads
    both serve exactly these bytes — byte-identity is pinned in
    tests/test_gateway.py."""
    return json.dumps(rec, sort_keys=True, separators=(",", ":")).encode()


def encode_pack(start: int, entries: list[tuple[int, str, bytes]],
                tail: bool) -> bytes:
    """`entries` is [(period, etag, body_bytes), ...] in period order."""
    body = b"".join(b for _, _, b in entries)
    index_entries, offset = [], 0
    for period, etag, data in entries:
        index_entries.append({"period": period, "etag": etag,
                              "offset": offset, "length": len(data)})
        offset += len(data)
    index = json.dumps({"start": start, "count": len(entries),
                        "tail": bool(tail), "entries": index_entries},
                       sort_keys=True, separators=(",", ":")).encode()
    return PACK_MAGIC + struct.pack(">I", len(index)) + index + body


def decode_pack(data: bytes) -> tuple[dict, int]:
    """Returns (index dict, body base offset). Raises ValueError on a
    malformed pack (the caller treats it like corruption: drop+rebuild)."""
    if data[:len(PACK_MAGIC)] != PACK_MAGIC:
        raise ValueError("bad pack magic")
    hdr = len(PACK_MAGIC)
    (index_len,) = struct.unpack(">I", data[hdr:hdr + 4])
    index = json.loads(data[hdr + 4:hdr + 4 + index_len])
    return index, hdr + 4 + index_len


class PackBuilder:
    """Seals ranges of the given :class:`UpdateStore` into pack
    artifacts. Thread-safe; one instance per gateway."""

    def __init__(self, store, pack_periods: int | None = None,
                 health=HEALTH):
        if pack_periods is None:
            pack_periods = int(os.environ.get(PACK_PERIODS_ENV)
                               or DEFAULT_PACK_PERIODS)
        self.store = store                  # UpdateStore
        self.artifacts = store.store        # shared ArtifactStore
        self.pack_periods = max(1, int(pack_periods))
        self.health = health
        self._lock = threading.RLock()
        # start -> {"start", "count", "digest", "tail"}
        self._packs: dict[int, dict] = {}
        self._journal_path = os.path.join(store.dir, PACKS_JOURNAL_NAME)
        self._replay()

    # -- journal -----------------------------------------------------------

    def _replay(self):
        """Last record per start wins; a mapping whose artifact no
        longer exists on disk is dropped (ensure_packs rebuilds it from
        the update store — the journal is an index, not the source of
        truth). Torn tails parse-fail and are skipped, JobJournal-style."""
        try:
            with open(self._journal_path, "rb") as f:
                raw = f.read()
        except OSError:
            return
        for line in raw.split(b"\n"):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue                    # torn tail
            try:
                start = int(rec["start"])
            except (KeyError, TypeError, ValueError):
                continue
            self._packs[start] = {"start": start,
                                  "count": int(rec.get("count", 0)),
                                  "digest": rec.get("digest"),
                                  "tail": bool(rec.get("tail"))}
        for start in list(self._packs):
            meta = self._packs[start]
            if not meta["digest"] or not self.artifacts.exists(
                    meta["digest"], PACK_SUFFIX):
                del self._packs[start]
                self.health.incr("gateway_pack_replay_dropped")

    def _journal_append(self, rec: dict):
        """Best-effort fsync'd append: pack writes are content-addressed
        and idempotent, so a lost index record costs one rebuild, never
        correctness."""
        try:
            with open(self._journal_path, "a", encoding="utf-8") as f:
                f.write(json.dumps(rec, sort_keys=True,
                                   separators=(",", ":")) + "\n")
                f.flush()
                os.fsync(f.fileno())
        except OSError:
            self.health.incr("gateway_pack_journal_failures")

    # -- sealing -----------------------------------------------------------

    def _alignment(self) -> int | None:
        return self.store.anchor_period()

    def range_start(self, period: int) -> int | None:
        """The aligned full-range start covering `period` (anchor-based:
        the first pack starts exactly at the chain's trust anchor)."""
        anchor = self._alignment()
        if anchor is None or period < anchor:
            return None
        n = self.pack_periods
        return anchor + ((period - anchor) // n) * n

    def ensure_packs(self) -> int:
        """Build every missing sealed pack (full ranges + the tail);
        returns how many packs were built. Called from the store's
        append hook and once at gateway construction (journal replay
        recovery). Build failures are counted and retried on the next
        call — never raised into the appending follower."""
        anchor = self._alignment()
        tip = self.store.tip_period()
        if anchor is None or tip is None:
            return 0
        built = 0
        n = self.pack_periods
        with self._lock:
            start = anchor
            while start + n <= tip:         # full ranges: all members sealed
                meta = self._packs.get(start)
                if meta is None or meta["tail"]:
                    if self._build(start, n, tail=False):
                        built += 1
                start += n
            # the sealed remainder [start, tip): rebuilt as the tip moves
            count = tip - start
            if count > 0:
                meta = self._packs.get(start)
                if meta is None or meta["count"] != count:
                    if self._build(start, count, tail=True):
                        built += 1
        return built

    def _build(self, start: int, count: int, tail: bool) -> bool:
        entries = []
        for period in range(start, start + count):
            rec = self.store.get_committee(period)
            if rec is None:
                # a hole (invalidated mid-chain record being re-proved):
                # this range can't seal yet — retry on a later append
                return False
            entries.append((period, rec["digest"],
                            canonical_update_body(rec)))
        data = encode_pack(start, entries, tail)
        try:
            digest = self.artifacts.write(data, suffix=PACK_SUFFIX,
                                          fault_site=PACK_FAULT_SITE)
        except faults.InjectedCrash:
            raise
        except Exception:
            self.health.incr("gateway_pack_build_failures")
            return False
        self._packs[start] = {"start": start, "count": count,
                              "digest": digest, "tail": tail}
        self._journal_append({"start": start, "count": count,
                              "digest": digest, "tail": tail})
        self.health.incr("gateway_packs_built")
        return True

    # -- lookup / read -----------------------------------------------------

    def pack_for(self, period: int) -> dict | None:
        """Pack metadata covering `period`, or None when unpacked."""
        period = int(period)
        with self._lock:
            start = self.range_start(period)
            if start is None:
                return None
            meta = self._packs.get(start)
            if meta is not None and start + meta["count"] > period:
                return dict(meta)
        return None

    def read_pack(self, meta: dict) -> tuple[dict, bytes] | None:
        """Load + verify a pack's bytes; returns (slices, raw) where
        `slices` maps period -> (etag, offset, length) with offsets into
        `raw`. Corruption (the artifact store quarantines the file) or a
        malformed payload drops the mapping and triggers an immediate
        rebuild — the next request serves fresh pack bytes."""
        try:
            raw = self.artifacts.read(meta["digest"], PACK_SUFFIX)
            index, base = decode_pack(raw)
            slices = {int(e["period"]): (e["etag"], base + int(e["offset"]),
                                         int(e["length"]))
                      for e in index["entries"]}
            return slices, raw
        except (ArtifactCorrupt, OSError, ValueError, KeyError):
            self.health.incr("gateway_pack_corrupt")
            with self._lock:
                cur = self._packs.get(meta["start"])
                if cur is not None and cur["digest"] == meta["digest"]:
                    del self._packs[meta["start"]]
            self.ensure_packs()             # rebuild from the update store
            return None

    def live_artifacts(self) -> set:
        """(digest, suffix) keep-set for the artifact scrubber: current
        packs are never expired as orphans (superseded tail packs drop
        out and get reaped — that is the intended lifecycle)."""
        with self._lock:
            return {(m["digest"], PACK_SUFFIX)
                    for m in self._packs.values() if m["digest"]}

    def snapshot(self) -> dict:
        with self._lock:
            return {"packs": len(self._packs),
                    "pack_periods": self.pack_periods,
                    "packed_through": max(
                        (m["start"] + m["count"] for m in
                         self._packs.values()), default=None)}

"""Generalized SSZ merkle multiproofs (native/witness side).

Reference parity: `witness/multiproof.rs` (the reference vendors ssz-rs
PR#118): generalized-index helper-set computation, multiproof creation from
a full tree, and multi-merkle-root verification. The reference's test-data
generator uses these to derive the finality/execution/committee branches
from a real BeaconState; this module serves the same role for this
framework's preprocessor and fixture tooling.

Generalized indices: root = 1; node i has children 2i, 2i+1. All functions
are pure host math (witness preparation happens before circuits)."""

from __future__ import annotations

import hashlib


def _sha(a: bytes, b: bytes) -> bytes:
    return hashlib.sha256(a + b).digest()


def get_branch_indices(tree_index: int) -> list[int]:
    """Sibling indices along the path to the root (deepest first).
    Reference: `multiproof.rs` get_branch_indices."""
    out = []
    i = tree_index
    while i > 1:
        out.append(i ^ 1)
        i //= 2
    return out


def get_path_indices(tree_index: int) -> list[int]:
    """The node's own path to (excluding) the root, deepest first."""
    out = []
    i = tree_index
    while i > 1:
        out.append(i)
        i //= 2
    return out


def get_helper_indices(indices: list[int]) -> list[int]:
    """Minimal set of extra node indices needed to prove `indices`
    together, sorted descending (reference `multiproof.rs:79`): the union
    of all branch indices minus every index on any path (those are
    recomputed, not supplied)."""
    all_helpers: set[int] = set()
    all_path: set[int] = set()
    for idx in indices:
        all_helpers.update(get_branch_indices(idx))
        all_path.update(get_path_indices(idx))
    return sorted(all_helpers - all_path, reverse=True)


def merkle_tree(leaves: list[bytes]) -> dict[int, bytes]:
    """Full tree {gindex: node} over a power-of-two leaf list
    (reference `multiproof.rs:166`)."""
    n = len(leaves)
    assert n and (n & (n - 1)) == 0, "leaf count must be a power of two"
    nodes: dict[int, bytes] = {}
    for i, leaf in enumerate(leaves):
        nodes[n + i] = leaf
    for i in range(n - 1, 0, -1):
        nodes[i] = _sha(nodes[2 * i], nodes[2 * i + 1])
    return nodes


def create_multiproof(tree: dict[int, bytes], indices: list[int]):
    """(leaves, helper nodes) proving `indices` against tree[1]
    (reference `create_multiproof`)."""
    leaves = [tree[i] for i in indices]
    helpers = [tree[i] for i in get_helper_indices(indices)]
    return leaves, helpers


def calculate_multi_merkle_root(leaves: list[bytes], proof: list[bytes],
                                indices: list[int]) -> bytes:
    """Root from (leaves at indices, helper nodes) — reference
    `multiproof.rs:116`. Raises KeyError on malformed/insufficient proofs."""
    assert len(leaves) == len(indices)
    helper_indices = get_helper_indices(indices)
    assert len(proof) == len(helper_indices), \
        f"need {len(helper_indices)} helpers, got {len(proof)}"
    objects = dict(zip(indices, leaves))
    objects.update(zip(helper_indices, proof))
    # standard SSZ-spec merge loop: walk keys descending, emit parents as
    # both children appear (appended parents are processed after all deeper
    # nodes, preserving the invariant)
    keys = sorted(objects, reverse=True)
    pos = 0
    while pos < len(keys):
        key = keys[pos]
        if key > 1 and key ^ 1 in objects and key // 2 not in objects:
            objects[key // 2] = _sha(objects[(key | 1) ^ 1],
                                     objects[key | 1])
            keys.append(key // 2)
        pos += 1
    return objects[1]


def verify_multiproof(root: bytes, leaves: list[bytes], proof: list[bytes],
                      indices: list[int]) -> bool:
    try:
        return calculate_multi_merkle_root(leaves, proof, indices) == root
    except (AssertionError, KeyError):
        return False

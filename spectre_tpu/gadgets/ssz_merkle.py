"""SSZ merkleization gadgets (in-circuit) + native mirrors.

Reference parity: `ssz_merkle.rs:27-73` (ssz_merkleize_chunks with zero-hash
padding), `:78-112` (gindex-guided merkle branch verification), ZERO_HASHES
(`:114`). Chunks are 8-Word (32-byte) values from the Sha256Chip.
"""

from __future__ import annotations

import hashlib

from ..builder.context import Context
from ..builder.sha256_chip import Sha256Chip


# -- native mirrors (witness-side; preprocessor uses these too) --------------

def sha256_pair_native(left: bytes, right: bytes) -> bytes:
    return hashlib.sha256(left + right).digest()


def zero_hashes(depth: int) -> list[bytes]:
    out = [b"\x00" * 32]
    for _ in range(depth):
        out.append(sha256_pair_native(out[-1], out[-1]))
    return out


def merkleize_chunks_native(chunks: list[bytes], limit: int | None = None) -> bytes:
    """Binary merkle root with zero-chunk padding up to `limit` leaves."""
    n = limit or max(len(chunks), 1)
    depth = max((n - 1).bit_length(), 0)
    layer = list(chunks)
    zh = zero_hashes(depth)
    for d in range(depth):
        if len(layer) % 2:
            layer.append(zh[d])
        layer = [sha256_pair_native(layer[i], layer[i + 1])
                 for i in range(0, len(layer), 2)]
    return layer[0] if layer else zh[depth]


def verify_merkle_proof_native(leaf: bytes, branch: list[bytes], gindex: int,
                               root: bytes) -> bool:
    node = leaf
    for sib in branch:
        if gindex % 2 == 0:
            node = sha256_pair_native(node, sib)
        else:
            node = sha256_pair_native(sib, node)
        gindex //= 2
    return node == root


# -- in-circuit versions -----------------------------------------------------

def merkleize_chunks(ctx: Context, sha: Sha256Chip, chunks: list, limit: int | None = None):
    """chunks: list of 8-Word lists -> 8-Word root.

    Zero-padding uses in-circuit constants of the precomputed zero-hash levels
    (reference precomputes 2 levels; we precompute all needed)."""
    n = limit or max(len(chunks), 1)
    depth = max((n - 1).bit_length(), 0)
    zh = zero_hashes(depth)

    def const_chunk(b: bytes):
        return [sha.constant_word(ctx, int.from_bytes(b[4 * i:4 * i + 4], "big"))
                for i in range(8)]

    layer = list(chunks)
    for d in range(depth):
        if len(layer) % 2:
            layer.append(const_chunk(zh[d]))
        layer = [sha.digest_two_to_one(ctx, layer[i], layer[i + 1])
                 for i in range(0, len(layer), 2)]
    return layer[0] if layer else const_chunk(zh[depth])


def verify_merkle_proof(ctx: Context, sha: Sha256Chip, leaf: list, branch: list,
                        gindex: int, root: list):
    """Constrain that `leaf` under `branch` at `gindex` hashes to `root`.

    gindex is a circuit-shape constant (reference: `verify_merkle_proof`,
    `ssz_merkle.rs:78` — the gindex comes from the Spec consts); branch items
    are 8-Word lists."""
    node = leaf
    g = gindex
    for sib in branch:
        if g % 2 == 0:
            node = sha.digest_two_to_one(ctx, node, sib)
        else:
            node = sha.digest_two_to_one(ctx, sib, node)
        g //= 2
    for a, b in zip(node, root):
        ctx.constrain_equal(a.cell, b.cell)


def load_bytes_checked(ctx: Context, sha: Sha256Chip, data: bytes) -> list:
    """Witness a byte string as 8-bit-checked cells (the shared loader both
    app circuits use for roots/branches/pubkeys)."""
    out = []
    for bt in data:
        c = ctx.load_witness(bt)
        sha._range_bits(ctx, c, 8)
        out.append(c)
    return out


def bytes_to_chunk(ctx: Context, sha: Sha256Chip, byte_cells: list) -> list:
    """32 byte cells (8-bit checked) -> 8-Word chunk (big-endian words)."""
    assert len(byte_cells) == 32
    return [sha.word_from_bytes_be(ctx, byte_cells[4 * i:4 * i + 4])
            for i in range(8)]


def chunk_to_le_hilo(ctx: Context, gate, chunk: list):
    """8-Word BE chunk -> two 128-bit field values (hi, lo) for public-input
    packing (reference: `util/bytes.rs:7` bytes_be_to_u128)."""
    # words are big-endian; bytes 0..15 -> hi, 16..31 -> lo
    hi = gate.inner_product_const(ctx, [w.cell for w in chunk[:4]],
                                  [1 << 96, 1 << 64, 1 << 32, 1])
    lo = gate.inner_product_const(ctx, [w.cell for w in chunk[4:]],
                                  [1 << 96, 1 << 64, 1 << 32, 1])
    return hi, lo

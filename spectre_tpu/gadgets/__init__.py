"""Reusable circuit gadgets above the chips.

Reference parity (SURVEY.md L2): `ssz_merkle.rs` (merkleization + branch
verification), `poseidon.rs` (committee commitment), `gadget/common.rs` /
`util/bytes.rs` (byte/limb plumbing).
"""

from .ssz_merkle import merkleize_chunks, verify_merkle_proof  # noqa: F401
from .poseidon_commit import g1_array_poseidon  # noqa: F401

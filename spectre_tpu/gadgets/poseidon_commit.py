"""Poseidon commitment to a sync-committee pubkey array.

Reference parity: `poseidon.rs:42-95` (`g1_array_poseidon`: fold each pubkey's
X-coordinate limbs 5->2 and sponge over the folded pairs + packed y-signs) and
its native mirrors (`poseidon_hash_g1_array:100`,
`..._from_uncompressed:147`, `..._from_compressed:166`). The circuit and the
native function here are the SAME folding scheme, so the commitment a
CommitteeUpdate proof outputs equals the one the Step proof consumes.

Our folding: X is NUM_LIMBS=5 limbs of LIMB_BITS=104 (spec.py); limbs fold to
2 field elements (limbs 0..2 -> lo via base 2^104, limbs 3..4 -> hi); y signs
pack 253 per field element.
"""

from __future__ import annotations

from ..fields import bn254
from ..ops import poseidon as P
from ..spec import LIMB_BITS, NUM_LIMBS
from ..builder.context import Context
from ..builder.gate import GateChip
from ..builder.poseidon_chip import PoseidonChip

R = bn254.R

FOLD_LO = 3  # limbs folded into the low element
SIGN_PACK = 253


def fold_limbs_native(x_limbs: list[int]) -> tuple[int, int]:
    assert len(x_limbs) == NUM_LIMBS
    lo = sum(v << (LIMB_BITS * i) for i, v in enumerate(x_limbs[:FOLD_LO])) % R
    hi = sum(v << (LIMB_BITS * i) for i, v in enumerate(x_limbs[FOLD_LO:])) % R
    return lo, hi


def g1_array_poseidon_native(x_limbs_list: list, y_signs: list[int]) -> int:
    """Native commitment: inputs are per-pubkey X limb vectors + y sign bits."""
    sponge = P.PoseidonSponge()
    for limbs in x_limbs_list:
        lo, hi = fold_limbs_native(limbs)
        sponge.absorb([lo, hi])
    for off in range(0, len(y_signs), SIGN_PACK):
        packed = 0
        for i, b in enumerate(y_signs[off:off + SIGN_PACK]):
            packed |= (int(b) & 1) << i
        sponge.absorb([packed])
    return sponge.squeeze()


def committee_poseidon_from_uncompressed(points) -> int:
    """Host: affine BLS12-381 G1 points -> commitment (reference:
    `poseidon_committee_commitment_from_uncompressed`, `poseidon.rs:147`)."""
    from ..fields import bls12_381 as bls
    limbs_list, signs = [], []
    mask = (1 << LIMB_BITS) - 1
    for pt in points:
        x = int(pt[0])
        limbs_list.append([(x >> (LIMB_BITS * i)) & mask for i in range(NUM_LIMBS)])
        signs.append(1 if bls._fq_sign(pt[1]) else 0)
    return g1_array_poseidon_native(limbs_list, signs)


def g1_array_poseidon(ctx: Context, gate: GateChip, poseidon: PoseidonChip,
                      x_limbs_cells: list, y_sign_cells: list):
    """In-circuit commitment. x_limbs_cells: per pubkey, NUM_LIMBS cells
    (already range-checked to LIMB_BITS); y_sign_cells: bit cells."""
    inputs = []
    for limbs in x_limbs_cells:
        assert len(limbs) == NUM_LIMBS
        lo = gate.inner_product_const(
            ctx, limbs[:FOLD_LO], [1 << (LIMB_BITS * i) for i in range(FOLD_LO)])
        hi = gate.inner_product_const(
            ctx, limbs[FOLD_LO:],
            [1 << (LIMB_BITS * i) for i in range(NUM_LIMBS - FOLD_LO)])
        inputs.extend([lo, hi])
    for off in range(0, len(y_sign_cells), SIGN_PACK):
        batch = y_sign_cells[off:off + SIGN_PACK]
        packed = gate.inner_product_const(ctx, batch, [1 << i for i in range(len(batch))])
        inputs.append(packed)
    return poseidon.hash_values(ctx, inputs)

"""Fp12 tower chip: BLS12-381 Fq12 arithmetic over BN254 Fr cells.

Reference parity: halo2-ecc `Fp12Chip` (SURVEY.md L0; the pairing layer of
`sync_step_circuit.rs:171` `assert_valid_signature`). Tower: Fq12 =
Fq2[w]/(w^6 - xi), xi = 1 + u — consistent with the host poly basis
(fields/bls12_381.py: u = w^6 - 1), so host<->tower conversion is linear.

Elements are 6-tuples of reduced Fq2 pairs ((CrtUint, CrtUint) each).
Multiplication runs in the LAZY domain (Fp2Lazy): 36 coefficient products
accumulated without carries, ONE carry_mod per output coefficient limb pair
(12 total) — the constraint-count backbone of the in-circuit pairing.

Frobenius constants gamma1/gamma2 and the p^6 conjugation sign are derived
from xi at import (no opaque tables); `tests/test_builder.py` checks chip
arithmetic against the host Fq12 through the tower<->poly conversion.
"""

from __future__ import annotations

import functools

from ..fields import bls12_381 as bls
from .context import Context
from .fp2_chip import Fp2Chip, Fp2Lazy

P = bls.P
XI = bls.Fq2([1, 1])


# ---------------------------------------------------------------------------
# host-side tower <-> poly-basis conversion (for witnesses and test oracles)
# ---------------------------------------------------------------------------

def tower_to_fq12(coeffs) -> "bls.Fq12":
    """[6 x Fq2] tower coords -> host poly-basis Fq12 (u = w^6 - 1)."""
    c = [0] * 12
    for i, a in enumerate(coeffs):
        a0, a1 = int(a.c[0]), int(a.c[1])
        c[i] = (c[i] + a0 - a1) % P
        c[i + 6] = (c[i + 6] + a1) % P
    return bls.Fq12(c)


def fq12_to_tower(x: "bls.Fq12"):
    """Host poly-basis Fq12 -> [6 x Fq2] tower coords."""
    c = x.c
    return [bls.Fq2([(c[i] + c[i + 6]) % P, c[i + 6]]) for i in range(6)]


@functools.cache
def frobenius_constants():
    """(gamma1[i], gamma2[i], i=0..5): xi^(i(p-1)/6) and xi^(i(p^2-1)/6).
    Conjugation sign for p^6 is -1 (asserted — xi^((p^6-1)/6) = -1)."""
    g1 = [XI ** ((i * (P - 1)) // 6) for i in range(6)]
    g2 = [XI ** ((i * (P * P - 1)) // 6) for i in range(6)]
    assert XI ** ((P ** 6 - 1) // 6) == bls.Fq2([P - 1, 0])
    return g1, g2


class Fp12Chip:
    def __init__(self, fp2: Fp2Chip):
        self.fp2 = fp2
        self.lazy = fp2.lz   # the one shared lazy engine (fp2_chip.py)

    # -- loading --------------------------------------------------------
    def load(self, ctx: Context, coeffs) -> tuple:
        """coeffs: [6 x Fq2] tower coordinates (or host Fq12)."""
        if isinstance(coeffs, bls.Fq12):
            coeffs = fq12_to_tower(coeffs)
        return tuple(self.fp2.load(ctx, a) for a in coeffs)

    def load_constant(self, ctx: Context, coeffs) -> tuple:
        if isinstance(coeffs, bls.Fq12):
            coeffs = fq12_to_tower(coeffs)
        return tuple(self.fp2.load_constant(ctx, a) for a in coeffs)

    def one(self, ctx: Context) -> tuple:
        return self.load_constant(ctx, [bls.Fq2([1, 0])] + [bls.Fq2([0, 0])] * 5)

    def value(self, a) -> "bls.Fq12":
        return tower_to_fq12([self.fp2.value(c) for c in a])

    # -- arithmetic ------------------------------------------------------
    def mul(self, ctx: Context, a, b) -> tuple:
        """Schoolbook over w-slots, lazy: S_k = sum_{i+j=k} a_i b_j;
        c_k = S_k + xi * S_{k+6}; 12 reductions total. Karatsuba operand
        sums are hoisted per coefficient (each is reused 6 times)."""
        lz = self.lazy
        sums_a = [lz.coeff_sum(ctx, a[i]) for i in range(6)]
        sums_b = [lz.coeff_sum(ctx, b[j]) for j in range(6)]
        s = [None] * 11
        for i in range(6):
            for j in range(6):
                t = lz.mul(ctx, a[i], b[j], sa=sums_a[i], sb=sums_b[j])
                k = i + j
                s[k] = t if s[k] is None else lz.add(ctx, s[k], t)
        return self._fold_and_reduce(ctx, s)

    def _fold_and_reduce(self, ctx: Context, s: list) -> tuple:
        """Slot sums s[0..10] -> 6 reduced tower coefficients:
        c_k = reduce(s_k + xi * s_{k+6})."""
        lz = self.lazy
        out = []
        for k in range(6):
            acc = s[k]
            if k + 6 <= 10 and s[k + 6] is not None:
                acc = lz.add(ctx, acc, lz.mul_by_xi(ctx, s[k + 6]))
            out.append(lz.reduce(ctx, acc))
        return tuple(out)

    def square(self, ctx: Context, a) -> tuple:
        """Symmetric schoolbook: 21 Fq2 products (6 diagonal + 15 doubled
        cross terms) instead of 36."""
        lz = self.lazy
        big = lz.big
        sums = [lz.coeff_sum(ctx, a[i]) for i in range(6)]
        s = [None] * 11
        for i in range(6):
            for j in range(i, 6):
                t = lz.mul(ctx, a[i], a[j], sa=sums[i], sb=sums[j])
                if j > i:
                    t = (big.scale_ovf(ctx, t[0], 2), big.scale_ovf(ctx, t[1], 2))
                k = i + j
                s[k] = t if s[k] is None else lz.add(ctx, s[k], t)
        return self._fold_and_reduce(ctx, s)

    def _sq4(self, ctx: Context, za, zb):
        """Fp4 squaring (za + zb V)^2 = (za^2 + xi zb^2) + (2 za zb) V for
        V = w^3, V^2 = xi — shared by the full Granger–Scott square and the
        compressed-coordinate square."""
        lz = self.lazy
        ta = lz.mul(ctx, za, za)
        tb = lz.mul(ctx, zb, zb)
        zs = lz.add(ctx, lz.lift(ctx, za), lz.lift(ctx, zb))
        ts = lz.mul(ctx, zs, zs)
        tab = lz.sub(ctx, lz.sub(ctx, ts, ta), tb)
        return lz.add(ctx, ta, lz.mul_by_xi(ctx, tb)), tab

    def _two(self, ctx: Context, p):
        """2x a reduced Fq2 pair, lazily."""
        lz = self.lazy
        return lz.scale(ctx, lz.lift(ctx, p), 2)

    def cyclotomic_square(self, ctx: Context, a) -> tuple:
        """Granger–Scott squaring, valid ONLY for elements of the cyclotomic
        subgroup (as everything after the final exponentiation's easy part
        is): with g0=(z0,z3), g1=(z1,z4), g2=(z2,z5) in Fp4 = Fp2[V],
        V = w^3, V^2 = xi, and A=g0^2, C=g1^2, B=g2^2:
            h0 = 3A - 2*conj(g0)   h1 = 3*V*B + 2*conj(g1)
            h2 = 3C - 2*conj(g2)
        Cost: 3 Fp4 squarings (27 limb convolutions) vs the generic
        symmetric square's 21 Fq2 products (63 convolutions) — the final
        exp's ~315 chain squarings are the dominant convolution count in
        the pairing. Formula numerically validated against the host tower
        (a non-cyclotomic input does NOT satisfy it; inputs here are
        constraint-forced into the subgroup by the easy part)."""
        lz = self.lazy
        sq4 = lambda za, zb: self._sq4(ctx, za, zb)
        two = lambda p: self._two(ctx, p)
        scale3 = lambda p: lz.scale(ctx, p, 3)

        z = a
        A0, A1 = sq4(z[0], z[3])
        B0, B1 = sq4(z[2], z[5])
        C0, C1 = sq4(z[1], z[4])
        y0 = lz.sub(ctx, scale3(A0), two(z[0]))
        y3 = lz.add(ctx, scale3(A1), two(z[3]))
        y1 = lz.add(ctx, scale3(lz.mul_by_xi(ctx, B1)), two(z[1]))
        y4 = lz.sub(ctx, scale3(B0), two(z[4]))
        y2 = lz.sub(ctx, scale3(C0), two(z[2]))
        y5 = lz.add(ctx, scale3(C1), two(z[5]))
        return tuple(lz.reduce(ctx, y) for y in (y0, y1, y2, y3, y4, y5))

    # -- Karabina-style compressed cyclotomic squaring ------------------
    # In this tower the coordinate set {c1, c2, c4, c5} is CLOSED under the
    # Granger–Scott square map (y1,y2,y4,y5 depend only on z1,z2,z4,z5 —
    # read off cyclotomic_square above), so long square runs in pow_abs_x
    # carry 4 coefficients instead of 6: 6 Fq2 products + 8 reductions per
    # square vs the full GS 9 + 12. Decompression recovers (c0, c3) from
    # the unit-norm identity g·conj(g) = 1, which in v-coordinates
    # (v = w², E = c0 + c2 v + c4 v², O = c1 + c3 v + c5 v²; E² − vO² = 1)
    # yields the LINEAR system
    #     2 c2·c0 − 2ξ c5·c3 = c1² − ξ c4²
    #     2 c4·c0 − 2 c1·c3 = ξ c5² − c2²
    # — witnessed (c0, c3), both equations constrained, and the system's
    # determinant 4(ξ c4 c5 − c1 c2) constrained nonzero so the solution is
    # pinned uniquely. Host-validated against the full tower square.

    def _compressed_square(self, ctx: Context, comp) -> tuple:
        """One squaring step on (c1, c2, c4, c5) of a cyclotomic element."""
        lz = self.lazy
        z1, z2, z4, z5 = comp
        two = lambda p: self._two(ctx, p)
        B0, B1 = self._sq4(ctx, z2, z5)
        C0, C1 = self._sq4(ctx, z1, z4)
        y1 = lz.add(ctx, lz.scale(ctx, lz.mul_by_xi(ctx, B1), 3), two(z1))
        y4 = lz.sub(ctx, lz.scale(ctx, B0, 3), two(z4))
        y2 = lz.sub(ctx, lz.scale(ctx, C0, 3), two(z2))
        y5 = lz.add(ctx, lz.scale(ctx, C1, 3), two(z5))
        return tuple(lz.reduce(ctx, y) for y in (y1, y2, y4, y5))

    def _decompress(self, ctx: Context, comp) -> tuple:
        """(c1, c2, c4, c5) -> full 6-tuple, recovering (c0, c3)."""
        fp2, lz = self.fp2, self.lazy
        z1, z2, z4, z5 = comp
        XI_h = bls.Fq2([1, 1])
        two_h = bls.Fq2([2, 0])
        v1, v2, v4, v5 = (fp2.value(z) for z in comp)
        a11, a12 = v2 * two_h, bls.Fq2([0, 0]) - XI_h * v5 * two_h
        a21, a22 = v4 * two_h, bls.Fq2([0, 0]) - v1 * two_h
        b1 = v1 * v1 - XI_h * v4 * v4
        b2 = XI_h * v5 * v5 - v2 * v2
        det = a11 * a22 - a12 * a21
        # det == 0 (xi c4 c5 == c1 c2) happens with probability ~2^-381 for
        # the final-exp chain values of an honest witness, and a witness
        # engineered to hit it only aborts ITS OWN proving (witness-time
        # assert; constraint shape must stay witness-independent, so a
        # dynamic fallback to full squares is not an option)
        assert det != bls.Fq2([0, 0]), "compressed element not decompressible"
        c0 = fp2.load(ctx, (b1 * a22 - b2 * a12) / det)
        c3 = fp2.load(ctx, (a11 * b2 - a21 * b1) / det)
        # det != 0 pins (c0, c3) as the unique solution (reduce before the
        # inverse product so the quotient stays within limb width)
        det_cell = lz.reduce(
            ctx, lz.sub(ctx, lz.mul_by_xi(ctx, lz.mul(ctx, z4, z5)),
                        lz.mul(ctx, z1, z2)))
        fp2.assert_nonzero(ctx, det_cell)
        eq1 = lz.sub(
            ctx,
            lz.sub(ctx, lz.scale(ctx, lz.mul(ctx, z2, c0), 2),
                   lz.scale(ctx, lz.mul_by_xi(ctx, lz.mul(ctx, z5, c3)), 2)),
            lz.sub(ctx, lz.mul(ctx, z1, z1),
                   lz.mul_by_xi(ctx, lz.mul(ctx, z4, z4))))
        lz.assert_zero(ctx, eq1)
        eq2 = lz.sub(
            ctx,
            lz.sub(ctx, lz.scale(ctx, lz.mul(ctx, z4, c0), 2),
                   lz.scale(ctx, lz.mul(ctx, z1, c3), 2)),
            lz.sub(ctx, lz.mul_by_xi(ctx, lz.mul(ctx, z5, z5)),
                   lz.mul(ctx, z2, z2)))
        lz.assert_zero(ctx, eq2)
        return (c0, z1, z2, c3, z4, z5)

    def conjugate(self, ctx: Context, a) -> tuple:
        """f^(p^6): w -> -w (gamma6 = -1): negate odd slots."""
        fp2 = self.fp2
        out = []
        for i, c in enumerate(a):
            out.append(fp2.neg(ctx, c) if i % 2 else c)
        return tuple(out)

    def frobenius(self, ctx: Context, a, power: int = 1) -> tuple:
        """f^(p^power) for power in {1, 2}: coefficient-wise Fq2 frobenius
        (conjugation for odd power) then gamma constant mul. (The final
        exponentiation needs only these two powers.)"""
        assert power in (1, 2)
        g1, g2 = frobenius_constants()
        fp2, lz = self.fp2, self.lazy
        out = []
        for i, c in enumerate(a):
            if power == 1:
                cc, k = fp2.conjugate(ctx, c), g1[i]
            else:
                cc, k = c, g2[i]
            out.append(lz.reduce(ctx, lz.mul_const(ctx, cc, k)))
        return tuple(out)

    def mul_sparse_035(self, ctx: Context, f, c0, c3, c5) -> tuple:
        """f * (c0 + c3 w^3 + c5 w^5) where c0/c3/c5 are REDUCED Fq2 pairs
        (the Miller line shape for the M-twist with 1/w folding; see
        pairing_chip). 18 Fq2 products, 12 reductions."""
        lz = self.lazy
        s = [None] * 11
        sums_f = [lz.coeff_sum(ctx, f[i]) for i in range(6)]
        sum_c0 = lz.coeff_sum(ctx, c0)
        sum_c3 = lz.coeff_sum(ctx, c3)
        sum_c5 = lz.coeff_sum(ctx, c5)

        def acc(k, t):
            s[k] = t if s[k] is None else lz.add(ctx, s[k], t)

        for i in range(6):
            fi, sfi = f[i], sums_f[i]
            acc(i, lz.mul(ctx, fi, c0, sa=sfi, sb=sum_c0))
            acc(i + 3, lz.mul(ctx, fi, c3, sa=sfi, sb=sum_c3))
            acc(i + 5, lz.mul(ctx, fi, c5, sa=sfi, sb=sum_c5))
        return self._fold_and_reduce(ctx, s)

    def assert_equal(self, ctx: Context, a, b):
        for x, y in zip(a, b):
            self.fp2.assert_equal(ctx, x, y)

    def assert_one(self, ctx: Context, a):
        one = self.one(ctx)
        self.assert_equal(ctx, a, one)

    def inverse(self, ctx: Context, a) -> tuple:
        """Witnessed inverse: load inv(a) and constrain a * inv == 1."""
        av = self.value(a)
        inv = self.load(ctx, av.inv())
        prod = self.mul(ctx, a, inv)
        self.assert_one(ctx, prod)
        return inv

    # -- exponentiation by |x| (BLS parameter), for the final exp -------
    def pow_abs_x(self, ctx: Context, a, cyclotomic: bool = False) -> tuple:
        """a^|x|, |x| = 0xd201000000010000 (square-and-multiply over the
        fixed bit pattern; bits 63,62,60,57,48,16). cyclotomic=True uses
        Granger–Scott squaring, with square runs >= 3 carried in the
        compressed (c1,c2,c4,c5) coordinates (see _compressed_square) —
        only valid for subgroup elements."""
        absx = -bls.BLS_X
        bits = bin(absx)[2:]
        if not cyclotomic:
            acc = a
            for bit in bits[1:]:
                acc = self.square(ctx, acc)
                if bit == "1":
                    acc = self.mul(ctx, acc, a)
            return acc
        # runs of squares between multiplies: [(k squares, mul after?)]
        runs = []
        cnt = 0
        for bit in bits[1:]:
            cnt += 1
            if bit == "1":
                runs.append((cnt, True))
                cnt = 0
        if cnt:
            runs.append((cnt, False))
        acc = a
        for k, mul_after in runs:
            if k >= 3:   # decompression overhead (~2 squares) amortized
                comp = (acc[1], acc[2], acc[4], acc[5])
                for _ in range(k):
                    comp = self._compressed_square(ctx, comp)
                acc = self._decompress(ctx, comp)
            else:
                for _ in range(k):
                    acc = self.cyclotomic_square(ctx, acc)
            if mul_after:
                acc = self.mul(ctx, acc, a)
        return acc

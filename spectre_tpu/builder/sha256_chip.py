"""In-circuit SHA256 via packed nibble-op lookups.

Reference parity: the flex-gate SHA256 chip lineage (`gadget/crypto/
sha256_flex.rs`, SURVEY.md L2) — but redesigned around THIS framework's single
universal gate + multi-table lookup argument instead of custom spread-table
gate regions: every 4-bit XOR/AND is one membership proof of the packed value
(op<<12 | x<<8 | y<<4 | z) in the "nibble_op" table. Correct at any k >= 13;
a custom spread-gate region for bulk hashing efficiency is the planned
round-2 upgrade (this encoding costs ~50k gate units per block vs the
reference's ~15k rows).

Words are (32-bit cell, 8 little-endian nibble cells); the nibble form is the
working representation, the cell form feeds arithmetic (mod-2^32 adds).
"""

from __future__ import annotations

from dataclasses import dataclass

from ..fields import bn254
from ..ops.sha256 import H0, K
from .context import AssignedValue, Context
from .gate import GateChip

R = bn254.R

XOR_OP = 0
AND_OP = 1

_POW16 = [1 << (4 * i) for i in range(8)]


@dataclass
class Word:
    cell: AssignedValue
    nibs: list  # 8 nibble cells, little-endian

    @property
    def value(self) -> int:
        return self.cell.value


class Sha256Chip:
    """lookup_col: index of the lookup-advice column carrying 'nibble_op'."""

    def __init__(self, gate: GateChip | None = None):
        self.gate = gate or GateChip()

    # -- nibble plumbing ------------------------------------------------
    def _push_op(self, ctx: Context, op: int, x: AssignedValue, y: AssignedValue,
                 z_val: int) -> AssignedValue:
        """Witness z and prove (op, x, y, z) is a table row.

        SOUNDNESS INVARIANT: x and y must ALREADY be range-checked nibbles by
        the caller (decompositions check theirs; chained op outputs are checked
        here). z is range-checked before packing — without it (or with the old
        257*x "self-XOR" trick) the packed fields alias across bit boundaries
        and a malicious prover can forge bitwise results (found by review:
        packed 17 = 0x011 decodes as the valid XOR row 0^1=1)."""
        assert x.value < 16 and y.value < 16, "unchecked nibble into _push_op"
        z = ctx.load_witness(z_val)
        self._check_nibble(ctx, z)
        # packed = op*4096 + x*256 + y*16 + z — uniquely decodable since all
        # three fields are independently constrained to [0, 16)
        t1 = self.gate.mul_add(ctx, y, 16, z)
        packed = self.gate.mul_add(ctx, x, 256, t1)
        if op:
            packed = self.gate.add(ctx, packed, op << 12)
        ctx.push_lookup_table(packed, "nibble_op")
        return z

    def _check_nibble(self, ctx: Context, x: AssignedValue):
        """x in [0,16) via membership in the dedicated 16-row nibble table."""
        ctx.push_lookup_table(x, "nibble")

    def _decompose(self, ctx: Context, cell: AssignedValue) -> list:
        """cell (32-bit value) -> 8 checked nibbles, recomposition constrained
        (bulk-appended)."""
        v = cell.value
        assert v < (1 << 32)
        nib_vals = [(v >> (4 * i)) & 0xF for i in range(8)]
        start = ctx.bulk_cells(nib_vals)
        ctx.bulk_lookup("nibble",
                        [(start + i, nv) for i, nv in enumerate(nib_vals)])
        nibs = [AssignedValue("adv", start + i, nv)
                for i, nv in enumerate(nib_vals)]
        acc = self.gate.inner_product_const(ctx, nibs, _POW16)
        ctx.constrain_equal(acc, cell)
        return nibs

    # -- word construction ---------------------------------------------
    def load_word(self, ctx: Context, v: int) -> Word:
        cell = ctx.load_witness(v & 0xFFFFFFFF)
        return Word(cell, self._decompose(ctx, cell))

    def constant_word(self, ctx: Context, v: int) -> Word:
        cell = ctx.load_constant(v & 0xFFFFFFFF)
        return Word(cell, self._decompose(ctx, cell))

    def word_from_cell(self, ctx: Context, cell: AssignedValue) -> Word:
        return Word(cell, self._decompose(ctx, cell))

    def word_from_bytes_be(self, ctx: Context, byte_cells: list) -> Word:
        """4 byte cells (big-endian, already range-checked to 8 bits) -> Word."""
        assert len(byte_cells) == 4
        cell = self.gate.inner_product_const(
            ctx, byte_cells, [1 << 24, 1 << 16, 1 << 8, 1])
        return self.word_from_cell(ctx, cell)

    def _recompose(self, ctx: Context, nibs: list) -> Word:
        cell = self.gate.inner_product_const(ctx, nibs, _POW16)
        return Word(cell, nibs)

    # -- bitwise ops ----------------------------------------------------
    def _nib_op(self, ctx: Context, op: int, a_nibs, b_nibs) -> list:
        """Bulk form of `_push_op` over a nibble vector: identical constraint
        structure (witness z, nibble-check z, pack (op,x,y,z), table lookup),
        appended through the bulk primitives. Inputs must already be checked
        nibbles (same soundness invariant as `_push_op`)."""
        if op == XOR_OP:
            z_vals = [x.value ^ y.value for x, y in zip(a_nibs, b_nibs)]
        else:
            z_vals = [x.value & y.value for x, y in zip(a_nibs, b_nibs)]
        zstart = ctx.bulk_cells(z_vals)
        ctx.bulk_lookup("nibble",
                        [(zstart + i, zv) for i, zv in enumerate(z_vals)])
        copies = ctx.copies
        pin = ctx.pin_const
        op_hi = op << 12
        flat = []
        lkp = []
        pos = len(ctx.adv_values)
        for i, (x, y) in enumerate(zip(a_nibs, b_nibs)):
            assert x.value < 16 and y.value < 16, "unchecked nibble into _nib_op"
            xv, yv, zv = x.value, y.value, z_vals[i]
            t1 = yv * 16 + zv
            # unit: t1 = y*16 + z  as  [z, y, 16, t1]
            copies.append((("adv", zstart + i), ("adv", pos)))
            copies.append((("adv", y.index), ("adv", pos + 1)))
            pin(pos + 2, 16)
            flat.append(zv), flat.append(yv), flat.append(16), flat.append(t1)
            packed = xv * 256 + t1
            # unit: packed = x*256 + t1  as  [t1, x, 256, packed]
            copies.append((("adv", pos + 3), ("adv", pos + 4)))
            copies.append((("adv", x.index), ("adv", pos + 5)))
            pin(pos + 6, 256)
            flat.append(t1), flat.append(xv), flat.append(256), flat.append(packed)
            pos += 8
            if op_hi:
                # unit: out = packed + op<<12  as  [packed, op<<12, 1, out]
                out = packed + op_hi
                copies.append((("adv", pos - 1), ("adv", pos)))
                pin(pos + 1, op_hi)
                pin(pos + 2, 1)
                flat.append(packed), flat.append(op_hi), flat.append(1), \
                    flat.append(out)
                pos += 4
                lkp.append((pos - 1, out))
            else:
                lkp.append((pos - 1, packed))
        ctx.bulk_gated(flat)
        ctx.bulk_lookup("nibble_op", lkp)
        return [AssignedValue("adv", zstart + i, zv)
                for i, zv in enumerate(z_vals)]

    def xor3(self, ctx: Context, a_nibs, b_nibs, c_nibs) -> list:
        return self._nib_op(ctx, XOR_OP, self._nib_op(ctx, XOR_OP, a_nibs, b_nibs), c_nibs)

    def ch(self, ctx: Context, e: Word, f: Word, g: Word) -> Word:
        """(e & f) ^ (~e & g), nibble-wise."""
        ef = self._nib_op(ctx, AND_OP, e.nibs, f.nibs)
        ne = [self.gate.sub(ctx, 15, x) for x in e.nibs]
        neg = self._nib_op(ctx, AND_OP, ne, g.nibs)
        return self._recompose(ctx, self._nib_op(ctx, XOR_OP, ef, neg))

    def maj(self, ctx: Context, a: Word, b: Word, c: Word) -> Word:
        """maj = (a + b + c - xor3(a,b,c)) / 2 — word-level identity (each bit
        position: sum of 3 bits = maj*2 + xor)."""
        x = self._recompose(ctx, self.xor3(ctx, a.nibs, b.nibs, c.nibs))
        s = self.gate.add(ctx, self.gate.add(ctx, a.cell, b.cell), c.cell)
        d = self.gate.sub(ctx, s, x.cell)
        mv = (a.value + b.value + c.value - x.value) // 2
        m = ctx.load_witness(mv)
        two_m = self.gate.mul(ctx, m, 2)
        ctx.constrain_equal(two_m, d)
        # m < 2^32 is implied bit-wise, but constrain anyway (cheap, safe):
        return self.word_from_cell(ctx, m)

    # -- rotations / shifts --------------------------------------------
    def _split(self, ctx: Context, w: Word, s: int):
        """w = hi * 2^s + lo with lo < 2^s, hi < 2^(32-s); returns (lo, hi)
        as cells with tight range checks via nibble lookups."""
        v = w.value
        lo_v, hi_v = v & ((1 << s) - 1), v >> s
        lo = ctx.load_witness(lo_v)
        hi = ctx.load_witness(hi_v)
        acc = self.gate.mul_add(ctx, hi, 1 << s, lo)
        ctx.constrain_equal(acc, w.cell)
        self._range_bits(ctx, lo, s)
        self._range_bits(ctx, hi, 32 - s)
        return lo, hi

    def _range_bits(self, ctx: Context, cell: AssignedValue, bits: int):
        """cell < 2^bits via nibble decomposition (+ shifted top nibble),
        bulk-appended."""
        v = cell.value
        assert v < (1 << bits)
        nn = (bits + 3) // 4
        nib_vals = [(v >> (4 * i)) & 0xF for i in range(nn)]
        start = ctx.bulk_cells(nib_vals)
        ctx.bulk_lookup("nibble",
                        [(start + i, nv) for i, nv in enumerate(nib_vals)])
        nibs = [AssignedValue("adv", start + i, nv)
                for i, nv in enumerate(nib_vals)]
        rem = bits - 4 * (nn - 1)
        if rem < 4:
            shifted = self.gate.mul(ctx, nibs[-1], 1 << (4 - rem))
            self._check_nibble(ctx, shifted)
        acc = self.gate.inner_product_const(ctx, nibs, _POW16[:nn])
        ctx.constrain_equal(acc, cell)

    def rotr(self, ctx: Context, w: Word, r: int) -> Word:
        lo, hi = self._split(ctx, w, r)
        cell = self.gate.mul_add(ctx, lo, 1 << (32 - r), hi)
        return self.word_from_cell(ctx, cell)

    def shr(self, ctx: Context, w: Word, s: int) -> Word:
        _lo, hi = self._split(ctx, w, s)
        return self.word_from_cell(ctx, hi)

    # -- modular addition ----------------------------------------------
    def mod_add(self, ctx: Context, items: list) -> Word:
        """(sum of 32-bit words/cells/consts) mod 2^32."""
        total = 0
        acc = None
        for it in items:
            if isinstance(it, Word):
                total += it.value
                acc = it.cell if acc is None else self.gate.add(ctx, acc, it.cell)
            elif isinstance(it, AssignedValue):
                total += it.value
                acc = it if acc is None else self.gate.add(ctx, acc, it)
            else:
                total += int(it)
                acc = ctx.load_constant(int(it)) if acc is None else \
                    self.gate.add(ctx, acc, int(it))
        out_v = total & 0xFFFFFFFF
        carry_v = total >> 32
        assert carry_v < 16
        out = ctx.load_witness(out_v)
        carry = ctx.load_witness(carry_v)
        self._check_nibble(ctx, carry)
        recomb = self.gate.mul_add(ctx, carry, 1 << 32, out)
        ctx.constrain_equal(recomb, acc)
        return self.word_from_cell(ctx, out)

    # -- compression ----------------------------------------------------
    def compress(self, ctx: Context, state: list, block: list) -> list:
        """state: 8 Words; block: 16 Words -> 8 Words."""
        a, b, c, d, e, f, g, h = state
        w = list(block)
        for t in range(64):
            if t >= 16:
                s0w = w[t - 15]
                sig0 = self._recompose(ctx, self.xor3(
                    ctx, self.rotr(ctx, s0w, 7).nibs, self.rotr(ctx, s0w, 18).nibs,
                    self.shr(ctx, s0w, 3).nibs))
                s1w = w[t - 2]
                sig1 = self._recompose(ctx, self.xor3(
                    ctx, self.rotr(ctx, s1w, 17).nibs, self.rotr(ctx, s1w, 19).nibs,
                    self.shr(ctx, s1w, 10).nibs))
                w.append(self.mod_add(ctx, [sig1, w[t - 7], sig0, w[t - 16]]))
            s1 = self._recompose(ctx, self.xor3(
                ctx, self.rotr(ctx, e, 6).nibs, self.rotr(ctx, e, 11).nibs,
                self.rotr(ctx, e, 25).nibs))
            chv = self.ch(ctx, e, f, g)
            t1 = self.mod_add(ctx, [h, s1, chv, int(K[t]), w[t]])
            s0 = self._recompose(ctx, self.xor3(
                ctx, self.rotr(ctx, a, 2).nibs, self.rotr(ctx, a, 13).nibs,
                self.rotr(ctx, a, 22).nibs))
            majv = self.maj(ctx, a, b, c)
            t2 = self.mod_add(ctx, [s0, majv])
            h, g, f = g, f, e
            e = self.mod_add(ctx, [d, t1])
            d, c, b = c, b, a
            a = self.mod_add(ctx, [t1, t2])
        return [self.mod_add(ctx, [x, y]) for x, y in zip(state, [a, b, c, d, e, f, g, h])]

    def initial_state(self, ctx: Context) -> list:
        return [self.constant_word(ctx, int(v)) for v in H0]

    def digest_two_to_one(self, ctx: Context, left: list, right: list) -> list:
        """SSZ merkle node: sha256(left32 || right32); inputs/outputs are
        8-Word lists. One data block + the constant 512-bit-length pad block."""
        state = self.compress(ctx, self.initial_state(ctx), left + right)
        pad = [self.constant_word(ctx, 0x80000000)] + \
              [self.constant_word(ctx, 0)] * 14 + \
              [self.constant_word(ctx, 512)]
        return self.compress(ctx, state, pad)

    def digest_bytes(self, ctx: Context, byte_cells: list) -> list:
        """Full SHA256 of a byte-cell message (bytes already 8-bit checked).
        Padding is fixed at trace time by the message length."""
        msg_len = len(byte_cells)
        padded = list(byte_cells)
        padded.append(ctx.load_constant(0x80))
        while (len(padded) % 64) != 56:
            padded.append(ctx.load_constant(0))
        for byte in (8 * msg_len).to_bytes(8, "big"):
            padded.append(ctx.load_constant(byte))
        state = self.initial_state(ctx)
        for off in range(0, len(padded), 64):
            block = [self.word_from_bytes_be(ctx, padded[off + 4 * i:off + 4 * i + 4])
                     for i in range(16)]
            state = self.compress(ctx, state, block)
        return state

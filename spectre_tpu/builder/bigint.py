"""CRT big-integer arithmetic over the gate/range chips.

Reference parity: halo2-ecc's `ProperCrtUint` machinery (SURVEY.md L0/N5) —
non-native field elements as NUM_LIMBS x LIMB_BITS limb cells plus a native
(mod r) accumulator, with the classic CRT reduction: an identity is enforced
mod r (one native inner product) AND over the limb radix (carry chain with
signed range-checked carries), which together pin it over the integers.

Redesigned, not ported: one universal vertical gate, range checks via the
lookup table, carries witnessed with an offset to keep them unsigned.
"""

from __future__ import annotations

from dataclasses import dataclass

from ..fields import bn254
from ..spec import LIMB_BITS, NUM_LIMBS
from .context import AssignedValue, Context
from .range_chip import RangeChip

R = bn254.R
BASE = 1 << LIMB_BITS


@dataclass
class CrtUint:
    """limbs: NUM_LIMBS cells (< 2^LIMB_BITS each); native: value mod r;
    value: the integer (witness bookkeeping)."""

    limbs: list
    native: AssignedValue
    value: int


@dataclass
class OverflowInt:
    """Lazily-reduced big integer: limb cells whose SIGNED values may exceed
    LIMB_BITS (products, sums, differences accumulated without carrying).
    halo2-ecc's CRTInteger-with-overflow role: the pairing tower does many
    no-carry ops per single carry_mod, which is where non-native field
    arithmetic gets its constraint budget back.

    value: exact signed integer; limb_abs: bound on each |limb| (signed
    interpretation); val_abs: bound on |value| (sizes the reduction
    quotient)."""

    limbs: list
    value: int
    limb_abs: int
    val_abs: int


class BigUintChip:
    """num_limbs x limb_bits CRT bigint chip. Defaults match the reference's
    BLS12-381-over-BN254 shape (5 x 104, `eth-types/src/lib.rs:12-16`); the
    aggregation layer instantiates 3 x 88 for BN254 Fq (the reference
    accumulator's limb encoding, snark-verifier `LimbsEncoding<3, 88>`)."""

    def __init__(self, rng: RangeChip, num_limbs: int = NUM_LIMBS,
                 limb_bits: int = LIMB_BITS):
        self.rng = rng
        self.gate = rng.gate
        self.num_limbs = num_limbs
        self.limb_bits = limb_bits
        self.base = 1 << limb_bits
        self._pow_native = [pow(self.base, i, R) for i in range(2 * num_limbs + 2)]

    # -- construction ---------------------------------------------------
    def load(self, ctx: Context, value: int, max_bits: int | None = None) -> CrtUint:
        value = int(value)
        assert value >= 0
        max_bits = max_bits or self.num_limbs * self.limb_bits
        assert max_bits <= self.num_limbs * self.limb_bits, \
            "value exceeds limb capacity — pick a wider num_limbs/limb_bits"
        assert value < (1 << max_bits)
        limb_vals = [(value >> (self.limb_bits * i)) & (self.base - 1)
                     for i in range(self.num_limbs)]
        start = ctx.bulk_cells(limb_vals)
        limbs = [AssignedValue("adv", start + i, v)
                 for i, v in enumerate(limb_vals)]
        for i, limb in enumerate(limbs):
            bits = min(self.limb_bits, max(max_bits - self.limb_bits * i, 0))
            if bits == 0:
                ctx.constrain_constant(limb, 0)
            else:
                self.rng.range_check(ctx, limb, bits)
        native = self.gate.inner_product_const(
            ctx, limbs, self._pow_native[:self.num_limbs])
        return CrtUint(limbs, native, value)

    def load_constant(self, ctx: Context, value: int) -> CrtUint:
        assert 0 <= value < (1 << (self.num_limbs * self.limb_bits)), \
            "constant exceeds limb capacity — pick a wider num_limbs/limb_bits"
        limbs = [ctx.load_constant((value >> (self.limb_bits * i)) & (self.base - 1))
                 for i in range(self.num_limbs)]
        native = self.gate.inner_product_const(
            ctx, limbs, self._pow_native[:self.num_limbs])
        return CrtUint(limbs, native, int(value))

    # -- arithmetic (lazy: no reduction) --------------------------------
    def add_no_carry(self, ctx: Context, a: CrtUint, b: CrtUint) -> CrtUint:
        limbs = [self.gate.add(ctx, x, y) for x, y in zip(a.limbs, b.limbs)]
        native = self.gate.add(ctx, a.native, b.native)
        return CrtUint(limbs, native, a.value + b.value)

    def mul_no_carry(self, ctx: Context, a: CrtUint, b: CrtUint) -> list:
        """Limb convolution: returns 2*num_limbs-1 product-limb cells (each up
        to ~2^(2*limb_bits + log num_limbs) — still < r)."""
        NUM_LIMBS = self.num_limbs
        out = []
        for k in range(2 * NUM_LIMBS - 1):
            terms_a, terms_b = [], []
            for i in range(max(0, k - NUM_LIMBS + 1), min(NUM_LIMBS, k + 1)):
                terms_a.append(a.limbs[i])
                terms_b.append(b.limbs[k - i])
            out.append(self.gate.inner_product(ctx, terms_a, terms_b))
        return out

    # -- lazy (no-carry) arithmetic on OverflowInt ----------------------
    def to_overflow(self, a, val_bits: int | None = None) -> OverflowInt:
        if isinstance(a, OverflowInt):
            return a
        val_bits = val_bits or self.num_limbs * self.limb_bits
        return OverflowInt(list(a.limbs), a.value, self.base - 1, 1 << val_bits)

    def mul_ovf(self, ctx: Context, a, b,
                val_bits: int | None = None) -> OverflowInt:
        """Product as overflowed limbs (no reduction). a, b: CrtUint or
        OverflowInt. val_bits bounds each CrtUint operand's |value| — pass
        the tight field bound (e.g. 381 for reduced Fq elements): the
        reduction quotient is sized from it, and the 5-limb quotient caps
        honest accumulations at |value| < ~2^515."""
        val_bits = val_bits or self.num_limbs * self.limb_bits
        xa, xb = self.to_overflow(a, val_bits), self.to_overflow(b, val_bits)
        la, lb = len(xa.limbs), len(xb.limbs)
        out = []
        for k in range(la + lb - 1):
            terms_a, terms_b = [], []
            for i in range(max(0, k - lb + 1), min(la, k + 1)):
                terms_a.append(xa.limbs[i])
                terms_b.append(xb.limbs[k - i])
            out.append(self.gate.inner_product(ctx, terms_a, terms_b))
        return OverflowInt(out, xa.value * xb.value,
                           min(la, lb) * xa.limb_abs * xb.limb_abs,
                           xa.val_abs * xb.val_abs)

    def mul_ovf_const(self, ctx: Context, a, k: int,
                      val_bits: int | None = None) -> OverflowInt:
        """Product with a non-negative host constant, as a constant-limb
        convolution (inner_product_const — no witness cells for k)."""
        assert k >= 0
        BASE, LIMB_BITS = self.base, self.limb_bits
        val_bits = val_bits or self.num_limbs * self.limb_bits
        xa = self.to_overflow(a, val_bits)
        if k == 0:
            zero = ctx.load_constant(0)
            return OverflowInt([zero], 0, 0, 1)
        k_limbs = []
        rem = k
        while rem:
            k_limbs.append(rem & (BASE - 1))
            rem >>= LIMB_BITS
        la, lb = len(xa.limbs), len(k_limbs)
        out = []
        for kk in range(la + lb - 1):
            terms, consts = [], []
            for i in range(max(0, kk - lb + 1), min(la, kk + 1)):
                terms.append(xa.limbs[i])
                consts.append(k_limbs[kk - i])
            out.append(self.gate.inner_product_const(ctx, terms, consts))
        return OverflowInt(out, xa.value * k,
                           min(la, lb) * xa.limb_abs * (BASE - 1),
                           xa.val_abs * k)

    def const_ovf(self, ctx: Context, k: int) -> OverflowInt:
        """A small non-negative host constant as a single-limb OverflowInt
        (centralizes the limb_abs/val_abs bounds)."""
        assert 0 <= k < self.base
        return OverflowInt([ctx.load_constant(k)], k, k, k + 1)

    def add_ovf(self, ctx: Context, x: OverflowInt, y: OverflowInt) -> OverflowInt:
        gate = self.gate
        nc = min(len(x.limbs), len(y.limbs))
        added = gate.add_pairs(ctx, zip(x.limbs[:nc], y.limbs[:nc]))
        limbs = added + x.limbs[nc:] + y.limbs[nc:]
        return OverflowInt(limbs, x.value + y.value,
                           x.limb_abs + y.limb_abs, x.val_abs + y.val_abs)

    def sub_ovf(self, ctx: Context, x: OverflowInt, y: OverflowInt) -> OverflowInt:
        gate = self.gate
        nc = min(len(x.limbs), len(y.limbs))
        subbed = gate.sub_pairs(ctx, zip(x.limbs[:nc], y.limbs[:nc]))
        tail = (x.limbs[nc:] if len(x.limbs) >= len(y.limbs)
                else gate.sub_pairs(ctx, ((0, l) for l in y.limbs[nc:])))
        return OverflowInt(subbed + tail, x.value - y.value,
                           x.limb_abs + y.limb_abs, x.val_abs + y.val_abs)

    def scale_ovf(self, ctx: Context, x: OverflowInt, c: int) -> OverflowInt:
        """Multiply by a small non-negative host constant."""
        assert c >= 0
        gate = self.gate
        limbs = [gate.mul(ctx, l, c) for l in x.limbs]
        return OverflowInt(limbs, x.value * c, x.limb_abs * c, x.val_abs * c)

    def carry_mod_ovf(self, ctx: Context, x: OverflowInt, p: int) -> CrtUint:
        """Reduce an OverflowInt to a canonical-width CrtUint mod p. Handles
        negative values by first adding a constant multiple of p (limb-wise
        constant adds), then runs the usual CRT carry chain with carry widths
        sized from the tracked limb bound."""
        return self._reduce_ovf(ctx, x, p, with_remainder=True)

    def assert_zero_mod(self, ctx: Context, x: OverflowInt, p: int):
        """Constrain x ≡ 0 (mod p) for a (possibly negative) OverflowInt with
        a quotient-only identity (x + k·p = q·p) — no remainder witness, no
        remainder range checks. The lazy-EC workhorse (λ·dx - dy ≡ 0, etc.)."""
        assert x.value % p == 0, "assert_zero_mod: witness not divisible"
        self._reduce_ovf(ctx, x, p, with_remainder=False)

    def _reduce_ovf(self, ctx: Context, x: OverflowInt, p: int,
                    with_remainder: bool):
        gate = self.gate
        NUM_LIMBS, LIMB_BITS, BASE = self.num_limbs, self.limb_bits, self.base
        limbs, value = list(x.limbs), x.value
        limb_abs = x.limb_abs
        assert abs(value) <= x.val_abs, "OverflowInt value bound violated"
        # shift by k*p >= val_abs so the quotient is non-negative for any
        # honest value (constant limb adds; constraints unchanged in kind)
        k = (x.val_abs + p - 1) // p
        shift = k * p
        s_limbs = []
        rem = shift
        nl = max(len(limbs), NUM_LIMBS)
        for i in range(nl - 1):
            s_limbs.append(rem & (BASE - 1))
            rem >>= LIMB_BITS
        s_limbs.append(rem)   # top limb takes the remainder (constant)
        while len(limbs) < len(s_limbs):
            limbs.append(ctx.load_constant(0))
        for i, sv in enumerate(s_limbs):
            if sv:
                limbs[i] = gate.add(ctx, limbs[i], sv % R)
        value = value + shift
        limb_abs = limb_abs + max(s_limbs)
        assert value >= 0
        q_val, r_val = divmod(value, p)
        # q <= (val_abs + shift)/p < 2*val_abs/p + 1
        q_bits = max((x.val_abs * 2).bit_length() - p.bit_length() + 1, 8)
        assert q_bits <= NUM_LIMBS * LIMB_BITS, \
            "OverflowInt accumulation too large for the limb-width quotient — " \
            "reduce earlier or tighten val_bits"
        assert q_val < (1 << q_bits)
        q = self.load(ctx, q_val, max_bits=q_bits)
        r = (self.load(ctx, r_val, max_bits=p.bit_length())
             if with_remainder else None)
        assert with_remainder or r_val == 0

        ntot = max(len(limbs), 2 * NUM_LIMBS - 1)
        qp_limbs = self._qp_identity(ctx, q, p)
        zero = None
        while len(limbs) < ntot:
            zero = zero or ctx.load_constant(0)
            limbs.append(zero)
        while len(qp_limbs) < ntot:
            zero = zero or ctx.load_constant(0)
            qp_limbs.append(zero)
        self._native_zero(ctx, limbs, qp_limbs, r)

        assert len(limbs) <= 2 * NUM_LIMBS - 1, "too many overflow limbs"
        # limb-radix identity with carry widths sized from the limb bound
        qp_abs = NUM_LIMBS * (BASE - 1) ** 2
        max_t = limb_abs + qp_abs + BASE
        carry_bits = max(max_t.bit_length() - LIMB_BITS + 1, 2)
        # no mod-R wraparound in the chain: t + carry + offset*BASE must
        # stay far below R
        assert carry_bits + 2 + LIMB_BITS < 250, "overflow limbs too wide"
        t_vals = [_signed(_val_of(limbs[k])) - _signed(_val_of(qp_limbs[k]))
                  for k in range(ntot)]
        t_cells = gate.sub_pairs(ctx, zip(limbs, qp_limbs))
        if r is not None:
            for k in range(NUM_LIMBS):
                t_vals[k] -= r.limbs[k].value
            t_cells[:NUM_LIMBS] = gate.sub_pairs(
                ctx, zip(t_cells[:NUM_LIMBS], r.limbs))
        self._carry_chain_zero(ctx, t_cells, t_vals, carry_bits=carry_bits)
        return r

    # -- the CRT reduction ---------------------------------------------
    def carry_mod(self, ctx: Context, prod_limbs: list, prod_value: int,
                  p: int) -> CrtUint:
        """Given overflowed limbs representing X (an integer < ~L*2^(2*104+3)),
        witness q, r with X = q*p + r, 0 <= r < p; constrain the identity
        (a) mod r via natives and (b) over the limb radix via a carry chain
        with range-checked carries. Returns r as a CrtUint."""
        gate = self.gate
        NUM_LIMBS = self.num_limbs
        q_val, r_val = divmod(prod_value, p)
        q = self.load(ctx, q_val, max_bits=p.bit_length() + 8)
        r = self.load(ctx, r_val, max_bits=p.bit_length())

        # (a) q*p convolution + native identity: X - q*p - r == 0 (mod r)
        qp_limbs = self._qp_identity(ctx, q, p)
        self._native_zero(ctx, prod_limbs, qp_limbs, r)

        # (b) limb-radix identity via carries:
        #     t_k = X_k - (qp)_k - r_k ;  t_k + c_{k-1} = c_k * 2^LIMB_BITS
        # carries are signed; witness c_k + OFFSET to range-check unsigned.
        nlimbs_tot = 2 * NUM_LIMBS - 1
        t_vals = [_signed(_val_of(prod_limbs[k])) - _signed(_val_of(qp_limbs[k]))
                  - (r.limbs[k].value if k < NUM_LIMBS else 0)
                  for k in range(nlimbs_tot)]
        t_cells = gate.sub_pairs(ctx, zip(prod_limbs, qp_limbs))
        t_cells[:NUM_LIMBS] = gate.sub_pairs(
            ctx, zip(t_cells[:NUM_LIMBS], r.limbs))
        self._carry_chain_zero(ctx, t_cells, t_vals)
        return r

    def _qp_identity(self, ctx: Context, q: CrtUint, p: int):
        """The q*p constant-limb convolution (shared by every reduction)."""
        gate = self.gate
        NUM_LIMBS, LIMB_BITS, BASE = self.num_limbs, self.limb_bits, self.base
        p_limbs = [(p >> (LIMB_BITS * i)) & (BASE - 1) for i in range(NUM_LIMBS)]
        qp_limbs = []
        for k in range(2 * NUM_LIMBS - 1):
            terms, consts = [], []
            for i in range(max(0, k - NUM_LIMBS + 1), min(NUM_LIMBS, k + 1)):
                terms.append(q.limbs[i])
                consts.append(p_limbs[k - i])
            qp_limbs.append(gate.inner_product_const(ctx, terms, consts))
        return qp_limbs

    def _native_zero(self, ctx: Context, x_limbs: list, qp_limbs: list,
                     r: CrtUint | None):
        """Constrain sum(x)*B^k - sum(qp)*B^k - r == 0 (mod native r)."""
        gate = self.gate
        x_native = gate.inner_product_const(
            ctx, x_limbs, self._pow_native[:len(x_limbs)])
        qp_native = gate.inner_product_const(
            ctx, qp_limbs, self._pow_native[:len(qp_limbs)])
        lhs = gate.sub(ctx, x_native, qp_native)
        if r is not None:
            lhs = gate.sub(ctx, lhs, r.native)
        ctx.constrain_constant(lhs, 0)

    def _carry_chain_zero(self, ctx: Context, t_cells: list, t_vals: list,
                          carry_bits: int | None = None):
        """Constrain sum_k t_k * BASE^k == 0 over the integers, given limb
        cells t_k with |t_k| < ~2^(LIMB_BITS + carry_bits). Carries are signed;
        each is witnessed as c_k = carry_k + offset so a single unsigned range
        check bounds it, and each chain link is ONE fused gate unit:
          k=0:  t_0 + offset*BASE - c_0*BASE == 0
          k>0:  (t_k + c_{k-1}) + (offset*BASE - offset) - c_k*BASE == 0
        (the k>0 sum takes one extra add unit), with the final carry pinned
        via c_last == offset."""
        BASE = self.base
        if carry_bits is None:
            carry_bits = self.limb_bits + self.num_limbs.bit_length() + 2
        offset = 1 << (carry_bits + 1)
        # witness all carry cells upfront (one splittable record)
        c_vals = []
        carry_prev_val = 0
        for tv in t_vals:
            total = tv + carry_prev_val
            assert total % BASE == 0, "carry chain misaligned"
            c_val = total // BASE
            assert abs(c_val) < offset
            c_vals.append(c_val + offset)
            carry_prev_val = c_val
        cstart = ctx.bulk_cells(c_vals)
        c_cells = [AssignedValue("adv", cstart + i, v)
                   for i, v in enumerate(c_vals)]
        for c in c_cells:
            self.rng.range_check(ctx, c, carry_bits + 2)
        # fused chain links
        copies = ctx.copies
        pin = ctx.pin_const
        pos = len(ctx.adv_values)
        flat = []
        neg_base = (-BASE) % R
        k0_const = (offset * BASE) % R
        kk_const = (offset * BASE - offset) % R
        neg_kk = (offset - offset * BASE) % R
        for k, (t, cv) in enumerate(zip(t_cells, c_vals)):
            if k == 0:
                # [t_0, c_0, -BASE, -(offset*BASE)]: t0 + c0*(-BASE) + oB == 0
                copies.append((("adv", t.index), ("adv", pos)))
                copies.append((("adv", cstart), ("adv", pos + 1)))
                pin(pos + 2, neg_base)
                pin(pos + 3, (-k0_const) % R)
                flat.append(t.value), flat.append(cv), flat.append(neg_base), \
                    flat.append((-k0_const) % R)
                pos += 4
            else:
                # s = t_k + c_{k-1}
                sv = (t.value + c_vals[k - 1]) % R
                copies.append((("adv", t.index), ("adv", pos)))
                copies.append((("adv", cstart + k - 1), ("adv", pos + 1)))
                pin(pos + 2, 1)
                flat.append(t.value), flat.append(c_vals[k - 1]), \
                    flat.append(1), flat.append(sv)
                # [s, c_k, -BASE, -(oB - offset)]: s + kk_const - c_k*BASE == 0
                copies.append((("adv", pos + 3), ("adv", pos + 4)))
                copies.append((("adv", cstart + k), ("adv", pos + 5)))
                pin(pos + 6, neg_base)
                pin(pos + 7, neg_kk)
                flat.append(sv), flat.append(cv), flat.append(neg_base), \
                    flat.append(neg_kk)
                pos += 8
        ctx.bulk_gated(flat)
        # final carry must be zero: c_last == offset
        ctx.constrain_constant(c_cells[-1], offset % R)

    def check_carry_to_zero(self, ctx: Context, prod_limbs: list,
                            prod_value: int, p: int):
        """Constrain X == 0 (mod p) for overflowed limbs X: witness q with
        X = q*p exactly, constrain natively and over the limb radix. The
        mod-p analog of halo2-ecc `check_carry_mod_to_zero`."""
        gate = self.gate
        assert prod_value % p == 0, "check_carry_to_zero: value not divisible"
        q_val = prod_value // p
        # same static shape as carry_mod's quotient (shape must not depend on
        # the witness): products of reduced operands give q < ~L * 2^(2*104) / p
        q = self.load(ctx, q_val, max_bits=p.bit_length() + 8)
        qp_limbs = self._qp_identity(ctx, q, p)
        self._native_zero(ctx, prod_limbs, qp_limbs, None)
        t_vals = [_signed(_val_of(prod_limbs[k])) - _signed(_val_of(qp_limbs[k]))
                  for k in range(2 * self.num_limbs - 1)]
        t_cells = gate.sub_pairs(ctx, zip(prod_limbs, qp_limbs))
        self._carry_chain_zero(ctx, t_cells, t_vals)

    def enforce_lt(self, ctx: Context, a: CrtUint, bound: int):
        """Constrain a < bound (a compile-time constant) exactly, not just by
        limb width: witness d = bound-1-a, range-check d's limbs, and tie
        a + d == bound-1 over the limb radix. halo2-ecc ProperCrtUint's
        canonicality check (`ADVICE.md` bigint.py finding)."""
        gate = self.gate
        NUM_LIMBS, LIMB_BITS, BASE = self.num_limbs, self.limb_bits, self.base
        m = bound - 1
        assert 0 <= a.value <= m, "enforce_lt: witness out of range"
        d = self.load(ctx, m - a.value, max_bits=bound.bit_length())
        m_limbs = [(m >> (LIMB_BITS * i)) & (BASE - 1) for i in range(NUM_LIMBS)]
        t_cells, t_vals = [], []
        for k in range(NUM_LIMBS):
            t = gate.add(ctx, a.limbs[k], d.limbs[k])
            t_cells.append(gate.sub(ctx, t, m_limbs[k]))
            t_vals.append(a.limbs[k].value + d.limbs[k].value - m_limbs[k])
        # sums of two limbs minus a limb: carries fit in 2 bits
        self._carry_chain_zero(ctx, t_cells, t_vals, carry_bits=2)


def _val_of(cell) -> int:
    return cell.value


def _signed(v: int) -> int:
    """Interpret a mod-r value produced by gate.sub as a (small) signed int."""
    return v if v < R // 2 else v - R

"""Virtual circuit builder: the halo2-lib equivalent layer.

Reference parity (SURVEY.md L2): halo2-base's `BaseCircuitBuilder` / `Context` /
`GateChip` / `RangeChip` — circuit logic appends virtual cells to streams; a
finalize pass lays streams out across physical columns (the break-point
system), producing a plonk.Assignment. App circuits (models/) are written
against these chips, never against raw columns.
"""

from .context import AssignedValue, Context  # noqa: F401
from .gate import GateChip  # noqa: F401
from .range_chip import RangeChip  # noqa: F401

"""In-circuit multi-scalar multiplication over BN254 G1 (non-native Fq).

Reference parity: snark-verifier's in-circuit accumulator MSM — the heart of
`AggregationCircuit` (`aggregation_circuit.rs:69-124` drives it through the
SDK; the MSM itself lives in snark-verifier's `EccInstructions` usage). This
is a ground-up TPU-era redesign of the same role: fixed 4-bit windows, one
shared doubling chain for all witness points, host-precomputed tables for
vk-constant points, and offset points so the incomplete (strict chord)
addition formulas never meet the identity.

Correctness argument for the offsets: every addition is a constrained chord
add (x1 != x2 enforced), so the loop computes exactly

    acc = 16^63*C + sum_i k_i*P_i + (sum_j 16^j) * sum_i Q_i      (witness)
    acc2 =            sum_i k'_i*P'_i + 64 * sum_i Q'_ij          (constant)

for ANY satisfying witness; the known constant correction D is subtracted at
the end. Offsets only affect completeness: an honest run fails (negligibly)
iff some intermediate x-coordinates collide; soundness needs no independence
assumption on the offsets because nothing is left unconstrained.

Scalar decomposition: bits are witnessed and recombined mod r. A non-canonical
decomposition (s + r) yields the same group element because |G1| = r exactly
(cofactor 1), so canonicality of the split is not required for soundness.
"""

from __future__ import annotations

import hashlib

from ..fields import bn254
from ..fields.common import tonelli_shanks
from .context import AssignedValue, Context
from .fp_chip import EccChip, FpChip

R = bn254.R
P = bn254.P
WINDOW = 4
NBITS = 256                      # 64 windows of 4 bits
NWINDOWS = NBITS // WINDOW


def deterministic_point(tag: bytes):
    """Nothing-up-my-sleeve BN254 G1 point: try-and-increment from a hash.
    Used for the MSM offset points (completeness only; see module doc)."""
    x = int.from_bytes(hashlib.blake2b(b"spectre-tpu-msm/" + tag,
                                       digest_size=32).digest(), "big") % P
    while True:
        rhs = (x * x % P * x + 3) % P
        y = tonelli_shanks(rhs, P)
        if y is not None:
            # Fq-wrapped: curve-group ops on plain ints silently skip
            # the modular reduction
            return (bn254.Fq(x), bn254.Fq(min(y, P - y)))
        x = (x + 1) % P


class MsmChip:
    def __init__(self, ecc: EccChip):
        assert ecc.fp.p == P and ecc.b == 3, "MsmChip is BN254-G1 specific"
        self.ecc = ecc
        self.fp: FpChip = ecc.fp
        self.gate = ecc.fp.gate

    # -- scalar windows ---------------------------------------------------
    def _windows(self, ctx: Context, scalar: AssignedValue) -> list:
        """256 bit cells, grouped MSB-window-first: [(b3,b2,b1,b0), ...]."""
        bits = self.gate.num_to_bits(ctx, scalar, NBITS - 2)  # 254-bit field
        zero = ctx.load_constant(0)
        bits = bits + [zero, zero]  # pad to 256
        wins = []
        for j in range(NWINDOWS - 1, -1, -1):
            chunk = bits[j * WINDOW:(j + 1) * WINDOW]
            wins.append(chunk)  # LSB-first within the window
        return wins

    def _select16(self, ctx: Context, table: list, bits4: list):
        """Binary select tree over 16 (x, y) CrtUint pairs; bits LSB-first."""
        ecc = self.ecc
        level = table
        for b in bits4:
            level = [ecc.select(ctx, b, level[2 * i + 1], level[2 * i])
                     for i in range(len(level) // 2)]
        return level[0]

    def _onehot16(self, ctx: Context, bits4: list) -> list:
        """One-hot 16-vector of cells from 4 bit cells (LSB-first)."""
        gate = self.gate
        one = ctx.load_constant(1)
        level = [one]
        for b in bits4:
            nb = gate.not_(ctx, b)
            nxt = []
            for cell in level:
                nxt.append(gate.mul(ctx, cell, nb))
            for cell in level:
                nxt.append(gate.mul(ctx, cell, b))
            level = nxt
        return level

    def _const_entry(self, ctx: Context, onehot: list, pts: list):
        """Inner-product a one-hot selector against 16 CONSTANT points,
        returning the selected point as a CrtUint pair (limbs constrained by
        the one-hot linear combination — exact because the one-hot is 0/1
        cells and the constants are canonical)."""
        fp = self.fp
        nl, lb = fp.big.num_limbs, fp.big.limb_bits
        xs, ys = [int(p[0]) for p in pts], [int(p[1]) for p in pts]
        out = []
        for coords in (xs, ys):
            limbs = []
            for li in range(nl):
                consts = [(c >> (lb * li)) & ((1 << lb) - 1) for c in coords]
                limbs.append(self.gate.inner_product_const(ctx, onehot, consts))
            sel = 0
            for i, c in enumerate(coords):
                if onehot[i].value:
                    sel = c
            out.append(fp.from_limbs(ctx, limbs, sel))
        return (out[0], out[1])

    # -- the MSM ----------------------------------------------------------
    def msm(self, ctx: Context, witness_pairs: list, constant_pairs: list):
        """sum of scalar*point over witness_pairs [(point_cells, scalar_cell)]
        and constant_pairs [(host_point, scalar_cell)]. Returns point cells.

        witness point_cells: ((x CrtUint, y CrtUint)) already on-curve
        constrained by the caller (load via EccChip.load_point or equivalent).
        """
        ecc, fp, gate = self.ecc, self.fp, self.gate
        g1 = bn254.g1_curve

        # --- witness part: shared doubling chain ---
        c0_host = deterministic_point(b"acc-init")
        tables = []
        offsets = []
        for i, (pt, _s) in enumerate(witness_pairs):
            q_host = deterministic_point(b"witness-%d" % i)
            q = fp.load_constant_point(ctx, q_host)
            entries = [q]
            for w in range(1, 16):
                entries.append(ecc.add_unequal_lazy(ctx, entries[-1], pt))
            tables.append(entries)
            offsets.append(q_host)

        win_bits = [self._windows(ctx, s) for (_p, s) in witness_pairs]

        acc = fp.load_constant_point(ctx, c0_host)
        for j in range(NWINDOWS):
            if j:
                for _ in range(WINDOW):
                    acc = ecc.double_lazy(ctx, acc)
            for i in range(len(witness_pairs)):
                entry = self._select16(ctx, tables[i], win_bits[i][j])
                acc = ecc.add_unequal_lazy(ctx, acc, entry)

        # host-side correction for the witness part:
        # acc = 16^63*C0 + sum k_i P_i + (sum_j 16^j) * sum Q_i
        d = g1.mul(c0_host, pow(16, NWINDOWS - 1, R))
        geom = sum(pow(16, j, R) for j in range(NWINDOWS)) % R
        for q_host in offsets:
            d = g1.add(d, g1.mul(q_host, geom))

        # --- constant part: host-precomputed scaled tables, no doublings ---
        for i, (pt_host, s) in enumerate(constant_pairs):
            wins = self._windows(ctx, s)
            q_host = deterministic_point(b"const-%d" % i)
            for j in range(NWINDOWS):
                # window j (MSB-first in wins) covers exponent 16^(NW-1-j)
                scale = pow(16, NWINDOWS - 1 - j, R)
                base = g1.mul(pt_host, scale)
                entries = [q_host]
                for w in range(1, 16):
                    entries.append(g1.add(entries[-1], base))
                onehot = self._onehot16(ctx, wins[j])
                entry = self._const_entry(ctx, onehot, entries)
                acc = ecc.add_unequal_lazy(ctx, acc, entry)
                d = g1.add(d, q_host)

        # --- subtract the known correction D ---
        neg_d = (int(d[0]), (P - int(d[1])) % P)
        nd = fp.load_constant_point(ctx, neg_d)
        acc = ecc.add_unequal_lazy(ctx, acc, nd)
        return acc

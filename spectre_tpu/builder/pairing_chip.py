"""In-circuit BLS12-381 pairing: multi-Miller loop + final exponentiation.

Reference parity: halo2-ecc `PairingChip` / `BlsSignatureChip`
(`sync_step_circuit.rs:171` `assert_valid_signature` — the single largest
constraint block of the reference StepCircuit, SURVEY.md §3.3 step 5).

Design notes (TPU-first means constraint-count-first here):
- Affine Miller loop over the TWISTED coordinates with witnessed slopes
  (div_unsafe + the chord/tangent constraint); untwisting is folded into the
  line's w-slot placement: l = xi*y_P + (lam*x_T - y_T) w^3 - lam*x_P w^5
  (the xi scaling lies in Fq2 (a subfield), killed by the final
  exponentiation, so it is sound to fold).
- Lines are 3-sparse in the w-basis -> `Fp12Chip.mul_sparse_035` (18 Fq2
  products instead of 36).
- Final exponentiation: easy part (conj/inv, frobenius^2) then the hard part
  via the BLS12 chain for the 3x exponent identity
      3*(p^4 - p^2 + 1)/r = 3 + (x-1)^2 (x+p) (x^2 + p^2 - 1)
  (host-validated in tests; the 3x multiple is sound for an ==1 check since
  cubing is a bijection on the order-r roots of unity).
- Signature soundness: adds a psi-endomorphism G2 subgroup check
  (psi(Q) == [x]Q) on the assigned signature so low-order points cannot hit
  the T == +-Q degenerate chord cases mid-loop.
"""

from __future__ import annotations

from ..fields import bls12_381 as bls
from .context import Context
from .fp2_chip import Fp2Chip, G2Chip
from .fp12_chip import Fp12Chip

P = bls.P
ABS_X_BITS = bin(-bls.BLS_X)[2:]   # |x| = 0xd201000000010000, MSB first


class PairingChip:
    def __init__(self, fp12: Fp12Chip):
        self.fp12 = fp12
        self.fp2 = fp12.fp2
        self.lz = fp12.lazy
        self.g2 = G2Chip(self.fp2)

    # -- line construction ---------------------------------------------
    def _line(self, ctx: Context, lam, t_pt, p_pt) -> tuple:
        """Sparse line coefficients (c0, c3, c5) for the line of slope lam
        through T (twisted coords), evaluated at P = (x_p, y_p) in G1."""
        lz = self.lz
        x_t, y_t = t_pt
        x_p, y_p = p_pt
        c0 = (y_p, y_p)                               # xi * y_P = y_P(1 + u)
        c3 = lz.reduce(ctx, lz.sub(ctx, lz.mul(ctx, lam, x_t),
                                   lz.lift(ctx, y_t)))
        c5 = lz.reduce(ctx, lz.neg(ctx, lz.mul_by_fq_cell(ctx, lam, x_p)))
        return c0, c3, c5

    def _double_step(self, ctx: Context, t_pt) -> tuple:
        """(2T, tangent slope): 2·(λ·y) ≡ 3x² constrained lazily
        (G2Chip.double_core)."""
        return self.g2.double_core(ctx, t_pt)

    def _add_step(self, ctx: Context, t_pt, q_pt, strict: bool = True) -> tuple:
        """(T+Q, chord slope; G2Chip.add_core). strict constrains
        x_T != x_Q; pass False only where T is itself fully
        constraint-determined (e.g. deterministic ladders over a pinned
        input), where dx != 0 as witnessed values already pins the slope
        uniquely."""
        return self.g2.add_core(ctx, t_pt, q_pt, strict=strict)

    def _sparse_to_fp12(self, ctx: Context, c0, c3, c5) -> tuple:
        zero = self.fp2.load_constant(ctx, (0, 0))
        return (c0, zero, zero, c3, zero, c5)

    # -- Miller loop ----------------------------------------------------
    def multi_miller_loop(self, ctx: Context, pairs) -> tuple:
        """pairs: [(P, Q)] with P = (x, y) G1 CrtUints (from
        EccChip.load_point) and Q a G2 point (from G2Chip.load_point).
        Returns f (Fp12 element, conjugated for the negative x)."""
        fp12 = self.fp12
        ts = [q for (_p, q) in pairs]
        f = None
        for bit in ABS_X_BITS[1:]:
            if f is not None:
                f = fp12.square(ctx, f)
            for i, (p_pt, q_pt) in enumerate(pairs):
                t2, lam = self._double_step(ctx, ts[i])
                c0, c3, c5 = self._line(ctx, lam, ts[i], p_pt)
                if f is None:
                    f = self._sparse_to_fp12(ctx, c0, c3, c5)
                else:
                    f = fp12.mul_sparse_035(ctx, f, c0, c3, c5)
                ts[i] = t2
            if bit == "1":
                for i, (p_pt, q_pt) in enumerate(pairs):
                    t2, lam = self._add_step(ctx, ts[i], q_pt)
                    c0, c3, c5 = self._line(ctx, lam, ts[i], p_pt)
                    f = fp12.mul_sparse_035(ctx, f, c0, c3, c5)
                    ts[i] = t2
        # x < 0: f_{x} ~ conj(f_{|x|}) up to final-exp-killed factors
        return fp12.conjugate(ctx, f)

    # -- final exponentiation ------------------------------------------
    def final_exponentiation(self, ctx: Context, f) -> tuple:
        fp12 = self.fp12
        # easy: f^((p^6-1)(p^2+1))
        t = fp12.mul(ctx, fp12.conjugate(ctx, f), fp12.inverse(ctx, f))
        t = fp12.mul(ctx, fp12.frobenius(ctx, t, 2), t)

        # hard (3x multiple): 3 + (x-1)^2 (x+p) (x^2+p^2-1); t is now
        # cyclotomic so inverse == conjugate, x<0 folds into conjugates,
        # and every chain square uses Granger-Scott cyclotomic squaring
        def pow_x_minus_1(u):
            # u^(x-1) = conj(u^|x| * u)
            return fp12.conjugate(ctx, fp12.mul(
                ctx, fp12.pow_abs_x(ctx, u, cyclotomic=True), u))

        a = pow_x_minus_1(t)
        a = pow_x_minus_1(a)
        b = fp12.mul(ctx, fp12.conjugate(
                         ctx, fp12.pow_abs_x(ctx, a, cyclotomic=True)),
                     fp12.frobenius(ctx, a, 1))
        bx2 = fp12.pow_abs_x(ctx, fp12.pow_abs_x(ctx, b, cyclotomic=True),
                             cyclotomic=True)
        c2 = fp12.mul(ctx, fp12.mul(ctx, bx2, fp12.frobenius(ctx, b, 2)),
                      fp12.conjugate(ctx, b))
        t3 = fp12.mul(ctx, fp12.cyclotomic_square(ctx, t), t)
        return fp12.mul(ctx, c2, t3)

    def assert_pairing_product_one(self, ctx: Context, pairs):
        """Constrain prod e(P_i, Q_i) == 1 (the BLS verification shape:
        e(pk, H(m)) * e(-g1, sig) == 1)."""
        f = self.multi_miller_loop(ctx, pairs)
        res = self.final_exponentiation(ctx, f)
        self.fp12.assert_one(ctx, res)

    # -- psi endomorphism + subgroup check ------------------------------
    def g2_psi(self, ctx: Context, q_pt) -> tuple:
        cx, cy = bls.psi_constants()
        fp2, lz = self.fp2, self.lz
        x, y = q_pt
        px = lz.reduce(ctx, lz.mul_const(ctx, fp2.conjugate(ctx, x), cx))
        py = lz.reduce(ctx, lz.mul_const(ctx, fp2.conjugate(ctx, y), cy))
        return (px, py)

    def g2_scalar_mul(self, ctx: Context, q_pt, k: int,
                      strict: bool = True) -> tuple:
        """[k]Q (k > 0) by double-and-add over the lazy point steps.
        strict=False is sound ONLY when Q is itself fully
        constraint-determined (e.g. a hash-to-curve output): there the
        witnessed dx != 0 pins every slope. For prover-chosen Q (a
        signature) keep strict: a crafted low-order Q can hit T == +-Q
        mid-ladder and an unconstrained slope would forge the rest."""
        assert k > 0
        t = q_pt
        for bit in bin(k)[3:]:
            t, _ = self._double_step(ctx, t)
            if bit == "1":
                t, _ = self._add_step(ctx, t, q_pt, strict=strict)
        return t

    def g2_scalar_mul_abs_x(self, ctx: Context, q_pt) -> tuple:
        """[|x|] Q for the subgroup check — STRICT (adversarial input)."""
        return self.g2_scalar_mul(ctx, q_pt, -bls.BLS_X, strict=True)

    def assert_g2_subgroup(self, ctx: Context, q_pt):
        """psi(Q) == [x]Q = -[|x|]Q — rejects points outside the r-order
        subgroup (soundness guard for the Miller loop's strict chords)."""
        fp2 = self.fp2
        psi_q = self.g2_psi(ctx, q_pt)
        t = self.g2_scalar_mul_abs_x(ctx, q_pt)
        neg_y = fp2.neg(ctx, t[1])
        fp2.assert_equal(ctx, psi_q[0], t[0])
        fp2.assert_equal(ctx, psi_q[1], neg_y)

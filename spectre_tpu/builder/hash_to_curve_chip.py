"""In-circuit hash-to-G2: BLS12381G2_XMD:SHA-256_SSWU_RO.

Reference parity: the halo2-lib fork's `HashToCurveChip` (SSWU +
ExpandMsgXmd; `sync_step_circuit.rs:165-169`) — the reason the reference
forks halo2-lib at all (SURVEY.md L0).

Pipeline (mirrors fields/bls12_381.py's host implementation, which is
blst-fixture-validated):
  expand_message_xmd (SHA chip; the all-constant z_pad block is folded into
  a precomputed constant state) -> hash_to_field (nibble recomposition into
  104-bit limbs + one lazy reduction per component) -> simplified SWU on E2'
  with SOUND branch selection (w^2 == gx1 * sel pins is_square(gx1); sel
  selects {1, Z} by the e1 bit and Z is a non-residue) -> Velu-derived
  3-isogeny -> strict point add -> Budroni-Pintore cofactor clearing
  (psi-endomorphism ladder; host-validated equal to the H_EFF scalar).

sgn0 uses canonicalized coordinates (enforce_lt p) so parity is
well-defined; the witnessed y is pinned by y^2 == g(x) AND
sgn0(y) == sgn0(u).
"""

from __future__ import annotations

from ..fields import bls12_381 as bls, bn254
from .bigint import BASE, LIMB_BITS, NUM_LIMBS, CrtUint, OverflowInt
from .context import AssignedValue, Context
from .pairing_chip import PairingChip
from .sha256_chip import Sha256Chip, XOR_OP

P = bls.P
R = bn254.R


class HashToCurveChip:
    def __init__(self, pairing: PairingChip, sha: Sha256Chip,
                 sha_wide=None):
        """sha: the nibble-lookup chip (XOR plumbing + nibble recomposition).
        sha_wide: optional Sha256WideChip — when present, expand_message's
        SHA compressions run in the wide bit-ladder region (~200 main cells
        per block vs ~45k in the nibble chip), with only the digest XOR mix
        and the field recomposition on nibbles."""
        self.pairing = pairing
        self.fp2 = pairing.fp2
        self.fp = self.fp2.fp
        self.lz = pairing.lz
        self.g2 = pairing.g2
        self.sha = sha
        self.sha_wide = sha_wide

    # ------------------------------------------------------------------
    # expand_message_xmd
    # ------------------------------------------------------------------
    def expand_message_xmd(self, ctx: Context, msg_bytes: list,
                           dst: bytes, len_in_bytes: int) -> list:
        """msg_bytes: 8-bit-checked byte cells. Returns len_in_bytes//32
        digests (lists of 8 Words)."""
        sha = self.sha
        assert len(dst) <= 255
        ell = (len_in_bytes + 31) // 32
        assert ell <= 255 and len_in_bytes % 32 == 0
        dst_prime = dst + bytes([len(dst)])
        lib = len_in_bytes.to_bytes(2, "big")

        # b0 = H(Z_pad(64) || msg || lib || 0x00 || dst'); the z_pad block is
        # constant, so start from its precomputed state
        state = [sha.constant_word(ctx, w) for w in _STATE_AFTER_ZERO_BLOCK]
        tail = [("v", c) for c in msg_bytes]
        tail += [("c", b) for b in lib + b"\x00" + dst_prime]
        b0 = self._digest_tail(ctx, state, tail,
                               total_len=64 + len(msg_bytes) + 3 + len(dst_prime))

        outs = []
        prev = None
        for i in range(1, ell + 1):
            if i == 1:
                first8 = b0
            else:
                first8 = []          # b0 XOR b_{i-1}, nibble-wise
                for w0, wp in zip(b0, prev):
                    nibs = sha._nib_op(ctx, XOR_OP, w0.nibs, wp.nibs)
                    first8.append(sha._recompose(ctx, nibs))
            tail = [("w", w) for w in first8]
            tail += [("c", b) for b in bytes([i]) + dst_prime]
            prev = self._digest_tail(ctx, sha.initial_state(ctx), tail,
                                     total_len=32 + 1 + len(dst_prime))
            outs.append(prev)
        return outs

    def expand_message_xmd_wide(self, ctx: Context, msg_bytes: list,
                                dst: bytes, len_in_bytes: int) -> list:
        """expand_message_xmd with the compressions in the wide SHA region.
        Digest words come back as single cells; they are nibble-decomposed
        (lookup-checked) once each, for the b0 XOR mix and the downstream
        field recomposition. Returns nibble-chip Words like the nibble
        path."""
        shaw = self.sha_wide
        sha = self.sha
        assert len(dst) <= 255
        ell = (len_in_bytes + 31) // 32
        assert ell <= 255 and len_in_bytes % 32 == 0
        dst_prime = dst + bytes([len(dst)])
        lib = len_in_bytes.to_bytes(2, "big")

        def pack_words(byte_items: list, total_len: int, skipped: int) -> list:
            """byte_items: cells ('v') or ints ('c'); pads for a message of
            total_len bytes of which `skipped` were folded into the
            midstate; packs 4 bytes -> 1 word cell."""
            stream = list(byte_items)
            blen = len(stream) + 1
            stream.append(0x80)
            while ((skipped + blen) % 64) != 56:
                stream.append(0)
                blen += 1
            stream += list((8 * total_len).to_bytes(8, "big"))
            assert (skipped + len(stream)) % 64 == 0
            words = []
            for off in range(0, len(stream), 4):
                quad = stream[off:off + 4]
                if all(isinstance(b, int) for b in quad):
                    words.append(shaw.constant_word(
                        ctx, int.from_bytes(bytes(quad), "big")))
                else:
                    cells = [b if not isinstance(b, int)
                             else ctx.load_constant(b) for b in quad]
                    words.append(shaw.word_from_bytes_be(ctx, cells))
            return words

        # b0 = H(z_pad(64) || msg || lib || 0x00 || dst'): the all-zero
        # z_pad block enters via the constant midstate
        tail = list(msg_bytes) + [int(b) for b in lib + b"\x00" + dst_prime]
        b0_words = shaw._compress_chain(
            ctx, pack_words(tail, 64 + len(msg_bytes) + 3 + len(dst_prime), 64),
            initial_state=list(_STATE_AFTER_ZERO_BLOCK))
        b0 = [sha.word_from_cell(ctx, w.cell) for w in b0_words]

        outs = []
        prev = None
        for i in range(1, ell + 1):
            if i == 1:
                first8 = b0
            else:
                first8 = []          # b0 XOR b_{i-1}, nibble-wise
                for w0, wp in zip(b0, prev):
                    nibs = sha._nib_op(ctx, XOR_OP, w0.nibs, wp.nibs)
                    first8.append(sha._recompose(ctx, nibs))
            tail = [int(b) for b in bytes([i]) + dst_prime]
            # total message = 32 (first8 words) + 1 + len(dst'); skipped=32
            # accounts for the first8 words already in the stream
            words = list(first8) + pack_words(tail, 32 + 1 + len(dst_prime), 32)
            prev_words = shaw._compress_chain(ctx, words)
            prev = [sha.word_from_cell(ctx, w.cell) for w in prev_words]
            outs.append(prev)
        return outs

    def _digest_tail(self, ctx: Context, state: list, items: list,
                     total_len: int) -> list:
        """SHA-compress a tail of items (('v', byte cell) | ('c', const
        byte) | ('w', Word)) onto state, with padding for a total message of
        total_len bytes (bytes already folded into `state` included)."""
        sha = self.sha
        stream = list(items)
        blen = sum(4 if k == "w" else 1 for k, _ in stream) + 1
        stream.append(("c", 0x80))
        while (blen % 64) != 56:
            stream.append(("c", 0))
            blen += 1
        stream += [("c", b) for b in (8 * total_len).to_bytes(8, "big")]

        words, buf = [], []
        for kind, v in stream:
            if kind == "w":
                assert not buf, "Word not 4-byte aligned in digest tail"
                words.append(v)
                continue
            buf.append((kind, v))
            if len(buf) == 4:
                if all(k == "c" for k, _ in buf):
                    words.append(sha.constant_word(
                        ctx, int.from_bytes(bytes(b for _, b in buf), "big")))
                else:
                    cells = [c if k == "v" else ctx.load_constant(c)
                             for k, c in buf]
                    words.append(sha.word_from_bytes_be(ctx, cells))
                buf = []
        assert not buf and len(words) % 16 == 0
        for off in range(0, len(words), 16):
            state = sha.compress(ctx, state, words[off:off + 16])
        return state

    # ------------------------------------------------------------------
    # hash_to_field
    # ------------------------------------------------------------------
    def _digests_to_fq(self, ctx: Context, d1: list, d2: list) -> CrtUint:
        """Two 8-Word digests = one 64-byte BE integer -> reduced mod p.
        Words carry LSB-first nibbles; ascending 4-bit positions of the BE
        value are word 15..0, nibble 0..7."""
        nibs = []
        for w in reversed(d1 + d2):
            nibs.extend(w.nibs)
        assert len(nibs) == 128
        per_limb = LIMB_BITS // 4            # 26 nibbles per 104-bit limb
        val = sum(n.value << (4 * i) for i, n in enumerate(nibs))
        limbs = []
        for j in range(NUM_LIMBS):
            chunk = nibs[j * per_limb:(j + 1) * per_limb]
            if not chunk:
                break
            limbs.append(self.fp.gate.inner_product_const(
                ctx, chunk, [1 << (4 * i) for i in range(len(chunk))]))
        x = OverflowInt(limbs, val, BASE - 1, 1 << 512)
        return self.fp.big.carry_mod_ovf(ctx, x, P)

    def hash_to_field_fq2(self, ctx: Context, msg_bytes: list,
                          dst: bytes, count: int = 2) -> list:
        expand = (self.expand_message_xmd_wide if self.sha_wide is not None
                  else self.expand_message_xmd)
        digests = expand(ctx, msg_bytes, dst, count * 128)
        return [(self._digests_to_fq(ctx, digests[4 * i], digests[4 * i + 1]),
                 self._digests_to_fq(ctx, digests[4 * i + 2], digests[4 * i + 3]))
                for i in range(count)]

    # ------------------------------------------------------------------
    # selects / zero assertions over Fq2 pairs
    # ------------------------------------------------------------------
    def _select_const_fq2(self, ctx: Context, bit, a_const, b_const) -> tuple:
        """bit ? a_const : b_const (host Fq2 constants) as a reduced pair:
        limb = b + bit*(a-b), affine in the boolean bit."""
        gate = self.fp.gate
        out = []
        for comp in range(2):
            av, bv = int(a_const.c[comp]), int(b_const.c[comp])
            limbs = []
            for i in range(NUM_LIMBS):
                al = (av >> (LIMB_BITS * i)) & (BASE - 1)
                bl = (bv >> (LIMB_BITS * i)) & (BASE - 1)
                limbs.append(gate.mul_add(ctx, bit, (al - bl) % R, bl))
            out.append(self.fp.from_limbs(
                ctx, limbs, av if bit.value else bv))
        return tuple(out)

    def _assert_zero_lazy(self, ctx: Context, pair):
        """Constrain a lazy Fq2 pair == 0 (mod p): reduce, pin r = 0."""
        for comp in pair:
            r = self.fp.big.carry_mod_ovf(ctx, comp, P)
            for limb in r.limbs:
                ctx.constrain_constant(limb, 0)

    # ------------------------------------------------------------------
    # sgn0 (RFC 9380, m = 2) over canonicalized components
    # ------------------------------------------------------------------
    def _parity_and_zero(self, ctx: Context, a: CrtUint) -> tuple:
        gate = self.fp.gate
        rng = self.fp.big.rng
        l0 = a.limbs[0]
        b = ctx.load_witness(l0.value & 1)
        gate.assert_bit(ctx, b)
        h = ctx.load_witness(l0.value >> 1)
        rng.range_check(ctx, h, LIMB_BITS - 1)
        ctx.constrain_equal(gate.mul_add(ctx, h, 2, b), l0)
        z = None
        for limb in a.limbs:
            zi = gate.is_zero(ctx, limb)
            z = zi if z is None else gate.and_(ctx, z, zi)
        return b, z

    def sgn0(self, ctx: Context, a) -> AssignedValue:
        """RFC sgn0 of a CANONICAL Fq2 pair: s0 | (z0 & s1)."""
        gate = self.fp.gate
        s0, z0 = self._parity_and_zero(ctx, a[0])
        s1, _ = self._parity_and_zero(ctx, a[1])
        return gate.or_(ctx, s0, gate.and_(ctx, z0, s1))

    def _canonical_fq2(self, ctx: Context, a) -> tuple:
        return (self.fp.canonicalize(ctx, a[0]),
                self.fp.canonicalize(ctx, a[1]))

    # ------------------------------------------------------------------
    # simplified SWU on E2' + derived 3-isogeny
    # ------------------------------------------------------------------
    def map_to_curve_g2(self, ctx: Context, u) -> tuple:
        """u: reduced Fq2 pair -> point on E2 (post-isogeny)."""
        fp2, lz = self.fp2, self.lz
        A = fp2.load_constant(ctx, bls.SSWU_A)
        B = fp2.load_constant(ctx, bls.SSWU_B)
        zconst = bls.SSWU_Z

        u_can = self._canonical_fq2(ctx, u)
        u2 = lz.reduce(ctx, lz.mul(ctx, u_can, u_can))
        zu2 = lz.reduce(ctx, lz.mul_const(ctx, u2, zconst))
        tv1 = lz.reduce(ctx, lz.add(ctx, lz.mul(ctx, zu2, zu2),
                                    lz.lift(ctx, zu2)))
        one = fp2.load_constant(ctx, (1, 0))
        inv_tv1 = fp2.div_unsafe(ctx, one, tv1)     # proves tv1 != 0 too
        neg_b_over_a = bls.Fq2([0, 0]) - (bls.SSWU_B / bls.SSWU_A)
        x1 = lz.reduce(ctx, lz.mul_const(
            ctx, fp2.add(ctx, inv_tv1, one), neg_b_over_a))

        def g_of(x):
            x2 = lz.reduce(ctx, lz.mul(ctx, x, x))
            x3 = lz.mul(ctx, x2, x)
            ax = lz.mul(ctx, A, x)
            return lz.reduce(ctx, lz.add(ctx, lz.add(ctx, x3, ax),
                                         lz.lift(ctx, B)))

        gx1 = g_of(x1)
        # branch bit e1 = is_square(gx1), pinned by w^2 == gx1 * sel with
        # sel = e1 ? 1 : Z (Z a non-residue, so the bit cannot be flipped)
        gx1_v = fp2.value(gx1)
        e1_v = gx1_v.sqrt() is not None
        e1 = ctx.load_witness(int(e1_v))
        self.fp.gate.assert_bit(ctx, e1)
        sel = self._select_const_fq2(ctx, e1, bls.Fq2([1, 0]), zconst)
        w_v = (gx1_v * fp2.value(sel)).sqrt()
        assert w_v is not None, "neither gx1 nor gx1*Z is square"
        w = fp2.load(ctx, w_v)
        self._assert_zero_lazy(ctx, lz.sub(ctx, lz.mul(ctx, w, w),
                                           lz.mul(ctx, gx1, sel)))

        x2c = lz.reduce(ctx, lz.mul(ctx, zu2, x1))
        x_sel = self.fp2.select(ctx, e1, x1, x2c)
        gx_sel = g_of(x_sel)

        # y: witnessed sign-adjusted root of g(x_sel)
        gv = fp2.value(gx_sel)
        y_v = gv.sqrt()
        assert y_v is not None, "selected branch has no root (SSWU broken)"
        uv = fp2.value(u_can)
        if uv.sgn0() != y_v.sgn0():
            y_v = bls.Fq2([0, 0]) - y_v
        y = fp2.load(ctx, y_v)
        self._assert_zero_lazy(ctx, lz.sub(ctx, lz.mul(ctx, y, y),
                                           lz.lift(ctx, gx_sel)))
        y_can = self._canonical_fq2(ctx, y)
        ctx.constrain_equal(self.sgn0(ctx, y_can), self.sgn0(ctx, u_can))

        return self._iso3(ctx, (x_sel, y_can))

    def _iso3(self, ctx: Context, pt) -> tuple:
        """The Velu-derived 3-isogeny E2' -> E2 (fields/bls12_381.py
        `iso3_map`), with the division by (x - xq) done via a witnessed
        inverse (also proving x != xq; the kernel x never occurs for hashed
        inputs)."""
        fp2, lz = self.fp2, self.lz
        xq, t, uq, _cs = bls._iso3_constants()
        c = bls._ISO3_C
        c2_const, c3_const = c * c, c * c * c
        x, y = pt
        xq_c = fp2.load_constant(ctx, xq)
        d = fp2.sub(ctx, x, xq_c)
        one = fp2.load_constant(ctx, (1, 0))
        i1 = fp2.div_unsafe(ctx, one, d)          # proves d != 0
        i2 = lz.reduce(ctx, lz.mul(ctx, i1, i1))
        i3 = lz.reduce(ctx, lz.mul(ctx, i2, i1))
        # X = c^2 (x + t*i1 + uq*i2) ; Y = c^3 y (1 - t*i2 - 2 uq*i3)
        tx = lz.mul_const(ctx, i1, t)
        ux = lz.mul_const(ctx, i2, uq)
        xs = lz.add(ctx, lz.add(ctx, tx, ux), lz.lift(ctx, x))
        xx = lz.reduce(ctx, xs)
        xx = lz.reduce(ctx, lz.mul_const(ctx, xx, c2_const))
        ti2 = lz.mul_const(ctx, i2, t)
        ui3 = lz.mul_const(ctx, i3, uq + uq)
        ys = lz.sub(ctx, lz.sub(ctx, lz.lift(ctx, one), ti2), ui3)
        yy = lz.reduce(ctx, ys)
        yy = lz.reduce(ctx, lz.mul(ctx, y, yy))
        yy = lz.reduce(ctx, lz.mul_const(ctx, yy, c3_const))
        return (xx, yy)

    # ------------------------------------------------------------------
    # cofactor clearing (Budroni–Pintore) + full hash
    # ------------------------------------------------------------------
    def clear_cofactor(self, ctx: Context, q) -> tuple:
        """BP: [x^2-x-1]Q + [x-1]psi(Q) + psi^2(2Q) == [H_EFF]Q. The input
        q is fully constraint-determined (SSWU output), so the lazy
        non-strict ladder steps pin every slope (see
        PairingChip.g2_scalar_mul)."""
        pairing = self.pairing
        x = bls.BLS_X
        a = pairing.g2_scalar_mul(ctx, q, x * x - x - 1, strict=False)
        psi_q = pairing.g2_psi(ctx, q)
        # [x-1]psi(Q) = [|x|+1] (-psi(Q))
        neg_psi = (psi_q[0], self.fp2.neg(ctx, psi_q[1]))
        b = pairing.g2_scalar_mul(ctx, neg_psi, -x + 1, strict=False)
        two_q, _ = pairing._double_step(ctx, q)
        c = pairing.g2_psi(ctx, pairing.g2_psi(ctx, two_q))
        out, _ = pairing._add_step(ctx, a, b, strict=False)
        out, _ = pairing._add_step(ctx, out, c, strict=False)
        return out

    def hash_to_g2(self, ctx: Context, msg_bytes: list,
                   dst: bytes) -> tuple:
        """Full suite: two field elements, two maps, strict add, cofactor
        clearing. The witness values are asserted equal to the host
        `bls.hash_to_g2` (blst-fixture-validated) — a built-in oracle that
        catches any drift in the chip pipeline at witness-gen time."""
        u0, u1 = self.hash_to_field_fq2(ctx, msg_bytes, dst)
        q0 = self.map_to_curve_g2(ctx, u0)
        q1 = self.map_to_curve_g2(ctx, u1)
        q = self.g2.add_unequal(ctx, q0, q1, strict=True)
        out = self.clear_cofactor(ctx, q)
        msg = bytes(c.value for c in msg_bytes)
        want = bls.hash_to_g2(msg, dst)
        got = (self.fp2.value(out[0]), self.fp2.value(out[1]))
        assert got == want, "hash_to_g2 chip drifted from the host suite"
        return out


def _sha_compress_py(state, block_bytes: bytes):
    """Minimal host SHA-256 compression (FIPS 180-4) for deriving the
    constant midstate of expand_message_xmd's all-zero z_pad block."""
    K = [
        0x428A2F98, 0x71374491, 0xB5C0FBCF, 0xE9B5DBA5, 0x3956C25B, 0x59F111F1,
        0x923F82A4, 0xAB1C5ED5, 0xD807AA98, 0x12835B01, 0x243185BE, 0x550C7DC3,
        0x72BE5D74, 0x80DEB1FE, 0x9BDC06A7, 0xC19BF174, 0xE49B69C1, 0xEFBE4786,
        0x0FC19DC6, 0x240CA1CC, 0x2DE92C6F, 0x4A7484AA, 0x5CB0A9DC, 0x76F988DA,
        0x983E5152, 0xA831C66D, 0xB00327C8, 0xBF597FC7, 0xC6E00BF3, 0xD5A79147,
        0x06CA6351, 0x14292967, 0x27B70A85, 0x2E1B2138, 0x4D2C6DFC, 0x53380D13,
        0x650A7354, 0x766A0ABB, 0x81C2C92E, 0x92722C85, 0xA2BFE8A1, 0xA81A664B,
        0xC24B8B70, 0xC76C51A3, 0xD192E819, 0xD6990624, 0xF40E3585, 0x106AA070,
        0x19A4C116, 0x1E376C08, 0x2748774C, 0x34B0BCB5, 0x391C0CB3, 0x4ED8AA4A,
        0x5B9CCA4F, 0x682E6FF3, 0x748F82EE, 0x78A5636F, 0x84C87814, 0x8CC70208,
        0x90BEFFFA, 0xA4506CEB, 0xBEF9A3F7, 0xC67178F2,
    ]
    M = 0xFFFFFFFF

    def rotr(x, r):
        return ((x >> r) | (x << (32 - r))) & M

    w = [int.from_bytes(block_bytes[4 * i:4 * i + 4], "big") for i in range(16)]
    for i in range(16, 64):
        s0 = rotr(w[i - 15], 7) ^ rotr(w[i - 15], 18) ^ (w[i - 15] >> 3)
        s1 = rotr(w[i - 2], 17) ^ rotr(w[i - 2], 19) ^ (w[i - 2] >> 10)
        w.append((w[i - 16] + s0 + w[i - 7] + s1) & M)
    a, b, c, d, e, f, g, h = state
    for i in range(64):
        s1 = rotr(e, 6) ^ rotr(e, 11) ^ rotr(e, 25)
        ch = (e & f) ^ (~e & g)
        t1 = (h + s1 + ch + K[i] + w[i]) & M
        s0 = rotr(a, 2) ^ rotr(a, 13) ^ rotr(a, 22)
        maj = (a & b) ^ (a & c) ^ (b & c)
        t2 = (s0 + maj) & M
        h, g, f, e, d, c, b, a = g, f, e, (d + t1) & M, c, b, a, (t1 + t2) & M
    return tuple((x + y) & M for x, y in zip(state, (a, b, c, d, e, f, g, h)))


_IV = (0x6A09E667, 0xBB67AE85, 0x3C6EF372, 0xA54FF53A,
       0x510E527F, 0x9B05688C, 0x1F83D9AB, 0x5BE0CD19)
_STATE_AFTER_ZERO_BLOCK = _sha_compress_py(_IV, b"\x00" * 64)
# sanity: streaming equivalence with hashlib on a two-block message
# (block 2 = 55 data bytes + 0x80 + 8-byte bit length)
import hashlib as _hl
_probe = _sha_compress_py(
    _STATE_AFTER_ZERO_BLOCK,
    b"\x01" * 55 + b"\x80" + (8 * 119).to_bytes(8, "big"))
assert b"".join(x.to_bytes(4, "big") for x in _probe) == \
    _hl.sha256(b"\x00" * 64 + b"\x01" * 55).digest(), "midstate derivation broken"

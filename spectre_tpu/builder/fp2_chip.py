"""Fp2 chip: BLS12-381 quadratic-extension arithmetic over FpChip.

Reference parity: halo2-ecc `Fp2Chip` (SURVEY.md L0) — the coordinate field of
G2 points (signatures live in G2), and with it the G2 EccChip. This is the
round-2 pairing path's next layer; landed in round 1 so the StepCircuit's
signature block can assemble on top of tested primitives.

Elements are (c0, c1) CrtUint pairs representing c0 + c1*u with u^2 = -1.
"""

from __future__ import annotations

from ..fields import bls12_381 as bls
from .context import Context
from .fp_chip import FpChip

P = bls.P


class Fp2Chip:
    def __init__(self, fp: FpChip):
        self.fp = fp
        self._lz = None

    @property
    def lz(self) -> "Fp2Lazy":
        # internal lazy engine (created on first use; Fp2Lazy(self) is just
        # two attribute grabs, the cycle is benign)
        if self._lz is None:
            self._lz = Fp2Lazy(self)
        return self._lz

    def load(self, ctx: Context, v) -> tuple:
        """v: fields.bls12_381.Fq2 or (c0, c1) ints."""
        c0, c1 = (v.c if hasattr(v, "c") else v)
        return (self.fp.load(ctx, int(c0)), self.fp.load(ctx, int(c1)))

    def load_constant(self, ctx: Context, v) -> tuple:
        c0, c1 = (v.c if hasattr(v, "c") else v)
        return (self.fp.load_constant(ctx, int(c0)),
                self.fp.load_constant(ctx, int(c1)))

    def value(self, a) -> "bls.Fq2":
        return bls.Fq2([a[0].value % P, a[1].value % P])

    def add(self, ctx: Context, a, b) -> tuple:
        return (self.fp.add(ctx, a[0], b[0]), self.fp.add(ctx, a[1], b[1]))

    def sub(self, ctx: Context, a, b) -> tuple:
        return (self.fp.sub(ctx, a[0], b[0]), self.fp.sub(ctx, a[1], b[1]))

    def mul(self, ctx: Context, a, b) -> tuple:
        """(a0 + a1 u)(b0 + b1 u) = (a0b0 - a1b1) + (a0b1 + a1b0) u.
        Runs on the lazy engine (Karatsuba: 3 limb convolutions) with one
        reduction per output coefficient."""
        lz = self.lz
        return lz.reduce(ctx, lz.mul(ctx, a, b))

    def square(self, ctx: Context, a) -> tuple:
        """(a0^2 - a1^2) + 2 a0 a1 u (complex squaring, lazy: 2 limb
        convolutions + 2 reductions)."""
        lz = self.lz
        return lz.reduce(ctx, lz.square(ctx, a))

    def mul_scalar(self, ctx: Context, a, k: int) -> tuple:
        return (self.fp.mul_scalar(ctx, a[0], k), self.fp.mul_scalar(ctx, a[1], k))

    def neg(self, ctx: Context, a) -> tuple:
        zero = self.fp.load_constant(ctx, 0)
        return (self.fp.sub(ctx, zero, a[0]), self.fp.sub(ctx, zero, a[1]))

    def conjugate(self, ctx: Context, a) -> tuple:
        zero = self.fp.load_constant(ctx, 0)
        return (a[0], self.fp.sub(ctx, zero, a[1]))

    def div_unsafe(self, ctx: Context, a, b) -> tuple:
        """q with q*b == a; witness the quotient, constrain q*b - a ≡ 0 via
        the lazy engine (3 convolutions + 2 quotient-only reductions — no
        eager product or remainder witnesses)."""
        lz = self.lz
        av, bv = self.value(a), self.value(b)
        qv = av / bv
        q = self.load(ctx, qv)
        lz.assert_zero(ctx, lz.sub(ctx, lz.mul(ctx, q, b), lz.lift(ctx, a)))
        return q

    def assert_equal(self, ctx: Context, a, b):
        self.fp.assert_equal(ctx, self.fp._reduced(ctx, a[0]),
                             self.fp._reduced(ctx, b[0]))
        self.fp.assert_equal(ctx, self.fp._reduced(ctx, a[1]),
                             self.fp._reduced(ctx, b[1]))

    def select(self, ctx: Context, bit, a, b) -> tuple:
        return (self.fp.select(ctx, bit, a[0], b[0]),
                self.fp.select(ctx, bit, a[1], b[1]))

    def assert_nonzero(self, ctx: Context, a):
        """Constrain a != 0 in Fp2 via witnessed inverse a*inv - 1 ≡ 0 (same
        soundness argument as FpChip.assert_nonzero), on the lazy engine."""
        self.lz.assert_nonzero(ctx, a)


class Fp2Lazy:
    """Lazily-reduced Fq2 arithmetic: elements are (OverflowInt, OverflowInt)
    pairs accumulated with no-carry limb ops and reduced once per output
    coefficient (halo2-ecc's FieldExtPoint-over-CRTInteger pattern — this is
    what makes the in-circuit pairing affordable: an Fp12 mul costs 12
    reductions instead of 144)."""

    FQ_BITS = 381  # reduced CrtUint elements are < 2^381

    def __init__(self, fp2: Fp2Chip):
        self.fp2 = fp2
        self.big = fp2.fp.big

    # -- entering the lazy domain --------------------------------------
    def lift(self, ctx: Context, a) -> tuple:
        """(CrtUint, CrtUint) -> (OverflowInt, OverflowInt)."""
        return (self.big.to_overflow(a[0], self.FQ_BITS),
                self.big.to_overflow(a[1], self.FQ_BITS))

    def coeff_sum(self, ctx: Context, a):
        """a0 + a1 as an OverflowInt (the Karatsuba operand sum) — hoist and
        reuse when the same element multiplies many others (Fp12 mul)."""
        big = self.big
        return big.add_ovf(ctx, big.to_overflow(a[0], self.FQ_BITS),
                           big.to_overflow(a[1], self.FQ_BITS))

    def mul(self, ctx: Context, a, b, sa=None, sb=None) -> tuple:
        """Reduced pairs -> lazy product (a0b0 - a1b1, a0b1 + a1b0),
        Karatsuba: 3 limb convolutions instead of 4. sa/sb: optional
        precomputed coeff_sum(a)/coeff_sum(b)."""
        big = self.big
        t0 = big.mul_ovf(ctx, a[0], b[0], self.FQ_BITS)
        t1 = big.mul_ovf(ctx, a[1], b[1], self.FQ_BITS)
        sa = sa if sa is not None else self.coeff_sum(ctx, a)
        sb = sb if sb is not None else self.coeff_sum(ctx, b)
        t01 = big.mul_ovf(ctx, sa, sb)
        cross = big.sub_ovf(ctx, big.sub_ovf(ctx, t01, t0), t1)
        return (big.sub_ovf(ctx, t0, t1), cross)

    def square(self, ctx: Context, a) -> tuple:
        """Complex squaring, lazy: ((a0+a1)(a0-a1), 2 a0 a1) — 2 limb
        convolutions. a: reduced pair or OverflowInt pair."""
        big = self.big
        oa0 = big.to_overflow(a[0], self.FQ_BITS)
        oa1 = big.to_overflow(a[1], self.FQ_BITS)
        s = big.add_ovf(ctx, oa0, oa1)
        d = big.sub_ovf(ctx, oa0, oa1)
        c0 = big.mul_ovf(ctx, s, d)
        a0a1 = big.mul_ovf(ctx, oa0, oa1)
        return (c0, big.scale_ovf(ctx, a0a1, 2))

    def scale(self, ctx: Context, x, k: int) -> tuple:
        """Lazy pair times a small non-negative host constant."""
        big = self.big
        return (big.scale_ovf(ctx, x[0], k), big.scale_ovf(ctx, x[1], k))

    def assert_zero(self, ctx: Context, x) -> None:
        """Constrain a lazy pair ≡ (0, 0) mod p (quotient-only reductions)."""
        big = self.big
        big.assert_zero_mod(ctx, x[0], P)
        big.assert_zero_mod(ctx, x[1], P)

    def value(self, x) -> "bls.Fq2":
        """Host value of a lazy (or reduced) pair."""
        return bls.Fq2([x[0].value % P, x[1].value % P])

    def assert_nonzero(self, ctx: Context, x) -> None:
        """Constrain a lazy pair != 0 via witnessed inverse: x*inv - 1 ≡ 0."""
        big = self.big
        v = self.value(x)
        assert v != bls.Fq2([0, 0]), "assert_nonzero: witness is zero"
        inv = self.fp2.load(ctx, bls.Fq2([1, 0]) / v)
        prod = self.mul(ctx, x, inv)
        one = big.const_ovf(ctx, 1)
        self.assert_zero(ctx, (big.sub_ovf(ctx, prod[0], one), prod[1]))

    def mul_by_fq_cell(self, ctx: Context, a, x: "CrtUint") -> tuple:
        """Fq2 pair times a base-field CrtUint cell."""
        big = self.big
        return (big.mul_ovf(ctx, a[0], x, self.FQ_BITS),
                big.mul_ovf(ctx, a[1], x, self.FQ_BITS))

    # -- lazy-domain ops ------------------------------------------------
    def add(self, ctx: Context, x, y) -> tuple:
        big = self.big
        return (big.add_ovf(ctx, x[0], y[0]), big.add_ovf(ctx, x[1], y[1]))

    def sub(self, ctx: Context, x, y) -> tuple:
        big = self.big
        return (big.sub_ovf(ctx, x[0], y[0]), big.sub_ovf(ctx, x[1], y[1]))

    def mul_const(self, ctx: Context, a, k: "bls.Fq2") -> tuple:
        """REDUCED pair times an Fq2 host constant (k0 + k1 u), via
        constant-limb convolutions: (a0k0 - a1k1, a0k1 + a1k0) lazy."""
        big = self.big
        k0, k1 = int(k.c[0]) % P, int(k.c[1]) % P
        a0k0 = big.mul_ovf_const(ctx, a[0], k0, self.FQ_BITS)
        a1k1 = big.mul_ovf_const(ctx, a[1], k1, self.FQ_BITS)
        a0k1 = big.mul_ovf_const(ctx, a[0], k1, self.FQ_BITS)
        a1k0 = big.mul_ovf_const(ctx, a[1], k0, self.FQ_BITS)
        return (big.sub_ovf(ctx, a0k0, a1k1), big.add_ovf(ctx, a0k1, a1k0))

    def mul_by_xi(self, ctx: Context, x) -> tuple:
        """Times xi = 1 + u: (c0 - c1, c0 + c1)."""
        big = self.big
        return (big.sub_ovf(ctx, x[0], x[1]), big.add_ovf(ctx, x[0], x[1]))

    def neg(self, ctx: Context, x) -> tuple:
        from .bigint import OverflowInt
        gate = self.fp2.fp.gate

        def n(v):
            return OverflowInt([gate.neg(ctx, l) for l in v.limbs],
                               -v.value, v.limb_abs, v.val_abs)

        return (n(x[0]), n(x[1]))

    def reduce(self, ctx: Context, x) -> tuple:
        """Lazy pair -> reduced (CrtUint, CrtUint) mod p."""
        big = self.big
        return (big.carry_mod_ovf(ctx, x[0], P),
                big.carry_mod_ovf(ctx, x[1], P))


class G2Chip:
    """Non-native G2 affine arithmetic over Fp2Chip (reference: halo2-ecc
    `EccChip<Fp2>` — the signature-side group of `assign_signature:279`).

    All point formulas run on the lazy engine: the chord/tangent identities
    are constrained directly on unreduced accumulations (λ·dx - dy ≡ 0 etc.),
    so an add costs 2 quotient-only checks + 4 reductions instead of ~10
    eager Fq2 operations."""

    def __init__(self, fp2: Fp2Chip):
        self.fp2 = fp2

    def load_point(self, ctx: Context, pt) -> tuple:
        """On-curve check y^2 - x^3 - 4(1+u) ≡ 0, lazy (2 squares + 1 mul
        as convolutions, one intermediate reduction, 2 zero checks)."""
        fp2 = self.fp2
        lz = fp2.lz
        x = fp2.load(ctx, pt[0])
        y = fp2.load(ctx, pt[1])
        y2 = lz.square(ctx, y)
        x2r = lz.reduce(ctx, lz.square(ctx, x))
        x3 = lz.mul(ctx, x2r, x)
        t = lz.sub(ctx, y2, x3)
        b0, b1 = int(bls.B2.c[0]), int(bls.B2.c[1])
        big = lz.big
        t = (big.sub_ovf(ctx, t[0], big.const_ovf(ctx, b0)),
             big.sub_ovf(ctx, t[1], big.const_ovf(ctx, b1)))
        lz.assert_zero(ctx, t)
        return (x, y)

    # -- lazy chord/tangent cores (shared with PairingChip's Miller steps) --
    def add_core(self, ctx: Context, t_pt, q_pt, strict: bool = True) -> tuple:
        """((T+Q), chord slope λ). strict constrains x_T != x_Q — without it
        T == ±Q lets any witnessed slope satisfy 0·λ = 0 (see
        EccChip.add_unequal). Operands are reduced Fq2 pairs."""
        fp2 = self.fp2
        lz = fp2.lz
        xt, yt = t_pt
        xq, yq = q_pt
        dx = lz.sub(ctx, lz.lift(ctx, xt), lz.lift(ctx, xq))
        dy = lz.sub(ctx, lz.lift(ctx, yt), lz.lift(ctx, yq))
        if strict:
            lz.assert_nonzero(ctx, dx)
        lam = fp2.load(ctx, lz.value(dy) / lz.value(dx))
        # λ·dx - dy ≡ 0
        lz.assert_zero(ctx, lz.sub(ctx, lz.mul(ctx, lam, dx), dy))
        lam2 = lz.mul(ctx, lam, lam)
        oxt = lz.lift(ctx, xt)
        x3 = lz.reduce(ctx, lz.sub(ctx, lz.sub(ctx, lam2, oxt),
                                   lz.lift(ctx, xq)))
        d13 = lz.sub(ctx, oxt, lz.lift(ctx, x3))
        y3 = lz.reduce(ctx, lz.sub(ctx, lz.mul(ctx, lam, d13),
                                   lz.lift(ctx, yt)))
        return (x3, y3), lam

    def double_core(self, ctx: Context, t_pt) -> tuple:
        """((2T), tangent slope λ): constrain 2·(λ·y) - 3·x² ≡ 0 directly
        (no reduced intermediates for the slope identity). y != 0 always
        holds on-curve: no order-2 points with b != 0 twists here."""
        fp2 = self.fp2
        lz = fp2.lz
        x, y = t_pt
        xv, yv = fp2.value(x), fp2.value(y)
        lam = fp2.load(ctx, xv * xv * bls.Fq2([3, 0]) / (yv * bls.Fq2([2, 0])))
        lamy = lz.mul(ctx, lam, y)
        x2 = lz.square(ctx, x)
        lz.assert_zero(ctx, lz.sub(ctx, lz.scale(ctx, lamy, 2),
                                   lz.scale(ctx, x2, 3)))
        lam2 = lz.mul(ctx, lam, lam)
        ox = lz.lift(ctx, x)
        x3 = lz.reduce(ctx, lz.sub(ctx, lz.sub(ctx, lam2, ox), ox))
        d13 = lz.sub(ctx, ox, lz.lift(ctx, x3))
        y3 = lz.reduce(ctx, lz.sub(ctx, lz.mul(ctx, lam, d13),
                                   lz.lift(ctx, y)))
        return (x3, y3), lam

    def add_unequal(self, ctx: Context, p, q, strict: bool = True) -> tuple:
        pt, _lam = self.add_core(ctx, p, q, strict=strict)
        return pt

    def double(self, ctx: Context, p) -> tuple:
        pt, _lam = self.double_core(ctx, p)
        return pt

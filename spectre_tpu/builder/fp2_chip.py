"""Fp2 chip: BLS12-381 quadratic-extension arithmetic over FpChip.

Reference parity: halo2-ecc `Fp2Chip` (SURVEY.md L0) — the coordinate field of
G2 points (signatures live in G2), and with it the G2 EccChip. This is the
round-2 pairing path's next layer; landed in round 1 so the StepCircuit's
signature block can assemble on top of tested primitives.

Elements are (c0, c1) CrtUint pairs representing c0 + c1*u with u^2 = -1.
"""

from __future__ import annotations

from ..fields import bls12_381 as bls
from .context import Context
from .fp_chip import FpChip

P = bls.P


class Fp2Chip:
    def __init__(self, fp: FpChip):
        self.fp = fp

    def load(self, ctx: Context, v) -> tuple:
        """v: fields.bls12_381.Fq2 or (c0, c1) ints."""
        c0, c1 = (v.c if hasattr(v, "c") else v)
        return (self.fp.load(ctx, int(c0)), self.fp.load(ctx, int(c1)))

    def load_constant(self, ctx: Context, v) -> tuple:
        c0, c1 = (v.c if hasattr(v, "c") else v)
        return (self.fp.load_constant(ctx, int(c0)),
                self.fp.load_constant(ctx, int(c1)))

    def value(self, a) -> "bls.Fq2":
        return bls.Fq2([a[0].value % P, a[1].value % P])

    def add(self, ctx: Context, a, b) -> tuple:
        return (self.fp.add(ctx, a[0], b[0]), self.fp.add(ctx, a[1], b[1]))

    def sub(self, ctx: Context, a, b) -> tuple:
        return (self.fp.sub(ctx, a[0], b[0]), self.fp.sub(ctx, a[1], b[1]))

    def mul(self, ctx: Context, a, b) -> tuple:
        """(a0 + a1 u)(b0 + b1 u) = (a0b0 - a1b1) + (a0b1 + a1b0) u."""
        a0b0 = self.fp.mul(ctx, a[0], b[0])
        a1b1 = self.fp.mul(ctx, a[1], b[1])
        a0b1 = self.fp.mul(ctx, a[0], b[1])
        a1b0 = self.fp.mul(ctx, a[1], b[0])
        return (self.fp.sub(ctx, a0b0, a1b1), self.fp.add(ctx, a0b1, a1b0))

    def square(self, ctx: Context, a) -> tuple:
        """(a0^2 - a1^2) + 2 a0 a1 u (complex squaring)."""
        s = self.fp.add(ctx, a[0], a[1])
        d = self.fp.sub(ctx, a[0], a[1])
        c0 = self.fp.mul(ctx, s, d)
        a0a1 = self.fp.mul(ctx, a[0], a[1])
        return (c0, self.fp.mul_scalar(ctx, a0a1, 2))

    def mul_scalar(self, ctx: Context, a, k: int) -> tuple:
        return (self.fp.mul_scalar(ctx, a[0], k), self.fp.mul_scalar(ctx, a[1], k))

    def neg(self, ctx: Context, a) -> tuple:
        zero = self.fp.load_constant(ctx, 0)
        return (self.fp.sub(ctx, zero, a[0]), self.fp.sub(ctx, zero, a[1]))

    def conjugate(self, ctx: Context, a) -> tuple:
        zero = self.fp.load_constant(ctx, 0)
        return (a[0], self.fp.sub(ctx, zero, a[1]))

    def div_unsafe(self, ctx: Context, a, b) -> tuple:
        """q with q*b == a; witness the quotient, constrain the product."""
        av, bv = self.value(a), self.value(b)
        qv = av / bv
        q = self.load(ctx, qv)
        prod = self.mul(ctx, q, b)
        self.assert_equal(ctx, prod, a)
        return q

    def assert_equal(self, ctx: Context, a, b):
        self.fp.assert_equal(ctx, self.fp._reduced(ctx, a[0]),
                             self.fp._reduced(ctx, b[0]))
        self.fp.assert_equal(ctx, self.fp._reduced(ctx, a[1]),
                             self.fp._reduced(ctx, b[1]))

    def assert_nonzero(self, ctx: Context, a):
        """Constrain a != 0 in Fp2 via witnessed inverse a*inv == 1 (same
        soundness argument as FpChip.assert_nonzero)."""
        av = self.value(a)
        assert av != bls.Fq2([0, 0]), "assert_nonzero: witness is zero"
        inv = self.load(ctx, bls.Fq2([1, 0]) / av)
        prod = self.mul(ctx, a, inv)
        one = self.load_constant(ctx, (1, 0))
        self.assert_equal(ctx, prod, one)


class G2Chip:
    """Non-native G2 affine arithmetic over Fp2Chip (reference: halo2-ecc
    `EccChip<Fp2>` — the signature-side group of `assign_signature:279`)."""

    def __init__(self, fp2: Fp2Chip):
        self.fp2 = fp2

    def load_point(self, ctx: Context, pt) -> tuple:
        """On-curve check y^2 == x^3 + 4(1+u)."""
        x = self.fp2.load(ctx, pt[0])
        y = self.fp2.load(ctx, pt[1])
        y2 = self.fp2.square(ctx, y)
        x3 = self.fp2.mul(ctx, self.fp2.square(ctx, x), x)
        b2 = self.fp2.load_constant(ctx, bls.B2)
        rhs = self.fp2.add(ctx, x3, b2)
        self.fp2.assert_equal(ctx, y2, rhs)
        return (x, y)

    def add_unequal(self, ctx: Context, p, q, strict: bool = True) -> tuple:
        """Chord addition; strict constrains x1 != x2 (see EccChip.add_unequal)."""
        x1, y1 = p
        x2, y2 = q
        dx = self.fp2.sub(ctx, x2, x1)
        if strict:
            self.fp2.assert_nonzero(ctx, dx)
        lam = self.fp2.div_unsafe(ctx, self.fp2.sub(ctx, y2, y1), dx)
        lam2 = self.fp2.square(ctx, lam)
        x3 = self.fp2.sub(ctx, self.fp2.sub(ctx, lam2, x1), x2)
        y3 = self.fp2.sub(ctx, self.fp2.mul(ctx, lam, self.fp2.sub(ctx, x1, x3)), y1)
        return (x3, y3)

    def double(self, ctx: Context, p) -> tuple:
        x1, y1 = p
        three_x2 = self.fp2.mul_scalar(ctx, self.fp2.square(ctx, x1), 3)
        two_y = self.fp2.mul_scalar(ctx, y1, 2)
        lam = self.fp2.div_unsafe(ctx, three_x2, two_y)
        lam2 = self.fp2.square(ctx, lam)
        x3 = self.fp2.sub(ctx, self.fp2.sub(ctx, lam2, x1), x1)
        y3 = self.fp2.sub(ctx, self.fp2.mul(ctx, lam, self.fp2.sub(ctx, x1, x3)), y1)
        return (x3, y3)

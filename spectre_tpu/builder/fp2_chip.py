"""Fp2 chip: BLS12-381 quadratic-extension arithmetic over FpChip.

Reference parity: halo2-ecc `Fp2Chip` (SURVEY.md L0) — the coordinate field of
G2 points (signatures live in G2), and with it the G2 EccChip. This is the
round-2 pairing path's next layer; landed in round 1 so the StepCircuit's
signature block can assemble on top of tested primitives.

Elements are (c0, c1) CrtUint pairs representing c0 + c1*u with u^2 = -1.
"""

from __future__ import annotations

from ..fields import bls12_381 as bls
from .context import Context
from .fp_chip import FpChip

P = bls.P


class Fp2Chip:
    def __init__(self, fp: FpChip):
        self.fp = fp

    def load(self, ctx: Context, v) -> tuple:
        """v: fields.bls12_381.Fq2 or (c0, c1) ints."""
        c0, c1 = (v.c if hasattr(v, "c") else v)
        return (self.fp.load(ctx, int(c0)), self.fp.load(ctx, int(c1)))

    def load_constant(self, ctx: Context, v) -> tuple:
        c0, c1 = (v.c if hasattr(v, "c") else v)
        return (self.fp.load_constant(ctx, int(c0)),
                self.fp.load_constant(ctx, int(c1)))

    def value(self, a) -> "bls.Fq2":
        return bls.Fq2([a[0].value % P, a[1].value % P])

    def add(self, ctx: Context, a, b) -> tuple:
        return (self.fp.add(ctx, a[0], b[0]), self.fp.add(ctx, a[1], b[1]))

    def sub(self, ctx: Context, a, b) -> tuple:
        return (self.fp.sub(ctx, a[0], b[0]), self.fp.sub(ctx, a[1], b[1]))

    def mul(self, ctx: Context, a, b) -> tuple:
        """(a0 + a1 u)(b0 + b1 u) = (a0b0 - a1b1) + (a0b1 + a1b0) u."""
        a0b0 = self.fp.mul(ctx, a[0], b[0])
        a1b1 = self.fp.mul(ctx, a[1], b[1])
        a0b1 = self.fp.mul(ctx, a[0], b[1])
        a1b0 = self.fp.mul(ctx, a[1], b[0])
        return (self.fp.sub(ctx, a0b0, a1b1), self.fp.add(ctx, a0b1, a1b0))

    def square(self, ctx: Context, a) -> tuple:
        """(a0^2 - a1^2) + 2 a0 a1 u (complex squaring)."""
        s = self.fp.add(ctx, a[0], a[1])
        d = self.fp.sub(ctx, a[0], a[1])
        c0 = self.fp.mul(ctx, s, d)
        a0a1 = self.fp.mul(ctx, a[0], a[1])
        return (c0, self.fp.mul_scalar(ctx, a0a1, 2))

    def mul_scalar(self, ctx: Context, a, k: int) -> tuple:
        return (self.fp.mul_scalar(ctx, a[0], k), self.fp.mul_scalar(ctx, a[1], k))

    def neg(self, ctx: Context, a) -> tuple:
        zero = self.fp.load_constant(ctx, 0)
        return (self.fp.sub(ctx, zero, a[0]), self.fp.sub(ctx, zero, a[1]))

    def conjugate(self, ctx: Context, a) -> tuple:
        zero = self.fp.load_constant(ctx, 0)
        return (a[0], self.fp.sub(ctx, zero, a[1]))

    def div_unsafe(self, ctx: Context, a, b) -> tuple:
        """q with q*b == a; witness the quotient, constrain the product."""
        av, bv = self.value(a), self.value(b)
        qv = av / bv
        q = self.load(ctx, qv)
        prod = self.mul(ctx, q, b)
        self.assert_equal(ctx, prod, a)
        return q

    def assert_equal(self, ctx: Context, a, b):
        self.fp.assert_equal(ctx, self.fp._reduced(ctx, a[0]),
                             self.fp._reduced(ctx, b[0]))
        self.fp.assert_equal(ctx, self.fp._reduced(ctx, a[1]),
                             self.fp._reduced(ctx, b[1]))

    def select(self, ctx: Context, bit, a, b) -> tuple:
        return (self.fp.select(ctx, bit, a[0], b[0]),
                self.fp.select(ctx, bit, a[1], b[1]))

    def assert_nonzero(self, ctx: Context, a):
        """Constrain a != 0 in Fp2 via witnessed inverse a*inv == 1 (same
        soundness argument as FpChip.assert_nonzero)."""
        av = self.value(a)
        assert av != bls.Fq2([0, 0]), "assert_nonzero: witness is zero"
        inv = self.load(ctx, bls.Fq2([1, 0]) / av)
        prod = self.mul(ctx, a, inv)
        one = self.load_constant(ctx, (1, 0))
        self.assert_equal(ctx, prod, one)


class Fp2Lazy:
    """Lazily-reduced Fq2 arithmetic: elements are (OverflowInt, OverflowInt)
    pairs accumulated with no-carry limb ops and reduced once per output
    coefficient (halo2-ecc's FieldExtPoint-over-CRTInteger pattern — this is
    what makes the in-circuit pairing affordable: an Fp12 mul costs 12
    reductions instead of 144)."""

    FQ_BITS = 381  # reduced CrtUint elements are < 2^381

    def __init__(self, fp2: Fp2Chip):
        self.fp2 = fp2
        self.big = fp2.fp.big

    # -- entering the lazy domain --------------------------------------
    def lift(self, ctx: Context, a) -> tuple:
        """(CrtUint, CrtUint) -> (OverflowInt, OverflowInt)."""
        return (self.big.to_overflow(a[0], self.FQ_BITS),
                self.big.to_overflow(a[1], self.FQ_BITS))

    def coeff_sum(self, ctx: Context, a):
        """a0 + a1 as an OverflowInt (the Karatsuba operand sum) — hoist and
        reuse when the same element multiplies many others (Fp12 mul)."""
        big = self.big
        return big.add_ovf(ctx, big.to_overflow(a[0], self.FQ_BITS),
                           big.to_overflow(a[1], self.FQ_BITS))

    def mul(self, ctx: Context, a, b, sa=None, sb=None) -> tuple:
        """Reduced pairs -> lazy product (a0b0 - a1b1, a0b1 + a1b0),
        Karatsuba: 3 limb convolutions instead of 4. sa/sb: optional
        precomputed coeff_sum(a)/coeff_sum(b)."""
        big = self.big
        t0 = big.mul_ovf(ctx, a[0], b[0], self.FQ_BITS)
        t1 = big.mul_ovf(ctx, a[1], b[1], self.FQ_BITS)
        sa = sa if sa is not None else self.coeff_sum(ctx, a)
        sb = sb if sb is not None else self.coeff_sum(ctx, b)
        t01 = big.mul_ovf(ctx, sa, sb)
        cross = big.sub_ovf(ctx, big.sub_ovf(ctx, t01, t0), t1)
        return (big.sub_ovf(ctx, t0, t1), cross)

    def mul_by_fq_cell(self, ctx: Context, a, x: "CrtUint") -> tuple:
        """Fq2 pair times a base-field CrtUint cell."""
        big = self.big
        return (big.mul_ovf(ctx, a[0], x, self.FQ_BITS),
                big.mul_ovf(ctx, a[1], x, self.FQ_BITS))

    # -- lazy-domain ops ------------------------------------------------
    def add(self, ctx: Context, x, y) -> tuple:
        big = self.big
        return (big.add_ovf(ctx, x[0], y[0]), big.add_ovf(ctx, x[1], y[1]))

    def sub(self, ctx: Context, x, y) -> tuple:
        big = self.big
        return (big.sub_ovf(ctx, x[0], y[0]), big.sub_ovf(ctx, x[1], y[1]))

    def mul_const(self, ctx: Context, a, k: "bls.Fq2") -> tuple:
        """REDUCED pair times an Fq2 host constant (k0 + k1 u), via
        constant-limb convolutions: (a0k0 - a1k1, a0k1 + a1k0) lazy."""
        big = self.big
        k0, k1 = int(k.c[0]) % P, int(k.c[1]) % P
        a0k0 = big.mul_ovf_const(ctx, a[0], k0, self.FQ_BITS)
        a1k1 = big.mul_ovf_const(ctx, a[1], k1, self.FQ_BITS)
        a0k1 = big.mul_ovf_const(ctx, a[0], k1, self.FQ_BITS)
        a1k0 = big.mul_ovf_const(ctx, a[1], k0, self.FQ_BITS)
        return (big.sub_ovf(ctx, a0k0, a1k1), big.add_ovf(ctx, a0k1, a1k0))

    def mul_by_xi(self, ctx: Context, x) -> tuple:
        """Times xi = 1 + u: (c0 - c1, c0 + c1)."""
        big = self.big
        return (big.sub_ovf(ctx, x[0], x[1]), big.add_ovf(ctx, x[0], x[1]))

    def neg(self, ctx: Context, x) -> tuple:
        from .bigint import OverflowInt
        gate = self.fp2.fp.gate

        def n(v):
            return OverflowInt([gate.neg(ctx, l) for l in v.limbs],
                               -v.value, v.limb_abs, v.val_abs)

        return (n(x[0]), n(x[1]))

    def reduce(self, ctx: Context, x) -> tuple:
        """Lazy pair -> reduced (CrtUint, CrtUint) mod p."""
        big = self.big
        return (big.carry_mod_ovf(ctx, x[0], P),
                big.carry_mod_ovf(ctx, x[1], P))


class G2Chip:
    """Non-native G2 affine arithmetic over Fp2Chip (reference: halo2-ecc
    `EccChip<Fp2>` — the signature-side group of `assign_signature:279`)."""

    def __init__(self, fp2: Fp2Chip):
        self.fp2 = fp2

    def load_point(self, ctx: Context, pt) -> tuple:
        """On-curve check y^2 == x^3 + 4(1+u)."""
        x = self.fp2.load(ctx, pt[0])
        y = self.fp2.load(ctx, pt[1])
        y2 = self.fp2.square(ctx, y)
        x3 = self.fp2.mul(ctx, self.fp2.square(ctx, x), x)
        b2 = self.fp2.load_constant(ctx, bls.B2)
        rhs = self.fp2.add(ctx, x3, b2)
        self.fp2.assert_equal(ctx, y2, rhs)
        return (x, y)

    def add_unequal(self, ctx: Context, p, q, strict: bool = True) -> tuple:
        """Chord addition; strict constrains x1 != x2 (see EccChip.add_unequal)."""
        x1, y1 = p
        x2, y2 = q
        dx = self.fp2.sub(ctx, x2, x1)
        if strict:
            self.fp2.assert_nonzero(ctx, dx)
        lam = self.fp2.div_unsafe(ctx, self.fp2.sub(ctx, y2, y1), dx)
        lam2 = self.fp2.square(ctx, lam)
        x3 = self.fp2.sub(ctx, self.fp2.sub(ctx, lam2, x1), x2)
        y3 = self.fp2.sub(ctx, self.fp2.mul(ctx, lam, self.fp2.sub(ctx, x1, x3)), y1)
        return (x3, y3)

    def double(self, ctx: Context, p) -> tuple:
        x1, y1 = p
        three_x2 = self.fp2.mul_scalar(ctx, self.fp2.square(ctx, x1), 3)
        two_y = self.fp2.mul_scalar(ctx, y1, 2)
        lam = self.fp2.div_unsafe(ctx, three_x2, two_y)
        lam2 = self.fp2.square(ctx, lam)
        x3 = self.fp2.sub(ctx, self.fp2.sub(ctx, lam2, x1), x1)
        y3 = self.fp2.sub(ctx, self.fp2.mul(ctx, lam, self.fp2.sub(ctx, x1, x3)), y1)
        return (x3, y3)

"""Non-native field chip: BLS12-381 Fq arithmetic over BN254 Fr cells.

Reference parity: halo2-ecc `FpChip` (SURVEY.md N5's in-circuit side) — the
foundation of the in-circuit BLS machinery (G1/G2 point ops, and in round 2
the pairing). Built on BigUintChip's CRT reduction.
"""

from __future__ import annotations

from ..fields import bls12_381 as bls
from .bigint import BigUintChip, CrtUint, OverflowInt
from .context import Context
from .range_chip import RangeChip

P = bls.P


class FpChip:
    """Non-native Fp chip over a run-time modulus. Defaults to BLS12-381 Fq
    with the spec limb shape; the aggregation layer instantiates it for
    BN254 Fq with 3 x 88-bit limbs (snark-verifier's accumulator encoding)."""

    def __init__(self, rng: RangeChip, modulus: int = P,
                 num_limbs: int | None = None, limb_bits: int | None = None):
        kw = {}
        if num_limbs is not None:
            kw["num_limbs"] = num_limbs
        if limb_bits is not None:
            kw["limb_bits"] = limb_bits
        self.big = BigUintChip(rng, **kw)
        self.gate = rng.gate
        self.p = int(modulus)

    def load(self, ctx: Context, v: int) -> CrtUint:
        v = int(v) % self.p
        return self.big.load(ctx, v, max_bits=self.p.bit_length())

    def load_constant(self, ctx: Context, v: int) -> CrtUint:
        return self.big.load_constant(ctx, int(v) % self.p)

    def add(self, ctx: Context, a: CrtUint, b: CrtUint) -> CrtUint:
        s = self.big.add_no_carry(ctx, a, b)
        # reduce via carry_mod on the (L-limb) sum: reuse the product path by
        # padding to 2L-1 limbs with zeros
        zero = ctx.load_constant(0)
        limbs = s.limbs + [zero] * (2 * len(a.limbs) - 1 - len(s.limbs))
        return self.big.carry_mod(ctx, limbs, s.value, self.p)

    def mul(self, ctx: Context, a: CrtUint, b: CrtUint) -> CrtUint:
        prod = self.big.mul_no_carry(ctx, a, b)
        return self.big.carry_mod(ctx, prod, a.value * b.value, self.p)

    def sub(self, ctx: Context, a: CrtUint, b: CrtUint) -> CrtUint:
        """a - b mod p: compute via a + (p*k - b) with k s.t. values stay
        non-negative (k=1 suffices since b < p)."""
        pk = self.big.load_constant(ctx, self.p)
        t = self.big.add_no_carry(ctx, a, pk)
        limbs = [self.gate.sub(ctx, x, y) if y is not None else x
                 for x, y in zip(t.limbs, b.limbs + [None] * (len(t.limbs) - len(b.limbs)))]
        value = a.value + self.p - b.value
        zero = ctx.load_constant(0)
        padded = limbs + [zero] * (2 * len(a.limbs) - 1 - len(limbs))
        native = None
        # rebuild native for the carry path consistency: carry_mod recomputes
        # natives from the limbs, so only limbs + value matter here
        return self.big.carry_mod(ctx, padded, value, self.p)

    def assert_equal(self, ctx: Context, a: CrtUint, b: CrtUint):
        for x, y in zip(a.limbs, b.limbs):
            ctx.constrain_equal(x, y)

    def mul_scalar(self, ctx: Context, a: CrtUint, k: int) -> CrtUint:
        limbs = [self.gate.mul(ctx, x, k) for x in a.limbs]
        zero = ctx.load_constant(0)
        padded = limbs + [zero] * (2 * len(a.limbs) - 1 - len(limbs))
        return self.big.carry_mod(ctx, padded, a.value * k, self.p)

    def div_unsafe(self, ctx: Context, a: CrtUint, b: CrtUint) -> CrtUint:
        """q with q*b = a (mod p); only the product relation is constrained."""
        p = self.p
        q_val = a.value % p * pow(b.value % p, -1, p) % p
        q = self.load(ctx, q_val)
        prod = self.big.mul_no_carry(ctx, q, b)
        r = self.big.carry_mod(ctx, prod, q_val * b.value, self.p)
        # r must equal a mod p — a is already reduced (< p), so limb equality
        self.assert_equal(ctx, r, self._reduced(ctx, a))
        return q

    def _reduced(self, ctx: Context, a: CrtUint) -> CrtUint:
        if a.value < self.p:
            return a
        zero = ctx.load_constant(0)
        padded = a.limbs + [zero] * (2 * len(a.limbs) - 1 - len(a.limbs))
        return self.big.carry_mod(ctx, padded, a.value, self.p)

    def from_limbs(self, ctx: Context, limbs: list, value: int) -> CrtUint:
        """CrtUint from existing (range-checked) limb cells."""
        native = self.gate.inner_product_const(
            ctx, limbs, self.big._pow_native[:len(limbs)])
        return CrtUint(limbs, native, value)

    def select(self, ctx: Context, bit, a: CrtUint, b: CrtUint) -> CrtUint:
        """bit ? a : b — limbs and the already-constrained natives both
        selected directly (no native rebuild)."""
        gate = self.gate
        limbs = [gate.select(ctx, x, y, bit) for x, y in zip(a.limbs, b.limbs)]
        native = gate.select(ctx, a.native, b.native, bit)
        return CrtUint(limbs, native, a.value if bit.value else b.value)

    def load_constant_point(self, ctx: Context, pt) -> tuple:
        """Constant G1 point as CrtUint pair (no on-curve check needed)."""
        return (self.load_constant(ctx, int(pt[0])),
                self.load_constant(ctx, int(pt[1])))

    def assert_nonzero(self, ctx: Context, a: CrtUint):
        """Constrain a != 0 (mod p) via a witnessed inverse: a*inv - 1 == 0
        (mod p). Sound without canonical form — no inverse of 0 exists, so no
        witness satisfies the relation when a = 0 mod p. Closes the P == Q
        forgery hole in witness-slope addition (`ADVICE.md` fp_chip finding;
        reference: halo2-ecc strict `ec_add_unequal`)."""
        av = a.value % self.p
        assert av != 0, "assert_nonzero: witness is zero"
        inv = self.load(ctx, pow(av, -1, self.p))
        prod = self.big.mul_no_carry(ctx, a, inv)
        # subtract 1 from the low product limb, then carry the lot to zero
        from ..fields import bn254
        prod0 = self.gate.add(ctx, prod[0], bn254.R - 1)
        self.big.check_carry_to_zero(ctx, [prod0] + prod[1:],
                                     a.value * inv.value - 1, self.p)

    def canonicalize(self, ctx: Context, a: CrtUint) -> CrtUint:
        """Reduce and enforce the canonical representative r < p (not just
        r < 2^381). Use at circuit boundaries where limbs become public or
        byte-compared (`ADVICE.md` bigint.py finding)."""
        r = self._reduced(ctx, a)
        self.big.enforce_lt(ctx, r, self.p)
        return r


class EccChip:
    """Non-native G1 affine arithmetic (BLS12-381) over FpChip.

    Reference parity: halo2-ecc `EccChip` — witness-slope addition/doubling
    (the 512-iteration aggregation loop of `aggregate_pubkeys:292` builds on
    exactly these ops)."""

    def __init__(self, fp: FpChip, b: int = 4):
        """b: the short-Weierstrass constant (y^2 = x^3 + b). 4 for
        BLS12-381 G1, 3 for BN254 G1 (the aggregation layer's curve)."""
        self.fp = fp
        self.b = b

    def load_point(self, ctx: Context, pt) -> tuple:
        x, y = int(pt[0]), int(pt[1])
        # on-curve check: y^2 == x^3 + b
        xc = self.fp.load(ctx, x)
        yc = self.fp.load(ctx, y)
        return self.constrain_on_curve(ctx, xc, yc)

    def constrain_on_curve(self, ctx: Context, xc, yc) -> tuple:
        """On-curve check y² - x³ - b ≡ 0 for already-loaded coordinates,
        lazy: 3 limb convolutions, one intermediate reduction (x² — needed to
        keep the cubic's quotient within limb width), one quotient-only
        zero check."""
        fp, big = self.fp, self.fp.big
        p = fp.p
        bits = p.bit_length()
        y2 = big.mul_ovf(ctx, yc, yc, bits)
        x2r = big.carry_mod_ovf(ctx, big.mul_ovf(ctx, xc, xc, bits), p)
        x3 = big.mul_ovf(ctx, x2r, xc, bits)
        t = big.sub_ovf(ctx, y2, x3)
        big.assert_zero_mod(ctx, big.sub_ovf(ctx, t, big.const_ovf(ctx, self.b)), p)
        return (xc, yc)

    def add_unequal(self, ctx: Context, p, q, strict: bool = True) -> tuple:
        """(x1,y1)+(x2,y2), x1 != x2: witness slope; standard chord formulas.

        strict constrains dx != 0 — without it, P == Q makes both div_unsafe
        operands 0 and ANY slope satisfies q*0 = 0, letting a prover forge the
        sum (halo2-ecc strict mode; `ADVICE.md`). Pass strict=False only when
        x1 != x2 is already constrained elsewhere."""
        x1, y1 = p
        x2, y2 = q
        dx = self.fp.sub(ctx, x2, x1)
        if strict:
            self.fp.assert_nonzero(ctx, dx)
        dy = self.fp.sub(ctx, y2, y1)
        lam = self.fp.div_unsafe(ctx, dy, dx)
        lam2 = self.fp.mul(ctx, lam, lam)
        x3 = self.fp.sub(ctx, self.fp.sub(ctx, lam2, x1), x2)
        y3 = self.fp.sub(ctx, self.fp.mul(ctx, lam, self.fp.sub(ctx, x1, x3)), y1)
        return (x3, y3)

    def double(self, ctx: Context, p) -> tuple:
        x1, y1 = p
        x2 = self.fp.mul(ctx, x1, x1)
        three_x2 = self.fp.mul_scalar(ctx, x2, 3)
        two_y = self.fp.mul_scalar(ctx, y1, 2)
        lam = self.fp.div_unsafe(ctx, three_x2, two_y)
        lam2 = self.fp.mul(ctx, lam, lam)
        x3 = self.fp.sub(ctx, self.fp.sub(ctx, lam2, x1), x1)
        y3 = self.fp.sub(ctx, self.fp.mul(ctx, lam, self.fp.sub(ctx, x1, x3)), y1)
        return (x3, y3)

    # -- lazy variants: one carry per constrained identity ----------------
    # The chord/tangent equations are enforced directly on OverflowInt
    # accumulations (λ·dx - dy ≡ 0 etc.), so an add costs 4-5 reductions
    # instead of ~10. This is what makes the aggregation circuit's in-circuit
    # MSM (reference: snark-verifier's in-circuit accumulator MSM) tractable.

    def _lam_witness(self, num: int, den: int) -> int:
        p = self.fp.p
        return num % p * pow(den % p, -1, p) % p

    def add_unequal_lazy(self, ctx: Context, pt, q, strict: bool = True) -> tuple:
        fp, big = self.fp, self.fp.big
        p = fp.p
        bits = p.bit_length()
        x1, y1 = pt
        x2, y2 = q
        ox1, oy1 = big.to_overflow(x1, bits), big.to_overflow(y1, bits)
        ox2, oy2 = big.to_overflow(x2, bits), big.to_overflow(y2, bits)
        dx = big.sub_ovf(ctx, ox2, ox1)
        dy = big.sub_ovf(ctx, oy2, oy1)
        if strict:
            # dx != 0 (mod p): witnessed inverse, dx*inv - 1 ≡ 0
            assert dx.value % p != 0, "add_unequal_lazy: P == ±Q"
            inv = fp.load(ctx, pow(dx.value % p, -1, p))
            t = big.mul_ovf(ctx, dx, inv, bits)
            big.assert_zero_mod(ctx, big.sub_ovf(ctx, t, big.const_ovf(ctx, 1)), p)
        lam = fp.load(ctx, self._lam_witness(dy.value, dx.value))
        # λ·dx - dy ≡ 0
        big.assert_zero_mod(
            ctx, big.sub_ovf(ctx, big.mul_ovf(ctx, lam, dx, bits), dy), p)
        # x3 = λ² - x1 - x2
        lam2 = big.mul_ovf(ctx, lam, lam, bits)
        x3 = big.carry_mod_ovf(
            ctx, big.sub_ovf(ctx, big.sub_ovf(ctx, lam2, ox1), ox2), p)
        # y3 = λ(x1 - x3) - y1
        d13 = big.sub_ovf(ctx, ox1, big.to_overflow(x3, bits))
        y3 = big.carry_mod_ovf(
            ctx, big.sub_ovf(ctx, big.mul_ovf(ctx, lam, d13, bits), oy1), p)
        return (x3, y3)

    def double_lazy(self, ctx: Context, pt) -> tuple:
        fp, big = self.fp, self.fp.big
        p = fp.p
        bits = p.bit_length()
        x1, y1 = pt
        ox1, oy1 = big.to_overflow(x1, bits), big.to_overflow(y1, bits)
        xx = big.mul_ovf(ctx, x1, x1, bits)
        lam = fp.load(ctx, self._lam_witness(3 * xx.value, 2 * oy1.value))
        # λ·2y - 3x² ≡ 0  (y != 0 always holds: no order-2 points in a prime-
        # order G1, and operands are constrained on-curve)
        two_y = big.scale_ovf(ctx, oy1, 2)
        t = big.sub_ovf(ctx, big.mul_ovf(ctx, lam, two_y, bits),
                        big.scale_ovf(ctx, xx, 3))
        big.assert_zero_mod(ctx, t, p)
        lam2 = big.mul_ovf(ctx, lam, lam, bits)
        x3 = big.carry_mod_ovf(
            ctx, big.sub_ovf(ctx, big.sub_ovf(ctx, lam2, ox1), ox1), p)
        d13 = big.sub_ovf(ctx, ox1, big.to_overflow(x3, bits))
        y3 = big.carry_mod_ovf(
            ctx, big.sub_ovf(ctx, big.mul_ovf(ctx, lam, d13, bits), oy1), p)
        return (x3, y3)

    def select(self, ctx: Context, bit, a: tuple, b: tuple) -> tuple:
        """bit ? a : b on affine points."""
        return (self.fp.select(ctx, bit, a[0], b[0]),
                self.fp.select(ctx, bit, a[1], b[1]))

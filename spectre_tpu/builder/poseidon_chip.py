"""In-circuit Poseidon sponge over the GateChip.

Reference parity: halo2-base `PoseidonSponge` as used by the committee
commitment (`poseidon.rs:42-95`); parameters pinned to ops.poseidon
(T=12, RATE=11, R_F=8, R_P=65) so the circuit and the native mirror agree.
"""

from __future__ import annotations

from ..fields import bn254
from ..ops import poseidon as P
from .context import AssignedValue, Context
from .gate import GateChip

R = bn254.R


class PoseidonChip:
    def __init__(self, gate: GateChip | None = None,
                 t: int = P.T, rate: int = P.RATE,
                 r_f: int = P.R_F, r_p: int = P.R_P):
        self.gate = gate or GateChip()
        self.t, self.rate, self.r_f, self.r_p = t, rate, r_f, r_p
        self.rc, self.mds = P.constants(t, r_f, r_p)

    def permute(self, ctx: Context, state: list) -> list:
        """state: t AssignedValues -> t AssignedValues."""
        gate = self.gate
        assert len(state) == self.t
        half = self.r_f // 2
        ri = 0

        def sbox(x):
            x2 = gate.mul(ctx, x, x)
            x4 = gate.mul(ctx, x2, x2)
            return gate.mul(ctx, x4, x)

        def mds_mul(s):
            return [gate.inner_product_const(ctx, s, self.mds[i])
                    for i in range(self.t)]

        s = state
        for _ in range(half):
            s = [gate.add(ctx, x, self.rc[ri * self.t + i]) for i, x in enumerate(s)]
            s = [sbox(x) for x in s]
            s = mds_mul(s)
            ri += 1
        for _ in range(self.r_p):
            s = [gate.add(ctx, x, self.rc[ri * self.t + i]) for i, x in enumerate(s)]
            s = [sbox(s[0])] + s[1:]
            s = mds_mul(s)
            ri += 1
        for _ in range(half):
            s = [gate.add(ctx, x, self.rc[ri * self.t + i]) for i, x in enumerate(s)]
            s = [sbox(x) for x in s]
            s = mds_mul(s)
            ri += 1
        return s

    def hash_values(self, ctx: Context, inputs: list) -> AssignedValue:
        """Sponge squeeze matching ops.poseidon.PoseidonSponge: absorb all
        inputs + trailing 1, permute per RATE chunk, output state[1]."""
        gate = self.gate
        state = [ctx.load_constant(0) for _ in range(self.t)]
        chunks = list(inputs) + [ctx.load_constant(1)]
        for off in range(0, len(chunks), self.rate):
            chunk = chunks[off:off + self.rate]
            state = ([state[0]]
                     + [gate.add(ctx, state[i + 1], v) for i, v in enumerate(chunk)]
                     + state[1 + len(chunk):])
            state = self.permute(ctx, state)
        return state[1]

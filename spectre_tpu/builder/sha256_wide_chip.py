"""Wide SHA-256 chip: hashing in the dedicated bit-ladder region.

Reference parity: `gadget/crypto/sha256_wide.rs:25-129` + its bit gate
manager (`sha256_wide/gate.rs`) — the reference wraps the zkevm "vanilla"
SHA circuit (few rows, many columns, no lookups) for the hash-heavy
committee-update circuit. This is the same trade re-designed for this
framework's expression machinery (see plonk/constraint_system.py header):
each 64-byte block occupies one 72-row slot of 104 bit columns + 10 word
columns (incl. the pinned act flag); round identities are enforced by the region expressions, and only
WORD cells cross into the main region via copy constraints.

Cost: ~200 main-region cells per block (input-word packing + digest mirror)
vs ~45k for the nibble-lookup chip — the scale enabler for 512-pubkey
committees. Witness generation is a plain u32 round trace (vectorizable).

Interface-compatible with Sha256Chip for the gadget layer (digest_bytes,
digest_two_to_one, constant_word, word_from_bytes_be, _range_bits);
subclasses it to reuse the byte/nibble range plumbing.
"""

from __future__ import annotations

from ..ops.sha256 import H0, K
from ..plonk.constraint_system import (SHA_A, SHA_ACT_WORD, SHA_CARRY, SHA_E,
                                       SHA_OUT_ROW, SHA_SEED_ROW,
                                       SHA_SLOT_ROWS, SHA_W)
from .context import AssignedValue, Context
from .sha256_chip import Sha256Chip

M32 = 0xFFFFFFFF


def _rotr(v, r):
    return ((v >> r) | (v << (32 - r))) & M32


class WideWord:
    """A 32-bit word as a single main-region cell (no nibble decomposition —
    the region's bit ladder carries the bits)."""

    __slots__ = ("cell",)

    def __init__(self, cell: AssignedValue):
        self.cell = cell

    @property
    def value(self) -> int:
        return self.cell.value


class Sha256WideChip(Sha256Chip):
    def constant_word(self, ctx: Context, v: int) -> WideWord:
        return WideWord(ctx.load_constant(v & M32))

    def word_from_bytes_be(self, ctx: Context, byte_cells: list) -> WideWord:
        """4 byte cells (already 8-bit checked) -> word cell; the region's
        input identity binds its bits."""
        assert len(byte_cells) == 4
        cell = self.gate.inner_product_const(
            ctx, byte_cells, [1 << 24, 1 << 16, 1 << 8, 1])
        return WideWord(cell)

    # -- region plumbing -------------------------------------------------

    def _trace_block(self, state: list, words: list):
        """Native u32 round trace. Returns (rows, h_out, out_carries) where
        rows[t] = (w_t, a_t, e_t, ce, ca, cs)."""
        a, b, c, d, e, f, g, h = state
        w = list(words)
        rows = []
        for t in range(64):
            cs = 0
            if t >= 16:
                s0 = _rotr(w[t - 15], 7) ^ _rotr(w[t - 15], 18) ^ (w[t - 15] >> 3)
                s1 = _rotr(w[t - 2], 17) ^ _rotr(w[t - 2], 19) ^ (w[t - 2] >> 10)
                tot = w[t - 16] + s0 + w[t - 7] + s1
                w.append(tot & M32)
                cs = tot >> 32
            S1 = _rotr(e, 6) ^ _rotr(e, 11) ^ _rotr(e, 25)
            ch = (e & f) ^ (~e & g)
            t1 = h + S1 + (ch & M32) + int(K[t]) + w[t]
            tot_e = d + t1
            new_e, ce = tot_e & M32, tot_e >> 32
            S0 = _rotr(a, 2) ^ _rotr(a, 13) ^ _rotr(a, 22)
            mj = (a & b) | (a & c) | (b & c)
            tot_a = t1 + S0 + mj
            new_a, ca = tot_a & M32, tot_a >> 32
            h, g, f, e = g, f, e, new_e
            d, c, b, a = c, b, a, new_a
            rows.append((w[t], new_a, new_e, ce, ca, cs))
        fin = [a, b, c, d, e, f, g, h]
        h_out = [(s + v) & M32 for s, v in zip(state, fin)]
        out_c = [(s + v) >> 32 for s, v in zip(state, fin)]
        return rows, h_out, out_c

    @staticmethod
    def _bits32(arr_row, base, v):
        for i in range(32):
            arr_row[base + i] = (v >> i) & 1

    def _fill_slot(self, ctx: Context, slot: int, state: list, words: list):
        """Fill one slot's witness; returns h_out values. Copies for h_in /
        inputs / outputs are the CALLER's job (it knows the sources)."""
        sd = ctx.sha_slots[slot]
        bits, wcols = sd["bits"], sd["words"]
        rows, h_out, out_c = self._trace_block(state, words)
        # seed rows: a ladder rows 0..3 = H[3-r], e ladder = H[7-r]
        for r in range(4):
            self._bits32(bits[r], SHA_A, state[3 - r])
            self._bits32(bits[r], SHA_E, state[7 - r])
        for j in range(8):
            wcols[SHA_SEED_ROW][j] = state[j]
        # round rows
        for t, (wt, at, et, ce, ca, cs) in enumerate(rows):
            r = 4 + t
            self._bits32(bits[r], SHA_W, wt)
            self._bits32(bits[r], SHA_A, at)
            self._bits32(bits[r], SHA_E, et)
            for i in range(3):
                bits[r][SHA_CARRY + i] = (ce >> i) & 1
                bits[r][SHA_CARRY + 3 + i] = (ca >> i) & 1
            for i in range(2):
                bits[r][SHA_CARRY + 6 + i] = (cs >> i) & 1
            if t < 16:
                wcols[r][8] = wt
        # output row
        for j in range(8):
            wcols[SHA_OUT_ROW][j] = h_out[j]
            bits[SHA_OUT_ROW][SHA_CARRY + j] = out_c[j]
        # act = 1 on rows 0..68 (pinned to const 1 by the caller's copy)
        wcols[: SHA_OUT_ROW + 1, SHA_ACT_WORD] = 1
        return h_out

    def _compress_chain(self, ctx: Context, word_cells: list,
                        initial_state: list | None = None):
        """Run len(word_cells)/16 chained blocks from the IV (or a caller
        constant midstate, e.g. expand_message_xmd's all-zero z_pad block);
        word_cells are main-region cells (witness or constant) of the padded
        message. Returns 8 WideWords mirroring the final H_out."""
        assert len(word_cells) % 16 == 0
        nblocks = len(word_cells) // 16
        copies = ctx.copies
        state = [int(v) for v in (initial_state or H0)]
        prev_slot = None
        for b in range(nblocks):
            blk = word_cells[16 * b:16 * b + 16]
            slot = ctx.alloc_sha_slot()
            base = slot * SHA_SLOT_ROWS
            # act pin: the copy to the constant 1 makes this slot's round
            # identities include the real K_t terms (soundness: an unpinned
            # act could be zeroed to prove a K-less hash variant)
            one = ctx.load_constant(1)
            copies.append((("adv", one.index),
                           ("shwc", (SHA_ACT_WORD, base + SHA_SEED_ROW))))
            # h_in binding
            if prev_slot is None:
                for j in range(8):
                    cst = ctx.load_constant(state[j])
                    copies.append((("adv", cst.index),
                                   ("shwc", (j, base + SHA_SEED_ROW))))
            else:
                pbase = prev_slot * SHA_SLOT_ROWS
                for j in range(8):
                    copies.append((("shwc", (j, pbase + SHA_OUT_ROW)),
                                   ("shwc", (j, base + SHA_SEED_ROW))))
            # input words -> shw8 rows 4..19
            for t, wcell in enumerate(blk):
                copies.append((("adv", wcell.cell.index),
                               ("shwc", (8, base + 4 + t))))
            state = self._fill_slot(ctx, slot, state,
                                    [w.value for w in blk])
            prev_slot = slot
        # mirror the final digest into the main region. The out-row identity
        # pins h_out only mod 2^32 with a boolean carry — without a 32-bit
        # range check here a prover could shift a digest word (and the
        # carry bit) by 2^32 and expose sha256(msg) + 2^32 (found by
        # review, PoC'd against mock_prove). Range-checking the mirror
        # makes the candidate unique, which pins the carry bit too.
        # (Intermediate blocks need no check: the next slot's seed identity
        # recombines h_in from boolean ladder bits, forcing < 2^32.)
        out = []
        obase = prev_slot * SHA_SLOT_ROWS + SHA_OUT_ROW
        for j in range(8):
            cell = ctx.load_witness(state[j])
            self._range_bits(ctx, cell, 32)
            copies.append((("adv", cell.index), ("shwc", (j, obase))))
            out.append(WideWord(cell))
        return out

    # -- public interface (gadget layer) ---------------------------------

    def digest_two_to_one(self, ctx: Context, left: list, right: list) -> list:
        """SSZ merkle node: sha256(left32 || right32); inputs are 8-word
        lists (WideWord or any .cell/.value word)."""
        pad = [self.constant_word(ctx, 0x80000000)] + \
              [self.constant_word(ctx, 0)] * 14 + \
              [self.constant_word(ctx, 512)]
        return self._compress_chain(ctx, list(left) + list(right) + pad)

    def digest_bytes(self, ctx: Context, byte_cells: list) -> list:
        """Full SHA256 of a byte-cell message (bytes already 8-bit checked);
        fixed-shape padding, words packed 4 bytes -> 1 cell."""
        msg_len = len(byte_cells)
        padded = list(byte_cells)
        padded.append(ctx.load_constant(0x80))
        while (len(padded) % 64) != 56:
            padded.append(ctx.load_constant(0))
        for byte in (8 * msg_len).to_bytes(8, "big"):
            padded.append(ctx.load_constant(byte))
        words = [self.word_from_bytes_be(ctx, padded[4 * i:4 * i + 4])
                 for i in range(len(padded) // 4)]
        return self._compress_chain(ctx, words)

    # the nibble-path entry points make no sense on the wide chip
    def compress(self, *a, **k):  # pragma: no cover
        raise NotImplementedError("wide chip hashes via the region")

    def initial_state(self, *a, **k):  # pragma: no cover
        raise NotImplementedError("wide chip hashes via the region")

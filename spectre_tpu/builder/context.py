"""Context: virtual advice/lookup cell streams + copy manager + finalizer.

Reference parity: halo2-base `Context`/`SinglePhaseCoreManager` and the
shared copy-constraint manager (`gadget/crypto/builder.rs:56-63`); the
finalize pass is the break-points system (`config/*.json` break_points,
SURVEY.md §2c witness-layout parallelism): the single logical stream is cut
at gate-unit boundaries across physical advice columns.

Cells are python ints mod R; every op appends a unit of 1 or 4 cells (a bare
witness or one vertical-gate activation q*(s0 + s1*s2 - s3) = 0).
"""

from __future__ import annotations

from ..fields import bn254
from ..plonk.constraint_system import Assignment, CircuitConfig

R = bn254.R


class AssignedValue:
    """Handle to a stream cell: (stream id, index, cached value). Cells are
    immutable once appended, so the value is stored directly (the dataclass/
    property indirection dominated witness-gen profiles)."""

    __slots__ = ("stream", "index", "value")

    def __init__(self, stream: str, index: int, value: int):
        self.stream = stream    # always "adv" (lookup streams hold raw copies)
        self.index = index
        self.value = value

    def __repr__(self):
        return f"AV({self.stream}[{self.index}]=0x{self.value:x})"


class Context:
    def __init__(self):
        self.adv_values: list[int] = []       # advice stream
        self.adv_units: list[tuple[int, int, bool]] = []  # (start, size, gated)
        # lookup streams, one per table id ("range", "nibble_op", ...)
        self.lkp_streams: dict[str, list[int]] = {}
        self.copies: list[tuple] = []         # ((stream, idx), (stream, idx))
        self.constants: dict[int, int] = {}   # value -> fixed row
        self.const_uses: list[tuple[int, int]] = []  # (adv idx, fixed row)
        self.instance_cells: list[AssignedValue] = []
        # wide SHA region slots (builder/sha256_wide_chip.py): per slot,
        # bits [SLOT_ROWS, SHA_BIT_COLS] uint32 + words [SLOT_ROWS, SHA_WORD_COLS] uint64. Copies
        # may reference ("shwc", (word_col, global_row)) cells.
        self.sha_slots: list[dict] = []

    def alloc_sha_slot(self) -> int:
        """Reserve one wide-SHA block slot; returns its index (global row
        base = index * SHA_SLOT_ROWS)."""
        import numpy as np
        from ..plonk.constraint_system import (SHA_BIT_COLS, SHA_SLOT_ROWS,
                                               SHA_WORD_COLS)
        self.sha_slots.append({
            "bits": np.zeros((SHA_SLOT_ROWS, SHA_BIT_COLS), np.uint32),
            "words": np.zeros((SHA_SLOT_ROWS, SHA_WORD_COLS), np.uint64),
        })
        return len(self.sha_slots) - 1

    # -- stream access --
    def stream_values(self, stream) -> list[int]:
        assert stream == "adv", "handles only exist for the advice stream"
        return self.adv_values

    # -- primitive appends --
    def _push_unit(self, vals: list[int], gated: bool) -> int:
        start = len(self.adv_values)
        self.adv_values.extend(v % R for v in vals)
        self.adv_units.append((start, len(vals), gated))
        return start

    def load_witness(self, v: int) -> AssignedValue:
        v = int(v) % R
        start = len(self.adv_values)
        self.adv_values.append(v)
        self.adv_units.append((start, 1, False))
        return AssignedValue("adv", start, v)

    def load_constant(self, v: int) -> AssignedValue:
        v = int(v) % R
        start = len(self.adv_values)
        self.adv_values.append(v)
        self.adv_units.append((start, 1, False))
        row = self.constants.setdefault(v, len(self.constants))
        self.const_uses.append((start, row))
        return AssignedValue("adv", start, v)

    def load_zero(self) -> AssignedValue:
        return self.load_constant(0)

    def gate_unit(self, vals: list[int], copy_from: list) -> list[AssignedValue]:
        """Append a gated 4-cell unit. copy_from[i] is None (fresh cell),
        an AssignedValue (equality to an existing cell), or ("const", v)."""
        assert len(vals) == 4
        start = self._push_unit(vals, gated=True)
        adv = self.adv_values
        out = []
        for i, src in enumerate(copy_from):
            av = AssignedValue("adv", start + i, adv[start + i])
            if isinstance(src, AssignedValue):
                assert src.value == adv[start + i], "copy value mismatch"
                self.copies.append(((src.stream, src.index), ("adv", start + i)))
            elif isinstance(src, tuple) and src and src[0] == "const":
                row = self.constants.setdefault(src[1] % R, len(self.constants))
                self.const_uses.append((start + i, row))
            out.append(av)
        return out

    def gate_unit_out(self, v0: int, v1: int, v2: int, v3: int,
                      s0, s1, s2, s3, out_i: int) -> AssignedValue:
        """Fast path: append one gated unit, return ONLY the out_i cell.
        Sources s0..s3: None (fresh), AssignedValue (copy), or an int
        (constant-pin). Values must already be reduced mod R."""
        start = len(self.adv_values)
        adv = self.adv_values
        adv.append(v0), adv.append(v1), adv.append(v2), adv.append(v3)
        self.adv_units.append((start, 4, True))
        copies = self.copies
        const_uses = self.const_uses
        constants = self.constants
        i = start
        for src in (s0, s1, s2, s3):
            if src is not None:
                if src.__class__ is AssignedValue:
                    assert src.value == adv[i], "copy value mismatch"
                    copies.append(((src.stream, src.index), ("adv", i)))
                else:  # int constant
                    row = constants.setdefault(src, len(constants))
                    const_uses.append((i, row))
            i += 1
        return AssignedValue("adv", start + out_i, adv[start + out_i])

    # -- bulk primitives (vectorized witness generation) ----------------
    # Death-by-a-thousand-cuts fix: per-op Python call overhead dominated
    # witness-gen profiles (~18us/gate unit), so hot chips build value lists
    # in tight loops and append through these. Constraint semantics are
    # IDENTICAL to the per-op paths — only the append mechanics change.

    def bulk_cells(self, vals: list[int]) -> int:
        """Append ungated witness cells as ONE splittable unit record.
        vals must already be reduced mod R. Returns the start index."""
        start = len(self.adv_values)
        self.adv_values.extend(vals)
        self.adv_units.append((start, len(vals), False))
        return start

    def bulk_gated(self, flat_vals: list[int]) -> int:
        """Append len(flat_vals)//4 gated 4-cell units (values reduced mod R).
        Returns the start index; callers register copies/pins themselves."""
        start = len(self.adv_values)
        self.adv_values.extend(flat_vals)
        self.adv_units.extend(
            (start + i, 4, True) for i in range(0, len(flat_vals), 4))
        return start

    def bulk_lookup(self, table: str, idx_val_pairs) -> None:
        """Push (adv index, value) pairs into a lookup table stream."""
        stream = self.lkp_streams.setdefault(table, [])
        base = len(stream)
        copies = self.copies
        key = ("lkp", table)
        for j, (i, v) in enumerate(idx_val_pairs):
            stream.append(v)
            copies.append((("adv", i), (key, base + j)))

    def pin_const(self, adv_idx: int, v: int) -> None:
        """Constant-pin an advice cell by index (value already reduced)."""
        row = self.constants.setdefault(v, len(self.constants))
        self.const_uses.append((adv_idx, row))

    def push_lookup(self, av: AssignedValue) -> None:
        """Copy a cell into the range-table lookup stream."""
        self.push_lookup_table(av, "range")

    def push_lookup_table(self, av: AssignedValue, table: str) -> None:
        """Copy a cell into the lookup stream of the given table."""
        assert av.stream == "adv"
        stream = self.lkp_streams.setdefault(table, [])
        idx = len(stream)
        stream.append(av.value)
        self.copies.append((("adv", av.index), (("lkp", table), idx)))

    def constrain_equal(self, a: AssignedValue, b: AssignedValue):
        assert a.value == b.value, "constrain_equal on unequal values"
        self.copies.append(((a.stream, a.index), (b.stream, b.index)))

    def constrain_constant(self, a: AssignedValue, v: int):
        assert a.value == int(v) % R, "constrain_constant mismatch"
        row = self.constants.setdefault(int(v) % R, len(self.constants))
        self.const_uses.append((a.index, row))
        assert a.stream == "adv"

    def expose_public(self, a: AssignedValue):
        """Append a cell to the instance column (copy-constrained)."""
        self.instance_cells.append(a)

    # ------------------------------------------------------------------
    # finalize: streams -> physical columns -> plonk.Assignment
    # ------------------------------------------------------------------

    def cell_references(self) -> dict:
        """Analysis hook (spectre_tpu.analysis.circuit_audit): per-cell
        reference metadata for the advice stream. A cell is CONSTRAINED when
        it sits inside a gated unit (the vertical gate reads all 4 rows) or
        is an endpoint of a copy constraint / constant pin / lookup push /
        instance exposure; an ungated cell with no reference is a free
        witness the proof never binds — the under-constrained bug class.

        Returns {"n_cells", "gated", "referenced"}; the latter two are
        bytearrays indexed by advice-stream position (1 = covered)."""
        n = len(self.adv_values)
        gated = bytearray(n)
        referenced = bytearray(n)
        for start, size, is_gated in self.adv_units:
            if is_gated:
                gated[start:start + size] = b"\x01" * size
        for (sa, ia), (sb, ib) in self.copies:
            if sa == "adv" and 0 <= ia < n:
                referenced[ia] = 1
            if sb == "adv" and 0 <= ib < n:
                referenced[ib] = 1
        for adv_idx, _row in self.const_uses:
            if 0 <= adv_idx < n:
                referenced[adv_idx] = 1
        for av in self.instance_cells:
            if av.stream == "adv" and 0 <= av.index < n:
                referenced[av.index] = 1
        return {"n_cells": n, "gated": gated, "referenced": referenced}

    def stats(self) -> dict:
        return {
            "advice_cells": len(self.adv_values),
            "lookup_cells": {t: len(v) for t, v in self.lkp_streams.items()},
            "copies": len(self.copies),
            "constants": len(self.constants),
            "instances": len(self.instance_cells),
        }

    def auto_config(self, k: int, lookup_bits: int, min_advice: int = 1) -> CircuitConfig:
        """Column counts sized from actual stream lengths (reference parity:
        halo2-lib `calculate_params`, `sync_step_circuit.rs:421-427`)."""
        probe = CircuitConfig(k=k, num_advice=1, num_lookup_advice=1,
                              num_fixed=1, lookup_bits=lookup_bits,
                              num_sha_slots=len(self.sha_slots))
        u = probe.usable_rows
        # advice columns: account for per-unit padding at column breaks (worst
        # case wastes <= 3 rows per column)
        num_advice = max(min_advice, (len(self.adv_values) + u - 1) // (u - 3))
        tables = []
        for tid in sorted(self.lkp_streams):
            ncols = max(1, (len(self.lkp_streams[tid]) + u - 1) // u)
            tables.extend([tid] * ncols)
        if not tables:
            tables = ["range"]  # config always carries at least one table
        num_fixed = max(1, (len(self.constants) + u - 1) // u)
        nsl = len(self.sha_slots)
        if nsl:
            from ..plonk.constraint_system import SHA_SLOT_ROWS
            assert nsl * SHA_SLOT_ROWS <= u, \
                "sha slots exceed usable rows: raise k"
        return CircuitConfig(k=k, num_advice=num_advice,
                             num_lookup_advice=len(tables), num_fixed=num_fixed,
                             lookup_bits=lookup_bits, lookup_tables=tuple(tables),
                             num_sha_slots=nsl)

    def layout(self, cfg: CircuitConfig):
        """Place units into columns. Returns (advice_cols, lookup_cols,
        fixed_cols, selectors, copies, instances) for plonk.Assignment —
        and the break points (row where each column's stream segment ends).

        Memoized on the config: `create_pk` runs layout once for the pinning
        (break points) and once for the assignment — at 30M cells each pass
        is minutes of pure Python, so the second is a cache hit."""
        cached = getattr(self, "_layout_cache", None)
        if cached is not None and cached[0] == cfg:
            return cached[1]
        result = self._layout_uncached(cfg)
        self._layout_cache = (cfg, result)
        return result

    def _layout_uncached(self, cfg: CircuitConfig):
        n, u = cfg.n, cfg.usable_rows
        advice = [[0] * n for _ in range(cfg.num_advice)]
        selectors = [[0] * n for _ in range(cfg.num_advice)]
        placement = {}  # adv stream idx -> (col, row)
        col, row = 0, 0
        break_points = []
        for start, size, gated in self.adv_units:
            if gated:
                # gated units are a vertical-gate activation over 4 consecutive
                # rows (or a sequence of such for bulk records): each 4-block
                # must stay contiguous within a column
                for off in range(0, size, 4):
                    if row + 4 > u:
                        break_points.append(row)
                        col += 1
                        row = 0
                        assert col < cfg.num_advice, \
                            "advice overflow: raise k or columns"
                    acol, s = advice[col], start + off
                    acol[row] = self.adv_values[s]
                    acol[row + 1] = self.adv_values[s + 1]
                    acol[row + 2] = self.adv_values[s + 2]
                    acol[row + 3] = self.adv_values[s + 3]
                    placement[s] = (col, row)
                    placement[s + 1] = (col, row + 1)
                    placement[s + 2] = (col, row + 2)
                    placement[s + 3] = (col, row + 3)
                    selectors[col][row] = 1
                    row += 4
            else:
                # ungated cells carry no relative-rotation constraint: split
                # freely across column boundaries
                done = 0
                while done < size:
                    if row >= u:
                        break_points.append(row)
                        col += 1
                        row = 0
                        assert col < cfg.num_advice, \
                            "advice overflow: raise k or columns"
                    take = min(size - done, u - row)
                    acol = advice[col]
                    for i in range(take):
                        acol[row + i] = self.adv_values[start + done + i]
                        placement[start + done + i] = (col, row + i)
                    done += take
                    row += take
        break_points.append(row)

        lookup = [[0] * n for _ in range(cfg.num_lookup_advice)]
        lkp_placement = {}
        # columns grouped by table id (order must match cfg.lookup_tables)
        cols_for_table: dict[str, list[int]] = {}
        for j in range(cfg.num_lookup_advice):
            cols_for_table.setdefault(cfg.table_id(j), []).append(j)
        for tid, stream in self.lkp_streams.items():
            cols = cols_for_table.get(tid, [])
            assert cols, f"no lookup column configured for table {tid}"
            for idx, v in enumerate(stream):
                ci, r = divmod(idx, u)
                assert ci < len(cols), f"lookup overflow for table {tid}"
                c = cols[ci]
                lookup[c][r] = v
                lkp_placement[(tid, idx)] = (c, r)

        fixed = [[0] * n for _ in range(cfg.num_fixed)]
        fix_placement = {}
        for v, row_f in self.constants.items():
            c, r = divmod(row_f, u)
            assert c < cfg.num_fixed, "fixed overflow"
            fixed[c][r] = v
            fix_placement[row_f] = (c, r)

        # translate copies to global column coordinates
        def cell_coord(stream, idx):
            if stream == "adv":
                c, r = placement[idx]
                return (cfg.col_gate_advice(c), r)
            if stream == "shwc":
                j, grow = idx
                return (cfg.col_sha_word(j), grow)
            c, r = lkp_placement[(stream[1], idx)]
            return (cfg.col_lookup_advice(c), r)

        copies = [(cell_coord(*a), cell_coord(*b)) for a, b in self.copies]
        for adv_idx, fix_row in self.const_uses:
            c, r = fix_placement[fix_row]
            copies.append((cell_coord("adv", adv_idx), (cfg.col_fixed(c), r)))

        instances = [[av.value for av in self.instance_cells]]
        for i, av in enumerate(self.instance_cells):
            copies.append((cell_coord(av.stream, av.index),
                           (cfg.col_instance(0), i)))
        # stash the physical placement for the row-wise coverage audit
        # (analysis/circuit_audit): rebuilt together with the layout, so the
        # two caches can never disagree about which cfg they describe
        self._placement_cache = (cfg, placement)
        return advice, lookup, fixed, selectors, copies, instances, break_points

    def cell_placement(self, cfg: CircuitConfig) -> dict:
        """Analysis hook (spectre_tpu.analysis.circuit_audit): physical
        placement of the advice stream, {stream index -> (column, row)}.
        The row auditor joins this against the layout's selector grid and
        copy endpoints to find rows no gate window or copy binds."""
        cached = getattr(self, "_placement_cache", None)
        if cached is not None and cached[0] == cfg:
            return cached[1]
        result = self._layout_uncached(cfg)
        self._layout_cache = (cfg, result)
        cached = self._placement_cache
        assert cached[0] == cfg
        return cached[1]

    def sha_columns(self, cfg: CircuitConfig):
        """Materialize the slot list into full [cols, n] region columns."""
        import numpy as np
        from ..plonk.constraint_system import (SHA_BIT_COLS, SHA_SLOT_ROWS,
                                               SHA_WORD_COLS)
        if not self.sha_slots:
            return None, None
        assert cfg.num_sha_slots >= len(self.sha_slots), \
            "config allocates fewer sha slots than the circuit used"
        n = cfg.n
        sha_bit = np.zeros((SHA_BIT_COLS, n), np.uint32)
        sha_word = np.zeros((SHA_WORD_COLS, n), np.uint64)
        for s, slot in enumerate(self.sha_slots):
            base = s * SHA_SLOT_ROWS
            sha_bit[:, base:base + SHA_SLOT_ROWS] = slot["bits"].T
            sha_word[:, base:base + SHA_SLOT_ROWS] = slot["words"].T
        return sha_bit, sha_word

    def assignment(self, cfg: CircuitConfig) -> Assignment:
        advice, lookup, fixed, selectors, copies, instances, _bp = self.layout(cfg)
        sha_bit, sha_word = self.sha_columns(cfg)
        return Assignment(cfg, advice, lookup, fixed, selectors, instances,
                          copies, sha_bit=sha_bit, sha_word=sha_word)

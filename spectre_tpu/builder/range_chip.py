"""RangeChip: range checks via the lookup table, comparisons, div_mod.

Reference parity: halo2-base `RangeChip` (lookup_bits-limb decomposition into
lookup-enabled columns; `check_less_than`, `div_mod`) — the workhorse under
all bigint/Fp arithmetic (SURVEY.md L2).
"""

from __future__ import annotations

from ..fields import bn254
from .context import AssignedValue, Context
from .gate import GateChip

R = bn254.R


class RangeChip:
    def __init__(self, lookup_bits: int, gate: GateChip | None = None):
        self.lookup_bits = lookup_bits
        self.gate = gate or GateChip()

    def range_check(self, ctx: Context, a: AssignedValue, nbits: int):
        """Constrain 0 <= a < 2^nbits via lookup_bits-limb decomposition
        (bulk-appended: one splittable witness record + bulk lookup pushes)."""
        lb = self.lookup_bits
        av = a.value
        assert av < (1 << nbits), f"range_check witness {av} >= 2^{nbits}"
        nlimbs = (nbits + lb - 1) // lb
        rem = nbits - (nlimbs - 1) * lb      # bits of the top limb
        mask = (1 << lb) - 1
        limb_vals = [(av >> (lb * i)) & mask for i in range(nlimbs)]
        start = ctx.bulk_cells(limb_vals)
        ctx.bulk_lookup("range",
                        [(start + i, v) for i, v in enumerate(limb_vals)])
        limbs = [AssignedValue("adv", start + i, v)
                 for i, v in enumerate(limb_vals)]
        # top limb tighter bound: limb * 2^(lb-rem) must also be in table
        if rem < lb:
            shifted = self.gate.mul(ctx, limbs[-1], 1 << (lb - rem))
            ctx.push_lookup(shifted)
        acc = self.gate.inner_product_const(
            ctx, limbs, [1 << (lb * i) for i in range(nlimbs)])
        ctx.constrain_equal(acc, a)
        return limbs

    def check_less_than(self, ctx: Context, a: AssignedValue, b: AssignedValue,
                        nbits: int):
        """Constrain a < b, given both already known < 2^nbits."""
        # shifted = a - b + 2^nbits  in [0, 2^nbits)  iff  a < b
        t = self.gate.add(ctx, a, (1 << nbits) % R)
        shifted = self.gate.sub(ctx, t, b)
        self.range_check(ctx, shifted, nbits)

    def is_less_than(self, ctx: Context, a: AssignedValue, b: AssignedValue,
                     nbits: int) -> AssignedValue:
        """Return bit (a < b); both < 2^nbits. shifted = a - b + 2^nbits has
        bit nbits set iff a >= b."""
        t = self.gate.add(ctx, a, (1 << nbits) % R)
        shifted = self.gate.sub(ctx, t, b)
        sv = shifted.value
        hi = ctx.load_witness(sv >> nbits)      # 0 or 1
        self.gate.assert_bit(ctx, hi)
        lo = ctx.load_witness(sv & ((1 << nbits) - 1))
        self.range_check(ctx, lo, nbits)
        acc = self.gate.mul_add(ctx, hi, (1 << nbits) % R, lo)
        ctx.constrain_equal(acc, shifted)
        return self.gate.not_(ctx, hi)

    def div_mod(self, ctx: Context, a: AssignedValue, divisor: int,
                nbits: int):
        """(q, r) with a = q*divisor + r, 0 <= r < divisor, a < 2^nbits."""
        av = a.value
        q_v, r_v = divmod(av, divisor)
        q = ctx.load_witness(q_v)
        r = ctx.load_witness(r_v)
        acc = self.gate.mul_add(ctx, q, divisor % R, r)
        ctx.constrain_equal(acc, a)
        self.range_check(ctx, q, nbits)
        # r < divisor
        d_bits = max((divisor - 1).bit_length(), 1)
        self.range_check(ctx, r, d_bits)
        dc = ctx.load_constant(divisor)
        self.check_less_than(ctx, r, dc, d_bits + 1)
        return q, r

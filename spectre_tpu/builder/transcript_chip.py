"""In-circuit mirror of the PoseidonTranscript (Fiat–Shamir as constraints).

Reference parity: snark-verifier's `PoseidonTranscript<Rc<Halo2Loader>>` —
the aggregation circuit re-derives every challenge of the inner proof's
transcript as circuit cells, so the verified statement is bound to the exact
proof bytes (`aggregation_circuit.rs:69-124` uses it through the SDK's
`Halo2Loader`).

Cell-for-cell mirror of `plonk.transcript.PoseidonTranscript`: same duplex
schedule (flush pending in RATE chunks, counter element before each squeeze),
same point encoding (3 x 88-bit limbs per coordinate, the cells the MSM
operates on), so `challenge().value` equals the native transcript's output.
"""

from __future__ import annotations

from ..fields import bn254
from .context import AssignedValue, Context
from .poseidon_chip import PoseidonChip

R = bn254.R


class TranscriptChip:
    def __init__(self, poseidon: PoseidonChip | None = None):
        from ..plonk.transcript import PoseidonTranscript as PT
        self.pos = poseidon or PoseidonChip(t=PT.T, rate=PT.RATE,
                                            r_f=PT.R_F, r_p=PT.R_P)
        self.gate = self.pos.gate
        self._state: list | None = None
        self._pending: list = []
        self._counter = 0

    def _ensure_state(self, ctx: Context):
        if self._state is None:
            self._state = [ctx.load_constant(0) for _ in range(self.pos.t)]

    # -- absorbs ----------------------------------------------------------
    def absorb(self, cells):
        """Queue field-element cells (instance values, eval scalars, point
        limbs — already range-checked by their producers)."""
        self._pending.extend(cells)

    def absorb_constant_bytes(self, ctx: Context, b: bytes):
        """Constants (the vk digest): 16-byte BE chunks, as native side."""
        for off in range(0, len(b), 16):
            self._pending.append(
                ctx.load_constant(int.from_bytes(b[off:off + 16], "big")))

    def absorb_point_limbs(self, ctx: Context, xy_limbs: list):
        """6 limb cells (x lo->hi, y lo->hi), the transcript point encoding."""
        assert len(xy_limbs) == 6
        self._pending.extend(xy_limbs)

    # -- squeeze ----------------------------------------------------------
    def challenge(self, ctx: Context) -> AssignedValue:
        self._ensure_state(ctx)
        gate = self.gate
        self._counter += 1
        self._pending.append(ctx.load_constant(self._counter))
        state = self._state
        rate = self.pos.rate
        pend = self._pending
        for off in range(0, len(pend), rate):
            chunk = pend[off:off + rate]
            state = ([state[0]]
                     + [gate.add(ctx, state[1 + i], v) for i, v in enumerate(chunk)]
                     + state[1 + len(chunk):])
            state = self.pos.permute(ctx, state)
        self._pending = []
        self._state = state
        return state[1]

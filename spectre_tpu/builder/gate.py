"""GateChip: arithmetic over the vertical gate q*(s0 + s1*s2 - s3) = 0.

Reference parity: halo2-base `GateChip` (flex-gate instructions) — add, mul,
mul_add, select, is_zero, inner products, bit decomposition. Every op appends
one or more 4-cell gate units; inputs are copy-constrained into the unit.
"""

from __future__ import annotations

from ..fields import bn254
from .context import AssignedValue, Context

R = bn254.R


def _v(x) -> int:
    return x.value if isinstance(x, AssignedValue) else int(x) % R


def _src(x, xv):
    """Copy source for an operand: the cell itself, or its value as a
    constant pin."""
    return x if x.__class__ is AssignedValue else xv


class GateChip:
    # -- basic ops ------------------------------------------------------
    def add(self, ctx: Context, a, b) -> AssignedValue:
        """out = a + b  via  [a, b, 1, out]."""
        av, bv = _v(a), _v(b)
        return ctx.gate_unit_out(av, bv, 1, (av + bv) % R,
                                 _src(a, av), _src(b, bv), 1, None, 3)

    def sub(self, ctx: Context, a, b) -> AssignedValue:
        """out = a - b  via  [out, b, 1, a]."""
        av, bv = _v(a), _v(b)
        return ctx.gate_unit_out((av - bv) % R, bv, 1, av,
                                 None, _src(b, bv), 1, _src(a, av), 0)

    def neg(self, ctx: Context, a) -> AssignedValue:
        return self.sub(ctx, 0, a)

    def mul(self, ctx: Context, a, b) -> AssignedValue:
        """out = a * b  via  [0, a, b, out]."""
        av, bv = _v(a), _v(b)
        return ctx.gate_unit_out(0, av, bv, av * bv % R,
                                 0, _src(a, av), _src(b, bv), None, 3)

    def mul_add(self, ctx: Context, a, b, c) -> AssignedValue:
        """out = a * b + c  via  [c, a, b, out]."""
        av, bv, cv = _v(a), _v(b), _v(c)
        return ctx.gate_unit_out(cv, av, bv, (cv + av * bv) % R,
                                 _src(c, cv), _src(a, av), _src(b, bv), None, 3)

    def div_unsafe(self, ctx: Context, a, b) -> AssignedValue:
        """out = a / b (b must be nonzero; only the product is constrained)."""
        av, bv = _v(a), _v(b)
        q = av * pow(bv, -1, R) % R
        return ctx.gate_unit_out(0, q, bv, av,
                                 0, None, _src(b, bv), _src(a, av), 1)

    # -- boolean -------------------------------------------------------
    def assert_bit(self, ctx: Context, a: AssignedValue):
        """a * a = a  via  [0, a, a, a]."""
        av = _v(a)
        ctx.gate_unit([0, av, av, av], [("const", 0), a, a, a])

    def and_(self, ctx: Context, a, b) -> AssignedValue:
        return self.mul(ctx, a, b)

    def not_(self, ctx: Context, a) -> AssignedValue:
        return self.sub(ctx, 1, a)

    def or_(self, ctx: Context, a, b) -> AssignedValue:
        # a + b - a*b
        ab = self.mul(ctx, a, b)
        s = self.add(ctx, a, b)
        return self.sub(ctx, s, ab)

    def select(self, ctx: Context, a, b, sel) -> AssignedValue:
        """sel ? a : b  =  b + sel*(a-b)."""
        d = self.sub(ctx, a, b)
        return self.mul_add(ctx, sel, d, b)

    def is_zero(self, ctx: Context, a) -> AssignedValue:
        """out = (a == 0), via out*a = 0 and out + a*inv = 1."""
        av = _v(a)
        out_v = 1 if av == 0 else 0
        inv_v = 0 if av == 0 else pow(av, -1, R)
        a_src = a if isinstance(a, AssignedValue) else ("const", av)
        # 0 + out*a = 0
        cells = ctx.gate_unit([0, out_v, av, 0],
                              [("const", 0), None, a_src, ("const", 0)])
        out = cells[1]
        # out + a*inv = 1
        ctx.gate_unit([out_v, av, inv_v, 1],
                      [out, a_src if not isinstance(a, AssignedValue) else a,
                       None, ("const", 1)])
        return out

    def is_equal(self, ctx: Context, a, b) -> AssignedValue:
        return self.is_zero(ctx, self.sub(ctx, a, b))

    # -- aggregates ----------------------------------------------------
    def sum_(self, ctx: Context, vals) -> AssignedValue:
        acc = None
        for v in vals:
            acc = v if acc is None else self.add(ctx, acc, v)
        return acc if acc is not None else ctx.load_zero()

    def inner_product(self, ctx: Context, a_vals, b_vals) -> AssignedValue:
        """sum a_i * b_i as a mul_add chain (bulk-appended: [c, a, b, out]
        units where c chains the previous out; first unit is a bare mul)."""
        assert len(a_vals) == len(b_vals) and a_vals
        copies = ctx.copies
        pos = len(ctx.adv_values)
        flat = []
        acc = 0
        first = True
        for x, y in zip(a_vals, b_vals):
            if x.__class__ is AssignedValue:
                xv = x.value
                copies.append((("adv", x.index), ("adv", pos + 1)))
            else:
                xv = int(x) % R
                ctx.pin_const(pos + 1, xv)
            if y.__class__ is AssignedValue:
                yv = y.value
                copies.append((("adv", y.index), ("adv", pos + 2)))
            else:
                yv = int(y) % R
                ctx.pin_const(pos + 2, yv)
            if first:
                ctx.pin_const(pos, 0)
                first = False
            else:
                copies.append((("adv", pos - 1), ("adv", pos)))
            out = (acc + xv * yv) % R
            flat.append(acc), flat.append(xv), flat.append(yv), flat.append(out)
            acc = out
            pos += 4
        ctx.bulk_gated(flat)
        return AssignedValue("adv", pos - 1, acc)

    def inner_product_const(self, ctx: Context, vals, consts) -> AssignedValue:
        """sum vals_i * c_i with host constants c_i (bulk-appended chain)."""
        assert len(vals) == len(consts) and vals
        copies = ctx.copies
        pos = len(ctx.adv_values)
        flat = []
        acc = 0
        first = True
        for x, cst in zip(vals, consts):
            c = int(cst) % R
            if x.__class__ is AssignedValue:
                xv = x.value
                copies.append((("adv", x.index), ("adv", pos + 1)))
            else:
                xv = int(x) % R
                ctx.pin_const(pos + 1, xv)
            ctx.pin_const(pos + 2, c)
            if first:
                ctx.pin_const(pos, 0)
                first = False
            else:
                copies.append((("adv", pos - 1), ("adv", pos)))
            out = (acc + xv * c) % R
            flat.append(acc), flat.append(xv), flat.append(c), flat.append(out)
            acc = out
            pos += 4
        ctx.bulk_gated(flat)
        return AssignedValue("adv", pos - 1, acc)

    def add_pairs(self, ctx: Context, pairs) -> list:
        """Elementwise a+b over (a, b) pairs, bulk-appended [a, b, 1, out]
        units (identical constraints to add())."""
        copies = ctx.copies
        pin = ctx.pin_const
        pos = len(ctx.adv_values)
        flat = []
        outs = []
        for a, b in pairs:
            if a.__class__ is AssignedValue:
                av = a.value
                copies.append((("adv", a.index), ("adv", pos)))
            else:
                av = int(a) % R
                pin(pos, av)
            if b.__class__ is AssignedValue:
                bv = b.value
                copies.append((("adv", b.index), ("adv", pos + 1)))
            else:
                bv = int(b) % R
                pin(pos + 1, bv)
            pin(pos + 2, 1)
            out = (av + bv) % R
            flat.append(av), flat.append(bv), flat.append(1), flat.append(out)
            outs.append(AssignedValue("adv", pos + 3, out))
            pos += 4
        ctx.bulk_gated(flat)
        return outs

    def sub_pairs(self, ctx: Context, pairs) -> list:
        """Elementwise a-b over (a, b) pairs, bulk-appended [out, b, 1, a]
        units (identical constraints to sub())."""
        copies = ctx.copies
        pin = ctx.pin_const
        pos = len(ctx.adv_values)
        flat = []
        outs = []
        for a, b in pairs:
            av = a.value if a.__class__ is AssignedValue else int(a) % R
            if b.__class__ is AssignedValue:
                bv = b.value
                copies.append((("adv", b.index), ("adv", pos + 1)))
            else:
                bv = int(b) % R
                pin(pos + 1, bv)
            pin(pos + 2, 1)
            if a.__class__ is AssignedValue:
                copies.append((("adv", a.index), ("adv", pos + 3)))
            else:
                pin(pos + 3, av)
            out = (av - bv) % R
            flat.append(out), flat.append(bv), flat.append(1), flat.append(av)
            outs.append(AssignedValue("adv", pos, out))
            pos += 4
        ctx.bulk_gated(flat)
        return outs

    def num_to_bits(self, ctx: Context, a: AssignedValue, nbits: int) -> list:
        """Little-endian bit decomposition, each bit boolean-constrained and
        the recomposition equality-constrained to a."""
        av = _v(a)
        assert av < (1 << nbits), "value too large for bit width"
        bits = []
        for i in range(nbits):
            b = ctx.load_witness((av >> i) & 1)
            self.assert_bit(ctx, b)
            bits.append(b)
        acc = self.inner_product_const(ctx, bits, [1 << i for i in range(nbits)])
        ctx.constrain_equal(acc, a)
        return bits

    def bits_to_num(self, ctx: Context, bits) -> AssignedValue:
        return self.inner_product_const(ctx, bits, [1 << i for i in range(len(bits))])

    def pow_const(self, ctx: Context, a: AssignedValue, e: int) -> AssignedValue:
        result = None
        base = a
        while e:
            if e & 1:
                result = base if result is None else self.mul(ctx, result, base)
            e >>= 1
            if e:
                base = self.mul(ctx, base, base)
        return result if result is not None else ctx.load_constant(1)

"""CommitteeUpdateCircuit: map the next sync committee to its commitments.

Reference parity: `committee_update_circuit.rs` — in-circuit logic
(`assign_virtual:50`): SSZ root of the compressed pubkey list, X-coordinate
decode (`decode_pubkeys_x:129`), Poseidon commitment, finalized-header SSZ
root, committee-branch merkle proof against the finalized STATE root; public
outputs [poseidon_commit, header_root_lo, header_root_hi]
(`get_instances:198`).
"""

from __future__ import annotations

from ..builder import Context, GateChip
from ..builder.poseidon_chip import PoseidonChip
from ..builder.sha256_wide_chip import Sha256WideChip
from ..fields import bn254
from ..gadgets import poseidon_commit as PC
from ..gadgets import ssz_merkle as M
from ..spec import NUM_LIMBS
from ..witness.types import CommitteeUpdateArgs
from .app_circuit import AppCircuit

R = bn254.R


class CommitteeUpdateCircuit(AppCircuit):
    name = "committee_update"

    @classmethod
    def build(cls, ctx: Context, args: CommitteeUpdateArgs, spec):
        """Hashing runs on the wide-region chip (reference uses the zkevm
        wide SHA here for the same reason: this circuit is hash-dominated,
        `committee_update_circuit.rs:50` + `sha256_wide.rs`)."""
        gate = GateChip()
        sha = Sha256WideChip(gate)
        poseidon = PoseidonChip(gate)
        n = spec.sync_committee_size
        assert len(args.pubkeys_compressed) == n

        # load pubkey bytes (8-bit checked once; reused by SSZ + decode)
        pubkey_bytes = []
        for pk in args.pubkeys_compressed:
            assert len(pk) == 48
            pubkey_bytes.append(M.load_bytes_checked(ctx, sha, pk))

        # --- committee pubkeys SSZ root (leaf = sha256(pk padded to 64)) ---
        zero = ctx.load_constant(0)
        leaves = []
        for cells in pubkey_bytes:
            padded = cells + [zero] * 16
            leaves.append(sha.digest_bytes(ctx, padded))
        committee_root = M.merkleize_chunks(ctx, sha, leaves)

        # --- decode X coordinates + y signs; Poseidon commitment ---
        limbs_list, sign_cells = [], []
        for cells in pubkey_bytes:
            flag_byte = cells[0]  # big-endian first byte carries the 3 flags
            bits = gate.num_to_bits(ctx, flag_byte, 8)
            cleared = gate.bits_to_num(ctx, bits[:5])
            y_sign = bits[5]
            le_bytes = list(reversed(cells[1:])) + [cleared]  # little-endian X
            limbs = []
            for i in range(NUM_LIMBS):
                chunk = le_bytes[13 * i:13 * i + 13]
                if chunk:
                    limbs.append(gate.inner_product_const(
                        ctx, chunk, [1 << (8 * j) for j in range(len(chunk))]))
                else:
                    limbs.append(ctx.load_constant(0))
            limbs_list.append(limbs)
            sign_cells.append(y_sign)
        poseidon_commit = PC.g1_array_poseidon(ctx, gate, poseidon,
                                               limbs_list, sign_cells)

        # --- finalized header SSZ root ---
        def uint64_chunk_cells(v: int):
            cells = M.load_bytes_checked(ctx, sha, int(v).to_bytes(8, "little"))
            return cells + [zero] * 24

        def root_chunk_cells(b: bytes):
            return M.load_bytes_checked(ctx, sha, b)

        hdr = args.finalized_header
        state_root_cells = root_chunk_cells(hdr.state_root)
        header_chunks = [
            M.bytes_to_chunk(ctx, sha, uint64_chunk_cells(hdr.slot)),
            M.bytes_to_chunk(ctx, sha, uint64_chunk_cells(hdr.proposer_index)),
            M.bytes_to_chunk(ctx, sha, root_chunk_cells(hdr.parent_root)),
            M.bytes_to_chunk(ctx, sha, state_root_cells),
            M.bytes_to_chunk(ctx, sha, root_chunk_cells(hdr.body_root)),
        ]
        header_root = M.merkleize_chunks(ctx, sha, header_chunks, limit=8)

        # --- committee branch against the finalized state root ---
        branch = [M.bytes_to_chunk(ctx, sha, root_chunk_cells(b))
                  for b in args.sync_committee_branch]
        state_chunk = M.bytes_to_chunk(ctx, sha, state_root_cells)
        M.verify_merkle_proof(ctx, sha, committee_root, branch,
                              spec.sync_committee_pubkeys_root_index, state_chunk)

        # --- public inputs: [poseidon, header_root_lo, header_root_hi] ---
        hi, lo = M.chunk_to_le_hilo(ctx, gate, header_root)
        ctx.expose_public(poseidon_commit)
        ctx.expose_public(lo)
        ctx.expose_public(hi)
        return [poseidon_commit, lo, hi]

    @classmethod
    def get_instances(cls, args: CommitteeUpdateArgs, spec) -> list:
        """Native recomputation (reference `get_instances:198`)."""
        from ..fields import bls12_381 as bls
        from ..ops.field384 import g1_decompress_batch
        pts = [(bls.Fq(x), bls.Fq(y)) for x, y in
               g1_decompress_batch(list(args.pubkeys_compressed))]
        poseidon = PC.committee_poseidon_from_uncompressed(pts)
        root = args.finalized_header.hash_tree_root()
        lo = int.from_bytes(root[16:], "big")
        hi = int.from_bytes(root[:16], "big")
        return [poseidon, lo, hi]

"""Application circuits — the "model families" of this framework.

Reference parity (SURVEY.md L3): `sync_step_circuit.rs` (StepCircuit),
`committee_update_circuit.rs` (CommitteeUpdateCircuit),
`aggregation_circuit.rs` (proof compression). Circuits are written against
the builder chips and proved by the plonk backend (cpu or tpu).
"""

from .aggregation import (AggregationArgs, AggregationCircuit,  # noqa: F401
                          Accumulator)
from .app_circuit import AppCircuit  # noqa: F401
from .committee_update import CommitteeUpdateCircuit  # noqa: F401
from .step import StepCircuit  # noqa: F401

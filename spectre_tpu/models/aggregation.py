"""Proof aggregation / compression circuit.

Reference parity: `aggregation_circuit.rs:69-124` — snark-verifier's
`AggregationCircuit`: one-layer SHPLONK compression of an app snark. The
inner proof (generated with the Poseidon transcript) is verified entirely
in-circuit (`plonk/in_circuit.py`); the final pairing is NOT performed —
its two G1 inputs are exposed as 12 x 88-bit limbs followed by the app
instances (`expose_previous_instances(false)` layout), so the outer
verifier (EVM contract or host) finishes with ONE pairing check.

Statement: [lhs.x (3), lhs.y (3), rhs.x (3), rhs.y (3), app instances...]
where e(lhs, [tau]_2) == e(rhs, [1]_2) iff the inner proof verifies.
"""

from __future__ import annotations

from dataclasses import dataclass

from ..builder.range_chip import RangeChip
from ..fields import bn254
from ..plonk.srs import SRS
from ..plonk.transcript import PoseidonTranscript
from ..plonk.verifier import verify as plonk_verify
from .app_circuit import AppCircuit

R = bn254.R
ACC_LIMB_BITS = 88
ACC_LIMBS_PER_COORD = 3  # 12 limbs total: (lhs.x, lhs.y, rhs.x, rhs.y) x 3
NUM_ACC_LIMBS = 12


@dataclass
class Accumulator:
    """Deferred KZG pairing check: e(lhs, [tau]_2) == e(rhs, [1]_2)."""

    lhs: object  # G1 point
    rhs: object

    def limbs(self) -> list[int]:
        """12 x 88-bit limbs, the aggregation circuit's first instances
        (reference: snark-verifier `LimbsEncoding<3, 88>`)."""
        out = []
        for pt in (self.lhs, self.rhs):
            for coord in (int(pt[0]), int(pt[1])):
                for i in range(ACC_LIMBS_PER_COORD):
                    out.append((coord >> (ACC_LIMB_BITS * i))
                               & ((1 << ACC_LIMB_BITS) - 1))
        return out

    @classmethod
    def from_limbs(cls, limbs: list) -> "Accumulator":
        assert len(limbs) >= NUM_ACC_LIMBS
        coords = []
        for c in range(4):
            v = sum(int(limbs[3 * c + i]) << (ACC_LIMB_BITS * i)
                    for i in range(ACC_LIMBS_PER_COORD))
            coords.append(bn254.Fq(v))
        return cls(lhs=(coords[0], coords[1]), rhs=(coords[2], coords[3]))

    def check(self, srs: SRS) -> bool:
        g1 = bn254.g1_curve
        return bn254.pairing_check([
            (self.lhs, srs.g2_tau),
            (g1.neg(self.rhs), srs.g2_gen),
        ])


def accumulate(accs: list[Accumulator]) -> Accumulator:
    """Linear-combination of deferred pairing checks into one. Challenges are
    transcript-derived from the accumulator points themselves (Fiat–Shamir,
    re-derivable by any verifier — `ADVICE.md` round-1: local randomness is
    unusable for an in-circuit accumulator)."""
    g1 = bn254.g1_curve
    tr = PoseidonTranscript()
    for acc in accs:
        tr.common_point(acc.lhs)
        tr.common_point(acc.rhs)
    lhs, rhs = None, None
    for acc in accs:
        r = tr.challenge()
        lhs = g1.add(lhs, g1.mul(acc.lhs, r))
        rhs = g1.add(rhs, g1.mul(acc.rhs, r))
    return Accumulator(lhs, rhs)


@dataclass
class SnarkWitness:
    """One inner snark: its verifying key, public inputs, and proof bytes
    (reference: snark-verifier-sdk's `Snark` — the unit the aggregation
    circuit consumes)."""

    vk: object                  # plonk VerifyingKey
    instances: list             # [[int]] public inputs
    proof: bytes                # Poseidon-transcript proof


@dataclass
class AggregationArgs:
    """Witness for one compression layer: the inner proof(s) and context.

    Single-snark compression (the service's two-stage flow) uses the first
    four fields; `more_snarks` adds further inner proofs, RLC-folded into
    ONE deferred accumulator with transcript-bound challenges (reference:
    `AggregationCircuit::new(Vec<Snark>)` aggregating N snarks)."""

    inner_vk: object            # plonk VerifyingKey of the app circuit
    srs: SRS
    inner_instances: list       # [[int]] app public inputs
    proof: bytes                # Poseidon-transcript app proof
    more_snarks: tuple = ()     # additional SnarkWitness entries

    @property
    def snarks(self) -> list:
        return [SnarkWitness(self.inner_vk, self.inner_instances,
                             self.proof)] + list(self.more_snarks)


class AggregationCircuit(AppCircuit):
    """In-circuit SHPLONK verification of one app snark.

    The app snark must be generated with `PoseidonTranscript` (the
    aggregation-bound transcript, reference: snark-verifier's
    `gen_snark_shplonk`); the outer proof itself can use any transcript —
    Keccak for the EVM path (`gen_evm_proof_shplonk` role)."""

    name = "aggregation"
    default_lookup_bits = 14

    @classmethod
    def variant(cls, inner_name: str):
        """Subclass with a distinct name, so pk/pinning caches of different
        inner circuits don't collide (reference: per-circuit verifier pkeys
        in `ProverState::new`)."""
        return type(f"AggregationCircuit_{inner_name}", (cls,),
                    {"name": f"aggregation_{inner_name}"})

    @classmethod
    def build(cls, ctx, args: AggregationArgs, spec):
        from ..plonk.in_circuit import VerifierChip
        rng = RangeChip(lookup_bits=cls.default_lookup_bits)
        vc = VerifierChip(rng)
        accs, all_inst_cells = [], []
        for sn in args.snarks:
            inst_cells = [[ctx.load_witness(int(v) % R) for v in col]
                          for col in sn.instances]
            all_inst_cells.append(inst_cells)
            accs.append(vc.verify_proof(ctx, sn.vk, args.srs,
                                        inst_cells, sn.proof))
        if len(accs) == 1:
            lhs, rhs = accs[0]
        else:
            lhs, rhs = vc.fold_accumulators(ctx, accs)
        # accumulator limbs: canonical representatives (the statement is
        # compared coordinate-for-coordinate by the outer pairing check)
        out = []
        for pt in (lhs, rhs):
            for coord in pt:
                can = vc.fq.canonicalize(ctx, coord)
                out.extend(can.limbs)
        for cell in out:
            ctx.expose_public(cell)
        for inst_cells in all_inst_cells:
            for col in inst_cells:
                for cell in col:
                    ctx.expose_public(cell)
        return out

    @classmethod
    def get_instances(cls, args: AggregationArgs, spec) -> list:
        from ..plonk.in_circuit import VerifierChip
        accs = []
        for sn in args.snarks:
            acc = VerifierChip.native_accumulator(
                sn.vk, args.srs, sn.instances, sn.proof)
            assert acc is not None, "inner proof invalid"
            accs.append(acc)
        acc = accs[0] if len(accs) == 1 else accumulate(accs)
        flat = [int(v) % R for sn in args.snarks
                for col in sn.instances for v in col]
        return acc.limbs() + flat

    @classmethod
    def verify(cls, vk, srs: SRS, instances, proof: bytes,
               transcript_cls=None) -> bool:
        """Outer proof verification INCLUDING the deferred pairing: the
        complete check a consumer of the compressed proof performs."""
        kw = {"transcript_cls": transcript_cls} if transcript_cls else {}
        if not plonk_verify(vk, srs, [instances], proof, **kw):
            return False
        return Accumulator.from_limbs(instances[:NUM_ACC_LIMBS]).check(srs)

    @classmethod
    def batch_verify(cls, vk, srs: SRS, items: list,
                     transcript_cls=PoseidonTranscript) -> bool:
        """items: [(instances, proof)] — native verification of a batch of
        app proofs. Utility API (nothing in the service layer calls it).
        Default transcript is Poseidon because app snarks bound for
        aggregation are produced that way (prover_service cli two-stage
        flow); pass Blake2b/Keccak for standalone proofs."""
        return all(plonk_verify(vk, srs, [inst], proof,
                                transcript_cls=transcript_cls)
                   for inst, proof in items)

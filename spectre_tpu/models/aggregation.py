"""Proof aggregation / compression layer.

Reference parity: `aggregation_circuit.rs` (snark-verifier's
`AggregationCircuit`: one-layer SHPLONK compression of an app snark, keeping
the 12 KZG accumulator limbs + the app instances as public inputs).

ROUND-1 SCOPE: recursive in-circuit verification of a BN254 KZG proof needs
the non-native Fq ECC chip (the same machinery as the in-circuit BLS pairing)
— that is the round-2 milestone. This module already provides:
  * the aggregation STATEMENT layout (accumulator limbs || app instances),
    matching `expose_previous_instances(false)`;
  * KZG accumulation of the deferred pairing checks of N app proofs into ONE
    pairing (the heart of the aggregation argument, runs natively today and
    becomes the in-circuit constraint in round 2);
  * batch verification API used by the RPC/CLI layer.
"""

from __future__ import annotations

import secrets
from dataclasses import dataclass

from ..fields import bn254
from ..plonk.srs import SRS
from ..plonk.verifier import verify as plonk_verify

R = bn254.R
ACC_LIMB_BITS = 88
ACC_LIMBS_PER_COORD = 3  # 12 limbs total: (lhs.x, lhs.y, rhs.x, rhs.y) x 3


@dataclass
class Accumulator:
    """Deferred KZG pairing check: e(lhs, [tau]_2) == e(rhs, [1]_2)."""

    lhs: object  # G1 point
    rhs: object

    def limbs(self) -> list[int]:
        """12 x 88-bit limbs, the aggregation circuit's first instances
        (reference: accumulator limb encoding in snark-verifier)."""
        out = []
        for pt in (self.lhs, self.rhs):
            for coord in (int(pt[0]), int(pt[1])):
                for i in range(ACC_LIMBS_PER_COORD):
                    out.append((coord >> (ACC_LIMB_BITS * i))
                               & ((1 << ACC_LIMB_BITS) - 1))
        return out

    def check(self, srs: SRS) -> bool:
        g1 = bn254.g1_curve
        return bn254.pairing_check([
            (self.lhs, srs.g2_tau),
            (g1.neg(self.rhs), srs.g2_gen),
        ])


def accumulate(accs: list[Accumulator]) -> Accumulator:
    """Random-linear-combination of deferred pairing checks into one."""
    g1 = bn254.g1_curve
    lhs, rhs = None, None
    for acc in accs:
        r = secrets.randbelow(R)
        lhs = g1.add(lhs, g1.mul(acc.lhs, r))
        rhs = g1.add(rhs, g1.mul(acc.rhs, r))
    return Accumulator(lhs, rhs)


class AggregationCircuit:
    """Round-1 API shell: batch-verifies app proofs and produces the
    aggregation statement (accumulator limbs || flattened app instances)."""

    name = "aggregation"

    @classmethod
    def aggregate_statement(cls, acc: Accumulator, app_instances: list) -> list:
        return acc.limbs() + [v % R for v in app_instances]

    @classmethod
    def batch_verify(cls, vk, srs: SRS, items: list) -> bool:
        """items: [(instances, proof)] — verifies each app proof (native;
        becomes one recursive proof in round 2)."""
        return all(plonk_verify(vk, srs, [inst], proof)
                   for inst, proof in items)

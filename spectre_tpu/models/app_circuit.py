"""AppCircuit lifecycle: build -> pin -> keygen -> prove -> verify.

Reference parity: the `AppCircuit` trait (`util/circuit.rs:86-239`):
staged circuit creation (keygen from a default witness, prover from pinning),
pk caching, proof generation. The TPU/CPU backend choice threads through to
the plonk prover (BASELINE.json north star's `--backend` selection).
"""

from __future__ import annotations

import os
import pickle

from ..builder import Context
from ..plonk import backend as B
from ..plonk.keygen import ProvingKey, keygen
from ..plonk.mock import mock_prove
from ..plonk.prover import prove as plonk_prove
from ..plonk.srs import SRS
from ..plonk.verifier import verify as plonk_verify
from ..utils.pinning import Pinning

BUILD_DIR = os.environ.get("BUILD_DIR", os.path.join(
    os.path.dirname(__file__), "..", "..", "build"))


class AppCircuit:
    """Subclasses define: name, default_lookup_bits, build(ctx, args, spec) ->
    list of instance AssignedValues (already exposed), and
    get_instances(args, spec) -> native public inputs."""

    name = "app"
    default_lookup_bits = 8

    # -- to implement ---------------------------------------------------
    @classmethod
    def build(cls, ctx: Context, args, spec):
        raise NotImplementedError

    @classmethod
    def get_instances(cls, args, spec) -> list:
        raise NotImplementedError

    # -- lifecycle ------------------------------------------------------
    @classmethod
    def build_context(cls, args, spec, **kwargs) -> Context:
        """Witness generation with the cyclic GC paused: builder structures
        hold no reference cycles, and gen-2 collections over tens of
        millions of cells turn an ~6-minute build into >30 minutes."""
        import gc
        ctx = Context()
        was_enabled = gc.isenabled()
        gc.disable()
        try:
            cls.build(ctx, args, spec, **kwargs)
        finally:
            if was_enabled:
                gc.enable()
        return ctx

    @classmethod
    def pinning_path(cls, spec, k: int) -> str:
        return os.path.join(BUILD_DIR, f"{cls.name}_{spec.name}_{k}.pinning.json")

    @classmethod
    def create_pk(cls, srs: SRS, spec, k: int, dummy_args, bk=None,
                  cache: bool = True):
        """Keygen from a default witness; pin the shape; cache pk to disk
        (reference: pk written next to pinning, `util/circuit.rs:130-136`).
        dummy_args may be a zero-arg callable, evaluated only on cache miss."""
        bk = bk or B.get_backend()
        pk_path = os.path.join(BUILD_DIR, f"{cls.name}_{spec.name}_{k}.pk")
        pin_path = cls.pinning_path(spec, k)
        if cache and os.path.exists(pk_path) and os.path.exists(pin_path):
            with open(pk_path, "rb") as f:
                return pickle.load(f)
        if callable(dummy_args):
            # lazy: aggregation dummy args cost a full inner proof — only
            # pay it on a cache miss
            dummy_args = dummy_args()
        ctx = cls.build_context(dummy_args, spec)
        pin = Pinning.load_or_create(pin_path, ctx, k, cls.default_lookup_bits)
        asg = ctx.assignment(pin.config)
        pk = keygen(srs, pin.config, asg.fixed, asg.selectors, asg.copies, bk)
        if cache:
            os.makedirs(BUILD_DIR, exist_ok=True)
            with open(pk_path, "wb") as f:
                pickle.dump(pk, f)
        return pk

    @classmethod
    def mock(cls, args, spec, k: int) -> bool:
        import gc
        ctx = cls.build_context(args, spec)
        was_enabled = gc.isenabled()
        gc.disable()     # same no-cycles argument as build_context
        try:
            cfg = ctx.auto_config(k=k, lookup_bits=cls.default_lookup_bits)
            return mock_prove(cfg, ctx.assignment(cfg))
        finally:
            if was_enabled:
                gc.enable()

    @classmethod
    def prove(cls, pk: ProvingKey, srs: SRS, args, spec, bk=None,
              transcript=None) -> bytes:
        """transcript: None = Blake2b; pass PoseidonTranscript() for
        aggregation-bound snarks, KeccakTranscript() for the EVM path
        (reference: gen_snark_shplonk vs gen_evm_proof_shplonk)."""
        ctx = cls.build_context(args, spec)
        asg = ctx.assignment(pk.vk.config)
        return plonk_prove(pk, srs, asg, bk, transcript=transcript)

    @classmethod
    def verify(cls, vk, srs: SRS, instances, proof: bytes) -> bool:
        return plonk_verify(vk, srs, [instances], proof)

"""StepCircuit: verify one sync-step of the Altair light-client protocol.

Reference parity: `sync_step_circuit.rs` (`assign_virtual:64`) — the FULL
constraint set, including the flagship BLS block:
- participation bit-check + sum, and the n-iteration conditional point-add
  aggregation loop over on-curve-checked pubkeys (`aggregate_pubkeys:292`,
  hot loop `:344-355`; blinded accumulator start so strict chords never
  degenerate);
- Poseidon commitment of the committee with the y-sign derived from the
  on-curve-bound y (closes round-1 VERDICT weak #5);
- SSZ roots of attested/finalized headers, the signing root, two Merkle
  proofs (finality `:174-183`, execution `:186-195`);
- in-circuit hash-to-curve of the signing root (`:165-169`), G2 signature
  assignment with a psi subgroup check (`assign_signature:279`), and the
  pairing check e(agg_pk, H(m)) * e(-g1, sig) == 1
  (`assert_valid_signature:171`);
- SHA256 public-input commitment truncated to 253 bits (`:199-221`).
Instances: [pub_inputs_commit, poseidon_commit] (`get_instances:228`).

The native aggregate-verify remains as a fast-fail witness guard; the same
property is enforced by constraints (see tests: removing the guard still
rejects forgeries at the constraint level).
"""

from __future__ import annotations

import hashlib

from ..builder import Context, GateChip, RangeChip
from ..builder.fp_chip import EccChip, FpChip
from ..builder.fp2_chip import Fp2Chip, G2Chip
from ..builder.fp12_chip import Fp12Chip
from ..builder.hash_to_curve_chip import HashToCurveChip
from ..builder.pairing_chip import PairingChip
from ..builder.poseidon_chip import PoseidonChip
from ..builder.sha256_chip import Sha256Chip
from ..builder.sha256_wide_chip import Sha256WideChip
from ..fields import bls12_381 as bls
from ..gadgets import poseidon_commit as PC
from ..gadgets import ssz_merkle as M
from ..spec import LIMB_BITS, NUM_LIMBS
from ..witness.types import SyncStepArgs
from .app_circuit import AppCircuit

# Accumulator blinding point for the aggregation loop: a fixed
# nothing-up-my-sleeve point subtracted back out at the end, so the strict
# chord additions never see x1 == x2 for honest witnesses (the reference
# seeds its loop from the first participant instead; a fixed offset keeps
# the loop shape static in the participation bits).
AGG_BLIND_SCALAR = int.from_bytes(b"spectre_tpu/step/agg-blind/v1", "big") % bls.R
AGG_BLIND = bls.g1_curve.mul(bls.G1_GEN, AGG_BLIND_SCALAR)

LIMB_MASK = (1 << LIMB_BITS) - 1
HALF_P = (bls.P - 1) // 2


def _fq_limbs(v: int):
    return [(int(v) >> (LIMB_BITS * i)) & LIMB_MASK for i in range(NUM_LIMBS)]


class StepCircuit(AppCircuit):
    name = "sync_step"
    # The reference splits its SHA backends per circuit for exactly the
    # reason we do: the step circuit is the one that gets COMPRESSED
    # (in-circuit-verified by the aggregation layer), so its proof must
    # stay small — the wide region adds 114 committed columns (+~550
    # opening evals), which dwarfs the compression circuit. Step therefore
    # uses the lookup ("flex") SHA chip (reference: `Sha256Chip` =
    # sha256_flex, `sync_step_circuit.rs:71`), committee-update keeps the
    # wide region (reference: `Sha256ChipWide`). The ~45k-cells/block cost
    # of the 66 hashed blocks is bought back by a big range table halving
    # every range-check in the non-native BLS arithmetic (reference pins
    # lookup_bits=20 at k=21 for the same reason,
    # `config/sync_step_testnet.json`). Measured at Testnet-512/k=21:
    # lookup_bits=16 -> 17 advice / 35.6M cells; 18 -> 16 advice / 32.79M
    # cells (-8%); every advice column dropped is one fewer commitment in
    # the inner proof and a smaller in-circuit verifier downstream.
    use_wide_sha = False
    default_lookup_bits = 18

    @classmethod
    def build(cls, ctx: Context, args: SyncStepArgs, spec,
              native_precheck: bool = True, use_wide_sha: bool | None = None):
        if use_wide_sha is None:
            use_wide_sha = cls.use_wide_sha
        gate = GateChip()
        rng = RangeChip(cls.default_lookup_bits, gate)
        sha_nib = Sha256Chip(gate)
        sha = Sha256WideChip(gate) if use_wide_sha else sha_nib
        poseidon = PoseidonChip(gate)
        fp = FpChip(rng)
        fp2 = Fp2Chip(fp)
        ecc = EccChip(fp)
        g2 = G2Chip(fp2)
        pairing = PairingChip(Fp12Chip(fp2))
        h2c = HashToCurveChip(pairing, sha_nib,
                              sha_wide=sha if use_wide_sha else None)
        n = spec.sync_committee_size
        assert len(args.pubkeys_uncompressed) == n
        assert len(args.participation_bits) == n

        # --- witness-side fast-fail guard (constraints enforce the same) ---
        participating = [pk for pk, b in
                         zip(args.pubkeys_uncompressed, args.participation_bits) if b]
        sig = bls.g2_decompress(args.signature_compressed)
        if native_precheck:
            pts = [(bls.Fq(x), bls.Fq(y)) for x, y in participating]
            assert bls.fast_aggregate_verify(pts, args.signing_root(), sig,
                                             dst=spec.dst), \
                "aggregate signature invalid (native pre-check)"

        # --- participation bits + sum ---
        bit_cells = []
        for b in args.participation_bits:
            c = ctx.load_witness(int(b))
            gate.assert_bit(ctx, c)
            bit_cells.append(c)
        participation_sum = gate.sum_(ctx, bit_cells)

        # --- pubkeys: on-curve assignment + poseidon commitment + the
        #     conditional-add aggregation loop (`aggregate_pubkeys:292`) ---
        assert any(args.participation_bits), \
            "no participants: empty aggregation is not a provable statement"
        half_p_limbs = _fq_limbs(HALF_P)
        limbs_list, sign_cells = [], []
        acc = fp.load_constant_point(ctx, AGG_BLIND)
        for (x, y), bit_cell in zip(args.pubkeys_uncompressed, bit_cells):
            pt = ecc.load_point(ctx, (x, y))      # y^2 = x^3 + 4 binds y to x
            xc, yc = pt
            # y_sign = ((p-1)/2 < y) from the ON-CURVE y limbs
            sign = cls._big_less_than_const(ctx, gate, rng, half_p_limbs,
                                            yc.limbs)
            limbs_list.append(xc.limbs)
            sign_cells.append(sign)
            summed = ecc.add_unequal_lazy(ctx, acc, pt)  # strict chord
            acc = (fp.select(ctx, bit_cell, summed[0], acc[0]),
                   fp.select(ctx, bit_cell, summed[1], acc[1]))
        neg_blind = fp.load_constant_point(
            ctx, bls.g1_curve.neg(AGG_BLIND))
        agg_pk = ecc.add_unequal_lazy(ctx, acc, neg_blind)
        poseidon_commit = PC.g1_array_poseidon(ctx, gate, poseidon,
                                               limbs_list, sign_cells)

        # --- header roots + signing root ---
        zero = ctx.load_constant(0)

        def byte_cells_checked(bs: bytes):
            return M.load_bytes_checked(ctx, sha, bs)

        def uint64_cells(v: int):
            return byte_cells_checked(int(v).to_bytes(8, "little"))

        def header_chunks(hdr):
            slot_cells = uint64_cells(hdr.slot)
            chunks = [
                M.bytes_to_chunk(ctx, sha, slot_cells + [zero] * 24),
                M.bytes_to_chunk(ctx, sha, uint64_cells(hdr.proposer_index) + [zero] * 24),
                M.bytes_to_chunk(ctx, sha, byte_cells_checked(hdr.parent_root)),
                M.bytes_to_chunk(ctx, sha, byte_cells_checked(hdr.state_root)),
                M.bytes_to_chunk(ctx, sha, byte_cells_checked(hdr.body_root)),
            ]
            return slot_cells, chunks

        att_slot_cells, att_chunks = header_chunks(args.attested_header)
        fin_slot_cells, fin_chunks = header_chunks(args.finalized_header)
        attested_root = M.merkleize_chunks(ctx, sha, att_chunks, limit=8)
        finalized_root = M.merkleize_chunks(ctx, sha, fin_chunks, limit=8)

        domain_chunk = M.bytes_to_chunk(ctx, sha, byte_cells_checked(args.domain))
        signing_root = sha.digest_two_to_one(ctx, attested_root, domain_chunk)

        # --- the BLS block (`:165-171`): hash the signing root to G2,
        #     assign + subgroup-check the signature, pairing check ---
        signing_root_bytes = cls._chunk_bytes(ctx, gate, sha, signing_root)
        msg_point = h2c.hash_to_g2(ctx, signing_root_bytes, spec.dst)
        sig_pt = g2.load_point(ctx, sig)
        pairing.assert_g2_subgroup(ctx, sig_pt)
        neg_g1 = fp.load_constant_point(ctx, bls.g1_curve.neg(bls.G1_GEN))
        pairing.assert_pairing_product_one(
            ctx, [(agg_pk, msg_point), (neg_g1, sig_pt)])

        # --- merkle proofs ---
        att_state_chunk = att_chunks[3]
        fin_branch = [M.bytes_to_chunk(ctx, sha, byte_cells_checked(b))
                      for b in args.finality_branch]
        M.verify_merkle_proof(ctx, sha, finalized_root, fin_branch,
                              spec.finalized_header_index, att_state_chunk)

        exec_chunk = M.bytes_to_chunk(ctx, sha,
                                      byte_cells_checked(args.execution_payload_root))
        exec_branch = [M.bytes_to_chunk(ctx, sha, byte_cells_checked(b))
                       for b in args.execution_payload_branch]
        fin_body_chunk = fin_chunks[4]
        M.verify_merkle_proof(ctx, sha, exec_chunk, exec_branch,
                              spec.execution_state_root_index, fin_body_chunk)

        # --- public input commitment ---
        sum_cells = M.load_bytes_checked(
            ctx, sha, int(participation_sum.value).to_bytes(8, "little"))
        acc = gate.inner_product_const(ctx, sum_cells, [1 << (8 * i) for i in range(8)])
        ctx.constrain_equal(acc, participation_sum)

        fin_root_bytes = cls._chunk_bytes(ctx, gate, sha, finalized_root)
        exec_root_bytes = cls._chunk_bytes(ctx, gate, sha, exec_chunk)

        concat = (att_slot_cells + fin_slot_cells + sum_cells
                  + fin_root_bytes + exec_root_bytes)
        digest_words = sha.digest_bytes(ctx, concat)
        pub_commit = cls._truncate_words_le(ctx, gate, sha, digest_words)

        ctx.expose_public(pub_commit)
        ctx.expose_public(poseidon_commit)
        return [pub_commit, poseidon_commit]

    # -- helpers ---------------------------------------------------------
    @staticmethod
    def _big_less_than_const(ctx, gate: GateChip, rng: RangeChip,
                             a_limbs_const: list, b_limbs: list):
        """(a < b) for a constant limb vector vs limb cells (both LIMB_BITS)."""
        result = None
        eq_chain = None
        for i in range(NUM_LIMBS - 1, -1, -1):
            ac = ctx.load_constant(a_limbs_const[i])
            lt = rng.is_less_than(ctx, ac, b_limbs[i], LIMB_BITS)
            eq = gate.is_equal(ctx, ac, b_limbs[i])
            if result is None:
                result = lt
                eq_chain = eq
            else:
                term = gate.and_(ctx, eq_chain, lt)
                result = gate.or_(ctx, result, term)
                eq_chain = gate.and_(ctx, eq_chain, eq)
        return result

    @staticmethod
    def _chunk_bytes(ctx, gate: GateChip, sha: Sha256Chip, chunk: list):
        """8-Word chunk -> 32 byte cells (BE), byte-decomposed + constrained."""
        out = []
        for w in chunk:
            v = w.value
            cells = []
            for i in range(4):
                c = ctx.load_witness((v >> (8 * (3 - i))) & 0xFF)
                sha._range_bits(ctx, c, 8)
                cells.append(c)
            acc = gate.inner_product_const(ctx, cells, [1 << 24, 1 << 16, 1 << 8, 1])
            ctx.constrain_equal(acc, w.cell)
            out.extend(cells)
        return out

    @staticmethod
    def _truncate_words_le(ctx, gate: GateChip, sha: Sha256Chip, words: list):
        """SHA digest (8 BE Words) -> field element from LE bytes with the top
        3 bits dropped (reference `truncate_sha256_into_single_elem:368`)."""
        byte_cells = StepCircuit._chunk_bytes(ctx, gate, sha, words)
        # byte 31 (last LE byte... byte_cells are BE order: byte 31 is index 31)
        top = byte_cells[31]
        bits = gate.num_to_bits(ctx, top, 8)
        cleared = gate.bits_to_num(ctx, bits[:5])
        # LE interpretation: digest[i] has weight 2^(8i), digest[31] masked
        coeffs = [1 << (8 * i) for i in range(32)]
        ordered = byte_cells[:31] + [cleared]
        return gate.inner_product_const(ctx, ordered, coeffs)

    @classmethod
    def get_instances(cls, args: SyncStepArgs, spec) -> list:
        """Native recomputation (reference `get_instances:228`)."""
        participation = sum(args.participation_bits)
        data = (int(args.attested_header.slot).to_bytes(8, "little")
                + int(args.finalized_header.slot).to_bytes(8, "little")
                + int(participation).to_bytes(8, "little")
                + args.finalized_header.hash_tree_root()
                + args.execution_payload_root)
        digest = bytearray(hashlib.sha256(data).digest())
        digest[31] &= 0x1F
        pub_commit = int.from_bytes(bytes(digest), "little")
        pts = [(bls.Fq(x), bls.Fq(y)) for x, y in args.pubkeys_uncompressed]
        poseidon = PC.committee_poseidon_from_uncompressed(pts)
        return [pub_commit, poseidon]

"""`python -m spectre_tpu.observability` — operator tooling.

Subcommands:

  report <job-id|manifest.json> [--diff <job-id|manifest.json>] [--url U]
      Render a proof provenance manifest (observability/manifest.py) as
      a phase/compile/queue-wait breakdown. The target is either a path
      to a manifest JSON file (as stored in the artifact store /
      downloaded earlier) or a job id, fetched live over the
      `getProofManifest` RPC from --url. `--diff` renders the breakdown
      of the first manifest followed by a field-by-field regression
      diff against the second — the triage loop for "why did tonight's
      prove get slower".

Stdlib-only: rendering a manifest must work on a laptop with neither
jax nor the prover installed beyond this package.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from . import manifest as man_mod

DEFAULT_URL = "http://127.0.0.1:3000/rpc"


def _load(target: str, url: str) -> dict:
    """A target that exists on disk is a manifest file; anything else is
    treated as a job id and fetched over RPC."""
    if os.path.exists(target):
        with open(target, "rb") as f:
            return man_mod.from_bytes(f.read())
    from ..prover_service.rpc_client import ProverClient
    return ProverClient(url).get_manifest(target)


def _cmd_report(args) -> int:
    a = _load(args.target, args.url)
    print(man_mod.render(a))
    if args.diff is not None:
        b = _load(args.diff, args.url)
        print()
        print(man_mod.diff(a, b))
    if args.json:
        print()
        print(json.dumps(a, indent=2, sort_keys=True))
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="python -m spectre_tpu.observability")
    sub = p.add_subparsers(dest="cmd", required=True)
    r = sub.add_parser("report", help="render a proof provenance manifest")
    r.add_argument("target",
                   help="manifest JSON path, or a job id (fetched via RPC)")
    r.add_argument("--diff", default=None, metavar="OTHER",
                   help="second manifest (path or job id) to diff against")
    r.add_argument("--url", default=DEFAULT_URL,
                   help=f"prover RPC endpoint for job-id targets "
                        f"(default {DEFAULT_URL})")
    r.add_argument("--json", action="store_true",
                   help="also dump the raw manifest JSON")
    args = p.parse_args(argv)
    if args.cmd == "report":
        return _cmd_report(args)
    return 2


if __name__ == "__main__":
    sys.exit(main())

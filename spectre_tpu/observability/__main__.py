"""`python -m spectre_tpu.observability` — operator tooling.

Subcommands:

  report <job-id|manifest.json> [--diff <job-id|manifest.json>] [--url U]
      Render a proof provenance manifest (observability/manifest.py) as
      a phase/compile/queue-wait breakdown. The target is either a path
      to a manifest JSON file (as stored in the artifact store /
      downloaded earlier) or a job id, fetched live over the
      `getProofManifest` RPC from --url. `--diff` renders the breakdown
      of the first manifest followed by a field-by-field regression
      diff against the second — the triage loop for "why did tonight's
      prove get slower".

  report BASELINE --diff CANDIDATE --ci [--max-prove-regress F]
                                        [--max-compile-count-increase N]
      CI gate (ISSUE 10): exits 3 when the CANDIDATE manifest regresses
      prove_s beyond the fractional threshold (default 0.10 = +10%) or
      its compile.count grows beyond the allowed increase (default 0 —
      a new compile in a steady-state path is a cache regression).
      Wired as `make report-ci`.

Stdlib-only: rendering a manifest must work on a laptop with neither
jax nor the prover installed beyond this package.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from . import manifest as man_mod

DEFAULT_URL = "http://127.0.0.1:3000/rpc"


def _load(target: str, url: str) -> dict:
    """A target that exists on disk is a manifest file; anything else is
    treated as a job id and fetched over RPC."""
    if os.path.exists(target):
        with open(target, "rb") as f:
            return man_mod.from_bytes(f.read())
    from ..prover_service.rpc_client import ProverClient
    return ProverClient(url).get_manifest(target)


def _ci_regressions(baseline: dict, candidate: dict,
                    max_prove_regress: float,
                    max_compile_count_increase: int) -> list[str]:
    """The CI gate findings: target = baseline, --diff = candidate."""
    findings = []
    base_prove = baseline.get("prove_s")
    cand_prove = candidate.get("prove_s")
    if base_prove and cand_prove is not None:
        allowed = base_prove * (1.0 + max_prove_regress)
        if cand_prove > allowed:
            findings.append(
                f"prove_s regressed: {base_prove:.3f}s -> {cand_prove:.3f}s "
                f"(+{(cand_prove / base_prove - 1.0) * 100:.1f}%, "
                f"threshold +{max_prove_regress * 100:.0f}%)")
    base_cc = (baseline.get("compile") or {}).get("count", 0)
    cand_cc = (candidate.get("compile") or {}).get("count", 0)
    if cand_cc > base_cc + max_compile_count_increase:
        findings.append(
            f"compile.count regressed: {base_cc} -> {cand_cc} "
            f"(allowed increase {max_compile_count_increase})")
    return findings


def _cmd_report(args) -> int:
    if args.ci and args.diff is None:
        print("--ci requires --diff CANDIDATE (target is the baseline)",
              file=sys.stderr)
        return 2
    a = _load(args.target, args.url)
    print(man_mod.render(a))
    b = None
    if args.diff is not None:
        b = _load(args.diff, args.url)
        print()
        print(man_mod.diff(a, b))
    if args.json:
        print()
        print(json.dumps(a, indent=2, sort_keys=True))
    if args.ci:
        findings = _ci_regressions(a, b, args.max_prove_regress,
                                   args.max_compile_count_increase)
        print()
        if findings:
            for f in findings:
                print(f"CI REGRESSION: {f}")
            return 3
        print("CI gate: ok (no prove_s / compile.count regression)")
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="python -m spectre_tpu.observability")
    sub = p.add_subparsers(dest="cmd", required=True)
    r = sub.add_parser("report", help="render a proof provenance manifest")
    r.add_argument("target",
                   help="manifest JSON path, or a job id (fetched via RPC)")
    r.add_argument("--diff", default=None, metavar="OTHER",
                   help="second manifest (path or job id) to diff against")
    r.add_argument("--url", default=DEFAULT_URL,
                   help=f"prover RPC endpoint for job-id targets "
                        f"(default {DEFAULT_URL})")
    r.add_argument("--json", action="store_true",
                   help="also dump the raw manifest JSON")
    r.add_argument("--ci", action="store_true",
                   help="CI gate: exit 3 when --diff (the candidate) "
                   "regresses prove_s or compile.count beyond thresholds "
                   "vs the target (the baseline)")
    r.add_argument("--max-prove-regress", type=float, default=0.10,
                   help="allowed fractional prove_s increase "
                   "(default 0.10 = +10%%)")
    r.add_argument("--max-compile-count-increase", type=int, default=0,
                   help="allowed compile.count increase (default 0)")
    args = p.parse_args(argv)
    if args.cmd == "report":
        return _cmd_report(args)
    return 2


if __name__ == "__main__":
    sys.exit(main())

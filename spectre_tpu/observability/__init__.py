"""spectre_tpu.observability — the telemetry spine of the prover service.

Six pieces, one principle (bridge, don't duplicate):

* :mod:`.metrics` — counters/gauges/fixed-bucket histograms; the
  prove-latency and per-phase histograms ServiceHealth's running means
  cannot express.
* :mod:`.prom` — Prometheus text exposition (0.0.4) over
  `HEALTH.snapshot()`, queue stats, breaker states, table-LRU stats and
  the registered histograms; served as `GET /metrics` by
  prover_service/rpc.py.
* :mod:`.tracing` — per-job span trees (trace id = job id) fed by
  `utils/profiling.phase`; Chrome trace-event export via the `getTrace`
  RPC and the SPECTRE_TRACE_DIR file sink.
* :mod:`.rss` — per-job peak-RSS attribution from /proc/self/statm.
* :mod:`.manifest` — per-proof provenance manifests (PR 8): timestamps,
  modes/knobs, degrade+fault events, LRU deltas, compile events, phase
  seconds, result digest; stored content-addressed, journal keeps only
  the digest; `getProofManifest` RPC + `report` CLI.
* :mod:`.compilelog` — jax.monitoring compile-duration listener feeding
  `spectre_compile_seconds{fn=}`, nested `compile/*` trace spans and the
  per-job manifest capture (jax imported lazily inside `install()`).

Import order matters downstream: utils/profiling.py imports
`.metrics`/`.tracing` (both stdlib-only), so nothing here may import
the service layer or jax at module scope.
"""

from . import metrics, rss, tracing          # noqa: F401  (stdlib-only)
from . import compilelog, manifest, prom     # noqa: F401  (build on the above)

__all__ = ["compilelog", "manifest", "metrics", "prom", "rss", "tracing"]

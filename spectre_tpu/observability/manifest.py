"""Per-proof provenance manifests ("what produced these bytes?").

A finished proof used to be a digest in the journal and a blob in the
artifact store — mode selections, degrade events, cache churn, compile
time and queue wait were all gone the moment the worker thread moved
on. This module makes every job emit one JSON manifest capturing:

* timestamps (submitted / admitted / started / finished) so queue wait
  is separable from prove time — `queue_wait_s` here is the SAME float
  observed into `spectre_queue_wait_seconds` (tests pin exact parity);
* the resolved MSM/NTT modes plus the env knobs that chose them;
* every degrade / fallback / fault event that fired during the prove
  (CPU fallback, fixed→glv+signed, LRU evictions, injected faults) via
  the thread-local `record_event` collector below;
* `_TableLRU` hit/build/eviction deltas for the MSM and NTT caches;
* JIT compile events (observability/compilelog) — a warm second prove
  shows `compile.count == 0`;
* phase seconds from the job's span tree, peak RSS, result digest.

Manifests are artifacts, not journal payload: the JobQueue writes the
canonical JSON through `utils/artifacts.ArtifactStore` under suffix
`.manifest.json` (content-addressed, sha256-verified, quarantined on
rot) and the journal records only the digest — O(#jobs), replay
re-verifies. Retrieval: `getProofManifest` RPC / `ProverClient.
get_manifest` / `python -m spectre_tpu.observability report`.

Stdlib-only at import time (the prom scraper and the report CLI must
never pull in jax); resolved modes and LRU stats are read through
`sys.modules`, so an unloaded ops module reads as absent, never as an
import.
"""

from __future__ import annotations

import contextlib
import json
import sys
import threading

from ..utils import faults

SCHEMA = "spectre/proof-manifest/v1"
MANIFEST_SUFFIX = ".manifest.json"

# the env knobs that shape a prove; recorded even when unset (null) so
# two manifests always diff key-for-key
ENV_KNOBS = (
    "SPECTRE_MSM_MODE", "SPECTRE_NTT_MODE",
    "SPECTRE_NTT_KERNEL", "SPECTRE_MSM_IMPL", "SPECTRE_MSM_WINDOW",
    "SPECTRE_QUOTIENT_FUSED_VINV",
    "SPECTRE_MSM_TABLE_MB", "SPECTRE_NTT_TABLE_MB",
    "SPECTRE_QUOTIENT_CACHE_MB", "SPECTRE_FIELD_IMPL",
    "SPECTRE_JOB_QUEUE_DEPTH", "SPECTRE_MEM_WATERMARK_MB",
    "SPECTRE_FAULT_PLAN", "JAX_PLATFORMS",
)


# -- per-job event collector (thread-local, like the compile capture) ------

class _Local(threading.local):
    def __init__(self):
        self.events: list | None = None


_local = _Local()


def record_event(kind: str, **detail):
    """Append a degrade/fallback/fault event to the collecting job's
    manifest; free no-op when no job is collecting on this thread.
    Call sites: plonk/backend.py (cpu_fallback), ops/msm.py
    (msm_fixed_degraded, LRU churn), plonk/prover.py (quotient-cache
    thrash), utils/faults.py observer (every injected fault)."""
    sink = _local.events
    if sink is not None:
        sink.append({"kind": kind, **detail})


@contextlib.contextmanager
def collect_events(into: list | None = None):
    """Collect this thread's events into `into` (or a fresh list) for
    the duration of the block; yields the list."""
    sink = into if into is not None else []
    prev = _local.events
    _local.events = sink
    try:
        yield sink
    finally:
        _local.events = prev


def _on_fault(site: str, kind: str):
    record_event("fault", site=site, fault_kind=kind)


# every injected fault that fires while a job is collecting lands in
# that job's manifest (module import is idempotent => registered once)
faults.add_observer(_on_fault)


# -- environment / mode / cache snapshots ----------------------------------

def env_snapshot() -> dict:
    import os
    return {k: os.environ.get(k) for k in ENV_KNOBS}


def resolved_modes() -> dict:
    """Active MSM/NTT modes — read through sys.modules so building a
    manifest never imports jax; an ops module that was never loaded
    (pure service-layer job) reads as None."""
    out: dict = {"msm": None, "ntt": None}
    msm = sys.modules.get("spectre_tpu.ops.msm")
    if msm is not None:
        try:
            out["msm"] = msm.msm_mode()
        except Exception:
            pass
    ntt = sys.modules.get("spectre_tpu.ops.ntt")
    if ntt is not None:
        try:
            out["ntt"] = ntt.ntt_mode()
        except Exception:
            pass
    return out


def lru_snapshot() -> dict:
    """Point-in-time `_TableLRU.stats()` for both caches (None when the
    ops module is not loaded); `lru_delta` turns two of these into the
    per-job churn the manifest stores."""
    out: dict = {}
    for name in ("msm", "ntt"):
        mod = sys.modules.get(f"spectre_tpu.ops.{name}")
        stats = None
        if mod is not None:
            try:
                stats = mod.lru_stats()
            except Exception:
                pass
        out[name] = stats
    return out


_LRU_COUNTERS = ("hits", "builds", "evictions", "recomputes")


def lru_delta(before: dict | None, after: dict | None) -> dict:
    """Per-cache counter deltas across a job, plus the cache's final
    occupancy. A cache absent at either end reads as None."""
    out: dict = {}
    for name in ("msm", "ntt"):
        b = (before or {}).get(name)
        a = (after or {}).get(name)
        if a is None:
            out[name] = None
            continue
        b = b or {}
        d = {k: a.get(k, 0) - b.get(k, 0) for k in _LRU_COUNTERS}
        d["bytes"] = a.get("bytes", 0)
        d["entries"] = a.get("entries", 0)
        out[name] = d
    return out


# -- manifest construction --------------------------------------------------

def build(*, job_id: str, method: str, witness_digest: str | None = None,
          attempts: int = 0, submitted: float | None = None,
          admitted: float | None = None, started: float | None = None,
          finished: float | None = None, queue_wait_s: float | None = None,
          trace=None, compile_events=(), events=(),
          lru_before: dict | None = None, lru_after: dict | None = None,
          peak_rss_mb: float | None = None,
          result_digest: str | None = None,
          error: str | None = None) -> dict:
    """Assemble the manifest dict. `trace` is an observability.tracing
    Trace (phase seconds are derived from the same tree `getTrace`
    serves, so the two agree by construction); `compile_events` is the
    compilelog.capture output; `events` the collect_events output."""
    from . import compilelog, tracing
    prove_s = None
    if started is not None and finished is not None:
        prove_s = round(finished - started, 6)
    return {
        "schema": SCHEMA,
        "job_id": job_id,
        "method": method,
        "witness_digest": witness_digest,
        "attempts": attempts,
        "timestamps": {"submitted": submitted, "admitted": admitted,
                       "started": started, "finished": finished},
        "queue_wait_s": queue_wait_s,
        "prove_s": prove_s,
        "env": env_snapshot(),
        "modes": resolved_modes(),
        "events": list(events),
        "compile": compilelog.summarize(compile_events),
        "lru_delta": lru_delta(lru_before, lru_after),
        "phase_seconds": (tracing.phase_seconds(trace)
                          if trace is not None else {}),
        "peak_rss_mb": peak_rss_mb,
        "result_digest": result_digest,
        "error": error,
    }


def to_bytes(manifest: dict) -> bytes:
    """Canonical JSON encoding (sorted keys, tight separators) — the
    artifact digest is computed over exactly these bytes, so replay
    re-verification is byte-stable."""
    return (json.dumps(manifest, sort_keys=True,
                       separators=(",", ":")) + "\n").encode()


def from_bytes(data: bytes) -> dict:
    man = json.loads(data.decode())
    if not isinstance(man, dict) or man.get("schema") != SCHEMA:
        got = man.get("schema") if isinstance(man, dict) else type(man).__name__
        raise ValueError(f"not a {SCHEMA} manifest (got {got!r})")
    return man


# -- rendering (`python -m spectre_tpu.observability report`) ---------------

def _fmt_s(v) -> str:
    return "-" if v is None else f"{v:.3f}s"


def render(man: dict) -> str:
    """Human-readable phase/compile/queue-wait breakdown."""
    lines = [
        f"manifest {man.get('job_id')}  method={man.get('method')}"
        f"  attempts={man.get('attempts')}",
        f"  result digest : {man.get('result_digest') or '-'}",
        f"  witness digest: {man.get('witness_digest') or '-'}",
    ]
    if man.get("error"):
        lines.append(f"  error         : {man['error']}")
    comp = man.get("compile") or {}
    lines += [
        f"  queue wait    : {_fmt_s(man.get('queue_wait_s'))}"
        "   (admission -> worker start)",
        f"  prove         : {_fmt_s(man.get('prove_s'))}"
        f"   (peak RSS {man.get('peak_rss_mb') or '-'} MB)",
        f"  compile       : {_fmt_s(comp.get('seconds'))} across "
        f"{comp.get('count', 0)} backend compile(s)",
    ]
    for fn, slot in (comp.get("by_fn") or {}).items():
        lines.append(f"      {fn:<28} {slot['seconds']:.3f}s"
                     f" x{slot['count']}")
    modes = man.get("modes") or {}
    lines.append(f"  modes         : msm={modes.get('msm') or '-'}"
                 f"  ntt={modes.get('ntt') or '-'}")
    phases = man.get("phase_seconds") or {}
    if phases:
        lines.append("  phases:")
        for name, sec in sorted(phases.items(), key=lambda kv: -kv[1]):
            lines.append(f"      {name:<28} {sec:.3f}s")
    events = man.get("events") or []
    if events:
        lines.append("  events:")
        for ev in events:
            detail = ", ".join(f"{k}={v}" for k, v in ev.items()
                               if k != "kind")
            lines.append(f"      {ev.get('kind')}"
                         + (f" ({detail})" if detail else ""))
    lru = man.get("lru_delta") or {}
    for name in ("msm", "ntt"):
        d = lru.get(name)
        if d:
            lines.append(
                f"  lru[{name}]      : +{d.get('hits', 0)} hits"
                f"  +{d.get('builds', 0)} builds"
                f"  +{d.get('evictions', 0)} evictions"
                f"  +{d.get('recomputes', 0)} recomputes"
                f"  ({d.get('entries', 0)} entries resident)")
    return "\n".join(lines)


def diff(a: dict, b: dict) -> str:
    """Regression-triage diff of two manifests: wait/prove/compile and
    per-phase deltas (b relative to a), plus mode/env knob changes."""
    lines = [f"diff {a.get('job_id')} -> {b.get('job_id')}"]

    def num(m, *path):
        cur = m
        for p in path:
            cur = (cur or {}).get(p)
        return cur if isinstance(cur, (int, float)) else 0.0

    for label, path in (("queue wait", ("queue_wait_s",)),
                        ("prove", ("prove_s",)),
                        ("compile", ("compile", "seconds"))):
        va, vb = num(a, *path), num(b, *path)
        lines.append(f"  {label:<12}: {va:.3f}s -> {vb:.3f}s"
                     f"  ({vb - va:+.3f}s)")
    ca, cb = num(a, "compile", "count"), num(b, "compile", "count")
    if ca != cb:
        lines.append(f"  compile count: {int(ca)} -> {int(cb)}")
    pa = a.get("phase_seconds") or {}
    pb = b.get("phase_seconds") or {}
    deltas = [(name, pb.get(name, 0.0) - pa.get(name, 0.0))
              for name in sorted(set(pa) | set(pb))]
    moved = [(n, d) for n, d in deltas if abs(d) >= 0.0005]
    if moved:
        lines.append("  phases (delta):")
        for name, d in sorted(moved, key=lambda kv: -abs(kv[1])):
            lines.append(f"      {name:<28} {d:+.3f}s")
    for scope in ("modes", "env"):
        sa, sb = a.get(scope) or {}, b.get(scope) or {}
        for k in sorted(set(sa) | set(sb)):
            if sa.get(k) != sb.get(k):
                lines.append(f"  {scope}.{k}: {sa.get(k)!r} -> {sb.get(k)!r}")
    return "\n".join(lines)

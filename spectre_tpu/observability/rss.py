"""Per-job peak-RSS attribution (closes a ROADMAP PR-6 follow-up).

The memory watermark (prover_service/jobs.py) sheds on process-wide
RSS — necessary but unattributable: when the box is near the watermark
the operator needs to know WHICH running job is the hog. RssSampler
polls the same psutil-free `/proc/self/statm` source on a small shared
daemon thread and keeps a running max per registered key (job id), so
every finished job record carries `peak_rss_mb` and a memory shed can
name the jobs it protected the box from.

Peak RSS is a process-wide number — concurrent jobs all see the same
high-water mark, so attribution is "RSS while this job ran", not an
isolated per-job footprint (that would need cgroup accounting). That is
still the operative signal: the job whose lifetime covers the spike is
the one to re-spec or re-schedule.

Lifecycle: the sampler thread starts lazily on the first `start()` and
EXITS when the last active key finishes — no leaked threads after job
completion (pinned in tests/test_observability.py). Off-Linux
(`rss_mb()` -> None) everything degrades to a no-op returning None.
"""

from __future__ import annotations

import os
import threading

SAMPLE_INTERVAL_ENV = "SPECTRE_RSS_SAMPLE_S"
SAMPLE_INTERVAL_DEFAULT_S = 0.2


def rss_mb() -> float | None:
    """Resident set size in MB via /proc/self/statm (no psutil). Returns
    None where procfs is unavailable (macOS CI etc.) — the memory
    watermark and the sampler then degrade to no-ops, never a crash."""
    try:
        with open("/proc/self/statm") as f:
            pages = int(f.read().split()[1])
        return pages * os.sysconf("SC_PAGE_SIZE") / (1024.0 * 1024.0)
    except (OSError, IndexError, ValueError):
        return None


class RssSampler:
    def __init__(self, interval_s: float | None = None):
        if interval_s is None:
            try:
                interval_s = float(os.environ.get(
                    SAMPLE_INTERVAL_ENV, SAMPLE_INTERVAL_DEFAULT_S))
            except ValueError:
                interval_s = SAMPLE_INTERVAL_DEFAULT_S
        self.interval_s = max(0.005, interval_s)
        self._lock = threading.Lock()
        self._peaks: dict[str, float] = {}     # active keys only
        self._thread: threading.Thread | None = None
        self._wake = threading.Event()

    def start(self, key: str):
        """Begin attributing RSS to `key`; takes an immediate sample so
        even a sub-interval job gets a real peak."""
        v = rss_mb()
        if v is None:
            return
        with self._lock:
            self._peaks[key] = max(self._peaks.get(key, 0.0), v)
            if self._thread is None:
                self._wake.clear()
                self._thread = threading.Thread(
                    target=self._run, daemon=True,
                    name="spectre-rss-sampler")
                self._thread.start()

    def peak(self, key: str) -> float | None:
        """Current running peak for an ACTIVE key (shed attribution
        reads this for still-running jobs)."""
        with self._lock:
            v = self._peaks.get(key)
        return None if v is None else round(v, 1)

    def finish(self, key: str) -> float | None:
        """Stop attributing to `key`, return its peak. A final sample is
        folded in first (a job shorter than the interval still reports)."""
        v = rss_mb()
        with self._lock:
            peak = self._peaks.pop(key, None)
            if peak is None:
                return None
            if v is not None:
                peak = max(peak, v)
            if not self._peaks:
                self._wake.set()              # sampler thread exits
        return round(peak, 1)

    def _run(self):
        while True:
            self._wake.wait(self.interval_s)
            with self._lock:
                if not self._peaks:
                    # last key finished: self-terminate (the "no leaked
                    # threads" contract); a later start() respawns
                    self._thread = None
                    return
                # a start() raced the wake: un-signal and keep sampling
                self._wake.clear()
                v = rss_mb()
                if v is not None:
                    for k in self._peaks:
                        if v > self._peaks[k]:
                            self._peaks[k] = v


# process-global sampler the JobQueue workers share (one thread no
# matter how many queues/jobs are live)
SAMPLER = RssSampler()

"""Prometheus text exposition (format 0.0.4) for GET /metrics.

The renderer BRIDGES existing instrumentation rather than duplicating
it (ROADMAP: `HEALTH.snapshot()["counters"]` is "THE HOOK for future
metrics export"):

* every ServiceHealth counter becomes `spectre_<name>_total` — counter
  parity with `/healthz` is exact by construction (both read the same
  snapshot) and pinned in tests;
* ServiceHealth running means surface as `spectre_mean_<name>` gauges;
* JobQueue stats become per-status job gauges + worker/backlog gauges;
* beacon circuit breakers export a numeric state code per base_url;
* the MSM/NTT `_TableLRU` caches export hit/build/eviction/recompute
  counters and byte occupancy — read via `sys.modules` so a scrape
  never triggers the heavy jax import itself;
* registered metrics (the prove-latency and per-phase histograms in
  observability/metrics.py) render as native histogram families.

No HTTP here: `prover_service/rpc.py` calls `render()` from its GET
handler. Keep this importable without jax."""

from __future__ import annotations

import sys

from ..utils.health import HEALTH
from . import metrics as _metrics
from .rss import rss_mb

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


def _fmt(v) -> str:
    if isinstance(v, bool):
        return str(int(v))
    if isinstance(v, int):
        return str(v)
    f = float(v)
    return str(int(f)) if f == int(f) else repr(f)


def _fmt_le(le: float) -> str:
    if le == float("inf"):
        return "+Inf"
    return "%g" % le


def _esc(s: str) -> str:
    return (str(s).replace("\\", r"\\").replace("\n", r"\n")
            .replace('"', r'\"'))


def _labels(d: dict) -> str:
    if not d:
        return ""
    inner = ",".join(f'{k}="{_esc(v)}"' for k, v in d.items())
    return "{" + inner + "}"


def _family(out: list, name: str, kind: str, help: str):
    out.append(f"# HELP {name} {help}")
    out.append(f"# TYPE {name} {kind}")


def _sample(out: list, name: str, labels: dict, v):
    out.append(f"{name}{_labels(labels)} {_fmt(v)}")


def _render_histogram(out: list, name: str, h) -> None:
    snap = h.snapshot()
    base = dict(h.labels)
    for le, cum in snap["buckets"]:
        lab = dict(base)
        lab["le"] = _fmt_le(le)
        _sample(out, f"{name}_bucket", lab, cum)
    _sample(out, f"{name}_sum", base, snap["sum"])
    _sample(out, f"{name}_count", base, snap["count"])


def _lru_stats() -> list[tuple[str, dict]]:
    """(cache_label, stats) for each derived-table LRU whose module is
    ALREADY imported — sys.modules only, so a scrape of an idle service
    never pays the jax import for ops it hasn't used."""
    items = []
    for cache, mod, fn in (
            ("msm", "spectre_tpu.ops.msm", "lru_stats"),
            ("ntt", "spectre_tpu.ops.ntt", "lru_stats"),
            ("quotient_scalar", "spectre_tpu.plonk.quotient_device",
             "scalar_lru_stats")):
        m = sys.modules.get(mod)
        if m is None:
            continue
        try:
            items.append((cache, getattr(m, fn)()))
        except Exception:
            continue
    return items


def render(health=None, jobs=None, registry=None) -> str:
    """The full /metrics body. `health`/`jobs`/`registry` are injectable
    for tests; the service passes its JobQueue and defaults the rest."""
    health = HEALTH if health is None else health
    registry = _metrics.REGISTRY if registry is None else registry
    out: list[str] = []

    snap = health.snapshot()
    for name, v in snap["counters"].items():
        mn = f"spectre_{name}_total"
        _family(out, mn, "counter",
                f"ServiceHealth counter {name} (parity with /healthz)")
        _sample(out, mn, {}, int(v))
    _family(out, "spectre_uptime_seconds", "gauge",
            "Seconds since ServiceHealth start")
    _sample(out, "spectre_uptime_seconds", {}, snap["uptime_s"])
    for name, v in (snap.get("means") or {}).items():
        mn = f"spectre_mean_{name}"
        _family(out, mn, "gauge", f"ServiceHealth running mean of {name}")
        _sample(out, mn, {}, v)

    v = rss_mb()
    if v is not None:
        _family(out, "spectre_process_rss_mb", "gauge",
                "Process resident set size (MB, /proc/self/statm)")
        _sample(out, "spectre_process_rss_mb", {}, round(v, 1))

    if jobs is not None:
        st = jobs.stats()
        _family(out, "spectre_jobs", "gauge", "Jobs by status")
        for status in sorted(st.get("jobs", {})):
            _sample(out, "spectre_jobs", {"status": status},
                    st["jobs"][status])
        _family(out, "spectre_job_workers", "gauge",
                "Job worker pool size")
        _sample(out, "spectre_job_workers", {}, st.get("workers", 0))
        _family(out, "spectre_job_queue_depth_limit", "gauge",
                "Admission-control backlog bound (SPECTRE_JOB_QUEUE_DEPTH)")
        _sample(out, "spectre_job_queue_depth_limit", {},
                st.get("queue_depth", 0))
        _family(out, "spectre_job_retry_after_seconds", "gauge",
                "Current shed backoff hint (p90-priced)")
        _sample(out, "spectre_job_retry_after_seconds", {},
                jobs.retry_after_s())

    try:
        from ..preprocessor.beacon import (BREAKER_STATE_CODES,
                                           breaker_snapshot)
        breakers = breaker_snapshot()
    except Exception:
        breakers = []
    if breakers:
        _family(out, "spectre_beacon_breaker_state", "gauge",
                "Beacon circuit-breaker state (0=closed 1=half-open 2=open)")
        for b in breakers:
            _sample(out, "spectre_beacon_breaker_state",
                    {"base_url": b["base_url"]},
                    b.get("state_code",
                          BREAKER_STATE_CODES.get(b["state"], -1)))
        _family(out, "spectre_beacon_breaker_consecutive_failures", "gauge",
                "Consecutive beacon failures per client")
        for b in breakers:
            _sample(out, "spectre_beacon_breaker_consecutive_failures",
                    {"base_url": b["base_url"]}, b["consecutive_failures"])

    try:
        from ..prover_service.dispatcher import dispatcher_snapshot
        replicas = dispatcher_snapshot()
    except Exception:
        replicas = []
    if replicas:
        for key, kind, help_ in (
                ("breaker_state", "gauge",
                 "Replica circuit-breaker state "
                 "(0=closed 1=half-open 2=open)"),
                ("consecutive_failures", "gauge",
                 "Consecutive failures per prover replica"),
                ("active_leases", "gauge",
                 "Jobs currently leased to the replica"),
                ("healthy", "gauge",
                 "Last health-probe result (1=healthy 0=unhealthy; "
                 "absent until first probe)")):
            mn = f"spectre_replica_{key}"
            _family(out, mn, kind, help_)
            for r in replicas:
                if key == "breaker_state":
                    v = r["breaker"]["state_code"]
                elif key == "consecutive_failures":
                    v = r["breaker"]["consecutive_failures"]
                elif key == "healthy":
                    if r["healthy"] is None:
                        continue
                    v = int(r["healthy"])
                else:
                    v = r[key]
                _sample(out, mn, {"replica": r["replica_id"]}, v)
        _family(out, "spectre_replica_heartbeat_age_s", "gauge",
                "Seconds since the replica's last announce heartbeat "
                "(dynamic members only; past the TTL the member is "
                "demoted and deregistered)")
        for r in replicas:
            age = r.get("last_heartbeat_age_s")
            if age is not None:
                _sample(out, "spectre_replica_heartbeat_age_s",
                        {"replica": r["replica_id"]}, age)
        _family(out, "spectre_dispatcher_members", "gauge",
                "Proof-farm membership size by kind (total vs "
                "announce-registered dynamic members)")
        _sample(out, "spectre_dispatcher_members", {"kind": "total"},
                len(replicas))
        _sample(out, "spectre_dispatcher_members", {"kind": "dynamic"},
                sum(1 for r in replicas if r.get("dynamic")))

    try:
        from ..follower.daemon import follower_snapshot
        followers = follower_snapshot()
    except Exception:
        followers = []
    if followers:
        for key, help_ in (
                ("head_lag_slots",
                 "Slots between newest finalized header and newest "
                 "stored step proof"),
                ("periods_behind",
                 "Sync-committee periods between current period and the "
                 "verified update chain tip"),
                ("scheduler_backlog",
                 "Follower work items pending submit/collect")):
            mn = f"spectre_follower_{key}"
            _family(out, mn, "gauge", help_)
            for f in followers:
                _sample(out, mn, {"store": f.get("store", "")},
                        f.get(key, 0))

    try:
        from ..gateway.serving import gateway_snapshot
        gateways = gateway_snapshot()
    except Exception:
        gateways = []
    if gateways:
        for key, help_ in (
                ("packs", "Sealed update-range packs currently indexed"),
                ("pack_periods", "Periods per full pack "
                                 "(SPECTRE_PACK_PERIODS)"),
                ("cache_bytes", "Gateway hot-cache occupancy (bytes)"),
                ("cache_budget_bytes", "Gateway hot-cache byte budget "
                                       "(SPECTRE_GATEWAY_CACHE_MB)"),
                ("cache_entries", "Gateway hot-cache entry count"),
                ("cache_hits", "Gateway hot-cache lookup hits"),
                ("cache_misses", "Gateway hot-cache lookup misses")):
            mn = f"spectre_gateway_{key}"
            _family(out, mn, "gauge", help_)
            for g in gateways:
                cache = g.get("cache") or {}
                if key.startswith("cache_"):
                    v = cache.get(key[len("cache_"):], 0)
                else:
                    v = g.get(key) or 0
                _sample(out, mn, {"store": g.get("store", "")}, v)

    lru = _lru_stats()
    if lru:
        counter_keys = ("hits", "builds", "evictions", "recomputes")
        for key in counter_keys:
            mn = f"spectre_table_lru_{key}_total"
            _family(out, mn, "counter",
                    f"Derived-table LRU {key} (msm fixed-base / "
                    f"ntt twiddle caches)")
            for cache, st in lru:
                _sample(out, mn, {"cache": cache}, st.get(key, 0))
        for key, help_ in (("bytes", "Derived-table LRU occupancy (bytes)"),
                           ("budget_bytes",
                            "Derived-table LRU byte budget"),
                           ("entries", "Derived-table LRU entry count")):
            mn = f"spectre_table_lru_{key}"
            _family(out, mn, "gauge", help_)
            for cache, st in lru:
                _sample(out, mn, {"cache": cache}, st.get(key, 0))

    for m in registry.collect():
        _family(out, m.name, m.kind, m.help or m.name)
        if isinstance(m, _metrics.HistogramVec):
            for h in m.children():
                _render_histogram(out, m.name, h)
        elif isinstance(m, _metrics.Histogram):
            _render_histogram(out, m.name, m)
        else:
            _sample(out, m.name, getattr(m, "labels", {}), m.value())

    return "\n".join(out) + "\n"

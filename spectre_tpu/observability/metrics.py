"""Metric primitives: counters, gauges, fixed-bucket histograms.

This is deliberately NOT a reimplementation of the service's
instrumentation — ServiceHealth (utils/health.py) stays the single
source of truth for degradation counters, and the Prometheus renderer
(observability/prom.py) reads `HEALTH.snapshot()` at scrape time. What
lives here is the machinery ServiceHealth lacks: *distributions*.
Retry-after pricing needs a p90 (a mean hides the outlier that caused
the overload), and the per-phase decomposition central to the
hardware-acceleration literature (zkSpeed/SZKP, PAPERS.md) needs
latency histograms per prover phase, not one running mean.

Buckets are fixed at construction (cumulative `le` semantics, implicit
+Inf overflow bucket) so exposition is allocation-free and quantile
estimation is a single cumulative scan. Everything is thread-safe; the
prover's worker threads observe concurrently with scrapes.

Dependency-free on purpose (stdlib only): utils/profiling.py feeds
PHASE_SECONDS from inside `phase(...)`, which runs inside ops/ kernels
— no service-layer imports may sneak in here.
"""

from __future__ import annotations

import bisect
import threading

# prove latency: sub-second tiny-spec CPU proves up to multi-minute
# production compressed proofs (the admission controller caps
# retry_after at 600s, so the top finite bound matches)
LATENCY_BUCKETS = (0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0,
                   60.0, 120.0, 300.0, 600.0, 1800.0)

# per-phase wall clock: phases span ~ms (transcript hashing) to minutes
# (quotient on a large k)
PHASE_BUCKETS = (0.001, 0.005, 0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5,
                 5.0, 10.0, 30.0, 60.0, 300.0)

# queue wait (admission -> worker start): near-zero on an idle box, up
# to the admission controller's 600s retry_after cap (and beyond, when
# a replayed journal re-queues jobs across an outage)
QUEUE_WAIT_BUCKETS = (0.001, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 10.0,
                      30.0, 60.0, 300.0, 600.0, 1800.0)

# XLA compile durations: jaxpr traces are ~ms, backend_compile of a
# large quotient kernel can run minutes on first prove
COMPILE_BUCKETS = (0.001, 0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0,
                   10.0, 30.0, 60.0, 300.0)


class Counter:
    kind = "counter"

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self.labels: dict[str, str] = {}
        self._lock = threading.Lock()
        self._value = 0

    def inc(self, n: int = 1):
        with self._lock:
            self._value += n

    def value(self) -> int:
        with self._lock:
            return self._value

    def reset(self):
        with self._lock:
            self._value = 0


class Gauge:
    """Point-in-time value; `fn` makes it a pull gauge evaluated at
    scrape time (queue depth, RSS — values nobody should have to push)."""

    kind = "gauge"

    def __init__(self, name: str, help: str = "", fn=None):
        self.name = name
        self.help = help
        self.labels: dict[str, str] = {}
        self._fn = fn
        self._lock = threading.Lock()
        self._value = 0.0

    def set(self, v: float):
        with self._lock:
            self._value = float(v)

    def value(self) -> float:
        if self._fn is not None:
            return self._fn()
        with self._lock:
            return self._value

    def reset(self):
        with self._lock:
            self._value = 0.0


class Histogram:
    """Fixed-bucket cumulative histogram (Prometheus `le` semantics).

    `quantile(q)` returns the upper bound of the bucket where the
    cumulative count crosses q — intentionally conservative (an
    over-estimate by at most one bucket width), which is the right bias
    for backoff hints: better to tell a shed client to wait slightly
    too long than to invite an immediate re-shed."""

    kind = "histogram"

    def __init__(self, name: str, help: str = "",
                 buckets=LATENCY_BUCKETS, labels=None):
        if not buckets:
            raise ValueError("histogram needs at least one finite bucket")
        self.name = name
        self.help = help
        self.buckets = tuple(sorted(float(b) for b in buckets))
        self.labels = dict(labels or {})
        self._lock = threading.Lock()
        self._counts = [0] * (len(self.buckets) + 1)   # [+Inf] overflow last
        self._sum = 0.0
        self._count = 0

    def observe(self, v: float):
        v = float(v)
        with self._lock:
            self._counts[bisect.bisect_left(self.buckets, v)] += 1
            self._sum += v
            self._count += 1

    def quantile(self, q: float, default: float | None = None):
        """Bucket-resolution quantile; `default` when nothing observed.
        Values past the largest finite bucket clamp to that bound (the
        +Inf bucket has no upper edge to report)."""
        with self._lock:
            if self._count == 0:
                return default
            target = q * self._count
            cum = 0
            for i, le in enumerate(self.buckets):
                cum += self._counts[i]
                if cum >= target:
                    return le
            return self.buckets[-1]

    def snapshot(self) -> dict:
        """Cumulative view for exposition: [(le, cumulative_count)]
        including the +Inf bucket, plus sum and count."""
        with self._lock:
            out, cum = [], 0
            for i, le in enumerate(self.buckets):
                cum += self._counts[i]
                out.append((le, cum))
            out.append((float("inf"), cum + self._counts[-1]))
            return {"buckets": out, "sum": self._sum, "count": self._count}

    def reset(self):
        with self._lock:
            self._counts = [0] * (len(self.buckets) + 1)
            self._sum = 0.0
            self._count = 0


class HistogramVec:
    """Labelled histogram family (one child Histogram per label set)."""

    kind = "histogram"

    def __init__(self, name: str, help: str = "",
                 buckets=LATENCY_BUCKETS, labelnames=("phase",)):
        self.name = name
        self.help = help
        self.buckets = tuple(sorted(float(b) for b in buckets))
        self.labelnames = tuple(labelnames)
        self._lock = threading.Lock()
        self._children: dict[tuple, Histogram] = {}

    def labels(self, **kw) -> Histogram:
        key = tuple(str(kw[ln]) for ln in self.labelnames)
        with self._lock:
            h = self._children.get(key)
            if h is None:
                h = Histogram(self.name, self.help, self.buckets,
                              labels=dict(zip(self.labelnames, key)))
                self._children[key] = h
            return h

    def children(self) -> list[Histogram]:
        with self._lock:
            return [self._children[k] for k in sorted(self._children)]

    def reset(self):
        with self._lock:
            self._children.clear()


class MetricsRegistry:
    """Name-keyed metric registry the exposition renderer iterates.
    Re-registering a name returns the existing metric (module reload /
    test-process reuse must not fork the series)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: dict[str, object] = {}

    def _get_or_add(self, name: str, make):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = make()
                self._metrics[name] = m
            return m

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get_or_add(name, lambda: Counter(name, help))

    def gauge(self, name: str, help: str = "", fn=None) -> Gauge:
        return self._get_or_add(name, lambda: Gauge(name, help, fn=fn))

    def histogram(self, name: str, help: str = "",
                  buckets=LATENCY_BUCKETS) -> Histogram:
        return self._get_or_add(name, lambda: Histogram(name, help, buckets))

    def histogram_vec(self, name: str, help: str = "",
                      buckets=LATENCY_BUCKETS,
                      labelnames=("phase",)) -> HistogramVec:
        return self._get_or_add(
            name, lambda: HistogramVec(name, help, buckets, labelnames))

    def collect(self) -> list:
        with self._lock:
            return [self._metrics[k] for k in sorted(self._metrics)]

    def reset(self):
        for m in self.collect():
            m.reset()


# process-global registry the /metrics endpoint renders
REGISTRY = MetricsRegistry()

# end-to-end prove latency (observed by the JobQueue worker on every
# completed job) — the acceptance-gated histogram
PROVE_LATENCY = REGISTRY.histogram(
    "spectre_prove_latency_seconds",
    "End-to-end prove latency per completed job (seconds)",
    LATENCY_BUCKETS)

# per-phase wall clock, fed by utils/profiling.phase — the production
# counterpart of bench.py's MSM/NTT phase decomposition
PHASE_SECONDS = REGISTRY.histogram_vec(
    "spectre_phase_seconds",
    "Wall-clock seconds per instrumented prover phase",
    PHASE_BUCKETS, ("phase",))


# admission -> worker-start wait, observed by the JobQueue worker with
# the SAME value the job's provenance manifest records as queue_wait_s
# (tests pin exact parity) — splits queueing from proving in the
# latency story that spectre_prove_latency_seconds alone conflates
QUEUE_WAIT = REGISTRY.histogram(
    "spectre_queue_wait_seconds",
    "Seconds between job admission and worker start",
    QUEUE_WAIT_BUCKETS)

# XLA backend-compile seconds attributed to the prover phase (fn label)
# that was open when the compile fired; fed by observability/compilelog
# from jax.monitoring events. Zero observations after warmup = the jit
# caches are doing their job.
COMPILE_SECONDS = REGISTRY.histogram_vec(
    "spectre_compile_seconds",
    "XLA backend compile seconds per triggering prover phase",
    COMPILE_BUCKETS, ("fn",))


def queue_latency_histogram() -> Histogram:
    """Fresh UNregistered prove-latency histogram. Each JobQueue prices
    retry_after off its own instance (queue-local load, not whatever a
    previous queue in the same process observed); the registered
    PROVE_LATENCY above aggregates process-wide for exposition."""
    return Histogram("prove_latency_seconds", buckets=LATENCY_BUCKETS)

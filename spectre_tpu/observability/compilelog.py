"""JIT-compile telemetry: a `jax.monitoring` event-duration listener.

XLA compile time dominates first-prove latency on a reconfigured
accelerator (mode flip, new k, fresh process), yet it is invisible in
the phase histograms — `prove/quotient` taking 90s tells you nothing
about whether that was math or `backend_compile`. jax emits
`/jax/core/compile/*_duration` events (jaxpr trace, MLIR lowering,
backend compile) through `jax.monitoring`; `install()` registers one
process-global listener that fans each event into three sinks:

  1. `spectre_compile_seconds{fn=}` (metrics.COMPILE_SECONDS) — fn is
     the innermost open `entry_point(...)` (the named jitted entry that
     actually missed its trace cache — sharded MSM/NTT runners push one),
     falling back to the innermost open tracing span
     (`prove/commit_advice`, ...) so compile cost is attributed to the
     phase that triggered it.
     Only `backend_compile` events are observed (the others are
     sub-steps of the same compilation; counting all three would
     triple-count one cache miss).
  2. a completed `compile/<kind>` child span in the active trace, so
     `getTrace` / Chrome trace JSON shows compiles nested inside their
     phase.
  3. the thread-local `capture(...)` collector, which the JobQueue
     worker opens around the runner — this is what lands in the job's
     provenance manifest. A second identical prove collects ZERO events
     (jit cache hit); that invariant is pinned in tests.

Listeners cannot be unregistered in this jax version, so `install()`
is idempotent and the hook lives for the process. The module itself is
stdlib-only at import time (the jax import happens inside `install()`,
and degrades to a no-op when jax is absent) — scraping /metrics or
building a manifest never pulls in jax.
"""

from __future__ import annotations

import contextlib
import threading

from . import metrics, tracing

COMPILE_EVENT_PREFIX = "/jax/core/compile/"

# the event that represents one actual XLA compilation (cache-miss
# signal); the others are phases of the same miss
BACKEND_COMPILE = "backend_compile"

# plain (duration-less) jax.monitoring events fired by the PERSISTENT
# compilation cache on every lookup: a hit means the XLA compile step was
# skipped entirely (tracing/lowering still ran). Surfaced so bench JSON
# can distinguish "warm disk cache" from "genuinely recompiled" — the
# multichip SPMD programs are minutes-scale compiles on this box
PERSISTENT_CACHE_EVENTS = {
    "/jax/compilation_cache/cache_hits": "hit",
    "/jax/compilation_cache/cache_misses": "miss",
}

UNATTRIBUTED = "unattributed"

_LOCK = threading.Lock()
_installed = False
_install_failed: str | None = None


class _Local(threading.local):
    def __init__(self):
        self.events: list | None = None
        # innermost-wins stack of named compile entry points (see
        # `entry_point`): sharded/batched runner entries push their own
        # name so a compile triggered inside e.g. `prove/commit_advice`
        # is attributed to the jitted entry that actually missed its
        # trace cache, not lumped into the parent phase span
        self.entry_points: list[str] = []


_local = _Local()


@contextlib.contextmanager
def entry_point(name: str):
    """Attribute compile events fired inside this block to `name`.

    Nested entry points win innermost-first (a two-level jit compiles
    under the inner name); with no entry point open, attribution falls
    back to the innermost tracing span (the phase) as before."""
    _local.entry_points.append(name)
    try:
        yield
    finally:
        _local.entry_points.pop()


def current_entry_point() -> str | None:
    st = _local.entry_points
    return st[-1] if st else None


def _attribution() -> str:
    return (current_entry_point() or tracing.current_span_name()
            or UNATTRIBUTED)


def _kind(event: str) -> str:
    # "/jax/core/compile/backend_compile_duration" -> "backend_compile"
    k = event[len(COMPILE_EVENT_PREFIX):]
    return k[:-len("_duration")] if k.endswith("_duration") else k


def _listener(event: str, duration_secs: float, **_kw):
    # fires synchronously on the compiling thread => the thread-local
    # trace/collector of the job that triggered the compile is active
    if not event.startswith(COMPILE_EVENT_PREFIX):
        return
    kind = _kind(event)
    fn = _attribution()
    # round ONCE and feed the same float to histogram and manifest sink:
    # tests pin exact (not approximate) parity between the two
    secs = round(float(duration_secs), 6)
    if kind == BACKEND_COMPILE:
        metrics.COMPILE_SECONDS.labels(fn=fn).observe(secs)
    tracing.add_completed_span(f"compile/{kind}", duration_secs, fn=fn)
    sink = _local.events
    if sink is not None:
        sink.append({"event": kind, "fn": fn, "seconds": secs})


def _event_listener(event: str, **_kw):
    """Plain-event listener: persistent compile-cache hit/miss counts."""
    tag = PERSISTENT_CACHE_EVENTS.get(event)
    if tag is None:
        return
    with _LOCK:
        _cache_counts[tag] += 1
    sink = _local.events
    if sink is not None:
        sink.append({"event": f"persistent_cache_{tag}",
                     "fn": _attribution(),
                     "seconds": 0.0})


_cache_counts = {"hit": 0, "miss": 0}


def cache_counts() -> dict:
    """Process-lifetime persistent compile-cache hit/miss totals."""
    with _LOCK:
        return dict(_cache_counts)


def install() -> bool:
    """Register the listeners (idempotent). Returns True when the hook
    is live; False when jax is unavailable in this process."""
    global _installed, _install_failed
    with _LOCK:
        if _installed:
            return True
        if _install_failed is not None:
            return False
        try:
            from jax import monitoring
            monitoring.register_event_duration_secs_listener(_listener)
            # older jax lacks the plain-event hook; duration telemetry
            # still works without cache hit/miss counts
            if hasattr(monitoring, "register_event_listener"):
                monitoring.register_event_listener(_event_listener)
        except Exception as exc:  # no jax / ancient jax: telemetry off
            _install_failed = f"{type(exc).__name__}: {exc}"
            return False
        _installed = True
        return True


def installed() -> bool:
    with _LOCK:
        return _installed


@contextlib.contextmanager
def capture(into: list | None = None):
    """Collect this thread's compile events into `into` (or a fresh
    list) for the duration of the block; yields the list. Nested
    captures shadow the outer one (innermost wins — one job, one
    manifest)."""
    sink = into if into is not None else []
    prev = _local.events
    _local.events = sink
    try:
        yield sink
    finally:
        _local.events = prev


def summarize(events) -> dict:
    """Manifest-shape summary of captured events: `count`/`seconds`
    cover backend_compile only (one entry per actual XLA cache miss —
    the "zero new compiles on a warm cache" signal); `by_fn` breaks the
    same backend seconds down by triggering phase; `events` keeps the
    full list including trace/lowering sub-steps."""
    backend = [e for e in events if e["event"] == BACKEND_COMPILE]
    by_fn: dict[str, dict] = {}
    for e in backend:
        slot = by_fn.setdefault(e["fn"], {"count": 0, "seconds": 0.0})
        slot["count"] += 1
        slot["seconds"] = round(slot["seconds"] + e["seconds"], 6)
    return {
        "count": len(backend),
        "seconds": round(sum(e["seconds"] for e in backend), 6),
        "by_fn": {k: by_fn[k] for k in sorted(by_fn)},
        # persistent DISK cache lookups captured in this block (a hit =
        # XLA compile skipped; tracing/lowering still ran)
        "persistent_cache": {
            tag: sum(1 for e in events
                     if e["event"] == f"persistent_cache_{tag}")
            for tag in ("hit", "miss")},
        "events": list(events),
    }


def reset_for_tests():
    """Drop the installed/failed flags so a test can exercise install()
    again. The underlying jax listener (if any) stays registered —
    re-install just won't double-register thanks to the flag staying
    set after the first successful call in a process... so tests that
    reset MUST NOT call install() again unless they accept a second
    listener. Prefer asserting on capture() output instead."""
    global _install_failed
    with _LOCK:
        _install_failed = None

"""Per-job span trees, exportable as Chrome trace-event JSON.

`utils/profiling.phase(...)` is span-aware: while a trace is active on
the current thread, every `phase` becomes a child span of the enclosing
one, so the existing instrumentation in `plonk/prover.py`,
`ProverState.prove_*` and `run_proof_method` yields a full tree per job
with ZERO changes at the call sites. The JobQueue worker opens the
trace (`trace(job_id)`) around the runner call; prove runs on that
worker thread, so propagation is implicit (thread-local).

Finished traces land in a bounded in-memory ring (SPECTRE_TRACE_KEEP,
default 128) served by the `getTrace` RPC, and — when SPECTRE_TRACE_DIR
is set — in `<dir>/<trace_id>.trace.json` files in Chrome trace-event
format (load via chrome://tracing or https://ui.perfetto.dev). The file
sink is best-effort: a full disk never fails a prove.

No trace active => `span(...)` is a no-op; the tracer costs nothing on
untraced paths (a thread-local read and a None check).
"""

from __future__ import annotations

import collections
import contextlib
import json
import os
import threading
import time

TRACE_DIR_ENV = "SPECTRE_TRACE_DIR"          # file sink (off when unset)
TRACE_KEEP_ENV = "SPECTRE_TRACE_KEEP"        # in-memory ring size
TRACE_KEEP_DEFAULT = 128


class Span:
    __slots__ = ("name", "t0", "t1", "children", "meta")

    def __init__(self, name: str, t0: float):
        self.name = name
        self.t0 = t0                 # perf_counter timestamps
        self.t1: float | None = None
        self.children: list[Span] = []
        self.meta: dict = {}

    def seconds(self) -> float | None:
        return None if self.t1 is None else self.t1 - self.t0


class Trace:
    """One span tree; trace id = job id (or a bench run label)."""

    def __init__(self, trace_id: str):
        self.trace_id = trace_id
        self.started_at = time.time()         # wall anchor for export
        self.perf0 = time.perf_counter()
        self.root = Span("job", self.perf0)
        self.finished_at: float | None = None

    def finish(self):
        if self.root.t1 is None:
            self.root.t1 = time.perf_counter()
        self.finished_at = time.time()


class _Local(threading.local):
    def __init__(self):
        self.trace: Trace | None = None
        self.stack: list[Span] = []


_local = _Local()
_LOCK = threading.Lock()
# finished traces, oldest-first (OrderedDict as a bounded ring)
_RECENT: "collections.OrderedDict[str, Trace]" = collections.OrderedDict()


def _keep() -> int:
    try:
        return max(1, int(os.environ.get(TRACE_KEEP_ENV,
                                         TRACE_KEEP_DEFAULT)))
    except ValueError:
        return TRACE_KEEP_DEFAULT


@contextlib.contextmanager
def trace(trace_id: str):
    """Open a trace on the current thread; on exit it is finished,
    registered for `getTrace`, and (optionally) written to the file
    sink. Nesting restores the previous trace (bench wraps sub-runs)."""
    prev_trace, prev_stack = _local.trace, _local.stack
    tr = Trace(trace_id)
    _local.trace, _local.stack = tr, [tr.root]
    try:
        yield tr
    finally:
        _local.trace, _local.stack = prev_trace, prev_stack
        tr.finish()
        _register(tr)
        _file_sink(tr)


def active() -> Trace | None:
    return _local.trace


@contextlib.contextmanager
def span(name: str):
    """Child span of the innermost open span; no-op without a trace."""
    tr = _local.trace
    if tr is None:
        yield None
        return
    s = Span(name, time.perf_counter())
    _local.stack[-1].children.append(s)
    _local.stack.append(s)
    try:
        yield s
    finally:
        s.t1 = time.perf_counter()
        if _local.stack and _local.stack[-1] is s:
            _local.stack.pop()


def current_span_name() -> str | None:
    """Name of the innermost open span on this thread, or None when no
    trace is active. The compile-telemetry listener uses this to label
    `spectre_compile_seconds{fn=}` with the phase that triggered the
    compile (e.g. `prove/commit_advice`)."""
    tr = _local.trace
    if tr is None or not _local.stack:
        return None
    return _local.stack[-1].name


def add_completed_span(name: str, seconds: float, **meta):
    """Append an already-finished child span (ending now) under the
    innermost open span; no-op without a trace. This is how events timed
    elsewhere — XLA compile durations reported by `jax.monitoring` —
    land in the tree as `compile/*` children of the phase that was open
    while they ran."""
    tr = _local.trace
    if tr is None or not _local.stack:
        return None
    t1 = time.perf_counter()
    s = Span(name, t1 - max(0.0, float(seconds)))
    s.t1 = t1
    if meta:
        s.meta.update(meta)
    _local.stack[-1].children.append(s)
    return s


def annotate(**kw):
    """Attach key/values to the innermost open span (exported as Chrome
    `args`) — e.g. the CPU-fallback path stamps its oom/compile kind."""
    tr = _local.trace
    if tr is not None and _local.stack:
        _local.stack[-1].meta.update(kw)


def get_trace(trace_id: str) -> Trace | None:
    with _LOCK:
        return _RECENT.get(trace_id)


def _register(tr: Trace):
    with _LOCK:
        _RECENT[tr.trace_id] = tr          # re-prove overwrites: last wins
        _RECENT.move_to_end(tr.trace_id)
        keep = _keep()
        while len(_RECENT) > keep:
            _RECENT.popitem(last=False)


def _file_sink(tr: Trace):
    d = os.environ.get(TRACE_DIR_ENV)
    if not d:
        return
    try:
        os.makedirs(d, exist_ok=True)
        path = os.path.join(d, f"{tr.trace_id}.trace.json")
        with open(path, "w") as f:
            json.dump(chrome_trace(tr), f)
    except OSError:
        pass                               # the sink never fails a prove


def chrome_trace(tr: Trace) -> dict:
    """Chrome trace-event JSON (the `traceEvents` object form): one "X"
    (complete) event per span, timestamps in microseconds anchored to
    the trace's wall-clock start."""
    pid = os.getpid()
    events = []

    def emit(s: Span):
        t1 = s.t1 if s.t1 is not None else s.t0
        events.append({
            "name": s.name, "ph": "X", "cat": "prove",
            "ts": round((tr.started_at + (s.t0 - tr.perf0)) * 1e6, 3),
            "dur": round((t1 - s.t0) * 1e6, 3),
            "pid": pid, "tid": 0,
            **({"args": dict(s.meta)} if s.meta else {}),
        })
        for c in s.children:
            emit(c)

    emit(tr.root)
    return {"traceEvents": events, "displayTimeUnit": "ms",
            "otherData": {"trace_id": tr.trace_id}}


def phase_seconds(tr: Trace) -> dict[str, float]:
    """Total seconds per span name (root excluded) — the shared schema
    between production traces and bench.py's `phase_seconds` key."""
    out: dict[str, float] = {}

    def walk(s: Span):
        for c in s.children:
            if c.t1 is not None:
                out[c.name] = out.get(c.name, 0.0) + (c.t1 - c.t0)
            walk(c)

    walk(tr.root)
    return {k: round(v, 6) for k, v in sorted(out.items())}


def reset():
    """Test hook: drop all retained traces."""
    with _LOCK:
        _RECENT.clear()

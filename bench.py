#!/usr/bin/env python
"""Benchmark entry point: BN254 MSM throughput, TPU vs measured CPU baseline.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

The metric is the north star from BASELINE.md: BN254 MSM points/s (the
dominant prover cost). Baseline = this repo's native C++ single-thread
Pippenger measured on this machine (the reference Rust prover cannot run here;
its MSM is the same algorithm on the same hardware class).

Resilience (round-1 lesson: the axon tunnel wedged and the bench silently fell
back to CPU at 0.014x): the device phase runs in a SUBPROCESS with a hard
deadline — a hung tunnel kills the child, not the benchmark — and is retried
before a clearly-labeled CPU fallback.
"""

import json
import os
import subprocess
import sys
import tempfile
import time

import numpy as np


def build_points(n: int) -> np.ndarray:
    """n distinct affine points as [n, 8] u64 limbs via the native lib."""
    from spectre_tpu.fields import bn254 as bn
    from spectre_tpu.native import host

    base = host.points_to_limbs([bn.G1_GEN])
    arrs = [base]
    total = 1
    while total < n:
        allp = np.concatenate(arrs)
        new = host.g1_add_affine_batch(allp, np.roll(allp, 1, axis=0))
        arrs.append(new)
        total *= 2
    return np.concatenate(arrs)[:n]


def bench_inputs(logn: int):
    n = 1 << logn
    pts64 = build_points(n)
    rng = np.random.default_rng(7)
    sc64 = rng.integers(0, 2**63, size=(n, 4), dtype=np.uint64)
    sc64[:, 3] &= (1 << 61) - 1
    return pts64, sc64


def device_phase(out_path: str):
    """Child process: run the device MSM benchmark; write JSON to out_path.

    BENCH_FORCE_CPU=1 pins the CPU platform (the labeled fallback path)."""
    if os.environ.get("BENCH_FORCE_CPU") == "1":
        import jax
        jax.config.update("jax_platforms", "cpu")
    import jax
    from spectre_tpu.plonk.backend import setup_compile_cache
    setup_compile_cache()
    import jax.numpy as jnp

    from spectre_tpu.ops import ec, field_ops as F, limbs as L, msm as MSM

    logn = int(os.environ.get("BENCH_LOGN", "16"))
    n = 1 << logn
    c = int(os.environ.get("BENCH_C", "13" if logn >= 18 else "10"))
    pts64, sc64 = bench_inputs(logn)

    ctxq = F.fq_ctx()
    x16 = L.u64limbs_to_u16limbs(pts64[:, :4])
    y16 = L.u64limbs_to_u16limbs(pts64[:, 4:])
    to_mont = jax.jit(lambda v: F.to_mont(ctxq, v))
    xm, ym = to_mont(jnp.asarray(x16)), to_mont(jnp.asarray(y16))
    one = jnp.broadcast_to(jnp.asarray(ctxq.one_mont), (n, F.NLIMBS))
    pts = jnp.stack([xm, ym, one], axis=1)
    sc16 = jnp.asarray(L.u64limbs_to_u16limbs(sc64))

    def run_aos():
        # NOTE: block_until_ready is not reliable through the axon tunnel;
        # a host transfer (np.asarray) is the only trustworthy sync point.
        return np.asarray(MSM.combine_windows(MSM.msm_windows(pts, sc16, c), c))

    from spectre_tpu.ops import msm_pallas as MP
    _soa_cache = []

    def run_soa():
        # Pallas fused-kernel SoA path; layout conversion cached outside
        # the timed iterations
        if not _soa_cache:
            _soa_cache.append(MP.to_soa(pts))
        return np.asarray(MP.combine_windows_soa(
            MP.msm_windows_soa(_soa_cache[0], sc16, c), c))

    expect = os.environ.get("BENCH_EXPECT")

    def check(res):
        if not expect:
            return True
        ex, ey = (int(v, 16) for v in expect.split(","))
        return ec.decode_points(jnp.asarray(res)[None])[0] == (ex, ey)

    # impl order: the pallas kernel path first on real devices, with the
    # plain-XLA path as in-child fallback (Mosaic availability varies by
    # backend); BENCH_IMPL=aos|soa pins one.
    impl_env = os.environ.get("BENCH_IMPL", "auto")
    if impl_env == "soa":
        impls = [("soa", run_soa)]
    elif impl_env == "aos" or jax.default_backend() == "cpu":
        impls = [("aos", run_aos)]
    else:
        impls = [("soa", run_soa), ("aos", run_aos)]

    mismatch = None
    infra_fail = None
    for impl_name, run in impls:
        try:
            res = run()  # compile + first run
            if not check(res):
                mismatch = f"{impl_name}: result mismatch"
                break      # a wrong result is a correctness regression —
                           # do NOT mask it behind a working fallback impl
            dt = float("inf")
            for _ in range(3):
                t0 = time.time()
                res = run()
                dt = min(dt, time.time() - t0)
            if not check(res):
                mismatch = f"{impl_name}: result mismatch"
                break
        except Exception as exc:  # Mosaic/lowering failures -> next impl
            infra_fail = f"{impl_name}: {type(exc).__name__}: {exc}"
            print(f"# bench impl {impl_name} failed: {infra_fail}",
                  file=sys.stderr, flush=True)
            continue
        if F._USE_MXU:
            impl_name += "+mxu"    # SPECTRE_FIELD_IMPL=mxu matmul field path
        with open(out_path, "w") as f:
            json.dump({"points_per_s": n / dt, "impl": impl_name,
                       "backend": jax.default_backend()}, f)
        return
    if mismatch:
        # WRONG result (exit 0): the parent must fail loudly — a correctness
        # regression must not masquerade as unavailability
        with open(out_path, "w") as f:
            json.dump({"error": mismatch, "backend": jax.default_backend()}, f)
    else:
        # infra-only failures: exit nonzero so the parent retries/falls back
        raise SystemExit(f"device impls failed: {infra_fail}")


def _run_child(force_cpu: bool, expect: str, timeout: float):
    """Launch the device phase with a hard deadline; returns dict or None."""
    fd, out = tempfile.mkstemp(suffix=".json")
    os.close(fd)
    env = dict(os.environ, BENCH_PHASE="device", BENCH_EXPECT=expect,
               BENCH_OUT=out)
    if force_cpu:
        env["BENCH_FORCE_CPU"] = "1"
    proc = subprocess.Popen([sys.executable, os.path.abspath(__file__)],
                            env=env, stdout=sys.stderr,
                            start_new_session=True)
    try:
        deadline = time.time() + timeout
        while time.time() < deadline:
            rc = proc.poll()
            if rc is not None:
                if rc == 0 and os.path.getsize(out):
                    with open(out) as f:
                        res = json.load(f)
                    if "error" in res:
                        raise SystemExit(
                            f"FATAL: device phase: {res['error']} "
                            f"(backend={res.get('backend')}) — correctness "
                            f"regression, not unavailability")
                    if not force_cpu and res.get("backend") == "cpu":
                        # the 'device' attempt silently came up on the CPU
                        # platform (round-1 failure mode) — treat as failed
                        return None
                    return res
                return None
            time.sleep(2.0)
        import signal
        try:
            os.killpg(proc.pid, signal.SIGKILL)
        except Exception:
            pass
        return None
    finally:
        try:
            os.unlink(out)
        except OSError:
            pass


def main():
    if os.environ.get("BENCH_PHASE") == "device":
        device_phase(os.environ["BENCH_OUT"])
        return

    from spectre_tpu.native import host

    logn = int(os.environ.get("BENCH_LOGN", "16"))
    n = 1 << logn
    pts64, sc64 = bench_inputs(logn)

    # --- CPU baseline (native C++ Pippenger, single thread, min of 3) ---
    cpu_dt = float("inf")
    for _ in range(3):
        t0 = time.time()
        cpu_res = host.g1_msm(pts64, sc64)
        cpu_dt = min(cpu_dt, time.time() - t0)
    baseline = n / cpu_dt
    expect = f"{cpu_res[0]:x},{cpu_res[1]:x}"

    # --- device phase: subprocess w/ hard deadline, retried, then fallback ---
    dev_timeout = float(os.environ.get("BENCH_DEVICE_TIMEOUT", "540"))
    suffix = ""
    result = None
    for attempt in range(int(os.environ.get("BENCH_DEVICE_ATTEMPTS", "2"))):
        result = _run_child(False, expect, dev_timeout)
        if result:
            break
        print(f"# device attempt {attempt + 1} failed/timed out; retrying",
              file=sys.stderr, flush=True)
    if not result:
        suffix = " [device backend unreachable: cpu fallback]"
        result = _run_child(True, expect,
                            float(os.environ.get("BENCH_CPU_TIMEOUT", "1200")))
    if not result:
        print(json.dumps({"metric": f"bn254_msm_2^{logn} throughput [failed]",
                          "value": 0, "unit": "points/s", "vs_baseline": 0.0}))
        return

    value = result["points_per_s"]
    print(json.dumps({
        "metric": f"bn254_msm_2^{logn} throughput" + suffix,
        "value": round(value),
        "unit": "points/s",
        "vs_baseline": round(value / baseline, 3),
    }))


if __name__ == "__main__":
    main()

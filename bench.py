#!/usr/bin/env python
"""Benchmark entry point: BN254 MSM throughput, TPU vs measured CPU baseline.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

The metric is the north star from BASELINE.md: BN254 MSM points/s (the
dominant prover cost). Baseline = this repo's native C++ single-thread
Pippenger measured on this machine (the reference Rust prover cannot run here;
its MSM is the same algorithm on the same hardware class).
"""

import json
import os
import sys
import time

import numpy as np


def build_points(n: int) -> np.ndarray:
    """n distinct affine points as [n, 8] u64 limbs via the native lib."""
    from spectre_tpu.fields import bn254 as bn
    from spectre_tpu.native import host

    base = host.points_to_limbs([bn.G1_GEN])
    arrs = [base]
    total = 1
    while total < n:
        allp = np.concatenate(arrs)
        new = host.g1_add_affine_batch(allp, np.roll(allp, 1, axis=0))
        arrs.append(new)
        total *= 2
    return np.concatenate(arrs)[:n]


def _backend_alive(timeout: float = 240.0) -> bool:
    """Probe the default JAX backend in a subprocess (the axon TPU tunnel can
    wedge; a hung backend would otherwise hang the whole benchmark).

    The probe itself must be unhangable: run in its own session with
    DEVNULL-ed pipes and poll with a hard deadline — no blocking wait that a
    D-state child could stall (capture_output's post-kill communicate can)."""
    import os as _os
    import signal
    import subprocess
    import time as _t
    code = ("import jax, numpy as np, jax.numpy as jnp;"
            "np.asarray(jnp.arange(4) * 2)")
    proc = subprocess.Popen([sys.executable, "-c", code],
                            stdout=subprocess.DEVNULL,
                            stderr=subprocess.DEVNULL,
                            start_new_session=True)
    deadline = _t.time() + timeout
    while _t.time() < deadline:
        rc = proc.poll()
        if rc is not None:
            return rc == 0
        _t.sleep(1.0)
    try:
        _os.killpg(proc.pid, signal.SIGKILL)
    except Exception:
        pass
    return False


def main():
    suffix = ""
    if not _backend_alive():
        # device backend unreachable: fall back to the CPU platform so the
        # driver still gets a valid (clearly labeled) measurement
        os.environ["JAX_PLATFORMS"] = "cpu"
        suffix = " [device backend unreachable: cpu fallback]"
    import jax
    if suffix:
        jax.config.update("jax_platforms", "cpu")
    from spectre_tpu.plonk.backend import setup_compile_cache
    setup_compile_cache()
    import jax.numpy as jnp

    from spectre_tpu.native import host
    from spectre_tpu.ops import ec, field_ops as F, limbs as L, msm as MSM

    logn = int(os.environ.get("BENCH_LOGN", "16"))
    n = 1 << logn
    c = 13 if logn >= 18 else 10

    pts64 = build_points(n)
    rng = np.random.default_rng(7)
    sc64 = rng.integers(0, 2**63, size=(n, 4), dtype=np.uint64)
    sc64[:, 3] &= (1 << 61) - 1

    # --- CPU baseline (native C++ Pippenger, single thread, min of 3) ---
    cpu_dt = float("inf")
    for _ in range(3):
        t0 = time.time()
        cpu_res = host.g1_msm(pts64, sc64)
        cpu_dt = min(cpu_dt, time.time() - t0)

    # --- TPU (or default backend) ---
    ctxq = F.fq_ctx()
    x16 = L.u64limbs_to_u16limbs(pts64[:, :4])
    y16 = L.u64limbs_to_u16limbs(pts64[:, 4:])
    to_mont = jax.jit(lambda v: F.to_mont(ctxq, v))
    xm, ym = to_mont(jnp.asarray(x16)), to_mont(jnp.asarray(y16))
    one = jnp.broadcast_to(jnp.asarray(ctxq.one_mont), (n, F.NLIMBS))
    pts = jnp.stack([xm, ym, one], axis=1)
    sc16 = jnp.asarray(L.u64limbs_to_u16limbs(sc64))

    def run():
        # NOTE: block_until_ready is not reliable through the axon tunnel;
        # a host transfer (np.asarray) is the only trustworthy sync point.
        return np.asarray(MSM.combine_windows(MSM.msm_windows(pts, sc16, c), c))

    res = run()  # compile + first run
    tpu_dt = float("inf")
    for _ in range(3):
        t0 = time.time()
        res = run()
        tpu_dt = min(tpu_dt, time.time() - t0)

    got = ec.decode_points(jnp.asarray(res)[None])[0]
    assert got == cpu_res, "TPU MSM result != CPU baseline result"

    value = n / tpu_dt
    baseline = n / cpu_dt
    print(json.dumps({
        "metric": f"bn254_msm_2^{logn} throughput" + suffix,
        "value": round(value),
        "unit": "points/s",
        "vs_baseline": round(value / baseline, 3),
    }))


if __name__ == "__main__":
    main()

#!/usr/bin/env python
"""Benchmark entry point: BN254 MSM + NTT throughput vs measured baselines.

Prints ONE JSON line PER METRIC:
  {"metric": "bn254_msm_2^k throughput", "value": N, "unit": "points/s",
   "vs_baseline": N, "backend": ..., "msm_mode": ..., "impl": ...,
   "fallback": bool}
  {"metric": "bn254_ntt_2^k throughput", "value": N, "unit": "polys/s",
   "vs_baseline": N, "backend": ..., "ntt_mode": ..., "impl": "batched",
   "fallback": bool}

MSM metric (north star from BASELINE.md): BN254 MSM points/s (a dominant
prover cost). Baseline = this repo's native C++ single-thread Pippenger
measured on this machine (the reference Rust prover cannot run here; its
MSM is the same algorithm on the same hardware class). `backend` and
`msm_mode` are first-class JSON keys — the metric name is never mangled.

NTT metric (ISSUE 4): batched coset-LDE throughput in polys/s — B columns
of 2^k coefficients extended onto the 4x coset (the quotient-pass shape)
through the batched FUSED kernel (`ops/ntt.py:coset_lde_std`,
SPECTRE_NTT_MODE). Baseline = the pre-PR shape: a per-column jitted
scale-then-radix-2-NTT loop over the same columns on the same platform.
The batched result is checked byte-identical against the per-column loop
in-run, so a kernel bug fails loudly instead of producing a fast wrong
number. `ntt_mode` is a first-class JSON key. BENCH_METRIC=msm|ntt runs
one metric; default runs both.

MSM mode: SPECTRE_MSM_MODE if set, else the full `fixed` stack
(GLV + signed digits + per-SRS precomputed tables, ops/msm.py). The result
is checked in-run against the native oracle, so a mode bug fails loudly
instead of producing a fast wrong number.

Resilience (round-1 lesson: the axon tunnel wedged and the bench silently fell
back to CPU at 0.014x): the device phase runs in a SUBPROCESS with a hard
deadline — a hung tunnel kills the child, not the benchmark — and is retried
before a clearly-labeled CPU fallback. SPECTRE_BENCH_PLATFORM skips the
guesswork: "cpu" goes straight to the pinned-CPU phase (no device attempts,
no fallback label — r05 burned ~18 min on two doomed device attempts);
any other value is pinned into the child's JAX_PLATFORMS.

`python bench.py --fast` is the CI tier: 2^12 on pinned CPU, compared
against the checked-in floor in bench_floor.json (fails on >20% regression).

`python bench.py --sweep-window` times the MSM at each window width c and
emits one points/s JSON line per width (see bench_sweep_window) — the
measurement behind the default_window tables; SPECTRE_MSM_WINDOW pins a
winner. Every MSM JSON line records the resolved `msm_impl`
(SPECTRE_MSM_IMPL), and `--impl xla|pallas` pins it for the invocation —
the pallas-vs-xla per-width sweep is `--sweep-window --impl pallas`. The NTT child additionally reports `ntt_kernel` and a byte-checked
stages-vs-matmul `kernel_compare` sample (SPECTRE_NTT_KERNEL).

Multichip tier (ISSUE 13): BENCH_METRIC=multichip (= `make bench-multichip`)
forces SPECTRE_BENCH_DEVICES virtual CPU devices in the child, runs the
sharded MSM/NTT micro-kernels (oracle-checked) AND a complete k=13 mesh
prove byte-checked against the host prover, and must finish inside
BENCH_MULTICHIP_TIMEOUT — the JSON carries n_devices, per-device points/s,
the ShardingPlan description, compile + persistent-cache telemetry, and on
failure the child's rc + stderr tail (the MULTICHIP_r01-r05 rc=124 history
is the reason this tier exists).
"""

import json
import os
import subprocess
import sys
import tempfile
import time

import numpy as np

FLOOR_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "bench_floor.json")


def bench_msm_mode() -> str:
    return os.environ.get("SPECTRE_MSM_MODE", "fixed")


def bench_ntt_mode() -> str:
    # radix2 is the measured-faster CPU default for the batched kernel;
    # fourstep is the TPU/MXU-shaped mode (see README "NTT modes")
    return os.environ.get("SPECTRE_NTT_MODE", "radix2")


def build_points(n: int) -> np.ndarray:
    """n distinct affine points as [n, 8] u64 limbs via the native lib."""
    from spectre_tpu.fields import bn254 as bn
    from spectre_tpu.native import host

    base = host.points_to_limbs([bn.G1_GEN])
    arrs = [base]
    total = 1
    while total < n:
        allp = np.concatenate(arrs)
        new = host.g1_add_affine_batch(allp, np.roll(allp, 1, axis=0))
        arrs.append(new)
        total *= 2
    return np.concatenate(arrs)[:n]


def bench_inputs(logn: int):
    n = 1 << logn
    pts64 = build_points(n)
    rng = np.random.default_rng(7)
    sc64 = rng.integers(0, 2**63, size=(n, 4), dtype=np.uint64)
    sc64[:, 3] &= (1 << 61) - 1
    return pts64, sc64


def device_phase(out_path: str):
    """Child process: run the device MSM benchmark; write JSON to out_path.

    BENCH_FORCE_CPU=1 pins the CPU platform (the labeled fallback path)."""
    if os.environ.get("BENCH_FORCE_CPU") == "1":
        import jax
        jax.config.update("jax_platforms", "cpu")
    import jax
    from spectre_tpu.plonk.backend import setup_compile_cache
    setup_compile_cache()
    import jax.numpy as jnp

    from spectre_tpu.ops import ec, field_ops as F, limbs as L, msm as MSM

    logn = int(os.environ.get("BENCH_LOGN", "16"))
    n = 1 << logn
    mode = bench_msm_mode()
    # BENCH_C pins the window size; unset -> the mode's own tuning table
    c_env = os.environ.get("BENCH_C")
    c = int(c_env) if c_env else None
    pts64, sc64 = bench_inputs(logn)

    ctxq = F.fq_ctx()
    x16 = L.u64limbs_to_u16limbs(pts64[:, :4])
    y16 = L.u64limbs_to_u16limbs(pts64[:, 4:])
    to_mont = jax.jit(lambda v: F.to_mont(ctxq, v))
    xm, ym = to_mont(jnp.asarray(x16)), to_mont(jnp.asarray(y16))
    one = jnp.broadcast_to(jnp.asarray(ctxq.one_mont), (n, F.NLIMBS))
    pts = jnp.stack([xm, ym, one], axis=1)
    sc16 = jnp.asarray(L.u64limbs_to_u16limbs(sc64))

    def run_aos():
        # NOTE: block_until_ready is not reliable through the axon tunnel;
        # a host transfer (np.asarray) is the only trustworthy sync point.
        # The mode dispatch (vanilla/glv/glv+signed/fixed) lives in MSM.msm;
        # the fixed-base table is built+cached on the first (untimed) call.
        return np.asarray(MSM.msm(pts, sc16, c=c, mode=mode,
                                  base_key=("bench", logn)))

    from spectre_tpu.ops import msm_pallas as MP
    _soa_cache = []

    def run_soa():
        # direct bucket-kernel SoA path (vanilla recode, no mode dispatch);
        # layout conversion cached outside the timed iterations
        c_soa = c or (11 if logn >= 18 else 10)
        if not _soa_cache:
            _soa_cache.append(MP.to_soa(pts))
        return np.asarray(MP.combine_windows_soa(
            MP.msm_bucket_windows(_soa_cache[0], sc16, None, c_soa, 254),
            c_soa))

    expect = os.environ.get("BENCH_EXPECT")

    def check(res):
        if not expect:
            return True
        ex, ey = (int(v, 16) for v in expect.split(","))
        return ec.decode_points(jnp.asarray(res)[None])[0] == (ex, ey)

    # impl order: the raw SoA kernel path first on real devices, with the
    # mode-dispatched AoS path (which itself honors SPECTRE_MSM_IMPL —
    # xla or the pallas bucket pipeline, every mode) as in-child fallback
    # (Mosaic availability varies by backend); BENCH_IMPL=aos|soa pins
    # one. run_soa times the vanilla recode only, so non-vanilla modes
    # pin the AoS dispatch path.
    impl_env = os.environ.get("BENCH_IMPL", "auto")
    if impl_env == "soa":
        impls = [("soa", run_soa)]
    elif (impl_env == "aos" or mode != "vanilla"
          or jax.default_backend() == "cpu"):
        impls = [("aos", run_aos)]
    else:
        impls = [("soa", run_soa), ("aos", run_aos)]

    # span-traced phases (ISSUE 7): bench JSON carries the SAME
    # phase_seconds schema production traces expose via getTrace, and
    # running the gated floors with tracing active doubles as the
    # instrumentation-overhead gate. Compile telemetry (ISSUE 8) rides
    # the same runs: the jax.monitoring hook splits compile_seconds out
    # of the record so floors keep gating steady-state run time only.
    from spectre_tpu.observability import compilelog, tracing
    from spectre_tpu.utils.profiling import phase
    compilelog.install()

    mismatch = None
    infra_fail = None
    for impl_name, run in impls:
        try:
            with tracing.trace(f"bench-msm-{impl_name}") as tr, \
                    compilelog.capture() as cev:
                with phase("bench/warmup_compile"):
                    # compile + first run (+ fixed-base table build)
                    res = run()
                if not check(res):
                    mismatch = f"{impl_name}: result mismatch"
                    break  # a wrong result is a correctness regression —
                           # do NOT mask it behind a working fallback impl
                dt = float("inf")
                for _ in range(3):
                    with phase("bench/run"):
                        t0 = time.time()
                        res = run()
                        dt = min(dt, time.time() - t0)
                if not check(res):
                    mismatch = f"{impl_name}: result mismatch"
                    break
        except Exception as exc:  # Mosaic/lowering failures -> next impl
            infra_fail = f"{impl_name}: {type(exc).__name__}: {exc}"
            print(f"# bench impl {impl_name} failed: {infra_fail}",
                  file=sys.stderr, flush=True)
            continue
        if F._USE_MXU:
            impl_name += "+mxu"    # SPECTRE_FIELD_IMPL=mxu matmul field path
        comp = compilelog.summarize(cev)
        with open(out_path, "w") as f:
            json.dump({"points_per_s": n / dt, "impl": impl_name,
                       "msm_mode": mode if impl_name.startswith("aos")
                       else "vanilla",
                       "msm_impl": MSM.msm_impl(),
                       "phase_seconds": tracing.phase_seconds(tr),
                       "compile_seconds": comp["seconds"],
                       "compile_count": comp["count"],
                       "backend": jax.default_backend()}, f)
        return
    if mismatch:
        # WRONG result (exit 0): the parent must fail loudly — a correctness
        # regression must not masquerade as unavailability
        with open(out_path, "w") as f:
            json.dump({"error": mismatch, "backend": jax.default_backend()}, f)
    else:
        # infra-only failures: exit nonzero so the parent retries/falls back
        raise SystemExit(f"device impls failed: {infra_fail}")


def ntt_device_phase(out_path: str):
    """Child process: batched fused coset-LDE vs the per-column pre-PR
    loop, SAME platform for both — the ratio isolates the pipeline win."""
    if os.environ.get("BENCH_FORCE_CPU") == "1":
        import jax
        jax.config.update("jax_platforms", "cpu")
    import jax
    from spectre_tpu.plonk.backend import setup_compile_cache
    setup_compile_cache()
    import jax.numpy as jnp

    from spectre_tpu.fields import bn254 as bn
    from spectre_tpu.ops import field_ops as F, limbs as L, ntt as NTT
    from spectre_tpu.plonk.domain import COSET_GEN, EXTENSION

    logn = int(os.environ.get("BENCH_LOGN", "16"))
    batch = int(os.environ.get("BENCH_NTT_BATCH", "16"))
    mode = bench_ntt_mode()
    n = 1 << logn
    n_ext = n * EXTENSION
    log_ext = logn + 2
    omega_ext = bn.fr_root_of_unity(log_ext)
    g = COSET_GEN

    rng = np.random.default_rng(11)
    coeffs = rng.integers(0, 2**63, size=(batch, n, 4), dtype=np.uint64)
    coeffs[:, :, 3] &= (1 << 61) - 1          # < R
    stack = np.zeros((batch, n_ext, 4), dtype=np.uint64)
    stack[:, :n] = coeffs
    std16 = L.u64limbs_to_u16limbs(stack.reshape(-1, 4)).reshape(
        batch, n_ext, 16)
    stack_d = jnp.asarray(std16)

    fctx = F.fr_ctx()
    pow_tab = NTT._power_table(log_ext, g)
    to_mont_jit = jax.jit(lambda v: F.to_mont(fctx, v))

    def one_col_prepr(x_std):
        # the FAITHFUL pre-PR per-column shape (backend.ntt /
        # domain.coeff_to_extended): jitted boundary conversion, then a
        # separate coset-scale pass and an EAGER op-by-op radix-2 NTT —
        # the unjitted module functions the backend used to call, one
        # device dispatch per mont_mul/add/sub/gather per stage
        m16 = to_mont_jit(x_std)
        scaled = F.mont_mul(fctx, m16, jnp.asarray(pow_tab))
        return NTT._ntt_stages(scaled, log_ext, omega_ext)

    # jitted-loop reference (not the headline baseline): the same
    # per-column pipeline as ONE compiled program per column — isolates
    # how much of the win is batching+fusion vs dispatch amortization
    one_col_jit = jax.jit(
        lambda x: NTT._ntt_stages(
            F.mont_mul(fctx, F.to_mont(fctx, x), jnp.asarray(pow_tab)),
            log_ext, omega_ext))

    def run_batched():
        return np.asarray(NTT.coset_lde_std(stack_d, omega_ext, g,
                                            mode=mode))

    # span-traced phases (ISSUE 7): same schema as the MSM child / getTrace;
    # compile telemetry (ISSUE 8) separates compile from throughput
    from spectre_tpu.observability import compilelog, tracing
    from spectre_tpu.utils.profiling import phase
    compilelog.install()

    with tracing.trace(f"bench-ntt-{mode}") as tr, \
            compilelog.capture() as cev:
        # compile + correctness gate: the batched fused kernel must be
        # BYTE-IDENTICAL to the per-column jitted loop (exact arithmetic)
        with phase("bench/byte_check"):
            want = np.stack([np.asarray(one_col_jit(stack_d[i]))
                             for i in range(batch)])
            got = run_batched()
        if not np.array_equal(want, got):
            with open(out_path, "w") as f:
                json.dump({"error": f"ntt batched/{mode} result mismatch vs "
                           f"per-column loop",
                           "backend": jax.default_backend()}, f)
            return

        # the eager pre-PR loop is ~60x slower per column on this box —
        # time a small sample once and scale (it IS the thing being
        # replaced; burning the full batch x3 would dominate bench
        # wall-clock)
        base_cols = min(2, batch)
        with phase("bench/eager_baseline"):
            sample = np.asarray(one_col_prepr(stack_d[0]))  # warm caches
            assert np.array_equal(sample, want[0]), \
                "pre-PR loop result mismatch"
            t0 = time.time()
            for i in range(base_cols):
                np.asarray(one_col_prepr(stack_d[i]))
            base_dt = (time.time() - t0) / base_cols * batch

        jl_dt = float("inf")
        for _ in range(3):
            with phase("bench/jitted_loop"):
                t0 = time.time()
                for i in range(batch):
                    np.asarray(one_col_jit(stack_d[i]))
                jl_dt = min(jl_dt, time.time() - t0)

        dt = float("inf")
        for _ in range(3):
            with phase("bench/run"):
                t0 = time.time()
                run_batched()
                dt = min(dt, time.time() - t0)

        # short-transform kernel comparison (SPECTRE_NTT_KERNEL): time the
        # fourstep pipeline with butterfly stages vs the DFT-matmul body on
        # a small sample of the same columns, byte-checked against each
        # other — the honest stages-vs-matmul number for THIS platform
        # (BASELINE.md: the matmul body targets the MXU; CPU runs it on
        # im2col-style matmuls and is expected slower). BENCH_NTT_COMPARE=0
        # skips the sample.
        kcomp = None
        if os.environ.get("BENCH_NTT_COMPARE", "1") != "0":
            bc = min(batch, 4)
            sample_d = stack_d[:bc]

            def run_kernel(kern):
                return np.asarray(NTT.coset_lde_std(
                    sample_d, omega_ext, g, mode="fourstep", kernel=kern))

            with phase("bench/kernel_compare"):
                ks = {}
                outs = {}
                for kern in NTT.NTT_KERNELS:
                    outs[kern] = run_kernel(kern)      # compile + warm
                    kdt = float("inf")
                    for _ in range(2):
                        t0 = time.time()
                        run_kernel(kern)
                        kdt = min(kdt, time.time() - t0)
                    ks[kern] = round(bc / kdt, 3)
                if not np.array_equal(outs["stages"], outs["matmul"]):
                    with open(out_path, "w") as f:
                        json.dump({"error": "ntt kernel compare: matmul "
                                   "result differs from stages",
                                   "backend": jax.default_backend()}, f)
                    return
                kcomp = {"mode": "fourstep", "batch": bc,
                         "polys_per_s": ks}

        comp = compilelog.summarize(cev)
        with open(out_path, "w") as f:
            json.dump({"polys_per_s": batch / dt,
                       "baseline_polys_per_s": batch / base_dt,
                       "jitted_loop_polys_per_s": batch / jl_dt,
                       "ntt_mode": mode, "ntt_kernel": NTT.ntt_kernel(),
                       "kernel_compare": kcomp, "impl": "batched",
                       "phase_seconds": tracing.phase_seconds(tr),
                       "compile_seconds": comp["seconds"],
                       "compile_count": comp["count"],
                       "backend": jax.default_backend()}, f)


def quotient_device_phase(out_path: str):
    """Child process: time the quotient phase (`compute_quotient`) with
    PRODUCTION inputs — a real prove runs with the host quotient hooked, so
    blinds/grand products/challenges are the ones a prover would see — and
    byte-check every timed device run against the host result. With >1
    device up (the multichip variant) the mesh-sharded pipeline engages and
    `quotient_sharded_degraded` must stay at zero (BENCH_EXPECT_SHARDED=1
    turns any degrade into a hard error)."""
    if os.environ.get("BENCH_FORCE_CPU") == "1":
        import jax
        jax.config.update("jax_platforms", "cpu")
    import jax
    from spectre_tpu.plonk.backend import setup_compile_cache
    setup_compile_cache()

    import spectre_tpu.plonk.prover as P
    from spectre_tpu.observability import compilelog, tracing
    from spectre_tpu.plonk import backend as B, quotient_device as QD
    from spectre_tpu.test_utils import (mesh_prove_fixture,
                                        seeded_blinding_rng)
    from spectre_tpu.utils.health import HEALTH
    from spectre_tpu.utils.profiling import phase
    compilelog.install()

    kk = int(os.environ.get("BENCH_QUOTIENT_K", "11"))
    srs, pk, asg = mesh_prove_fixture(k=kk)

    cap = {}
    orig_q = P._quotient_host

    def wrapped(cfg_, dom_, bk_, pk_, polys_, beta, gamma, y):
        h_host = orig_q(cfg_, dom_, bk_, pk_, polys_, beta, gamma, y)

        def fetch(key):
            kind, j = key
            if key in polys_:
                return polys_[key]
            if kind == "shk":
                return pk_.sha_k_poly
            return {"q": pk_.selector_polys, "fix": pk_.fixed_polys,
                    "sig": pk_.sigma_polys, "tab": pk_.table_polys,
                    "shq": pk_.sha_selector_polys}[kind][j]

        cap.update(cfg=cfg_, dom=dom_, fetch=fetch, beta=beta,
                   gamma=gamma, y=y, h_host=h_host)
        return h_host

    with tracing.trace(f"bench-quotient-k{kk}") as tr, \
            compilelog.capture() as cev:
        with phase("bench/prove_host"):
            P._quotient_host = wrapped
            try:
                P.prove(pk, srs, asg, B.CpuBackend(),
                        blinding_rng=seeded_blinding_rng())
            finally:
                P._quotient_host = orig_q

        ndev = jax.local_device_count()
        deg0 = HEALTH.snapshot()["counters"].get(
            "quotient_sharded_degraded", 0)

        def run():
            return QD.compute_quotient(cap["cfg"], cap["dom"], cap["fetch"],
                                       cap["beta"], cap["gamma"], cap["y"])

        with phase("bench/warmup_compile"):
            got = run()
        dt = float("inf")
        for _ in range(3):
            with phase("bench/run"):
                t0 = time.time()
                got = run()
                dt = min(dt, time.time() - t0)
        degraded = HEALTH.snapshot()["counters"].get(
            "quotient_sharded_degraded", 0) - deg0
        if not np.array_equal(got, cap["h_host"]):
            with open(out_path, "w") as f:
                json.dump({"error": f"device quotient k={kk} != host "
                           "quotient bytes",
                           "backend": jax.default_backend()}, f)
            return
        if os.environ.get("BENCH_EXPECT_SHARDED") == "1" and degraded:
            with open(out_path, "w") as f:
                json.dump({"error": f"quotient mesh path degraded "
                           f"{degraded}x on the happy path "
                           f"(n_devices={ndev})",
                           "backend": jax.default_backend()}, f)
            return

    comp = compilelog.summarize(cev)
    with open(out_path, "w") as f:
        json.dump({"quotients_per_s": 1.0 / dt,
                   "quotient_s": round(dt, 3),
                   "quotient_k": kk,
                   "n_devices": ndev,
                   "sharded_degraded": degraded,
                   "ntt_mode": bench_ntt_mode(),
                   "ntt_kernel": os.environ.get("SPECTRE_NTT_KERNEL",
                                                "stages"),
                   "phase_seconds": tracing.phase_seconds(tr),
                   "compile_seconds": comp["seconds"],
                   "compile_count": comp["count"],
                   "backend": jax.default_backend()}, f)


def multichip_device_phase(out_path: str):
    """Child process: N virtual-device mesh prove + MSM/NTT micro-bench.

    The parent injects XLA_FLAGS=--xla_force_host_platform_device_count=N
    and pins the CPU platform before jax loads; the shard gates are forced
    low so 2^12 kernels and the k=13 prove actually ride the mesh path.
    Every result is correctness-gated in-run: MSM vs the native oracle,
    NTT vs the single-device CPU backend, and the prove BYTE-IDENTICAL to
    a host prove with the same seeded blinding — the rc=124 history of
    this path (MULTICHIP_r01-r05) is exactly why finishing inside the
    parent's deadline IS the metric."""
    import jax
    jax.config.update("jax_platforms", "cpu")
    from spectre_tpu.plonk.backend import setup_compile_cache
    setup_compile_cache()

    from spectre_tpu.native import host
    from spectre_tpu.observability import compilelog, tracing
    from spectre_tpu.ops import msm as MSM
    from spectre_tpu.parallel.plan import current_plan
    from spectre_tpu.utils.profiling import phase
    compilelog.install()

    want_dev = int(os.environ.get("SPECTRE_BENCH_DEVICES", "8"))
    ndev = jax.local_device_count()
    if ndev < want_dev:
        raise SystemExit(
            f"multichip bench: {want_dev} virtual devices requested, got "
            f"{ndev} — XLA_FLAGS applied after jax init?")
    plan = current_plan()

    from spectre_tpu.plonk import backend as B
    tbk = B.TpuBackend()
    logn = int(os.environ.get("BENCH_LOGN", "12"))
    n = 1 << logn
    assert tbk._use_mesh(n, tbk._shard_min_logn), \
        "multichip bench: shard gates not engaged"
    pts64, sc64 = bench_inputs(logn)

    with tracing.trace("bench-multichip") as tr, \
            compilelog.capture() as cev:
        # --- sharded MSM micro-bench (oracle-checked) ---
        with phase("bench/msm_warmup"):
            got = tbk.msm(pts64, sc64)
        ref = host.g1_msm(pts64, sc64)
        if (int(got[0]), int(got[1])) != (int(ref[0]), int(ref[1])):
            with open(out_path, "w") as f:
                json.dump({"error": "sharded MSM result mismatch vs native "
                           "oracle", "backend": jax.default_backend()}, f)
            return
        msm_dt = float("inf")
        for _ in range(3):
            with phase("bench/msm_run"):
                t0 = time.time()
                tbk.msm(pts64, sc64)
                msm_dt = min(msm_dt, time.time() - t0)

        # --- sharded NTT micro-bench (vs single-device CPU backend) ---
        from spectre_tpu.plonk.domain import Domain
        dom = Domain(logn)
        rng = np.random.default_rng(5)
        coeffs = rng.integers(0, 2**63, size=(n, 4), dtype=np.uint64)
        coeffs[:, 3] &= (1 << 61) - 1
        with phase("bench/ntt_warmup"):
            got_ntt = tbk.ntt(coeffs, dom.omega)
        if not np.array_equal(got_ntt, B.CpuBackend().ntt(coeffs,
                                                          dom.omega)):
            with open(out_path, "w") as f:
                json.dump({"error": "sharded NTT result mismatch vs CPU "
                           "backend", "backend": jax.default_backend()}, f)
            return
        ntt_dt = float("inf")
        for _ in range(3):
            with phase("bench/ntt_run"):
                t0 = time.time()
                tbk.ntt(coeffs, dom.omega)
                ntt_dt = min(ntt_dt, time.time() - t0)

        # --- the headline: a COMPLETE k-mesh prove, byte-checked ---
        from spectre_tpu.plonk.prover import prove
        from spectre_tpu.plonk.verifier import verify
        from spectre_tpu.test_utils import (mesh_prove_fixture,
                                            seeded_blinding_rng)
        kk = int(os.environ.get("BENCH_MULTICHIP_K", "13"))
        srs, pk, asg = mesh_prove_fixture(k=kk)
        with phase("bench/prove_host"):
            p_host = prove(pk, srs, asg, B.CpuBackend(),
                           blinding_rng=seeded_blinding_rng())
        with phase("bench/prove_mesh"):
            t0 = time.time()
            p_mesh = prove(pk, srs, asg, tbk,
                           blinding_rng=seeded_blinding_rng())
            prove_s = time.time() - t0
        if p_mesh != p_host:
            with open(out_path, "w") as f:
                json.dump({"error": f"mesh k={kk} proof bytes != host "
                           "prove bytes", "backend": jax.default_backend()},
                          f)
            return
        inst = [asg.instances[0]] if asg.instances else [[]]
        if not verify(pk.vk, srs, inst, p_mesh):
            with open(out_path, "w") as f:
                json.dump({"error": f"mesh k={kk} proof does not verify",
                           "backend": jax.default_backend()}, f)
            return

    comp = compilelog.summarize(cev)
    with open(out_path, "w") as f:
        json.dump({"points_per_s": n / msm_dt,
                   "points_per_s_per_device": n / msm_dt / ndev,
                   "polys_per_s": 1.0 / ntt_dt,
                   "prove_s": round(prove_s, 2),
                   "prove_k": kk,
                   "proof_bytes_identical": True,
                   "n_devices": ndev,
                   "plan": plan.describe(),
                   "msm_mode": bench_msm_mode(),
                   "msm_impl": MSM.msm_impl(),
                   "ntt_mode": bench_ntt_mode(),
                   "phase_seconds": tracing.phase_seconds(tr),
                   "compile_seconds": comp["seconds"],
                   "compile_count": comp["count"],
                   "persistent_cache": comp["persistent_cache"],
                   "backend": jax.default_backend()}, f)


def _run_child(force_cpu: bool, expect: str, timeout: float,
               platform: str | None = None, kind: str = "msm"):
    """Launch the device phase with a hard deadline; returns dict or None."""
    fd, out = tempfile.mkstemp(suffix=".json")
    os.close(fd)
    env = dict(os.environ, BENCH_PHASE="device", BENCH_EXPECT=expect,
               BENCH_OUT=out, BENCH_KIND=kind)
    if force_cpu:
        env["BENCH_FORCE_CPU"] = "1"
    elif platform:
        # operator-pinned device platform (SPECTRE_BENCH_PLATFORM): no
        # guessing which backend the ambient sitecustomize resolves to
        env["JAX_PLATFORMS"] = platform
    proc = subprocess.Popen([sys.executable, os.path.abspath(__file__)],
                            env=env, stdout=sys.stderr,
                            start_new_session=True)
    try:
        deadline = time.time() + timeout
        while time.time() < deadline:
            rc = proc.poll()
            if rc is not None:
                if rc == 0 and os.path.getsize(out):
                    with open(out) as f:
                        res = json.load(f)
                    if "error" in res:
                        raise SystemExit(
                            f"FATAL: device phase: {res['error']} "
                            f"(backend={res.get('backend')}) — correctness "
                            f"regression, not unavailability")
                    if not force_cpu and res.get("backend") == "cpu":
                        # the 'device' attempt silently came up on the CPU
                        # platform (round-1 failure mode) — treat as failed
                        return None
                    return res
                return None
            time.sleep(2.0)
        import signal
        try:
            os.killpg(proc.pid, signal.SIGKILL)
        except Exception:
            pass
        return None
    finally:
        try:
            os.unlink(out)
        except OSError:
            pass


def _run_multichip_child(timeout: float, kind: str = "multichip",
                         extra_env: dict | None = None):
    """Launch the multichip phase: fresh process (XLA_FLAGS must precede
    jax init), hard deadline, rc + stderr tail captured for the failure
    record (the MULTICHIP_r01-r05 logs all died as bare rc=124 with no
    forensics — never again)."""
    import signal

    fd, out = tempfile.mkstemp(suffix=".json")
    os.close(fd)
    logfd, logpath = tempfile.mkstemp(suffix=".log")
    os.close(logfd)
    ndev = int(os.environ.get("SPECTRE_BENCH_DEVICES", "8"))
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        flags += f" --xla_force_host_platform_device_count={ndev}"
    env = dict(os.environ, BENCH_PHASE="device", BENCH_KIND=kind,
               BENCH_OUT=out, JAX_PLATFORMS="cpu", XLA_FLAGS=flags.strip())
    # the shard gates must engage for 2^12 micro-kernels + the k=13 prove
    env.setdefault("SPECTRE_SHARD_MSM_MIN_LOGN", "10")
    env.setdefault("SPECTRE_SHARD_NTT_MIN_LOGN", "10")
    env.update(extra_env or {})
    rc, tail = None, ""
    try:
        with open(logpath, "w") as logf:
            proc = subprocess.Popen(
                [sys.executable, os.path.abspath(__file__)], env=env,
                stdout=logf, stderr=logf, start_new_session=True)
            deadline = time.time() + timeout
            while time.time() < deadline:
                rc = proc.poll()
                if rc is not None:
                    break
                time.sleep(2.0)
            if rc is None:
                try:
                    os.killpg(proc.pid, signal.SIGKILL)
                except Exception:
                    pass
                rc = 124
        with open(logpath) as f:
            tail = f.read()[-2000:]
        if rc == 0 and os.path.getsize(out):
            with open(out) as f:
                res = json.load(f)
            if "error" in res:
                raise SystemExit(
                    f"FATAL: multichip phase: {res['error']} — correctness "
                    f"regression, not unavailability")
            return res, rc, tail
        return None, rc, tail
    finally:
        for p in (out, logpath):
            try:
                os.unlink(p)
            except OSError:
                pass


def bench_multichip(fast: bool) -> bool:
    """N-virtual-device mesh bench (BENCH_METRIC=multichip): sharded
    MSM/NTT micro-throughput + a complete byte-checked k=13 mesh prove,
    all inside one hard wall-clock budget (BENCH_MULTICHIP_TIMEOUT).
    The MSM floor is gated like the other --fast floors; the prove
    *finishing* under budget is the regression gate the rc=124 history
    demanded."""
    ndev = int(os.environ.get("SPECTRE_BENCH_DEVICES", "8"))
    logn = int(os.environ.get("BENCH_LOGN", "12"))
    # measured on the 1-core reference box: ~29 min end-to-end with a
    # partially warm compile cache (the k=13 mesh prove alone is ~935s of
    # 8-way SPMD on one physical core). The budget is the REGRESSION gate —
    # the broken pre-13 path burned 600s+ without finishing the prove at
    # all; a real multi-chip host clears this with an order of magnitude
    # to spare
    budget = float(os.environ.get("BENCH_MULTICHIP_TIMEOUT", "2700"))
    result, rc, tail = _run_multichip_child(budget)
    if not result:
        print(json.dumps({
            "metric": f"multichip{ndev}_msm_2^{logn} throughput",
            "value": 0, "unit": "points/s", "vs_baseline": 0.0,
            "backend": None, "n_devices": ndev, "failed": True,
            "rc": rc, "tail": tail[-800:]}))
        return False

    value = result["points_per_s"]
    record = {
        "metric": f"multichip{ndev}_msm_2^{logn} throughput",
        "value": round(value),
        "unit": "points/s",
        "points_per_s_per_device": round(
            result["points_per_s_per_device"]),
        "ntt_polys_per_s": round(result["polys_per_s"], 2),
        "prove_s": result["prove_s"],
        "prove_k": result["prove_k"],
        "proof_bytes_identical": result["proof_bytes_identical"],
        "n_devices": result["n_devices"],
        "plan": result["plan"],
        "backend": result.get("backend"),
        "msm_mode": result.get("msm_mode"),
        "msm_impl": result.get("msm_impl"),
        "ntt_mode": result.get("ntt_mode"),
        "budget_s": budget,
    }
    if result.get("phase_seconds"):
        record["phase_seconds"] = result["phase_seconds"]
    if result.get("compile_seconds") is not None:
        record["compile_seconds"] = result["compile_seconds"]
        record["compile_count"] = result.get("compile_count", 0)
    if result.get("persistent_cache") is not None:
        # persistent compile-cache hits/misses (compilelog): a warm cache
        # shows hits>0, misses==0 — the "compile cost paid once" signal
        record["persistent_cache"] = result["persistent_cache"]
    return _emit(record, fast,
                 f"bn254_msm_2^{logn}_multichip{ndev}_points_per_s",
                 "points/s")


def bench_serve(fast: bool) -> bool:
    """Gateway read-plane drill (BENCH_METRIC=serve / make bench-serve):
    a scaled-down ISSUE-14 load drill — 10^4 simulated light clients,
    Zipf over a synthetic sealed-period store, in process. The floor
    gates requests/s; ZERO sealed-period store fallbacks is a hard
    assertion at every tier (a fallback means the pack plane silently
    stopped covering the sealed range — a correctness bug, not a perf
    regression)."""
    import tempfile

    from spectre_tpu.follower.updates import UpdateStore
    from spectre_tpu.gateway import Gateway
    from spectre_tpu.loadgen import InProcessTarget, run_drill
    from spectre_tpu.utils.health import ServiceHealth

    clients = int(os.environ.get("BENCH_SERVE_CLIENTS", "10000"))
    requests = int(os.environ.get("BENCH_SERVE_REQUESTS",
                                  str(2 * clients)))
    n_periods = int(os.environ.get("BENCH_SERVE_PERIODS", "32"))
    health = ServiceHealth()
    with tempfile.TemporaryDirectory() as tmp:
        store = UpdateStore(tmp, health=health)
        for p in range(1, n_periods + 1):
            store.append_committee(p, {
                "proof": "0x" + bytes([p % 251]).hex() * 64,
                "committee_poseidon": hex(p * 7919 + 13),
                "instances": [hex(p), hex(p + 1)]})
        gw = Gateway(store, pack_periods=8, cache_mb=32, health=health)
        tip = store.tip_period()
        rep = run_drill(InProcessTarget(gw),
                        periods=list(range(tip, 0, -1)), tip=tip,
                        clients=clients, requests=requests, seed=14,
                        health=health)
    fallbacks = rep["gateway_counters"].get("gateway_store_fallbacks", 0)
    record = {
        "metric": f"gateway_serve {clients}-client drill",
        "value": round(rep["rps"]),
        "unit": "requests/s",
        "requests": rep["requests"],
        "clients": clients,
        "periods": n_periods,
        "latency_ms": rep["latency_ms"],
        "ratio_304": rep["ratio_304"],
        "sealed_requests": rep["sealed_requests"],
        "sealed_store_fallbacks": fallbacks,
        "pack_hits": rep["gateway_counters"].get("gateway_pack_hits", 0),
    }
    if fallbacks != 0:
        record["failed"] = True
        print(json.dumps(record))
        print(f"FAIL: {fallbacks} sealed-period responses fell back to "
              "the update store — every sealed period must be served "
              "from the pack/304 plane", file=sys.stderr)
        return False
    return _emit(record, fast, "gateway_serve_requests_per_s",
                 "requests/s")


def main():
    if os.environ.get("BENCH_PHASE") == "device":
        kind = os.environ.get("BENCH_KIND")
        if kind == "ntt":
            ntt_device_phase(os.environ["BENCH_OUT"])
        elif kind == "quotient":
            quotient_device_phase(os.environ["BENCH_OUT"])
        elif kind == "multichip":
            multichip_device_phase(os.environ["BENCH_OUT"])
        else:
            device_phase(os.environ["BENCH_OUT"])
        return

    fast = "--fast" in sys.argv[1:]
    # --impl xla|pallas pins SPECTRE_MSM_IMPL for every metric this
    # invocation times (pallas-vs-xla window sweeps ride this); the
    # resolved impl is recorded in every MSM JSON line either way
    argv = sys.argv[1:]
    if "--impl" in argv:
        idx = argv.index("--impl")
        if idx + 1 >= len(argv):
            print("FAIL: --impl needs a value (xla|pallas)",
                  file=sys.stderr)
            sys.exit(2)
        os.environ["SPECTRE_MSM_IMPL"] = argv[idx + 1]
    # bench floors gate PROVE/kernel throughput, not the verify-before-
    # serve overhead (ISSUE 9) — off unless the operator pins it on; the
    # resolved value is recorded in every metric line
    os.environ.setdefault("SPECTRE_SELF_VERIFY", "off")
    if fast:
        # CI tier: seconds-scale 2^12 on pinned CPU, regression-gated
        # against the checked-in floors (bench_floor.json)
        os.environ.setdefault("BENCH_LOGN", "12")
        os.environ.setdefault("SPECTRE_BENCH_PLATFORM", "cpu")

    if "--sweep-window" in sys.argv[1:]:
        sys.exit(0 if bench_sweep_window() else 1)

    which = os.environ.get("BENCH_METRIC", "all")
    ok = True
    if which in ("all", "msm"):
        ok = bench_msm(fast) and ok
    if which in ("all", "ntt"):
        ok = bench_ntt(fast) and ok
    if which in ("all", "serve"):
        ok = bench_serve(fast) and ok
    if which in ("all", "quotient"):
        ok = bench_quotient(fast) and ok
    # multichip is opt-in (BENCH_METRIC=multichip / make bench-multichip):
    # the k=13 mesh prove is minutes-scale even warm, too heavy for "all"
    if which == "multichip":
        ok = bench_multichip(fast) and ok
    if which == "quotient_multichip":
        ok = bench_quotient_multichip(fast) and ok
    if not ok:
        sys.exit(1)


def bench_sweep_window() -> bool:
    """`python bench.py --sweep-window`: time the full MSM at each window
    width c and print one JSON line per width (points/s) plus a summary
    with the fastest c — the measurement that picks the default_window
    tables; SPECTRE_MSM_WINDOW then pins the winner fleet-wide.

    Runs in-process on the default JAX backend (SPECTRE_BENCH_PLATFORM
    pins it). BENCH_LOGN sizes the instance (default 2^12 — minutes-scale
    on CPU); BENCH_SWEEP_CS overrides the width list. Mode defaults to
    `vanilla` (SPECTRE_MSM_MODE overrides): the fixed-base path rebuilds
    its precomputed table per c, which would time table builds, not MSMs.
    Every width's result is checked equal (affine) to the first width's —
    a sweep that returns different points is a bug, not a datapoint."""
    platform = os.environ.get("SPECTRE_BENCH_PLATFORM")
    if platform:
        os.environ.setdefault("JAX_PLATFORMS", platform)
    import jax
    import jax.numpy as jnp

    from spectre_tpu.ops import ec, field_ops as F, limbs as L, msm as MSM

    logn = int(os.environ.get("BENCH_LOGN", "12"))
    n = 1 << logn
    mode = os.environ.get("SPECTRE_MSM_MODE", "vanilla")
    cs = [int(c) for c in os.environ.get(
        "BENCH_SWEEP_CS", "4,6,8,10,12").split(",")]
    pts64, sc64 = bench_inputs(logn)

    ctxq = F.fq_ctx()
    to_mont = jax.jit(lambda v: F.to_mont(ctxq, v))
    xm = to_mont(jnp.asarray(L.u64limbs_to_u16limbs(pts64[:, :4])))
    ym = to_mont(jnp.asarray(L.u64limbs_to_u16limbs(pts64[:, 4:])))
    one = jnp.broadcast_to(jnp.asarray(ctxq.one_mont), (n, F.NLIMBS))
    pts = jnp.stack([xm, ym, one], axis=1)
    sc16 = jnp.asarray(L.u64limbs_to_u16limbs(sc64))

    want_affine = None
    results = {}
    for c in cs:
        def run():
            return np.asarray(MSM.msm(pts, sc16, c=c, mode=mode,
                                      base_key=("sweep", logn, c)))

        res = run()                                # compile + warm
        affine = ec.decode_points(jnp.asarray(res)[None])[0]
        if want_affine is None:
            want_affine = affine
        elif affine != want_affine:
            print(f"FAIL: window sweep c={c} result diverges",
                  file=sys.stderr)
            return False
        dt = float("inf")
        for _ in range(2):
            t0 = time.time()
            run()
            dt = min(dt, time.time() - t0)
        results[c] = round(n / dt)
        print(json.dumps({"metric": f"bn254_msm_2^{logn} window sweep",
                          "c": c, "value": results[c], "unit": "points/s",
                          "msm_mode": mode, "msm_impl": MSM.msm_impl(),
                          "backend": jax.default_backend()}))
    best = max(results, key=results.get)
    print(json.dumps({"metric": f"bn254_msm_2^{logn} window sweep best",
                      "best_c": best, "value": results[best],
                      "unit": "points/s", "msm_mode": mode,
                      "msm_impl": MSM.msm_impl(),
                      "backend": jax.default_backend()}))
    return True


def bench_msm(fast: bool) -> bool:
    from spectre_tpu.native import host

    logn = int(os.environ.get("BENCH_LOGN", "16"))
    n = 1 << logn
    pts64, sc64 = bench_inputs(logn)

    # --- CPU baseline (native C++ Pippenger, single thread, min of 3) ---
    cpu_dt = float("inf")
    for _ in range(3):
        t0 = time.time()
        cpu_res = host.g1_msm(pts64, sc64)
        cpu_dt = min(cpu_dt, time.time() - t0)
    baseline = n / cpu_dt
    expect = f"{cpu_res[0]:x},{cpu_res[1]:x}"

    # --- device phase: subprocess w/ hard deadline, retried, then fallback.
    # SPECTRE_BENCH_PLATFORM=cpu skips the device attempts entirely (an
    # explicit pin, NOT a fallback); any other value is pinned into the
    # child's JAX_PLATFORMS. r05 lesson: two 540 s device attempts before
    # the CPU fallback burned ~18 min — the retry budget is now one 240 s
    # attempt by default (BENCH_DEVICE_TIMEOUT / BENCH_DEVICE_ATTEMPTS). ---
    platform = os.environ.get("SPECTRE_BENCH_PLATFORM")
    dev_timeout = float(os.environ.get("BENCH_DEVICE_TIMEOUT", "240"))
    cpu_timeout = float(os.environ.get("BENCH_CPU_TIMEOUT", "1200"))
    fallback = False
    result = None
    if platform == "cpu":
        result = _run_child(True, expect, cpu_timeout)
    else:
        for attempt in range(int(os.environ.get("BENCH_DEVICE_ATTEMPTS",
                                                "1"))):
            result = _run_child(False, expect, dev_timeout,
                                platform=platform)
            if result:
                break
            print(f"# device attempt {attempt + 1} failed/timed out",
                  file=sys.stderr, flush=True)
        if not result:
            fallback = True
            result = _run_child(True, expect, cpu_timeout)
    if not result:
        print(json.dumps({"metric": f"bn254_msm_2^{logn} throughput",
                          "value": 0, "unit": "points/s", "vs_baseline": 0.0,
                          "backend": None, "msm_mode": bench_msm_mode(),
                          "impl": None, "fallback": fallback,
                          "failed": True}))
        return not fast

    value = result["points_per_s"]
    record = {
        "metric": f"bn254_msm_2^{logn} throughput",
        "value": round(value),
        "unit": "points/s",
        "vs_baseline": round(value / baseline, 3),
        "backend": result.get("backend"),
        "msm_mode": result.get("msm_mode", bench_msm_mode()),
        "msm_impl": result.get("msm_impl"),
        "impl": result.get("impl"),
        "fallback": fallback,
        "self_verify": os.environ.get("SPECTRE_SELF_VERIFY", "always"),
    }
    if result.get("phase_seconds"):
        # per-phase breakdown from the child's span trace (ISSUE 7) —
        # the same schema getTrace/phase_seconds exposes in production
        record["phase_seconds"] = result["phase_seconds"]
    if result.get("compile_seconds") is not None:
        # JIT compile cost recorded separately from steady-state
        # throughput (ISSUE 8): floors keep gating run time only
        record["compile_seconds"] = result["compile_seconds"]
        record["compile_count"] = result.get("compile_count", 0)
    return _emit(record, fast, f"bn254_msm_2^{logn}_cpu_points_per_s",
                 "points/s")


def bench_ntt(fast: bool) -> bool:
    """Batched coset-LDE throughput (polys/s): same subprocess + deadline
    machinery as the MSM metric; the child measures its own per-column
    baseline on the same platform and byte-checks the batched kernel
    against it (see ntt_device_phase)."""
    logn = int(os.environ.get("BENCH_LOGN", "16"))
    platform = os.environ.get("SPECTRE_BENCH_PLATFORM")
    dev_timeout = float(os.environ.get("BENCH_DEVICE_TIMEOUT", "240"))
    cpu_timeout = float(os.environ.get("BENCH_CPU_TIMEOUT", "1200"))
    fallback = False
    result = None
    if platform == "cpu":
        result = _run_child(True, "", cpu_timeout, kind="ntt")
    else:
        for attempt in range(int(os.environ.get("BENCH_DEVICE_ATTEMPTS",
                                                "1"))):
            result = _run_child(False, "", dev_timeout, platform=platform,
                                kind="ntt")
            if result:
                break
            print(f"# ntt device attempt {attempt + 1} failed/timed out",
                  file=sys.stderr, flush=True)
        if not result:
            fallback = True
            result = _run_child(True, "", cpu_timeout, kind="ntt")
    if not result:
        print(json.dumps({"metric": f"bn254_ntt_2^{logn} throughput",
                          "value": 0, "unit": "polys/s", "vs_baseline": 0.0,
                          "backend": None, "ntt_mode": bench_ntt_mode(),
                          "impl": None, "fallback": fallback,
                          "failed": True}))
        return not fast

    value = result["polys_per_s"]
    baseline = result.get("baseline_polys_per_s") or value
    record = {
        "metric": f"bn254_ntt_2^{logn} throughput",
        "value": round(value, 2),
        "unit": "polys/s",
        "vs_baseline": round(value / baseline, 3),
        "backend": result.get("backend"),
        "ntt_mode": result.get("ntt_mode", bench_ntt_mode()),
        "ntt_kernel": result.get("ntt_kernel"),
        "impl": result.get("impl"),
        "fallback": fallback,
        "self_verify": os.environ.get("SPECTRE_SELF_VERIFY", "always"),
    }
    if result.get("kernel_compare"):
        # stages-vs-matmul short-transform sample (byte-checked in-child)
        record["kernel_compare"] = result["kernel_compare"]
    jl = result.get("jitted_loop_polys_per_s")
    if jl:
        # decomposition: how much of vs_baseline is batching+fusion vs
        # plain dispatch amortization (BASELINE.md records both)
        record["vs_jitted_loop"] = round(value / jl, 3)
    if result.get("phase_seconds"):
        record["phase_seconds"] = result["phase_seconds"]
    if result.get("compile_seconds") is not None:
        record["compile_seconds"] = result["compile_seconds"]
        record["compile_count"] = result.get("compile_count", 0)
    return _emit(record, fast, f"bn254_ntt_2^{logn}_cpu_polys_per_s",
                 "polys/s")


def bench_quotient(fast: bool) -> bool:
    """Quotient-phase latency (BENCH_METRIC=quotient / make bench-quotient):
    the child runs a real prove with the host quotient hooked to capture
    production inputs, then times byte-checked `compute_quotient` runs.
    --fast gates k=11 against the checked-in floor; the full tier adds an
    ungated k=13 datapoint (BENCH_QUOTIENT_KS overrides)."""
    default_ks = "11" if fast else "11,13"
    ks = [int(s) for s in os.environ.get("BENCH_QUOTIENT_KS",
                                         default_ks).split(",") if s]
    timeout = float(os.environ.get("BENCH_QUOTIENT_TIMEOUT", "1800"))
    ok = True
    for kk in ks:
        os.environ["BENCH_QUOTIENT_K"] = str(kk)
        result = _run_child(True, "", timeout, kind="quotient")
        if not result:
            print(json.dumps({"metric": f"quotient_k{kk} latency",
                              "value": 0, "unit": "quotients/s",
                              "backend": None, "failed": True}))
            ok = False
            continue
        record = {
            "metric": f"quotient_k{kk} latency",
            "value": round(result["quotients_per_s"], 3),
            "unit": "quotients/s",
            "quotient_s": result["quotient_s"],
            "n_devices": result["n_devices"],
            "sharded_degraded": result["sharded_degraded"],
            "backend": result.get("backend"),
            "ntt_mode": result.get("ntt_mode"),
            "ntt_kernel": result.get("ntt_kernel"),
        }
        if result.get("phase_seconds"):
            record["phase_seconds"] = result["phase_seconds"]
        if result.get("compile_seconds") is not None:
            record["compile_seconds"] = result["compile_seconds"]
            record["compile_count"] = result.get("compile_count", 0)
        ok = _emit(record, fast, f"quotient_k{kk}_cpu_per_s",
                   "quotients/s") and ok
    return ok


def bench_quotient_multichip(fast: bool) -> bool:
    """8-virtual-device mesh quotient (BENCH_METRIC=quotient_multichip /
    make bench-quotient-multichip): same child as bench_quotient on an
    N-device mesh — the sharded pipeline MUST engage (BENCH_EXPECT_SHARDED
    turns any `quotient_sharded_degraded` tick into a hard error) and
    every timed run is byte-checked against the host quotient."""
    ndev = int(os.environ.get("SPECTRE_BENCH_DEVICES", "8"))
    kk = int(os.environ.get("BENCH_QUOTIENT_K", "13"))
    budget = float(os.environ.get("BENCH_QUOTIENT_TIMEOUT", "2700"))
    result, rc, tail = _run_multichip_child(
        budget, kind="quotient",
        extra_env={"BENCH_QUOTIENT_K": str(kk), "BENCH_EXPECT_SHARDED": "1",
                   "SPECTRE_SHARD_QUOTIENT_MIN_LOGN": "10"})
    if not result:
        print(json.dumps({
            "metric": f"quotient_k{kk}_multichip{ndev} latency",
            "value": 0, "unit": "quotients/s", "backend": None,
            "n_devices": ndev, "failed": True, "rc": rc,
            "tail": tail[-800:]}))
        return False
    record = {
        "metric": f"quotient_k{kk}_multichip{ndev} latency",
        "value": round(result["quotients_per_s"], 3),
        "unit": "quotients/s",
        "quotient_s": result["quotient_s"],
        "n_devices": result["n_devices"],
        "sharded_degraded": result["sharded_degraded"],
        "backend": result.get("backend"),
        "ntt_mode": result.get("ntt_mode"),
        "ntt_kernel": result.get("ntt_kernel"),
        "budget_s": budget,
    }
    if result.get("phase_seconds"):
        record["phase_seconds"] = result["phase_seconds"]
    if result.get("compile_seconds") is not None:
        record["compile_seconds"] = result["compile_seconds"]
        record["compile_count"] = result.get("compile_count", 0)
    return _emit(record, fast, f"quotient_k{kk}_multichip{ndev}_per_s",
                 "quotients/s")


def _emit(record: dict, fast: bool, floor_key: str, unit: str) -> bool:
    """Print the metric line; in --fast mode gate >20% regressions against
    the checked-in floor (bench_floor.json)."""
    value = record["value"]
    if fast:
        floor = None
        if os.path.exists(FLOOR_PATH):
            with open(FLOOR_PATH) as f:
                floors = json.load(f)
            floor = floors.get(floor_key)
        if floor is not None:
            record["floor"] = floor
            record["regression"] = bool(value < 0.8 * floor)
        print(json.dumps(record))
        if record.get("regression"):
            print(f"FAIL: {value} {unit} is >20% below the checked-in "
                  f"floor {floor} (bench_floor.json)", file=sys.stderr)
            return False
        return True
    print(json.dumps(record))
    return True


if __name__ == "__main__":
    main()

"""Multi-device sharding tests on the virtual 8-device CPU mesh."""

import secrets

import pytest

import jax
import jax.numpy as jnp

from spectre_tpu.fields import bn254 as bn
from spectre_tpu.ops import ec, limbs as L
from spectre_tpu.parallel import make_mesh, sharded_msm
from spectre_tpu.parallel.sharded_msm import shard_points

import os

# These compile an 8-way SPMD program on virtual CPU devices — minutes of XLA
# compile on this 1-core box. The driver's dryrun_multichip covers the same
# path; run here only when explicitly requested.
pytestmark = [
    pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8 devices"),
    pytest.mark.skipif(not os.environ.get("RUN_SLOW"),
                       reason="slow SPMD compile; set RUN_SLOW=1"),
]


class TestShardedMSM:
    def test_matches_oracle_on_4x2_mesh(self):
        mesh = make_mesh(8)
        assert dict(mesh.shape) == {"data": 4, "win": 2}
        n = 64
        g = bn.G1_GEN
        pts = [bn.g1_curve.mul(g, secrets.randbelow(bn.R)) for _ in range(n)]
        scalars = [secrets.randbelow(bn.R) for _ in range(n)]
        pd, sd = shard_points(ec.encode_points(pts),
                              jnp.asarray(L.ints_to_limbs16(scalars)), mesh)
        got = ec.decode_points(sharded_msm(pd, sd, 7, mesh)[None])[0]
        want = bn.g1_curve.msm(pts, scalars)
        assert got == (int(want[0]), int(want[1]))

    def test_1d_mesh(self):
        mesh = make_mesh(8, data_axis=8)
        n = 32
        pts = [bn.g1_curve.mul(bn.G1_GEN, k + 1) for k in range(n)]
        scalars = [k * 31 + 1 for k in range(n)]
        pd, sd = shard_points(ec.encode_points(pts),
                              jnp.asarray(L.ints_to_limbs16(scalars)), mesh)
        got = ec.decode_points(sharded_msm(pd, sd, 4, mesh)[None])[0]
        want = bn.g1_curve.msm(pts, scalars)
        assert got == (int(want[0]), int(want[1]))


class TestBatchMsmDP:
    def test_batch_matches_oracle(self):
        from spectre_tpu.parallel.batch_msm import batch_msm_dp

        n, batch = 32, 5     # 5 -> exercises padding to the 8-device mesh
        pts = [bn.g1_curve.mul(bn.G1_GEN, k + 3) for k in range(n)]
        enc = ec.encode_points(pts)
        scalars = [[(k * 7 + b * 13 + 1) for k in range(n)]
                   for b in range(batch)]
        sc = jnp.stack([jnp.asarray(L.ints_to_limbs16(s)) for s in scalars])
        res = batch_msm_dp(enc, sc, c=4)
        import numpy as np
        got = ec.decode_points(np.asarray(res))
        for b in range(batch):
            want = bn.g1_curve.msm(pts, scalars[b])
            assert got[b] == (int(want[0]), int(want[1]))


def test_graft_entry_dryrun():
    import sys
    sys.path.insert(0, "/root/repo")
    import __graft_entry__ as ge
    fn, args = ge.entry()
    out = jax.jit(fn)(*args)
    assert out.shape == (3, 16)
    ge.dryrun_multichip(8)

"""Multi-device sharding tests on the virtual 8-device CPU mesh."""

import secrets

import pytest

import jax
import jax.numpy as jnp

from spectre_tpu.fields import bn254 as bn
from spectre_tpu.ops import ec, limbs as L
from spectre_tpu.parallel import make_mesh, sharded_msm
from spectre_tpu.parallel.sharded_msm import shard_points

import os

# These compile an 8-way SPMD program on virtual CPU devices — minutes of XLA
# compile on this 1-core box. The driver's dryrun_multichip covers the same
# path; run here only when explicitly requested.
pytestmark = [
    pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8 devices"),
    pytest.mark.skipif(not os.environ.get("RUN_SLOW"),
                       reason="slow SPMD compile; set RUN_SLOW=1"),
]


class TestShardedMSM:
    def test_matches_oracle_on_4x2_mesh(self):
        mesh = make_mesh(8)
        assert dict(mesh.shape) == {"data": 4, "win": 2}
        n = 64
        g = bn.G1_GEN
        pts = [bn.g1_curve.mul(g, secrets.randbelow(bn.R)) for _ in range(n)]
        scalars = [secrets.randbelow(bn.R) for _ in range(n)]
        pd, sd = shard_points(ec.encode_points(pts),
                              jnp.asarray(L.ints_to_limbs16(scalars)), mesh)
        got = ec.decode_points(sharded_msm(pd, sd, 7, mesh)[None])[0]
        want = bn.g1_curve.msm(pts, scalars)
        assert got == (int(want[0]), int(want[1]))

    def test_1d_mesh(self):
        mesh = make_mesh(8, data_axis=8)
        n = 32
        pts = [bn.g1_curve.mul(bn.G1_GEN, k + 1) for k in range(n)]
        scalars = [k * 31 + 1 for k in range(n)]
        pd, sd = shard_points(ec.encode_points(pts),
                              jnp.asarray(L.ints_to_limbs16(scalars)), mesh)
        got = ec.decode_points(sharded_msm(pd, sd, 4, mesh)[None])[0]
        want = bn.g1_curve.msm(pts, scalars)
        assert got == (int(want[0]), int(want[1]))


class TestBatchMsmDP:
    def test_batch_matches_oracle(self):
        from spectre_tpu.parallel.batch_msm import batch_msm_dp

        n, batch = 32, 5     # 5 -> exercises padding to the 8-device mesh
        pts = [bn.g1_curve.mul(bn.G1_GEN, k + 3) for k in range(n)]
        enc = ec.encode_points(pts)
        scalars = [[(k * 7 + b * 13 + 1) for k in range(n)]
                   for b in range(batch)]
        sc = jnp.stack([jnp.asarray(L.ints_to_limbs16(s)) for s in scalars])
        res = batch_msm_dp(enc, sc, c=4)
        import numpy as np
        got = ec.decode_points(np.asarray(res))
        for b in range(batch):
            want = bn.g1_curve.msm(pts, scalars[b])
            assert got[b] == (int(want[0]), int(want[1]))


def test_graft_entry_dryrun():
    import sys
    sys.path.insert(0, "/root/repo")
    import __graft_entry__ as ge
    fn, args = ge.entry()
    out = jax.jit(fn)(*args)
    assert out.shape == (3, 16)
    ge.dryrun_multichip(8)


class TestShardedNTT:
    def test_matches_single_device_kernel(self):
        from spectre_tpu.ops import field_ops as F, ntt as NTT
        from spectre_tpu.parallel.sharded_ntt import sharded_ntt
        import numpy as np

        mesh = make_mesh(8)          # data axis = 4 divides 32x32
        logn = 10
        n = 1 << logn
        from spectre_tpu.plonk.domain import Domain
        omega = Domain(logn).omega
        ctx = F.fr_ctx()
        vals = [(i * 2654435761 + 17) % bn.R for i in range(n)]
        a = jnp.asarray(ctx.encode_np(vals))
        want = np.asarray(NTT.ntt(a, omega))
        got = np.asarray(sharded_ntt(a, omega, mesh))
        assert np.array_equal(want, got)

    def test_odd_log_size(self):
        # logn=11 -> 32x64 matrix: exercises rr != cc
        from spectre_tpu.ops import field_ops as F, ntt as NTT
        from spectre_tpu.parallel.sharded_ntt import sharded_ntt
        import numpy as np

        mesh = make_mesh(8)
        logn = 11
        n = 1 << logn
        from spectre_tpu.plonk.domain import Domain
        omega = Domain(logn).omega
        ctx = F.fr_ctx()
        vals = [(i * 40503 + 5) % bn.R for i in range(n)]
        a = jnp.asarray(ctx.encode_np(vals))
        want = np.asarray(NTT.ntt(a, omega))
        got = np.asarray(sharded_ntt(a, omega, mesh))
        assert np.array_equal(want, got)


class TestShardedMsmRouting:
    @pytest.mark.parametrize("mode", ["vanilla", "glv", "glv+signed", "fixed"])
    def test_backend_routes_large_msm_through_mesh(self, monkeypatch, mode):
        """TpuBackend.msm: >= 2^min_logn points + >1 device -> sharded_msm
        (tiny threshold here; the production default is 2^20). Every MSM
        mode must survive the mesh: the GLV scalar-prep stage runs before
        device_put, signed digits recode per shard, and `fixed` runs
        SHARDED since ISSUE 13 — the window table is built by the mesh
        with rows co-resident with their point shards, and must NOT
        degrade to glv+signed (pinned via the health counter)."""
        import numpy as np
        from spectre_tpu.plonk import backend as B
        from spectre_tpu.native import host
        from spectre_tpu.utils.health import HEALTH

        monkeypatch.setenv("SPECTRE_SHARD_MSM_MIN_LOGN", "5")
        monkeypatch.setenv("SPECTRE_MSM_MODE", mode)
        bk = B.TpuBackend()
        n = 37          # deliberately not divisible by the data axis (pads)
        pts = [bn.g1_curve.mul(bn.G1_GEN, 3 * k + 2) for k in range(n)]
        scs = [(k * 7919 + 5) % bn.R for k in range(n)]
        pts64 = host.points_to_limbs(pts)
        sc64 = np.zeros((n, 4), np.uint64)
        for i, s in enumerate(scs):
            for j in range(4):
                sc64[i, j] = (s >> (64 * j)) & 0xFFFFFFFFFFFFFFFF
        degraded_before = HEALTH.get("msm_fixed_degraded")
        got = bk.msm(pts64, sc64)
        want = bn.g1_curve.msm(pts, scs)
        assert got == (int(want[0]), int(want[1]))
        if mode == "fixed":
            # the whole point of the sharded table: fixed stays fixed
            assert HEALTH.get("msm_fixed_degraded") == degraded_before


class TestBatchMsmGLVModes:
    def test_msm_many_glv_modes_match_oracle(self, monkeypatch):
        """TpuBackend.msm_many on the >1-device batch DP path with the GLV
        scalar-prep stage threaded through (half-scalar + sign-mask batch
        rows against one replicated endomorphism-expanded base)."""
        import numpy as np
        from spectre_tpu.plonk import backend as B
        from spectre_tpu.native import host

        n, batch = 32, 3
        pts = [bn.g1_curve.mul(bn.G1_GEN, 3 * k + 2) for k in range(n)]
        pts64 = host.points_to_limbs(pts)
        scs = [[(b * 131071 + k * 7919 + 5) % bn.R for k in range(n)]
               for b in range(batch)]
        sc64s = []
        for sc in scs:
            sc64 = np.zeros((n, 4), np.uint64)
            for i, s in enumerate(sc):
                for j in range(4):
                    sc64[i, j] = (s >> (64 * j)) & 0xFFFFFFFFFFFFFFFF
            sc64s.append(sc64)
        bk = B.TpuBackend()
        for mode in ("glv", "glv+signed", "fixed"):
            monkeypatch.setenv("SPECTRE_MSM_MODE", mode)
            got = bk.msm_many(pts64, sc64s)
            for sc, g in zip(scs, got):
                want = bn.g1_curve.msm(pts, sc)
                assert g == (int(want[0]), int(want[1])), mode


class TestMeshProve:
    """A COMPLETE prove rides the mesh (sharded MSM + sharded NTT through
    the TpuBackend gates) and is byte-identical to the host prove — the
    difference between 'three kernels shard' and 'the prover is multi-chip'
    (SURVEY §2c(a)). Same k as dryrun_multichip phase 4 (shared compile
    cache)."""

    _fixture = None
    _host_proofs: dict = {}

    @classmethod
    def _get_fixture(cls):
        if cls._fixture is None:
            from spectre_tpu.test_utils import mesh_prove_fixture
            cls._fixture = mesh_prove_fixture(k=13)
        return cls._fixture

    @classmethod
    def _host_proof(cls, ntt_mode):
        # one CPU reference prove per NTT mode (the identity matrix below
        # re-proves on every mesh shape against the SAME reference bytes)
        if ntt_mode not in cls._host_proofs:
            from spectre_tpu.plonk import backend as B
            from spectre_tpu.plonk.prover import prove
            from spectre_tpu.test_utils import seeded_blinding_rng
            srs, pk, asg = cls._get_fixture()
            cls._host_proofs[ntt_mode] = prove(
                pk, srs, asg, B.CpuBackend(),
                blinding_rng=seeded_blinding_rng())
        return cls._host_proofs[ntt_mode]

    def test_full_prove_byte_equality_on_mesh(self, monkeypatch):
        from spectre_tpu.plonk import backend as B
        from spectre_tpu.plonk.prover import prove
        from spectre_tpu.plonk.verifier import verify
        from spectre_tpu.test_utils import seeded_blinding_rng

        monkeypatch.setenv("SPECTRE_SHARD_MSM_MIN_LOGN", "10")
        monkeypatch.setenv("SPECTRE_SHARD_NTT_MIN_LOGN", "10")
        srs, pk, asg = self._get_fixture()
        p_host = self._host_proof("default")
        tbk = B.TpuBackend()
        assert tbk._use_mesh(1 << 13, tbk._shard_ntt_min_logn)
        p_mesh = prove(pk, srs, asg, tbk,
                       blinding_rng=seeded_blinding_rng())
        assert p_mesh == p_host
        inst = [asg.instances[0]] if asg.instances else [[]]
        assert verify(pk.vk, srs, inst, p_mesh)

    @pytest.mark.parametrize("mesh_shape", ["1x1", "2x1", "4x2"])
    @pytest.mark.parametrize("msm_mode", ["glv+signed", "fixed"])
    @pytest.mark.parametrize("ntt_mode", ["radix2", "fourstep"])
    def test_identity_matrix(self, monkeypatch, mesh_shape, msm_mode,
                             ntt_mode):
        """ISSUE 13 acceptance: proof bytes byte-identical across
        1/2/8-device meshes for every MSM/NTT mode combo, with `fixed`
        running SHARDED (the health counter pins no silent degrade).
        1x1 means a one-device plan — the mesh gates disengage and the
        plain single-device kernels prove, which IS the single-device arm
        of the identity."""
        from spectre_tpu.plonk import backend as B
        from spectre_tpu.plonk.prover import prove
        from spectre_tpu.test_utils import seeded_blinding_rng
        from spectre_tpu.utils.health import HEALTH

        monkeypatch.setenv("SPECTRE_SHARD_MSM_MIN_LOGN", "10")
        monkeypatch.setenv("SPECTRE_SHARD_NTT_MIN_LOGN", "10")
        monkeypatch.setenv("SPECTRE_MESH_SHAPE", mesh_shape)
        monkeypatch.setenv("SPECTRE_MSM_MODE", msm_mode)
        monkeypatch.setenv("SPECTRE_NTT_MODE", ntt_mode)
        srs, pk, asg = self._get_fixture()
        p_host = self._host_proof(ntt_mode)
        degraded_before = HEALTH.get("msm_fixed_degraded")
        p_mesh = prove(pk, srs, asg, B.TpuBackend(),
                       blinding_rng=seeded_blinding_rng())
        assert p_mesh == p_host, \
            f"proof bytes diverge on {mesh_shape} / {msm_mode} / {ntt_mode}"
        if msm_mode == "fixed":
            assert HEALTH.get("msm_fixed_degraded") == degraded_before, \
                "fixed mode silently degraded on the mesh"

"""C++ host library (libspectre_host.so) vs pure-Python oracle."""

import secrets

import numpy as np
import pytest

from spectre_tpu.fields import bn254 as bn
from spectre_tpu.native import host

pytestmark = pytest.mark.skipif(not host.available(), reason="native lib unavailable")


def rand_fr(n):
    return [secrets.randbelow(bn.R) for _ in range(n)]


class TestFieldOps:
    def test_mul(self):
        a, b = rand_fr(64), rand_fr(64)
        got = host.limbs_to_ints(
            host.fp_mul_batch(host.FR, host.ints_to_limbs(a), host.ints_to_limbs(b)))
        assert got == [x * y % bn.R for x, y in zip(a, b)]

    def test_fq_mul(self):
        a = [secrets.randbelow(bn.P) for _ in range(32)]
        b = [secrets.randbelow(bn.P) for _ in range(32)]
        got = host.limbs_to_ints(
            host.fp_mul_batch(host.FQ, host.ints_to_limbs(a), host.ints_to_limbs(b)))
        assert got == [x * y % bn.P for x, y in zip(a, b)]

    def test_add_sub(self):
        a, b = rand_fr(32), rand_fr(32)
        al, bl = host.ints_to_limbs(a), host.ints_to_limbs(b)
        assert host.limbs_to_ints(host.fp_add_batch(host.FR, al, bl)) == \
            [(x + y) % bn.R for x, y in zip(a, b)]
        assert host.limbs_to_ints(host.fp_sub_batch(host.FR, al, bl)) == \
            [(x - y) % bn.R for x, y in zip(a, b)]

    def test_inv_batch_with_zero(self):
        a = rand_fr(16)
        a[5] = 0  # inv(0) := 0 convention
        got = host.limbs_to_ints(host.fp_inv_batch(host.FR, host.ints_to_limbs(a)))
        for x, g in zip(a, got):
            assert g == (0 if x == 0 else pow(x, -1, bn.R))

    def test_edge_values(self):
        a = [0, 1, bn.R - 1, bn.R - 2]
        b = [bn.R - 1, bn.R - 1, bn.R - 1, 2]
        got = host.limbs_to_ints(
            host.fp_mul_batch(host.FR, host.ints_to_limbs(a), host.ints_to_limbs(b)))
        assert got == [x * y % bn.R for x, y in zip(a, b)]


class TestNTT:
    @pytest.mark.parametrize("k", [1, 3, 6, 10])
    def test_matches_naive_dft(self, k):
        n = 1 << k
        w = bn.fr_root_of_unity(k)
        data = rand_fr(n)
        dl = host.ints_to_limbs(data)
        host.fr_ntt(dl, w)
        got = host.limbs_to_ints(dl)
        if k <= 6:
            want = [sum(data[j] * pow(w, i * j, bn.R) for j in range(n)) % bn.R
                    for i in range(n)]
            assert got == want
        # inverse via omega^{-1} and scaling recovers input for all k
        dl2 = host.ints_to_limbs(got)
        host.fr_ntt(dl2, pow(w, -1, bn.R))
        ninv = pow(n, -1, bn.R)
        back = [x * ninv % bn.R for x in host.limbs_to_ints(dl2)]
        assert back == data


class TestMSM:
    def test_small_oracle(self):
        g = bn.G1_GEN
        pts = [g, bn.g1_curve.mul(g, 7), bn.g1_curve.mul(g, 1234567)]
        scalars = [3, 9, bn.R - 5]
        got = host.g1_msm(host.points_to_limbs(pts), host.ints_to_limbs(scalars))
        want = bn.g1_curve.msm(pts, scalars)
        assert got == (int(want[0]), int(want[1]))

    def test_edge_cases(self):
        g = bn.G1_GEN
        pts = [None, g, bn.g1_curve.mul(g, 3), bn.g1_curve.mul(g, 11)]
        scalars = [5, 0, secrets.randbelow(bn.R), 1]
        got = host.g1_msm(host.points_to_limbs(pts), host.ints_to_limbs(scalars))
        want = bn.g1_curve.msm(pts, scalars)
        assert got == (int(want[0]), int(want[1]))

    def test_cancellation_to_infinity(self):
        g = bn.G1_GEN
        pts = [g, bn.g1_curve.neg(g)]
        got = host.g1_msm(host.points_to_limbs(pts), host.ints_to_limbs([7, 7]))
        assert got is None

    def test_medium_random(self):
        n = 128
        g = bn.G1_GEN
        pts = [bn.g1_curve.mul(g, secrets.randbelow(bn.R)) for _ in range(n)]
        scalars = rand_fr(n)
        got = host.g1_msm(host.points_to_limbs(pts), host.ints_to_limbs(scalars))
        want = bn.g1_curve.msm(pts, scalars)
        assert got == (int(want[0]), int(want[1]))


class TestBatchedAdd:
    def test_all_cases(self):
        g = bn.G1_GEN
        a = [bn.g1_curve.mul(g, k + 1) for k in range(6)] + [None, g, None]
        b = [bn.g1_curve.mul(g, 100 + k) for k in range(6)] + [g, None, None]
        b[2] = a[2]                   # doubling
        b[3] = bn.g1_curve.neg(a[3])  # cancellation
        got = host.g1_add_affine_batch(host.points_to_limbs(a), host.points_to_limbs(b))
        for i in range(len(a)):
            want = bn.g1_curve.add(a[i], b[i])
            gx = sum(int(got[i, j]) << (64 * j) for j in range(4))
            gy = sum(int(got[i, 4 + j]) << (64 * j) for j in range(4))
            if want is None:
                assert (gx, gy) == (0, 0)
            else:
                assert (gx, gy) == (int(want[0]), int(want[1]))

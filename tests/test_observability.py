"""Observability tier (ISSUE 7): Prometheus exposition, per-job span
tracing, peak-RSS attribution.

Pins the acceptance gates:
  * GET /metrics is valid text exposition 0.0.4 whose counters match
    `HEALTH.snapshot()["counters"]` exactly (parity by construction —
    both read the same snapshot), including the
    `spectre_prove_latency_seconds` histogram;
  * `getTrace` returns well-formed Chrome trace-event JSON (nested "X"
    events) for a completed job, -32002 while it runs, -32004 when
    unknown;
  * histogram bucket math / conservative quantile pins (the p90 that
    prices `retry_after_s` must ignore the outlier a mean would not);
  * the RSS sampler thread self-terminates when the last job finishes
    (no leaked threads) and every finished job record carries
    `peak_rss_mb` through journal write AND replay.
"""

import json
import re
import threading
import time
import types
import urllib.request

import pytest

from spectre_tpu.observability import metrics as M
from spectre_tpu.observability import prom, tracing
from spectre_tpu.observability.rss import RssSampler, rss_mb
from spectre_tpu.utils import profiling as prof
from spectre_tpu.utils.health import HEALTH, ServiceHealth

# ---------------------------------------------------------------------------
# exposition parsing (strict: every non-comment line must be a sample)

_SAMPLE_RE = re.compile(
    r'^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)'
    r'(?P<labels>\{[^}]*\})? (?P<value>[^ ]+)$')


def _parse_exposition(text: str):
    """-> (samples {name{labels} -> float}, types {family -> type}).
    Raises on any line that is neither a comment nor a valid sample."""
    samples: dict[str, float] = {}
    types_: dict[str, str] = {}
    assert text.endswith("\n"), "exposition must end with a newline"
    for line in text.splitlines():
        if not line:
            continue
        if line.startswith("# TYPE "):
            _, _, rest = line.partition("# TYPE ")
            fam, typ = rest.split(" ", 1)
            types_[fam] = typ
            continue
        if line.startswith("#"):
            assert line.startswith("# HELP ") or line.startswith("# TYPE "), \
                f"stray comment: {line!r}"
            continue
        m = _SAMPLE_RE.match(line)
        assert m, f"invalid sample line: {line!r}"
        key = m.group("name") + (m.group("labels") or "")
        samples[key] = float(m.group("value").replace("+Inf", "inf"))
    return samples, types_


# ---------------------------------------------------------------------------


class TestHistogram:
    def test_bucket_math_pins(self):
        h = M.Histogram("h", buckets=(0.1, 1.0, 10.0))
        for v in (0.05, 0.1, 0.5, 5.0, 50.0):
            h.observe(v)
        snap = h.snapshot()
        # le is INCLUSIVE: 0.1 lands in the le=0.1 bucket
        assert snap["buckets"] == [(0.1, 2), (1.0, 3), (10.0, 4),
                                   (float("inf"), 5)]
        assert snap["count"] == 5
        assert snap["sum"] == pytest.approx(55.65)

    def test_quantile_conservative_and_clamped(self):
        h = M.Histogram("h", buckets=(0.1, 1.0, 10.0))
        for v in (0.05, 0.1, 0.5, 5.0, 50.0):
            h.observe(v)
        # upper bound of the bucket where cumulative crosses q*count
        assert h.quantile(0.5) == 1.0
        assert h.quantile(0.8) == 10.0
        # overflow (+Inf has no edge): clamp to the largest finite bound
        assert h.quantile(1.0) == 10.0

    def test_quantile_empty(self):
        h = M.Histogram("h", buckets=(1.0,))
        assert h.quantile(0.9) is None
        assert h.quantile(0.9, default=3.5) == 3.5

    def test_registry_reregister_returns_existing(self):
        reg = M.MetricsRegistry()
        a = reg.histogram("x", buckets=(1.0,))
        b = reg.histogram("x", buckets=(2.0, 3.0))   # ignored: same series
        assert a is b
        assert reg.counter("c") is reg.counter("c")
        assert reg.gauge("g") is reg.gauge("g")

    def test_pull_gauge(self):
        reg = M.MetricsRegistry()
        g = reg.gauge("depth", fn=lambda: 7)
        assert g.value() == 7

    def test_histogram_vec_children_per_label(self):
        vec = M.HistogramVec("v", buckets=(1.0,), labelnames=("phase",))
        vec.labels(phase="a").observe(0.5)
        vec.labels(phase="b").observe(2.0)
        vec.labels(phase="a").observe(0.5)
        kids = vec.children()
        assert [k.labels for k in kids] == [{"phase": "a"}, {"phase": "b"}]
        assert kids[0].snapshot()["count"] == 2


class TestExposition:
    def test_counter_parity_with_health_snapshot(self):
        h = ServiceHealth()
        h.incr("jobs_done", 3)
        h.incr("prove_cpu_fallbacks_step")
        h.observe("prove_latency_s", 2.0)
        reg = M.MetricsRegistry()
        text = prom.render(health=h, registry=reg)
        samples, types_ = _parse_exposition(text)
        snap = h.snapshot()
        assert snap["counters"], "test needs at least one counter"
        for name, v in snap["counters"].items():
            key = f"spectre_{name}_total"
            assert samples[key] == v, key
            assert types_[key] == "counter"
        assert samples["spectre_mean_prove_latency_s"] == 2
        assert types_["spectre_uptime_seconds"] == "gauge"

    def test_histogram_family_rendering(self):
        reg = M.MetricsRegistry()
        hist = reg.histogram("spectre_t_seconds", "help text",
                             buckets=(1.0, 10.0))
        hist.observe(0.5)
        hist.observe(5.0)
        text = prom.render(health=ServiceHealth(), registry=reg)
        samples, types_ = _parse_exposition(text)
        assert types_["spectre_t_seconds"] == "histogram"
        assert samples['spectre_t_seconds_bucket{le="1"}'] == 1
        assert samples['spectre_t_seconds_bucket{le="10"}'] == 2
        assert samples['spectre_t_seconds_bucket{le="+Inf"}'] == 2
        assert samples["spectre_t_seconds_count"] == 2
        assert samples["spectre_t_seconds_sum"] == pytest.approx(5.5)
        # +Inf bucket always equals _count (Prometheus invariant)
        assert samples['spectre_t_seconds_bucket{le="+Inf"}'] == \
            samples["spectre_t_seconds_count"]

    def test_label_escaping(self):
        assert prom._esc('a"b\nc\\d') == r'a\"b\nc\\d'

    def test_table_lru_families(self, monkeypatch):
        """LRU stats render per cache; read via sys.modules so the scrape
        never imports jax itself — faked here to keep the test light."""
        import sys
        stats = {"hits": 4, "builds": 2, "evictions": 1, "recomputes": 1,
                 "bytes": 1024, "budget_bytes": 4096, "entries": 2}
        fake = types.SimpleNamespace(lru_stats=lambda: dict(stats))
        monkeypatch.setitem(sys.modules, "spectre_tpu.ops.msm", fake)
        text = prom.render(health=ServiceHealth(),
                           registry=M.MetricsRegistry())
        samples, _ = _parse_exposition(text)
        assert samples['spectre_table_lru_hits_total{cache="msm"}'] == 4
        assert samples['spectre_table_lru_recomputes_total{cache="msm"}'] == 1
        assert samples['spectre_table_lru_bytes{cache="msm"}'] == 1024


class TestTracing:
    def test_span_nesting_and_chrome_schema(self):
        with tracing.trace("t-nest") as tr:
            with prof.phase("a"):
                with prof.phase("b"):
                    time.sleep(0.002)
            with prof.phase("c"):
                pass
        ct = tracing.chrome_trace(tr)
        assert set(ct) == {"traceEvents", "displayTimeUnit", "otherData"}
        assert ct["displayTimeUnit"] == "ms"
        assert ct["otherData"]["trace_id"] == "t-nest"
        ev = ct["traceEvents"]
        assert [e["name"] for e in ev] == ["job", "a", "b", "c"]
        for e in ev:
            assert e["ph"] == "X"
            for k in ("ts", "dur", "pid", "tid", "cat"):
                assert k in e, (k, e)
        by = {e["name"]: e for e in ev}
        # containment: child interval inside parent interval
        for child, parent in (("a", "job"), ("b", "a"), ("c", "job")):
            assert by[parent]["ts"] <= by[child]["ts"]
            assert (by[child]["ts"] + by[child]["dur"]
                    <= by[parent]["ts"] + by[parent]["dur"] + 1e-3)

    def test_span_is_noop_without_trace(self):
        assert tracing.active() is None
        with tracing.span("orphan") as s:
            assert s is None
        with prof.phase("orphan-phase"):   # must not raise either
            pass

    def test_phase_seconds_sums_per_name_excluding_root(self):
        with tracing.trace("t-ps") as tr:
            with prof.phase("p"):
                time.sleep(0.002)
            with prof.phase("p"):
                time.sleep(0.002)
            with prof.phase("q"):
                pass
        ps = tracing.phase_seconds(tr)
        assert set(ps) == {"p", "q"}       # root span "job" excluded
        assert ps["p"] >= 0.004
        assert ps["p"] >= ps["q"]

    def test_annotate_exports_as_args(self):
        with tracing.trace("t-ann") as tr:
            with tracing.span("s"):
                tracing.annotate(cpu_fallback="oom")
        ev = {e["name"]: e for e in tracing.chrome_trace(tr)["traceEvents"]}
        assert ev["s"]["args"] == {"cpu_fallback": "oom"}

    def test_retention_ring_bounded(self, monkeypatch):
        monkeypatch.setenv(tracing.TRACE_KEEP_ENV, "2")
        tracing.reset()
        for i in range(3):
            with tracing.trace(f"ring-{i}"):
                pass
        assert tracing.get_trace("ring-0") is None      # evicted
        assert tracing.get_trace("ring-1") is not None
        assert tracing.get_trace("ring-2") is not None

    def test_file_sink_writes_chrome_json(self, tmp_path, monkeypatch):
        monkeypatch.setenv(tracing.TRACE_DIR_ENV, str(tmp_path))
        with tracing.trace("sink-job"):
            with prof.phase("p"):
                pass
        ct = json.loads((tmp_path / "sink-job.trace.json").read_text())
        assert [e["name"] for e in ct["traceEvents"]] == ["job", "p"]

    def test_file_sink_tolerates_unwritable_dir(self, tmp_path, monkeypatch):
        blocker = tmp_path / "not-a-dir"
        blocker.write_text("x")
        monkeypatch.setenv(tracing.TRACE_DIR_ENV,
                           str(blocker / "sub"))     # makedirs -> OSError
        with tracing.trace("sink-fail"):             # must not raise
            pass
        assert tracing.get_trace("sink-fail") is not None

    def test_nested_trace_restores_previous(self):
        with tracing.trace("outer") as outer:
            with tracing.trace("inner"):
                assert tracing.active().trace_id == "inner"
            assert tracing.active() is outer
        assert tracing.active() is None


class TestRssSampler:
    def test_lifecycle_no_leaked_threads(self):
        if rss_mb() is None:
            pytest.skip("no /proc/self/statm on this platform")
        s = RssSampler(interval_s=0.01)
        s.start("j1")
        th = s._thread
        assert th is not None and th.is_alive()
        ballast = bytearray(4 * 1024 * 1024)        # bump RSS by ~4MB
        time.sleep(0.05)                            # let it sample
        peak = s.finish("j1")
        del ballast
        assert peak is not None and peak > 1.0
        # the "no leaked threads" contract: last key out -> thread exits
        th.join(2.0)
        assert not th.is_alive()
        deadline = time.time() + 2.0
        while s._thread is not None and time.time() < deadline:
            time.sleep(0.01)
        assert s._thread is None

    def test_finish_unknown_key_is_none(self):
        s = RssSampler(interval_s=0.01)
        assert s.finish("nope") is None

    def test_peak_readable_while_active_and_respawn(self):
        if rss_mb() is None:
            pytest.skip("no /proc/self/statm on this platform")
        s = RssSampler(interval_s=0.01)
        s.start("a")
        assert s.peak("a") is not None and s.peak("a") > 1.0
        s.finish("a")
        time.sleep(0.05)
        s.start("b")                     # respawns after self-terminate
        assert s._thread is not None and s._thread.is_alive()
        assert s.finish("b") is not None


# ---------------------------------------------------------------------------
# JobQueue integration: p90 pricing, peak-RSS through journal + replay


def _ok_runner(method, params):
    with prof.phase("prove/commit_advice"):
        time.sleep(0.005)
    return {"proof": "0xab", "w": params.get("w")}


class TestQueueObservability:
    def test_retry_after_priced_by_p90_not_mean(self, tmp_path):
        """The satellite pin: one 500s outlier in ten proves drags the
        MEAN to 57.2s but the p90 bucket bound stays 10.0 — the shed
        hint must not punish every client for one pathological job."""
        from spectre_tpu.prover_service.jobs import JobQueue
        h = ServiceHealth()
        hist = M.queue_latency_histogram()
        lat = [8.0] * 9 + [500.0]
        for v in lat:
            hist.observe(v)
            h.observe("prove_latency_s", v)
        assert h.mean("prove_latency_s") == pytest.approx(57.2)
        q = JobQueue(_ok_runner, concurrency=1,
                     journal_dir=str(tmp_path), health=h, latency_hist=hist)
        assert q.retry_after_s() == 10.0          # p90, not ~57.2
        q.stop()

    def test_retry_after_empty_histogram_falls_back_to_mean(self, tmp_path):
        from spectre_tpu.prover_service.jobs import JobQueue
        h = ServiceHealth()
        h.observe("prove_latency_s", 15.0)
        q = JobQueue(_ok_runner, concurrency=1,
                     journal_dir=str(tmp_path), health=h)
        assert q.retry_after_s() == 15.0          # seed-pinned behavior
        q.stop()

    def test_job_carries_peak_rss_through_journal_and_replay(self, tmp_path):
        from spectre_tpu.prover_service.jobs import JobQueue
        if rss_mb() is None:
            pytest.skip("no /proc/self/statm on this platform")
        q = JobQueue(_ok_runner, concurrency=1, journal_dir=str(tmp_path))
        jid = q.submit("m", {"w": 1})
        job = q.wait(jid, timeout=10)
        assert job.status == "done"
        assert job.peak_rss_mb is not None and job.peak_rss_mb > 1.0
        assert q.status(jid)["peak_rss_mb"] == job.peak_rss_mb
        recs = [json.loads(l) for l in
                open(q.journal.path)]            # noqa: E741
        done = [r for r in recs if r.get("event") == "done"]
        assert done and done[0]["peak_rss_mb"] == job.peak_rss_mb
        q.stop()
        q2 = JobQueue(_ok_runner, concurrency=1, journal_dir=str(tmp_path))
        assert q2.result(jid).peak_rss_mb == job.peak_rss_mb
        q2.stop()

    def test_memory_shed_attributes_running_jobs(self, tmp_path):
        """A memory shed journals WHICH jobs were running and their
        running peaks; the record has no job_id so replay skips it."""
        from spectre_tpu.prover_service.jobs import JobQueue, \
            ServiceOverloaded
        if rss_mb() is None:
            pytest.skip("no /proc/self/statm on this platform")
        started, gate = threading.Event(), threading.Event()

        def runner(method, params):
            started.set()
            gate.wait(10)
            return {"proof": "0x01"}

        q = JobQueue(runner, concurrency=1, journal_dir=str(tmp_path),
                     mem_watermark_mb=0)          # admit the first job
        a = q.submit("m", {"w": "a"})
        assert started.wait(10)
        q.mem_watermark_mb = 1.0                  # now any submit sheds
        with pytest.raises(ServiceOverloaded, match="memory watermark"):
            q.submit("m", {"w": "b"})
        recs = [json.loads(l) for l in
                open(q.journal.path)]            # noqa: E741
        shed = [r for r in recs if r.get("event") == "shed_memory"]
        assert shed, recs
        assert "job_id" not in shed[-1]           # replay-safe
        running = shed[-1]["running"]
        assert [r["job_id"] for r in running] == [a]
        assert running[0]["peak_rss_mb"] > 1.0
        assert shed[-1]["rss_mb"] > 1.0
        gate.set()
        assert q.wait(a, timeout=10).status == "done"
        q.stop()
        q2 = JobQueue(runner, concurrency=1,      # replay tolerates record
                      journal_dir=str(tmp_path), mem_watermark_mb=0)
        assert q2.result(a).status == "done"
        q2.stop()

    def test_prove_latency_histogram_observes_completions(self, tmp_path):
        from spectre_tpu.prover_service.jobs import JobQueue
        c0 = M.PROVE_LATENCY.snapshot()["count"]
        q = JobQueue(_ok_runner, concurrency=1, journal_dir=str(tmp_path))
        jid = q.submit("m", {"w": 2})
        assert q.wait(jid, timeout=10).status == "done"
        q.stop()
        assert M.PROVE_LATENCY.snapshot()["count"] == c0 + 1


# ---------------------------------------------------------------------------
# end to end over HTTP: /metrics scrape parity + getTrace contract


def _rpc(port, method, params, id_=1, timeout=30):
    body = json.dumps({"jsonrpc": "2.0", "id": id_, "method": method,
                       "params": params}).encode()
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/rpc", data=body,
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.load(resp)


class TestServiceObservabilityHTTP:
    def _serve(self, tmp_path, runner):
        from spectre_tpu.prover_service.jobs import ensure_jobs
        from spectre_tpu.prover_service.rpc import serve

        class S:                                   # minimal state shim
            concurrency = 1
            params_dir = str(tmp_path)

        state = S()
        ensure_jobs(state, runner=runner)          # serve() reuses it
        server = serve(state, port=0, background=True)
        return server, server.server_address[1], state

    def test_get_trace_contract_and_metrics_parity(self, tmp_path):
        gate = threading.Event()
        started = threading.Event()

        def runner(method, params):
            with prof.phase("prove/commit_advice"):
                started.set()
                gate.wait(10)
            return {"proof": "0xab"}

        server, port, state = self._serve(tmp_path, runner)
        try:
            sub = _rpc(port, "submitProof_SyncStepCompressed", {"w": 1})
            jid = sub["result"]["job_id"]
            assert started.wait(10)
            # live job: trace not available yet -> JOB_NOT_DONE
            err = _rpc(port, "getTrace", {"job_id": jid})["error"]
            assert err["code"] == -32002
            # unknown job -> JOB_NOT_FOUND
            err = _rpc(port, "getTrace", {"job_id": "nope"})["error"]
            assert err["code"] == -32004
            gate.set()
            deadline = time.time() + 10
            while time.time() < deadline:
                st = _rpc(port, "getProofStatus", {"job_id": jid})["result"]
                if st["status"] == "done":
                    break
                time.sleep(0.02)
            assert st["status"] == "done"
            prss = st.get("peak_rss_mb")
            assert prss is None or prss > 1.0     # absent off-Linux only

            # -- getTrace: well-formed Chrome trace-event JSON -----------
            ct = _rpc(port, "getTrace", {"job_id": jid})["result"]
            names = [e["name"] for e in ct["traceEvents"]]
            assert names[0] == "job"
            assert "prove/commit_advice" in names
            assert all(e["ph"] == "X" for e in ct["traceEvents"])
            assert ct["otherData"]["trace_id"] == jid
            json.dumps(ct)                         # JSON-serializable

            # -- /metrics: exact counter parity with HEALTH.snapshot -----
            snap = HEALTH.snapshot()               # no RPCs after this
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/metrics")
            with urllib.request.urlopen(req, timeout=30) as resp:
                assert resp.headers["Content-Type"] == prom.CONTENT_TYPE
                text = resp.read().decode()
            samples, types_ = _parse_exposition(text)
            for name, v in snap["counters"].items():
                assert samples[f"spectre_{name}_total"] == v, name
            # the acceptance-gated histogram, with its invariant
            assert types_["spectre_prove_latency_seconds"] == "histogram"
            cnt = samples["spectre_prove_latency_seconds_count"]
            assert cnt >= 1
            assert samples[
                'spectre_prove_latency_seconds_bucket{le="+Inf"}'] == cnt
            # job gauges reflect the drained queue
            assert samples['spectre_jobs{status="done"}'] >= 1
            assert samples["spectre_job_workers"] == 1
        finally:
            gate.set()
            state.jobs.stop()
            server.shutdown()

    def test_queue_wait_and_compile_exposition_parity(self, tmp_path):
        """ISSUE-8 acceptance: /metrics exposes
        `spectre_queue_wait_seconds` and `spectre_compile_seconds{fn=}`
        with EXACT float parity against the manifest-derived values —
        one rounded float feeds every sink, so equality is ==, not
        approx. The compile event is driven through the listener
        directly (same plumbing jax.monitoring calls into)."""
        from spectre_tpu.observability import compilelog
        M.QUEUE_WAIT.reset()
        M.COMPILE_SECONDS.reset()

        def runner(method, params):
            with prof.phase("prove/quotient"):
                compilelog._listener(
                    "/jax/core/compile/backend_compile_duration",
                    1.23456789)
            return {"proof": "0xab"}

        server, port, state = self._serve(tmp_path, runner)
        try:
            jid = _rpc(port, "submitProof_SyncStepCompressed",
                       {"w": 1})["result"]["job_id"]
            assert state.jobs.wait(jid, timeout=10).status == "done"
            man = _rpc(port, "getProofManifest",
                       {"job_id": jid})["result"]
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/metrics",
                    timeout=30) as resp:
                text = resp.read().decode()
            samples, types_ = _parse_exposition(text)

            assert types_["spectre_queue_wait_seconds"] == "histogram"
            assert samples["spectre_queue_wait_seconds_count"] == 1
            assert samples["spectre_queue_wait_seconds_sum"] \
                == man["queue_wait_s"]

            assert types_["spectre_compile_seconds"] == "histogram"
            assert man["compile"]["count"] == 1
            key = 'spectre_compile_seconds_count{fn="prove/quotient"}'
            assert samples[key] == man["compile"]["count"]
            key = 'spectre_compile_seconds_sum{fn="prove/quotient"}'
            assert samples[key] \
                == man["compile"]["by_fn"]["prove/quotient"]["seconds"] \
                == 1.234568
        finally:
            state.jobs.stop()
            server.shutdown()

    def test_rpc_client_helpers(self, tmp_path):
        from spectre_tpu.prover_service.rpc_client import ProverClient
        server, port, state = self._serve(tmp_path, _ok_runner)
        try:
            cli = ProverClient(f"http://127.0.0.1:{port}/rpc")
            text = cli.metrics_text()
            samples, _ = _parse_exposition(text)
            assert "spectre_uptime_seconds" in samples
            jid = state.jobs.submit("m", {"w": 9})
            assert state.jobs.wait(jid, timeout=10).status == "done"
            ct = cli.get_trace(jid)
            assert ct["otherData"]["trace_id"] == jid
        finally:
            state.jobs.stop()
            server.shutdown()


class TestCompileAttribution:
    """ISSUE 16 satellite: compile telemetry attributes each cache miss to
    the INNERMOST open `compilelog.entry_point`, not the parent phase —
    a two-level entry (sharded runner inside a prove phase) books its
    compile under its own name, and the span fallback still holds when no
    entry point is open."""

    def test_two_level_entry_points_per_function_counts(self):
        import jax
        import jax.numpy as jnp

        from spectre_tpu.observability import compilelog

        assert compilelog.install()
        # fresh lambdas => guaranteed trace-cache misses for each level
        outer_fn = jax.jit(lambda v: v + jnp.uint32(1))
        inner_fn = jax.jit(lambda v: v * jnp.uint32(3))
        x = jnp.arange(8, dtype=jnp.uint32)
        with tracing.trace("attr-two-level"), tracing.span("prove/phase"):
            with compilelog.capture() as events:
                with compilelog.entry_point("runner.outer"):
                    outer_fn(x).block_until_ready()
                    with compilelog.entry_point("runner.inner"):
                        inner_fn(x).block_until_ready()
                    # warm second calls: zero new events at either level
                    outer_fn(x).block_until_ready()
                    with compilelog.entry_point("runner.inner"):
                        inner_fn(x).block_until_ready()
        s = compilelog.summarize(events)
        assert s["by_fn"]["runner.outer"]["count"] == 1
        assert s["by_fn"]["runner.inner"]["count"] == 1
        # nothing leaked into the parent phase span's bucket
        assert "prove/phase" not in s["by_fn"]
        assert s["count"] == 2

    def test_span_fallback_without_entry_point(self):
        import jax
        import jax.numpy as jnp

        from spectre_tpu.observability import compilelog

        assert compilelog.install()
        fn = jax.jit(lambda v: v - jnp.uint32(7))
        x = jnp.arange(8, dtype=jnp.uint32)
        with tracing.trace("attr-fallback"), tracing.span("prove/fallback"):
            with compilelog.capture() as events:
                fn(x).block_until_ready()
        s = compilelog.summarize(events)
        assert list(s["by_fn"]) == ["prove/fallback"]
        assert s["by_fn"]["prove/fallback"]["count"] == 1


class TestIntegrityCounters:
    """ISSUE 9 pin: every output-integrity counter rides the existing
    ServiceHealth -> /healthz -> /metrics bridge — each appears in the
    exposition as spectre_<name>_total with exact snapshot parity."""

    COUNTERS = ("proofs_verified", "proofs_verify_failed",
                "proofs_sdc_retried", "self_check_failures",
                "artifacts_scrubbed", "artifacts_scrub_corrupt",
                "artifacts_expired")

    def test_new_counters_render_with_parity(self):
        h = ServiceHealth()
        for i, name in enumerate(self.COUNTERS, start=1):
            h.incr(name, i)
        text = prom.render(health=h, registry=M.MetricsRegistry())
        samples, types_ = _parse_exposition(text)
        snap = h.snapshot()["counters"]
        for i, name in enumerate(self.COUNTERS, start=1):
            key = f"spectre_{name}_total"
            assert samples[key] == i == snap[name], key
            assert types_[key] == "counter"

    def test_self_verify_phase_in_histogram_vec(self):
        # the prove/self_verify span cost lands in the same
        # spectre_phase_seconds{phase=} family every other phase uses
        from spectre_tpu.observability.metrics import PHASE_SECONDS
        PHASE_SECONDS.labels(phase="prove/self_verify").observe(0.001)
        kids = PHASE_SECONDS.children()
        assert any(k.labels == {"phase": "prove/self_verify"} for k in kids)

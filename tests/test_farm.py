"""Proof-farm failover matrix (ISSUE 11, tests/test_farm.py).

The dispatcher tier: replica crash mid-prove -> lease takeover with a
byte-identical proof, breaker-open replica receives no work, SDC
re-prove on a DIFFERENT replica (cross-host verification), dispatcher
restart replays leases without double-proving, lease expiry on a
stalled replica, beacon quorum ignores a lone dissenting head, and the
UpdateStore 10k-period RSS bound. Seconds-scale: every replica is an
in-process :class:`LocalReplica` with a canned runner, clocks are
injectable, and fault plans come from spectre_tpu.utils.faults.

Runs in the default tier, via `make test-faults` and `make test-farm`.
"""

import hashlib
import json
import os
import socket
import subprocess
import sys
import threading
import time
import tracemalloc
import urllib.request

import pytest

from spectre_tpu.observability import manifest as obs_manifest
from spectre_tpu.prover_service.dispatcher import (Dispatcher, HttpReplica,
                                                   LocalReplica,
                                                   NoReplicaAvailable)
from spectre_tpu.prover_service.jobs import JobQueue, witness_digest
from spectre_tpu.utils import faults
from spectre_tpu.utils.breaker import BreakerOpen, CircuitBreaker
from spectre_tpu.utils.health import HEALTH, ServiceHealth

METHOD = "genEvmProof_SyncStepCompressed"
PROOF = bytes(range(64))


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.clear()
    yield
    faults.clear()


def _result(proof: bytes = PROOF) -> dict:
    return {"proof": "0x" + proof.hex(), "instances": ["0x7", "0x9"]}


def _digest_of(result: dict) -> str:
    return hashlib.sha256(json.dumps(result, sort_keys=True,
                                     separators=(",", ":")).encode()
                          ).hexdigest()


def _mk_runner(calls: list, proof: bytes = PROOF, mangle_site=None):
    """Canned queue-runner: records calls, returns a deterministic
    result (optionally passing the proof bytes through a mangle site —
    the SDC stand-in)."""
    def runner(method, params, heartbeat=None):
        calls.append(method)
        p = faults.mangle(mangle_site, proof) if mangle_site else proof
        return _result(p)
    return runner


def _ranked_ids(ids, method=METHOD, params=None):
    """Replica ids in the dispatcher's rendezvous order for a digest —
    so tests can pin WHICH replica is tried first."""
    digest = witness_digest(method, params if params is not None else {})
    return sorted(ids, key=lambda rid: hashlib.sha256(
        f"{digest}|{rid}".encode()).hexdigest())


class _VerifyState:
    """Cross-host verifier: accepts exactly the canned PROOF bytes."""

    def __init__(self, proof: bytes = PROOF):
        self._proof = proof
        self.calls = 0

    def verify_proof(self, kind, proof, instances):
        self.calls += 1
        return proof == self._proof


# -- circuit breaker unit (shared beacon/dispatcher machinery) --------------


class TestCircuitBreaker:
    def test_full_state_machine_with_fake_clock(self):
        clk = [0.0]
        h = ServiceHealth()
        br = CircuitBreaker(threshold=2, cooldown=10.0, health=h,
                            counter_prefix="t", clock=lambda: clk[0])
        assert br.state == "closed"
        br.admit()
        br.record(False)
        assert br.state == "closed"
        br.record(False)                      # threshold -> OPEN + trip
        assert br.state == "open"
        assert h.get("t_trips") == 1
        with pytest.raises(BreakerOpen):
            br.admit()                        # fails fast while open
        assert 0.0 < br.remaining() <= 10.0
        clk[0] = 10.0                         # cooldown over -> half-open
        assert br.state == "half-open"
        br.admit()                            # the one trial admission
        assert h.get("t_half_open") == 1
        br.record(False)                      # failed trial -> re-open
        assert br.state == "open"
        assert h.get("t_trips") == 2
        clk[0] = 20.0
        br.admit()
        br.record(True)                       # successful trial -> closed
        assert br.state == "closed"
        assert br.consecutive_failures == 0
        assert br.snapshot() == {"state": "closed", "state_code": 0,
                                 "consecutive_failures": 0}


# -- routing ----------------------------------------------------------------


class TestRouting:
    def test_same_witness_prefers_same_replica(self, tmp_path):
        calls = {"a": [], "b": [], "c": []}
        d = Dispatcher([LocalReplica(r, runner=_mk_runner(calls[r]))
                        for r in calls], poll_s=0.005)
        for _ in range(3):
            assert d.dispatch(METHOD, {"w": 1}) == _result()
        first = _ranked_ids(list(calls), params={"w": 1})[0]
        assert len(calls[first]) == 3
        assert all(not calls[r] for r in calls if r != first)

    def test_breaker_open_replica_gets_no_work(self, tmp_path):
        calls = {"a": [], "b": []}
        d = Dispatcher([LocalReplica(r, runner=_mk_runner(calls[r]))
                        for r in calls], poll_s=0.005, breaker_threshold=2,
                       breaker_cooldown=60.0)
        first, second = _ranked_ids(list(calls))
        for _ in range(2):                    # trip the preferred replica
            d.breaker(first).record(False)
        assert d.breaker(first).state == "open"
        skips0 = HEALTH.get("dispatcher_breaker_skips")
        assert d.dispatch(METHOD, {}) == _result()
        assert calls[first] == []             # open breaker: skipped
        assert len(calls[second]) == 1
        assert HEALTH.get("dispatcher_breaker_skips") == skips0 + 1

    def test_failing_health_probe_skips_not_crashes(self, monkeypatch):
        calls = {"a": [], "b": []}
        d = Dispatcher([LocalReplica(r, runner=_mk_runner(calls[r]))
                        for r in calls], poll_s=0.005)
        first, second = _ranked_ids(list(calls))
        un0 = HEALTH.get("dispatcher_replica_unhealthy")
        # the probe fault fires once: the FIRST-ranked replica's probe
        # blows up, it is skipped (not crashed), work lands on the other
        monkeypatch.setenv("SPECTRE_FAULT_PLAN", "replica.health:raise:1")
        assert d.dispatch(METHOD, {}) == _result()
        assert calls[first] == [] and len(calls[second]) == 1
        assert HEALTH.get("dispatcher_replica_unhealthy") == un0 + 1
        snap = {r["replica_id"]: r for r in d.snapshot()["replicas"]}
        assert snap[first]["healthy"] is False
        assert snap[second]["healthy"] is True

    def test_no_replica_available(self):
        d = Dispatcher([], poll_s=0.005)
        n0 = HEALTH.get("dispatcher_no_replica")
        with pytest.raises(NoReplicaAvailable):
            d.dispatch(METHOD, {})
        assert HEALTH.get("dispatcher_no_replica") == n0 + 1

    def test_capability_routing(self):
        calls = {"step-only": [], "full": []}
        d = Dispatcher([
            LocalReplica("step-only", runner=_mk_runner(calls["step-only"]),
                         capabilities={METHOD}),
            LocalReplica("full", runner=_mk_runner(calls["full"]))],
            poll_s=0.005)
        d.dispatch("genEvmProof_CommitteeUpdateCompressed", {})
        assert calls["step-only"] == []       # can't serve committee
        assert len(calls["full"]) == 1

    def test_duplicate_replica_id_rejected(self):
        d = Dispatcher([LocalReplica("a", runner=_mk_runner([]))])
        with pytest.raises(ValueError, match="duplicate replica id"):
            d.register(LocalReplica("a", runner=_mk_runner([])))

    def test_deterministic_prover_error_not_failed_over(self):
        """Witness rejection is the JOB's fault, not the replica's: it
        re-raises unchanged instead of burning the other replicas."""
        calls_b = []

        def bad_witness(method, params, heartbeat=None):
            raise AssertionError("finality branch mismatch")

        ids = _ranked_ids(["a", "b"])
        runners = {ids[0]: bad_witness, ids[1]: _mk_runner(calls_b)}
        d = Dispatcher([LocalReplica(r, runner=runners[r]) for r in ids],
                       poll_s=0.005)
        with pytest.raises(AssertionError, match="finality branch"):
            d.dispatch(METHOD, {})
        assert calls_b == []                  # no failover for bad input


# -- the acceptance drill: crash mid-prove -> lease takeover ----------------


class TestFailoverDrill:
    def test_replica_crash_byte_identical_takeover(self, tmp_path,
                                                   monkeypatch):
        """ISSUE-11 acceptance: SPECTRE_FAULT_PLAN=replica.dispatch:crash:1
        against 3 in-process replicas — the job completes on a surviving
        replica, the result digest is byte-identical to a clean
        single-replica prove, dispatcher_lease_takeovers ticks once."""
        # clean single-replica reference prove first (no faults armed)
        ref = Dispatcher([LocalReplica("solo", runner=_mk_runner([]))],
                         poll_s=0.005)
        ref_digest = _digest_of(ref.dispatch(METHOD, {"w": "drill"}))

        calls = {"r1": [], "r2": [], "r3": []}
        d = Dispatcher([LocalReplica(r, runner=_mk_runner(calls[r]))
                        for r in calls],
                       journal_dir=str(tmp_path), lease_s=30.0, poll_s=0.005)
        take0 = HEALTH.get("dispatcher_lease_takeovers")
        fail0 = HEALTH.get("dispatcher_replica_failures")
        monkeypatch.setenv("SPECTRE_FAULT_PLAN", "replica.dispatch:crash:1")
        result = d.dispatch(METHOD, {"w": "drill"})
        assert _digest_of(result) == ref_digest     # byte-identical
        assert faults.fired_count("replica.dispatch") == 1
        assert HEALTH.get("dispatcher_lease_takeovers") == take0 + 1
        assert HEALTH.get("dispatcher_replica_failures") == fail0 + 1
        # the crash killed the first-ranked replica BEFORE its runner ran;
        # exactly one surviving replica proved
        first, second, _ = _ranked_ids(list(calls), params={"w": "drill"})
        assert calls[first] == []
        assert len(calls[second]) == 1
        assert sum(len(c) for c in calls.values()) == 1
        # the lease journal tells the story: crashed grant, takeover
        # grant, done release
        recs = [json.loads(line) for line in
                (tmp_path / "dispatcher.leases.jsonl").read_text()
                .splitlines()]
        events = [(r["event"], r.get("outcome")) for r in recs]
        assert events == [("lease", None), ("release", "crashed"),
                          ("lease", None), ("release", "done")]
        assert recs[0]["replica"] == first
        assert recs[2]["replica"] == second and recs[2]["takeover"] is True

    def test_manifest_records_both_replicas(self, monkeypatch):
        calls = {"a": [], "b": []}
        d = Dispatcher([LocalReplica(r, runner=_mk_runner(calls[r]))
                        for r in calls], poll_s=0.005)
        monkeypatch.setenv("SPECTRE_FAULT_PLAN", "replica.dispatch:crash:1")
        with obs_manifest.collect_events() as events:
            d.dispatch(METHOD, {})
        leases = [e for e in events if e["kind"] == "replica_lease"]
        assert [e["takeover"] for e in leases] == [False, True]
        assert leases[0]["replica"] != leases[1]["replica"]

    def test_lease_journal_ioerror_tolerated(self, tmp_path, monkeypatch):
        """`replica.lease:ioerror` (disk trouble on the lease journal)
        must not fail the prove — counted, farm keeps going."""
        d = Dispatcher([LocalReplica("a", runner=_mk_runner([]))],
                       journal_dir=str(tmp_path), poll_s=0.005)
        j0 = HEALTH.get("dispatcher_lease_journal_failures")
        monkeypatch.setenv("SPECTRE_FAULT_PLAN", "replica.lease:ioerror:1")
        assert d.dispatch(METHOD, {}) == _result()
        assert HEALTH.get("dispatcher_lease_journal_failures") == j0 + 1


# -- lease expiry on a stalled (not crashed) replica ------------------------


class TestLeaseExpiry:
    def test_stalled_replica_lease_expires_and_job_moves(self):
        clk = [0.0]
        release = threading.Event()
        ids = _ranked_ids(["stall", "live"])
        calls_live = []

        def stalling(method, params, heartbeat=None):
            clk[0] += 1000.0          # way past the lease, never renewing
            release.wait(10.0)        # disowned thread parks here

        runners = {"stall": stalling, "live": _mk_runner(calls_live)}
        # make the STALLED replica the rendezvous favourite
        d = Dispatcher([LocalReplica(ids[0], runner=runners["stall"]),
                        LocalReplica(ids[1], runner=runners["live"])],
                       lease_s=60.0, poll_s=0.005, clock=lambda: clk[0])
        exp0 = HEALTH.get("dispatcher_lease_expired")
        take0 = HEALTH.get("dispatcher_lease_takeovers")
        try:
            assert d.dispatch(METHOD, {}) == _result()
        finally:
            release.set()
        assert HEALTH.get("dispatcher_lease_expired") == exp0 + 1
        assert HEALTH.get("dispatcher_lease_takeovers") == take0 + 1
        assert len(calls_live) == 1

    def test_heartbeat_renews_lease(self):
        """A slow-but-renewing replica keeps its lease: the runner's
        heartbeat resets expiry, so a prove longer than lease_s still
        completes on the SAME replica."""
        clk = [0.0]
        calls = []

        def slow(method, params, heartbeat=None):
            for _ in range(5):
                clk[0] += 40.0        # 200s of "work" under a 60s lease
                heartbeat()
            calls.append(method)
            return _result()

        d = Dispatcher([LocalReplica("slow", runner=slow)],
                       lease_s=60.0, poll_s=0.005, clock=lambda: clk[0])
        exp0 = HEALTH.get("dispatcher_lease_expired")
        assert d.dispatch(METHOD, {}) == _result()
        assert len(calls) == 1
        assert HEALTH.get("dispatcher_lease_expired") == exp0


# -- SDC: cross-host verification reroutes to a different replica -----------


class TestSdcReroute:
    def _farm(self, tmp_path=None, verify=None):
        ids = _ranked_ids(["a", "b"])
        calls = {rid: [] for rid in ids}
        # the rendezvous favourite passes its proof through the SDC
        # mangle site; the other returns clean bytes
        reps = [LocalReplica(ids[0], runner=_mk_runner(
                    calls[ids[0]], mangle_site="proof.bytes")),
                LocalReplica(ids[1], runner=_mk_runner(calls[ids[1]]))]
        d = Dispatcher(reps, poll_s=0.005,
                       journal_dir=str(tmp_path) if tmp_path else None,
                       verify_state=verify or _VerifyState())
        return d, ids, calls

    def test_sdc_reproved_on_different_replica(self, tmp_path, monkeypatch):
        # an earlier bench run may have left SPECTRE_SELF_VERIFY=off in
        # the process env; cross-verification honors the same policy knob
        monkeypatch.setenv("SPECTRE_SELF_VERIFY", "always")
        d, ids, calls = self._farm(tmp_path)
        sdc0 = HEALTH.get("dispatcher_sdc_rerouted")
        xf0 = HEALTH.get("proofs_cross_verify_failed")
        xok0 = HEALTH.get("proofs_cross_verified")
        monkeypatch.setenv("SPECTRE_FAULT_PLAN", "proof.bytes:corrupt:1")
        with obs_manifest.collect_events() as events:
            result = d.dispatch(METHOD, {})
        assert result == _result()            # the CLEAN bytes are served
        assert len(calls[ids[0]]) == 1 and len(calls[ids[1]]) == 1
        assert HEALTH.get("dispatcher_sdc_rerouted") == sdc0 + 1
        assert HEALTH.get("proofs_cross_verify_failed") == xf0 + 1
        assert HEALTH.get("proofs_cross_verified") == xok0 + 1
        # manifest pins BOTH hosts: the corrupting one and the fixer
        reroute = [e for e in events if e["kind"] == "sdc_reroute"]
        assert reroute == [{"kind": "sdc_reroute",
                            "from_replica": ids[0], "to_replica": ids[1]}]
        leases = [e["replica"] for e in events
                  if e["kind"] == "replica_lease"]
        assert leases == [ids[0], ids[1]]

    def test_double_sdc_fails_job(self, monkeypatch):
        from spectre_tpu.prover_service.selfverify import ProofVerifyFailed
        monkeypatch.setenv("SPECTRE_SELF_VERIFY", "always")
        ids = _ranked_ids(["a", "b"])
        calls = {rid: [] for rid in ids}
        d = Dispatcher([LocalReplica(r, runner=_mk_runner(
                            calls[r], mangle_site="proof.bytes"))
                        for r in ids],
                       poll_s=0.005, verify_state=_VerifyState())
        monkeypatch.setenv("SPECTRE_FAULT_PLAN", "proof.bytes:corrupt:2")
        with pytest.raises(ProofVerifyFailed):
            d.dispatch(METHOD, {})
        # both replicas produced unverifiable bytes -> terminal, same
        # error class as the single-host verify-before-serve path
        assert len(calls[ids[0]]) == 1 and len(calls[ids[1]]) == 1

    def test_sdc_bytes_quarantined(self, tmp_path, monkeypatch):
        from spectre_tpu.utils.artifacts import ArtifactStore
        monkeypatch.setenv("SPECTRE_SELF_VERIFY", "always")
        d, ids, calls = self._farm()
        store = ArtifactStore(str(tmp_path))

        class _Q:                              # queue façade: just a store
            pass

        q = _Q()
        q.store = store
        d.attach_queue(q)
        monkeypatch.setenv("SPECTRE_FAULT_PLAN", "proof.bytes:corrupt:1")
        d.dispatch(METHOD, {})
        quarantined = os.listdir(store.quarantine_dir)
        assert len(quarantined) == 1
        assert quarantined[0].endswith(".proof")
        with open(os.path.join(store.quarantine_dir, quarantined[0]),
                  "rb") as f:
            bad = f.read()
        assert bad != PROOF                    # the CORRUPT bytes, parked


# -- restart: lease journal replay ------------------------------------------


class TestLeaseReplay:
    def test_restart_replays_open_lease_and_reroutes(self, tmp_path,
                                                     monkeypatch):
        """Dispatcher dies right after journaling a lease grant (the
        post-append crash window): the restarted dispatcher must not
        re-trust the replica that died holding the lease, and the
        queue's dedup must not double-prove."""
        qdir, ddir = str(tmp_path / "q"), str(tmp_path / "d")
        ids = _ranked_ids(["a", "b"], params={"w": 1})
        calls1 = {rid: [] for rid in ids}
        d1 = Dispatcher([LocalReplica(r, runner=_mk_runner(calls1[r]))
                         for r in ids], journal_dir=ddir, poll_s=0.005)
        q1 = JobQueue(d1, concurrency=1, journal_dir=qdir)
        monkeypatch.setenv("SPECTRE_FAULT_PLAN", "replica.lease:crash:1")
        # the InjectedCrash kills the worker thread like a dead process;
        # silence the default excepthook traceback spam
        old_hook = threading.excepthook
        threading.excepthook = lambda args: None
        try:
            jid = q1.submit(METHOD, {"w": 1})
            deadline = time.time() + 10
            while faults.fired_count("replica.lease") < 1:
                assert time.time() < deadline, "lease crash never fired"
                time.sleep(0.01)
            deadline = time.time() + 10
            while any(w.is_alive() for w in q1._workers):
                assert time.time() < deadline, "worker did not die"
                time.sleep(0.01)
        finally:
            threading.excepthook = old_hook
        assert q1.status(jid)["status"] == "running"   # crashed mid-job
        assert not calls1[ids[0]] and not calls1[ids[1]]
        q1.stop()

        monkeypatch.delenv("SPECTRE_FAULT_PLAN")
        faults.clear()                        # disarm for the restart
        rep0 = HEALTH.get("dispatcher_leases_replayed")
        take0 = HEALTH.get("dispatcher_lease_takeovers")
        calls2 = {rid: [] for rid in ids}
        d2 = Dispatcher([LocalReplica(r, runner=_mk_runner(calls2[r]))
                         for r in ids], journal_dir=ddir, poll_s=0.005)
        assert HEALTH.get("dispatcher_leases_replayed") == rep0 + 1
        q2 = JobQueue(d2, concurrency=1, journal_dir=qdir)
        try:
            job = q2.wait(jid, timeout=10)    # recovery requeued it
            assert job.status == "done"
            assert job.result == _result()
            # the dead-lease replica is excluded: the OTHER one proved
            assert calls2[ids[0]] == []
            assert len(calls2[ids[1]]) == 1
            assert HEALTH.get("dispatcher_lease_takeovers") == take0 + 1
            # resubmitting the same witness is a dedup cache hit
            assert q2.submit(METHOD, {"w": 1}) == jid
            assert sum(len(c) for c in calls2.values()) == 1
        finally:
            q2.stop()

    def test_replay_skips_torn_tail_and_done_leases(self, tmp_path):
        ddir = str(tmp_path)
        d1 = Dispatcher([LocalReplica("a", runner=_mk_runner([]))],
                        journal_dir=ddir, poll_s=0.005)
        d1.dispatch(METHOD, {"w": 1})         # grant + done release
        path = os.path.join(ddir, "dispatcher.leases.jsonl")
        with open(path, "a") as f:
            f.write('{"event": "lease", "digest": "tor')   # torn append
        rep0 = HEALTH.get("dispatcher_leases_replayed")
        d2 = Dispatcher([LocalReplica("a", runner=_mk_runner([]))],
                        journal_dir=ddir, poll_s=0.005)
        # the done lease is NOT an exclusion and the torn line is skipped
        assert HEALTH.get("dispatcher_leases_replayed") == rep0
        assert d2.dispatch(METHOD, {"w": 1}) == _result()


# -- lease-journal startup compaction (ISSUE 14 satellite) ------------------


_LEASE_HISTORY = [
    {"event": "lease", "digest": "d1", "replica": "a"},
    {"event": "release", "digest": "d1", "replica": "a",
     "outcome": "done"},
    {"event": "lease", "digest": "d2", "replica": "a"},
    {"event": "release", "digest": "d2", "replica": "a",
     "outcome": "failed"},
    {"event": "lease", "digest": "d3", "replica": "b"},   # still open
]


def _write_lease_journal(ddir, records=_LEASE_HISTORY) -> str:
    path = os.path.join(ddir, "dispatcher.leases.jsonl")
    with open(path, "w") as f:
        for rec in records:
            f.write(json.dumps(rec, sort_keys=True) + "\n")
    return path


class TestLeaseCompaction:
    def test_startup_compaction_is_a_replay_fixpoint(self, tmp_path):
        """Restart compacts the grant/release history down to open
        leases + exclusions; replaying the compacted file reconstructs
        the SAME state, and a further restart has nothing left to drop."""
        ddir = str(tmp_path)
        path = _write_lease_journal(ddir)
        c0 = HEALTH.get("dispatcher_lease_compactions")
        d1 = Dispatcher([LocalReplica(r, runner=_mk_runner([]))
                         for r in ("a", "b")], journal_dir=ddir,
                        poll_s=0.005)
        assert HEALTH.get("dispatcher_lease_compactions") == c0 + 1
        assert d1._excluded == {"d2": {"a"}, "d3": {"b"}}
        assert d1._takeover_due == {"d3"}
        lines = [json.loads(ln) for ln in
                 open(path).read().splitlines() if ln.strip()]
        # the done pair and the open lease's separate grant are gone
        assert len(lines) == 2
        assert {(r["event"], r["digest"]) for r in lines} == \
            {("release", "d2"), ("lease", "d3")}
        # replaying the compacted journal reconstructs identical state
        # and, being the fixpoint, does NOT compact again
        d2 = Dispatcher([LocalReplica(r, runner=_mk_runner([]))
                         for r in ("a", "b")], journal_dir=ddir,
                        poll_s=0.005)
        assert HEALTH.get("dispatcher_lease_compactions") == c0 + 1
        assert d2._excluded == d1._excluded
        assert d2._takeover_due == d1._takeover_due

    def test_crash_mid_compact_leaves_original_journal(self, tmp_path,
                                                       monkeypatch):
        """`replica.lease_compact:crash` fires in the staged-but-not-
        swapped window: the original journal survives byte-for-byte, and
        the next startup re-compacts to the same state."""
        ddir = str(tmp_path)
        path = _write_lease_journal(ddir)
        before = open(path, "rb").read()
        monkeypatch.setenv("SPECTRE_FAULT_PLAN",
                           "replica.lease_compact:crash:1")
        with pytest.raises(faults.InjectedCrash):
            Dispatcher([LocalReplica("a", runner=_mk_runner([]))],
                       journal_dir=ddir, poll_s=0.005)
        assert open(path, "rb").read() == before
        monkeypatch.delenv("SPECTRE_FAULT_PLAN")
        faults.clear()
        d = Dispatcher([LocalReplica(r, runner=_mk_runner([]))
                        for r in ("a", "b")], journal_dir=ddir,
                       poll_s=0.005)
        assert d._excluded == {"d2": {"a"}, "d3": {"b"}}
        assert d._takeover_due == {"d3"}
        lines = [ln for ln in open(path).read().splitlines() if ln.strip()]
        assert len(lines) == 2

    def test_compact_ioerror_tolerated_keeps_history(self, tmp_path,
                                                     monkeypatch):
        """Disk trouble during compaction degrades to keeping the full
        history (counted), never to losing lease state."""
        ddir = str(tmp_path)
        path = _write_lease_journal(ddir)
        before = open(path, "rb").read()
        f0 = HEALTH.get("dispatcher_lease_compact_failures")
        monkeypatch.setenv("SPECTRE_FAULT_PLAN",
                           "replica.lease_compact:ioerror:1")
        d = Dispatcher([LocalReplica(r, runner=_mk_runner([]))
                        for r in ("a", "b")], journal_dir=ddir,
                       poll_s=0.005)
        assert HEALTH.get("dispatcher_lease_compact_failures") == f0 + 1
        assert open(path, "rb").read() == before
        assert d._excluded == {"d2": {"a"}, "d3": {"b"}}


# -- multi-beacon quorum ----------------------------------------------------


class _StubBeacon:
    def __init__(self, head_root, breaker_state="closed", error=None):
        self._head = head_root
        self.breaker_state = breaker_state
        self._error = error
        self.demoted = 0
        self.polls = 0

    def finality_update(self):
        self.polls += 1
        if self._error is not None:
            raise self._error
        return {"finalized_header": {"slot": 64, "root": self._head},
                "signature_slot": 66}

    def demote(self):
        self.demoted += 1


class TestBeaconQuorum:
    def _quorum(self, *clients, quorum=2):
        from spectre_tpu.preprocessor.beacon import BeaconQuorum
        return BeaconQuorum(list(clients), quorum=quorum)

    def test_dissenting_beacon_ignored_and_demoted(self):
        """ISSUE-11 acceptance: 2-of-3 agree on the finalized head; the
        lone divergent beacon is outvoted and demoted."""
        a, b = _StubBeacon("0xaa"), _StubBeacon("0xaa")
        liar = _StubBeacon("0xff")
        dis0 = HEALTH.get("beacon_quorum_dissent")
        upd = self._quorum(a, b, liar).finality_update()
        assert upd["finalized_header"]["root"] == "0xaa"
        assert liar.demoted == 1 and a.demoted == 0 and b.demoted == 0
        assert HEALTH.get("beacon_quorum_dissent") == dis0 + 1

    def test_no_quorum_raises(self):
        from spectre_tpu.preprocessor.beacon import QuorumNotReached
        f0 = HEALTH.get("beacon_quorum_failures")
        q = self._quorum(_StubBeacon("0xaa"), _StubBeacon("0xbb"),
                         _StubBeacon("0xcc"))
        with pytest.raises(QuorumNotReached, match="split"):
            q.finality_update()
        assert HEALTH.get("beacon_quorum_failures") == f0 + 1

    def test_erroring_beacon_tolerated(self):
        e0 = HEALTH.get("beacon_quorum_errors")
        upd = self._quorum(_StubBeacon("0xaa"), _StubBeacon("0xaa"),
                           _StubBeacon(None, error=TimeoutError("down"))
                           ).finality_update()
        assert upd["finalized_header"]["root"] == "0xaa"
        assert HEALTH.get("beacon_quorum_errors") == e0 + 1

    def test_breaker_open_beacon_skipped(self):
        parked = _StubBeacon("0xff", breaker_state="open")
        upd = self._quorum(_StubBeacon("0xaa"), _StubBeacon("0xaa"),
                           parked).finality_update()
        assert upd["finalized_header"]["root"] == "0xaa"
        assert parked.polls == 0              # never even polled

    def test_quorum_clamped_to_pool_size(self):
        q = self._quorum(_StubBeacon("0xaa"), quorum=5)
        assert q.quorum == 1
        assert q.finality_update()["finalized_header"]["root"] == "0xaa"

    def test_needs_clients(self):
        from spectre_tpu.preprocessor.beacon import BeaconQuorum
        with pytest.raises(ValueError):
            BeaconQuorum([])

    def test_persistent_dissenter_trips_own_breaker(self):
        """demote() rides the real breaker: a beacon outvoted
        `threshold` times in a row drops out of the pool entirely."""
        from spectre_tpu.preprocessor.beacon import BeaconClient
        bc = BeaconClient("http://127.0.0.1:9", breaker_threshold=2,
                          breaker_cooldown=60.0)
        assert bc.breaker_state == "closed"
        bc.demote()
        bc.demote()
        assert bc.breaker_state == "open"


# -- UpdateStore memory bound (10k-period backfill) -------------------------


class TestUpdateStoreBound:
    def test_10k_period_backfill_fits_lru_budget(self, tmp_path):
        """A mainnet-scale backfill (10k committee periods) must replay
        into a BOUNDED resident set: offsets+digests only, full records
        LRU-capped, cache misses reloaded from the journal offset."""
        from spectre_tpu.follower.updates import (UPDATE_SUFFIX, UpdateStore,
                                                  _canonical)
        from spectre_tpu.utils.artifacts import ArtifactStore

        n, cap, probe = 10_000, 256, 1234
        pos = lambda p: f"0x{p:x}"
        art = ArtifactStore(str(tmp_path))
        lines = []
        for p in range(n):
            result = {"proof": "0x01", "instances": ["0x1"],
                      "committee_poseidon": pos(p)}
            if p in (probe, n - 2, n - 1):
                # only the records the test actually reads back (and the
                # tip, which replay re-verifies) need real artifacts
                digest = art.write(_canonical(result), UPDATE_SUFFIX)
            else:
                digest = f"{p:064x}"
            lines.append(json.dumps(
                {"kind": "committee", "period": p, "digest": digest,
                 "committee_poseidon": pos(p),
                 "prev_poseidon": pos(p - 1) if p else None},
                sort_keys=True, separators=(",", ":")))
        with open(tmp_path / "follower.updates.jsonl", "w") as f:
            f.write("\n".join(lines) + "\n")

        ev0 = HEALTH.get("follower_update_cache_evictions")
        tracemalloc.start()
        try:
            store = UpdateStore(str(tmp_path), cache_periods=cap)
            _, peak = tracemalloc.get_traced_memory()
        finally:
            tracemalloc.stop()
        assert peak < 64 * 2**20              # the fixed RSS budget pin
        assert len(store._committee) == n     # every period indexed...
        assert len(store._committee._lru) <= cap   # ...few resident
        assert HEALTH.get("follower_update_cache_evictions") > ev0
        assert store.tip_period() == n - 1
        assert store.anchor_period() == 0
        # a cold period reloads through its journal offset — record AND
        # artifact round-trip
        rec = store.get_committee(probe)
        assert rec["result"]["committee_poseidon"] == pos(probe)
        assert len(store._committee._lru) <= cap

    def test_journal_name_matches_follower(self, tmp_path):
        from spectre_tpu.follower import updates as U
        assert U.JOURNAL_NAME == "follower.updates.jsonl"


# -- farm-aware RPC plumbing ------------------------------------------------


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


class _ServeState:
    """Bare state for serve(): the dispatcher replaces the runner, so no
    prove methods are ever touched."""
    concurrency = 1


class TestFarmRpc:
    def test_healthz_and_errors_carry_farm_identity(self, tmp_path):
        """The full acceptance surface over HTTP: serve() with a
        dispatcher -> prove lands on a replica, /healthz grows the
        dispatcher section, RPC errors are stamped with the serving
        replica id (RpcError.replica_id)."""
        from spectre_tpu.prover_service.rpc import serve
        from spectre_tpu.prover_service.rpc_client import (ProverClient,
                                                           RpcError)
        calls = []
        d = Dispatcher([LocalReplica("farm-1", runner=_mk_runner(calls))],
                       journal_dir=str(tmp_path), poll_s=0.005)
        server = serve(_ServeState(), port=0, background=True,
                       journal_dir=str(tmp_path), dispatcher=d,
                       replica_id="head-1")
        port = server.server_address[1]
        try:
            client = ProverClient(f"http://127.0.0.1:{port}", timeout=10)
            assert client._call(METHOD, {"w": 1}) == _result()
            assert len(calls) == 1            # the farm proved it
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/healthz", timeout=5) as resp:
                snap = json.load(resp)
            reps = {r["replica_id"]: r
                    for r in snap["dispatcher"]["replicas"]}
            assert reps["farm-1"]["breaker"]["state"] == "closed"
            assert reps["farm-1"]["dispatched"] == 1
            assert snap["counters"]["dispatcher_jobs_dispatched"] >= 1
            with pytest.raises(RpcError) as exc:
                client.proof_status("no-such-job")
            assert exc.value.code == -32004
            assert exc.value.replica_id == "head-1"
            assert "[replica head-1]" in str(exc.value)
        finally:
            server.shutdown()

    def test_conn_reset_retry_rotates_endpoint(self, tmp_path):
        """A client with several farm frontends retries a connection
        reset against a DIFFERENT endpoint."""
        from spectre_tpu.prover_service.rpc import serve
        from spectre_tpu.prover_service.rpc_client import ProverClient
        dead = f"http://127.0.0.1:{_free_port()}"
        server = serve(_ServeState(), port=0, background=True,
                       journal_dir=str(tmp_path))
        live = f"http://127.0.0.1:{server.server_address[1]}"
        try:
            client = ProverClient([dead, live], timeout=10, conn_retries=1,
                                  sleep=lambda s: None)
            assert client.ping() == "pong"    # refused -> rotate -> live
            assert client.url == live
        finally:
            server.shutdown()

    def test_single_url_client_unchanged(self):
        from spectre_tpu.prover_service.rpc_client import ProverClient
        c = ProverClient("http://127.0.0.1:1")
        assert c.urls == ["http://127.0.0.1:1"]
        assert c.url == "http://127.0.0.1:1"
        with pytest.raises(ValueError):
            ProverClient([])


# -- dynamic membership (ISSUE 18) ------------------------------------------


AGG_METHOD = "genEvmProof_AggregationCadence"


class TestMembership:
    def test_register_heartbeat_ttl_lifecycle(self):
        """registerReplica joins the fleet with a capability record;
        re-announces are heartbeats; a member silent past ttl_s is
        demoted through its breaker and deregistered; a re-join keeps
        the open breaker (readmission via the half-open trial)."""
        clk = [0.0]
        d = Dispatcher([], ttl_s=30.0, clock=lambda: clk[0], poll_s=0.005)
        hb0 = HEALTH.get("dispatcher_heartbeats")
        ttl0 = HEALTH.get("dispatcher_member_ttl_expired")
        res = d.register_remote("dyn-1", url="http://127.0.0.1:1",
                                capabilities={"device": "cpu",
                                              "memory_mb": 1024,
                                              "max_k": 17})
        assert res == {"replica_id": "dyn-1", "ttl_s": 30.0, "members": 1}
        row = d.snapshot()["replicas"][0]
        assert row["dynamic"] is True
        assert row["capabilities"]["device"] == "cpu"
        assert row["capabilities"]["max_k"] == 17
        assert row["url"] == "http://127.0.0.1:1"
        assert row["last_heartbeat_age_s"] == 0.0
        clk[0] = 20.0                         # heartbeat refreshes TTL
        d.register_remote("dyn-1", url="http://127.0.0.1:1")
        assert HEALTH.get("dispatcher_heartbeats") == hb0 + 1
        clk[0] = 45.0                         # 25 s since announce: alive
        assert d.sweep_members() == []
        clk[0] = 51.0                         # 31 s: past the TTL
        assert d.sweep_members() == ["dyn-1"]
        assert d.snapshot()["members"] == 0
        assert HEALTH.get("dispatcher_member_ttl_expired") == ttl0 + 1
        assert d.breaker("dyn-1").state == "open"   # demoted, not dropped
        # re-join: membership is back, the breaker history is NOT reset
        d.register_remote("dyn-1", url="http://127.0.0.1:1")
        snap = d.snapshot()
        assert snap["members"] == 1 and snap["dynamic_members"] == 1
        assert d.breaker("dyn-1").state == "open"

    def test_member_journal_replay_and_compaction(self, tmp_path):
        """A dispatcher restart reconstructs the fleet from
        dispatcher.members.jsonl (last join/leave per id wins) and
        compacts it to the replay fixpoint."""
        d1 = Dispatcher([], journal_dir=str(tmp_path), ttl_s=30.0,
                        poll_s=0.005)
        d1.register_remote("m1", url="http://127.0.0.1:9001",
                           capabilities={"max_k": 18,
                                         "mesh_shape": [2, 4]})
        d1.register_remote("m2", url="http://127.0.0.1:9002")
        d1.deregister("m2", reason="drain")
        rep0 = HEALTH.get("dispatcher_members_replayed")
        d2 = Dispatcher([], journal_dir=str(tmp_path), ttl_s=30.0,
                        poll_s=0.005)
        snap = d2.snapshot()
        assert [r["replica_id"] for r in snap["replicas"]] == ["m1"]
        assert snap["replicas"][0]["dynamic"] is True
        assert snap["replicas"][0]["capabilities"]["max_k"] == 18
        assert snap["replicas"][0]["capabilities"]["mesh_shape"] == [2, 4]
        assert HEALTH.get("dispatcher_members_replayed") == rep0 + 1
        lines = [ln for ln in
                 (tmp_path / "dispatcher.members.jsonl").read_text()
                 .splitlines() if ln.strip()]
        assert len(lines) == 1                # compacted to one join
        assert json.loads(lines[0])["replica"] == "m1"

    def test_static_id_never_shadowed_by_journal(self, tmp_path):
        """A statically-registered replica keeps its in-process identity
        even when the member journal remembers a same-named announce."""
        d1 = Dispatcher([], journal_dir=str(tmp_path), poll_s=0.005)
        d1.register_remote("a", url="http://127.0.0.1:9009")
        calls = []
        d2 = Dispatcher([LocalReplica("a", runner=_mk_runner(calls))],
                        journal_dir=str(tmp_path), poll_s=0.005)
        assert d2.dispatch(METHOD, {}) == _result()
        assert len(calls) == 1                # the LOCAL replica proved

    def test_register_fault_site_leaves_fleet_unchanged(self):
        faults.arm("replica.register", "raise", 1)
        d = Dispatcher([], poll_s=0.005)
        with pytest.raises(faults.InjectedFault):
            d.register_remote("x", url="http://127.0.0.1:1")
        assert d.snapshot()["members"] == 0
        d.register_remote("x", url="http://127.0.0.1:1")  # next announce
        assert d.snapshot()["members"] == 1

    def test_register_without_url_rejected(self):
        d = Dispatcher([], poll_s=0.005)
        with pytest.raises(ValueError, match="needs a url"):
            d.register_remote("nourl")

    def test_announce_loop_joins_fleet_over_http(self, tmp_path):
        """Full announce wiring: serve(announce=...) spawns the
        heartbeat loop, the dispatcher head admits the replica with its
        capability record, /healthz lists capability + heartbeat age,
        and /metrics grows the membership gauges."""
        from spectre_tpu.observability.prom import render
        from spectre_tpu.prover_service.rpc import serve
        d = Dispatcher([], journal_dir=str(tmp_path), ttl_s=60.0,
                       poll_s=0.005)
        port = _free_port()
        # the head announces itself to itself: one process exercises
        # both sides of the registerReplica loop
        server = serve(_ServeState(), host="127.0.0.1", port=port,
                       background=True, journal_dir=str(tmp_path),
                       dispatcher=d, replica_id="self-1",
                       announce=f"http://127.0.0.1:{port}",
                       announce_interval=0.05)
        try:
            deadline = time.time() + 10
            while time.time() < deadline and d.snapshot()["members"] == 0:
                time.sleep(0.02)
            snap = d.snapshot()
            assert snap["members"] == 1 and snap["dynamic_members"] == 1
            row = snap["replicas"][0]
            assert row["replica_id"] == "self-1"
            assert row["url"] == f"http://127.0.0.1:{port}"
            assert row["capabilities"]["memory_mb"]   # sysconf-derived
            assert row["last_heartbeat_age_s"] is not None
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/healthz", timeout=5) as resp:
                hz = json.load(resp)
            reps = {x["replica_id"]: x
                    for x in hz["dispatcher"]["replicas"]}
            assert reps["self-1"]["capabilities"]["url"] == \
                f"http://127.0.0.1:{port}"
            assert reps["self-1"]["last_heartbeat_age_s"] is not None
            metrics = render()
            # the membership gauges are a union over every live
            # Dispatcher (weakset registry), so other tests' uncollected
            # dispatchers may inflate the counts — pin OUR replica's
            # sample and a lower bound, not the global total
            assert 'spectre_replica_heartbeat_age_s{replica="self-1"}' \
                in metrics
            dyn = [ln for ln in metrics.splitlines()
                   if ln.startswith('spectre_dispatcher_members'
                                    '{kind="dynamic"}')]
            assert dyn and int(float(dyn[0].split()[-1])) >= 1
        finally:
            server._announce_stop.set()
            server.shutdown()

    def test_announce_failure_tolerated_and_retried(self, tmp_path):
        """An injected announce failure is counted and absorbed — the
        replica keeps serving and the NEXT heartbeat joins it."""
        from spectre_tpu.prover_service.rpc import serve
        faults.arm("replica.announce", "raise", 1)
        d = Dispatcher([], journal_dir=str(tmp_path), ttl_s=60.0,
                       poll_s=0.005)
        port = _free_port()
        af0 = HEALTH.get("replica_announce_failures")
        server = serve(_ServeState(), host="127.0.0.1", port=port,
                       background=True, journal_dir=str(tmp_path),
                       dispatcher=d, replica_id="flaky-1",
                       announce=f"http://127.0.0.1:{port}",
                       announce_interval=0.05)
        try:
            deadline = time.time() + 10
            while time.time() < deadline and d.snapshot()["members"] == 0:
                time.sleep(0.02)
            assert d.snapshot()["members"] == 1
            assert HEALTH.get("replica_announce_failures") == af0 + 1
        finally:
            server._announce_stop.set()
            server.shutdown()


# -- capability-aware placement (ISSUE 18) ----------------------------------


class TestPlacement:
    def test_aggregation_routes_to_mesh_or_big_memory(self):
        """Aggregation proves land only on replicas advertising a mesh
        or the largest declared memory — zero fallbacks while one is
        healthy."""
        calls = {r: [] for r in ("plain", "meshy", "big")}
        caps = {"plain": {"memory_mb": 8192},
                "meshy": {"mesh_shape": [2, 4], "memory_mb": 4096},
                "big": {"memory_mb": 65536}}
        d = Dispatcher([LocalReplica(r, runner=_mk_runner(calls[r]),
                                     capabilities=caps[r])
                        for r in calls], poll_s=0.005)
        fb0 = HEALTH.get("dispatcher_placement_fallbacks")
        for i in range(8):
            assert d.dispatch(AGG_METHOD, {"w": i}) == _result()
        assert calls["plain"] == []
        assert len(calls["meshy"]) + len(calls["big"]) == 8
        assert HEALTH.get("dispatcher_placement_fallbacks") == fb0

    def test_max_k_placement(self):
        """k-sized work skips replicas DECLARING a too-small max_k even
        when rendezvous ranks them first."""
        calls = {"tiny": [], "big": []}
        d = Dispatcher([
            LocalReplica("tiny", runner=_mk_runner(calls["tiny"]),
                         capabilities={"max_k": 14}),
            LocalReplica("big", runner=_mk_runner(calls["big"]),
                         capabilities={"max_k": 22})],
            poll_s=0.005, method_k={METHOD: 20})
        params = next({"w": i} for i in range(64)
                      if _ranked_ids(["tiny", "big"],
                                     params={"w": i})[0] == "tiny")
        assert d.dispatch(METHOD, params) == _result()
        assert calls["tiny"] == [] and len(calls["big"]) == 1

    def test_undeclared_capabilities_constrain_nothing(self):
        """A capability-less fleet routes exactly like before — plain
        rendezvous, no fallback accounting."""
        calls = {"a": [], "b": []}
        d = Dispatcher([LocalReplica(r, runner=_mk_runner(calls[r]))
                        for r in calls], poll_s=0.005)
        fb0 = HEALTH.get("dispatcher_placement_fallbacks")
        assert d.dispatch(AGG_METHOD, {"w": 3}) == _result()
        first = _ranked_ids(list(calls), method=AGG_METHOD,
                            params={"w": 3})[0]
        assert len(calls[first]) == 1
        assert HEALTH.get("dispatcher_placement_fallbacks") == fb0

    def test_fallback_counter_when_no_capable_replica_healthy(self):
        """With every eligible replica behind an open breaker, work
        still lands — on the ranked remainder, visibly counted."""
        calls = {"meshy": [], "plain": []}
        d = Dispatcher([
            LocalReplica("meshy", runner=_mk_runner(calls["meshy"]),
                         capabilities={"mesh_shape": [2, 2]}),
            LocalReplica("plain", runner=_mk_runner(calls["plain"]))],
            poll_s=0.005, breaker_threshold=1, breaker_cooldown=60.0)
        d.breaker("meshy").record(False)      # threshold 1 -> open
        fb0 = HEALTH.get("dispatcher_placement_fallbacks")
        assert d.dispatch(AGG_METHOD, {}) == _result()
        assert calls["meshy"] == [] and len(calls["plain"]) == 1
        assert HEALTH.get("dispatcher_placement_fallbacks") == fb0 + 1


# -- hygiene pins -----------------------------------------------------------


class TestFarmHygiene:
    def test_dispatcher_importable_without_jax(self):
        """prom.py imports dispatcher_snapshot on every /metrics render
        and the CLI builds a Dispatcher before any prove: the module
        must never pull in jax at import time."""
        probe = (
            "import builtins\n"
            "real = builtins.__import__\n"
            "def guard(name, *a, **k):\n"
            "    assert not name.split('.')[0] == 'jax', name\n"
            "    return real(name, *a, **k)\n"
            "builtins.__import__ = guard\n"
            "import spectre_tpu.prover_service.dispatcher\n"
            "import spectre_tpu.utils.breaker\n"
            "print('ok')\n"
        )
        out = subprocess.run(
            [sys.executable, "-c", probe], capture_output=True, text=True,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
        assert out.returncode == 0, out.stderr
        assert out.stdout.strip() == "ok"

    def test_analysis_baseline_still_empty(self):
        """ISSUE-11 satellite: the farm lands WITHOUT baselining any new
        analysis finding — the shipped suppression list stays empty."""
        import spectre_tpu.analysis as A
        path = os.path.join(os.path.dirname(A.__file__), "baseline.json")
        with open(path) as fh:
            assert json.load(fh) == {"suppressions": []}

    def test_fault_sites_documented(self):
        for site in ("replica.dispatch", "replica.health", "replica.lease",
                     "replica.register", "replica.announce"):
            assert site in faults.SITES

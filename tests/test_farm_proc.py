"""Real-process proof-farm drills (ISSUE 18).

Everything in test_farm.py runs the farm in ONE process — fast and
deterministic, but a thread can never die the way a box does. This tier
launches actual ``serve()`` subprocesses (each pays a real jax import,
hence the dedicated `make test-farm-proc` budget) and kills them with
SIGKILL:

* three replica processes announce themselves to an in-test dispatcher
  head over HTTP, one is SIGKILLed mid-prove -> exactly one lease
  takeover, a byte-identical final proof from a survivor, and TTL
  deregistration of the corpse (journaled as a ``leave``);
* a dispatcher-head PROCESS is SIGKILLed while its replica holds a
  lease -> a fresh in-test Dispatcher + JobQueue over the same journal
  directory replays the open lease as an exclusion, re-grants as a
  takeover, finishes the SAME job id, and the witness-digest dedup
  refuses to prove it twice.
"""

import json
import os
import signal
import subprocess
import sys
import time

import pytest

from spectre_tpu import spec as SP
from spectre_tpu.models import CommitteeUpdateCircuit
from spectre_tpu.prover_service.dispatcher import (LEASE_JOURNAL_NAME,
                                                   MEMBER_JOURNAL_NAME,
                                                   Dispatcher, LocalReplica)
from spectre_tpu.prover_service.jobs import JobQueue
from spectre_tpu.prover_service.rpc import (RPC_METHOD_COMMITTEE,
                                            RPC_METHOD_COMMITTEE_SUBMIT,
                                            run_proof_method, serve)
from spectre_tpu.prover_service.rpc_client import ProverClient
from spectre_tpu.utils import faults
from spectre_tpu.utils.health import HEALTH

from test_follower import TINY, _mk_committee_update

# `slow`: each drill pays real subprocess jax imports, and the tier-1
# window is already budget-bound — these run via `make test-farm-proc`
# (wired into `make test`) under their own wall-clock cap instead.
pytestmark = [
    pytest.mark.slow,
    pytest.mark.skipif(
        os.name != "posix", reason="needs POSIX subprocesses + SIGKILL"),
]


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.clear()
    yield
    faults.clear()


def _counter(name: str) -> int:
    return HEALTH.snapshot()["counters"].get(name, 0)


def _wait(predicate, timeout_s: float, what: str, poll_s: float = 0.05):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if predicate():
            return
        time.sleep(poll_s)
    raise AssertionError(f"timed out after {timeout_s}s waiting for {what}")


def _subprocess_env() -> dict:
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"            # subprocesses mirror the tier
    env["PYTHONPATH"] = os.pathsep.join(
        p for p in (os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), env.get("PYTHONPATH")) if p)
    return env


def _reap(procs):
    for p in procs:
        if p.poll() is None:
            p.kill()
    for p in procs:
        try:
            p.wait(timeout=10)
        except subprocess.TimeoutExpired:
            pass


class _CannedCommitteeState:
    """In-test twin of the subprocess replica state: same proof bytes,
    same real get_instances — the byte-identity reference."""

    def __init__(self, spec):
        self.spec = spec
        self.concurrency = 1

    def prove_committee(self, args):
        return (b"\x02" * 64,
                CommitteeUpdateCircuit.get_instances(args, self.spec))


class _HeadState:
    """The dispatcher head proves nothing itself — the queue's runner is
    the Dispatcher."""

    concurrency = 2


# argv: head_url replica_id prove_sleep_s journal_dir
REPLICA_SCRIPT = r"""
import sys, time

head_url, rid, sleep_s, jdir = sys.argv[1:5]

from spectre_tpu import spec as SP
from spectre_tpu.models import CommitteeUpdateCircuit
from spectre_tpu.prover_service.rpc import serve


class CannedState:
    def __init__(self):
        self.spec = SP.TINY
        self.concurrency = 1

    def prove_committee(self, args):
        deadline = time.monotonic() + float(sleep_s)
        while time.monotonic() < deadline:   # SIGKILL-able mid-prove
            time.sleep(0.05)
        return b"\x02" * 64, CommitteeUpdateCircuit.get_instances(
            args, self.spec)


serve(CannedState(), host="127.0.0.1", port=0, journal_dir=jdir,
      replica_id=rid, announce=head_url, announce_interval=0.25)
"""

# argv: journal_dir prove_sleep_s
HEAD_SCRIPT = r"""
import sys, time

jdir, sleep_s = sys.argv[1:3]

from spectre_tpu.prover_service.dispatcher import Dispatcher, LocalReplica
from spectre_tpu.prover_service.rpc import serve


def slow_runner(method, params, heartbeat=None):
    deadline = time.monotonic() + float(sleep_s)
    while time.monotonic() < deadline:       # SIGKILL-able mid-prove
        time.sleep(0.05)
    return {"proof": "0x" + "ab" * 64, "instances": ["0x1"]}


class HeadState:
    concurrency = 1


d = Dispatcher([LocalReplica("local-A", runner=slow_runner)],
               journal_dir=jdir, lease_s=30.0)
server = serve(HeadState(), host="127.0.0.1", port=0, background=True,
               journal_dir=jdir, dispatcher=d)
print(server.server_address[1], flush=True)
while True:
    time.sleep(1.0)
"""

STARTUP_S = 180.0           # three parallel jax imports on a cold cache
PROVE_SLEEP_S = 5.0


class TestRealProcessFailover:
    def test_sigkill_replica_mid_prove_takeover_byte_identical(
            self, tmp_path):
        """ISSUE 18 acceptance: >=3 real serve() processes, SIGKILL the
        lease holder mid-prove -> exactly one dispatcher_lease_takeovers
        increment, a byte-identical final proof, and the corpse
        deregistered by TTL with a journaled `leave`."""
        head_dir = tmp_path / "head"
        head_dir.mkdir()
        d = Dispatcher(replicas=[], journal_dir=str(head_dir),
                       lease_s=30.0, ttl_s=3.0, poll_s=0.05,
                       health_ttl_s=0.2)
        head_state = _HeadState()
        server = serve(head_state, host="127.0.0.1", port=0,
                       background=True, journal_dir=str(head_dir),
                       dispatcher=d)
        head_url = f"http://127.0.0.1:{server.server_address[1]}"
        procs: dict[str, subprocess.Popen] = {}
        try:
            for i in range(3):
                rid = f"proc-{i}"
                rdir = tmp_path / rid
                rdir.mkdir()
                procs[rid] = subprocess.Popen(
                    [sys.executable, "-c", REPLICA_SCRIPT, head_url, rid,
                     str(PROVE_SLEEP_S), str(rdir)],
                    env=_subprocess_env(), stdout=subprocess.DEVNULL,
                    stderr=subprocess.DEVNULL)

            def _members():
                return {r["replica_id"]
                        for r in d.snapshot()["replicas"] if r["dynamic"]}

            _wait(lambda: _members() == set(procs), STARTUP_S,
                  "all three replicas to announce")
            for row in d.snapshot()["replicas"]:
                assert row["capabilities"]["url"].startswith("http://")

            update = _mk_committee_update(TINY, 1)
            params = {"light_client_update": update}
            expected = run_proof_method(_CannedCommitteeState(TINY),
                                        RPC_METHOD_COMMITTEE, params)

            takeovers = _counter("dispatcher_lease_takeovers")
            client = ProverClient(head_url, timeout=120.0)
            jid = client._call(RPC_METHOD_COMMITTEE_SUBMIT,
                               params)["job_id"]

            def _lease_holder():
                for row in d.snapshot()["replicas"]:
                    if row["active_leases"]:
                        return row["replica_id"]
                return None

            _wait(lambda: _lease_holder() is not None, 60.0,
                  "a lease grant")
            victim = _lease_holder()
            time.sleep(1.0)          # the canned prove is mid-sleep now
            os.kill(procs[victim].pid, signal.SIGKILL)
            procs[victim].wait(timeout=10)

            _wait(lambda: client.proof_status(jid)["status"] == "done",
                  120.0, "the takeover replica to finish")
            result = client.proof_result(jid)
            # byte-identical completion on a DIFFERENT box
            for key in ("proof", "instances", "calldata",
                        "committee_poseidon"):
                assert result[key] == expected[key]
            assert _counter("dispatcher_lease_takeovers") == takeovers + 1

            # TTL liveness: the corpse stops heartbeating and is
            # deregistered, survivors stay
            _wait(lambda: victim not in _members(), 30.0,
                  "TTL deregistration of the killed replica")
            assert _members() == set(procs) - {victim}
            journal = (head_dir / MEMBER_JOURNAL_NAME).read_text()
            assert any(json.loads(ln).get("event") == "leave"
                       and json.loads(ln)["replica"] == victim
                       for ln in journal.splitlines() if ln.strip())
        finally:
            _reap(list(procs.values()))
            server.shutdown()
            head_state.jobs.stop()

    def test_sigkill_head_process_lease_replay_and_dedup(self, tmp_path):
        """Lease-journal replay across a PROCESS boundary: SIGKILL a
        dispatcher head (taking its in-process lease holder with it),
        rebuild Dispatcher + JobQueue over the same journals -> the open
        lease replays as an exclusion, the takeover re-grant finishes
        the SAME job id, and the witness-digest dedup refuses a second
        prove."""
        jdir = tmp_path / "head"
        jdir.mkdir()
        proc = subprocess.Popen(
            [sys.executable, "-c", HEAD_SCRIPT, str(jdir),
             str(PROVE_SLEEP_S)],
            env=_subprocess_env(), stdout=subprocess.PIPE,
            stderr=subprocess.DEVNULL, text=True)
        jid = None
        try:
            port_line = proc.stdout.readline().strip()
            assert port_line, "head subprocess never printed its port"
            client = ProverClient(f"http://127.0.0.1:{port_line}",
                                  timeout=60.0)
            params = {"light_client_update": {"window": 7}}
            jid = client._call(RPC_METHOD_COMMITTEE_SUBMIT,
                               params)["job_id"]

            lease_path = jdir / LEASE_JOURNAL_NAME
            _wait(lambda: lease_path.exists()
                  and '"event": "lease"' in lease_path.read_text(),
                  90.0, "the lease grant to hit the journal")
            time.sleep(0.5)          # local-A is mid-sleep in its prove
            os.kill(proc.pid, signal.SIGKILL)
            proc.wait(timeout=10)
        finally:
            if proc.poll() is None:
                proc.kill()
                proc.wait(timeout=10)

        replayed = _counter("dispatcher_leases_replayed")
        takeovers = _counter("dispatcher_lease_takeovers")
        calls = {"n": 0}

        def runner2(method, params, heartbeat=None):
            calls["n"] += 1
            return {"proof": "0x" + "ab" * 64, "instances": ["0x1"]}

        d2 = Dispatcher([LocalReplica("local-B", runner=runner2)],
                        journal_dir=str(jdir), lease_s=30.0)
        assert _counter("dispatcher_leases_replayed") == replayed + 1
        jobs2 = JobQueue(d2, concurrency=1, journal_dir=str(jdir),
                         stall_timeout=600.0)
        try:
            # replay requeued the running job under its ORIGINAL id and
            # the survivor finished it as a takeover
            _wait(lambda: jobs2.status(jid)["status"] == "done", 60.0,
                  "the replayed job to finish on the survivor")
            assert jobs2.result(jid).result["proof"] == "0x" + "ab" * 64
            assert calls["n"] == 1
            assert _counter("dispatcher_lease_takeovers") == takeovers + 1

            # witness-digest dedup across the process boundary: the same
            # (method, params) maps back to the finished job, no re-prove
            assert jobs2.submit(RPC_METHOD_COMMITTEE,
                                {"light_client_update": {"window": 7}}) \
                == jid
            assert calls["n"] == 1
        finally:
            jobs2.stop()

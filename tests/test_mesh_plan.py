"""Fast mesh/ShardingPlan tests (ISSUE 13) — no SPMD compiles.

Everything here exercises plan construction, env-knob parsing, cache
identity, and budget arithmetic on the virtual 8-device mesh that
conftest.py forces; nothing traces an 8-way program, so the whole module
stays tier-1-eligible. The minutes-scale SPMD byte-equality matrix lives
in tests/test_parallel.py behind RUN_SLOW.
"""

import pytest

import jax

from spectre_tpu.parallel import (MeshShapeError, current_plan, default_mesh,
                                  make_mesh, plan_for_mesh)

pytestmark = pytest.mark.skipif(len(jax.devices()) < 8,
                                reason="needs 8 virtual devices")


class TestMeshShapeKnob:
    def test_default_is_full_mesh(self, monkeypatch):
        monkeypatch.delenv("SPECTRE_MESH_SHAPE", raising=False)
        mesh = default_mesh()
        assert dict(mesh.shape) == {"data": 4, "win": 2}

    def test_explicit_shape(self, monkeypatch):
        monkeypatch.setenv("SPECTRE_MESH_SHAPE", "2x1")
        assert dict(default_mesh().shape) == {"data": 2, "win": 1}

    def test_bare_int_means_data_axis(self, monkeypatch):
        monkeypatch.setenv("SPECTRE_MESH_SHAPE", "8")
        assert dict(default_mesh().shape) == {"data": 8, "win": 1}

    def test_single_device_shape(self, monkeypatch):
        monkeypatch.setenv("SPECTRE_MESH_SHAPE", "1x1")
        mesh = default_mesh()
        assert plan_for_mesh(mesh).n_devices == 1

    def test_too_many_devices_is_typed_error(self, monkeypatch):
        monkeypatch.setenv("SPECTRE_MESH_SHAPE", "5x3")
        with pytest.raises(MeshShapeError, match="15 devices"):
            default_mesh()

    def test_parse_garbage_is_typed_error(self, monkeypatch):
        monkeypatch.setenv("SPECTRE_MESH_SHAPE", "bogus")
        with pytest.raises(MeshShapeError):
            default_mesh()

    def test_mesh_shape_error_is_value_error(self):
        # callers that catch ValueError (CLI arg validation) keep working
        assert issubclass(MeshShapeError, ValueError)


class TestPlanInterning:
    def test_same_mesh_same_plan(self):
        mesh = make_mesh(8)
        assert plan_for_mesh(mesh) is plan_for_mesh(mesh)

    def test_current_plan_tracks_env(self, monkeypatch):
        monkeypatch.setenv("SPECTRE_MESH_SHAPE", "2x1")
        p2 = current_plan()
        assert (p2.ndata, p2.nwin_shards) == (2, 1)
        monkeypatch.setenv("SPECTRE_MESH_SHAPE", "4x2")
        p8 = current_plan()
        assert (p8.ndata, p8.nwin_shards) == (4, 2)
        assert p2.key != p8.key

    def test_pad_rows_and_windows(self):
        plan = plan_for_mesh(make_mesh(8))     # data=4, win=2
        assert plan.pad_rows(37) == 40         # next multiple of 4
        assert plan.pad_rows(40) == 40
        assert plan.pad_windows(33) == 34      # next multiple of 2

    def test_describe_shape(self):
        plan = plan_for_mesh(make_mesh(8))
        d = plan.describe()
        assert d["n_devices"] == 8
        assert d["mesh"] == {"data": 4, "win": 2}

    def test_batch_mesh_is_cached_and_flat(self):
        plan = plan_for_mesh(make_mesh(8))
        bm = plan.batch_mesh
        assert bm is plan.batch_mesh
        assert dict(bm.shape) == {"batch": 8}


class TestRunnerCaches:
    """Stable jitted-program identity is THE rc=124 fix: a fresh jit per
    call re-traces the 8-way SPMD program every MSM/NTT of a prove. Runner
    construction is lazy (no trace until first call), so these stay fast."""

    def test_msm_windows_runner_is_stable(self):
        from spectre_tpu.parallel import sharded_msm as _  # noqa: F401
        import importlib
        SM = importlib.import_module("spectre_tpu.parallel.sharded_msm")
        plan = plan_for_mesh(make_mesh(8))
        a = SM._windows_runner(plan, 7, 254, False)
        assert SM._windows_runner(plan, 7, 254, False) is a
        assert SM._windows_runner(plan, 7, 254, True) is not a

    def test_ntt_runner_is_stable(self, monkeypatch):
        from spectre_tpu.parallel import sharded_ntt as SN
        from spectre_tpu.plonk.domain import Domain
        monkeypatch.setenv("SPECTRE_NTT_MODE", "radix2")
        plan = plan_for_mesh(make_mesh(8))
        omega = Domain(10).omega
        a = SN._ntt_runner(plan, "data", 10, omega)
        assert SN._ntt_runner(plan, "data", 10, omega) is a

    def test_ntt_runner_keys_on_resolved_mode(self, monkeypatch):
        # the env knob must not go stale inside a resident program
        from spectre_tpu.parallel import sharded_ntt as SN
        from spectre_tpu.plonk.domain import Domain
        plan = plan_for_mesh(make_mesh(8))
        omega = Domain(16).omega          # local dims 2^8
        monkeypatch.setenv("SPECTRE_NTT_MODE", "radix2")
        a = SN._ntt_runner(plan, "data", 16, omega)
        monkeypatch.setenv("SPECTRE_NTT_MODE", "fourstep")
        b = SN._ntt_runner(plan, "data", 16, omega)
        assert a is not b


class TestFixedMeshBudget:
    """Per-DEVICE budget arithmetic for mesh-sharded fixed-base tables —
    pure math, no tracing."""

    def test_mesh_affords_ndata_times_larger_tables(self, monkeypatch):
        import importlib
        SM = importlib.import_module("spectre_tpu.parallel.sharded_msm")
        from spectre_tpu.ops import msm as MSM
        plan = plan_for_mesh(make_mesh(8))     # ndata=4
        c, nbits = 8, 127
        total = SM._sharded_table_bytes(1 << 12, c, nbits, plan)
        # budget just under the WHOLE table but above the per-shard slice:
        # a single device would degrade, the mesh must not
        monkeypatch.setattr(MSM._TABLES, "budget", total // 2)
        assert SM.fixed_fits_mesh(1 << 12, c, nbits, plan)
        assert not SM._degrade_fixed_mesh(1 << 12, c, nbits, plan)

    def test_degrade_records_health_counter(self, monkeypatch):
        import importlib
        SM = importlib.import_module("spectre_tpu.parallel.sharded_msm")
        from spectre_tpu.ops import msm as MSM
        from spectre_tpu.utils.health import HEALTH
        plan = plan_for_mesh(make_mesh(8))
        c, nbits = 8, 127
        total = SM._sharded_table_bytes(1 << 12, c, nbits, plan)
        monkeypatch.setattr(MSM._TABLES, "budget",
                            total // plan.ndata - 1)   # busts per-shard
        before = HEALTH.get("msm_fixed_degraded")
        assert SM._degrade_fixed_mesh(1 << 12, c, nbits, plan)
        assert HEALTH.get("msm_fixed_degraded") == before + 1

"""Application circuits: witness builders, instance parity, (gated) mocks."""

import dataclasses
import os

import pytest

from spectre_tpu import spec as SP
from spectre_tpu.fields import bls12_381 as bls
from spectre_tpu.models import CommitteeUpdateCircuit, StepCircuit
from spectre_tpu.witness import (
    default_committee_update_args,
    default_sync_step_args,
)
from spectre_tpu.witness.types import BeaconBlockHeader, uint64_chunk
from spectre_tpu.witness.rotation import mock_root
from spectre_tpu.gadgets.ssz_merkle import (
    merkleize_chunks_native,
    verify_merkle_proof_native,
)

TINY = dataclasses.replace(SP.MINIMAL, name="tiny", sync_committee_size=2)


class TestWitnessTypes:
    def test_header_root_is_ssz(self):
        hdr = BeaconBlockHeader(slot=5, proposer_index=9,
                                parent_root=b"\x01" * 32,
                                state_root=b"\x02" * 32,
                                body_root=b"\x03" * 32)
        want = merkleize_chunks_native([
            uint64_chunk(5), uint64_chunk(9), b"\x01" * 32, b"\x02" * 32,
            b"\x03" * 32], limit=8)
        assert hdr.hash_tree_root() == want

    def test_default_committee_args_consistent(self):
        args = default_committee_update_args(TINY)
        assert len(args.pubkeys_compressed) == 2
        # the mocked branch actually verifies
        assert verify_merkle_proof_native(
            args.committee_pubkeys_root(), args.sync_committee_branch,
            TINY.sync_committee_pubkeys_root_index,
            args.finalized_header.state_root)
        # pubkeys decompress
        for pk in args.pubkeys_compressed:
            assert bls.g1_decompress(pk) is not None

    def test_default_step_args_signature_valid(self):
        args = default_sync_step_args(TINY)
        pts = [(bls.Fq(x), bls.Fq(y)) for x, y in args.pubkeys_uncompressed]
        sig = bls.g2_decompress(args.signature_compressed)
        assert bls.fast_aggregate_verify(pts, args.signing_root(), sig,
                                         dst=TINY.dst)
        # branches verify natively
        assert verify_merkle_proof_native(
            args.finalized_header.hash_tree_root(), args.finality_branch,
            TINY.finalized_header_index, args.attested_header.state_root)
        assert verify_merkle_proof_native(
            args.execution_payload_root, args.execution_payload_branch,
            TINY.execution_state_root_index, args.finalized_header.body_root)


class TestInstanceParity:
    """In-circuit exposed instances == native get_instances (full witness-gen:
    slow-ish but the core correctness property)."""

    def test_committee_update(self):
        args = default_committee_update_args(TINY)
        ctx = CommitteeUpdateCircuit.build_context(args, TINY)
        assert [c.value for c in ctx.instance_cells] == \
            CommitteeUpdateCircuit.get_instances(args, TINY)

    def test_step(self):
        # full BLS block witness gen: ~40s after the bulk/vectorization work
        # — kept in the default tier so plain pytest exercises the flagship
        # circuit end to end (round-1 verdict weak #3)
        args = default_sync_step_args(TINY)
        ctx = StepCircuit.build_context(args, TINY)
        assert [c.value for c in ctx.instance_cells] == \
            StepCircuit.get_instances(args, TINY)

    def test_step_rejects_invalid_signature(self):
        # fast-fail guard fires before the heavy BLS block is built
        args = default_sync_step_args(TINY)
        args.signature_compressed = bls.g2_compress(
            bls.g2_curve.mul(bls.G2_GEN, 123))
        with pytest.raises(AssertionError, match="aggregate signature invalid"):
            StepCircuit.build_context(args, TINY)

    @pytest.mark.skipif(not os.environ.get("RUN_SLOW"),
                        reason="~10 min witness gen (full BLS block)")
    def test_step_rejects_forged_signature_by_constraints(self):
        """The round-2 flagship property: with the native guard DISABLED, a
        forged signature still cannot satisfy the constraint system — the
        in-circuit pairing check rejects it (VERDICT r1 item 1)."""
        args = default_sync_step_args(TINY)
        args.signature_compressed = bls.g2_compress(
            bls.g2_curve.mul(bls.G2_GEN, 123))
        with pytest.raises(AssertionError):
            StepCircuit.build_context(args, TINY, native_precheck=False)

    def test_native_instances_stable(self):
        args = default_committee_update_args(TINY)
        i1 = CommitteeUpdateCircuit.get_instances(args, TINY)
        i2 = CommitteeUpdateCircuit.get_instances(args, TINY)
        assert i1 == i2 and len(i1) == 3
        sargs = default_sync_step_args(TINY)
        si = StepCircuit.get_instances(sargs, TINY)
        assert len(si) == 2 and all(0 < v < (1 << 254) for v in si)


class TestMockSatisfaction:
    def test_committee_update_mock(self):
        # wide-SHA region: tiny fits k=13 and mocks in seconds — default tier
        args = default_committee_update_args(TINY)
        assert CommitteeUpdateCircuit.mock(args, TINY, k=13)

    @pytest.mark.skipif(not os.environ.get("RUN_SLOW"),
                        reason="43M-cell mock (set RUN_SLOW=1)")
    def test_step_mock(self):
        args = default_sync_step_args(TINY)
        assert StepCircuit.mock(args, TINY, k=17)

"""In-circuit Fp12 tower + pairing chip tests.

Default tier: component correctness vs the host field oracle (values AND
mock-proved constraints at small scale). RUN_SLOW tier: the full two-pair
BLS verification shape (27M cells — witness-level assert + forged-signature
rejection; reference parity: `sync_step_circuit.rs:171`)."""

import os
import secrets

import pytest

from spectre_tpu.builder import Context, RangeChip
from spectre_tpu.builder.fp_chip import EccChip, FpChip
from spectre_tpu.builder.fp2_chip import Fp2Chip, G2Chip
from spectre_tpu.builder.fp12_chip import (Fp12Chip, fq12_to_tower,
                                           tower_to_fq12)
from spectre_tpu.builder.pairing_chip import PairingChip
from spectre_tpu.fields import bls12_381 as bls
from spectre_tpu.plonk.mock import mock_prove

RUN_SLOW = os.environ.get("RUN_SLOW") == "1"


def _chips():
    ctx = Context()
    fp = FpChip(RangeChip(lookup_bits=8))
    fp2 = Fp2Chip(fp)
    fp12 = Fp12Chip(fp2)
    return ctx, fp, fp2, fp12


def _mock(ctx, k=14):
    cfg = ctx.auto_config(k=k, lookup_bits=8)
    assert mock_prove(cfg, ctx.assignment(cfg))


def _rand_fq12():
    return bls.Fq12([secrets.randbelow(bls.P) for _ in range(12)])


class TestFp12Chip:
    def test_tower_conversion_roundtrip(self):
        x = _rand_fq12()
        assert tower_to_fq12(fq12_to_tower(x)) == x

    def test_mul_square_vs_host(self):
        ctx, fp, fp2, fp12 = _chips()
        x, y = _rand_fq12(), _rand_fq12()
        a, b = fp12.load(ctx, x), fp12.load(ctx, y)
        assert fp12.value(fp12.mul(ctx, a, b)) == x * y
        assert fp12.value(fp12.square(ctx, a)) == x * x
        _mock(ctx, k=14)

    def test_cyclotomic_square_vs_host(self):
        """Granger–Scott squaring == true square for a cyclotomic element
        (f^((p^6-1)(p^2+1))), with a satisfied mock — the final exp's chain
        squares all run through this path."""
        ctx, fp, fp2, fp12 = _chips()
        t = _rand_fq12() ** ((bls.P ** 6 - 1) * (bls.P ** 2 + 1))
        a = fp12.load(ctx, t)
        assert fp12.value(fp12.cyclotomic_square(ctx, a)) == t * t
        _mock(ctx, k=14)

    def test_compressed_pow_abs_x_vs_host(self):
        """pow_abs_x with Karabina-style compressed square runs (our-basis
        closed set {c1,c2,c4,c5} + linear decompression from the unit-norm
        identity) == host f^|x|, with a satisfied mock."""
        ctx, fp, fp2, fp12 = _chips()
        t = _rand_fq12() ** ((bls.P ** 6 - 1) * (bls.P ** 2 + 1))
        a = fp12.load(ctx, t)
        got = fp12.pow_abs_x(ctx, a, cyclotomic=True)
        assert fp12.value(got) == t ** (-bls.BLS_X)
        _mock(ctx, k=17)

    def test_frobenius_conjugate_inverse_vs_host(self):
        ctx, fp, fp2, fp12 = _chips()
        x = _rand_fq12()
        a = fp12.load(ctx, x)
        assert fp12.value(fp12.frobenius(ctx, a, 1)) == x ** bls.P
        assert fp12.value(fp12.frobenius(ctx, a, 2)) == x ** (bls.P ** 2)
        assert fp12.value(fp12.conjugate(ctx, a)) == x ** (bls.P ** 6)
        assert fp12.value(fp12.inverse(ctx, a)) == x.inv()
        _mock(ctx, k=14)

    def test_sparse_mul_matches_full(self):
        ctx, fp, fp2, fp12 = _chips()
        x = _rand_fq12()
        a = fp12.load(ctx, x)
        c0 = fp2.load(ctx, bls.Fq2([3, 5]))
        c3 = fp2.load(ctx, bls.Fq2([7, 11]))
        c5 = fp2.load(ctx, bls.Fq2([13, 17]))
        sparse = fp12.mul_sparse_035(ctx, a, c0, c3, c5)
        line = fp12.load_constant(
            ctx, [bls.Fq2([3, 5]), bls.Fq2([0, 0]), bls.Fq2([0, 0]),
                  bls.Fq2([7, 11]), bls.Fq2([0, 0]), bls.Fq2([13, 17])])
        full = fp12.mul(ctx, a, line)
        assert fp12.value(sparse) == fp12.value(full)
        _mock(ctx, k=14)


class TestPairingComponents:
    def test_double_add_steps_vs_host(self):
        ctx, fp, fp2, fp12 = _chips()
        chip = PairingChip(fp12)
        g2 = G2Chip(fp2)
        q1 = bls.g2_curve.mul(bls.G2_GEN, 5)
        q2 = bls.g2_curve.mul(bls.G2_GEN, 9)
        c1, c2 = g2.load_point(ctx, q1), g2.load_point(ctx, q2)
        d, _lam = chip._double_step(ctx, c1)
        want = bls.g2_curve.double(q1)
        assert (fp2.value(d[0]), fp2.value(d[1])) == want
        s, _lam = chip._add_step(ctx, c1, c2)
        want = bls.g2_curve.add(q1, q2)
        assert (fp2.value(s[0]), fp2.value(s[1])) == want
        _mock(ctx, k=14)

    def test_psi_vs_host(self):
        ctx, fp, fp2, fp12 = _chips()
        chip = PairingChip(fp12)
        g2 = G2Chip(fp2)
        q = bls.g2_curve.mul(bls.G2_GEN, 31337)
        qc = g2.load_point(ctx, q)
        p = chip.g2_psi(ctx, qc)
        want = bls.g2_psi(q)
        assert (fp2.value(p[0]), fp2.value(p[1])) == want
        _mock(ctx, k=13)

    def test_final_exp_chain_host_identity(self):
        # the 3x hard-part chain the circuit implements, validated on host
        P, R, X = bls.P, bls.R, bls.BLS_X
        f = _rand_fq12()
        t = (f ** (P ** 6 - 1)) ** (P ** 2 + 1)
        conj = lambda u: u ** (P ** 6)
        pax = lambda u: u ** (-X)
        pxm1 = lambda u: conj(pax(u) * u)
        a = pxm1(pxm1(t))
        b = conj(pax(a)) * (a ** P)
        res = pax(pax(b)) * (b ** (P ** 2)) * conj(b) * t * t * t
        assert res == t ** (3 * ((P ** 4 - P ** 2 + 1) // R))
        assert conj(t) == t.inv()


@pytest.mark.skipif(not RUN_SLOW, reason="27M-cell pairing (set RUN_SLOW=1)")
class TestFullPairing:
    def test_bls_verification_shape(self):
        sk = 0x1234567
        pk = bls.sk_to_pk(sk)
        h = bls.hash_to_g2(b"full pairing test")
        sig = bls.g2_curve.mul(h, sk)
        ctx, fp, fp2, fp12 = _chips()
        chip = PairingChip(fp12)
        ecc, g2 = EccChip(fp), G2Chip(fp2)
        sig_c = g2.load_point(ctx, sig)
        chip.assert_g2_subgroup(ctx, sig_c)
        chip.assert_pairing_product_one(ctx, [
            (ecc.load_point(ctx, pk), g2.load_point(ctx, h)),
            (ecc.load_point(ctx, bls.g1_curve.neg(bls.G1_GEN)), sig_c)])

    def test_forged_signature_rejected(self):
        sk = 0x1234567
        pk = bls.sk_to_pk(sk)
        h = bls.hash_to_g2(b"full pairing test")
        bad = bls.g2_curve.mul(h, sk + 1)
        ctx, fp, fp2, fp12 = _chips()
        chip = PairingChip(fp12)
        ecc, g2 = EccChip(fp), G2Chip(fp2)
        with pytest.raises(AssertionError):
            chip.assert_pairing_product_one(ctx, [
                (ecc.load_point(ctx, pk), g2.load_point(ctx, h)),
                (ecc.load_point(ctx, bls.g1_curve.neg(bls.G1_GEN)),
                 g2.load_point(ctx, bad))])

"""EVM verifier generation + execution-oracle tests.

Reference parity: the reference golden-tests its generated Yul via revm
(`evm_verify`); offline we execute the generated Solidity subset through
evm/simulator.py against real Keccak-transcript proofs."""


import pytest

from spectre_tpu.evm import encode_calldata, gen_evm_verifier
from spectre_tpu.evm.simulator import run_verifier
from spectre_tpu.plonk.constraint_system import Assignment, CircuitConfig
from spectre_tpu.plonk.keygen import keygen
from spectre_tpu.plonk.prover import prove
from spectre_tpu.plonk.srs import SRS
from spectre_tpu.plonk.transcript import KeccakTranscript, keccak256
from spectre_tpu.plonk.verifier import verify

K = 7


@pytest.fixture(scope="module")
def setup():
    from test_plonk import _tiny_circuit
    srs = SRS.unsafe_setup(K)
    cfg = CircuitConfig(k=K, num_advice=1, num_lookup_advice=1, num_fixed=1,
                        lookup_bits=4)
    advice, lookup, fixed, selectors, copies, out = _tiny_circuit(cfg)
    pk = keygen(srs, cfg, fixed, selectors, copies)
    asg = Assignment(cfg, advice, lookup, fixed, selectors, [[out]], copies)
    proof = prove(pk, srs, asg, transcript=KeccakTranscript())
    assert verify(pk.vk, srs, [[out]], proof, transcript_cls=KeccakTranscript)
    src = gen_evm_verifier(pk.vk, srs, num_instances=1)
    return srs, pk, out, proof, src


class TestCodegen:
    def test_deterministic_and_wellformed(self, setup):
        srs, pk, out, proof, src = setup
        assert src == gen_evm_verifier(pk.vk, srs, num_instances=1)
        assert src.count("{") == src.count("}")
        assert "0x" + pk.vk.digest().hex() in src          # vk binding
        assert f"require(proof.length == {len(proof)}" in src
        assert "pragma solidity" in src and "function verify" in src

    def test_generated_verifier_accepts_real_proof(self, setup):
        srs, pk, out, proof, src = setup
        assert run_verifier(src, [out], proof)

    def test_generated_verifier_rejects_forgeries(self, setup):
        srs, pk, out, proof, src = setup
        # tampered commitment section
        bad = bytearray(proof)
        bad[100] ^= 1
        assert not run_verifier(src, [out], bytes(bad))
        # tampered eval section
        bad2 = bytearray(proof)
        bad2[-100] ^= 1
        assert not run_verifier(src, [out], bytes(bad2))
        # wrong public input
        assert not run_verifier(src, [out + 1], proof)
        # wrong length
        assert not run_verifier(src, [out], proof + b"\x00" * 32)

    def test_multi_column_circuit(self, setup):
        # wider shape: 2 advice columns (multi perm chunks path)
        srs = setup[0]
        cfg = CircuitConfig(k=K, num_advice=2, num_lookup_advice=1,
                            num_fixed=1, lookup_bits=4)
        n = cfg.n
        advice = [[0] * n, [0] * n]
        selectors = [[0] * n, [0] * n]
        advice[0][0:4] = [2, 3, 4, 14]
        selectors[0][0] = 1
        advice[1][0:4] = [14, 14, 1, 28]
        selectors[1][0] = 1
        lookup = [[0] * n]
        lookup[0][0] = 14
        fixed = [[0] * n]
        copies = [
            ((cfg.col_gate_advice(0), 3), (cfg.col_gate_advice(1), 0)),
            ((cfg.col_gate_advice(1), 0), (cfg.col_gate_advice(1), 1)),
            ((cfg.col_gate_advice(0), 3), (cfg.col_lookup_advice(0), 0)),
            ((cfg.col_instance(0), 0), (cfg.col_gate_advice(1), 3)),
        ]
        pk = keygen(srs, cfg, fixed, selectors, copies)
        asg = Assignment(cfg, advice, lookup, fixed, selectors, [[28]], copies)
        proof = prove(pk, srs, asg, transcript=KeccakTranscript())
        src = gen_evm_verifier(pk.vk, srs, num_instances=1)
        assert run_verifier(src, [28], proof)
        assert not run_verifier(src, [29], proof)


class TestAccumulatorPairing:
    """num_acc_limbs=12: the generated contract must ALSO perform the
    deferred KZG pairing over the first 12 instances — an outer-valid proof
    wrapping a pairing-INVALID accumulator must be rejected (review finding:
    without this, compressed proofs over forged inner proofs verified)."""

    @staticmethod
    def _acc_proof(srs, s: int, valid: bool):
        from spectre_tpu.builder import Context
        from spectre_tpu.fields import bn254
        from spectre_tpu.models.aggregation import Accumulator

        from spectre_tpu.native import host

        g1 = bn254.g1_curve
        lhs = g1.mul(bn254.G1_GEN, s)          # [s] G1
        if valid:
            tau_g = host.limbs_to_ints(srs.g1_powers[1:2].reshape(2, 4))
            rhs = g1.mul((bn254.Fq(tau_g[0]), bn254.Fq(tau_g[1])), s)
        else:
            rhs = g1.mul(bn254.G1_GEN, s + 1)  # wrong: pairing fails
        acc = Accumulator(lhs=lhs, rhs=rhs)
        if valid:
            assert acc.check(srs)
        else:
            assert not acc.check(srs)

        ctx = Context()
        for v in acc.limbs():
            ctx.expose_public(ctx.load_witness(v))
        cfg = ctx.auto_config(k=K, lookup_bits=4)
        advice, lookup, fixed, selectors, copies, instances, _bp = \
            ctx.layout(cfg)
        pk = keygen(srs, cfg, fixed, selectors, copies)
        asg = Assignment(cfg, advice, lookup, fixed, selectors, instances,
                         copies)
        proof = prove(pk, srs, asg, transcript=KeccakTranscript())
        assert verify(pk.vk, srs, instances, proof,
                      transcript_cls=KeccakTranscript)
        src = gen_evm_verifier(pk.vk, srs, num_instances=12,
                               num_acc_limbs=12)
        return src, instances[0], proof

    def test_valid_accumulator_accepted(self, setup):
        srs = setup[0]
        src, inst, proof = self._acc_proof(srs, 12345, valid=True)
        assert run_verifier(src, inst, proof)

    def test_invalid_accumulator_rejected_despite_valid_outer(self, setup):
        srs = setup[0]
        src, inst, proof = self._acc_proof(srs, 12345, valid=False)
        # the outer PLONK proof itself is valid — only the deferred
        # accumulator pairing must reject it
        assert not run_verifier(src, inst, proof)


class TestCalldata:
    def test_layout_golden(self, setup):
        _, _, out, proof, _ = setup
        cd = encode_calldata([out], proof)
        assert cd[:4] == keccak256(b"verify(uint256[],bytes)")[:4]
        # head: two offsets
        assert int.from_bytes(cd[4:36], "big") == 64
        inst_off = 64
        assert int.from_bytes(cd[4 + 32:4 + 64], "big") == \
            inst_off + 32 + 32 * 1
        # instances array
        assert int.from_bytes(cd[4 + 64:4 + 96], "big") == 1
        assert int.from_bytes(cd[4 + 96:4 + 128], "big") == out
        # proof bytes
        assert int.from_bytes(cd[4 + 128:4 + 160], "big") == len(proof)
        assert cd[4 + 160:4 + 160 + len(proof)] == proof
        assert len(cd) % 32 == 4


class TestGasAndSizeEstimation:
    """Static gas/deployed-size model (evm/gas.py; reference prints these
    from revm, `prover/src/cli.rs:249-277`)."""

    def test_counts_and_gas_on_generated_verifier(self, setup):
        from spectre_tpu.evm import estimate_deployed_size, estimate_gas
        _, pk, out, proof, src = setup
        cd = encode_calldata([out], proof)
        g = estimate_gas(src, calldata=cd)
        c = g["counts"]
        # the verifier must contain the structural minimum: a pairing, the
        # SHPLONK W/W' ecMuls, transcript keccaks, and the identity's mulmods
        assert c["pairing"] >= 1
        assert c["ecmul"] >= 2
        assert c["keccak"] >= 3
        assert c["mulmod"] > 10
        assert g["gas_precompiles"] >= 45000 + 34000 * 2
        assert g["gas_total"] > g["gas_execution"] > 0
        assert g["gas_intrinsic"] >= 21000
        sz = estimate_deployed_size(src)
        assert sz["deployed_bytes_estimate"] > 2200
        assert sz["deployed_size_risk"] in ("ok", "tight", "exceeds-eip170")

    def test_flagship_scale_verifier_size_assessment(self):
        """The archived flagship aggregation verifier (107KB source) gets a
        concrete EIP-170 assessment instead of an unknown."""
        import glob
        import os
        from spectre_tpu.evm import estimate_deployed_size
        cands = sorted(glob.glob(os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "build", "**", "aggregation_sync_step_*_verifier.sol"),
            recursive=True))
        if not cands:
            import pytest
            pytest.skip("no flagship verifier source in build/")
        with open(cands[-1]) as f:
            src = f.read()
        sz = estimate_deployed_size(src)
        # record-keeping assertion: the estimate must be decided, whatever
        # the verdict — the flagship record embeds it
        assert sz["deployed_size_risk"] in ("ok", "tight", "exceeds-eip170")
        assert sz["deployed_bytes_estimate"] > 0

"""Proving-system tests: KZG/SHPLONK, transcripts, full prove/verify."""

import os
import secrets

import numpy as np
import pytest

from spectre_tpu.fields import bn254 as bn
from spectre_tpu.native import host
from spectre_tpu.plonk import backend as B, kzg
from spectre_tpu.plonk.constraint_system import Assignment, CircuitConfig
from spectre_tpu.plonk.domain import Domain
from spectre_tpu.plonk.keygen import keygen
from spectre_tpu.plonk.prover import prove
from spectre_tpu.plonk.srs import SRS
from spectre_tpu.plonk.transcript import Blake2bTranscript, KeccakTranscript, keccak256
from spectre_tpu.plonk.verifier import verify

K = 7


@pytest.fixture(scope="module")
def srs():
    return SRS.unsafe_setup(K)


class TestTranscript:
    def test_keccak256_vectors(self):
        # standard Keccak-256 (Ethereum) test vectors
        assert keccak256(b"").hex() == \
            "c5d2460186f7233c927e7db2dcc703c0e500b653ca82273b7bfad8045d85a470"
        assert keccak256(b"abc").hex() == \
            "4e03657aea45a94fc7d47ba826c8d667c0d1e6e33a64a036ec44f58fa12d6c45"

    def test_roundtrip_and_determinism(self):
        for cls in (Blake2bTranscript, KeccakTranscript):
            tw = cls()
            pt = bn.g1_curve.mul(bn.G1_GEN, 7)
            tw.write_point(pt)
            tw.write_scalar(12345)
            c1 = tw.challenge()
            proof = tw.finalize()
            tr = cls(proof)
            assert tr.read_point() == pt
            assert tr.read_scalar() == 12345
            assert tr.challenge() == c1
            tr.assert_consumed()

    def test_infinity_point(self):
        tw = Blake2bTranscript()
        tw.write_point(None)
        tr = Blake2bTranscript(tw.finalize())
        assert tr.read_point() is None


class TestDomain:
    def test_lagrange_roundtrip(self):
        dom = Domain(5)
        vals = [secrets.randbelow(bn.R) for _ in range(32)]
        arr = B.to_arr(vals)
        back = dom.coeff_to_lagrange(dom.lagrange_to_coeff(arr))
        assert B.arr_to_ints(back) == vals

    def test_extended_roundtrip(self):
        dom = Domain(4)
        coeffs = B.to_arr([secrets.randbelow(bn.R) for _ in range(16)])
        ext = dom.coeff_to_extended(coeffs)
        back = dom.extended_to_coeff(ext)
        assert B.arr_to_ints(back[:16]) == B.arr_to_ints(coeffs)
        assert all(v == 0 for v in B.arr_to_ints(back[16:]))

    def test_lagrange_evals(self):
        dom = Domain(4)
        x = secrets.randbelow(bn.R)
        lag = dom.lagrange_evals(x, [0, 3])
        # L_i(omega^i) = 1, L_i(omega^j) = 0
        lag_at_dom = dom.lagrange_evals(dom.omega ** 3 % bn.R, [0, 3])
        assert lag_at_dom[3] == 1 and lag_at_dom[0] == 0
        # sum of all lagranges = 1
        all_lag = dom.lagrange_evals(x, range(16))
        assert sum(all_lag.values()) % bn.R == 1


class TestSHPLONK:
    def test_multipoint_roundtrip(self, srs):
        dom = Domain(K)
        n = 1 << K
        c1 = B.to_arr([secrets.randbelow(bn.R) for _ in range(n)])
        c2 = B.to_arr([secrets.randbelow(bn.R) for _ in range(n)])
        C1, C2 = kzg.commit(srs, c1), kzg.commit(srs, c2)
        x = secrets.randbelow(bn.R)
        wx = x * dom.omega % bn.R
        e1 = (host.fp_horner(host.FR, c1, x), host.fp_horner(host.FR, c1, wx))
        e2 = (host.fp_horner(host.FR, c2, x),)
        tw = Blake2bTranscript()
        for e in e1 + e2:
            tw.write_scalar(e)
        kzg.shplonk_open(srs, dom, [
            kzg.OpenEntry(c1, None, (x, wx), e1),
            kzg.OpenEntry(c2, None, (x,), e2)], tw)
        tr = Blake2bTranscript(tw.finalize())
        f1 = (tr.read_scalar(), tr.read_scalar())
        f2 = (tr.read_scalar(),)
        assert kzg.shplonk_verify(srs, [
            kzg.OpenEntry(None, C1, (x, wx), f1),
            kzg.OpenEntry(None, C2, (x,), f2)], tr)

    def test_bad_eval_rejected(self, srs):
        dom = Domain(K)
        n = 1 << K
        c1 = B.to_arr([secrets.randbelow(bn.R) for _ in range(n)])
        C1 = kzg.commit(srs, c1)
        x = secrets.randbelow(bn.R)
        bad = ((host.fp_horner(host.FR, c1, x) + 1) % bn.R,)
        tw = Blake2bTranscript()
        tw.write_scalar(bad[0])
        kzg.shplonk_open(srs, dom, [kzg.OpenEntry(c1, None, (x,), bad)], tw)
        tr = Blake2bTranscript(tw.finalize())
        f = (tr.read_scalar(),)
        assert not kzg.shplonk_verify(srs, [kzg.OpenEntry(None, C1, (x,), f)], tr)


class TestMsmModeCommitments:
    """The ISSUE-2 correctness gate: KZG commitments through the device
    backend are byte-identical across every MSM mode (GLV, signed digits,
    fixed-base tables) AND match the native CPU oracle — the modes change
    work shape, never the committed group element. Commitment-level (not
    full-prove) in the default tier on purpose: this box's XLA CPU client
    segfaults in LLVM under repeated full-prove compile churn; the
    full-prove cross-mode equality is the SPECTRE_BYTEEQ_FULL tier in
    TestBackendByteEquality. Placed before the prove suites so it runs
    with minimal accumulated compile state."""

    def test_msm_mode_commitments_byte_identical(self, srs, monkeypatch):
        import random
        rng = random.Random(0xD16E57)
        n = srs.n
        coeffs = np.zeros((n, 4), dtype=np.uint64)
        for i in range(n):
            v = rng.randrange(bn.R)
            for j in range(4):
                coeffs[i, j] = (v >> (64 * j)) & 0xFFFFFFFFFFFFFFFF
        oracle = kzg.commit(srs, coeffs, B.get_backend("cpu"))
        bk = B.get_backend("tpu")
        for mode in ("vanilla", "glv", "glv+signed", "fixed"):
            monkeypatch.setenv("SPECTRE_MSM_MODE", mode)
            got = kzg.commit(srs, coeffs, bk)
            assert got == oracle, \
                f"SPECTRE_MSM_MODE={mode} commitment diverged from oracle"

    @pytest.mark.slow
    def test_pallas_impl_commitments_byte_identical(self, srs, monkeypatch):
        """ISSUE 17 tier of the same gate, impl axis: every mode under
        SPECTRE_MSM_IMPL=pallas (interpret mode off-TPU) commits to the
        SAME bytes as the CPU oracle through the device backend, and none
        of the four modes falls back to XLA (zero unsupported-mode
        events). Slow tier: four interpret-mode pallas compile chains at
        K=7 cost ~100s on the 1-core box; the fast tier covers the same
        matrix at MSM level in test_msm_modes."""
        import random

        from spectre_tpu.ops import msm as MSM
        rng = random.Random(0xD16E57)
        n = srs.n
        coeffs = np.zeros((n, 4), dtype=np.uint64)
        for i in range(n):
            v = rng.randrange(bn.R)
            for j in range(4):
                coeffs[i, j] = (v >> (64 * j)) & 0xFFFFFFFFFFFFFFFF
        oracle = kzg.commit(srs, coeffs, B.get_backend("cpu"))
        events = []
        orig = MSM._record_event
        monkeypatch.setattr(
            MSM, "_record_event",
            lambda name, **kw: (events.append((name, kw)),
                                orig(name, **kw)))
        monkeypatch.setenv("SPECTRE_MSM_IMPL", "pallas")
        bk = B.get_backend("tpu")
        for mode in ("glv+signed", "glv", "fixed", "vanilla"):
            monkeypatch.setenv("SPECTRE_MSM_MODE", mode)
            got = kzg.commit(srs, coeffs, bk)
            assert got == oracle, \
                f"impl=pallas mode={mode} commitment diverged from oracle"
        bad = [e for e in events if e[0] == "msm_pallas_unsupported_mode"]
        assert not bad, f"pallas path degraded to XLA: {bad}"


def _tiny_circuit(cfg):
    """x + x*y = out, x range-checked, one constant pin."""
    n = cfg.n
    x_w, y_w = 7, 3
    out = x_w + x_w * y_w
    advice = [[0] * n for _ in range(cfg.num_advice)]
    advice[0][0], advice[0][1], advice[0][2], advice[0][3] = x_w, x_w, y_w, out
    advice[0][4] = 5
    selectors = [[0] * n for _ in range(cfg.num_advice)]
    selectors[0][0] = 1
    lookup = [[0] * n for _ in range(cfg.num_lookup_advice)]
    lookup[0][0] = x_w
    fixed = [[0] * n for _ in range(cfg.num_fixed)]
    fixed[0][0] = 5
    copies = [
        ((cfg.col_instance(0), 0), (cfg.col_gate_advice(0), 3)),
        ((cfg.col_fixed(0), 0), (cfg.col_gate_advice(0), 4)),
        ((cfg.col_gate_advice(0), 0), (cfg.col_lookup_advice(0), 0)),
    ]
    return advice, lookup, fixed, selectors, copies, out


class TestProveVerify:
    def test_end_to_end(self, srs):
        cfg = CircuitConfig(k=K, num_advice=1, num_lookup_advice=1, num_fixed=1,
                            lookup_bits=4)
        advice, lookup, fixed, selectors, copies, out = _tiny_circuit(cfg)
        pk = keygen(srs, cfg, fixed, selectors, copies)
        asg = Assignment(cfg, advice, lookup, fixed, selectors, [[out]], copies)
        proof = prove(pk, srs, asg)
        assert verify(pk.vk, srs, [[out]], proof)
        assert not verify(pk.vk, srs, [[out + 1]], proof)

    def test_malformed_proof_bytes_reject_not_raise(self, srs):
        """Untrusted proof bytes must yield a boolean reject, never an
        exception: truncated, trailing-garbage, and non-canonical-scalar
        proofs all return False."""
        cfg = CircuitConfig(k=K, num_advice=1, num_lookup_advice=1, num_fixed=1,
                            lookup_bits=4)
        advice, lookup, fixed, selectors, copies, out = _tiny_circuit(cfg)
        pk = keygen(srs, cfg, fixed, selectors, copies)
        asg = Assignment(cfg, advice, lookup, fixed, selectors, [[out]], copies)
        proof = prove(pk, srs, asg)
        assert not verify(pk.vk, srs, [[out]], proof + b"\x00" * 7)
        assert not verify(pk.vk, srs, [[out]], proof[:-5])
        assert not verify(pk.vk, srs, [[out]], b"")
        assert not verify(pk.vk, srs, [[out]], proof[:64] + b"\xff" * (len(proof) - 64))

    def test_multi_advice_columns(self, srs):
        # two gate columns + wider permutation (multiple chunks exercised)
        cfg = CircuitConfig(k=K, num_advice=2, num_lookup_advice=1, num_fixed=1,
                            lookup_bits=4)
        n = cfg.n
        advice = [[0] * n, [0] * n]
        selectors = [[0] * n, [0] * n]
        # col0: 2 + 3*4 = 14 ; col1: 14 + 14*1 = 28, cross-column copy
        advice[0][0:4] = [2, 3, 4, 14]
        selectors[0][0] = 1
        advice[1][0:4] = [14, 14, 1, 28]
        selectors[1][0] = 1
        lookup = [[0] * n]
        lookup[0][0] = 14
        fixed = [[0] * n]
        copies = [
            ((cfg.col_gate_advice(0), 3), (cfg.col_gate_advice(1), 0)),
            ((cfg.col_gate_advice(1), 0), (cfg.col_gate_advice(1), 1)),
            ((cfg.col_gate_advice(0), 3), (cfg.col_lookup_advice(0), 0)),
            ((cfg.col_instance(0), 0), (cfg.col_gate_advice(1), 3)),
        ]
        pk = keygen(srs, cfg, fixed, selectors, copies)
        asg = Assignment(cfg, advice, lookup, fixed, selectors, [[28]], copies)
        proof = prove(pk, srs, asg)
        assert verify(pk.vk, srs, [[28]], proof)

    def test_invalid_gate_witness_rejected(self, srs):
        cfg = CircuitConfig(k=K, num_advice=1, num_lookup_advice=1, num_fixed=1,
                            lookup_bits=4)
        advice, lookup, fixed, selectors, copies, out = _tiny_circuit(cfg)
        advice[0][2] = 999  # breaks the gate (x + x*y != out)
        pk = keygen(srs, cfg, fixed, selectors, copies)
        asg = Assignment(cfg, advice, lookup, fixed, selectors, [[out]], copies)
        # the prover refuses: quotient division is inexact for a bad witness
        with pytest.raises(AssertionError, match="witness violates"):
            prove(pk, srs, asg)

    def test_out_of_range_lookup_rejected_at_prove(self, srs):
        cfg = CircuitConfig(k=K, num_advice=1, num_lookup_advice=1, num_fixed=1,
                            lookup_bits=4)
        advice, lookup, fixed, selectors, copies, out = _tiny_circuit(cfg)
        lookup[0][1] = 99999  # not in [0, 16)
        pk = keygen(srs, cfg, fixed, selectors, copies)
        asg = Assignment(cfg, advice, lookup, fixed, selectors, [[out]], copies)
        with pytest.raises(AssertionError, match="not in table"):
            prove(pk, srs, asg)

    def test_copy_violation_rejected_at_prove(self, srs):
        cfg = CircuitConfig(k=K, num_advice=1, num_lookup_advice=1, num_fixed=1,
                            lookup_bits=4)
        advice, lookup, fixed, selectors, copies, out = _tiny_circuit(cfg)
        advice[0][4] = 6  # violates the constant-5 copy constraint
        pk = keygen(srs, cfg, fixed, selectors, copies)
        asg = Assignment(cfg, advice, lookup, fixed, selectors, [[out]], copies)
        with pytest.raises(AssertionError, match="permutation product"):
            prove(pk, srs, asg)

    def test_proof_is_zk_randomized(self, srs):
        cfg = CircuitConfig(k=K, num_advice=1, num_lookup_advice=1, num_fixed=1,
                            lookup_bits=4)
        advice, lookup, fixed, selectors, copies, out = _tiny_circuit(cfg)
        pk = keygen(srs, cfg, fixed, selectors, copies)
        asg = Assignment(cfg, advice, lookup, fixed, selectors, [[out]], copies)
        p1 = prove(pk, srs, asg)
        p2 = prove(pk, srs, asg)
        assert p1 != p2  # blinding rows differ
        assert verify(pk.vk, srs, [[out]], p1) and verify(pk.vk, srs, [[out]], p2)


class TestLookupBoundarySoundness:
    """Round-1 ADVICE high finding: the lookup grand product needs the
    l_last*(lz^2 - lz) boundary constraint, or a prover who sets A'=T'=table
    can 'look up' arbitrary out-of-range advice (the permutation relation is
    never anchored). These keep that hole closed."""

    def test_boundary_term_present_in_expressions(self):
        from spectre_tpu.plonk.expressions import ScalarCtx, all_expressions

        class _Zeros(dict):
            def __missing__(self, key):
                return 0

        cfg = CircuitConfig(k=K, num_advice=1, num_lookup_advice=1,
                            num_fixed=1, lookup_bits=4)
        evals = _Zeros()
        evals[(("lz", 0), 0)] = 2  # lz(last) not in {0,1}
        # at the l_last row: l0=0, act = 1 - llast - lblind = 0 — every other
        # constraint vanishes on the all-zero evals, so any nonzero entry IS
        # the boundary term
        ctx = ScalarCtx(cfg, evals, l0=0, llast=1, lblind=0, x=0)
        exprs = all_expressions(cfg, ctx, beta=1, gamma=1)
        assert any(e % bn.R != 0 for e in exprs), \
            "lookup boundary constraint missing: lz(last)=2 satisfied everything"

    def test_forged_lookup_rejected(self, srs, monkeypatch):
        """Replays the round-1 PoC: permuted columns = (table, table), advice
        contains 99999999, honest-prover asserts bypassed. The boundary
        constraint must now make the quotient division inexact."""
        from spectre_tpu.plonk import prover as prover_mod

        cfg = CircuitConfig(k=K, num_advice=1, num_lookup_advice=1,
                            num_fixed=1, lookup_bits=4)
        advice, lookup, fixed, selectors, copies, out = _tiny_circuit(cfg)
        lookup[0][1] = 99999999  # far outside the 4-bit table

        def evil_permute(cfg_, a_vals, t_vals):
            return list(t_vals), list(t_vals)  # A' = T' = table

        def evil_grand_product(bk, n, u, a_v, pa_v, pt_v, t_v, beta, gamma):
            num = bk.mul(bk.add(B.to_arr(a_v), B.to_arr([beta] * n)),
                         bk.add(B.to_arr(t_v), B.to_arr([gamma] * n)))
            den = bk.mul(bk.add(B.to_arr(pa_v), B.to_arr([beta] * n)),
                         bk.add(B.to_arr(pt_v), B.to_arr([gamma] * n)))
            ratio = B.arr_to_ints(bk.mul(num, bk.inv(den)))
            for i in range(u, n):
                ratio[i] = 1
            prefix = B.arr_to_ints(bk.prefix_prod(B.to_arr(ratio)))
            return [1] + prefix[:-1]  # telescope assert skipped

        monkeypatch.setattr(prover_mod, "permute_lookup", evil_permute)
        monkeypatch.setattr(prover_mod, "lookup_grand_product",
                            evil_grand_product)
        pk = keygen(srs, cfg, fixed, selectors, copies)
        asg = Assignment(cfg, advice, lookup, fixed, selectors, [[out]], copies)
        with pytest.raises(AssertionError, match="witness violates"):
            prove(pk, srs, asg)


class TestMockProver:
    def test_satisfied(self):
        from spectre_tpu.plonk.mock import mock_prove
        cfg = CircuitConfig(k=7, num_advice=1, num_lookup_advice=1, num_fixed=1,
                            lookup_bits=4)
        advice, lookup, fixed, selectors, copies, out = _tiny_circuit(cfg)
        asg = Assignment(cfg, advice, lookup, fixed, selectors, [[out]], copies)
        assert mock_prove(cfg, asg)

    def test_reports_gate_violation(self):
        from spectre_tpu.plonk.mock import mock_prove
        cfg = CircuitConfig(k=7, num_advice=1, num_lookup_advice=1, num_fixed=1,
                            lookup_bits=4)
        advice, lookup, fixed, selectors, copies, out = _tiny_circuit(cfg)
        advice[0][2] = 12  # gate broken
        asg = Assignment(cfg, advice, lookup, fixed, selectors, [[out]], copies)
        with pytest.raises(AssertionError, match="constraint #0 violated at row 0"):
            mock_prove(cfg, asg)

    def test_reports_copy_violation(self):
        from spectre_tpu.plonk.mock import mock_prove
        cfg = CircuitConfig(k=7, num_advice=1, num_lookup_advice=1, num_fixed=1,
                            lookup_bits=4)
        advice, lookup, fixed, selectors, copies, out = _tiny_circuit(cfg)
        advice[0][4] = 99
        asg = Assignment(cfg, advice, lookup, fixed, selectors, [[out]], copies)
        with pytest.raises(AssertionError, match="copy constraint violated"):
            mock_prove(cfg, asg)

    def test_reports_lookup_violation(self):
        from spectre_tpu.plonk.mock import mock_prove
        cfg = CircuitConfig(k=7, num_advice=1, num_lookup_advice=1, num_fixed=1,
                            lookup_bits=4)
        advice, lookup, fixed, selectors, copies, out = _tiny_circuit(cfg)
        lookup[0][9] = 1 << 20
        asg = Assignment(cfg, advice, lookup, fixed, selectors, [[out]], copies)
        with pytest.raises(AssertionError, match="not in table"):
            mock_prove(cfg, asg)


class TestDeviceQuotient:
    """quotient_device.py: device-resident evaluation of the whole
    constraint identity must match the host-orchestrated quotient EXACTLY
    (same u64 coefficient arrays) — compared in-situ during a real prove
    via a _quotient_host wrapper, so all inputs (blinds, grand products,
    challenges) are the production ones."""

    def _check(self, build_fn, k, lookup_bits, srs_k):
        import spectre_tpu.plonk.prover as P
        from spectre_tpu.builder.context import Context
        from spectre_tpu.plonk.quotient_device import compute_quotient

        ctx = Context()
        build_fn(ctx)
        cfg = ctx.auto_config(k=k, lookup_bits=lookup_bits)
        asg = ctx.assignment(cfg)
        srs_ = SRS.unsafe_setup(srs_k)
        bk = B.get_backend("cpu")
        pk = keygen(srs_, cfg, asg.fixed, asg.selectors, asg.copies, bk)
        orig_q = P._quotient_host
        res = {}

        def wrapped(cfg_, dom_, bk_, pk_, polys_, beta, gamma, y):
            h_host = orig_q(cfg_, dom_, bk_, pk_, polys_, beta, gamma, y)

            def fetch(key):
                kind, j = key
                if key in polys_:
                    return polys_[key]
                if kind == "shk":
                    return pk_.sha_k_poly
                return {"q": pk_.selector_polys, "fix": pk_.fixed_polys,
                        "sig": pk_.sigma_polys, "tab": pk_.table_polys,
                        "shq": pk_.sha_selector_polys}[kind][j]

            h_dev = compute_quotient(cfg_, dom_, fetch, beta, gamma, y)
            res["equal"] = bool((h_host == h_dev).all())
            return h_host

        P._quotient_host = wrapped
        try:
            proof = P.prove(pk, srs_, asg, bk)
        finally:
            P._quotient_host = orig_q
        assert verify(pk.vk, srs_, asg.instances, proof)
        assert res["equal"], "device quotient != host quotient"

    def test_gate_lookup_circuit(self):
        from spectre_tpu.builder import RangeChip

        def build(ctx):
            rng = RangeChip(lookup_bits=4)
            g = rng.gate
            a = ctx.load_witness(5)
            b = ctx.load_witness(9)
            c = g.mul(ctx, a, b)
            rng.range_check(ctx, a, 4)
            ctx.expose_public(c)

        self._check(build, k=5, lookup_bits=4, srs_k=7)

    @pytest.mark.skipif(not os.environ.get("RUN_SLOW"),
                        reason="device NTT compiles (set RUN_SLOW=1)")
    def test_wide_sha_circuit(self):
        """Region expressions, negative rotations, ROT_LAST, inst."""
        from spectre_tpu.builder import GateChip
        from spectre_tpu.builder.sha256_wide_chip import Sha256WideChip
        from spectre_tpu.gadgets import ssz_merkle as M

        def build(ctx):
            sha = Sha256WideChip(GateChip())
            cells = M.load_bytes_checked(ctx, sha, b"dq")
            digest = sha.digest_bytes(ctx, cells)
            ctx.expose_public(digest[0].cell)

        self._check(build, k=9, lookup_bits=5, srs_k=11)


@pytest.mark.skipif(not os.environ.get("RUN_SLOW"),
                    reason="minutes of device-kernel compile")
class TestTpuBackendPath:
    def test_prove_via_device_kernels(self, srs):
        """The --backend tpu wiring: MSM/NTT through the JAX limb kernels
        (runs on whatever JAX backend is active — CPU in CI)."""
        cfg = CircuitConfig(k=K, num_advice=1, num_lookup_advice=1, num_fixed=1,
                            lookup_bits=4)
        advice, lookup, fixed, selectors, copies, out = _tiny_circuit(cfg)
        bk = B.get_backend("tpu")
        pk = keygen(srs, cfg, fixed, selectors, copies, bk)
        pk_cpu = keygen(srs, cfg, fixed, selectors, copies, B.get_backend("cpu"))
        assert pk.vk.digest() == pk_cpu.vk.digest()
        asg = Assignment(cfg, advice, lookup, fixed, selectors, [[out]], copies)
        proof = prove(pk, srs, asg, bk)
        assert verify(pk.vk, srs, [[out]], proof)


class TestKeccakTranscriptPath:
    """The EVM-oriented transcript (Keccak-256) through full prove/verify —
    the reference's gen_evm_proof path uses exactly this hash for challenges."""

    def test_prove_verify_keccak(self, srs):
        cfg = CircuitConfig(k=K, num_advice=1, num_lookup_advice=1, num_fixed=1,
                            lookup_bits=4)
        advice, lookup, fixed, selectors, copies, out = _tiny_circuit(cfg)
        pk = keygen(srs, cfg, fixed, selectors, copies)
        asg = Assignment(cfg, advice, lookup, fixed, selectors, [[out]], copies)
        proof = prove(pk, srs, asg, transcript=KeccakTranscript())
        assert verify(pk.vk, srs, [[out]], proof, transcript_cls=KeccakTranscript)
        # a keccak proof must NOT verify under the blake2b transcript
        assert not verify(pk.vk, srs, [[out]], proof)


class TestBackendByteEquality:
    """VERDICT r3 item 4: the SAME proof bytes must come out of CpuBackend
    and TpuBackend when the ZK blinding is seeded identically — the backends
    differ only in WHERE the math runs, never in WHAT they compute. Default
    tier (shapes shared with TestProveVerify for a warm compile cache)."""

    @staticmethod
    def _seeded_rng(seed: int):
        import random
        r = random.Random(seed)
        return lambda: r.randrange(bn.R)

    def test_cpu_tpu_proof_bytes_identical(self, srs):
        cfg = CircuitConfig(k=K, num_advice=1, num_lookup_advice=1, num_fixed=1,
                            lookup_bits=4)
        advice, lookup, fixed, selectors, copies, out = _tiny_circuit(cfg)
        asg = Assignment(cfg, advice, lookup, fixed, selectors, [[out]], copies)
        proofs = {}
        for name in ("cpu", "tpu"):
            bk = B.get_backend(name)
            pk = keygen(srs, cfg, fixed, selectors, copies, bk)
            proofs[name] = prove(pk, srs, asg, bk,
                                 blinding_rng=self._seeded_rng(0xC0FFEE))
            assert verify(pk.vk, srs, [[out]], proofs[name])
        assert proofs["cpu"] == proofs["tpu"], \
            "backend proof bytes diverge (transcript/serialization drift)"

    @pytest.mark.skipif(not os.environ.get("SPECTRE_BYTEEQ_FULL"),
                        reason="this box's XLA CPU LLVM segfaults under "
                               "repeated prove compile churn; opt in with "
                               "SPECTRE_BYTEEQ_FULL=1 (real-device tier)")
    def test_msm_mode_proof_bytes_identical(self, srs, monkeypatch):
        """Full-prove tier of the gate: every MSM mode must produce
        BYTE-IDENTICAL proofs to the vanilla path under seeded blinding."""
        cfg = CircuitConfig(k=K, num_advice=1, num_lookup_advice=1,
                            num_fixed=1, lookup_bits=4)
        advice, lookup, fixed, selectors, copies, out = _tiny_circuit(cfg)
        asg = Assignment(cfg, advice, lookup, fixed, selectors, [[out]], copies)
        bk = B.get_backend("tpu")
        monkeypatch.setenv("SPECTRE_MSM_MODE", "vanilla")
        pk = keygen(srs, cfg, fixed, selectors, copies, bk)
        base = prove(pk, srs, asg, bk, blinding_rng=self._seeded_rng(7))
        assert verify(pk.vk, srs, [[out]], base)
        for mode in ("glv", "glv+signed", "fixed"):
            monkeypatch.setenv("SPECTRE_MSM_MODE", mode)
            p = prove(pk, srs, asg, bk, blinding_rng=self._seeded_rng(7))
            assert p == base, \
                f"SPECTRE_MSM_MODE={mode} diverged from vanilla proof bytes"

    @pytest.mark.skipif(not os.environ.get("SPECTRE_BYTEEQ_FULL"),
                        reason="this box's XLA CPU LLVM segfaults under "
                               "repeated prove compile churn; opt in with "
                               "SPECTRE_BYTEEQ_FULL=1 (real-device tier)")
    def test_msm_impl_proof_bytes_identical(self, srs, monkeypatch):
        """ISSUE 17 acceptance gate, impl axis: SPECTRE_MSM_IMPL=pallas
        must produce BYTE-IDENTICAL proofs to xla through the device
        backend for every MSM mode, with zero unsupported-mode fallbacks
        in the glv/glv+signed/fixed runs. Same full-prove tier as the mode
        gate above (the commitment-level pallas sweep rides the slow tier
        in TestMsmModeCommitments)."""
        from spectre_tpu.ops import msm as MSM
        cfg = CircuitConfig(k=K, num_advice=1, num_lookup_advice=1,
                            num_fixed=1, lookup_bits=4)
        advice, lookup, fixed, selectors, copies, out = _tiny_circuit(cfg)
        asg = Assignment(cfg, advice, lookup, fixed, selectors, [[out]], copies)
        bk = B.get_backend("tpu")
        events = []
        orig = MSM._record_event
        monkeypatch.setattr(
            MSM, "_record_event",
            lambda name, **kw: (events.append((name, kw)),
                                orig(name, **kw)))
        for mode in ("vanilla", "glv", "glv+signed", "fixed"):
            monkeypatch.setenv("SPECTRE_MSM_MODE", mode)
            monkeypatch.setenv("SPECTRE_MSM_IMPL", "xla")
            pk = keygen(srs, cfg, fixed, selectors, copies, bk)
            base = prove(pk, srs, asg, bk, blinding_rng=self._seeded_rng(11))
            events.clear()
            monkeypatch.setenv("SPECTRE_MSM_IMPL", "pallas")
            p = prove(pk, srs, asg, bk, blinding_rng=self._seeded_rng(11))
            assert p == base, \
                f"mode={mode}: pallas proof bytes diverge from xla"
            if mode != "vanilla":
                bad = [e for e in events
                       if e[0] == "msm_pallas_unsupported_mode"]
                assert not bad, f"mode={mode} degraded to XLA: {bad}"

    def test_seeded_blinding_is_deterministic_and_fresh_is_not(self, srs):
        cfg = CircuitConfig(k=K, num_advice=1, num_lookup_advice=1, num_fixed=1,
                            lookup_bits=4)
        advice, lookup, fixed, selectors, copies, out = _tiny_circuit(cfg)
        asg = Assignment(cfg, advice, lookup, fixed, selectors, [[out]], copies)
        pk = keygen(srs, cfg, fixed, selectors, copies)
        p1 = prove(pk, srs, asg, blinding_rng=self._seeded_rng(1))
        p2 = prove(pk, srs, asg, blinding_rng=self._seeded_rng(1))
        assert p1 == p2
        # default blinding: fresh system randomness -> different bytes
        p3 = prove(pk, srs, asg)
        assert p3 != p1 and verify(pk.vk, srs, [[out]], p3)


class TestQuotientCacheEviction:
    """BASELINE.md claims the byte-budgeted extended-array LRU is
    'regression-pinned under forced eviction' — pin it for real (ADVICE r5):
    a prove under SPECTRE_QUOTIENT_CACHE_MB=1 must produce BYTE-EQUAL output
    to the default-budget prove with the same seeded blinding (eviction
    costs recompute time, never correctness), and the thrash warning must
    fire when a working set recomputes past the threshold."""

    def test_forced_eviction_proof_byte_equal(self, monkeypatch):
        # k=11: ~25 distinct extended arrays of 256KB each + rolls, so a
        # 1 MB budget GUARANTEES eviction + recomputes during the quotient
        k = 11
        srs11 = SRS.unsafe_setup(k)
        cfg = CircuitConfig(k=k, num_advice=1, num_lookup_advice=1,
                            num_fixed=1, lookup_bits=4)
        advice, lookup, fixed, selectors, copies, out = _tiny_circuit(cfg)
        asg = Assignment(cfg, advice, lookup, fixed, selectors, [[out]], copies)
        pk = keygen(srs11, cfg, fixed, selectors, copies)

        def seeded():
            import random
            r = random.Random(0xFEED)
            return lambda: r.randrange(bn.R)

        monkeypatch.delenv("SPECTRE_QUOTIENT_CACHE_MB", raising=False)
        p_default = prove(pk, srs11, asg, blinding_rng=seeded())
        monkeypatch.setenv("SPECTRE_QUOTIENT_CACHE_MB", "1")
        p_evicting = prove(pk, srs11, asg, blinding_rng=seeded())
        assert p_default == p_evicting, \
            "LRU eviction changed proof bytes (recompute path diverges)"
        assert verify(pk.vk, srs11, [[out]], p_evicting)

    def test_thrash_warning_fires_once(self, monkeypatch, capsys):
        from spectre_tpu.plonk.prover import _BudgetedExtLRU
        arr = np.zeros((64, 4), dtype=np.uint64)   # 2KB
        lru = _BudgetedExtLRU(budget_bytes=3 * arr.nbytes)
        monkeypatch.setattr(_BudgetedExtLRU, "THRASH_WARN_THRESHOLD", 4)
        for round_ in range(3):
            for key in ("a", "b", "c", "d", "e"):   # 5 keys, 3 fit
                if lru.get(key) is None:
                    lru.put(key, arr)
        assert lru.recompute_count >= 4
        err = capsys.readouterr().err
        assert err.count("cache thrashing") == 1


class TestArrayCtxExtContract:
    """_ArrayCtx._ext is 'a mapping or callable cache' — the base class must
    honor BOTH (ADVICE r5: _quotient_host now passes a callable)."""

    def test_var_accepts_mapping_and_callable(self):
        from spectre_tpu.plonk.prover import _ArrayCtx

        class Bare:
            pass

        ctx = Bare()
        ctx._ext = {("adv", 0): "mapped"}
        assert _ArrayCtx.var(ctx, ("adv", 0), 0) == "mapped"
        ctx._ext = lambda key: ("called", key)
        assert _ArrayCtx.var(ctx, ("adv", 0), 0) == ("called", ("adv", 0))

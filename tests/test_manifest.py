"""Provenance-manifest tier (ISSUE 8): the proof flight recorder.

Pins the acceptance gates:
  * end to end: submit -> prove -> `getProofManifest` returns a
    manifest whose result digest matches `getProofResult`'s artifact,
    whose phase seconds agree with the `getTrace` span tree, and which
    survives a journal replay (digest-verified through the artifact
    store);
  * a second identical prove (same shapes, fresh params so dedup does
    not short-circuit) records ZERO new compile events — the jit-cache
    warmth signal;
  * queue-wait decomposition: the SAME float lands in the job record,
    the manifest and the `spectre_queue_wait_seconds` histogram;
  * RPC contract: -32004 unknown job, -32002 while live, -32006 when
    the manifest is absent/corrupt (the RESULT still serves);
  * the report CLI renders and diffs manifests from files and job ids.
"""

import json
import threading
import time
import urllib.request

import pytest

from spectre_tpu.observability import compilelog, manifest
from spectre_tpu.observability import metrics as M
from spectre_tpu.observability import tracing
from spectre_tpu.utils import faults
from spectre_tpu.utils.health import HEALTH
from spectre_tpu.utils import profiling as prof


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.clear()
    yield
    faults.clear()


# ---------------------------------------------------------------------------
# unit: event collector, LRU deltas, canonical encoding, render/diff
# ---------------------------------------------------------------------------


class TestCollector:
    def test_record_event_noop_without_collector(self):
        manifest.record_event("orphan", x=1)     # must not raise

    def test_collect_events_thread_local_and_nested(self):
        with manifest.collect_events() as outer:
            manifest.record_event("a")
            with manifest.collect_events() as inner:
                manifest.record_event("b", n=2)
            manifest.record_event("c")
        assert outer == [{"kind": "a"}, {"kind": "c"}]
        assert inner == [{"kind": "b", "n": 2}]

    def test_injected_faults_land_in_collecting_manifest(self):
        """The faults.add_observer hook: a fault that fires while a job
        collects becomes a manifest event (site + kind)."""
        faults.install_plan("widget.io:ioerror:1")
        with manifest.collect_events() as ev:
            with pytest.raises(OSError):
                faults.check("widget.io")
        assert {"kind": "fault", "site": "widget.io",
                "fault_kind": "ioerror"} in ev

    def test_mangle_faults_observed_too(self):
        faults.install_plan("blob.site:corrupt:1")
        with manifest.collect_events() as ev:
            out = faults.mangle("blob.site", b"\x00" * 8)
        assert out != b"\x00" * 8
        assert ev == [{"kind": "fault", "site": "blob.site",
                       "fault_kind": "corrupt"}]


class TestLruDelta:
    def test_delta_counters_and_final_occupancy(self):
        before = {"msm": {"hits": 2, "builds": 1, "evictions": 0,
                          "recomputes": 0, "bytes": 10, "entries": 1},
                  "ntt": None}
        after = {"msm": {"hits": 5, "builds": 2, "evictions": 1,
                         "recomputes": 0, "bytes": 30, "entries": 2},
                 "ntt": None}
        d = manifest.lru_delta(before, after)
        assert d["msm"] == {"hits": 3, "builds": 1, "evictions": 1,
                            "recomputes": 0, "bytes": 30, "entries": 2}
        assert d["ntt"] is None

    def test_cache_loaded_mid_job_counts_from_zero(self):
        after = {"msm": {"hits": 1, "builds": 1, "evictions": 0,
                         "recomputes": 0, "bytes": 8, "entries": 1},
                 "ntt": None}
        d = manifest.lru_delta({"msm": None, "ntt": None}, after)
        assert d["msm"]["builds"] == 1


class TestEncoding:
    def _man(self):
        return manifest.build(
            job_id="job-1", method="m", witness_digest="ab" * 32,
            attempts=1, submitted=10.0, admitted=10.5, started=11.0,
            finished=14.0, queue_wait_s=0.5,
            events=[{"kind": "cpu_fallback", "fallback_kind": "oom"}],
            compile_events=[{"event": "backend_compile",
                             "fn": "prove/quotient", "seconds": 2.25}],
            peak_rss_mb=123.4, result_digest="cd" * 32)

    def test_round_trip_byte_stable(self):
        man = self._man()
        raw = manifest.to_bytes(man)
        again = manifest.from_bytes(raw)
        assert again == man
        assert manifest.to_bytes(again) == raw       # canonical: stable

    def test_prove_seconds_derived(self):
        man = self._man()
        assert man["prove_s"] == pytest.approx(3.0)
        assert man["compile"]["count"] == 1
        assert man["compile"]["by_fn"]["prove/quotient"]["seconds"] == 2.25

    def test_env_knobs_always_keyed(self):
        man = self._man()
        assert set(manifest.ENV_KNOBS) <= set(man["env"])

    def test_from_bytes_rejects_wrong_schema(self):
        with pytest.raises(ValueError, match="not a "):
            manifest.from_bytes(b'{"schema": "something/else"}')
        with pytest.raises(ValueError, match="not a "):
            manifest.from_bytes(b'[1, 2]')

    def test_render_mentions_the_load_bearing_facts(self):
        text = manifest.render(self._man())
        assert "job-1" in text
        assert "queue wait" in text and "0.500s" in text
        assert "prove" in text and "3.000s" in text
        assert "cpu_fallback" in text
        assert "prove/quotient" in text

    def test_diff_surfaces_regressions_and_knob_flips(self):
        a = self._man()
        b = json.loads(json.dumps(a))
        b["job_id"] = "job-2"
        b["prove_s"] = 9.0
        b["compile"] = {"count": 3, "seconds": 5.5, "by_fn": {},
                        "events": []}
        b["env"] = dict(a["env"], SPECTRE_MSM_MODE="glv")
        text = manifest.diff(a, b)
        assert "job-1 -> job-2" in text
        assert "+6.000s" in text                     # prove regression
        assert "compile count: 1 -> 3" in text
        assert "env.SPECTRE_MSM_MODE" in text


class TestCompilelog:
    def test_summarize_counts_backend_compile_only(self):
        events = [
            {"event": "jaxpr_trace", "fn": "p", "seconds": 0.1},
            {"event": "jaxpr_to_mlir_module", "fn": "p", "seconds": 0.2},
            {"event": "backend_compile", "fn": "p", "seconds": 1.5},
            {"event": "backend_compile", "fn": "q", "seconds": 0.5},
        ]
        s = compilelog.summarize(events)
        assert s["count"] == 2                       # not 4
        assert s["seconds"] == pytest.approx(2.0)
        assert s["by_fn"] == {"p": {"count": 1, "seconds": 1.5},
                              "q": {"count": 1, "seconds": 0.5}}
        assert len(s["events"]) == 4                 # sub-steps retained

    def test_listener_attributes_to_innermost_span(self):
        """Drive the listener directly (no jax needed): the event must
        hit the capture sink, the trace tree AND the fn-labelled
        histogram with the SAME rounded value."""
        M.COMPILE_SECONDS.reset()
        with tracing.trace("t-compile") as tr:
            with prof.phase("prove/commit_advice"):
                with compilelog.capture() as cev:
                    compilelog._listener(
                        "/jax/core/compile/backend_compile_duration",
                        0.123456789)
        assert cev == [{"event": "backend_compile",
                        "fn": "prove/commit_advice",
                        "seconds": 0.123457}]
        kids = M.COMPILE_SECONDS.children()
        assert [k.labels for k in kids] == [{"fn": "prove/commit_advice"}]
        assert kids[0].snapshot()["sum"] == 0.123457  # exact: same float
        names = [e["name"] for e in
                 tracing.chrome_trace(tr)["traceEvents"]]
        assert "compile/backend_compile" in names

    def test_listener_ignores_foreign_events(self):
        with compilelog.capture() as cev:
            compilelog._listener("/jax/core/something_else", 1.0)
        assert cev == []

    def test_persistent_cache_events_counted(self):
        """The plain-event listener (ISSUE 13): persistent compile-cache
        hit/miss events land in the capture sink and in summarize()'s
        persistent_cache key — the bench-multichip 'warm disk cache vs
        genuinely recompiled' signal."""
        before = compilelog.cache_counts()
        with compilelog.capture() as cev:
            compilelog._event_listener("/jax/compilation_cache/cache_hits")
            compilelog._event_listener("/jax/compilation_cache/cache_hits")
            compilelog._event_listener("/jax/compilation_cache/cache_misses")
            compilelog._event_listener("/jax/unrelated/event")
        assert [e["event"] for e in cev] == [
            "persistent_cache_hit", "persistent_cache_hit",
            "persistent_cache_miss"]
        s = compilelog.summarize(cev)
        assert s["persistent_cache"] == {"hit": 2, "miss": 1}
        assert s["count"] == 0            # cache events are not compiles
        after = compilelog.cache_counts()
        assert after["hit"] == before["hit"] + 2
        assert after["miss"] == before["miss"] + 1

    def test_unattributed_outside_any_span(self):
        with compilelog.capture() as cev:
            compilelog._listener(
                "/jax/core/compile/backend_compile_duration", 0.5)
        assert cev[0]["fn"] == compilelog.UNATTRIBUTED


# ---------------------------------------------------------------------------
# end to end through the JobQueue
# ---------------------------------------------------------------------------


def _runner(method, params):
    with prof.phase("prove/commit_advice"):
        time.sleep(0.002)
    with prof.phase("prove/quotient"):
        manifest.record_event("msm_fixed_degraded", n=64, window=4)
    return {"proof": "0xab", "w": params.get("w")}


def _mk(tmp_path, runner=_runner, **kw):
    from spectre_tpu.prover_service.jobs import JobQueue
    kw.setdefault("concurrency", 1)
    return JobQueue(runner, journal_dir=str(tmp_path), **kw)


class TestQueueManifest:
    def test_end_to_end_manifest_pin(self, tmp_path):
        """THE acceptance pin: digests match the result artifact, phase
        seconds agree with the getTrace span tree, queue wait has exact
        three-sink parity, and the manifest survives journal replay."""
        M.QUEUE_WAIT.reset()
        q = _mk(tmp_path)
        jid = q.submit("m", {"w": 1})
        job = q.wait(jid, timeout=10)
        assert job.status == "done"
        assert job.manifest_digest is not None

        man = q.manifest(jid)
        assert man is not None
        assert man["schema"] == manifest.SCHEMA
        assert man["job_id"] == jid
        assert man["witness_digest"] == job.digest
        # result digest matches the artifact getProofResult re-verifies
        assert man["result_digest"] == job.result_digest
        assert q.store.read(man["result_digest"]) is not None

        # phase seconds: same numbers the getTrace span tree yields
        tr = tracing.get_trace(jid)
        assert tr is not None
        assert man["phase_seconds"] == tracing.phase_seconds(tr)
        assert man["phase_seconds"]["prove/commit_advice"] >= 0.002

        # queue-wait: one float, three sinks, exact parity
        snap = M.QUEUE_WAIT.snapshot()
        assert snap["count"] == 1
        assert snap["sum"] == job.queue_wait_s == man["queue_wait_s"]
        ts = man["timestamps"]
        assert ts["submitted"] <= ts["admitted"] <= ts["started"] \
            <= ts["finished"]
        assert man["prove_s"] == pytest.approx(
            ts["finished"] - ts["started"], abs=1e-6)

        # the degrade event recorded inside the runner landed
        assert {"kind": "msm_fixed_degraded", "n": 64, "window": 4} \
            in man["events"]
        # the journal carries the digest, not the manifest body
        recs = [json.loads(ln) for ln in open(q.journal.path)]
        done = [r for r in recs if r.get("event") == "done"]
        assert done[0]["manifest_digest"] == job.manifest_digest
        assert all("phase_seconds" not in r for r in recs)
        q.stop()

        # replay: a fresh queue serves the byte-identical manifest
        q2 = _mk(tmp_path)
        j2 = q2.result(jid)
        assert j2.status == "done"
        assert j2.manifest_digest == job.manifest_digest
        assert j2.queue_wait_s is None       # not replayed: manifest has it
        assert q2.manifest(jid) == man
        q2.stop()

    def test_failed_jobs_get_manifests_too(self, tmp_path):
        def boom(method, params):
            with prof.phase("prove/commit_advice"):
                raise ValueError("witness is cursed")

        q = _mk(tmp_path, runner=boom)
        jid = q.submit("m", {"w": 2})
        job = q.wait(jid, timeout=10)
        assert job.status == "failed"
        man = q.manifest(jid)
        assert man is not None
        assert man["error"] == "ValueError: witness is cursed"
        assert man["result_digest"] is None
        assert "prove/commit_advice" in man["phase_seconds"]
        q.stop()

    def test_compact_preserves_manifest_digest_and_admitted(self, tmp_path):
        q = _mk(tmp_path)
        jid = q.submit("m", {"w": 3})
        job = q.wait(jid, timeout=10)
        man = q.manifest(jid)
        q.journal.compact(list(q._jobs.values()))
        q.stop()
        q2 = _mk(tmp_path)
        j2 = q2.result(jid)
        assert j2.manifest_digest == job.manifest_digest
        assert j2.admitted_at is not None
        assert q2.manifest(jid) == man
        q2.stop()

    def test_missing_manifest_artifact_still_serves_result(self, tmp_path):
        """A journaled job whose manifest artifact is GONE (disk cleanup,
        partial restore) still serves its result; the manifest degrades
        to absent with a counted read failure."""
        import os
        q = _mk(tmp_path)
        jid = q.submit("m", {"w": 4})
        job = q.wait(jid, timeout=10)
        assert job.status == "done"
        path = q.store.path_for(job.manifest_digest,
                                manifest.MANIFEST_SUFFIX)
        q.stop()
        os.remove(path)
        r0 = HEALTH.get("manifest_read_failures")
        q2 = _mk(tmp_path)
        res = q2.result(jid)
        assert res.status == "done" and res.result["proof"] == "0xab"
        assert q2.manifest(jid) is None
        assert HEALTH.get("manifest_read_failures") == r0 + 1
        q2.stop()

    def test_corrupt_manifest_artifact_quarantined_not_served(self, tmp_path):
        q = _mk(tmp_path)
        jid = q.submit("m", {"w": 5})
        job = q.wait(jid, timeout=10)
        path = q.store.path_for(job.manifest_digest,
                                manifest.MANIFEST_SUFFIX)
        with open(path, "r+b") as f:                 # flip one byte
            b = bytearray(f.read())
            b[len(b) // 2] ^= 0xFF
            f.seek(0)
            f.write(bytes(b))
        qn0 = HEALTH.get("artifacts_quarantined")
        assert q.manifest(jid) is None               # verification failed
        assert HEALTH.get("artifacts_quarantined") == qn0 + 1
        assert q.result(jid).status == "done"        # result unaffected
        q.stop()

    def test_crash_then_replay_manifest_from_rerun(self, tmp_path):
        """A worker killed mid-prove (InjectedCrash) writes NO manifest;
        the journal replay re-runs the job and the re-run writes one —
        the crash-recovery acceptance extended to provenance."""
        import threading as _t

        def runner(method, params):
            faults.check("backend.prove")
            return {"proof": "0xcd"}

        q = _mk(tmp_path, runner=runner)
        faults.install_plan("backend.prove:crash:1")
        old_hook = _t.excepthook
        _t.excepthook = lambda args: None
        try:
            jid = q.submit("m", {"w": 6})
            deadline = time.time() + 10
            while time.time() < deadline:
                st = q.status(jid)
                if st["status"] == "running" and not any(
                        w.is_alive() for w in q._workers):
                    break
                time.sleep(0.01)
            else:
                pytest.fail("worker did not crash")
        finally:
            _t.excepthook = old_hook
        assert q.manifest(jid) is None               # crash wrote nothing
        q2 = _mk(tmp_path, runner=runner)
        job = q2.wait(jid, timeout=10)
        assert job.status == "done"
        man = q2.manifest(jid)
        assert man is not None
        assert man["result_digest"] == job.result_digest
        q2.stop()


# ---------------------------------------------------------------------------
# jit-cache warmth: a second identical prove compiles NOTHING
# ---------------------------------------------------------------------------

_JITTED = None


def _jit_fn():
    """One process-lifetime jitted callable: the second call with the
    same shape/dtype must be an XLA cache hit."""
    global _JITTED
    if _JITTED is None:
        import jax
        _JITTED = jax.jit(lambda a: a * a + 1.0)
    return _JITTED


def _jit_runner(method, params):
    import jax.numpy as jnp
    with prof.phase("prove/commit_advice"):
        val = _jit_fn()(jnp.float32(params["x"]))
    return {"proof": float(val)}


class TestCompileWarmth:
    def test_second_prove_records_zero_compiles(self, tmp_path):
        """Acceptance: two proves with DIFFERENT params (dedup must not
        short-circuit) but identical shapes — the first manifest records
        the backend compile, the second records zero compile events."""
        if not compilelog.install():
            pytest.skip("jax.monitoring unavailable in this process")
        q = _mk(tmp_path, runner=_jit_runner)
        j1 = q.submit("m", {"x": 1.5})
        assert q.wait(j1, timeout=60).status == "done"
        j2 = q.submit("m", {"x": 2.5})
        assert j2 != j1                              # fresh witness digest
        assert q.wait(j2, timeout=60).status == "done"
        m1, m2 = q.manifest(j1), q.manifest(j2)
        # the first prove MAY be warm too (another test already traced
        # this exact function); the second must ALWAYS be
        if m1["compile"]["count"]:
            assert m1["compile"]["by_fn"]["prove/commit_advice"]["count"] >= 1
        assert m2["compile"]["count"] == 0
        assert m2["compile"]["events"] == []
        q.stop()


# ---------------------------------------------------------------------------
# RPC + client + report CLI
# ---------------------------------------------------------------------------


def _rpc(port, method, params, id_=1, timeout=30):
    body = json.dumps({"jsonrpc": "2.0", "id": id_, "method": method,
                       "params": params}).encode()
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/rpc", data=body,
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.load(resp)


def _serve(tmp_path, runner):
    from spectre_tpu.prover_service.jobs import ensure_jobs
    from spectre_tpu.prover_service.rpc import serve

    class S:                                         # minimal state shim
        concurrency = 1
        params_dir = str(tmp_path)

    state = S()
    ensure_jobs(state, runner=runner)
    server = serve(state, port=0, background=True)
    return server, server.server_address[1], state


class TestManifestRpc:
    def test_contract_and_client(self, tmp_path):
        gate, started = threading.Event(), threading.Event()

        def runner(method, params):
            with prof.phase("prove/commit_advice"):
                started.set()
                gate.wait(10)
            return {"proof": "0xab"}

        server, port, state = _serve(tmp_path, runner)
        try:
            jid = _rpc(port, "submitProof_SyncStepCompressed",
                       {"w": 1})["result"]["job_id"]
            assert started.wait(10)
            # live -> -32002; unknown -> -32004
            err = _rpc(port, "getProofManifest", {"job_id": jid})["error"]
            assert err["code"] == -32002
            err = _rpc(port, "getProofManifest", {"job_id": "nope"})["error"]
            assert err["code"] == -32004
            gate.set()
            assert state.jobs.wait(jid, timeout=10).status == "done"

            man = _rpc(port, "getProofManifest", {"job_id": jid})["result"]
            assert man["schema"] == manifest.SCHEMA
            res = _rpc(port, "getProofResult", {"job_id": jid})["result"]
            assert res == {"proof": "0xab"}
            # manifest digest is checkably about THESE result bytes
            job = state.jobs.result(jid)
            assert man["result_digest"] == job.result_digest

            from spectre_tpu.prover_service.rpc_client import ProverClient
            cli = ProverClient(f"http://127.0.0.1:{port}/rpc")
            assert cli.get_manifest(jid) == man

            # corrupt the stored artifact -> -32006, result still serves
            path = state.jobs.store.path_for(job.manifest_digest,
                                             manifest.MANIFEST_SUFFIX)
            with open(path, "wb") as f:
                f.write(b"rotten bytes")
            err = _rpc(port, "getProofManifest", {"job_id": jid})["error"]
            assert err["code"] == -32006
            assert _rpc(port, "getProofResult",
                        {"job_id": jid})["result"] == {"proof": "0xab"}
        finally:
            gate.set()
            state.jobs.stop()
            server.shutdown()


class TestReportCli:
    def _write(self, tmp_path, name, **over):
        kw = dict(job_id=name, method="m",
                  submitted=1.0, admitted=1.1, started=1.2,
                  finished=3.2, queue_wait_s=0.1)
        kw.update(over)
        man = manifest.build(**kw)
        p = tmp_path / f"{name}.manifest.json"
        p.write_bytes(manifest.to_bytes(man))
        return p

    def test_render_from_file(self, tmp_path, capsys):
        from spectre_tpu.observability.__main__ import main
        p = self._write(tmp_path, "job-a")
        assert main(["report", str(p)]) == 0
        out = capsys.readouterr().out
        assert "job-a" in out and "queue wait" in out

    def test_diff_two_files(self, tmp_path, capsys):
        from spectre_tpu.observability.__main__ import main
        pa = self._write(tmp_path, "job-a")
        pb = self._write(tmp_path, "job-b", peak_rss_mb=64.0)
        assert main(["report", str(pa), "--diff", str(pb)]) == 0
        out = capsys.readouterr().out
        assert "diff job-a -> job-b" in out

    def test_fetch_by_job_id_over_rpc(self, tmp_path, capsys):
        from spectre_tpu.observability.__main__ import main
        server, port, state = _serve(tmp_path, _runner)
        try:
            jid = state.jobs.submit("m", {"w": 9})
            assert state.jobs.wait(jid, timeout=10).status == "done"
            rc = main(["report", jid,
                       "--url", f"http://127.0.0.1:{port}/rpc"])
            assert rc == 0
            out = capsys.readouterr().out
            assert jid in out and "prove" in out
        finally:
            state.jobs.stop()
            server.shutdown()

    def test_ci_gate_passes_within_thresholds(self, tmp_path, capsys):
        """ISSUE 10 satellite: `report BASELINE --diff CANDIDATE --ci`
        exits 0 when the candidate stays inside the regression budget."""
        from spectre_tpu.observability.__main__ import main
        base = self._write(tmp_path, "base")                 # prove_s 2.0
        cand = self._write(tmp_path, "cand", finished=3.3)   # +5%
        assert main(["report", str(base), "--diff", str(cand),
                     "--ci"]) == 0
        assert "CI gate: ok" in capsys.readouterr().out

    def test_ci_gate_fails_on_prove_regression(self, tmp_path, capsys):
        from spectre_tpu.observability.__main__ import main
        base = self._write(tmp_path, "base")                 # prove_s 2.0
        cand = self._write(tmp_path, "cand", finished=3.7)   # +25%
        assert main(["report", str(base), "--diff", str(cand),
                     "--ci"]) == 3
        assert "prove_s regressed" in capsys.readouterr().out
        # a loosened threshold admits the same candidate
        assert main(["report", str(base), "--diff", str(cand),
                     "--ci", "--max-prove-regress", "0.5"]) == 0

    def test_ci_gate_fails_on_new_compiles(self, tmp_path, capsys):
        """A compile on the warm path is a cache regression even when
        wall time still squeaks under the prove_s threshold."""
        from spectre_tpu.observability.__main__ import main
        base = self._write(tmp_path, "base")
        cand = self._write(
            tmp_path, "cand",
            compile_events=[{"event": compilelog.BACKEND_COMPILE,
                             "fn": "prove", "seconds": 0.5}])
        assert main(["report", str(base), "--diff", str(cand),
                     "--ci"]) == 3
        assert "compile.count regressed" in capsys.readouterr().out
        assert main(["report", str(base), "--diff", str(cand), "--ci",
                     "--max-compile-count-increase", "1"]) == 0

    def test_ci_requires_diff(self, tmp_path, capsys):
        from spectre_tpu.observability.__main__ import main
        base = self._write(tmp_path, "base")
        assert main(["report", str(base), "--ci"]) == 2


# ---------------------------------------------------------------------------
# bench: compile telemetry rides along, floors still gate run time only
# ---------------------------------------------------------------------------


@pytest.mark.skipif(not __import__("os").environ.get("RUN_SLOW"),
                    reason="runs the full bench-fast tier (set RUN_SLOW=1)")
def test_bench_fast_floors_clear_with_compile_hook(tmp_path):
    """ISSUE-8 satellite pin: `bench.py --fast` with the compilelog hook
    installed still clears the checked-in msm/ntt floors (the hook must
    not slow the gated run loop), and every record carries
    `compile_seconds` SEPARATELY from the floor-gated throughput."""
    import os
    import subprocess
    import sys
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, "bench.py", "--fast"], env=env,
        capture_output=True, text=True, timeout=600,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    assert proc.returncode == 0, proc.stderr[-2000:]
    records = [json.loads(ln) for ln in proc.stdout.splitlines() if ln]
    assert len(records) >= 2                         # msm + ntt
    for rec in records:
        assert rec.get("regression") is False, rec   # floors clear
        assert rec["compile_seconds"] >= 0.0
        assert rec["compile_count"] >= 0
        # gated value is throughput, not wall time including compiles
        assert rec["value"] > 0

"""GLV decomposition, signed-digit recoding, and MSM mode equivalence.

The contract every mode must honor: identical group element out (the
commitment byte-equality gate rides on this), only the work shape differs.
"""

import secrets

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from spectre_tpu.fields import bn254 as bn
from spectre_tpu.ops import ec, glv, limbs as L, msm as MSM


def _edge_scalars():
    lam = glv.lam()
    return [0, 1, 2, bn.R - 1, bn.R - 2, lam, bn.R - lam, lam - 1,
            (bn.R - 1) // 2, 1 << 128, (1 << 253) - 1]


class TestGLVDecompose:
    def test_recomposes_mod_r(self):
        lam = glv.lam()
        for k in _edge_scalars() + [secrets.randbelow(bn.R)
                                    for _ in range(64)]:
            k1, k2 = glv.decompose(k)
            assert (k1 + k2 * lam) % bn.R == k % bn.R, k

    def test_half_scalars_bounded(self):
        bound = 1 << glv.glv_bits()
        assert glv.glv_bits() <= 16 * glv.HALF_LIMBS
        for k in _edge_scalars() + [secrets.randbelow(bn.R)
                                    for _ in range(64)]:
            k1, k2 = glv.decompose(k)
            assert -bound < k1 < bound and -bound < k2 < bound, k

    def test_batch_matches_scalar_path(self):
        ks = _edge_scalars() + [secrets.randbelow(bn.R) for _ in range(16)]
        a1, a2, n1, n2 = glv.decompose_batch(ks)
        for i, k in enumerate(ks):
            k1, k2 = glv.decompose(k)
            assert bool(n1[i]) == (k1 < 0) and bool(n2[i]) == (k2 < 0), k
            assert sum(int(a1[i, j]) << (16 * j)
                       for j in range(glv.HALF_LIMBS)) == abs(k1)
            assert sum(int(a2[i, j]) << (16 * j)
                       for j in range(glv.HALF_LIMBS)) == abs(k2)

    def test_sign_flip_cases(self):
        """Full-size scalars hit every half-scalar sign combination (small
        scalars decompose trivially to k1=k, k2=0 — the generator must span
        the whole of Fr)."""
        seen = set()
        g = bn.FR_GENERATOR
        for k in range(1, 256):
            k1, k2 = glv.decompose(pow(g, k, bn.R))
            seen.add((k1 < 0, k2 < 0))
            if len(seen) == 4:
                break
        assert len(seen) == 4, f"only sign patterns {seen} exercised"

    def test_endo_matches_lambda_mul(self):
        pts = [bn.g1_curve.mul(bn.G1_GEN, 3 * i + 2) for i in range(4)]
        pts.append(None)     # phi fixes infinity
        got = ec.decode_points(jax.jit(ec.endo)(ec.encode_points(pts)))
        lam = glv.lam()
        for p, g in zip(pts, got):
            want = bn.g1_curve.mul(p, lam) if p is not None else None
            want = None if want is None else (int(want[0]), int(want[1]))
            assert g == want


class TestGLVDeviceDecompose:
    """The traced on-device Babai rounding (glv.decompose_device) must be
    BIT-EXACT against the host decompose_batch — magnitudes AND signs —
    or pallas/xla proofs silently diverge."""

    def _device_vs_host(self, ks):
        limbs = np.asarray(L.ints_to_limbs16(ks), dtype=np.uint32)
        a1h, a2h, n1h, n2h = glv.decompose_batch(ks)
        a1d, a2d, n1d, n2d = (np.asarray(v) for v in
                              glv.decompose_device(jnp.asarray(limbs)))
        assert np.array_equal(a1d, a1h) and np.array_equal(a2d, a2h)
        assert np.array_equal(n1d.astype(bool), np.asarray(n1h))
        assert np.array_equal(n2d.astype(bool), np.asarray(n2h))

    def test_boundary_scalars(self):
        self._device_vs_host(_edge_scalars())

    def test_randomized_sweep(self):
        self._device_vs_host([secrets.randbelow(bn.R) for _ in range(64)])

    def test_babai_rounding_edges(self):
        """Scalars engineered near the floor-division rounding boundary:
        the device path computes c_i = floor((2k*b + R) / 2R) by exact
        Barrett division, so k values that put 2k*b + R within a few
        multiples of R of a 2R boundary are the worst case for an
        off-by-one (these are exactly where an inexact reciprocal
        approximation would break)."""
        (a1, b1), (a2, b2) = glv._constants()[2]
        edges = []
        for bb in (b2, -b1):
            for q in (1, 2, (1 << 125) // 7, (1 << 126) // 3):
                # 2k*bb + R ~= q*2R  ->  k ~= (2q - 1)*R / (2*bb)
                k0 = ((2 * q - 1) * bn.R) // (2 * bb)
                for d in (-2, -1, 0, 1, 2):
                    k = (k0 + d) % bn.R
                    edges.append(k)
        self._device_vs_host(edges)

    def test_device_split_feeds_msm_paths(self):
        """_glv_scalars_device output recomposes to k mod R through the
        lambda relation (the property every GLV MSM mode relies on)."""
        lam = glv.lam()
        ks = _edge_scalars()[:6] + [secrets.randbelow(bn.R)
                                    for _ in range(4)]
        sc2, neg = MSM._glv_scalars_device(
            jnp.asarray(np.asarray(L.ints_to_limbs16(ks),
                                   dtype=np.uint32)))
        sc2, neg = np.asarray(sc2), np.asarray(neg)
        n = len(ks)
        for i, k in enumerate(ks):
            k1 = sum(int(sc2[i, j]) << (16 * j)
                     for j in range(glv.HALF_LIMBS))
            k2 = sum(int(sc2[n + i, j]) << (16 * j)
                     for j in range(glv.HALF_LIMBS))
            if neg[i]:
                k1 = -k1
            if neg[n + i]:
                k2 = -k2
            assert (k1 + k2 * lam) % bn.R == k % bn.R, k


class TestSignedDigits:
    @pytest.mark.parametrize("c", [4, 8, 11, 13])
    def test_roundtrip_and_range(self, c):
        nbits = glv.glv_bits()
        nwin = (nbits + c) // c
        vals = [0, 1, (1 << nbits) - 1, 1 << (c - 1), (1 << c) - 1] + \
            [secrets.randbelow(1 << nbits) for _ in range(16)]
        limbs = np.zeros((len(vals), glv.HALF_LIMBS), np.uint32)
        for i, v in enumerate(vals):
            for j in range(glv.HALF_LIMBS):
                limbs[i, j] = (v >> (16 * j)) & 0xFFFF
        digs = np.asarray(MSM.signed_digit_stream(jnp.asarray(limbs), c, nwin))
        half = 1 << (c - 1)
        assert digs.min() >= -half + 1 and digs.max() <= half
        for i, v in enumerate(vals):
            back = sum(int(digs[w, i]) << (c * w) for w in range(nwin))
            assert back == v, (c, v)

    def test_matches_unsigned_stream(self):
        """The signed stream is a recoding OF the unsigned digit stream:
        summing both must agree (round-trip through the same scalar)."""
        import jax
        c, nbits = 10, glv.glv_bits()
        nwin_u = (nbits + c - 1) // c
        nwin_s = (nbits + c) // c
        k = secrets.randbelow(1 << nbits)
        limbs = np.zeros((1, glv.HALF_LIMBS), np.uint32)
        for j in range(glv.HALF_LIMBS):
            limbs[0, j] = (k >> (16 * j)) & 0xFFFF
        arr = jnp.asarray(limbs)
        from spectre_tpu.ops import field_ops as F
        unsigned = [int(np.asarray(
            jax.jit(lambda a, w=w: F.limb_digits(a, w, c))(arr))[0])
            for w in range(nwin_u)]
        signed = np.asarray(MSM.signed_digit_stream(arr, c, nwin_s))[:, 0]
        assert sum(d << (c * w) for w, d in enumerate(unsigned)) == \
            sum(int(d) << (c * w) for w, d in enumerate(signed)) == k


class TestMSMModes:
    def _inputs(self, n=48):
        pts = [bn.g1_curve.mul(bn.G1_GEN, secrets.randbelow(bn.R))
               for _ in range(n)]
        pts[3] = None
        scalars = [secrets.randbelow(bn.R) for _ in range(n)]
        scalars[0] = 0
        scalars[1] = 1
        scalars[2] = bn.R - 1
        want = bn.g1_curve.msm(pts, scalars)
        return (ec.encode_points(pts), jnp.asarray(L.ints_to_limbs16(scalars)),
                (int(want[0]), int(want[1])))

    @pytest.mark.parametrize("mode", MSM.MSM_MODES)
    def test_matches_oracle(self, mode):
        pp, ss, want = self._inputs()
        got = ec.decode_points(MSM.msm(pp, ss, mode=mode)[None])[0]
        assert got == want, mode

    @pytest.mark.parametrize("mode", MSM.MSM_MODES)
    def test_all_zero_is_identity(self, mode):
        pts = [bn.g1_curve.mul(bn.G1_GEN, k + 1) for k in range(8)]
        pp = ec.encode_points(pts)
        ss = jnp.asarray(L.ints_to_limbs16([0] * 8))
        assert ec.decode_points(MSM.msm(pp, ss, mode=mode)[None])[0] is None

    def test_env_mode_dispatch(self, monkeypatch):
        monkeypatch.setenv("SPECTRE_MSM_MODE", "glv+signed")
        assert MSM.msm_mode() == "glv+signed"
        monkeypatch.setenv("SPECTRE_MSM_MODE", "bogus")
        with pytest.raises(ValueError):
            MSM.msm_mode()

    def test_batch_modes_match_single(self):
        n, m = 24, 3
        pts = [bn.g1_curve.mul(bn.G1_GEN, k + 1) for k in range(n)]
        pp = ec.encode_points(pts)
        scs = [[(i * 131 + k * 7 + 1) % bn.R for k in range(n)]
               for i in range(m)]
        batch = jnp.stack([jnp.asarray(L.ints_to_limbs16(sc)) for sc in scs])
        for mode in ("glv", "glv+signed", "fixed"):
            got = ec.decode_points(MSM.msm_batch(pp, batch, mode=mode))
            for sc, g_pt in zip(scs, got):
                want = bn.g1_curve.msm(pts, sc)
                assert g_pt == (int(want[0]), int(want[1])), mode


class TestFixedTableCache:
    def test_hit_and_key_separation(self):
        pts = ec.encode_points(
            [bn.g1_curve.mul(bn.G1_GEN, k + 1) for k in range(8)])
        ss = jnp.asarray(L.ints_to_limbs16([k * 3 + 1 for k in range(8)]))
        MSM.msm(pts, ss, mode="fixed", base_key="t-cache-a")
        builds0, hits0 = MSM._TABLES.builds, MSM._TABLES.hits
        MSM.msm(pts, ss, mode="fixed", base_key="t-cache-a")
        assert MSM._TABLES.hits == hits0 + 1
        assert MSM._TABLES.builds == builds0
        # a different base key must NOT hit the same table
        MSM.msm(pts, ss, mode="fixed", base_key="t-cache-b")
        assert MSM._TABLES.builds == builds0 + 1

    def test_budget_passthrough_uncached(self, monkeypatch):
        tiny = MSM._TableLRU(1024)     # 1 KB: every table passes through
        table = jnp.zeros((4, 8, 3, 16), dtype=jnp.uint32)
        out = tiny.put(("k",), None, table)
        assert out is table
        assert tiny.get(("k",), None) is None   # nothing retained


class TestDefaultWindowTuning:
    def test_pinned_unsigned(self):
        assert [MSM.default_window(n) for n in
                (1 << 6, 1 << 7, 1 << 12, 1 << 16, 1 << 18)] == \
            [4, 7, 10, 10, 13]

    def test_pinned_signed(self):
        # signed digits halve the bucket array -> each size class affords
        # one larger window (the tuning-table change this PR pins)
        assert [MSM.default_window(n, signed=True) for n in
                (1 << 6, 1 << 7, 1 << 12, 1 << 16, 1 << 17, 1 << 18)] == \
            [5, 8, 11, 11, 11, 13]

    def test_fixed_follows_signed(self):
        for n in (1 << 7, 1 << 12, 1 << 17, 1 << 20):
            assert MSM.default_window_fixed(n) == \
                MSM.default_window(n, signed=True)

    def test_pinned_pallas(self):
        # pallas buckets are VMEM-resident: 254-bit vanilla scalars double
        # nwin vs GLV, so the 2^18 class drops 13 -> 11 (~4.5 MB resident
        # vs ~15 MB); the 126-bit signed paths fit their XLA widths.
        assert [MSM.default_window_pallas(n) for n in
                (1 << 6, 1 << 7, 1 << 12, 1 << 18)] == [4, 7, 10, 11]
        assert [MSM.default_window_pallas(n, signed=True) for n in
                (1 << 6, 1 << 7, 1 << 12, 1 << 18)] == [5, 8, 11, 13]
        # every pallas width actually fits the budget
        for signed, nbits in ((False, 254), (True, 126)):
            for n in (1 << 6, 1 << 12, 1 << 18):
                c = MSM.default_window_pallas(n, signed=signed)
                assert MSM._pallas_bucket_bytes(c, nbits) <= \
                    MSM._PALLAS_BUCKET_VMEM_BUDGET

    def test_pallas_override_wins(self, monkeypatch):
        monkeypatch.setenv("SPECTRE_MSM_WINDOW", "12")
        # the sweep knob must reach the pallas dispatch too, even past the
        # VMEM table (a real-hardware sweep needs to probe beyond the cap)
        assert MSM.default_window_pallas(1 << 18) == 12


class TestWindowOverride:
    """SPECTRE_MSM_WINDOW: one env knob retunes every MSM path (the value
    a bench.py --sweep-window run picks on real hardware)."""

    def test_override_wins_over_tables(self, monkeypatch):
        monkeypatch.setenv("SPECTRE_MSM_WINDOW", "9")
        assert MSM.window_override() == 9
        for n in (1 << 6, 1 << 12, 1 << 18):
            assert MSM.default_window(n) == 9
            assert MSM.default_window(n, signed=True) == 9
            assert MSM.default_window_fixed(n) == 9

    def test_unset_and_empty_mean_autotune(self, monkeypatch):
        monkeypatch.delenv("SPECTRE_MSM_WINDOW", raising=False)
        assert MSM.window_override() is None
        monkeypatch.setenv("SPECTRE_MSM_WINDOW", "")
        assert MSM.window_override() is None
        assert MSM.default_window(1 << 12) == 10     # table still pinned

    @pytest.mark.parametrize("bad", ["0", "14", "-3"])
    def test_out_of_range_rejected(self, bad, monkeypatch):
        monkeypatch.setenv("SPECTRE_MSM_WINDOW", bad)
        with pytest.raises(ValueError):
            MSM.window_override()

    def test_override_result_unchanged(self, monkeypatch):
        """An overridden window changes the work shape, never the point."""
        pts = ec.encode_points(
            [bn.g1_curve.mul(bn.G1_GEN, 3 * k + 1) for k in range(8)])
        ss = jnp.asarray(L.ints_to_limbs16([k * 5 + 2 for k in range(8)]))
        want = np.asarray(MSM.msm(pts, ss, mode="vanilla"))
        monkeypatch.setenv("SPECTRE_MSM_WINDOW", "3")
        got = np.asarray(MSM.msm(pts, ss, mode="vanilla"))
        assert ec.decode_points(jnp.asarray(got)[None]) == \
            ec.decode_points(jnp.asarray(want)[None])


class TestImplDispatch:
    """SPECTRE_MSM_IMPL: xla (default) vs the pallas SoA kernel path."""

    def test_env_validation(self, monkeypatch):
        monkeypatch.delenv("SPECTRE_MSM_IMPL", raising=False)
        assert MSM.msm_impl() == "xla"
        monkeypatch.setenv("SPECTRE_MSM_IMPL", "pallas")
        assert MSM.msm_impl() == "pallas"
        monkeypatch.setenv("SPECTRE_MSM_IMPL", "cuda")
        with pytest.raises(ValueError):
            MSM.msm_impl()

    def test_pallas_routes_vanilla(self, monkeypatch):
        from spectre_tpu.ops import msm_pallas as MP
        calls = []
        wins_sentinel = object()
        out_sentinel = jnp.zeros((3, 16), dtype=jnp.uint32)
        monkeypatch.setattr(
            MP, "msm_bucket_windows",
            lambda soa, sc, neg, c, nbits:
                calls.append((soa.shape, neg, int(c), int(nbits)))
                or wins_sentinel)
        monkeypatch.setattr(
            MP, "combine_windows_soa",
            lambda wins, c: out_sentinel if wins is wins_sentinel else None)
        monkeypatch.setenv("SPECTRE_MSM_IMPL", "pallas")
        pts = ec.encode_points(
            [bn.g1_curve.mul(bn.G1_GEN, k + 1) for k in range(4)])
        ss = jnp.asarray(L.ints_to_limbs16([k + 1 for k in range(4)]))
        out = MSM.msm(pts, ss, c=4, mode="vanilla")
        assert out is out_sentinel
        assert calls == [((MP.ROWS, 4), None, 4, 254)]

    def test_bucket_kernel_in_jaxpr_not_emission_path(self):
        """Structural pin for the tentpole: the pallas bucket pipeline's
        jaxpr contains the pallas_call bucket kernel and NONE of the old
        XLA argsort/scatter emission ops (the `_segmented_bucket_sums_soa`
        path this PR deleted)."""
        from spectre_tpu.ops import msm_pallas as MP
        sc = jnp.zeros((4, 8), jnp.uint32)
        soa = MP.inf_soa(4)
        jaxpr = str(jax.make_jaxpr(
            lambda p, s: MP._bucket_windows_jit.__wrapped__(
                p, s, None, 3, 8, True))(soa, sc))
        assert "pallas_call" in jaxpr
        # primitive applications print as `sort[`/`scatter...[` — plain
        # substring would trip on the `indices_are_sorted=` gather param
        import re
        assert not re.search(r"\bsort\[|\bscatter", jaxpr)
        assert not hasattr(MP, "_segmented_bucket_sums_soa")

    @pytest.mark.slow
    def test_pallas_all_modes_match_oracle_no_degrade(self, monkeypatch):
        """The mode x impl matrix (tentpole acceptance): every
        SPECTRE_MSM_MODE under SPECTRE_MSM_IMPL=pallas runs the
        interpret-mode bucket kernel, matches the host-curve oracle, emits
        ZERO msm_pallas_unsupported_mode events, and never round-trips
        scalars through the host GLV decomposition (decompose_limbs16 is
        poisoned for the duration). slow marker = the four interpret-mode
        compile chains (~40s, 1-core box); `make test` runs it (plain
        pytest, no marker filter) — the 870s driver tier keeps only the
        structural pins above."""
        events = []
        monkeypatch.setattr(
            MSM, "_record_event",
            lambda kind, **detail: events.append((kind, detail)))

        def _no_host(*a, **k):
            raise AssertionError(
                "host glv.decompose_limbs16 called on the pallas path — "
                "the GLV Babai rounding must stay on device")
        monkeypatch.setattr(glv, "decompose_limbs16", _no_host)
        monkeypatch.setenv("SPECTRE_MSM_IMPL", "pallas")

        n = 6
        pts = [bn.g1_curve.mul(bn.G1_GEN, 5 * k + 2) for k in range(n)]
        pts[3] = None
        scalars = [secrets.randbelow(bn.R) for _ in range(n)]
        scalars[0], scalars[1], scalars[2] = 0, 1, bn.R - 1
        want = bn.g1_curve.msm(pts, scalars)
        want = (int(want[0]), int(want[1]))
        pp = ec.encode_points(pts)
        ss = jnp.asarray(L.ints_to_limbs16(scalars))
        # c=3 shared across modes: the padd/bucket compile shapes are
        # process-cached, keeping the fast-tier matrix seconds-scale
        for mode in MSM.MSM_MODES:
            got = ec.decode_points(MSM.msm(pp, ss, c=3, mode=mode)[None])[0]
            assert got == want, mode
        assert not [e for e in events
                    if e[0] == "msm_pallas_unsupported_mode"], events

    @pytest.mark.slow
    def test_pallas_batch_matches_oracle(self, monkeypatch):
        monkeypatch.setenv("SPECTRE_MSM_IMPL", "pallas")
        n, m = 6, 2
        pts = [bn.g1_curve.mul(bn.G1_GEN, k + 1) for k in range(n)]
        pp = ec.encode_points(pts)
        scs = [[(i * 131 + k * 7 + 1) % bn.R for k in range(n)]
               for i in range(m)]
        batch = jnp.stack([jnp.asarray(L.ints_to_limbs16(sc)) for sc in scs])
        got = ec.decode_points(MSM.msm_batch(pp, batch, c=3, mode="glv"))
        for sc, g_pt in zip(scs, got):
            want = bn.g1_curve.msm(pts, sc)
            assert g_pt == (int(want[0]), int(want[1]))

    def test_dp_runner_records_degrade_event(self, monkeypatch):
        """The DP shard_map runner stays XLA: under impl=pallas it must
        fall back VISIBLY — provenance event with n, c, and caller site,
        plus the msm_pallas_degraded health counter. The SPMD runner is
        stubbed out (the degrade record happens before dispatch; compiling
        the real 8-way mesh program costs ~20s and is the trace-lint
        probes' job)."""
        from spectre_tpu.parallel import batch_msm as BM
        from spectre_tpu.parallel.batch_msm import batch_msm_dp
        from spectre_tpu.utils.health import HEALTH
        events = []
        monkeypatch.setattr(
            MSM, "_record_event",
            lambda kind, **detail: events.append((kind, detail)))
        monkeypatch.setattr(
            BM, "_runner_glv",
            lambda mesh, c, nbits, signed:
                lambda p, s, g: jnp.zeros(
                    (s.shape[0], 3, 16), jnp.uint32))
        monkeypatch.setenv("SPECTRE_MSM_IMPL", "pallas")
        before = HEALTH.get("msm_pallas_degraded")
        pts = jnp.zeros((8, 3, 16), jnp.uint32)
        sb = jnp.zeros((2, 8, 8), jnp.uint32)
        ng = jnp.zeros((2, 8), bool)
        batch_msm_dp(pts, sb, c=2, neg_batch=ng, nbits=4, signed=True)
        assert HEALTH.get("msm_pallas_degraded") == before + 1
        kinds = [e for e in events if e[0] == "msm_pallas_unsupported_mode"]
        assert len(kinds) == 1
        detail = kinds[0][1]
        assert detail["n"] == 8 and detail["c"] == 2
        assert detail["site"] == "parallel.batch_msm_dp"

"""GLV decomposition, signed-digit recoding, and MSM mode equivalence.

The contract every mode must honor: identical group element out (the
commitment byte-equality gate rides on this), only the work shape differs.
"""

import secrets

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from spectre_tpu.fields import bn254 as bn
from spectre_tpu.ops import ec, glv, limbs as L, msm as MSM


def _edge_scalars():
    lam = glv.lam()
    return [0, 1, 2, bn.R - 1, bn.R - 2, lam, bn.R - lam, lam - 1,
            (bn.R - 1) // 2, 1 << 128, (1 << 253) - 1]


class TestGLVDecompose:
    def test_recomposes_mod_r(self):
        lam = glv.lam()
        for k in _edge_scalars() + [secrets.randbelow(bn.R)
                                    for _ in range(64)]:
            k1, k2 = glv.decompose(k)
            assert (k1 + k2 * lam) % bn.R == k % bn.R, k

    def test_half_scalars_bounded(self):
        bound = 1 << glv.glv_bits()
        assert glv.glv_bits() <= 16 * glv.HALF_LIMBS
        for k in _edge_scalars() + [secrets.randbelow(bn.R)
                                    for _ in range(64)]:
            k1, k2 = glv.decompose(k)
            assert -bound < k1 < bound and -bound < k2 < bound, k

    def test_batch_matches_scalar_path(self):
        ks = _edge_scalars() + [secrets.randbelow(bn.R) for _ in range(16)]
        a1, a2, n1, n2 = glv.decompose_batch(ks)
        for i, k in enumerate(ks):
            k1, k2 = glv.decompose(k)
            assert bool(n1[i]) == (k1 < 0) and bool(n2[i]) == (k2 < 0), k
            assert sum(int(a1[i, j]) << (16 * j)
                       for j in range(glv.HALF_LIMBS)) == abs(k1)
            assert sum(int(a2[i, j]) << (16 * j)
                       for j in range(glv.HALF_LIMBS)) == abs(k2)

    def test_sign_flip_cases(self):
        """Full-size scalars hit every half-scalar sign combination (small
        scalars decompose trivially to k1=k, k2=0 — the generator must span
        the whole of Fr)."""
        seen = set()
        g = bn.FR_GENERATOR
        for k in range(1, 256):
            k1, k2 = glv.decompose(pow(g, k, bn.R))
            seen.add((k1 < 0, k2 < 0))
            if len(seen) == 4:
                break
        assert len(seen) == 4, f"only sign patterns {seen} exercised"

    def test_endo_matches_lambda_mul(self):
        pts = [bn.g1_curve.mul(bn.G1_GEN, 3 * i + 2) for i in range(4)]
        pts.append(None)     # phi fixes infinity
        got = ec.decode_points(jax.jit(ec.endo)(ec.encode_points(pts)))
        lam = glv.lam()
        for p, g in zip(pts, got):
            want = bn.g1_curve.mul(p, lam) if p is not None else None
            want = None if want is None else (int(want[0]), int(want[1]))
            assert g == want


class TestSignedDigits:
    @pytest.mark.parametrize("c", [4, 8, 11, 13])
    def test_roundtrip_and_range(self, c):
        nbits = glv.glv_bits()
        nwin = (nbits + c) // c
        vals = [0, 1, (1 << nbits) - 1, 1 << (c - 1), (1 << c) - 1] + \
            [secrets.randbelow(1 << nbits) for _ in range(16)]
        limbs = np.zeros((len(vals), glv.HALF_LIMBS), np.uint32)
        for i, v in enumerate(vals):
            for j in range(glv.HALF_LIMBS):
                limbs[i, j] = (v >> (16 * j)) & 0xFFFF
        digs = np.asarray(MSM.signed_digit_stream(jnp.asarray(limbs), c, nwin))
        half = 1 << (c - 1)
        assert digs.min() >= -half + 1 and digs.max() <= half
        for i, v in enumerate(vals):
            back = sum(int(digs[w, i]) << (c * w) for w in range(nwin))
            assert back == v, (c, v)

    def test_matches_unsigned_stream(self):
        """The signed stream is a recoding OF the unsigned digit stream:
        summing both must agree (round-trip through the same scalar)."""
        import jax
        c, nbits = 10, glv.glv_bits()
        nwin_u = (nbits + c - 1) // c
        nwin_s = (nbits + c) // c
        k = secrets.randbelow(1 << nbits)
        limbs = np.zeros((1, glv.HALF_LIMBS), np.uint32)
        for j in range(glv.HALF_LIMBS):
            limbs[0, j] = (k >> (16 * j)) & 0xFFFF
        arr = jnp.asarray(limbs)
        from spectre_tpu.ops import field_ops as F
        unsigned = [int(np.asarray(
            jax.jit(lambda a, w=w: F.limb_digits(a, w, c))(arr))[0])
            for w in range(nwin_u)]
        signed = np.asarray(MSM.signed_digit_stream(arr, c, nwin_s))[:, 0]
        assert sum(d << (c * w) for w, d in enumerate(unsigned)) == \
            sum(int(d) << (c * w) for w, d in enumerate(signed)) == k


class TestMSMModes:
    def _inputs(self, n=48):
        pts = [bn.g1_curve.mul(bn.G1_GEN, secrets.randbelow(bn.R))
               for _ in range(n)]
        pts[3] = None
        scalars = [secrets.randbelow(bn.R) for _ in range(n)]
        scalars[0] = 0
        scalars[1] = 1
        scalars[2] = bn.R - 1
        want = bn.g1_curve.msm(pts, scalars)
        return (ec.encode_points(pts), jnp.asarray(L.ints_to_limbs16(scalars)),
                (int(want[0]), int(want[1])))

    @pytest.mark.parametrize("mode", MSM.MSM_MODES)
    def test_matches_oracle(self, mode):
        pp, ss, want = self._inputs()
        got = ec.decode_points(MSM.msm(pp, ss, mode=mode)[None])[0]
        assert got == want, mode

    @pytest.mark.parametrize("mode", MSM.MSM_MODES)
    def test_all_zero_is_identity(self, mode):
        pts = [bn.g1_curve.mul(bn.G1_GEN, k + 1) for k in range(8)]
        pp = ec.encode_points(pts)
        ss = jnp.asarray(L.ints_to_limbs16([0] * 8))
        assert ec.decode_points(MSM.msm(pp, ss, mode=mode)[None])[0] is None

    def test_env_mode_dispatch(self, monkeypatch):
        monkeypatch.setenv("SPECTRE_MSM_MODE", "glv+signed")
        assert MSM.msm_mode() == "glv+signed"
        monkeypatch.setenv("SPECTRE_MSM_MODE", "bogus")
        with pytest.raises(ValueError):
            MSM.msm_mode()

    def test_batch_modes_match_single(self):
        n, m = 24, 3
        pts = [bn.g1_curve.mul(bn.G1_GEN, k + 1) for k in range(n)]
        pp = ec.encode_points(pts)
        scs = [[(i * 131 + k * 7 + 1) % bn.R for k in range(n)]
               for i in range(m)]
        batch = jnp.stack([jnp.asarray(L.ints_to_limbs16(sc)) for sc in scs])
        for mode in ("glv", "glv+signed", "fixed"):
            got = ec.decode_points(MSM.msm_batch(pp, batch, mode=mode))
            for sc, g_pt in zip(scs, got):
                want = bn.g1_curve.msm(pts, sc)
                assert g_pt == (int(want[0]), int(want[1])), mode


class TestFixedTableCache:
    def test_hit_and_key_separation(self):
        pts = ec.encode_points(
            [bn.g1_curve.mul(bn.G1_GEN, k + 1) for k in range(8)])
        ss = jnp.asarray(L.ints_to_limbs16([k * 3 + 1 for k in range(8)]))
        MSM.msm(pts, ss, mode="fixed", base_key="t-cache-a")
        builds0, hits0 = MSM._TABLES.builds, MSM._TABLES.hits
        MSM.msm(pts, ss, mode="fixed", base_key="t-cache-a")
        assert MSM._TABLES.hits == hits0 + 1
        assert MSM._TABLES.builds == builds0
        # a different base key must NOT hit the same table
        MSM.msm(pts, ss, mode="fixed", base_key="t-cache-b")
        assert MSM._TABLES.builds == builds0 + 1

    def test_budget_passthrough_uncached(self, monkeypatch):
        tiny = MSM._TableLRU(1024)     # 1 KB: every table passes through
        table = jnp.zeros((4, 8, 3, 16), dtype=jnp.uint32)
        out = tiny.put(("k",), None, table)
        assert out is table
        assert tiny.get(("k",), None) is None   # nothing retained


class TestDefaultWindowTuning:
    def test_pinned_unsigned(self):
        assert [MSM.default_window(n) for n in
                (1 << 6, 1 << 7, 1 << 12, 1 << 16, 1 << 18)] == \
            [4, 7, 10, 10, 13]

    def test_pinned_signed(self):
        # signed digits halve the bucket array -> each size class affords
        # one larger window (the tuning-table change this PR pins)
        assert [MSM.default_window(n, signed=True) for n in
                (1 << 6, 1 << 7, 1 << 12, 1 << 16, 1 << 17, 1 << 18)] == \
            [5, 8, 11, 11, 11, 13]

    def test_fixed_follows_signed(self):
        for n in (1 << 7, 1 << 12, 1 << 17, 1 << 20):
            assert MSM.default_window_fixed(n) == \
                MSM.default_window(n, signed=True)


class TestWindowOverride:
    """SPECTRE_MSM_WINDOW: one env knob retunes every MSM path (the value
    a bench.py --sweep-window run picks on real hardware)."""

    def test_override_wins_over_tables(self, monkeypatch):
        monkeypatch.setenv("SPECTRE_MSM_WINDOW", "9")
        assert MSM.window_override() == 9
        for n in (1 << 6, 1 << 12, 1 << 18):
            assert MSM.default_window(n) == 9
            assert MSM.default_window(n, signed=True) == 9
            assert MSM.default_window_fixed(n) == 9

    def test_unset_and_empty_mean_autotune(self, monkeypatch):
        monkeypatch.delenv("SPECTRE_MSM_WINDOW", raising=False)
        assert MSM.window_override() is None
        monkeypatch.setenv("SPECTRE_MSM_WINDOW", "")
        assert MSM.window_override() is None
        assert MSM.default_window(1 << 12) == 10     # table still pinned

    @pytest.mark.parametrize("bad", ["0", "14", "-3"])
    def test_out_of_range_rejected(self, bad, monkeypatch):
        monkeypatch.setenv("SPECTRE_MSM_WINDOW", bad)
        with pytest.raises(ValueError):
            MSM.window_override()

    def test_override_result_unchanged(self, monkeypatch):
        """An overridden window changes the work shape, never the point."""
        pts = ec.encode_points(
            [bn.g1_curve.mul(bn.G1_GEN, 3 * k + 1) for k in range(8)])
        ss = jnp.asarray(L.ints_to_limbs16([k * 5 + 2 for k in range(8)]))
        want = np.asarray(MSM.msm(pts, ss, mode="vanilla"))
        monkeypatch.setenv("SPECTRE_MSM_WINDOW", "3")
        got = np.asarray(MSM.msm(pts, ss, mode="vanilla"))
        assert ec.decode_points(jnp.asarray(got)[None]) == \
            ec.decode_points(jnp.asarray(want)[None])


class TestImplDispatch:
    """SPECTRE_MSM_IMPL: xla (default) vs the pallas SoA kernel path."""

    def test_env_validation(self, monkeypatch):
        monkeypatch.delenv("SPECTRE_MSM_IMPL", raising=False)
        assert MSM.msm_impl() == "xla"
        monkeypatch.setenv("SPECTRE_MSM_IMPL", "pallas")
        assert MSM.msm_impl() == "pallas"
        monkeypatch.setenv("SPECTRE_MSM_IMPL", "cuda")
        with pytest.raises(ValueError):
            MSM.msm_impl()

    def test_pallas_routes_vanilla(self, monkeypatch):
        from spectre_tpu.ops import msm_pallas as MP
        calls = []
        sentinel = jnp.zeros((3, 16), dtype=jnp.uint32)
        monkeypatch.setattr(
            MP, "msm_soa",
            lambda soa, sc, c: calls.append((soa.shape, int(c))) or sentinel)
        monkeypatch.setenv("SPECTRE_MSM_IMPL", "pallas")
        pts = ec.encode_points(
            [bn.g1_curve.mul(bn.G1_GEN, k + 1) for k in range(4)])
        ss = jnp.asarray(L.ints_to_limbs16([k + 1 for k in range(4)]))
        out = MSM.msm(pts, ss, c=4, mode="vanilla")
        assert out is sentinel
        assert calls == [((MP.ROWS, 4), 4)]

    def test_pallas_nonvanilla_degrades_to_xla(self, monkeypatch):
        """GLV/fixed plumbing is AoS-only: pallas impl must fall through to
        the XLA path AND leave a provenance event, not fail or go wrong."""
        events = []
        monkeypatch.setattr(
            MSM, "_record_event",
            lambda kind, **detail: events.append((kind, detail)))
        pts = ec.encode_points(
            [bn.g1_curve.mul(bn.G1_GEN, 2 * k + 1) for k in range(6)])
        ss = jnp.asarray(L.ints_to_limbs16([k * 7 + 3 for k in range(6)]))
        want = ec.decode_points(
            jnp.asarray(MSM.msm(pts, ss, mode="glv"))[None])
        monkeypatch.setenv("SPECTRE_MSM_IMPL", "pallas")
        got = ec.decode_points(
            jnp.asarray(MSM.msm(pts, ss, mode="glv"))[None])
        assert got == want
        assert ("msm_pallas_unsupported_mode", {"mode": "glv"}) in events

    def test_pallas_vanilla_matches_xla_interpret(self, monkeypatch):
        """End-to-end impl parity THROUGH the real interpret-mode pallas
        kernel on a tiny instance."""
        import os
        if os.environ.get("RUN_SLOW") != "1":
            pytest.skip("interpret-mode MSM compiles many shapes "
                        "(set RUN_SLOW=1)")
        pts = ec.encode_points(
            [bn.g1_curve.mul(bn.G1_GEN, k + 2) for k in range(8)])
        ss = jnp.asarray(L.ints_to_limbs16([k * 3 + 1 for k in range(8)]))
        want = ec.decode_points(
            jnp.asarray(MSM.msm(pts, ss, c=4, mode="vanilla"))[None])
        monkeypatch.setenv("SPECTRE_MSM_IMPL", "pallas")
        got = ec.decode_points(
            jnp.asarray(MSM.msm(pts, ss, c=4, mode="vanilla"))[None])
        assert got == want

"""NTT pipeline modes (ISSUE 4): radix2 vs fourstep vs host oracle, batched
vs per-column loops, fused coset-LDE vs scale-then-NTT, the budgeted
twiddle-table LRU, and the proof-byte gate.

The contract every mode must honor (mirroring the MSM modes): identical
bytes out — radix2 and fourstep are the SAME transform in a different work
shape, and the batched kernels are the per-column kernels on a stack."""

import numpy as np
import pytest

import jax.numpy as jnp

from spectre_tpu.fields import bn254 as bn
from spectre_tpu.native import host
from spectre_tpu.ops import field_ops as F, limbs as L, ntt as NTT

R = bn.R


def _poly(n, seed=17):
    return [(i * 2654435761 + seed) % R for i in range(n)]


def _mont(vals):
    return jnp.asarray(F.fr_ctx().encode_np(vals))


class TestModeEquality:
    @pytest.mark.parametrize("k", [2, 3, 5, 7, 9])
    def test_modes_match_host_oracle(self, k):
        omega = bn.fr_root_of_unity(k)
        vals = _poly(1 << k)
        want = host.limbs_to_ints(
            host.fr_ntt(np.array(host.ints_to_limbs(vals)), omega))
        a = _mont(vals)
        ctx = F.fr_ctx()
        out = {}
        for mode in NTT.NTT_MODES:
            res = NTT.ntt(a, omega, mode=mode)
            assert ctx.decode(res) == want, (mode, k)
            out[mode] = np.asarray(res)
        # byte-identical across modes, not merely value-equal
        assert np.array_equal(out["radix2"], out["fourstep"]), k

    def test_env_mode_dispatch(self, monkeypatch):
        monkeypatch.setenv("SPECTRE_NTT_MODE", "fourstep")
        assert NTT.ntt_mode() == "fourstep"
        monkeypatch.setenv("SPECTRE_NTT_MODE", "bogus")
        with pytest.raises(ValueError):
            NTT.ntt_mode()

    def test_tiny_sizes_fall_back_to_radix2(self):
        # logn < 2 has no row/column split; fourstep must still answer
        omega = bn.fr_root_of_unity(1)
        a = _mont(_poly(2))
        assert np.array_equal(np.asarray(NTT.ntt(a, omega, mode="fourstep")),
                              np.asarray(NTT.ntt(a, omega, mode="radix2")))

    @pytest.mark.parametrize("mode", NTT.NTT_MODES)
    def test_intt_roundtrip(self, mode):
        k = 6
        omega = bn.fr_root_of_unity(k)
        vals = _poly(1 << k)
        a = _mont(vals)
        back = NTT.intt(NTT.ntt(a, omega, mode=mode), omega, mode=mode)
        assert F.fr_ctx().decode(back) == vals


class TestBatched:
    @pytest.mark.parametrize("mode", NTT.NTT_MODES)
    def test_ntt_many_matches_loop(self, mode):
        k = 5
        omega = bn.fr_root_of_unity(k)
        cols = [_poly(1 << k, seed=s) for s in (1, 2, 3)]
        stack = jnp.stack([_mont(c) for c in cols])
        many = np.asarray(NTT.ntt_many(stack, omega, mode=mode))
        for i, c in enumerate(cols):
            assert np.array_equal(
                many[i], np.asarray(NTT.ntt(_mont(c), omega, mode=mode))), i

    def test_intt_many_matches_loop(self):
        k = 5
        omega = bn.fr_root_of_unity(k)
        cols = [_poly(1 << k, seed=s) for s in (4, 5)]
        stack = jnp.stack([_mont(c) for c in cols])
        many = np.asarray(NTT.intt_many(stack, omega))
        for i, c in enumerate(cols):
            assert np.array_equal(many[i],
                                  np.asarray(NTT.intt(_mont(c), omega))), i

    def test_backend_ntt_many_matches_singles(self):
        from spectre_tpu.plonk import backend as B
        bk = B.get_backend("tpu")
        n = 1 << 5
        omega = bn.fr_root_of_unity(5)
        arrs = [B.to_arr(_poly(n, seed=s)) for s in (7, 8, 9)]
        many = bk.ntt_many(arrs, omega)
        inv_many = bk.intt_many(arrs, omega)
        for a, m, im in zip(arrs, many, inv_many):
            assert np.array_equal(m, bk.ntt(a, omega))
            assert np.array_equal(im, bk.intt(a, omega))
        # CPU backend agrees (the native oracle)
        cpu = B.get_backend("cpu")
        for a, m in zip(arrs, many):
            assert np.array_equal(m, cpu.ntt(a, omega))


class TestFusedCosetLde:
    @pytest.mark.parametrize("mode", NTT.NTT_MODES)
    def test_fused_equals_scale_then_ntt(self, mode):
        k, g = 6, 7
        omega = bn.fr_root_of_unity(k)
        a = _mont(_poly(1 << k))
        fused = np.asarray(NTT.coset_ntt(a, omega, g, mode=mode))
        unfused = np.asarray(
            NTT.ntt(NTT.coset_scale(a, g), omega, mode=mode))
        assert np.array_equal(fused, unfused)

    @pytest.mark.parametrize("mode", NTT.NTT_MODES)
    def test_std_boundary_fusions(self, mode):
        """coset_lde_std folds std→mont + scale into stage 0;
        coset_intt_std folds 1/n + g^{-i} + mont→std into one table."""
        k, g = 5, 7
        omega = bn.fr_root_of_unity(k)
        vals = _poly(1 << k)
        a_std = jnp.asarray(L.ints_to_limbs16(vals))
        fwd = NTT.coset_lde_std(a_std, omega, g, mode=mode)
        assert np.array_equal(
            np.asarray(fwd),
            np.asarray(NTT.coset_ntt(_mont(vals), omega, g, mode=mode)))
        back = NTT.coset_intt_std(fwd, omega, g, mode=mode)
        assert L.limbs16_to_ints(np.asarray(back)) == vals

    def test_inverse_roundtrip_batched(self):
        k, g = 5, 7
        omega = bn.fr_root_of_unity(k)
        cols = [_poly(1 << k, seed=s) for s in (11, 12)]
        stack = jnp.stack([_mont(c) for c in cols])
        ext = NTT.coset_ntt_many(stack, omega, g)
        back = NTT.coset_intt_many(ext, omega, g)
        ctx = F.fr_ctx()
        for i, c in enumerate(cols):
            assert ctx.decode(back[i]) == c

    def test_backend_coset_lde_many_matches_domain(self):
        """The device batched fused path reproduces the host
        coeff_to_extended (the quotient's correctness anchor)."""
        from spectre_tpu.plonk import backend as B
        from spectre_tpu.plonk.domain import Domain
        dom = Domain(5)
        cpu, tpu = B.get_backend("cpu"), B.get_backend("tpu")
        coeffs = [B.to_arr(_poly(dom.n, seed=s)) for s in (3, 4, 5)]
        want = [dom.coeff_to_extended(c, cpu) for c in coeffs]
        got = dom.coset_lde_many(coeffs, tpu)
        for w, g_ in zip(want, got):
            assert np.array_equal(w, g_)


class TestTwiddleTableLRU:
    def test_budget_eviction_and_recompute(self, monkeypatch):
        lru = NTT._TableLRU(1 << 20, label="test ntt table",
                            budget_var="SPECTRE_NTT_TABLE_MB")
        monkeypatch.setattr(NTT, "_TABLES", lru)
        omega = bn.fr_root_of_unity(12)
        t1 = NTT._stage_twiddles(12, omega)          # ~512KB of stages
        b0 = lru.builds
        assert NTT._stage_twiddles(12, omega) is t1  # hit
        assert lru.hits >= 1 and lru.builds == b0
        # a second table family under a 1MB budget forces eviction
        NTT._power_table(13, 7)                      # 512KB
        NTT._power_table(13, 5)                      # 512KB -> evicts
        assert lru.evictions >= 1
        # evicted entries recompute correctly (budget costs time, never
        # correctness)
        t1b = NTT._stage_twiddles(12, omega)
        assert all(np.array_equal(x, y) for x, y in zip(t1, t1b))

    def test_oversize_table_passes_through_uncached(self, monkeypatch):
        lru = NTT._TableLRU(1024, label="tiny", budget_var="X")
        monkeypatch.setattr(NTT, "_TABLES", lru)
        tab = NTT._power_table(10, 7)                # 64KB > 1KB budget
        assert tab.shape == (1 << 10, 16)
        assert lru._bytes == 0                       # nothing retained
        b0 = lru.builds
        NTT._power_table(10, 7)                      # rebuilds every time
        assert lru.builds == b0 + 1

    def test_budget_env_override(self, monkeypatch):
        monkeypatch.setenv("SPECTRE_NTT_TABLE_MB", "3")
        assert NTT._table_budget_bytes() == 3 << 20


class TestNttModeProofBytes:
    """The ISSUE-4 correctness gate, mirroring TestMsmModeCommitments:
    radix2 and fourstep must yield BYTE-IDENTICAL proofs through the device
    backend under seeded blinding — the modes change kernel work shape,
    never a single transformed value. Runs the tiny k=7 circuit shape
    shared with test_plonk's prove suites (warm compile cache)."""

    def test_proof_bytes_identical_across_ntt_modes(self, monkeypatch):
        import random

        from spectre_tpu.plonk import backend as B
        from spectre_tpu.plonk.constraint_system import (Assignment,
                                                         CircuitConfig)
        from spectre_tpu.plonk.keygen import keygen
        from spectre_tpu.plonk.prover import prove
        from spectre_tpu.plonk.srs import SRS
        from spectre_tpu.plonk.verifier import verify

        def seeded():
            r = random.Random(0x177E57)
            return lambda: r.randrange(R)

        k = 7
        srs = SRS.unsafe_setup(k)
        cfg = CircuitConfig(k=k, num_advice=1, num_lookup_advice=1,
                            num_fixed=1, lookup_bits=4)
        n = cfg.n
        x_w, y_w = 7, 3
        out = x_w + x_w * y_w
        advice = [[0] * n for _ in range(cfg.num_advice)]
        advice[0][0], advice[0][1], advice[0][2], advice[0][3] = \
            x_w, x_w, y_w, out
        advice[0][4] = 5
        selectors = [[0] * n for _ in range(cfg.num_advice)]
        selectors[0][0] = 1
        lookup = [[0] * n for _ in range(cfg.num_lookup_advice)]
        lookup[0][0] = x_w
        fixed = [[0] * n for _ in range(cfg.num_fixed)]
        fixed[0][0] = 5
        copies = [
            ((cfg.col_instance(0), 0), (cfg.col_gate_advice(0), 3)),
            ((cfg.col_fixed(0), 0), (cfg.col_gate_advice(0), 4)),
            ((cfg.col_gate_advice(0), 0), (cfg.col_lookup_advice(0), 0)),
        ]
        asg = Assignment(cfg, advice, lookup, fixed, selectors, [[out]],
                         copies)
        bk = B.get_backend("tpu")
        proofs = {}
        for mode in NTT.NTT_MODES:
            monkeypatch.setenv("SPECTRE_NTT_MODE", mode)
            pk = keygen(srs, cfg, fixed, selectors, copies, bk)
            proofs[mode] = prove(pk, srs, asg, bk, blinding_rng=seeded())
            assert verify(pk.vk, srs, [[out]], proofs[mode]), mode
        assert proofs["radix2"] == proofs["fourstep"], \
            "SPECTRE_NTT_MODE changed proof bytes (modes must be identical)"

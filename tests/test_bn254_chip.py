"""BN254-Fq instantiation of the generic CRT chips (3 x 88-bit limbs).

The aggregation layer (reference: `aggregation_circuit.rs`, snark-verifier's
`LimbsEncoding<3, 88>`) does non-native BN254 G1 arithmetic over BN254 Fr
cells; these tests pin the reparameterized quotient sizing, generic carry
widths, and the b=3 on-curve check that the BLS-default suite never exercises.
"""

import random

import pytest

from spectre_tpu.builder.context import Context
from spectre_tpu.builder.fp_chip import EccChip, FpChip
from spectre_tpu.builder.range_chip import RangeChip
from spectre_tpu.fields import bn254
from spectre_tpu.plonk.mock import mock_prove

P = bn254.P


def _fresh(lookup_bits=10):
    ctx = Context()
    rng = RangeChip(lookup_bits=lookup_bits)
    fp = FpChip(rng, modulus=P, num_limbs=3, limb_bits=88)
    return ctx, rng, fp


def _mock(ctx, k=14, lookup_bits=10):
    cfg = ctx.auto_config(k=k, lookup_bits=lookup_bits)
    return mock_prove(cfg, ctx.assignment(cfg))


class TestBn254Fp:
    def test_field_ops_match_host(self):
        random.seed(11)
        ctx, rng, fp = _fresh()
        for _ in range(4):
            a, b = random.randrange(P), random.randrange(P)
            ac, bc = fp.load(ctx, a), fp.load(ctx, b)
            assert fp.mul(ctx, ac, bc).value % P == a * b % P
            assert fp.add(ctx, ac, bc).value % P == (a + b) % P
            assert fp.sub(ctx, ac, bc).value % P == (a - b) % P
        assert _mock(ctx)

    def test_canonicalize_and_capacity_guard(self):
        ctx, rng, fp = _fresh()
        a = fp.load(ctx, P - 1)
        fp.canonicalize(ctx, a)
        assert _mock(ctx)
        # a modulus wider than the limb capacity must be rejected loudly
        wide = FpChip(rng, modulus=(1 << 300) - 153, num_limbs=3, limb_bits=88)
        with pytest.raises(AssertionError, match="limb capacity"):
            wide.load(ctx, (1 << 299))

    def test_ecc_chain_matches_host(self):
        ctx, rng, fp = _fresh()
        ecc = EccChip(fp, b=3)
        g1 = bn254.g1_curve
        host = bn254.G1_GEN
        acc = ecc.load_point(ctx, (int(host[0]), int(host[1])))
        q_host = g1.double(bn254.G1_GEN)
        for _ in range(3):
            q = ecc.load_point(ctx, (int(q_host[0]), int(q_host[1])))
            acc = ecc.add_unequal(ctx, acc, q)
            host = g1.add(host, q_host)
            q_host = g1.double(q_host)
        assert acc[0].value % P == int(host[0])
        assert acc[1].value % P == int(host[1])
        d = ecc.double(ctx, acc)
        host2 = g1.double(host)
        assert d[0].value % P == int(host2[0])
        assert _mock(ctx)

    def test_off_curve_point_rejected(self):
        ctx, rng, fp = _fresh()
        ecc = EccChip(fp, b=3)
        with pytest.raises(AssertionError):
            ecc.load_point(ctx, (1, 3))  # y^2 != x^3 + 3

    def test_lazy_ops_match_host_and_mock(self):
        """The lazy (OverflowInt, one-carry-per-identity) EC path — the
        aggregation MSM's workhorse: double/add chain vs host math, then
        full constraint satisfaction."""
        ctx, rng, fp = _fresh(lookup_bits=12)
        ecc = EccChip(fp, b=3)
        g1 = bn254.g1_curve
        base = bn254.G1_GEN
        acc = ecc.load_point(ctx, (int(base[0]), int(base[1])))
        gcell = acc
        host = base
        for bit in "0110101":  # scalar 0b10110101 = 181
            acc = ecc.double_lazy(ctx, acc)
            host = g1.double(host)
            if bit == "1":
                acc = ecc.add_unequal_lazy(ctx, acc, gcell)
                host = g1.add(host, base)
        expect = g1.mul(base, 0b10110101)
        assert host == expect
        assert acc[0].value % P == int(expect[0])
        assert acc[1].value % P == int(expect[1])
        # point select
        bit = ctx.load_witness(1)
        sel = ecc.select(ctx, bit, acc, gcell)
        assert sel[0].value == acc[0].value
        assert _mock(ctx, k=15, lookup_bits=12)

    def test_lazy_add_rejects_equal_points(self):
        ctx, rng, fp = _fresh()
        ecc = EccChip(fp, b=3)
        g = bn254.G1_GEN
        a = ecc.load_point(ctx, (int(g[0]), int(g[1])))
        with pytest.raises(AssertionError, match="P == "):
            ecc.add_unequal_lazy(ctx, a, a)

"""Circuit builder (Context/GateChip/RangeChip) tests."""

import pytest

from spectre_tpu.builder import Context, GateChip, RangeChip
from spectre_tpu.fields import bn254 as bn
from spectre_tpu.plonk.keygen import keygen
from spectre_tpu.plonk.mock import mock_prove
from spectre_tpu.plonk.prover import prove
from spectre_tpu.plonk.srs import SRS
from spectre_tpu.plonk.verifier import verify

R = bn.R


def _mock(ctx, k=9, lookup_bits=8):
    cfg = ctx.auto_config(k=k, lookup_bits=lookup_bits)
    asg = ctx.assignment(cfg)
    assert mock_prove(cfg, asg)
    return cfg, asg


class TestGateChip:
    def test_arithmetic(self):
        ctx, gate = Context(), GateChip()
        a, b = ctx.load_witness(17), ctx.load_witness(5)
        assert gate.add(ctx, a, b).value == 22
        assert gate.sub(ctx, a, b).value == 12
        assert gate.mul(ctx, a, b).value == 85
        assert gate.mul_add(ctx, a, b, 100).value == 185
        assert gate.neg(ctx, b).value == R - 5
        assert gate.div_unsafe(ctx, a, b).value == 17 * pow(5, -1, R) % R
        _mock(ctx)

    def test_boolean_and_select(self):
        ctx, gate = Context(), GateChip()
        t, f = ctx.load_witness(1), ctx.load_witness(0)
        gate.assert_bit(ctx, t)
        gate.assert_bit(ctx, f)
        assert gate.and_(ctx, t, f).value == 0
        assert gate.or_(ctx, t, f).value == 1
        assert gate.not_(ctx, f).value == 1
        a, b = ctx.load_witness(111), ctx.load_witness(222)
        assert gate.select(ctx, a, b, t).value == 111
        assert gate.select(ctx, a, b, f).value == 222
        assert gate.is_zero(ctx, f).value == 1
        assert gate.is_zero(ctx, a).value == 0
        assert gate.is_equal(ctx, a, a).value == 1
        _mock(ctx)

    def test_bits(self):
        ctx, gate = Context(), GateChip()
        a = ctx.load_witness(0b10110101)
        bits = gate.num_to_bits(ctx, a, 8)
        assert [b.value for b in bits] == [1, 0, 1, 0, 1, 1, 0, 1]
        back = gate.bits_to_num(ctx, bits)
        assert back.value == 0b10110101
        _mock(ctx)

    def test_inner_product(self):
        ctx, gate = Context(), GateChip()
        xs = [ctx.load_witness(v) for v in (2, 3, 5)]
        ys = [ctx.load_witness(v) for v in (7, 11, 13)]
        assert gate.inner_product(ctx, xs, ys).value == 2 * 7 + 3 * 11 + 5 * 13
        assert gate.inner_product_const(ctx, xs, [1, 10, 100]).value == 532
        _mock(ctx)

    def test_copy_mismatch_caught(self):
        ctx, gate = Context(), GateChip()
        a, b = ctx.load_witness(1), ctx.load_witness(2)
        with pytest.raises(AssertionError):
            ctx.constrain_equal(a, b)


class TestRangeChip:
    def test_range_check(self):
        ctx = Context()
        rng = RangeChip(lookup_bits=8)
        a = ctx.load_witness(0xABCDE)
        rng.range_check(ctx, a, 20)
        b = ctx.load_witness(255)
        rng.range_check(ctx, b, 8)
        z = ctx.load_witness(0)
        rng.range_check(ctx, z, 1)
        _mock(ctx)

    def test_range_check_rejects_oversize_witness(self):
        ctx = Context()
        rng = RangeChip(lookup_bits=8)
        a = ctx.load_witness(1 << 21)
        with pytest.raises(AssertionError):
            rng.range_check(ctx, a, 20)

    def test_nonmultiple_width_is_tight(self):
        # value fits 2^19 <= v < 2^20 boundary: 2^20 - 1 passes, 2^20 fails
        ctx = Context()
        rng = RangeChip(lookup_bits=8)
        rng.range_check(ctx, ctx.load_witness((1 << 20) - 1), 20)
        _mock(ctx)

    def test_comparisons(self):
        ctx = Context()
        rng = RangeChip(lookup_bits=8)
        a, b = ctx.load_witness(100), ctx.load_witness(200)
        rng.check_less_than(ctx, a, b, 16)
        assert rng.is_less_than(ctx, a, b, 16).value == 1
        assert rng.is_less_than(ctx, b, a, 16).value == 0
        assert rng.is_less_than(ctx, a, a, 16).value == 0
        _mock(ctx)

    def test_div_mod(self):
        ctx = Context()
        rng = RangeChip(lookup_bits=8)
        a = ctx.load_witness(987654)
        q, r = rng.div_mod(ctx, a, 1000, 20)
        assert (q.value, r.value) == (987, 654)
        _mock(ctx)


class TestEndToEnd:
    def test_builder_to_real_proof(self):
        ctx, gate = Context(), GateChip()
        rng = RangeChip(lookup_bits=8)
        x = ctx.load_witness(77)
        y = ctx.load_witness(1234)
        z = gate.mul_add(ctx, x, y, 5)
        rng.range_check(ctx, z, 20)
        ctx.expose_public(z)
        cfg, asg = _mock(ctx)
        srs = SRS.unsafe_setup(9)
        pk = keygen(srs, cfg, asg.fixed, asg.selectors, asg.copies)
        proof = prove(pk, srs, asg)
        assert verify(pk.vk, srs, [[z.value]], proof)
        assert not verify(pk.vk, srs, [[z.value + 1]], proof)

    def test_multi_column_layout(self):
        # force enough cells that layout spills into multiple advice columns
        ctx, gate = Context(), GateChip()
        acc = ctx.load_witness(1)
        for i in range(200):
            acc = gate.mul_add(ctx, acc, 3, 1)
        cfg = ctx.auto_config(k=8, lookup_bits=4)
        assert cfg.num_advice >= 2
        asg = ctx.assignment(cfg)
        assert mock_prove(cfg, asg)


class TestBigIntFpChip:
    """Non-native BLS12-381 Fq arithmetic (CRT carry-mod reduction)."""

    def _setup(self):
        from spectre_tpu.builder.fp_chip import EccChip, FpChip
        ctx = Context()
        rng = RangeChip(lookup_bits=8)
        return ctx, FpChip(rng)

    def test_fp_mul_add_sub(self):
        import secrets
        from spectre_tpu.fields import bls12_381 as bls
        ctx, fp = self._setup()
        a_v, b_v = secrets.randbelow(bls.P), secrets.randbelow(bls.P)
        a, b = fp.load(ctx, a_v), fp.load(ctx, b_v)
        assert fp.mul(ctx, a, b).value == a_v * b_v % bls.P
        assert fp.add(ctx, a, b).value == (a_v + b_v) % bls.P
        assert fp.sub(ctx, a, b).value == (a_v - b_v) % bls.P
        _mock(ctx, k=12)

    def test_fp_edge_values(self):
        from spectre_tpu.fields import bls12_381 as bls
        ctx, fp = self._setup()
        z = fp.load(ctx, 0)
        m = fp.load(ctx, bls.P - 1)
        assert fp.mul(ctx, m, m).value == (bls.P - 1) ** 2 % bls.P
        assert fp.add(ctx, m, fp.load(ctx, 1)).value == 0
        assert fp.mul(ctx, z, m).value == 0
        _mock(ctx, k=12)

    def test_ec_add_double(self):
        from spectre_tpu.builder.fp_chip import EccChip
        from spectre_tpu.fields import bls12_381 as bls
        ctx, fp = self._setup()
        ecc = EccChip(fp)
        p1, p2 = bls.sk_to_pk(3), bls.sk_to_pk(5)
        c1, c2 = ecc.load_point(ctx, p1), ecc.load_point(ctx, p2)
        s = ecc.add_unequal(ctx, c1, c2)
        want = bls.g1_curve.add(p1, p2)
        assert (s[0].value, s[1].value) == (int(want[0]), int(want[1]))
        d = ecc.double(ctx, c1)
        wantd = bls.g1_curve.double(p1)
        assert (d[0].value, d[1].value) == (int(wantd[0]), int(wantd[1]))
        _mock(ctx, k=13)

    def test_off_curve_point_rejected(self):
        from spectre_tpu.builder.fp_chip import EccChip
        from spectre_tpu.fields import bls12_381 as bls
        ctx, fp = self._setup()
        ecc = EccChip(fp)
        with pytest.raises(AssertionError):
            ecc.load_point(ctx, (bls.Fq(123), bls.Fq(456)))


class TestRound2Soundness:
    """Round-1 ADVICE findings: strict point addition (P==Q forgery),
    canonical bigint representatives."""

    def _fp(self):
        from spectre_tpu.builder.fp_chip import FpChip
        return Context(), FpChip(RangeChip(lookup_bits=8))

    def test_add_unequal_strict_rejects_equal_points(self):
        from spectre_tpu.builder.fp_chip import EccChip
        from spectre_tpu.fields import bls12_381 as bls
        ctx, fp = self._fp()
        ecc = EccChip(fp)
        p1 = bls.sk_to_pk(3)
        c1, c1b = ecc.load_point(ctx, p1), ecc.load_point(ctx, p1)
        with pytest.raises(AssertionError, match="zero"):
            ecc.add_unequal(ctx, c1, c1b)  # strict by default

    def test_add_unequal_strict_honest_still_proves(self):
        from spectre_tpu.builder.fp_chip import EccChip
        from spectre_tpu.fields import bls12_381 as bls
        ctx, fp = self._fp()
        ecc = EccChip(fp)
        p1, p2 = bls.sk_to_pk(3), bls.sk_to_pk(5)
        s = ecc.add_unequal(ctx, ecc.load_point(ctx, p1),
                            ecc.load_point(ctx, p2))
        want = bls.g1_curve.add(p1, p2)
        assert (s[0].value, s[1].value) == (int(want[0]), int(want[1]))
        _mock(ctx, k=13)

    def test_g2_add_unequal_strict_rejects_equal_points(self):
        from spectre_tpu.builder.fp_chip import FpChip
        from spectre_tpu.builder.fp2_chip import Fp2Chip, G2Chip
        from spectre_tpu.fields import bls12_381 as bls
        ctx = Context()
        g2 = G2Chip(Fp2Chip(FpChip(RangeChip(lookup_bits=8))))
        p1 = bls.g2_curve.mul(bls.G2_GEN, 7)
        c1, c1b = g2.load_point(ctx, p1), g2.load_point(ctx, p1)
        with pytest.raises(AssertionError, match="zero"):
            g2.add_unequal(ctx, c1, c1b)

    def test_forged_slope_blocked_by_nonzero_check(self):
        """The round-1 hole: dx = dy = 0 lets any witnessed slope satisfy
        q*0 = 0. The strict path's dx*inv == 1 relation has no satisfying
        witness for dx == 0 — emulating the forger (arbitrary 'inverse' cell)
        trips the carry-to-zero divisibility, i.e. the identity cannot hold."""
        from spectre_tpu.fields import bls12_381 as bls, bn254 as bn
        ctx, fp = self._fp()
        zero = fp.load(ctx, 0)
        forged_inv = fp.load(ctx, 99)
        prod = fp.big.mul_no_carry(ctx, zero, forged_inv)
        prod0 = fp.gate.add(ctx, prod[0], bn.R - 1)
        with pytest.raises(AssertionError, match="divisible"):
            fp.big.check_carry_to_zero(ctx, [prod0] + prod[1:], -1, bls.P)

    def test_assert_nonzero_honest(self):
        from spectre_tpu.fields import bls12_381 as bls
        ctx, fp = self._fp()
        a = fp.load(ctx, 123456789)
        fp.assert_nonzero(ctx, a)
        b = fp.load(ctx, bls.P - 1)
        fp.assert_nonzero(ctx, b)
        _mock(ctx, k=12)

    def test_canonicalize(self):
        from spectre_tpu.fields import bls12_381 as bls
        ctx, fp = self._fp()
        a = fp.load(ctx, bls.P - 1)
        fp.canonicalize(ctx, a)
        _mock(ctx, k=12)

    def test_canonicalize_rejects_p(self):
        """A residue r = p (non-canonical alias of 0) fits the 381-bit limb
        range checks but must fail enforce_lt."""
        from spectre_tpu.fields import bls12_381 as bls
        ctx, fp = self._fp()
        a = fp.big.load(ctx, bls.P, max_bits=bls.P.bit_length() + 1)
        with pytest.raises(AssertionError, match="out of range"):
            fp.big.enforce_lt(ctx, a, bls.P)


class TestShaSoundnessRegressions:
    """The packed-lookup aliasing forgeries found by review must stay dead."""

    def _mock_raw(self, ctx):
        from spectre_tpu.plonk.mock import mock_prove
        cfg = ctx.auto_config(k=10, lookup_bits=8)
        return mock_prove(cfg, ctx.assignment(cfg))

    def test_non_nibble_rejected(self):
        # value 16 through the nibble check must fail the lookup
        from spectre_tpu.builder.sha256_chip import Sha256Chip
        ctx = Context()
        sha = Sha256Chip()
        c = ctx.load_witness(16)
        sha._check_nibble(ctx, c)
        with pytest.raises(AssertionError, match="not in table"):
            self._mock_raw(ctx)

    def test_forged_xor_result_rejected(self):
        # with x=0,y=0 a forged z=17 used to alias the XOR row (0^1=1)
        from spectre_tpu.builder.sha256_chip import Sha256Chip
        ctx = Context()
        sha = Sha256Chip()
        x = ctx.load_witness(0)
        y = ctx.load_witness(0)
        sha._check_nibble(ctx, x)
        sha._check_nibble(ctx, y)
        # forge by hand: witness z=17, pack, push (bypassing _push_op's checks)
        z = ctx.load_witness(17)
        t1 = sha.gate.mul_add(ctx, y, 16, z)
        packed = sha.gate.mul_add(ctx, x, 256, t1)
        ctx.push_lookup_table(packed, "nibble_op")
        # the fix: z must be nibble-checked; emulate an honest chip which now
        # does this — the forged value fails
        sha._check_nibble(ctx, z)
        with pytest.raises(AssertionError, match="not in table"):
            self._mock_raw(ctx)

    def test_honest_sha_still_works(self):
        import hashlib
        from spectre_tpu.builder.sha256_chip import Sha256Chip
        from spectre_tpu.gadgets.ssz_merkle import load_bytes_checked
        ctx = Context()
        sha = Sha256Chip()
        msg = b"soundness fix regression"
        cells = load_bytes_checked(ctx, sha, msg)
        state = sha.digest_bytes(ctx, cells)
        digest = b"".join(int(w.value).to_bytes(4, "big") for w in state)
        assert digest == hashlib.sha256(msg).digest()
        from spectre_tpu.plonk.mock import mock_prove
        cfg = ctx.auto_config(k=13, lookup_bits=8)
        assert mock_prove(cfg, ctx.assignment(cfg))


class TestFp2G2Chips:
    """Quadratic extension + G2 ops (the signature-side group)."""

    def test_fp2_arithmetic(self):
        from spectre_tpu.builder.fp_chip import FpChip
        from spectre_tpu.builder.fp2_chip import Fp2Chip
        from spectre_tpu.fields import bls12_381 as bls
        ctx = Context()
        fp2 = Fp2Chip(FpChip(RangeChip(lookup_bits=8)))
        a_v, b_v = bls.Fq2([3, 7]), bls.Fq2([11, 13])
        a, b = fp2.load(ctx, a_v), fp2.load(ctx, b_v)
        assert fp2.value(fp2.mul(ctx, a, b)) == a_v * b_v
        assert fp2.value(fp2.square(ctx, a)) == a_v * a_v
        assert fp2.value(fp2.div_unsafe(ctx, a, b)) == a_v / b_v
        assert fp2.value(fp2.conjugate(ctx, a)) == bls.Fq2([3, (-7) % bls.P])
        _mock(ctx, k=13)

    def test_g2_group_law(self):
        from spectre_tpu.builder.fp_chip import FpChip
        from spectre_tpu.builder.fp2_chip import Fp2Chip, G2Chip
        from spectre_tpu.fields import bls12_381 as bls
        ctx = Context()
        fp2 = Fp2Chip(FpChip(RangeChip(lookup_bits=8)))
        g2 = G2Chip(fp2)
        p1 = bls.g2_curve.mul(bls.G2_GEN, 5)
        p2 = bls.g2_curve.mul(bls.G2_GEN, 9)
        c1, c2 = g2.load_point(ctx, p1), g2.load_point(ctx, p2)
        s = g2.add_unequal(ctx, c1, c2)
        want = bls.g2_curve.add(p1, p2)
        assert (fp2.value(s[0]), fp2.value(s[1])) == (want[0], want[1])
        d = g2.double(ctx, c1)
        wantd = bls.g2_curve.double(p1)
        assert (fp2.value(d[0]), fp2.value(d[1])) == (wantd[0], wantd[1])
        _mock(ctx, k=14)

    def test_g2_off_curve_rejected(self):
        from spectre_tpu.builder.fp_chip import FpChip
        from spectre_tpu.builder.fp2_chip import Fp2Chip, G2Chip
        from spectre_tpu.fields import bls12_381 as bls
        ctx = Context()
        g2 = G2Chip(Fp2Chip(FpChip(RangeChip(lookup_bits=8))))
        with pytest.raises(AssertionError):
            g2.load_point(ctx, (bls.Fq2([1, 2]), bls.Fq2([3, 4])))

"""Fault-injection tier (PR 3): every retry/degradation path in the
resilient prover service, exercised deterministically via
spectre_tpu.utils.faults (SPECTRE_FAULT_PLAN). Seconds-scale on tiny
specs/k — runs in the default tier and via `make test-faults`.

Covers the ISSUE-3 acceptance gates:
  * beacon client survives >=3 injected transient failures with backoff
    then succeeds; Retry-After honored; circuit breaker trips, fails
    fast, half-opens on cooldown and closes on success
  * a device-prove fault degrades to the CPU backend and the proof is
    byte-identical to a clean CPU prove (seeded blinding)
  * journal replay after a mid-prove crash re-runs the job and yields
    the same result digest as an uninterrupted run
  * fixed-base MSM degrades to glv+signed when one table would bust the
    byte budget — identical group element, no table build
"""

import hashlib
import json
import random
import threading
import time
from http.server import BaseHTTPRequestHandler, HTTPServer

import pytest

from spectre_tpu.utils import faults
from spectre_tpu.utils.health import HEALTH, ServiceHealth


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.clear()
    yield
    faults.clear()


class TestFaultPlan:
    def test_grammar(self):
        plan = faults.parse_plan("beacon.fetch:http503:3,backend.prove:oom")
        assert plan == [["beacon.fetch", "http503", 3],
                        ["backend.prove", "oom", 1]]
        assert faults.parse_plan("") == []

    def test_grammar_rejects_bad_entries(self):
        with pytest.raises(ValueError, match="unknown fault kind"):
            faults.parse_plan("site:frobnicate")
        with pytest.raises(ValueError, match="bad fault-plan entry"):
            faults.parse_plan("justasite")
        with pytest.raises(ValueError, match="bad fault count"):
            faults.parse_plan("s:raise:0")

    def test_fires_count_then_disarms(self):
        faults.install_plan("x.y:raise:2")
        for _ in range(2):
            with pytest.raises(faults.InjectedFault):
                faults.check("x.y")
        faults.check("x.y")            # exhausted: no-op
        faults.check("unrelated.site")  # never armed: no-op
        assert faults.fired_count("x.y") == 2
        assert faults.armed("x.y") == 0

    def test_env_plan(self, monkeypatch):
        faults.clear()
        monkeypatch.setenv(faults.ENV_VAR, "env.site:timeout:1")
        with pytest.raises(TimeoutError):
            faults.check("env.site")
        faults.check("env.site")       # count exhausted
        monkeypatch.delenv(faults.ENV_VAR)

    def test_kind_exceptions(self):
        import urllib.error
        faults.install_plan(
            "a:http503,a:http429,a:connreset,a:ioerror,a:compile")
        with pytest.raises(urllib.error.HTTPError) as e:
            faults.check("a")
        assert e.value.code == 503
        with pytest.raises(urllib.error.HTTPError) as e:
            faults.check("a")
        assert e.value.code == 429
        with pytest.raises(ConnectionResetError):
            faults.check("a")
        with pytest.raises(OSError):
            faults.check("a")
        with pytest.raises(faults.InjectedFault) as e:
            faults.check("a")
        assert e.value.kind == "compile"


# ---------------------------------------------------------------------------
# beacon client resilience
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def beacon_server():
    root = "0x" + (b"\xab" * 32).hex()

    class Handler(BaseHTTPRequestHandler):
        def do_GET(self):
            if self.path != "/eth/v1/beacon/blocks/head/root":
                self.send_response(404)
                self.end_headers()
                return
            body = json.dumps({"data": {"root": root}}).encode()
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *a):
            pass

    httpd = HTTPServer(("127.0.0.1", 0), Handler)
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    yield f"http://127.0.0.1:{httpd.server_port}", root
    httpd.shutdown()


def _client(url, **kw):
    from spectre_tpu.preprocessor.beacon import BeaconClient
    kw.setdefault("timeout", 5.0)
    kw.setdefault("retries", 5)
    kw.setdefault("backoff_base", 0.001)
    kw.setdefault("backoff_max", 0.01)
    kw.setdefault("total_timeout", 30.0)
    kw.setdefault("breaker_threshold", 100)
    kw.setdefault("breaker_cooldown", 0.05)
    return BeaconClient(url, **kw)


class TestBeaconResilience:
    def test_survives_transient_failures_with_backoff(self, beacon_server):
        url, root = beacon_server
        sleeps = []
        c = _client(url, sleep=sleeps.append)
        faults.install_plan("beacon.fetch:http503:3")
        r0 = HEALTH.get("beacon_retries")
        assert c.head_block_root() == root
        assert faults.fired_count("beacon.fetch") == 3
        assert len(sleeps) == 3                 # one backoff per failure
        assert HEALTH.get("beacon_retries") == r0 + 3
        assert c.breaker_state == "closed"

    def test_backoff_grows_exponentially(self, beacon_server):
        url, _ = beacon_server
        sleeps = []
        # rng pinned to 1.0: delay == min(max, base * 2^i) exactly
        c = _client(url, sleep=sleeps.append, rng=lambda: 1.0,
                    backoff_base=0.001, backoff_max=1.0)
        faults.install_plan("beacon.fetch:timeout:4")
        c.head_block_root()
        assert sleeps == [0.001, 0.002, 0.004, 0.008]

    def test_retry_after_honored(self, beacon_server):
        url, _ = beacon_server
        sleeps = []
        # rng 0.0 would give zero backoff; Retry-After (0.01 on the
        # injected 429) must floor the delay
        c = _client(url, sleep=sleeps.append, rng=lambda: 0.0)
        faults.install_plan("beacon.fetch:http429:1")
        c.head_block_root()
        assert sleeps == [0.01]

    def test_non_transient_raises_immediately(self, beacon_server):
        import urllib.error
        url, _ = beacon_server
        sleeps = []
        c = _client(url, sleep=sleeps.append)
        with pytest.raises(urllib.error.HTTPError):
            c._get("/nonexistent")
        assert sleeps == []

    def test_total_deadline_exceeded(self, beacon_server):
        url, _ = beacon_server
        c = _client(url, total_timeout=0.0)
        with pytest.raises(TimeoutError, match="total deadline"):
            c.head_block_root()

    def test_breaker_trips_fails_fast_half_opens(self, beacon_server):
        from spectre_tpu.preprocessor.beacon import CircuitBreakerOpen
        url, root = beacon_server
        c = _client(url, breaker_threshold=3, breaker_cooldown=0.05)
        trips0 = HEALTH.get("beacon_breaker_trips")
        faults.install_plan("beacon.fetch:connreset:10")
        # 3 consecutive failures trip the breaker mid-call
        with pytest.raises(CircuitBreakerOpen):
            c.head_block_root()
        assert faults.fired_count("beacon.fetch") == 3
        assert HEALTH.get("beacon_breaker_trips") == trips0 + 1
        # open: fail fast, no network attempt
        with pytest.raises(CircuitBreakerOpen):
            c.head_block_root()
        assert faults.fired_count("beacon.fetch") == 3
        # cooldown elapses -> half-open admits a trial; it fails (faults
        # still armed) and the breaker re-opens (counted as a trip)
        time.sleep(0.06)
        assert c.breaker_state == "half-open"
        with pytest.raises(CircuitBreakerOpen):
            c.head_block_root()
        assert faults.fired_count("beacon.fetch") == 4
        assert HEALTH.get("beacon_breaker_trips") == trips0 + 2
        assert HEALTH.get("beacon_breaker_half_open") >= 1
        # cooldown again; disarm faults -> the half-open trial succeeds
        # and the breaker closes
        faults.clear()
        time.sleep(0.06)
        assert c.head_block_root() == root
        assert c.breaker_state == "closed"


# ---------------------------------------------------------------------------
# device-prove -> CPU degradation (byte-identical proof)
# ---------------------------------------------------------------------------

K = 6


def _toy_proof_setup():
    from spectre_tpu.plonk import backend as B
    from spectre_tpu.plonk.constraint_system import Assignment, CircuitConfig
    from spectre_tpu.plonk.keygen import keygen
    from spectre_tpu.plonk.srs import SRS

    cfg = CircuitConfig(k=K, num_advice=1, num_lookup_advice=1, num_fixed=1,
                        lookup_bits=4)
    n = cfg.n
    x_w, y_w = 7, 3
    out = x_w + x_w * y_w
    advice = [[0] * n]
    advice[0][0:5] = [x_w, x_w, y_w, out, 5]
    selectors = [[0] * n]
    selectors[0][0] = 1
    lookup = [[0] * n]
    lookup[0][0] = x_w
    fixed = [[0] * n]
    fixed[0][0] = 5
    copies = [
        ((cfg.col_instance(0), 0), (cfg.col_gate_advice(0), 3)),
        ((cfg.col_fixed(0), 0), (cfg.col_gate_advice(0), 4)),
        ((cfg.col_gate_advice(0), 0), (cfg.col_lookup_advice(0), 0)),
    ]
    srs = SRS.unsafe_setup(K)
    pk = keygen(srs, cfg, fixed, selectors, copies)
    asg = Assignment(cfg, advice, lookup, fixed, selectors, [[out]], copies)
    return pk, srs, asg, out


def _seeded_rng():
    from spectre_tpu.fields import bn254
    rnd = random.Random(0xFA17)
    return lambda: rnd.randrange(bn254.R)


@pytest.fixture(scope="module")
def toy():
    return _toy_proof_setup()


@pytest.fixture(scope="module")
def clean_cpu_proof(toy):
    """The reference proof: a clean CPU prove with seeded blinding (every
    fallback prove below must reproduce these exact bytes)."""
    from spectre_tpu.plonk import backend as B
    from spectre_tpu.plonk.prover import prove
    pk, srs, asg, _ = toy
    return prove(pk, srs, asg, B.get_backend("cpu"),
                 blinding_rng=_seeded_rng())


class _FakeDeviceBackend:
    """Stands in for TpuBackend at the classification layer (the injected
    fault fires before any backend op runs, so no real device is needed)."""
    name = "tpu"


class TestBackendCpuFallback:
    def test_oom_degrades_byte_identical(self, toy, clean_cpu_proof):
        from spectre_tpu.plonk import backend as B
        from spectre_tpu.plonk.prover import prove
        from spectre_tpu.plonk.verifier import verify
        pk, srs, asg, out = toy
        faults.install_plan("backend.prove:oom:1")
        f0 = HEALTH.get("prove_cpu_fallbacks_oom")
        got = B.prove_with_fallback(
            lambda bk: prove(pk, srs, asg, bk, blinding_rng=_seeded_rng()),
            _FakeDeviceBackend())
        assert got == clean_cpu_proof          # byte-identical to clean CPU
        assert verify(pk.vk, srs, [[out]], got)
        assert HEALTH.get("prove_cpu_fallbacks_oom") == f0 + 1
        assert faults.armed("backend.prove") == 0

    def test_compile_failure_degrades(self, toy, clean_cpu_proof):
        from spectre_tpu.plonk import backend as B
        from spectre_tpu.plonk.prover import prove
        pk, srs, asg, _ = toy
        faults.install_plan("backend.prove:compile:1")
        f0 = HEALTH.get("prove_cpu_fallbacks_compile")
        got = B.prove_with_fallback(
            lambda bk: prove(pk, srs, asg, bk, blinding_rng=_seeded_rng()),
            _FakeDeviceBackend())
        assert got == clean_cpu_proof
        assert HEALTH.get("prove_cpu_fallbacks_compile") == f0 + 1

    def test_already_on_cpu_no_retry_loop(self):
        from spectre_tpu.plonk import backend as B
        faults.install_plan("backend.prove:oom:1")
        with pytest.raises(faults.InjectedFault):
            B.prove_with_fallback(lambda bk: b"unreached",
                                  B.get_backend("cpu"))

    def test_non_degradable_errors_propagate(self):
        from spectre_tpu.plonk import backend as B

        def bad_witness(bk):
            raise AssertionError("witness violates gate")

        with pytest.raises(AssertionError, match="witness violates"):
            B.prove_with_fallback(bad_witness, _FakeDeviceBackend())

    def test_classifiers(self):
        from spectre_tpu.plonk import backend as B
        assert B.is_device_oom(faults.InjectedFault("s", "oom"))
        assert not B.is_device_oom(faults.InjectedFault("s", "compile"))
        assert B.is_compile_failure(faults.InjectedFault("s", "compile"))
        assert not B.is_compile_failure(ValueError("nope"))
        assert not B.is_device_oom(MemoryError("host, not device"))


# ---------------------------------------------------------------------------
# job queue: journal recovery, dedup, timeout, cancellation
# ---------------------------------------------------------------------------

def _digest_runner(method, params):
    """Deterministic stand-in prover: result is a pure function of the
    witness, with the backend.prove fault site threaded through like the
    real runner."""
    faults.check("backend.prove")
    blob = json.dumps([method, params], sort_keys=True).encode()
    return {"proof": "0x" + hashlib.sha256(blob).hexdigest()}


class TestJobQueue:
    def _mk(self, tmp_path, runner=_digest_runner, **kw):
        from spectre_tpu.prover_service.jobs import JobQueue
        kw.setdefault("concurrency", 1)
        return JobQueue(runner, journal_dir=str(tmp_path), **kw)

    def test_submit_poll_result(self, tmp_path):
        q = self._mk(tmp_path)
        jid = q.submit("m", {"w": 1})
        job = q.wait(jid, timeout=10)
        assert job.status == "done"
        assert job.result == _digest_runner("m", {"w": 1})
        assert q.status(jid)["status"] == "done"
        q.stop()

    def test_dedup_by_witness_digest(self, tmp_path):
        q = self._mk(tmp_path)
        d0 = HEALTH.get("jobs_deduped")
        j1 = q.submit("m", {"w": 2})
        j2 = q.submit("m", {"w": 2})     # identical witness: same job
        j3 = q.submit("m", {"w": 3})
        assert j1 == j2 and j1 != j3
        assert HEALTH.get("jobs_deduped") == d0 + 1
        q.wait(j1, timeout=10)
        # done jobs stay dedup'd (a retried client gets the cached result)
        assert q.submit("m", {"w": 2}) == j1
        q.stop()

    def test_timeout_marks_failed(self, tmp_path):
        def slow(method, params):
            time.sleep(0.5)
            return {"ok": True}

        q = self._mk(tmp_path, runner=slow)
        jid = q.submit("m", {"w": 4}, timeout=0.05)
        job = q.wait(jid, timeout=10)
        assert job.status == "failed"
        assert job.error["kind"] == "TimeoutError"
        q.stop()

    def test_cancel_queued_job(self, tmp_path):
        release = threading.Event()

        def blocking(method, params):
            release.wait(5)
            return {"ok": True}

        q = self._mk(tmp_path, runner=blocking, concurrency=1)
        j1 = q.submit("m", {"w": 5})
        j2 = q.submit("m", {"w": 6})    # stuck behind j1
        assert q.cancel(j2)
        release.set()
        assert q.wait(j2, timeout=10).status == "cancelled"
        assert q.wait(j1, timeout=10).status == "done"
        q.stop()

    def test_journal_write_fault_fails_job_not_queue(self, tmp_path):
        q = self._mk(tmp_path)
        faults.install_plan("journal.write:ioerror:1")
        jid = q.submit("m", {"w": 7})
        job = q.wait(jid, timeout=10)
        assert job.status == "failed"
        assert job.error["kind"] == "OSError"
        # the queue survives: the next submit proves normally
        j2 = q.submit("m", {"w": 8})
        assert q.wait(j2, timeout=10).status == "done"
        q.stop()

    def test_torn_journal_tail_tolerated(self, tmp_path):
        from spectre_tpu.prover_service.jobs import JobJournal
        q = self._mk(tmp_path)
        jid = q.submit("m", {"w": 9})
        q.wait(jid, timeout=10)
        q.stop()
        # simulate a crash mid-append: torn, non-JSON final line
        with open(q.journal.path, "a") as f:
            f.write('{"event": "running", "job_')
        replayed = JobJournal(str(tmp_path)).replay()
        assert replayed[jid].status == "done"

    def test_crash_recovery_same_digest(self, tmp_path):
        """ISSUE-3 acceptance: kill a worker mid-prove (injected crash),
        restart the queue over the same params_dir, and the journal replay
        re-runs the job to the same result digest as an uninterrupted
        run."""
        import threading as _t
        q = self._mk(tmp_path)
        faults.install_plan("backend.prove:crash:1")
        r0 = HEALTH.get("jobs_requeued")
        # the InjectedCrash kills the worker thread like a dead process;
        # silence the default excepthook traceback spam
        old_hook = _t.excepthook
        _t.excepthook = lambda args: None
        try:
            jid = q.submit("m", {"w": 10})
            deadline = time.time() + 10
            while time.time() < deadline:
                st = q.status(jid)
                if st["status"] == "running" and not any(
                        w.is_alive() for w in q._workers):
                    break
                time.sleep(0.01)
            else:
                pytest.fail("worker did not crash")
        finally:
            _t.excepthook = old_hook
        # in-memory state died mid-prove: the journal's last record for
        # the job is "running" with no terminal event
        # --- restart: a fresh queue over the same journal dir ---
        q2 = self._mk(tmp_path)
        assert HEALTH.get("jobs_requeued") == r0 + 1
        job = q2.wait(jid, timeout=10)
        assert job.status == "done"
        assert job.result == _digest_runner("m", {"w": 10})
        assert job.attempts >= 1
        q2.stop()

    def test_manifest_write_fault_never_fails_prove(self, tmp_path):
        """ISSUE-8 pin: the provenance-manifest sink is IO-tolerant by
        the metrics.write contract — a broken disk at `manifest.write`
        costs the manifest (counted), never the prove."""
        q = self._mk(tmp_path)
        m0 = HEALTH.get("manifest_write_failures")
        faults.install_plan("manifest.write:ioerror:1")
        jid = q.submit("m", {"w": 40})
        job = q.wait(jid, timeout=10)
        assert job.status == "done"
        assert job.result == _digest_runner("m", {"w": 40})
        assert job.manifest_digest is None
        assert q.manifest(jid) is None
        assert HEALTH.get("manifest_write_failures") == m0 + 1
        # the fault is spent: the next prove manifests normally
        j2 = q.submit("m", {"w": 41})
        job2 = q.wait(j2, timeout=10)
        assert job2.status == "done" and job2.manifest_digest is not None
        assert q.manifest(j2)["result_digest"] == job2.result_digest
        q.stop()

    def test_journal_lives_under_params_dir(self, tmp_path):
        """ensure_jobs default wiring: the journal lands in the state's
        params_dir, so a service restart over the same dir recovers."""
        from spectre_tpu.prover_service.jobs import JOURNAL_NAME, ensure_jobs

        class S:
            spec = None
            concurrency = 1
            params_dir = str(tmp_path)
            jobs = None

        q = ensure_jobs(S(), runner=_digest_runner)
        jid = q.submit("m", {"w": 20})
        assert q.wait(jid, timeout=10).status == "done"
        assert (tmp_path / JOURNAL_NAME).exists()
        q.stop()

    def test_recovery_keeps_done_results(self, tmp_path):
        q = self._mk(tmp_path)
        jid = q.submit("m", {"w": 11})
        want = q.wait(jid, timeout=10).result
        q.stop()
        q2 = self._mk(tmp_path)
        # the restarted service still dedups + serves the journaled result
        assert q2.submit("m", {"w": 11}) == jid
        assert q2.result(jid).result == want
        q2.stop()


class TestJournalCompaction:
    """ROADMAP PR-3 follow-up (ISSUE 4 satellite): past a size threshold the
    startup replay rewrites the JSONL keeping only the terminal-state tail
    per job — the journal stops growing without bound, and a crash
    mid-compact loses NOTHING (atomic sidecar + replace)."""

    def _mk(self, tmp_path, **kw):
        from spectre_tpu.prover_service.jobs import JobQueue
        kw.setdefault("concurrency", 1)
        return JobQueue(_digest_runner, journal_dir=str(tmp_path), **kw)

    def test_compaction_shrinks_and_preserves_state(self, tmp_path,
                                                    monkeypatch):
        from spectre_tpu.prover_service.jobs import JOURNAL_NAME
        q = self._mk(tmp_path)
        jids = [q.submit("m", {"w": i}) for i in range(8)]
        results = {j: q.wait(j, timeout=10).result for j in jids}
        q.stop()
        path = tmp_path / JOURNAL_NAME
        before = path.stat().st_size
        # force the threshold below the journal size -> startup compacts
        monkeypatch.setenv("SPECTRE_JOURNAL_COMPACT_BYTES", "1")
        c0 = HEALTH.get("journal_compactions")
        q2 = self._mk(tmp_path)
        assert HEALTH.get("journal_compactions") == c0 + 1
        after = path.stat().st_size
        # submit+done per job vs submit+running+done: strictly smaller
        assert after < before
        # every result still served, dedup still pins the digests
        for jid in jids:
            assert q2.result(jid).result == results[jid]
            assert q2.submit("m", {"w": jids.index(jid)}) == jid
        q2.stop()
        # a THIRD restart replays the compacted journal identically
        q3 = self._mk(tmp_path)
        for jid in jids:
            assert q3.result(jid).result == results[jid]
        q3.stop()

    def test_compaction_drops_intermediate_transitions(self, tmp_path,
                                                       monkeypatch):
        from spectre_tpu.prover_service.jobs import JOURNAL_NAME
        q = self._mk(tmp_path)
        jid = q.submit("m", {"w": 1})
        q.wait(jid, timeout=10)
        q.stop()
        monkeypatch.setenv("SPECTRE_JOURNAL_COMPACT_BYTES", "1")
        q2 = self._mk(tmp_path)
        q2.stop()
        events = [json.loads(line)["event"]
                  for line in (tmp_path / JOURNAL_NAME).read_text()
                  .splitlines() if line]
        assert events == ["submit", "done"]     # no "running" tail noise

    def test_crash_mid_compact_loses_nothing(self, tmp_path, monkeypatch):
        """The ISSUE-4 hammer: an injected crash between staging the
        compacted sidecar and the atomic replace behaves like power loss —
        the ORIGINAL journal survives intact and the next startup both
        recovers every job and completes the deferred compaction."""
        from spectre_tpu.prover_service.jobs import JOURNAL_NAME
        q = self._mk(tmp_path)
        jids = [q.submit("m", {"w": i}) for i in range(4)]
        results = {j: q.wait(j, timeout=10).result for j in jids}
        q.stop()
        path = tmp_path / JOURNAL_NAME
        original = path.read_text()
        monkeypatch.setenv("SPECTRE_JOURNAL_COMPACT_BYTES", "1")
        faults.install_plan("journal.compact:crash:1")
        with pytest.raises(faults.InjectedCrash):
            self._mk(tmp_path)
        # the journal is byte-identical to before the attempt
        assert path.read_text() == original
        faults.clear()
        # restart after the "power loss": full recovery + compaction
        q2 = self._mk(tmp_path)
        for jid in jids:
            assert q2.result(jid).result == results[jid]
        assert path.stat().st_size < len(original)
        q2.stop()


# ---------------------------------------------------------------------------
# fixed-base MSM table-budget degradation
# ---------------------------------------------------------------------------

class TestMsmTableBudgetDegrade:
    def test_degrades_to_glv_signed_same_point(self, monkeypatch):
        import jax.numpy as jnp
        from spectre_tpu.fields import bn254 as bn
        from spectre_tpu.ops import ec, limbs as L, msm as MSM

        n = 8
        pts = [bn.g1_curve.mul(bn.G1_GEN, k + 1) for k in range(n)]
        pp = ec.encode_points(pts)
        sc = [(k * 977 + 5) % bn.R for k in range(n)]
        ss = jnp.asarray(L.ints_to_limbs16(sc))
        want = bn.g1_curve.msm(pts, sc)

        monkeypatch.setattr(MSM._TABLES, "budget", 64)   # nothing fits
        d0 = HEALTH.get("msm_fixed_degraded")
        builds0 = MSM._TABLES.builds
        got = ec.decode_points(
            MSM.msm(pp, ss, mode="fixed", base_key="degrade-test")[None])[0]
        assert got == (int(want[0]), int(want[1]))
        assert HEALTH.get("msm_fixed_degraded") == d0 + 1
        assert MSM._TABLES.builds == builds0     # no table was built

    # NOTE: the within-budget build path (table built + cached) is already
    # pinned by test_msm_modes.py::TestFixedTableCache — not duplicated
    # here to keep the fault tier inside the tier-1 time budget.

    def test_table_bytes_estimate_exact(self):
        from spectre_tpu.ops import msm as MSM
        n, c, nbits = 8, 8, 126
        nwin = (nbits + c) // c
        assert MSM._fixed_table_bytes(n, c, nbits) == \
            nwin * 2 * n * 3 * 16 * 4


# ---------------------------------------------------------------------------
# SRS load fault site
# ---------------------------------------------------------------------------

class TestSrsFaultSite:
    def test_srs_load_fault_fires(self, tmp_path):
        from spectre_tpu.plonk.srs import SRS
        faults.install_plan("srs.load:ioerror:1")
        with pytest.raises(OSError):
            SRS.load_or_setup(4, str(tmp_path))
        # disarmed: the retried load succeeds
        srs = SRS.load_or_setup(4, str(tmp_path))
        assert srs.k == 4


# ---------------------------------------------------------------------------
# ISSUE 6: admission control + backpressure
# ---------------------------------------------------------------------------

class TestAdmissionControl:
    """Overload-safe submission: a full queue (or a breached host-memory
    watermark) sheds NEW work with a typed ServiceOverloaded carrying a
    retry_after_s hint priced off the observed mean prove latency."""

    def _gated_runner(self):
        gate = threading.Event()
        started = threading.Event()

        def runner(method, params):
            started.set()
            assert gate.wait(timeout=30), "test forgot to open the gate"
            return _digest_runner(method, params)
        return runner, gate, started

    def test_queue_full_sheds_then_recovers(self, tmp_path):
        from spectre_tpu.prover_service.jobs import (JobQueue,
                                                     ServiceOverloaded)
        runner, gate, started = self._gated_runner()
        q = JobQueue(runner, concurrency=1, journal_dir=str(tmp_path),
                     queue_depth=1)
        shed0 = HEALTH.get("jobs_shed_queue")
        a = q.submit("m", {"w": "a"})
        assert started.wait(timeout=10)      # worker picked A up: running
        for _ in range(100):                 # drain race: wait off "queued"
            if q.status(a)["status"] == "running":
                break
            time.sleep(0.02)
        b = q.submit("m", {"w": "b"})        # fills the 1-deep backlog
        with pytest.raises(ServiceOverloaded) as exc:
            q.submit("m", {"w": "c"})
        assert exc.value.retry_after_s >= 1.0
        assert HEALTH.get("jobs_shed_queue") == shed0 + 1
        # ...but a DEDUP of already-admitted work is never shed
        assert q.submit("m", {"w": "b"}) == b
        gate.set()                           # drain
        assert q.wait(a, timeout=10).status == "done"
        assert q.wait(b, timeout=10).status == "done"
        # the retried submission now admits and completes
        c = q.submit("m", {"w": "c"})
        assert q.wait(c, timeout=10).status == "done"
        assert q.result(c).result == _digest_runner("m", {"w": "c"})
        q.stop()

    def test_memory_watermark_sheds(self, tmp_path):
        from spectre_tpu.prover_service.jobs import (JobQueue,
                                                     ServiceOverloaded,
                                                     rss_mb)
        if rss_mb() is None:
            pytest.skip("no /proc/self/statm on this platform")
        assert rss_mb() > 1.0                # a live CPython is >1MB
        q = JobQueue(_digest_runner, concurrency=1,
                     journal_dir=str(tmp_path), mem_watermark_mb=1.0)
        shed0 = HEALTH.get("jobs_shed_memory")
        with pytest.raises(ServiceOverloaded, match="memory watermark"):
            q.submit("m", {"w": 1})
        assert HEALTH.get("jobs_shed_memory") == shed0 + 1
        q.stop()

    def test_watermark_zero_disables(self, tmp_path):
        from spectre_tpu.prover_service.jobs import JobQueue
        q = JobQueue(_digest_runner, concurrency=1,
                     journal_dir=str(tmp_path), mem_watermark_mb=0)
        jid = q.submit("m", {"w": 2})
        assert q.wait(jid, timeout=10).status == "done"
        q.stop()

    def test_retry_after_priced_by_observed_latency(self, tmp_path):
        from spectre_tpu.prover_service.jobs import JobQueue
        h = ServiceHealth()
        h.observe("prove_latency_s", 10.0)
        h.observe("prove_latency_s", 20.0)   # mean 15s
        q = JobQueue(_digest_runner, concurrency=1,
                     journal_dir=str(tmp_path), health=h)
        assert q.retry_after_s() == 15.0     # empty backlog: one mean prove
        q.stop()

    def test_env_defaults(self, tmp_path, monkeypatch):
        from spectre_tpu.prover_service import jobs as J
        monkeypatch.setenv(J.QUEUE_DEPTH_ENV, "3")
        monkeypatch.setenv(J.MEM_WATERMARK_ENV, "123.5")
        monkeypatch.setenv(J.WORKER_STALL_ENV, "7.5")
        q = J.JobQueue(_digest_runner, concurrency=1,
                       journal_dir=str(tmp_path))
        assert q.queue_depth == 3
        assert q.mem_watermark_mb == 123.5
        assert q.stall_timeout == 7.5
        assert q.stats()["queue_depth"] == 3
        q.stop()


# ---------------------------------------------------------------------------
# ISSUE 6: deadline propagation + worker supervision
# ---------------------------------------------------------------------------

class TestDeadlinePropagation:
    def test_deadline_clamps_timeout(self, tmp_path):
        from spectre_tpu.prover_service.jobs import JobQueue
        q = JobQueue(_digest_runner, concurrency=1,
                     journal_dir=str(tmp_path), default_timeout=100.0)
        # client deadline below the server default wins...
        a = q.submit("m", {"w": "d1"}, deadline_s=0.5)
        assert q.result(a).timeout == 0.5
        # ...a LOOSER client deadline never relaxes the server's cap
        b = q.submit("m", {"w": "d2"}, timeout=0.25, deadline_s=50.0)
        assert q.result(b).timeout == 0.25
        q.stop()

    def test_deadline_expires_running_job(self, tmp_path):
        from spectre_tpu.prover_service.jobs import JobQueue
        gate = threading.Event()

        def runner(method, params):
            gate.wait(timeout=30)
            return _digest_runner(method, params)

        q = JobQueue(runner, concurrency=1, journal_dir=str(tmp_path))
        t0 = HEALTH.get("jobs_timed_out")
        jid = q.submit("m", {"w": "slow"}, deadline_s=0.15)
        job = q.wait(jid, timeout=10)
        assert job.status == "failed"
        assert job.error["kind"] == "TimeoutError"
        assert HEALTH.get("jobs_timed_out") == t0 + 1
        gate.set()
        q.stop()


class TestWorkerSupervision:
    """A hung worker (wedged device call: heartbeat stops) is detected by
    the supervisor, its job failed(stalled), and a replacement thread takes
    the slot — other jobs keep completing. Deterministic + fast via the
    injectable stall_timeout / sleep_interval knobs."""

    def test_stalled_worker_replaced(self, tmp_path):
        from spectre_tpu.prover_service.jobs import JobQueue
        release = threading.Event()

        def runner(method, params):
            if params.get("hang"):
                release.wait(timeout=30)     # no heartbeat: presumed hung
                return {"proof": "late"}
            return _digest_runner(method, params)

        q = JobQueue(runner, concurrency=1, journal_dir=str(tmp_path),
                     stall_timeout=0.3, sleep_interval=0.05)
        r0 = HEALTH.get("workers_replaced")
        hung = q.submit("m", {"hang": True})
        job = q.wait(hung, timeout=10)
        assert job.status == "failed"
        assert job.error["kind"] == "StalledWorker"
        assert HEALTH.get("workers_replaced") == r0 + 1
        # the REPLACEMENT worker serves new jobs
        ok = q.submit("m", {"w": "after-stall"})
        assert q.wait(ok, timeout=10).status == "done"
        # the disowned thread waking up must NOT resurrect the failed job
        release.set()
        time.sleep(0.2)
        assert q.result(hung).status == "failed"
        assert q.result(ok).result == _digest_runner("m",
                                                     {"w": "after-stall"})
        q.stop()

    def test_heartbeat_keeps_slow_prove_alive(self, tmp_path):
        from spectre_tpu.prover_service.jobs import JobQueue

        def runner(method, params, heartbeat=None):
            # a LEGITIMATE slow prove: total 0.6s >> stall_timeout, but
            # the phase-boundary heartbeats keep the supervisor off it
            for _ in range(6):
                time.sleep(0.1)
                heartbeat()
            return _digest_runner(method, params)

        q = JobQueue(runner, concurrency=1, journal_dir=str(tmp_path),
                     stall_timeout=0.3, sleep_interval=0.05)
        r0 = HEALTH.get("workers_replaced")
        jid = q.submit("m", {"w": "slow-but-alive"})
        assert q.wait(jid, timeout=10).status == "done"
        assert HEALTH.get("workers_replaced") == r0


# ---------------------------------------------------------------------------
# ISSUE 6: integrity-checked artifact store
# ---------------------------------------------------------------------------

class TestArtifactStore:
    def _mk(self, tmp_path):
        from spectre_tpu.utils.artifacts import ArtifactStore
        return ArtifactStore(str(tmp_path))

    def test_write_read_roundtrip_and_dedup(self, tmp_path):
        import os
        store = self._mk(tmp_path)
        d = store.write(b"proof-bytes")
        assert store.read(d) == b"proof-bytes"
        assert os.path.exists(store.path_for(d))
        assert store.write(b"proof-bytes") == d     # content-addressed

    def test_bitflip_quarantined(self, tmp_path):
        import os
        from spectre_tpu.utils.artifacts import ArtifactCorrupt
        store = self._mk(tmp_path)
        d = store.write(b"proof-bytes")
        path = store.path_for(d)
        blob = bytearray(open(path, "rb").read())
        blob[3] ^= 0x40
        with open(path, "wb") as f:
            f.write(bytes(blob))
        q0 = HEALTH.get("artifacts_quarantined")
        with pytest.raises(ArtifactCorrupt):
            store.read(d)
        assert HEALTH.get("artifacts_quarantined") == q0 + 1
        assert not os.path.exists(path)             # moved, NOT deleted
        assert os.path.exists(os.path.join(str(store.quarantine_dir),
                                           os.path.basename(path)))
        # the slot is re-writable after quarantine (re-prove path)
        assert store.write(b"proof-bytes") == d
        assert store.read(d) == b"proof-bytes"

    def test_fault_corrupt_on_read(self, tmp_path):
        from spectre_tpu.utils.artifacts import ArtifactCorrupt
        store = self._mk(tmp_path)
        d = store.write(b"payload")
        faults.install_plan("artifact.read:corrupt:1")
        with pytest.raises(ArtifactCorrupt):
            store.read(d)
        assert faults.fired_count("artifact.read") == 1

    def test_fault_corrupt_on_write_detected_at_read(self, tmp_path):
        from spectre_tpu.utils.artifacts import ArtifactCorrupt
        store = self._mk(tmp_path)
        faults.install_plan("artifact.write:corrupt:1")
        d = store.write(b"payload")     # digest records the INTENDED bytes
        with pytest.raises(ArtifactCorrupt):
            store.read(d)

    def test_fault_ioerror_on_write(self, tmp_path):
        store = self._mk(tmp_path)
        faults.install_plan("artifact.write:ioerror:1")
        with pytest.raises(OSError):
            store.write(b"payload")
        assert store.write(b"payload")  # disarmed: succeeds


class TestResultOffload:
    """Job results live in the artifact store, the journal carries only
    their sha256 — the journal is O(#jobs) and a flipped result bit is
    caught (and quarantined) at replay instead of silently served."""

    def _mk(self, tmp_path, runner=_digest_runner, **kw):
        from spectre_tpu.prover_service.jobs import JobQueue
        kw.setdefault("concurrency", 1)
        return JobQueue(runner, journal_dir=str(tmp_path), **kw)

    def test_result_offloaded_and_identical_after_restart(self, tmp_path):
        from spectre_tpu.prover_service.jobs import JOURNAL_NAME
        q = self._mk(tmp_path)
        jid = q.submit("m", {"w": "off"})
        job = q.wait(jid, timeout=10)
        want = _digest_runner("m", {"w": "off"})
        assert job.result == want
        assert job.result_digest is not None
        q.stop()
        # the payload is NOT inlined in the journal
        text = (tmp_path / JOURNAL_NAME).read_text()
        assert want["proof"] not in text
        assert job.result_digest in text
        q2 = self._mk(tmp_path)
        assert q2.result(jid).result == want        # re-verified hydrate
        q2.stop()

    def test_corrupt_result_quarantined_on_replay_then_reprovable(
            self, tmp_path):
        import os
        q = self._mk(tmp_path)
        jid = q.submit("m", {"w": "bits"})
        job = q.wait(jid, timeout=10)
        digest = job.result_digest
        q.stop()
        path = q.store.path_for(digest)
        blob = bytearray(open(path, "rb").read())
        blob[len(blob) // 2] ^= 0x01
        with open(path, "wb") as f:
            f.write(bytes(blob))
        q0 = HEALTH.get("artifacts_quarantined")
        q2 = self._mk(tmp_path)
        replayed = q2.result(jid)
        assert replayed.status == "failed"          # degraded, loudly
        assert replayed.error["kind"] == "ArtifactCorrupt"
        assert HEALTH.get("artifacts_quarantined") == q0 + 1
        assert not os.path.exists(path)
        # failed jobs do not pin the witness digest: resubmit RE-PROVES
        jid2 = q2.submit("m", {"w": "bits"})
        assert jid2 != jid
        assert q2.wait(jid2, timeout=10).result == _digest_runner(
            "m", {"w": "bits"})
        q2.stop()

    def test_journal_size_independent_of_payload(self, tmp_path,
                                                 monkeypatch):
        from spectre_tpu.prover_service.jobs import JOURNAL_NAME
        big = "ab" * 65536                           # 128KB proof payload

        def big_runner(method, params):
            return {"proof": big, "w": params["w"]}

        q = self._mk(tmp_path, runner=big_runner)
        jids = [q.submit("m", {"w": i}) for i in range(4)]
        for j in jids:
            assert q.wait(j, timeout=10).status == "done"
        q.stop()
        monkeypatch.setenv("SPECTRE_JOURNAL_COMPACT_BYTES", "1")
        q2 = self._mk(tmp_path, runner=big_runner)
        size = (tmp_path / JOURNAL_NAME).stat().st_size
        # O(#jobs): the compacted journal is smaller than ONE payload
        assert size < len(big)
        for i, j in enumerate(jids):
            assert q2.result(j).result == {"proof": big, "w": i}
        q2.stop()


class TestSrsChecksum:
    def test_sidecar_written_and_verified(self, tmp_path):
        from spectre_tpu.plonk.srs import SRS
        from spectre_tpu.utils.artifacts import SIDECAR_SUFFIX
        srs = SRS.load_or_setup(4, str(tmp_path))
        path = tmp_path / "kzg_bn254_4.srs"
        assert (tmp_path / ("kzg_bn254_4.srs" + SIDECAR_SUFFIX)).exists()
        assert SRS.read(str(path)).k == srs.k

    def test_bitflipped_srs_refused(self, tmp_path):
        from spectre_tpu.plonk.srs import SRS
        from spectre_tpu.utils.artifacts import ArtifactCorrupt
        SRS.load_or_setup(4, str(tmp_path))
        path = tmp_path / "kzg_bn254_4.srs"
        blob = bytearray(path.read_bytes())
        blob[40] ^= 0x08                             # one flipped tau limb
        path.write_bytes(bytes(blob))
        with pytest.raises(ArtifactCorrupt):
            SRS.read(str(path))
        with pytest.raises(ArtifactCorrupt):
            SRS.load_or_setup(4, str(tmp_path))      # load path refuses too

    def test_missing_sidecar_stays_loadable(self, tmp_path):
        from spectre_tpu.plonk.srs import SRS
        from spectre_tpu.utils.artifacts import SIDECAR_SUFFIX
        SRS.load_or_setup(4, str(tmp_path))
        (tmp_path / ("kzg_bn254_4.srs" + SIDECAR_SUFFIX)).unlink()
        assert SRS.read(str(tmp_path / "kzg_bn254_4.srs")).k == 4


class TestMetricsSinkFault:
    """ISSUE 7 satellite: the SPECTRE_METRICS JSONL sink is best-effort —
    a broken sink (full disk, revoked fd) must NEVER fail the prove it is
    observing; it counts on health and the next phase writes through."""

    def test_broken_sink_never_fails_a_prove(self, tmp_path, monkeypatch):
        from spectre_tpu.utils import profiling as prof
        sink = tmp_path / "metrics.jsonl"
        monkeypatch.setenv("SPECTRE_METRICS", str(sink))
        faults.install_plan("metrics.write:ioerror:1")
        before = HEALTH.get("metrics_write_failures")
        with prof.phase("sink-test-phase"):          # must not raise
            pass
        assert faults.fired_count("metrics.write") == 1
        assert HEALTH.get("metrics_write_failures") == before + 1
        assert not sink.exists()                     # faulted append skipped
        with prof.phase("sink-test-phase"):          # disarmed: writes thru
            pass
        lines = [json.loads(l) for l in sink.read_text().splitlines()]
        assert len(lines) == 1
        assert lines[0]["phase"] == "sink-test-phase"
        assert lines[0]["seconds"] >= 0


class TestDiskFullFault:
    """ISSUE 9 satellite: a full disk (`diskfull` kind = OSError ENOSPC)
    at any write site fails the JOB (or just the manifest, per the
    best-effort contract) with a typed error and a counter — never
    crashes the worker or wedges the queue."""

    def _mk(self, tmp_path, **kw):
        from spectre_tpu.prover_service.jobs import JobQueue
        kw.setdefault("concurrency", 1)
        return JobQueue(_digest_runner, journal_dir=str(tmp_path), **kw)

    def test_kind_raises_enospc(self):
        import errno
        faults.install_plan("d.site:diskfull:1")
        with pytest.raises(OSError) as e:
            faults.check("d.site")
        assert e.value.errno == errno.ENOSPC
        faults.check("d.site")         # spent: no-op

    def test_artifact_write_diskfull_fails_job_not_queue(self, tmp_path):
        q = self._mk(tmp_path)
        faults.install_plan("artifact.write:diskfull:1")
        jid = q.submit("m", {"w": 90})
        job = q.wait(jid, timeout=10)
        assert job.status == "failed"
        assert job.error["kind"] == "OSError"
        assert "ENOSPC" in job.error["message"]
        # queue survives: the next submit proves + persists normally
        j2 = q.submit("m", {"w": 91})
        job2 = q.wait(j2, timeout=10)
        assert job2.status == "done" and job2.result_digest is not None
        q.stop()

    def test_journal_write_diskfull_fails_job_not_queue(self, tmp_path):
        q = self._mk(tmp_path)
        faults.install_plan("journal.write:diskfull:1")
        jid = q.submit("m", {"w": 92})
        job = q.wait(jid, timeout=10)
        assert job.status == "failed"
        assert job.error["kind"] == "OSError"
        j2 = q.submit("m", {"w": 93})
        assert q.wait(j2, timeout=10).status == "done"
        q.stop()

    def test_manifest_write_diskfull_best_effort(self, tmp_path):
        # manifests are optional by contract: ENOSPC costs the manifest
        # (counted on manifest_write_failures), never the prove
        q = self._mk(tmp_path)
        m0 = HEALTH.get("manifest_write_failures")
        faults.install_plan("manifest.write:diskfull:1")
        jid = q.submit("m", {"w": 94})
        job = q.wait(jid, timeout=10)
        assert job.status == "done"
        assert job.manifest_digest is None and q.manifest(jid) is None
        assert HEALTH.get("manifest_write_failures") == m0 + 1
        q.stop()


class TestFaultSiteDocs:
    """The README fault-site table is generated, not hand-maintained.

    `python -m spectre_tpu.prover_service faults --list` prints
    `faults.render_site_table()`; the README embeds that output between
    `<!-- fault-sites:begin -->` / `<!-- fault-sites:end -->` markers.
    These pins make drift (a new site without a doc row, or a stale
    hand-edit) a test failure instead of a silent lie.
    """

    def _readme_block(self):
        import pathlib

        readme = pathlib.Path(__file__).resolve().parent.parent / "README.md"
        text = readme.read_text(encoding="utf-8")
        begin = "<!-- fault-sites:begin -->"
        end = "<!-- fault-sites:end -->"
        assert begin in text and end in text, "README fault-site markers missing"
        return text.split(begin, 1)[1].split(end, 1)[0].strip()

    def test_readme_table_matches_registry(self):
        assert self._readme_block() == faults.render_site_table().strip()

    def test_every_site_has_a_table_row(self):
        block = self._readme_block()
        for site in faults.SITES:
            assert f"`{site}`" in block

    def test_cli_faults_list_prints_table(self, capsys):
        from spectre_tpu.prover_service.cli import main

        assert main(["faults", "--list"]) in (0, None)
        out = capsys.readouterr().out
        assert faults.render_site_table().strip() in out

    def test_cli_faults_json_covers_sites_and_kinds(self, capsys):
        from spectre_tpu.prover_service.cli import main

        assert main(["faults", "--json"]) in (0, None)
        payload = json.loads(capsys.readouterr().out)
        assert set(payload["sites"]) == set(faults.SITES)
        assert tuple(payload["kinds"]) == faults.KINDS

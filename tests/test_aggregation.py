"""Aggregation/compression layer: in-circuit SHPLONK verification.

Reference parity: `aggregation_circuit.rs` + snark-verifier's
`AggregationCircuit` (mock + proof tests mirror
`sync_step_circuit.rs:544-604`'s two-stage flow at framework scale).

Default tier: the full in-circuit verification of a REAL inner proof
(witness generation + accumulator parity + the deferred pairing), and the
reject paths. RUN_SLOW tier: constraint satisfaction of the whole verifier
circuit and the outer prove/verify round-trip.
"""

import os
import random

import pytest

from spectre_tpu.builder.context import Context
from spectre_tpu.builder.range_chip import RangeChip
from spectre_tpu.fields import bn254
from spectre_tpu.models.aggregation import (Accumulator, AggregationArgs,
                                            AggregationCircuit, accumulate)
from spectre_tpu.plonk.in_circuit import VerifierChip
from spectre_tpu.plonk.keygen import keygen
from spectre_tpu.plonk.mock import mock_prove
from spectre_tpu.plonk.prover import prove
from spectre_tpu.plonk.srs import SRS
from spectre_tpu.plonk.transcript import PoseidonTranscript

RUN_SLOW = os.environ.get("RUN_SLOW") == "1"
R = bn254.R
P = bn254.P


@pytest.fixture(scope="module")
def inner():
    """A small app circuit proven with the Poseidon transcript."""
    random.seed(3)
    ctx = Context()
    rng = RangeChip(lookup_bits=8)
    g = rng.gate
    a = ctx.load_witness(1234)
    b = ctx.load_witness(5678)
    c = g.mul(ctx, a, b)
    rng.range_check(ctx, a, 16)
    ctx.expose_public(c)
    cfg = ctx.auto_config(k=10, lookup_bits=8)
    asg = ctx.assignment(cfg)
    srs = SRS.unsafe_setup(10)
    pk = keygen(srs, cfg, asg.fixed, asg.selectors, asg.copies)
    proof = prove(pk, srs, asg, transcript=PoseidonTranscript())
    return pk, srs, asg.instances, proof


class TestAccumulator:
    def test_limbs_roundtrip(self):
        g1 = bn254.g1_curve
        acc = Accumulator(lhs=g1.mul(bn254.G1_GEN, 7),
                          rhs=g1.mul(bn254.G1_GEN, 11))
        back = Accumulator.from_limbs(acc.limbs())
        assert (int(back.lhs[0]), int(back.lhs[1])) == \
            (int(acc.lhs[0]), int(acc.lhs[1]))
        assert (int(back.rhs[0]), int(back.rhs[1])) == \
            (int(acc.rhs[0]), int(acc.rhs[1]))

    def test_accumulate_is_deterministic_fiat_shamir(self):
        g1 = bn254.g1_curve
        accs = [Accumulator(g1.mul(bn254.G1_GEN, i + 2),
                            g1.mul(bn254.G1_GEN, i + 9)) for i in range(3)]
        a1 = accumulate(accs)
        a2 = accumulate(accs)
        assert (int(a1.lhs[0]), int(a1.rhs[0])) == \
            (int(a2.lhs[0]), int(a2.rhs[0]))
        # different input order -> different challenges
        a3 = accumulate(list(reversed(accs)))
        assert int(a3.lhs[0]) != int(a1.lhs[0])


class TestNativeAccumulator:
    def test_valid_proof_accumulates_and_pairs(self, inner):
        pk, srs, instances, proof = inner
        acc = VerifierChip.native_accumulator(pk.vk, srs, instances, proof)
        assert acc is not None
        assert acc.check(srs)

    def test_identity_failure_returns_none(self, inner):
        pk, srs, instances, proof = inner
        bad = [[(instances[0][0] + 1) % R]]
        assert VerifierChip.native_accumulator(pk.vk, srs, bad, proof) is None

    def test_tampered_commitment_fails_pairing(self, inner):
        pk, srs, instances, proof = inner
        # flip a byte in the FIRST commitment (point section): the identity
        # check at x still passes only with negligible probability; either
        # outcome (None or failed pairing) must reject
        bad = bytearray(proof)
        bad[1] ^= 1
        try:
            acc = VerifierChip.native_accumulator(pk.vk, srs, instances,
                                                  bytes(bad))
        except AssertionError:
            return  # off-curve / non-canonical: rejected at parse
        assert acc is None or not acc.check(srs)


class TestInCircuitVerifier:
    def test_accumulator_matches_native(self, inner):
        """The flagship path: a real proof verified as constraints; the
        cell-level accumulator equals the native one and the deferred
        pairing closes."""
        pk, srs, instances, proof = inner
        acc_native = VerifierChip.native_accumulator(pk.vk, srs, instances,
                                                     proof)
        ctx = Context()
        rng = RangeChip(lookup_bits=14)
        vc = VerifierChip(rng)
        cells = [[ctx.load_witness(int(v)) for v in col] for col in instances]
        lhs, rhs = vc.verify_proof(ctx, pk.vk, srs, cells, proof)
        assert (lhs[0].value % P, lhs[1].value % P) == \
            (int(acc_native.lhs[0]), int(acc_native.lhs[1]))
        assert (rhs[0].value % P, rhs[1].value % P) == \
            (int(acc_native.rhs[0]), int(acc_native.rhs[1]))
        assert Accumulator(
            lhs=(bn254.Fq(lhs[0].value % P), bn254.Fq(lhs[1].value % P)),
            rhs=(bn254.Fq(rhs[0].value % P), bn254.Fq(rhs[1].value % P)),
        ).check(srs)

    def test_sha_region_inner_proof_aggregates(self):
        """An inner proof whose circuit uses the wide-SHA region (extra
        commitment/query-plan keys: shb/shw/shq/shk) must flow through the
        in-circuit verifier and close the deferred pairing."""
        from spectre_tpu.builder import GateChip
        from spectre_tpu.builder.sha256_wide_chip import Sha256WideChip
        from spectre_tpu.gadgets import ssz_merkle as M

        ctx = Context()
        sha = Sha256WideChip(GateChip())
        cells = M.load_bytes_checked(ctx, sha, b"agg over wide sha")
        digest = sha.digest_bytes(ctx, cells)
        ctx.expose_public(digest[0].cell)
        cfg = ctx.auto_config(k=9, lookup_bits=5)
        asg = ctx.assignment(cfg)
        srs = SRS.unsafe_setup(11)
        pk = keygen(srs, cfg, asg.fixed, asg.selectors, asg.copies)
        proof = prove(pk, srs, asg, transcript=PoseidonTranscript())

        acc = VerifierChip.native_accumulator(pk.vk, srs, asg.instances,
                                              proof)
        assert acc is not None and acc.check(srs)
        vctx = Context()
        vc = VerifierChip(RangeChip(lookup_bits=14))
        icells = [[vctx.load_witness(int(v)) for v in col]
                  for col in asg.instances]
        lhs, rhs = vc.verify_proof(vctx, pk.vk, srs, icells, proof)
        assert (lhs[0].value % P, lhs[1].value % P) == \
            (int(acc.lhs[0]), int(acc.lhs[1]))
        assert (rhs[0].value % P, rhs[1].value % P) == \
            (int(acc.rhs[0]), int(acc.rhs[1]))

    def test_invalid_proof_rejected_at_witness_time(self, inner):
        pk, srs, instances, proof = inner
        ctx = Context()
        rng = RangeChip(lookup_bits=14)
        vc = VerifierChip(rng)
        bad_cells = [[ctx.load_witness((int(v) + 1) % R)
                      for v in col] for col in instances]
        with pytest.raises(AssertionError):
            vc.verify_proof(ctx, pk.vk, srs, bad_cells, proof)

    def test_statement_layout(self, inner):
        pk, srs, instances, proof = inner
        args = AggregationArgs(inner_vk=pk.vk, srs=srs,
                               inner_instances=instances, proof=proof)
        stmt = AggregationCircuit.get_instances(args, None)
        assert len(stmt) == 12 + sum(len(c) for c in instances)
        acc = Accumulator.from_limbs(stmt[:12])
        assert acc.check(srs)
        assert stmt[12:] == [int(v) % R for col in instances for v in col]


@pytest.fixture(scope="module")
def inner2():
    """A second app circuit (different shape/vk) for multi-snark folds."""
    random.seed(8)
    ctx = Context()
    rng = RangeChip(lookup_bits=8)
    g = rng.gate
    a = ctx.load_witness(31)
    b = ctx.load_witness(64)
    c = g.add(ctx, g.mul(ctx, a, a), b)
    rng.range_check(ctx, c, 12)
    ctx.expose_public(c)
    cfg = ctx.auto_config(k=10, lookup_bits=8)
    asg = ctx.assignment(cfg)
    srs = SRS.unsafe_setup(10)
    pk = keygen(srs, cfg, asg.fixed, asg.selectors, asg.copies)
    proof = prove(pk, srs, asg, transcript=PoseidonTranscript())
    return pk, srs, asg.instances, proof


class TestMultiSnarkFold:
    def test_fold_matches_native_accumulate(self, inner, inner2):
        """Two inner snarks (distinct vks) verified in-circuit; the
        transcript-bound RLC fold equals the native `accumulate` and the
        folded deferred pairing closes (reference: snark-verifier
        aggregating Vec<Snark> with N > 1)."""
        from spectre_tpu.models.aggregation import SnarkWitness

        pk1, srs, inst1, proof1 = inner
        pk2, _srs2, inst2, proof2 = inner2
        a1 = VerifierChip.native_accumulator(pk1.vk, srs, inst1, proof1)
        a2 = VerifierChip.native_accumulator(pk2.vk, srs, inst2, proof2)
        want = accumulate([a1, a2])
        assert want.check(srs)

        ctx = Context()
        vc = VerifierChip(RangeChip(lookup_bits=14))
        accs = []
        for pk, inst, proof in ((pk1, inst1, proof1), (pk2, inst2, proof2)):
            cells = [[ctx.load_witness(int(v)) for v in col] for col in inst]
            accs.append(vc.verify_proof(ctx, pk.vk, srs, cells, proof))
        lhs, rhs = vc.fold_accumulators(ctx, accs)
        assert (lhs[0].value % P, lhs[1].value % P) == \
            (int(want.lhs[0]), int(want.lhs[1]))
        assert (rhs[0].value % P, rhs[1].value % P) == \
            (int(want.rhs[0]), int(want.rhs[1]))

    def test_multi_snark_statement_layout(self, inner, inner2):
        from spectre_tpu.models.aggregation import SnarkWitness

        pk1, srs, inst1, proof1 = inner
        pk2, _srs2, inst2, proof2 = inner2
        args = AggregationArgs(
            inner_vk=pk1.vk, srs=srs, inner_instances=inst1, proof=proof1,
            more_snarks=(SnarkWitness(pk2.vk, inst2, proof2),))
        stmt = AggregationCircuit.get_instances(args, None)
        n1 = sum(len(c) for c in inst1)
        n2 = sum(len(c) for c in inst2)
        assert len(stmt) == 12 + n1 + n2
        acc = Accumulator.from_limbs(stmt[:12])
        assert acc.check(srs)


@pytest.mark.skipif(not RUN_SLOW, reason="~6M-cell mock (set RUN_SLOW=1)")
class TestAggregationCircuitSlow:
    def test_mock_satisfied(self, inner):
        pk, srs, instances, proof = inner
        args = AggregationArgs(inner_vk=pk.vk, srs=srs,
                               inner_instances=instances, proof=proof)
        assert AggregationCircuit.mock(args, None, k=17)

    def test_outer_prove_verify(self, inner, tmp_path, monkeypatch):
        pk, srs, instances, proof = inner
        args = AggregationArgs(inner_vk=pk.vk, srs=srs,
                               inner_instances=instances, proof=proof)
        # BUILD_DIR is bound at import time; patch the module attribute so
        # pinning/pk artifacts land in tmp_path, not the repo build dir
        from spectre_tpu.models import app_circuit as ac
        monkeypatch.setattr(ac, "BUILD_DIR", str(tmp_path))
        srs17 = SRS.load_or_setup(17, str(tmp_path))
        opk = AggregationCircuit.create_pk(srs17, type("S", (), {
            "name": "test"}), 17, args, cache=False)
        oproof = AggregationCircuit.prove(opk, srs17, args, None)
        stmt = AggregationCircuit.get_instances(args, None)
        assert AggregationCircuit.verify(opk.vk, srs17, stmt, oproof)
        # wrong accumulator limb -> pairing fails
        bad = list(stmt)
        bad[0] = (bad[0] + 1) % R
        assert not AggregationCircuit.verify(opk.vk, srs17, bad, oproof)
